"""Device-mesh distributed query execution (reference's scatter-gather over
Akka/Arrow-Flight — SURVEY.md §2 "Distributed communication backends" — is
replaced by XLA collectives over ICI: shards live on devices of one mesh, and
ReduceAggregateExec's cross-node merge becomes a psum).

Layout: the mesh has one axis, ``shard``. A query's staged blocks are
concatenated over series with equal per-device padding, sharded
``P('shard', None)``. One jit computes: range function on the local block,
local segment-reduce into label groups, then ``psum`` over the shard axis —
the whole distributed ``sum by (rate(...))`` in one compiled program with no
host round-trips.

Multi-host: the same program runs under ``jax.distributed`` with DCN-backed
meshes — the planner hierarchy stays identical (reference's
MultiPartitionPlanner analog would split across meshes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..jax_compat import shard_map
from ..ops import aggregations as AGG
from ..ops import kernels as K
from ..ops.staging import StagedBlock, pad_series


def make_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), axis_names=("shard",))


def make_series_mesh(devices=None) -> Mesh:
    """1-D mesh for the series-sharded fused superblock path
    (PartitionSpec('series', None) placement in ops/staging)."""
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), axis_names=("series",))


def series_mesh(mesh) -> Mesh:
    """Normalize any configured mesh to the 1-D form the sharded fused
    kernels partition the superblock series axis over: 1-D meshes pass
    through (whatever the axis is named), multi-axis meshes (shard x time)
    flatten their devices onto a fresh ``series`` axis. Mesh equality is by
    (devices, axis names), so repeated normalizations hit the same jit
    cache entries."""
    if len(mesh.axis_names) == 1:
        return mesh
    return make_series_mesh(list(mesh.devices.flat))


def _segment_psum(op: str, grid, gids_l, num_groups: int):
    """Local segment-reduce + psum over the shard axis (shared by the
    general and MXU local kernels). The ONE definition lives in
    ops/aggregations._segment_psum_axis, shared with the sharded fused
    superblock path."""
    return AGG._segment_psum_axis(op, grid, gids_l, num_groups, "shard")


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "func", "op", "num_groups", "is_counter", "is_delta"),
)
def distributed_agg_range_mxu(
    mesh: Mesh,
    func: str,
    op: str,
    vals, raw,  # [D*S, T] sharded
    lens, baseline, gids,  # [D*S]
    W, F, L, L2,  # [T, J] replicated window matrices
    count, t_first, t_last, t_last2, out_t,  # [J] replicated
    window_ms,
    num_groups: int,
    is_counter: bool = False,
    is_delta: bool = False,
):
    """Regular-grid mesh aggregation: the MXU matmul kernel inside shard_map
    (one compiled program; on one device this collapses a multi-shard query
    to a single kernel invocation)."""
    from ..ops.mxu_kernels import mxu_range_kernel

    def local(vals_l, raw_l, lens_l, base_l, gids_l):
        grid = mxu_range_kernel(
            func, vals_l, raw_l, base_l, W, F, L, L2,
            count, t_first, t_last, t_last2, out_t, window_ms,
            is_counter=is_counter, is_delta=is_delta,
        )
        # padded rows (lens 0) would read as zero-valued series: mask them
        grid = jnp.where((lens_l > 0)[:, None], grid, jnp.nan)
        return _segment_psum(op, grid, gids_l, num_groups)

    shard = P("shard")
    row = P("shard", None)
    rep = P()
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(row, row, shard, shard, shard),
        out_specs=rep,
        check=False,
    )(vals, raw, lens, baseline, gids)


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "func", "op", "num_groups", "is_counter", "is_delta", "fetch"
    ),
)
def distributed_agg_range_jitter(
    mesh: Mesh,
    func: str,
    op: str,
    vals, raw, dev,  # [D*S, T] sharded
    lens, gids,  # [D*S]
    W0,  # [T, J] replicated certain-membership matrix (mxu_jitter)
    SEL,  # [T, 5J] replicated boundary one-hot stack
    idx,  # [5, J] i32 replicated gather form (or None)
    count0, c0pos, c0ge2, has_klo, has_khi,  # [J] replicated
    F0_rel, L0_rel, L2_rel, Klo_rel, Khi_rel, blo_rel, ehi_rel,  # [J]
    window_ms,
    num_groups: int,
    is_counter: bool = False,
    is_delta: bool = False,
    fetch: str = "auto",
):
    """Near-regular (jittered) grid mesh aggregation: the certain-membership
    matmul + per-series boundary-correction kernel (ops/mxu_jitter.py) inside
    shard_map, so jittered real-world scrape data keeps the single-program
    multi-shard MXU path."""
    from ..ops.mxu_jitter import jitter_range_kernel

    def local(vals_l, raw_l, dev_l, lens_l, gids_l):
        grid = jitter_range_kernel(
            func, vals_l, dev_l, raw_l, W0, SEL, idx,
            count0, c0pos, c0ge2, has_klo, has_khi,
            F0_rel, L0_rel, L2_rel, Klo_rel, Khi_rel, blo_rel, ehi_rel,
            window_ms, is_counter=is_counter, is_delta=is_delta, fetch=fetch,
        )
        grid = jnp.where((lens_l > 0)[:, None], grid, jnp.nan)
        return _segment_psum(op, grid, gids_l, num_groups)

    shard = P("shard")
    row = P("shard", None)
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(row, row, row, shard, shard),
        out_specs=P(),
        check=False,
    )(vals, raw, dev, lens, gids)


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "func", "op", "num_groups", "is_counter", "is_delta", "fetch"
    ),
)
def distributed_agg_range_masked(
    mesh: Mesh,
    func: str,
    op: str,
    vals, dev, raw, valid, cc,  # [D*S, T] sharded slot-aligned masked arrays
    ffv, ffd, bfv, bfd, ff2v, ff2d, bfraw,  # [D*S, T] sharded fills
    lens, gids,  # [D*S]
    W0, SEL, idx,  # replicated window structure (mxu_jitter)
    c0pos_g, has_klo, has_khi,  # [J] replicated
    F0_rel, L0_rel, Klo_rel, Khi_rel, blo_rel, ehi_rel,  # [J]
    window_ms,
    num_groups: int,
    is_counter: bool = False,
    is_delta: bool = False,
    fetch: str = "auto",
):
    """Missing-scrape mesh aggregation: the masked jitter kernel
    (ops/mxu_jitter.jitter_masked_kernel) inside shard_map, so a dropped
    scrape keeps multi-shard queries on the single-program MXU path."""
    from ..ops.mxu_jitter import jitter_masked_kernel

    def local(vals_l, dev_l, raw_l, valid_l, cc_l, ffv_l, ffd_l, bfv_l,
              bfd_l, ff2v_l, ff2d_l, bfraw_l, lens_l, gids_l):
        grid = jitter_masked_kernel(
            func, vals_l, dev_l, raw_l, valid_l, cc_l,
            ffv_l, ffd_l, bfv_l, bfd_l, ff2v_l, ff2d_l, bfraw_l,
            W0, SEL, idx, c0pos_g, has_klo, has_khi,
            F0_rel, L0_rel, Klo_rel, Khi_rel, blo_rel, ehi_rel,
            window_ms, is_counter=is_counter, is_delta=is_delta, fetch=fetch,
        )
        grid = jnp.where((lens_l > 0)[:, None], grid, jnp.nan)
        return _segment_psum(op, grid, gids_l, num_groups)

    shard = P("shard")
    row = P("shard", None)
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(row,) * 12 + (shard, shard),
        out_specs=P(),
        check=False,
    )(vals, dev, raw, valid, cc, ffv, ffd, bfv, bfd, ff2v, ff2d, bfraw,
      lens, gids)


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "func", "op", "num_steps", "num_groups", "is_counter", "is_delta"),
)
def distributed_agg_range(
    mesh: Mesh,
    func: str,
    op: str,
    ts,  # [D*S, T] i32, sharded over devices
    vals,  # [D*S, T] f32
    lens,  # [D*S] i32
    baseline,  # [D*S] f32
    raw,  # [D*S, T] f32
    gids,  # [D*S] i32 group ids (global group numbering)
    start_off,
    step_ms,
    window,
    num_steps: int,
    num_groups: int,
    is_counter: bool = False,
    is_delta: bool = False,
):
    """sum/min/max/count/avg-by over a range function, sharded over the mesh.

    Returns [num_groups, num_steps] — already reduced across every shard via
    psum on ICI (the on-device form of ReduceAggregateExec).
    """

    def local(ts_l, vals_l, lens_l, base_l, raw_l, gids_l):
        grid = K.range_kernel(
            func, ts_l, vals_l, lens_l, base_l, raw_l,
            start_off, step_ms, window, num_steps,
            is_counter=is_counter, is_delta=is_delta,
        )
        return _segment_psum(op, grid, gids_l, num_groups)

    shard = P("shard")
    row = P("shard", None)
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(row, row, shard, shard, row, shard),
        out_specs=P(),
        check=False,
    )(ts, vals, lens, baseline, raw, gids)


def _mesh_layout(blocks: list[StagedBlock], n_devices: int):
    """Shared row layout for every mesh stacker: round-robin blocks over
    devices, one padded row band per device."""
    D = n_devices
    T = max(b.ts.shape[1] for b in blocks)
    per_dev: list[list[int]] = [[] for _ in range(D)]
    for i in range(len(blocks)):
        per_dev[i % D].append(i)
    S_dev = pad_series(max(1, max(
        sum(blocks[i].n_series for i in idxs) for idxs in per_dev
    )))
    return per_dev, S_dev, T


def stack_masked_for_mesh(blocks: list[StagedBlock], n_devices: int):
    """Stack the MaskedGrid sidecars (missing-scrape mesh path) using the
    SAME row layout as stack_blocks_for_mesh, recomputing the fills over the
    stacked width so padding columns carry correct forward/backward fills.
    Caller guarantees every non-empty block has a harmonized mgrid.
    Returns (vals, dev, raw, valid, cc, ffv, ffd, bfv, bfd, ff2v, ff2d,
    bfraw), all [D*S, T] f32."""
    from ..ops.staging import masked_fills

    per_dev, S_dev, _ = _mesh_layout(blocks, n_devices)
    # masked sidecars size by SLOT span, which can exceed the packed T
    T = max(b.mgrid.valid.shape[1] for b in blocks if b.mgrid is not None)
    D = n_devices
    N = D * S_dev
    vals = np.zeros((N, T), dtype=np.float32)
    dev = np.zeros((N, T), dtype=np.float32)
    raw = np.zeros((N, T), dtype=np.float32)
    valid = np.zeros((N, T), dtype=np.float32)
    g0 = next(b.mgrid for b in blocks if b.n_series > 0)
    interval = g0.interval_ms
    R0 = int(np.asarray(g0.nominal_ts)[0])
    R = np.rint(R0 + np.arange(T, dtype=np.float64) * interval).astype(np.int64)
    for d, idxs in enumerate(per_dev):
        o = d * S_dev
        for i in idxs:
            b = blocks[i]
            k = b.n_series
            if k == 0:
                continue
            g = b.mgrid
            t = g.valid.shape[1]
            valid[o : o + k, :t] = np.asarray(g.valid)[:k]
            vals[o : o + k, :t] = np.asarray(g.vals)[:k]
            dev[o : o + k, :t] = np.asarray(g.dev)[:k]
            raw_src = g.raw if g.raw is not None else g.vals
            raw[o : o + k, :t] = np.asarray(raw_src)[:k]
            o += k
    ffv, ffd, bfv, bfd, ff2v, ff2d, bfraw = masked_fills(
        valid, vals, dev, raw, R
    )
    cc = np.cumsum(valid, axis=1, dtype=np.float64).astype(np.float32)
    return vals, dev, raw, valid, cc, ffv, ffd, bfv, bfd, ff2v, ff2d, bfraw


def stack_blocks_for_mesh(blocks: list[StagedBlock], gids_per_block: list[np.ndarray], n_devices: int,
                          with_dev: bool = False):
    """Concatenate per-shard staged blocks into mesh-shardable arrays.

    Blocks distribute round-robin over devices (several shards may share a
    device — the single-chip case packs ALL shards into one block). Padded
    rows get group id 0 with len 0 (they contribute nothing).
    With ``with_dev``, also returns the stacked [D*S, T] timestamp-deviation
    matrix for the jittered-grid mesh path (zeros where a block has none)."""
    D = n_devices
    per_dev, S_dev, T = _mesh_layout(blocks, n_devices)
    ts = np.full((D * S_dev, T), np.int32(2**31 - 1), dtype=np.int32)
    vals = np.zeros((D * S_dev, T), dtype=np.float32)
    raw = np.zeros((D * S_dev, T), dtype=np.float32)
    lens = np.zeros(D * S_dev, dtype=np.int32)
    baseline = np.zeros(D * S_dev, dtype=np.float32)
    gids = np.zeros(D * S_dev, dtype=np.int32)
    dev = np.zeros((D * S_dev, T), dtype=np.float32) if with_dev else None
    for d, idxs in enumerate(per_dev):
        o = d * S_dev
        for i in idxs:
            b, g = blocks[i], gids_per_block[i]
            t = b.ts.shape[1]
            k = b.n_series
            ts[o : o + k, :t] = np.asarray(b.ts)[:k]
            vals[o : o + k, :t] = np.asarray(b.vals)[:k]
            raw_src = b.raw if b.raw is not None else b.vals
            raw[o : o + k, :t] = np.asarray(raw_src)[:k]
            lens[o : o + k] = np.asarray(b.lens)[:k]
            baseline[o : o + k] = np.asarray(b.baseline)[:k]
            gids[o : o + k] = g
            if with_dev and b.ts_dev is not None:
                dev[o : o + k, :t] = np.asarray(b.ts_dev)[:k]
            o += k
    if with_dev:
        return ts, vals, lens, baseline, raw, gids, dev
    return ts, vals, lens, baseline, raw, gids


def shard_arrays(mesh: Mesh, ts, vals, lens, baseline, raw, gids):
    """Place the stacked arrays on the mesh with shard-axis sharding."""
    row = NamedSharding(mesh, P("shard", None))
    vec = NamedSharding(mesh, P("shard"))
    return (
        jax.device_put(ts, row),
        jax.device_put(vals, row),
        jax.device_put(lens, vec),
        jax.device_put(baseline, vec),
        jax.device_put(raw, row),
        jax.device_put(gids, vec),
    )
