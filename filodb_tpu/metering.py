"""Tenant metering + label churn (reference: TenantIngestionMetering
(coordinator, 111 LoC) publishing per-tenant cardinality metrics, and the
spark-jobs LabelChurnFinder which sketches label-value churn with HLL).

Churn here uses exact capped sets per window (HLL precision is unnecessary
at per-shard scale; the cap bounds memory like HLL's fixed size).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from .metrics import REGISTRY


class TenantIngestionMetering:
    """Publishes per-tenant (ws/ns) active & total series gauges from the
    shards' cardinality trackers. Call ``publish`` on a timer."""

    def __init__(self, memstore, dataset: str):
        self.memstore = memstore
        self.dataset = dataset

    def collect(self) -> dict[tuple[str, str], dict]:
        merged: dict[tuple[str, str], dict] = {}
        for sh in self.memstore.shards(self.dataset):
            for rec in sh.cardinality.scan((), 2):
                key = (rec.prefix[0], rec.prefix[1])
                slot = merged.setdefault(key, {"ts_count": 0, "active": 0})
                slot["ts_count"] += rec.ts_count
                slot["active"] += rec.active_ts_count
        return merged

    def publish(self) -> int:
        stats = self.collect()
        for (ws, ns), rec in stats.items():
            REGISTRY.gauge("filodb_tenant_ts_total", ws=ws, ns=ns).set(rec["ts_count"])
            REGISTRY.gauge("filodb_tenant_ts_active", ws=ws, ns=ns).set(rec["active"])
        return len(stats)


@dataclass
class LabelChurn:
    label: str
    window_values: set = field(default_factory=set)
    prev_values: set = field(default_factory=set)
    total_seen: int = 0


class LabelChurnFinder:
    """Tracks per-label value churn across roll windows: how many label
    values are NEW relative to the previous window — the signal for
    runaway cardinality sources (reference LabelChurnFinder)."""

    def __init__(self, labels: list[str], cap_per_label: int = 100_000):
        self._state = {l: LabelChurn(l) for l in labels}
        self.cap = cap_per_label

    def observe(self, tags) -> None:
        for l, st in self._state.items():
            v = tags.get(l)
            if v is not None and len(st.window_values) < self.cap:
                if v not in st.window_values:
                    st.window_values.add(v)
                    st.total_seen += 1

    def roll(self) -> dict[str, dict]:
        """Close the window; returns per-label churn stats."""
        out = {}
        for l, st in self._state.items():
            new = st.window_values - st.prev_values
            out[l] = {
                "distinct": len(st.window_values),
                "new": len(new),
                "churn_ratio": len(new) / max(len(st.window_values), 1),
            }
            st.prev_values = st.window_values
            st.window_values = set()
        return out

    def scan_shard(self, shard) -> None:
        for part in list(shard.partitions.values()):
            self.observe(part.tags)
