"""Tenant metering + label churn (reference: TenantIngestionMetering
(coordinator, 111 LoC) publishing per-tenant cardinality metrics, and the
spark-jobs LabelChurnFinder which sketches label-value churn with HLL).

Churn here uses exact capped sets per window (HLL precision is unnecessary
at per-shard scale; the cap bounds memory like HLL's fixed size).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from .metrics import REGISTRY


# the per-tenant series gauges publish() maintains (and ages out)
_TENANT_SERIES_GAUGES = ("filodb_tenant_ts_total", "filodb_tenant_ts_active")


class TenantIngestionMetering:
    """Publishes per-tenant (ws/ns) active & total series gauges from the
    shards' cardinality trackers. Call ``publish`` on a timer."""

    def __init__(self, memstore, dataset: str):
        self.memstore = memstore
        self.dataset = dataset
        # tenants published last cycle: a tenant that vanishes (eviction,
        # retention) must have its gauges REMOVED, not frozen at the last
        # value forever (Registry.remove is the series-aging primitive)
        self._published: set[tuple[str, str]] = set()

    def collect(self) -> dict[tuple[str, str], dict]:
        merged: dict[tuple[str, str], dict] = {}
        for sh in self.memstore.shards(self.dataset):
            for rec in sh.cardinality.scan((), 2):
                key = (rec.prefix[0], rec.prefix[1])
                slot = merged.setdefault(key, {"ts_count": 0, "active": 0})
                slot["ts_count"] += rec.ts_count
                slot["active"] += rec.active_ts_count
        return merged

    def publish(self) -> int:
        stats = self.collect()
        live = set(stats)
        for ws, ns in self._published - live:
            for name in _TENANT_SERIES_GAUGES:
                REGISTRY.remove(name, ws=ws, ns=ns)
        for (ws, ns), rec in stats.items():
            REGISTRY.gauge("filodb_tenant_ts_total", ws=ws, ns=ns).set(rec["ts_count"])
            REGISTRY.gauge("filodb_tenant_ts_active", ws=ws, ns=ns).set(rec["active"])
        self._published = live
        return len(stats)


# -- per-query tenant attribution (the admission-control foundation) --------


def tenant_of_filters(filters) -> tuple[str | None, str | None]:
    """(ws, ns) from equality matchers on the shard-key tenant columns
    (``_ws_``/``_ns_``); None components when the filters don't pin one."""
    ws = ns = None
    for f in filters or ():
        if getattr(f, "op", None) != "=":
            continue
        if f.column == "_ws_":
            ws = str(f.value)
        elif f.column == "_ns_":
            ns = str(f.value)
    return ws, ns


def tenant_of_plan(plan) -> tuple[str, str]:
    """Resolve the query's tenant from its logical plan's raw-series leaves
    (the ExecPlan boundary: every leaf carries the selector's filters).
    Multi-tenant or tenant-less selections attribute to ``unknown`` — the
    honest bucket; quotas act on pinned tenants."""
    try:
        from .query.logical import leaf_raw_series

        leaves = leaf_raw_series(plan)
    except Exception:  # noqa: BLE001 — metadata plans have no series leaves
        leaves = []
    ws = ns = None
    for leaf in leaves:
        lws, lns = tenant_of_filters(getattr(leaf, "filters", ()))
        if lws is not None:
            if ws is not None and ws != lws:
                return "unknown", "unknown"  # cross-tenant query
            ws = lws
        if lns is not None:
            if ns is not None and ns != lns:
                return "unknown", "unknown"
            ns = lns
    return ws or "unknown", ns or "unknown"


# tenant labels come from CLIENT-supplied query matchers: without a bound,
# a scripted loop of made-up _ws_ values grows the registry (4 counter
# series per pair) forever. Past the cap, new pairs pool into "overflow".
MAX_TENANT_PAIRS = 256
_tenant_pairs: set[tuple[str, str]] = set()
_tenant_pairs_lock = threading.Lock()


def bounded_tenant_pair(ws: str, ns: str) -> tuple[str, str]:
    """Apply the :data:`MAX_TENANT_PAIRS` overflow-bucket cap: the pair
    itself when it is already known or the cap has room, else
    ``("overflow", "overflow")``. The ONE cardinality gate shared by the
    tenant resource counters here and the admission-control counters/state
    (query/scheduler.py) — both are driven by client-supplied labels."""
    with _tenant_pairs_lock:
        if (ws, ns) not in _tenant_pairs:
            if len(_tenant_pairs) >= MAX_TENANT_PAIRS:
                return "overflow", "overflow"
            _tenant_pairs.add((ws, ns))
    return ws, ns


def record_tenant_query(ws: str, ns: str, query_seconds: float,
                        kernel_seconds: float, bytes_staged: int) -> None:
    """Accumulate one finished query into the per-tenant resource counters
    — the accounting the ROADMAP's admission-control item builds quotas on:

    - ``filodb_tenant_queries_total{ws,ns}``
    - ``filodb_tenant_query_seconds_total{ws,ns}`` (wall clock)
    - ``filodb_tenant_kernel_seconds_total{ws,ns}`` (device dispatch)
    - ``filodb_tenant_bytes_staged_total{ws,ns}`` (HBM uploads)
    - ``filodb_tenant_query_latency_seconds{ws,ns}`` (histogram — the
      per-tenant latency-SLO feed obs/slo.py's burn-rate rules quantile
      over; counters can't answer "is tenant X's p99 over objective")

    Cardinality is bounded: at most :data:`MAX_TENANT_PAIRS` distinct
    (ws, ns) label pairs; later pairs attribute to ``overflow``."""
    ws, ns = bounded_tenant_pair(ws, ns)
    REGISTRY.counter("filodb_tenant_queries", ws=ws, ns=ns).inc()
    REGISTRY.counter("filodb_tenant_query_seconds", ws=ws, ns=ns).inc(
        float(query_seconds)
    )
    REGISTRY.histogram(
        "filodb_tenant_query_latency_seconds", ws=ws, ns=ns
    ).observe(float(query_seconds))
    REGISTRY.counter("filodb_tenant_kernel_seconds", ws=ws, ns=ns).inc(
        float(kernel_seconds)
    )
    REGISTRY.counter("filodb_tenant_bytes_staged", ws=ws, ns=ns).inc(
        int(bytes_staged)
    )


def tenant_query_snapshot() -> dict[str, dict]:
    """Current per-tenant query-resource totals, keyed ``ws/ns`` (the
    /debug/resources rendering)."""
    names = {
        "filodb_tenant_queries": "queries",
        "filodb_tenant_query_seconds": "query_seconds",
        "filodb_tenant_kernel_seconds": "kernel_seconds",
        "filodb_tenant_bytes_staged": "bytes_staged",
    }
    out: dict[str, dict] = {}
    with REGISTRY._lock:
        items = [(k, m.value) for k, m in REGISTRY._metrics.items()
                 if k[0] in names]
    for (name, labels), value in items:
        lbl = dict(labels)
        key = f"{lbl.get('ws', '?')}/{lbl.get('ns', '?')}"
        out.setdefault(key, {})[names[name]] = round(value, 6)
    return out


@dataclass
class LabelChurn:
    label: str
    window_values: set = field(default_factory=set)
    prev_values: set = field(default_factory=set)
    total_seen: int = 0


class LabelChurnFinder:
    """Tracks per-label value churn across roll windows: how many label
    values are NEW relative to the previous window — the signal for
    runaway cardinality sources (reference LabelChurnFinder)."""

    def __init__(self, labels: list[str], cap_per_label: int = 100_000):
        self._state = {l: LabelChurn(l) for l in labels}
        self.cap = cap_per_label

    def observe(self, tags) -> None:
        for l, st in self._state.items():
            v = tags.get(l)
            if v is not None and len(st.window_values) < self.cap:
                if v not in st.window_values:
                    st.window_values.add(v)
                    st.total_seen += 1

    def roll(self) -> dict[str, dict]:
        """Close the window; returns per-label churn stats."""
        out = {}
        for l, st in self._state.items():
            new = st.window_values - st.prev_values
            out[l] = {
                "distinct": len(st.window_values),
                "new": len(new),
                "churn_ratio": len(new) / max(len(st.window_values), 1),
            }
            st.prev_values = st.window_values
            st.window_values = set()
        return out

    def scan_shard(self, shard) -> None:
        for part in list(shard.partitions.values()):
            self.observe(part.tags)
