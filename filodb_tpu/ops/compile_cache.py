"""Persistent JAX compilation cache (SURVEY §7: recompilation is the #1
risk; BENCH_r05 measured a 24.6 s cold stage+compile warmup).

XLA executables for the shape-bucketed kernel set are small and extremely
reusable — padding discipline (staging.pad_series/pad_time, kernels
.pad_steps) means a production process compiles a handful of programs and
then never again. Persisting them to disk makes that true ACROSS process
restarts too: a rolling deploy or crash-restart skips straight to warm
dispatch latencies instead of re-paying multi-second XLA compiles.

Config: top-level ``compile_cache_dir`` —

- ``"auto"`` (default): ``<store_root>/jax-compile-cache`` when a data dir
  is configured, else ``~/.cache/filodb-tpu/jax-compile-cache``;
- an explicit path: used as-is;
- ``null``/empty: disabled.

Thresholds are forced to zero so even the fast-compiling CPU-backend
programs persist (jax's defaults skip entries under 1s compile time, which
would exclude most of our kernel set on small shapes).
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger("filodb_tpu.compile_cache")

_enabled_dir: str | None = None


def resolve_cache_dir(config: dict) -> str | None:
    """Map the ``compile_cache_dir`` knob to a concrete path (or None)."""
    raw = config.get("compile_cache_dir", "auto")
    if not raw:
        return None
    if raw != "auto":
        return str(raw)
    store_root = config.get("store_root")
    if store_root:
        return os.path.join(str(store_root), "jax-compile-cache")
    return os.path.join(
        os.path.expanduser(os.environ.get("XDG_CACHE_HOME", "~/.cache")),
        "filodb-tpu", "jax-compile-cache",
    )


def enable_compile_cache(cache_dir: str | None) -> str | None:
    """Point jax's persistent compilation cache at ``cache_dir``.

    Idempotent; returns the active dir or None when disabled/unsupported.
    Must run before the first jit dispatch to benefit that process's cold
    start (later calls still help subsequent compiles)."""
    global _enabled_dir
    if not cache_dir:
        return None
    if _enabled_dir == cache_dir:
        return _enabled_dir
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        for knob, v in (
            ("jax_persistent_cache_min_compile_time_secs", 0.0),
            ("jax_persistent_cache_min_entry_size_bytes", -1),
        ):
            try:
                jax.config.update(knob, v)
            except (AttributeError, ValueError):  # knob renamed/absent
                pass
        _enabled_dir = cache_dir
        _register_ledger_account(cache_dir)
        log.info("persistent jax compile cache at %s", cache_dir)
    except Exception as e:  # noqa: BLE001 — cache is an optimization, never fatal
        log.warning("persistent compile cache unavailable: %s", e)
        return None
    return _enabled_dir


class _CompileCacheProbe:
    """Ledger-account owner for the persistent compile cache: jax writes the
    entries, we only observe — the account is self-syncing from a disk walk
    (and also refreshes the entry-count gauge at scrape time)."""

    WALK_TTL_S = 15.0  # scrape-time collector: don't re-stat the dir per scrape

    def __init__(self, cache_dir: str):
        self.cache_dir = cache_dir
        self._walked_at = 0.0
        self._walked_bytes = 0

    def walk_bytes(self) -> int:
        import time

        from ..metrics import REGISTRY

        now = time.monotonic()
        if now - self._walked_at < self.WALK_TTL_S:
            return self._walked_bytes
        total = entries = 0
        for root, _dirs, files in os.walk(self.cache_dir):
            for f in files:
                try:
                    total += os.path.getsize(os.path.join(root, f))
                    entries += 1
                except OSError:
                    continue
        REGISTRY.gauge("filodb_compile_cache_entries").set(float(entries))
        self._walked_at = now
        self._walked_bytes = total
        return total


_probe: _CompileCacheProbe | None = None


def _register_ledger_account(cache_dir: str) -> None:
    """One compile-cache account in the device ledger (kind
    ``compile_cache``): re-registered (not stacked) when the dir changes."""
    global _probe
    from ..ledger import LEDGER

    # dropping the old probe unregisters its account via the weakref
    _probe = _CompileCacheProbe(cache_dir)
    LEDGER.register(
        _probe, "compile_cache", _CompileCacheProbe.walk_bytes,
        name=cache_dir, synced=True,
    )


def enable_from_config(config: dict) -> str | None:
    return enable_compile_cache(resolve_cache_dir(config))
