"""Persistent JAX compilation cache (SURVEY §7: recompilation is the #1
risk; BENCH_r05 measured a 24.6 s cold stage+compile warmup).

XLA executables for the shape-bucketed kernel set are small and extremely
reusable — padding discipline (staging.pad_series/pad_time, kernels
.pad_steps) means a production process compiles a handful of programs and
then never again. Persisting them to disk makes that true ACROSS process
restarts too: a rolling deploy or crash-restart skips straight to warm
dispatch latencies instead of re-paying multi-second XLA compiles.

Config: top-level ``compile_cache_dir`` —

- ``"auto"`` (default): ``<store_root>/jax-compile-cache`` when a data dir
  is configured, else ``~/.cache/filodb-tpu/jax-compile-cache``;
- an explicit path: used as-is;
- ``null``/empty: disabled.

Thresholds are forced to zero so even the fast-compiling CPU-backend
programs persist (jax's defaults skip entries under 1s compile time, which
would exclude most of our kernel set on small shapes).
"""

from __future__ import annotations

import logging
import os
import threading

log = logging.getLogger("filodb_tpu.compile_cache")

_enabled_dir: str | None = None

# compile-provenance state (classify_dispatch): the persistent entries seen
# on disk so far — a compile event that added a file was a FRESH trace
# (jax wrote its serialized executable), one that didn't was served FROM
# the persistent cache. Initialized when the cache is enabled.
_seen_lock = threading.Lock()
_seen_entries: set[str] | None = None


def _list_entries(cache_dir: str) -> dict[str, int]:
    """{relative path: size} of every persistent-cache entry file."""
    out: dict[str, int] = {}
    for root, _dirs, files in os.walk(cache_dir):
        for f in files:
            p = os.path.join(root, f)
            try:
                out[os.path.relpath(p, cache_dir)] = os.path.getsize(p)
            except OSError:
                continue
    return out


def classify_dispatch(compiled: bool) -> tuple[str, int | None]:
    """Classify one kernel dispatch's compile provenance and feed the
    ``filodb_compile_cache_{hits,misses}_total{tier=}`` counters — the
    cache's own numbers the executable registry's per-key provenance must
    reconcile with (both sides are fed from THIS one call).

    - ``compiled=False``  -> ``("in_process", None)``: the jit cache hit —
      counted ``hits{tier=in_process}``, the steady state.
    - ``compiled=True``   -> the in-process cache missed
      (``misses{tier=in_process}``). With the persistent cache enabled the
      disk tells the rest: a NEW entry file means jax traced + compiled
      from nothing and persisted it (``("fresh", entry_bytes)``, counted
      ``misses{tier=persistent}`` — the returned size is the serialized
      executable, the observatory's executable-bytes figure); no new file
      means the compile was deserialized from disk
      (``("persistent", None)``, counted ``hits{tier=persistent}``).
      Without a persistent cache every compile is ``("fresh", None)``.

    Walks the cache dir only on compile events (rare by construction —
    SURVEY §7's whole point), never on warm dispatches.

    Attribution is best-effort under CONCURRENT compiles (mirroring the
    ``_jit_cache_size`` contract): two racing fresh compiles can swap
    classifications (the first diff sees the other's entry), and when a
    diff finds more than one new file the entry-bytes attribution is
    ambiguous and returns None rather than summing unrelated executables.
    The steady-state signal is exact — warm serving is all
    ``in_process``, and any persistent-tier activity at all means
    compiles are happening."""
    from ..metrics import REGISTRY

    if not compiled:
        REGISTRY.counter("filodb_compile_cache_hits",
                         tier="in_process").inc()
        return "in_process", None
    REGISTRY.counter("filodb_compile_cache_misses", tier="in_process").inc()
    if _enabled_dir is None:
        return "fresh", None
    with _seen_lock:
        global _seen_entries
        before = _seen_entries if _seen_entries is not None else {}
        now = _list_entries(_enabled_dir)
        new = [p for p in now if p not in before]
        _seen_entries = set(now)
    if new:
        REGISTRY.counter("filodb_compile_cache_misses",
                         tier="persistent").inc()
        # exactly one new entry (jax pairs each `…-cache` payload with an
        # `…-atime` sidecar — only the payload is the executable): it is
        # this compile's serialized form; several means racing compiles
        # landed together and per-file attribution would be a guess
        payloads = [p for p in new if not p.endswith("-atime")]
        return "fresh", (now[payloads[0]] if len(payloads) == 1 else None)
    REGISTRY.counter("filodb_compile_cache_hits", tier="persistent").inc()
    return "persistent", None


def resolve_cache_dir(config: dict) -> str | None:
    """Map the ``compile_cache_dir`` knob to a concrete path (or None)."""
    raw = config.get("compile_cache_dir", "auto")
    if not raw:
        return None
    if raw != "auto":
        return str(raw)
    store_root = config.get("store_root")
    if store_root:
        return os.path.join(str(store_root), "jax-compile-cache")
    return os.path.join(
        os.path.expanduser(os.environ.get("XDG_CACHE_HOME", "~/.cache")),
        "filodb-tpu", "jax-compile-cache",
    )


def enable_compile_cache(cache_dir: str | None) -> str | None:
    """Point jax's persistent compilation cache at ``cache_dir``.

    Idempotent; returns the active dir or None when disabled/unsupported.
    Must run before the first jit dispatch to benefit that process's cold
    start (later calls still help subsequent compiles)."""
    global _enabled_dir
    if not cache_dir:
        return None
    if _enabled_dir == cache_dir:
        return _enabled_dir
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        for knob, v in (
            ("jax_persistent_cache_min_compile_time_secs", 0.0),
            ("jax_persistent_cache_min_entry_size_bytes", -1),
        ):
            try:
                jax.config.update(knob, v)
            except (AttributeError, ValueError):  # knob renamed/absent
                pass
        try:
            # jax latches a cache-unused verdict at the FIRST compile
            # (compilation_cache._cache_checked, initialized at most once):
            # a process that compiled anything before this call would
            # silently never persist. Reset so the new dir takes effect —
            # existing executables stay in the in-process jit caches.
            from jax._src import compilation_cache as _jcc

            _jcc.reset_cache()
        except Exception:  # noqa: BLE001 — internal API; best-effort
            pass
        _enabled_dir = cache_dir
        # seed the provenance baseline: entries already on disk must read
        # as persistent-cache HITS when a compile deserializes them, not
        # as fresh traces (classify_dispatch diffs against this set)
        global _seen_entries
        with _seen_lock:
            _seen_entries = set(_list_entries(cache_dir))
        _register_ledger_account(cache_dir)
        log.info("persistent jax compile cache at %s", cache_dir)
    except Exception as e:  # noqa: BLE001 — cache is an optimization, never fatal
        log.warning("persistent compile cache unavailable: %s", e)
        return None
    return _enabled_dir


class _CompileCacheProbe:
    """Ledger-account owner for the persistent compile cache: jax writes the
    entries, we only observe — the account is self-syncing from a disk walk
    (and also refreshes the entry-count gauge at scrape time).

    The walk is double-memoized: a TTL bounds how often the dir is stat'd
    at all, and past the TTL the walk itself only re-runs when the cache
    dir's mtime moved (jax writes entry files flat into the dir, so an
    add/remove bumps it) — steady state pays ONE stat per TTL instead of
    re-stat'ing every entry."""

    WALK_TTL_S = 15.0  # scrape-time collector: don't re-stat the dir per scrape

    def __init__(self, cache_dir: str):
        self.cache_dir = cache_dir
        self._stat_at = 0.0
        self._walked = False
        self._walked_bytes = 0
        self._walked_entries = 0
        self._mtime_ns = -1

    def walk_bytes(self) -> int:
        import time

        from ..metrics import REGISTRY

        now = time.monotonic()
        if self._walked and now - self._stat_at < self.WALK_TTL_S:
            return self._walked_bytes
        self._stat_at = now
        try:
            mtime_ns = os.stat(self.cache_dir).st_mtime_ns
        except OSError:
            mtime_ns = -2  # unreadable dir: fall through to the walk
        if self._walked and mtime_ns >= 0 and mtime_ns == self._mtime_ns:
            # nothing changed since the last walk — keep the memo (the
            # gauge re-sets cheaply so a registry reset still heals)
            REGISTRY.gauge("filodb_compile_cache_entries").set(
                float(self._walked_entries)
            )
            return self._walked_bytes
        total = entries = 0
        for root, _dirs, files in os.walk(self.cache_dir):
            for f in files:
                try:
                    total += os.path.getsize(os.path.join(root, f))
                    entries += 1
                except OSError:
                    continue
        REGISTRY.gauge("filodb_compile_cache_entries").set(float(entries))
        self._walked = True
        self._mtime_ns = mtime_ns
        self._walked_bytes = total
        self._walked_entries = entries
        return total


_probe: _CompileCacheProbe | None = None


def _register_ledger_account(cache_dir: str) -> None:
    """One compile-cache account in the device ledger (kind
    ``compile_cache``): re-registered (not stacked) when the dir changes."""
    global _probe
    from ..ledger import LEDGER

    # dropping the old probe unregisters its account via the weakref
    _probe = _CompileCacheProbe(cache_dir)
    LEDGER.register(
        _probe, "compile_cache", _CompileCacheProbe.walk_bytes,
        name=cache_dir, synced=True,
    )


def enable_from_config(config: dict) -> str | None:
    return enable_compile_cache(resolve_cache_dir(config))
