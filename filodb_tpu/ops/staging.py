"""Staging: memstore chunk windows -> fixed-shape device blocks.

This is the TPU-native replacement for the reference's per-series iterator
read path (ChunkedWindowIterator, PeriodicSamplesMapper.scala:256): instead of
cursoring over encoded off-heap vectors per window, we gather ALL samples for
ALL selected series in [start - lookback, end] into one padded
``[series, time]`` block, push it to HBM once, and let jit kernels compute
every output step for every series at once.

Shape discipline (SURVEY.md §7 "ragged data vs static shapes" — the #1 risk):
- NaN samples (Prometheus staleness markers) are dropped host-side; validity
  on device is purely "index < length", so kernels never branch on NaN inputs.
- Timestamps become int32 ms offsets from ``base_ms`` (exact for ranges up to
  ~24 days; queries longer than that split at the planner like the
  reference's LongTimeRangePlanner).
- Cumulative counters are reset-corrected HOST-SIDE in f64 (the prefix-sum
  form of the reference's CorrectingDoubleVectorReader carry), then staged
  minus a per-series baseline: staged values are small monotone increments, so
  f32 keeps full precision even on 1e15-magnitude raw counters, and the device
  needs no correction pass at all. A corrected-value difference across a reset
  equals the post-reset raw reading — exactly Prometheus' reset semantics —
  so rate/irate need no reset branches on device. Raw-minus-baseline offsets
  ride along only for Prometheus' zero-crossing extrapolation cap.
- S and T pad up to bucketed sizes so the jit cache stays small.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

# S pads to the next bucket; T pads to a multiple of 128 (TPU lane width)
_S_BUCKETS = (8, 32, 128, 512, 1024, 2048, 4096, 8192, 16384, 32768, 65536, 131072)


def pad_series(s: int) -> int:
    for b in _S_BUCKETS:
        if s <= b:
            return b
    return ((s + 8191) // 8192) * 8192


def pad_time(t: int) -> int:
    return max(128, ((t + 127) // 128) * 128)


TS_PAD = np.int32(2**31 - 1)  # padded slots sort after every real timestamp

# Widest selector span a staged block can represent exactly: ts offsets are
# int32 ms from base_ms (the selector start), so anything wider wraps
# negative and searchsorted over the no-longer-sorted vector silently
# empties late windows. Consumers that window over staged offsets
# (the fused superblock paths) must refuse wider selections up front;
# ~24.8 days — long-range reads beyond it are the rollup tier's job.
MAX_STAGE_SPAN_MS = 2**31 - 2


def series_put(mesh):
    """``jax.device_put`` closure for a block placement: single-device when
    ``mesh`` is None, else series-axis row sharding
    (``PartitionSpec(axis)`` — trailing dims replicate implicitly, so ONE
    spec covers [S], [S, T] and [S, T, B] arrays alike)."""
    import jax

    if mesh is None:
        return jax.device_put
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(mesh, PartitionSpec(mesh.axis_names[0]))
    return lambda a: jax.device_put(a, sharding)


def replicated_put(mesh):
    """``jax.device_put`` closure committing an array REPLICATED across the
    mesh (window matrices, group-id-free [J] vectors): placed once at build
    so warm dispatches pay no per-call broadcast transfer."""
    import jax

    if mesh is None:
        return jax.device_put
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(mesh, PartitionSpec())
    return lambda a: jax.device_put(a, sharding)


def mesh_spec_str(mesh) -> str | None:
    """Human-readable sharding descriptor for introspection endpoints
    (/debug/superblocks) — the EXACT spec series_put applies: leading dim
    sharded, trailing dims implicitly replicated whatever the rank."""
    if mesh is None:
        return None
    axis = mesh.axis_names[0]
    return f"PartitionSpec('{axis}') x {mesh.devices.size} devices"


def mesh_device_bytes(mesh, nbytes: int) -> dict | None:
    """Even per-device byte attribution of a series-sharded block (the row
    arrays dominate and split evenly across the mesh)."""
    if mesh is None:
        return None
    devs = list(mesh.devices.flat)
    per = nbytes // len(devs)
    out = {str(d): per for d in devs}
    # remainder lands on the first device so the totals stay exact
    out[str(devs[0])] += nbytes - per * len(devs)
    return out

# masked (missing-scrape) grid detection: tolerate up to this fraction of
# holes before dropping to the general gather path
MAX_HOLE_FRAC = 0.05


@dataclass
class MaskedGrid:
    """Slot-aligned sidecar for near-regular data with MISSED scrapes.

    The packed block arrays stay canonical (the general kernels and every
    other consumer read those); this sidecar maps each sample to its nominal
    slot and carries per-slot validity plus host-precomputed forward/backward
    fills, so the masked jitter kernel (ops/mxu_jitter.jitter_masked_kernel)
    can evaluate first/last/rate with shared-index fetches instead of
    per-series scans. All [S, T] f32; holes carry 0. Fill semantics:

    - ffv/ffd: value / time-offset of the LAST valid slot <= t
      (ffd = R[t'] - R[t] + dev[s, t'], small by construction)
    - bfv/bfd: value / time-offset of the FIRST valid slot >= t
    - ff2v/ff2d: value / time-offset of the SECOND-TO-LAST valid slot <= t
    - bfraw: backward fill of raw values (counter extrapolation cap only)

    Window-semantics contract: reference PeriodicSamplesMapper.scala:256 —
    the same windows the reference's iterators produce over data with gaps.
    """

    nominal_ts: np.ndarray  # [T] int32 ms offsets of the slot grid
    n_valid: int  # real slot count (grid width; <= T)
    interval_ms: float  # refined nominal interval (grid = t0 + k*interval)
    maxdev_ms: int
    valid: np.ndarray  # [S, T] f32 1.0 = real sample
    vals: np.ndarray  # [S, T] f32 transformed values, 0 at holes
    dev: np.ndarray  # [S, T] f32 ts deviation from nominal, 0 at holes
    raw: np.ndarray | None  # [S, T] f32 raw values (counters), 0 at holes
    ffv: np.ndarray
    ffd: np.ndarray
    bfv: np.ndarray
    bfd: np.ndarray
    ff2v: np.ndarray
    ff2d: np.ndarray
    bfraw: np.ndarray | None
    # cumulative valid count (prefix sum): per-series window counts become
    # two shared-index fetches instead of a [S,T]x[T,J] matmul
    cc: np.ndarray | None = None

    def to_device(self, put=None):
        """``put`` overrides the placement of every [S, T'] array (a
        series-sharded superblock passes its row-band sharding so the
        masked fused program spans the mesh without a gather)."""
        import jax

        if put is None:
            put = jax.device_put
        for f in ("valid", "vals", "dev", "raw", "ffv", "ffd", "bfv", "bfd",
                  "ff2v", "ff2d", "bfraw", "cc"):
            a = getattr(self, f)
            if a is not None:
                setattr(self, f, put(a))
        return self


def _snap_slots(cleaned) -> tuple[float, float, list] | None:
    """Estimate a shared nominal grid for series with missed scrapes.

    Returns (interval_ms, t0_ms, [per-series slot indices]) or None when the
    data isn't near-regular-with-holes. Holes make per-series sample counts
    differ, so the equal-count detection above can't see these blocks."""
    if not cleaned or any(len(ts) < 2 for ts, _ in cleaned):
        return None
    ref = max((ts for ts, _ in cleaned), key=len)
    d = np.diff(ref)
    if not len(d) or (d <= 0).any():
        return None
    est = float(np.median(d))
    if est <= 0:
        return None
    k = np.rint(d / est)
    if (k < 1).any():
        return None
    # least-squares interval refinement over the reference series
    interval = float(d.sum()) / float(k.sum())
    if interval <= 0:
        return None
    t0 = float(ref[0])
    ks = []
    for ts, _ in cleaned:
        ki = np.rint((ts.astype(np.float64) - t0) / interval).astype(np.int64)
        if len(ki) > 1 and (np.diff(ki) < 1).any():
            return None  # two samples snapped to one slot: not this grid
        ks.append(ki)
    return interval, t0, ks


def masked_fills(valid, m_vals, m_dev, m_raw, R):
    """Host-precomputed forward/backward fills over slot-aligned masked
    arrays (the MaskedGrid fill semantics); R is the full-length int64
    nominal offset vector. Returns (ffv, ffd, bfv, bfd, ff2v, ff2d, bfraw).

    Slots with NO valid neighbor in the fill direction carry value 0 and a
    SIGNED time sentinel (-3e38 forward, +3e38 backward) instead of 0: the
    kernels never select such slots, and the sentinel keeps the fill-time
    invariant the masked kernel's lean gather mode relies on — at a VALID
    slot t, ffd[t] == bfd[t] == dev[t] (|.| <= maxdev), while at a hole
    ffd is <= -(interval - maxdev) and bfd >= interval - maxdev — so
    window-boundary membership and slot validity are decidable from the
    time fills alone, without fetching the validity plane."""
    T = valid.shape[1]
    V = valid > 0
    tind = np.arange(T)

    def gather(a, idx):
        return np.take_along_axis(a, np.clip(idx, 0, T - 1), axis=1)

    ffi = np.maximum.accumulate(np.where(V, tind[None, :], -1), axis=1)
    rev = np.maximum.accumulate(np.where(V[:, ::-1], tind[None, :], -1), axis=1)
    bfi = np.where(rev[:, ::-1] >= 0, T - 1 - rev[:, ::-1], T)
    ff2i = np.where(ffi >= 1, gather(ffi, ffi - 1), -1)
    Rf = R.astype(np.float64)

    def fill(vsrc, idx, t_sentinel):
        ok = (idx >= 0) & (idx < T)
        v = np.where(ok, gather(vsrc, idx), 0.0).astype(np.float32)
        dd = np.where(
            ok,
            (Rf[np.clip(idx, 0, T - 1)] - Rf[tind[None, :]])
            + gather(m_dev, idx),
            t_sentinel,
        ).astype(np.float32)
        return v, dd

    ffv, ffd = fill(m_vals, ffi, -3e38)
    bfv, bfd = fill(m_vals, bfi, 3e38)
    ff2v, ff2d = fill(m_vals, ff2i, -3e38)
    bfraw = fill(m_raw, bfi, 3e38)[0] if m_raw is not None else None
    return ffv, ffd, bfv, bfd, ff2v, ff2d, bfraw


def _build_masked_grid(cleaned, base_ms, out_vals, out_raw, lens,
                       T: int, S: int, grid=None) -> MaskedGrid | None:
    """Slot-align already-transformed packed values onto a shared nominal
    grid with validity holes; returns None when the bound or hole-fraction
    checks fail. ``grid`` forces a (interval_ms, t0_abs_ms) pair — the
    harmonize path uses it to put every shard on ONE common grid (slot 0 at
    t0; per-block widths may differ, validity masks absorb the difference).
    """
    if grid is None:
        snap = _snap_slots(cleaned)
        if snap is None:
            return None
        interval, t0, ks = snap
        kmin = min(int(k[0]) for k in ks)
    else:
        interval, t0 = grid
        ks = []
        for ts, _ in cleaned:
            ki = np.rint((ts.astype(np.float64) - t0) / interval).astype(np.int64)
            if (ki < 0).any() or (len(ki) > 1 and (np.diff(ki) < 1).any()):
                return None
            ks.append(ki)
        kmin = 0
    kmax = max(int(k[-1]) for k in ks)
    width = kmax - kmin + 1
    # holes stretch the slot span beyond the packed sample width, so the
    # sidecar sizes itself by SLOT count (may exceed the packed block's T —
    # the masked kernel touches only sidecar arrays)
    T = max(T, pad_time(width))
    total = sum(len(k) for k in ks)
    if grid is None and total < len(ks) * width * (1.0 - MAX_HOLE_FRAC):
        return None
    # nominal slot times as exact ints; deviations measured against them
    nom_abs = np.rint(t0 + (kmin + np.arange(T, dtype=np.float64)) * interval
                      ).astype(np.int64)
    md = 0
    valid = np.zeros((S, T), dtype=np.float32)
    m_vals = np.zeros((S, T), dtype=np.float32)
    m_dev = np.zeros((S, T), dtype=np.float32)
    m_raw = np.zeros((S, T), dtype=np.float32) if out_raw is not None else None
    for i, ((ts, _), ki) in enumerate(zip(cleaned, ks)):
        slots = (ki - kmin).astype(np.int64)
        dv = ts - nom_abs[slots]
        md = max(md, int(np.abs(dv).max()))
        valid[i, slots] = 1.0
        m_vals[i, slots] = out_vals[i, : lens[i]]
        m_dev[i, slots] = dv.astype(np.float32)
        if m_raw is not None:
            m_raw[i, slots] = out_raw[i, : lens[i]]
    if 2 * md >= interval:
        return None  # same safety bound as the aligned jitter path
    R = (nom_abs - base_ms).astype(np.int64)
    if R.max() > 2**31 - 2 or R.min() < -(2**31):
        return None
    ffv, ffd, bfv, bfd, ff2v, ff2d, bfraw = masked_fills(
        valid, m_vals, m_dev, m_raw, R
    )
    nominal = np.full(T, TS_PAD, dtype=np.int32)
    nominal[:width] = R[:width].astype(np.int32)
    return MaskedGrid(
        nominal_ts=nominal, n_valid=width, interval_ms=float(interval),
        maxdev_ms=md, valid=valid, vals=m_vals, dev=m_dev, raw=m_raw,
        ffv=ffv, ffd=ffd, bfv=bfv, bfd=bfd, ff2v=ff2v, ff2d=ff2d,
        bfraw=bfraw, cc=np.cumsum(valid, axis=1, dtype=np.float64
                                  ).astype(np.float32),
    )


def harmonize_masked(blocks) -> bool:
    """Rewrite per-shard masked (missing-scrape) grids onto ONE common
    nominal grid so the mesh kernel can share a single window structure
    (parallel/exec.py). Per-shard staging snapped each block to its own
    anchor; the common grid takes the earliest anchor and the mean interval,
    and every block's sidecar is rebuilt against it from the packed arrays.
    Per-block widths may differ — validity masks make shorter blocks exact.
    Returns False (blocks untouched) when grids can't be reconciled."""
    real = [b for b in blocks if b.n_series > 0]
    if not real:
        return False
    if len({b.base_ms for b in real}) != 1:
        return False
    base = real[0].base_ms
    ints, anchors = [], []
    for b in real:
        # grid evidence per block: a masked grid, OR a (possibly trivially)
        # regular/near-regular grid — e.g. a single-series shard stages as
        # "regular" even when it has holes, but still snaps onto the common
        # grid below
        if b.mgrid is not None:
            src = np.asarray(b.mgrid.nominal_ts)[: b.mgrid.n_valid]
        elif b.regular_ts is not None or b.nominal_ts is not None:
            m = int(np.asarray(b.lens)[0])
            grid = b.regular_ts if b.regular_ts is not None else b.nominal_ts
            src = np.asarray(grid)[:m]
        else:
            return False
        src = src.astype(np.int64)
        if len(src) < 2:
            return False
        d = np.diff(src)
        if (d <= 0).any():
            return False
        est = float(np.median(d))
        k = np.rint(d / est)
        if est <= 0 or (k < 1).any():
            return False
        ints.append(float(d.sum()) / float(k.sum()))
        anchors.append(int(src[0]))
    interval = float(np.mean(ints))
    if interval <= 0 or max(
        abs(x - interval) for x in ints
    ) > 0.01 * interval:
        return False
    t0_abs = float(min(anchors) + base)
    rebuilt = []
    for b in real:
        n = b.n_series
        ts_np = np.asarray(b.ts)
        lens = np.asarray(b.lens)
        cleaned = [
            (ts_np[i, : lens[i]].astype(np.int64) + base, None)
            for i in range(n)
        ]
        mg = _build_masked_grid(
            cleaned, base, np.asarray(b.vals),
            np.asarray(b.raw) if b.raw is not None else None,
            lens, b.ts.shape[1], b.vals.shape[0], grid=(interval, t0_abs),
        )
        if mg is None:
            return False
        rebuilt.append(mg)
    md = max(mg.maxdev_ms for mg in rebuilt)
    if 2 * md >= interval:
        return False
    width = max(mg.n_valid for mg in rebuilt)
    if any(width > mg.valid.shape[1] for mg in rebuilt):
        return False  # a block can't advertise slots its sidecar can't hold
    for b, mg in zip(real, rebuilt):
        # unify the advertised grid: same width everywhere (validity masks
        # cover slots a block has no samples for), same maxdev bound
        T = len(mg.nominal_ts)
        R = np.rint(
            (t0_abs - base) + np.arange(T, dtype=np.float64) * interval
        ).astype(np.int64)
        nominal = np.full(T, TS_PAD, dtype=np.int32)
        nominal[:width] = R[:width].astype(np.int32)
        mg.nominal_ts = nominal
        mg.n_valid = width
        mg.maxdev_ms = md
        b.mgrid = mg
        if hasattr(b, "_mwm_cache"):
            del b._mwm_cache
    return True


@dataclass
class StagedBlock:
    """One staged window block: everything a range kernel needs."""

    ts: np.ndarray  # [S, T] int32 ms offsets from base_ms; TS_PAD in padding
    vals: np.ndarray  # [S, T] f32; counters: reset-corrected minus baseline
    lens: np.ndarray  # [S] int32 valid sample count per series
    base_ms: int  # absolute ms of offset 0
    baseline: np.ndarray  # [S] f32 per-series value offset (counters; else 0)
    n_series: int  # real series count (<= S)
    part_refs: list  # (shard_num, part_id) per real series row
    raw: np.ndarray | None = None  # [S, T] f32 raw values (counters only)
    # device mesh this block's series axis is partitioned over
    # (NamedSharding, PartitionSpec(axis, None)); None = single-device.
    # Set by to_device(mesh=...); consumers (group_ids_memo, the sharded
    # fused kernels, append repairs) read it to co-place their arrays.
    placement: "object | None" = None
    # regular-grid fast path: every real series shares ONE timestamp vector
    # and one length — window matrices become series-independent and the
    # range kernel becomes a batched matmul on the MXU (see kernels.py)
    regular_ts: np.ndarray | None = None  # [T] int32 shared offsets, or None
    # near-regular (jittered) fast path: every series has the same sample
    # COUNT and each sample sits within half a scrape interval of a shared
    # nominal grid. Window membership then deviates from the nominal-grid
    # answer by at most one sample per window boundary, which mxu_jitter.py
    # resolves per-series with one-hot-matmul gathers — keeping real-world
    # jittered scrapes on the MXU path (reference semantics contract:
    # PeriodicSamplesMapper.scala:256 window iterators over arbitrary ts)
    nominal_ts: np.ndarray | None = None  # [T] int32 shared nominal offsets
    ts_dev: np.ndarray | None = None  # [S, T] f32 per-sample deviation (ms)
    maxdev_ms: int = 0  # bound on |ts - nominal|; < half min nominal interval
    # missing-scrape fast path: near-regular grid with HOLES (a dropped
    # scrape breaks the equal-count detection above). Slot-aligned masked
    # sidecar; packed arrays above stay canonical. See MaskedGrid.
    mgrid: "MaskedGrid | None" = None

    @property
    def shape(self):
        return self.ts.shape

    def to_device(self, keep_host: bool = False,
                  mesh=None) -> "StagedBlock":
        """Pin the block's arrays in HBM (the north-star 'decoded chunk
        windows staged to HBM'); returns self for chaining. ``keep_host``
        retains mutable host mirrors so cached blocks can be incrementally
        APPENDED to when live samples arrive (append_to_block) instead of
        fully restaged.

        ``mesh`` partitions the SERIES axis across a device mesh
        (``NamedSharding``, ``PartitionSpec(axis)`` on the leading dim of
        every [S, ...] array) so one shard_map program spans all devices —
        the padded S must be mesh-divisible (concat_blocks
        ``series_multiple``). The mesh is recorded as ``self.placement``."""
        import jax

        if mesh is not None:
            self.placement = mesh
        if keep_host:
            # explicit copies: jax.device_put on the CPU backend can alias
            # numpy memory, and the mirrors get mutated by append repairs
            # while older device arrays may still be in flight
            self.h_ts = np.array(self.ts, copy=True)
            self.h_vals = np.array(self.vals, copy=True)
            self.h_lens = np.array(self.lens, copy=True)
            self.h_raw = (np.array(self.raw, copy=True)
                          if self.raw is not None else None)
            self.h_dev = (np.array(self.ts_dev, copy=True)
                          if self.ts_dev is not None else None)
        put = series_put(self.placement)
        self.ts = put(self.ts)
        self.vals = put(self.vals)
        self.lens = put(self.lens)
        self.baseline = put(self.baseline)
        if self.raw is not None:
            self.raw = put(self.raw)
        if self.ts_dev is not None:
            self.ts_dev = put(self.ts_dev)
        if self.mgrid is not None:
            self.mgrid.to_device(put if self.placement is not None else None)
        return self


def detect_shared_grid(out_ts: np.ndarray, lens: np.ndarray, n: int,
                       T: int, S: int):
    """Shared-grid classification over packed [S, T] timestamp rows — the
    ONE rule used by per-shard staging (stage_series /
    stage_histogram_series) AND superblock concatenation (concat_blocks), so
    a cross-shard superblock keeps the same fast-path eligibility its member
    blocks had. Returns ``(regular, nominal, ts_dev, maxdev)``:

    - regular [T] when every real series shares one exact timestamp vector;
    - else nominal [T] + ts_dev [S, T] + maxdev when every series has the
      same sample count and each sample lies within half the minimum
      nominal interval of the per-slot midrange grid (the mxu_jitter bound:
      at most ONE uncertain slot per window boundary);
    - (None, None, None, 0) otherwise (caller may still try the masked
      missing-scrape grid)."""
    if n <= 0 or not (lens[:n] == lens[0]).all() or lens[0] == 0:
        return None, None, None, 0
    if not (out_ts[:n] != out_ts[0]).any():
        return out_ts[0], None, None, 0
    if lens[0] < 2:
        return None, None, None, 0
    m = int(lens[0])
    real = out_ts[:n, :m].astype(np.int64)
    nom, dev, md = nominal_midrange(real)
    min_int = int(np.diff(nom).min()) if m >= 2 else 0
    if min_int > 0 and 2 * md < min_int:
        nominal = np.full(T, TS_PAD, dtype=np.int32)
        nominal[:m] = nom.astype(np.int32)
        ts_dev = np.zeros((S, T), dtype=np.float32)
        ts_dev[:n, :m] = dev.astype(np.float32)
        return None, nominal, ts_dev, md
    return None, None, None, 0


def grid_class(block) -> str:
    """Classification of a staged (super)block's time grid — the fused
    kernel-variant ladder (ops/aggregations) and the /debug/superblocks
    introspection both key on it: ``regular`` (exact shared grid, MXU
    window matmuls) > ``jitter`` (near-regular, certain-matmul + boundary
    corrections) > ``holes`` (near-regular with missed scrapes, masked
    sidecar) > ``irregular`` (general / Pallas gather-scan)."""
    if block.regular_ts is not None:
        return "regular"
    if block.nominal_ts is not None:
        return "jitter"
    if getattr(block, "mgrid", None) is not None:
        return "holes"
    return "irregular"


def nominal_midrange(real: np.ndarray):
    """Shared nominal-grid estimator for near-regular data: per-column
    midrange (minimax-optimal for the max deviation) over [n, m] actual
    timestamps. Returns (nominal int64 [m], deviations int64 [n, m],
    maxdev int). The ONE definition used by staging detection and the
    live-edge append repair — the 2*maxdev < min-interval safety bound must
    be checked against the same estimator everywhere."""
    nom = (real.min(axis=0) + real.max(axis=0)) // 2
    dev = real - nom[None, :]
    return nom, dev, int(np.abs(dev).max())


def counter_correct(vals: np.ndarray) -> np.ndarray:
    """f64 prefix-sum reset correction: add the prior raw value at each drop
    (Prometheus semantics; reference CorrectingDoubleVectorReader:308)."""
    v = vals.astype(np.float64)
    if len(v) < 2:
        return v
    drops = np.where(v[1:] < v[:-1], v[:-1], 0.0)
    corr = np.concatenate([[0.0], np.cumsum(drops)])
    return v + corr


def stage_series(
    series: list[tuple[np.ndarray, np.ndarray]],
    base_ms: int,
    part_refs: list | None = None,
    subtract_baseline: bool = False,
    counter_corrected: bool = False,
    diff_encode: bool = False,
    dtype=np.float32,
    time_headroom: int = 0,
) -> StagedBlock:
    """Build a StagedBlock from per-series (ts_ms int64, values f64) pairs.

    Drops NaN samples (staleness). Pads S and T to bucketed shapes;
    ``time_headroom`` extra columns let live-edge append repairs
    (append_to_block) absorb many scrapes before the padded width forces a
    full re-stage.
    With ``counter_corrected``, values are reset-corrected in f64 first and
    raw offsets are staged alongside (see module docstring).
    With ``diff_encode``, slot i carries the f64-exact adjacent difference
    v[i]-v[i-1] (slot 0 = 0): changes/resets/idelta are pure functions of the
    diff sequence, and no single f32 shift of the *values* can preserve both
    tiny adjacent changes and a 1e9-magnitude counter-reset cliff.
    """
    n = len(series)
    cleaned: list[tuple[np.ndarray, np.ndarray]] = []
    maxlen = 1
    for ts, vals in series:
        keep = ~np.isnan(vals)
        if not keep.all():
            ts, vals = ts[keep], vals[keep]
        cleaned.append((ts, vals))
        maxlen = max(maxlen, len(ts))
    S = pad_series(max(n, 1))
    T = pad_time(maxlen + max(time_headroom, 0))
    out_ts = np.full((S, T), TS_PAD, dtype=np.int32)
    out_vals = np.zeros((S, T), dtype=dtype)
    out_raw = np.zeros((S, T), dtype=dtype) if counter_corrected else None
    lens = np.zeros(S, dtype=np.int32)
    baseline = np.zeros(S, dtype=dtype)
    # f64 continuation state per series (last raw value, last corrected
    # value) so cached counter blocks can be incrementally appended to with
    # EXACT correction continuation (append_to_block); base64 keeps the
    # UNROUNDED per-series baseline — the f32 baseline array rounds to
    # +-64 at 1e9 magnitudes, which would shift every appended value
    cont_raw = np.zeros(S, dtype=np.float64)
    cont_corr = np.zeros(S, dtype=np.float64)
    base64 = np.zeros(S, dtype=np.float64)
    for i, (ts, vals) in enumerate(cleaned):
        m = len(ts)
        lens[i] = m
        if m == 0:
            continue
        out_ts[i, :m] = (ts - base_ms).astype(np.int32)
        if counter_corrected:
            b = np.float64(vals[0])
            baseline[i] = b
            base64[i] = b
            corrected = counter_correct(vals)
            cont_raw[i] = vals[-1]
            cont_corr[i] = corrected[-1]
            out_vals[i, :m] = (corrected - b).astype(dtype)
            # raw rides along unshifted: it only feeds the zero-crossing
            # extrapolation cap, which engages only for raw values near zero —
            # exactly where plain f32 is exact (large raws disable the cap)
            out_raw[i, :m] = vals.astype(dtype)
        elif diff_encode:
            v64 = vals.astype(np.float64)
            out_vals[i, 1:m] = np.diff(v64).astype(dtype)
        elif subtract_baseline:
            b = np.float64(vals[0])
            baseline[i] = b
            base64[i] = b
            out_vals[i, :m] = (vals.astype(np.float64) - b).astype(dtype)
        else:
            out_vals[i, :m] = vals.astype(dtype)
    mgrid = None
    regular, nominal, ts_dev, maxdev = detect_shared_grid(
        out_ts, lens, n, T, S
    )
    if n > 1 and regular is None and nominal is None:
        # unequal counts (or equal counts on misaligned slots): try the
        # missing-scrape masked grid before resigning to the general path
        mgrid = _build_masked_grid(
            cleaned[:n], base_ms, out_vals, out_raw, lens, T, S
        )
    block = StagedBlock(
        out_ts, out_vals, lens, base_ms, baseline, n, part_refs or [],
        raw=out_raw, regular_ts=regular, nominal_ts=nominal, ts_dev=ts_dev,
        maxdev_ms=maxdev, mgrid=mgrid,
    )
    if counter_corrected or subtract_baseline:
        block.base64 = base64
    if counter_corrected:
        block.cont = (cont_raw, cont_corr)
    return block


def append_to_block(shard, block: StagedBlock, part_ids, column: str,
                    end_ms: int, mode: str,
                    dirty_lo: int | None = None) -> "StagedBlock | None":
    """Incrementally append samples that arrived AFTER ``block`` was staged
    (the live-edge dashboard path: every scrape lands just past the staged
    head, and a full re-stage per scrape is the single biggest query cost
    under ingest — the reference serves this straight from write buffers).
    ``dirty_lo`` is the entry's accumulated effect-interval floor
    (StageEntry.dirty_lo): the repair is declined when the dirt provably
    reaches below the staged heads. Thin shard-level wrapper around
    :func:`_append_to_parts`; the cross-shard superblock variant is
    :func:`extend_superblock`."""
    refs = [(shard.shard_num, int(p)) for p in part_ids]
    if refs != list(block.part_refs):
        return None
    parts = [shard.partition(int(p)) for p in part_ids]
    return _append_to_parts(parts, block, column, end_ms, mode,
                            dirty_lo=dirty_lo)


def extend_superblock(memstore, dataset: str, block: StagedBlock,
                      column: str, end_ms: int, mode: str,
                      les=None) -> "StagedBlock | None":
    """``append_to_block`` lifted to the cross-shard superblock (the
    delta-summation move: maintain the device-resident aggregate input
    incrementally on append instead of invalidate-and-restage). Resolves
    every ``part_refs`` row back to its live partition across member shards
    and appends through the same uniform-batch repair core, so the warm
    single-dispatch query stays ONE dispatch under live ingest. The caller
    (plans.FusedAggregateExec) is responsible for proving the ROW SET is
    unchanged (fresh per-shard lookups + the shards' effect logs) before
    calling. ``les`` must be the entry's bucket bounds for [ΣS, T, B]
    histogram superblocks — extension declines when any member partition's
    scheme no longer matches (appended raw rows would land on the wrong
    bounds). Returns None when any precondition fails (caller restages)."""
    parts = []
    try:
        for sn, pid in block.part_refs:
            parts.append(memstore.shard(dataset, sn).partitions[int(pid)])
    except KeyError:
        return None
    if les is not None:
        from ..core.histograms import same_scheme

        for p in parts:
            if p.bucket_les is None or not same_scheme(p.bucket_les, les):
                return None
    return _append_to_parts(parts, block, column, end_ms, mode)


def _append_to_parts(parts, block: StagedBlock, column: str,
                     end_ms: int, mode: str,
                     dirty_lo: int | None = None) -> "StagedBlock | None":
    """Uniform-batch incremental append core shared by the per-shard repair
    path (append_to_block) and the cross-shard superblock extension
    (extend_superblock). ``parts`` are the live partitions in the block's
    ``part_refs`` order — callers have already verified the selection is
    unchanged.

    Mutates the big [n, T] HOST mirrors in place but only at columns >= the
    old head; the small per-series state (h_lens, cont) is copy-on-write,
    so a reader holding the OLD block — an in-flight concat_blocks as much
    as a device-array consumer — keeps a consistent head-m view. Returns a
    NEW
    StagedBlock carrying the refreshed device arrays and extended shared
    grid — the caller swaps it into the cache entry atomically, so a
    concurrent query sees either the whole old block or the whole new one,
    never a torn mix. Returns None whenever a precondition fails and the
    caller restages from scratch:

    - mode must be raw/shifted/corrected (diff continuation needs state the
      block doesn't carry) and the block host-mirrored, on a REGULAR or
      NEAR-REGULAR (jittered) shared grid — the common live cases;
      masked/irregular blocks restage. Scalar [S, T] blocks support all
      three modes; histogram [S, T, B] blocks (raw cumulative bucket
      counts) support raw on a regular grid;
    - every series must gain the SAME COUNT of new samples — identical
      timestamps on a regular grid, or near-nominal ones (the jitter bound
      re-checked over the extended grid) on a jittered grid — and the
      padded T must still fit.
    """
    if mode not in ("raw", "shifted", "corrected"):
        return None
    if mode == "corrected" and getattr(block, "cont", None) is None:
        return None
    if mode in ("corrected", "shifted") and getattr(block, "base64", None) is None:
        return None  # exact f64 baselines required (f32 rounds +-64 at 1e9)
    jittered = block.regular_ts is None and block.nominal_ts is not None
    if getattr(block, "h_ts", None) is None:
        return None
    if block.regular_ts is None and not jittered:
        return None
    if jittered and getattr(block, "h_dev", None) is None:
        return None
    if block.n_series == 0:
        return None
    is_hist = block.h_vals.ndim == 3
    if is_hist and (mode != "raw" or jittered):
        return None
    if not is_hist and block.h_vals.ndim != 2:
        return None
    n = block.n_series
    lens = block.h_lens
    m = int(lens[0])
    if m == 0 or not (lens[:n] == m).all():
        return None
    base = block.base_ms
    grid = np.asarray(block.nominal_ts if jittered else block.regular_ts)
    last_nom = int(grid[m - 1]) + base
    # jittered: each series' head sits at last_nom + its own deviation, so
    # the read starts PER SERIES — an in-order sample landing in another
    # series' (head, last_nom+maxdev] gap must not be silently skipped
    # (it shows up as a non-uniform batch and forces the restage fallback)
    if jittered:
        dev_last = block.h_dev[:n, m - 1].astype(np.int64)
        read_from = [last_nom + int(d) + 1 for d in dev_last]
    else:
        read_from = [last_nom + 1] * n
    # accumulated-dirt floor guard: the append-only repair can only be
    # correct when every dirtying sample sits at or past the staged heads.
    # Today that is guaranteed structurally (partitions drop out-of-order
    # rows and uniform lens pin every member's store head to its staged
    # head), so this cannot fire — it exists to turn a future relaxation
    # of either invariant (e.g. accepting backfill) into a safe restage
    # instead of a silently incomplete block.
    if dirty_lo is not None and dirty_lo < min(read_from) - 1:
        return None
    # gather the per-series tails with NO per-series validation — at 100k
    # series the python-level per-call overhead IS the cost of the repair,
    # so uniformity/NaN/grid checks run vectorized over the stacked [n, k]
    # batch below, with a per-series pass only when the batch is odd
    # (diverging counts, staleness NaNs, histogram shape drift)
    read = getattr(parts[0], "tail_samples", None)
    if read is None:  # test doubles without the lean path
        per = [p.samples_in_range(read_from[i], end_ms, column)
               for i, p in enumerate(parts)]
    else:
        per = [p.tail_samples(read_from[i], end_ms, column)
               for i, p in enumerate(parts)]
    per_ts = [ts for ts, _ in per]
    per_vals = [v for _, v in per]
    V0 = TS0 = None
    k = len(per_ts[0])
    uniform = all(len(ts) == k for ts in per_ts)
    if uniform and k > 0:
        V0 = np.stack(per_vals)
        if V0.ndim != (3 if is_hist else 2):
            uniform = False
            V0 = None
        elif is_hist and V0.shape[2] != block.h_vals.shape[2]:
            return None  # bucket scheme width changed: restage
        elif not is_hist and np.isnan(V0).any():
            uniform = False  # staleness markers: per-series filtering
            V0 = None
        else:
            TS0 = np.stack(per_ts)
            if not jittered and (TS0 != TS0[0]).any():
                return None  # regular grid would not stay shared
    if not uniform:
        # odd batch: the original per-series discipline (filter staleness
        # NaNs, then require uniform counts + a shared grid)
        new_ts = None
        per_vals = []
        per_ts = []
        for ts, vals in per:
            if getattr(vals, "ndim", 1) != (2 if is_hist else 1):
                return None
            if is_hist:
                if vals.shape[1] != block.h_vals.shape[2]:
                    return None  # bucket scheme width changed: restage
            else:
                keep = ~np.isnan(vals)
                if not keep.all():
                    ts, vals = ts[keep], vals[keep]
            if new_ts is None:
                new_ts = ts
            elif len(ts) != len(new_ts):
                return None  # appended counts diverge
            elif not jittered and (ts != new_ts).any():
                return None  # regular grid would not stay shared
            per_vals.append(vals)
            per_ts.append(ts)
        k = 0 if new_ts is None else len(new_ts)
    if k == 0:
        return block  # nothing new in this block's range: still clean
    new_ts = per_ts[0]
    T = block.h_ts.shape[1]
    if m + k > T:
        return None  # padded width exhausted: restage with a bigger T
    if jittered:
        TS = (TS0 if TS0 is not None else np.stack(per_ts)).astype(np.int64)
        if (np.diff(TS, axis=1) <= 0).any():
            return None
        nom_new, dev_new, md_new = nominal_midrange(TS)
        md = max(md_new, int(block.maxdev_ms))
        ext = np.concatenate([grid[:m].astype(np.int64) + base, nom_new])
        d = np.diff(ext)
        if (d <= 0).any() or 2 * md >= int(d.min()):
            return None  # jitter bound fails on the extended grid
        off = (nom_new - base)
        OFF = (TS - base).astype(np.int64)
        if OFF.max() >= 2**31 - 1:
            return None
    else:
        off = (new_ts - base).astype(np.int64)
        if off.max() >= 2**31 - 1 or off.min() <= int(grid[m - 1]):
            return None
    off32 = off.astype(np.int32)
    # vectorized across series: uniform appended counts make the whole
    # repair a handful of [n, k] array ops, not n small python loops
    V = (V0 if V0 is not None else np.stack(per_vals)).astype(np.float64)
    # [n, k] ([n, k, B] hist)
    if jittered:
        block.h_ts[:n, m : m + k] = (OFF).astype(np.int32)
        block.h_dev[:n, m : m + k] = dev_new.astype(np.float32)
    else:
        block.h_ts[:n, m : m + k] = off32[None, :]
    if mode == "raw":
        block.h_vals[:n, m : m + k] = V.astype(block.h_vals.dtype)
    elif mode == "shifted":
        b = block.base64[:n]
        block.h_vals[:n, m : m + k] = (V - b[:, None]).astype(block.h_vals.dtype)
    new_cont = None
    if mode == "corrected":
        # corrected: exact f64 continuation from the stored state. The
        # continuation arrays are COPY-ON-WRITE (like lens below): the old
        # block object must stay frozen at head m, or a concurrent
        # concat_blocks would snapshot cont at m+k against values at m and
        # a later superblock extension would mis-correct the re-read tail
        # as ~1e9 counter resets
        cont_raw, cont_corr = block.cont
        prev = np.concatenate([cont_raw[:n, None], V[:, :-1]], axis=1)
        drops = np.where(V < prev, prev, 0.0)
        corr = cont_corr[:n, None] + np.cumsum(V - prev + drops, axis=1)
        b = block.base64[:n]
        block.h_vals[:n, m : m + k] = (corr - b[:, None]).astype(block.h_vals.dtype)
        block.h_raw[:n, m : m + k] = V.astype(block.h_raw.dtype)
        new_cont = (cont_raw.copy(), cont_corr.copy())
        new_cont[0][:n] = V[:, -1]
        new_cont[1][:n] = corr[:, -1]
    # lens is copy-on-write: the big [n, T] mirrors may be shared with
    # readers of the OLD block (concat_blocks mid-superblock-build) — the
    # in-place column writes above land only at >= m, invisible under the
    # old lens, so the old block stays a consistent head-m view as long as
    # ITS lens never advances
    new_lens = lens.copy()
    new_lens[:n] = m + k
    ext_grid = grid.copy()
    ext_grid[m : m + k] = off32
    # fresh block object: in-flight readers keep the old (immutable device
    # arrays + old grid) view; window-matrix caches start empty against the
    # extended grid. device_put gets COPIES — on the CPU backend it can
    # alias numpy memory, and the next repair mutates these same mirrors.
    # A series-sharded block (mesh superblock) re-uploads with the SAME
    # placement: extension never changes S, so the row bands still divide.
    put = series_put(block.placement)
    nb = StagedBlock(
        put(block.h_ts.copy()), put(block.h_vals.copy()),
        put(new_lens.copy()), base, block.baseline, n,
        list(block.part_refs),
        raw=(put(block.h_raw.copy())
             if block.h_raw is not None else None),
        regular_ts=None if jittered else ext_grid,
        nominal_ts=ext_grid if jittered else None,
        ts_dev=(put(block.h_dev.copy()) if jittered else None),
        maxdev_ms=(md if jittered else 0),
        placement=block.placement,
    )
    nb.h_ts = block.h_ts
    nb.h_vals = block.h_vals
    nb.h_lens = new_lens
    nb.h_raw = block.h_raw
    nb.h_dev = getattr(block, "h_dev", None)
    if new_cont is not None:
        nb.cont = new_cont
    elif getattr(block, "cont", None) is not None:
        nb.cont = block.cont
    if getattr(block, "base64", None) is not None:
        nb.base64 = block.base64
    if "_gid_cache" in block.__dict__:
        # label grouping is a pure function of the (unchanged) series set:
        # carrying the memo keeps an extended superblock's warm query free
        # of the O(S) regroup AND the group-id device re-upload
        nb._gid_cache = dict(block._gid_cache)
    return nb


def harmonize_nominal(blocks) -> bool:
    """Rewrite per-shard near-regular blocks onto ONE common nominal grid so
    a mesh kernel can share a single certain/uncertain window structure
    across shards (parallel/exec.py). Each shard staged independently and
    estimated its own nominal grid; the common grid is the midrange of the
    per-block grids, deviations are recomputed exactly from the int
    timestamps, and the safety bound (2*maxdev < min interval) is re-checked
    against the common grid. Returns False (blocks untouched) when the
    blocks can't be harmonized."""
    real = [b for b in blocks if b.n_series > 0]
    if not real:
        return False
    noms = []
    m = None
    for b in real:
        lens = np.asarray(b.lens)
        if not (lens[: b.n_series] == lens[0]).all() or lens[0] == 0:
            return False
        if m is None:
            m = int(lens[0])
        elif int(lens[0]) != m:
            return False
        if b.regular_ts is not None:
            noms.append(np.asarray(b.regular_ts)[:m].astype(np.int64))
        elif b.nominal_ts is not None:
            noms.append(np.asarray(b.nominal_ts)[:m].astype(np.int64))
        else:
            return False
    if len({b.base_ms for b in real}) != 1:
        return False
    nom_mat = np.stack(noms)
    common = (nom_mat.min(axis=0) + nom_mat.max(axis=0)) // 2
    if m >= 2:
        min_int = int(np.diff(common).min())
    else:
        return False
    devs, md = [], 0
    for b in real:
        ts = np.asarray(b.ts)[: b.n_series, :m].astype(np.int64)
        d = ts - common[None, :]
        md = max(md, int(np.abs(d).max()))
        devs.append(d)
    if min_int <= 0 or 2 * md >= min_int:
        return False
    for b, d in zip(real, devs):
        T = b.ts.shape[1]
        S = b.vals.shape[0]
        nominal = np.full(T, TS_PAD, dtype=np.int32)
        nominal[:m] = common.astype(np.int32)
        ts_dev = np.zeros((S, T), dtype=np.float32)
        ts_dev[: b.n_series, :m] = d.astype(np.float32)
        b.nominal_ts = nominal
        b.ts_dev = ts_dev
        b.maxdev_ms = md
        b.regular_ts = b.regular_ts if md == 0 else None
        if hasattr(b, "_jwm_cache"):
            del b._jwm_cache
    return True


def stage_histogram_series(
    series: list[tuple[np.ndarray, np.ndarray]],
    base_ms: int,
    n_buckets: int,
    part_refs: list | None = None,
    subtract_baseline: bool = False,
    dtype=np.float32,
):
    """Like stage_series but values are [T, B] bucket-count rows.

    Returns (StagedBlock with vals [S, T, B], baseline [S, B]).
    """
    n = len(series)
    maxlen = 1
    for ts, _ in series:
        maxlen = max(maxlen, len(ts))
    S = pad_series(max(n, 1))
    T = pad_time(maxlen)
    out_ts = np.full((S, T), TS_PAD, dtype=np.int32)
    out_vals = np.zeros((S, T, n_buckets), dtype=dtype)
    lens = np.zeros(S, dtype=np.int32)
    baseline = np.zeros((S, n_buckets), dtype=dtype)
    for i, (ts, vals) in enumerate(series):
        m = len(ts)
        lens[i] = m
        if m == 0:
            continue
        out_ts[i, :m] = (ts - base_ms).astype(np.int32)
        if subtract_baseline:
            b = vals[0].astype(np.float64)
            baseline[i] = b.astype(dtype)
            out_vals[i, :m] = (vals.astype(np.float64) - b).astype(dtype)
        else:
            out_vals[i, :m] = vals.astype(dtype)
    # shared-grid detection, same rule as scalar staging: regular grids get
    # the series-independent [J] window boundaries (ops/hist_kernels shared
    # variant), NEAR-regular (jittered scrape) grids get the certain-range
    # boundaries + per-series one-slot corrections (jitter variant) instead
    # of the O(S*J*T) per-series compare
    regular, nominal, ts_dev, maxdev = detect_shared_grid(
        out_ts, lens, n, T, S
    )
    return StagedBlock(out_ts, out_vals, lens, base_ms, baseline, n,
                       part_refs or [], regular_ts=regular,
                       nominal_ts=nominal, ts_dev=ts_dev, maxdev_ms=maxdev)


def _slot_align(shard, part_ids, column, series, start_ms: int, end_ms: int):
    """Repair ragged staging of near-regular grids at the read-range edges.

    A sample whose jittered timestamp falls just outside [start_ms, end_ms]
    is excluded for SOME series, so per-series sample counts differ by 1-2
    and the near-regular detection (and with it the MXU jitter path) fails.
    Re-read with a one-interval margin, map every sample to its nominal slot,
    and trim all series to the common slot range that can contribute to any
    window. Dropped edge slots provably can't: a slot with nominal time
    g <= start - maxdev has true ts <= start for every series (windows need
    ts > bound >= start - window... bound >= start_ms here because start_ms
    is the staged lower bound = earliest window start), and one with
    g > end + maxdev has ts > end >= every window end.

    Returns the slot-aligned series list, or None when the data isn't
    near-regular (caller keeps the original packed staging)."""
    lens = [len(t) for t, _ in series]
    if not lens or min(lens) < 2 or max(lens) - min(lens) > 2:
        return None
    ref = series[int(np.argmax(lens))][0]
    diffs = np.diff(ref)
    # endpoint-based estimate: per-sample jitter contributes only
    # O(maxdev / n) error, where a median of jittered diffs drifts by
    # O(n * median_error) across the span
    interval = float(ref[-1] - ref[0]) / (len(ref) - 1)
    if interval <= 0 or (np.abs(diffs - interval) > 0.45 * interval).any():
        return None
    anchor = float(ref[0])
    margin = int(round(interval))
    per = []
    md = 0.0
    for pid in part_ids:
        ts, v = shard.partition(int(pid)).samples_in_range(
            start_ms - margin, end_ms + margin, column
        )
        if v.ndim == 2 or len(ts) < 2:
            return None
        keep = ~np.isnan(v)
        if not keep.all():
            return None  # staleness holes: packed staging handles them
        k = np.rint((ts.astype(np.float64) - anchor) / interval).astype(np.int64)
        if (np.diff(k) != 1).any():
            return None  # missed scrapes: not slot-contiguous
        md = max(md, float(np.abs(ts - (anchor + k * interval)).max()))
        per.append((k, ts, v))
    if 2.0 * md >= 0.9 * interval:
        return None
    # slots that could contribute to any window of the staged range
    k_need_lo = int(np.ceil((start_ms - md - anchor) / interval - 1e-9))
    while anchor + k_need_lo * interval <= start_ms - md:
        k_need_lo += 1
    k_need_hi = int(np.floor((end_ms + md - anchor) / interval + 1e-9))
    while anchor + k_need_hi * interval > end_ms + md:
        k_need_hi -= 1
    # clamp the needed range to slots where data EXISTS at all: a live-edge
    # query's end (beyond every series' newest sample) must not make the
    # repair demand future slots of nobody (and symmetrically at the low
    # edge before retention)
    k_need_lo = max(k_need_lo, min(k[0] for k, _, _ in per))
    k_need_hi = min(k_need_hi, max(k[-1] for k, _, _ in per))
    k_lo = max(k[0] for k, _, _ in per)
    k_hi = min(k[-1] for k, _, _ in per)
    if k_lo > k_need_lo or k_hi < k_need_hi or k_need_hi < k_need_lo:
        return None  # a needed slot is genuinely missing for some series
    out = []
    width = k_need_hi - k_need_lo + 1
    for k, ts, v in per:
        o = k_need_lo - int(k[0])
        out.append((ts[o : o + width], v[o : o + width]))
    return out


def staged_nbytes(block: StagedBlock) -> int:
    """True device-byte footprint of a staged block: every array a
    ``to_device`` pins in HBM. Histogram blocks carry [S, T, B] vals and
    [S, B] baselines — the B axis multiplies the footprint ~20-60x over a
    scalar block of the same selection, and cache eviction budgets
    (stage_cache_bytes, SuperblockCache.max_bytes) must see that. Reads
    ``.nbytes`` directly so device arrays are never fetched to host."""
    total = 0
    for arr in (block.ts, block.vals, block.raw, block.baseline, block.lens,
                block.ts_dev):
        if arr is not None:
            total += int(arr.nbytes)
    if block.mgrid is not None:
        for f in ("valid", "vals", "dev", "raw", "ffv", "ffd", "bfv", "bfd",
                  "ff2v", "ff2d", "bfraw", "cc"):
            arr = getattr(block.mgrid, f)
            if arr is not None:
                total += int(arr.nbytes)
    return total


def concat_blocks(blocks, force_raw: bool = False,
                  series_multiple: int = 1) -> StagedBlock:
    """Row-concatenate staged blocks into one padded superblock EXACTLY —
    corrected values, raw sidecars, baselines and part refs carry over with
    no restaging and no semantic drift. All blocks must share base_ms.

    Histogram blocks ([S, T, B] vals, [S, B] baselines) concatenate the same
    way into a ``[ΣS, T, B]`` superblock; all blocks must already share one
    bucket scheme (callers unify heterogeneous ``le`` schemes first via
    core.histograms.remap_buckets — see plans._build_superblock).

    The shared regular grid survives only when every non-empty block
    advertises the identical ``regular_ts`` (same padded length, same
    offsets) — that keeps the MXU window-matrix path available for the
    single-dispatch fused aggregate; otherwise the superblock runs the
    general kernels. ``force_raw`` always materializes the raw sidecar
    (filling from vals where a block has none) for consumers that index it
    unconditionally (the mesh stacking path); histogram blocks never carry
    one. ``series_multiple`` rounds the padded series axis up to a multiple
    (a device-mesh size): series-axis sharding needs equal per-device row
    bands, and the trash-group/padded-row masking already makes the extra
    rows inert."""
    real = [b for b in blocks if b.n_series > 0]
    if not real:  # keep an empty-but-shaped block (mesh rows can be empty)
        real = list(blocks[:1])
    assert real and len({b.base_ms for b in real}) == 1
    T = max(b.ts.shape[1] for b in real)
    S = sum(b.n_series for b in real)
    Sp = pad_series(S)
    if series_multiple > 1:
        Sp = ((Sp + series_multiple - 1) // series_multiple) * series_multiple
    is_hist = any(np.asarray(b.vals).ndim == 3 for b in real)
    if is_hist:
        assert len({np.asarray(b.vals).shape[2] for b in real}) == 1, (
            "histogram blocks must share one bucket scheme before concat"
        )
        B = np.asarray(real[0].vals).shape[2]
        val_shape, base_shape = (Sp, T, B), (Sp, B)
    else:
        val_shape, base_shape = (Sp, T), (Sp,)
    ts = np.full((Sp, T), TS_PAD, np.int32)
    vals = np.zeros(val_shape, np.float32)
    any_raw = (force_raw or any(b.raw is not None for b in real)) and not is_hist
    raw = np.zeros((Sp, T), np.float32) if any_raw else None
    lens = np.zeros(Sp, np.int32)
    baseline = np.zeros(base_shape, np.float32)
    part_refs: list = []
    o = 0
    for b in real:
        k, t = b.n_series, b.ts.shape[1]
        ts[o : o + k, :t] = np.asarray(b.ts)[:k]
        vals[o : o + k, :t] = np.asarray(b.vals)[:k]
        if raw is not None:
            src_raw = b.raw if b.raw is not None else b.vals
            raw[o : o + k, :t] = np.asarray(src_raw)[:k]
        lens[o : o + k] = np.asarray(b.lens)[:k]
        baseline[o : o + k] = np.asarray(b.baseline)[:k]
        part_refs.extend(b.part_refs)
        o += k
    reg = real[0].regular_ts
    regular = None
    if reg is not None and all(
        b.regular_ts is not None
        and len(b.regular_ts) == len(reg)
        and not (np.asarray(b.regular_ts) != np.asarray(reg)).any()
        for b in real[1:]
    ):
        regular = np.asarray(reg)
        if len(regular) < T:  # narrower padded blocks keep the shared grid
            ext = np.full(T, TS_PAD, np.int32)
            ext[: len(regular)] = regular
            regular = ext
    # grid classification does NOT stop at "not exactly regular": re-detect
    # the near-regular (jittered scrape) and masked (missing-scrape) grids
    # over the CONCATENATED rows, so a cross-shard superblock keeps the
    # jitter-tolerant fused kernels available instead of silently dropping
    # to the multi-pass general path (the jitter5pct 1.70x / jitter+holes
    # 4.85x gap). Per-shard blocks estimated their nominal grids
    # independently; the midrange over the full row set re-derives one
    # common grid with the same 2*maxdev < min-interval safety bound, and
    # the masked build snaps every row onto one slot grid with validity
    # holes. Truly irregular data fails both checks and stays general.
    nominal = ts_dev = None
    maxdev = 0
    mgrid = None
    if regular is None and S > 0:
        _reg2, nominal, ts_dev, maxdev = detect_shared_grid(
            ts, lens, S, T, Sp
        )
        if _reg2 is not None:
            # members' advertised grids differed (padded widths) but the
            # real rows agree exactly ([T]-wide: row 0 of the concatenated
            # timestamp array)
            regular = _reg2
        elif nominal is None and not is_hist and S > 1 and int(
            lens[:S].min()
        ) >= 2:
            base = real[0].base_ms
            cleaned = [
                (ts[i, : lens[i]].astype(np.int64) + base, None)
                for i in range(S)
            ]
            mgrid = _build_masked_grid(cleaned, base, vals, raw, lens, T, Sp)
    out = StagedBlock(ts, vals, lens, real[0].base_ms, baseline, S,
                      part_refs, raw=raw, regular_ts=regular,
                      nominal_ts=nominal, ts_dev=ts_dev, maxdev_ms=maxdev,
                      mgrid=mgrid)
    if not is_hist:
        # f64 continuation state rides along (snapshot — the member blocks'
        # own state keeps evolving under per-shard repairs) so the
        # superblock can itself be incrementally extended on live-edge
        # ingest (extend_superblock) with exact counter correction
        if all(getattr(b, "base64", None) is not None for b in real):
            base64 = np.zeros(Sp, np.float64)
            o = 0
            for b in real:
                base64[o : o + b.n_series] = np.asarray(b.base64)[: b.n_series]
                o += b.n_series
            out.base64 = base64
        if all(getattr(b, "cont", None) is not None for b in real):
            cont_raw = np.zeros(Sp, np.float64)
            cont_corr = np.zeros(Sp, np.float64)
            o = 0
            for b in real:
                k = b.n_series
                cont_raw[o : o + k] = np.asarray(b.cont[0])[:k]
                cont_corr[o : o + k] = np.asarray(b.cont[1])[:k]
                o += k
            out.cont = (cont_raw, cont_corr)
    return out


def _superblock_cache_walker(cache) -> int:
    """Cold recount of the superblock cache's true device footprint (drift
    ground truth; must match the staged_nbytes accounting put() receives)."""
    with cache._lock:
        values = [v[1] for v in cache._d.values()]
    total = 0
    for v in values:
        block = getattr(v, "block", None)
        if block is not None:
            total += staged_nbytes(block)
    return total


def _superblock_device_walker(cache) -> dict:
    """Per-device byte balances of SHARDED cached superblocks (metadata-only
    split recorded at put time) — the filodb_device_bytes{kind,device}
    breakdown; single-device entries carry no device dimension."""
    with cache._lock:
        metas = list(cache._meta.values())
    out: dict[str, int] = {}
    for m in metas:
        db = m.get("device_bytes")
        if db:
            for dev, b in db.items():
                out[dev] = out.get(dev, 0) + int(b)
    return out


class SuperblockCache:
    """Shard-version-keyed cache of device-resident cross-shard superblocks
    (the staging layer of the single-dispatch fused aggregate).

    Entries are keyed by the query's staging identity (selector filters,
    range, column, stage mode, shard set); each stores the vector of member
    shard versions it was built from, so ANY ingest on ANY member shard
    invalidates the entry at its next lookup — the rebuild then re-reads the
    per-shard blocks, which repair incrementally through the shard staging
    cache (append_to_block) instead of restaging from chunks. LRU on hit,
    bounded by entry count and bytes."""

    def __init__(self, max_entries: int = 8, max_bytes: int = 8 << 30):
        from ..ledger import LEDGER
        from ..singleflight import KeyedSingleFlight

        self.max_entries = max_entries
        self.max_bytes = max_bytes
        self._d: OrderedDict = OrderedDict()
        # per-key introspection sidecar for /debug/superblocks: created
        # time, hit count, last maintenance outcome (the PR-6 taxonomy)
        self._meta: dict = {}
        # pinned keys -> owner set (standing queries): pinned entries are
        # SKIPPED by put()'s eviction loop, so an ad-hoc eviction storm
        # cannot churn a standing query's entry out from under its delta
        # refresh (which would silently degrade every refresh to
        # rebuild+suffix). Pins are identity, not storage — a key may be
        # pinned before its entry is built, and unpinning never drops data.
        self._pins: dict = {}
        self._lock = threading.Lock()
        self._flight = KeyedSingleFlight(
            max_keys=4 * max_entries, alive=lambda k: k in self._d
        )
        # device-ledger account (filodb_tpu/ledger.py): every put/evict/drop
        # debits/credits; the walker recounts live entries for drift checks.
        # The device walker splits sharded entries' balances per device for
        # the filodb_device_bytes{kind,device} gauges.
        self.ledger = LEDGER.register(
            self, "superblock", _superblock_cache_walker,
            name="superblock-cache",
            device_walker=_superblock_device_walker,
        )

    def build_lock(self, key) -> threading.Lock:
        """Per-key single-flight for builders (the shared
        filodb_tpu/singleflight utility): concurrent identical cold queries
        serialize on this lock so only one concatenates + uploads the
        superblock; the rest hit its freshly-put entry. Locks for keys no
        longer cached are pruned opportunistically (a racer holding a pruned
        lock merely degrades to a duplicate build)."""
        return self._flight.lock(key)

    def get(self, key, versions: tuple):
        with self._lock:
            hit = self._d.get(key)
            if hit is None or hit[0] != versions:
                # version-stale entries are RETAINED (not dropped): the
                # interval-aware refresh path (peek/revalidate + the
                # superblock extension in plans.FusedAggregateExec) can
                # prove them still valid or extend them in place, which is
                # the whole point of surviving ingest that doesn't touch
                # their range. LRU + the byte budget bound them; put()
                # replaces in place on rebuild.
                return None
            self._d.move_to_end(key)
            meta = self._meta.get(key)
            if meta is not None:
                meta["hits"] += 1
            return hit[1]

    def peek(self, key):
        """The stored ``(versions, value, nbytes)`` triple regardless of
        staleness (None when absent) — input to the interval-aware
        revalidate/extend decision."""
        with self._lock:
            return self._d.get(key)

    def revalidate(self, key, old_versions: tuple, new_versions: tuple) -> bool:
        """CAS the stored version vector: the caller proved (via the member
        shards' effect logs) that every bump between the two vectors was
        disjoint from the entry's staged range. Fails — returns False —
        when a racer replaced or dropped the entry in the meantime."""
        with self._lock:
            hit = self._d.get(key)
            if hit is None or hit[0] != old_versions:
                return False
            self._d[key] = (new_versions, hit[1], hit[2])
            self._d.move_to_end(key)
            return True

    def drop(self, key) -> None:
        """Remove an entry outright — required when an in-place extension
        mutated its host mirrors but could not be committed (the mirrors
        are now ahead of the entry's device arrays, so it must never be
        served or extended again)."""
        with self._lock:
            gone = self._d.pop(key, None)
            self._meta.pop(key, None)
            if gone is not None:
                self.ledger.free(gone[2], reason="drop")
            self._publish_pinned_locked()

    def note(self, key, outcome: str) -> None:
        """Record the last maintenance outcome for an entry (the
        ``filodb_superblock_maintenance_total`` taxonomy, surfaced per
        entry at /debug/superblocks)."""
        with self._lock:
            meta = self._meta.get(key)
            if meta is not None:
                meta["last_outcome"] = outcome

    def pin(self, key, owner) -> None:
        """Pin ``key`` against eviction on behalf of ``owner`` (a standing
        query id). Pinning a not-yet-built key is allowed — the pin takes
        effect when put() stores it."""
        with self._lock:
            self._pins.setdefault(key, set()).add(owner)
            self._publish_pinned_locked()

    def unpin(self, key, owner) -> None:
        with self._lock:
            owners = self._pins.get(key)
            if owners is not None:
                owners.discard(owner)
                if not owners:
                    self._pins.pop(key, None)
            self._publish_pinned_locked()

    def unpin_owner(self, owner) -> None:
        """Release every pin held by ``owner`` (standing-query
        unregister)."""
        with self._lock:
            for key in [k for k, o in self._pins.items() if owner in o]:
                self._pins[key].discard(owner)
                if not self._pins[key]:
                    self._pins.pop(key, None)
            self._publish_pinned_locked()

    def pinned_bytes(self) -> int:
        with self._lock:
            return self._pinned_bytes_locked()

    def _pinned_bytes_locked(self) -> int:
        return sum(v[2] for k, v in self._d.items() if k in self._pins)

    def _publish_pinned_locked(self) -> None:
        from ..metrics import REGISTRY

        REGISTRY.gauge("filodb_superblock_pinned_bytes").set(
            float(self._pinned_bytes_locked())
        )

    def put(self, key, versions: tuple, value, nbytes: int) -> None:
        if nbytes > self.max_bytes:
            return  # never pin more device memory than the whole budget
        with self._lock:
            replaced = self._d.pop(key, None)
            if replaced is not None:
                self.ledger.free(replaced[2], reason="replace")
            used = sum(e[2] for e in self._d.values())
            while self._d and (
                len(self._d) >= self.max_entries
                or used + nbytes > self.max_bytes
            ):
                # evict in LRU order but never a pinned entry; when only
                # pinned entries remain, tolerate running over budget (the
                # standing set is deliberately small and bounded by its own
                # registration cap)
                ek = next((k for k in self._d if k not in self._pins), None)
                if ek is None:
                    break
                ev = self._d.pop(ek)
                self._meta.pop(ek, None)
                used -= ev[2]
                self.ledger.free(ev[2], reason="evict")
            self._d[key] = (versions, value, nbytes)
            self.ledger.alloc(nbytes)
            self._publish_pinned_locked()
            prev = self._meta.get(key)
            # sharded entries record their placement at put time (metadata
            # only — never touches device values): the sharding spec and
            # even per-device byte split feed /debug/superblocks and the
            # filodb_device_bytes{kind,device} gauges
            mesh = getattr(getattr(value, "block", None), "placement", None)
            self._meta[key] = {
                "created": time.time(),
                "hits": prev["hits"] if prev else 0,
                "last_outcome": prev["last_outcome"] if prev else None,
                "sharding": mesh_spec_str(mesh),
                "device_bytes": mesh_device_bytes(mesh, nbytes),
            }

    def snapshot(self) -> list[dict]:
        """Introspection view for /debug/superblocks: one dict per cached
        entry (key rendered, true device bytes, age, hits, last maintenance
        outcome, and the entry's scan accounting when it carries any)."""
        now = time.time()
        with self._lock:
            items = [(k, v, dict(self._meta.get(k) or {}),
                      k in self._pins)
                     for k, v in self._d.items()]
        out = []
        for key, (versions, value, nbytes), meta, pinned in items:
            entry = {
                "key": repr(key),
                "bytes": int(nbytes),
                "age_s": round(now - meta.get("created", now), 3),
                "hits": int(meta.get("hits", 0)),
                "last_outcome": meta.get("last_outcome"),
                "versions": list(versions),
                "sharding": meta.get("sharding"),
                "device_bytes": meta.get("device_bytes"),
                "pinned": bool(pinned),
            }
            block = getattr(value, "block", None)
            if block is not None:
                entry["series"] = int(getattr(value, "series", 0)
                                      or block.n_series)
                # .shape is metadata on both jax and numpy arrays — never
                # np.asarray here, that would pull the device block to host
                entry["shape"] = list(block.vals.shape)
                entry["is_hist"] = bool(getattr(value, "is_hist", False))
                entry["stage_mode"] = getattr(value, "stage_mode", None)
                entry["grid"] = grid_class(block)
            out.append(entry)
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)


def stage_from_shard(
    shard,
    part_ids,
    column: str,
    start_ms: int,
    end_ms: int,
    is_counter: bool = False,
    dtype=np.float32,
    mode: str | None = None,
) -> StagedBlock:
    """Gather [start_ms, end_ms] samples for part_ids from a shard and stage.

    ``mode`` selects the counter staging strategy (function-driven — the
    reference applies counter correction only inside rate-family
    RangeFunctions, never at the read path):

    - ``"corrected"`` — reset-corrected minus baseline (rate/increase/irate)
    - ``"shifted"``   — raw minus per-series baseline, NO reset correction:
      exact f32 for shift-invariant functions (delta/deriv/stddev...) even on
      1e15-magnitude counters
    - ``"diff"``      — f64-exact adjacent differences (changes/resets/idelta)
    - ``"raw"``       — plain raw values (value-returning functions: a plain
      selector, last/min/max/sum_over_time, quantile...)

    When mode is None, is_counter=True maps to "corrected" (legacy callers
    that only ever stage for rate-family kernels).
    """
    if mode is None:
        mode = "corrected" if is_counter else "raw"
    series = []
    refs = []
    hist_width = None
    for pid in part_ids:
        part = shard.partition(int(pid))
        ts, vals = part.samples_in_range(start_ms, end_ms, column)
        if vals.ndim == 2:
            hist_width = vals.shape[1]
        series.append((ts, vals))
        refs.append((shard.shard_num, int(pid)))
    if hist_width is not None:
        return stage_histogram_series(
            series, start_ms, hist_width, refs,
            subtract_baseline=mode in ("corrected", "shifted"), dtype=dtype
        )

    newest = max((int(ts[-1]) for ts, _ in series if len(ts)), default=None)

    def _stage(sr):
        # modest time headroom on small-to-medium LIVE-EDGE blocks (range
        # reaches past the newest sample): append repairs then absorb many
        # scrapes before the padded width forces a full re-stage. Purely
        # historical ranges never repair, so they never pay the wider T.
        live_edge = newest is not None and end_ms >= newest
        headroom = 256 if (live_edge and len(sr) <= 8192) else 0
        return stage_series(
            sr, start_ms, refs,
            counter_corrected=mode == "corrected",
            subtract_baseline=mode == "shifted",
            diff_encode=mode == "diff",
            dtype=dtype,
            time_headroom=headroom,
        )

    block = _stage(series)
    if (
        block.regular_ts is None and block.nominal_ts is None
        and block.n_series > 1
    ):
        aligned = _slot_align(shard, part_ids, column, series, start_ms, end_ms)
        if aligned is not None:
            block = _stage(aligned)
    return block
