"""Device-side posting-bitmap intersection (the part-key index's opt-in
HBM tier, memstore/index_device.py).

One tiny jit program: AND-reduce a stacked ``[M, W]`` array of packed
bitmap words — M staged posting bitmaps (one per equality matcher), W words
covering the shard's part-id universe. M is tiny (a selector rarely carries
more than ~6 matchers) so the reduction unrolls at trace time; the jit
cache keys on the (M, W) shape like every other kernel here.

Words are ``uint32`` on device: the host index packs ``uint64`` words, but
jax without ``jax_enable_x64`` silently narrows 64-bit integers, and
bitwise AND is invariant under the little-endian ``uint64 -> 2x uint32``
view reinterpretation, so the split is free and lossless both ways
(memstore/postings.py documents the bit-order contract).
"""

from __future__ import annotations

import numpy as np


def host_words_to_device(words: np.ndarray):
    """Pack host uint64 bitmap words for device residency (uint32 view)."""
    import jax

    return jax.device_put(np.ascontiguousarray(words).view(np.uint32))


def intersect_on_device(dev_words: list) -> np.ndarray:
    """AND the staged device bitmaps in ONE jit dispatch; returns the host
    uint64 result words. Instrumented like every other kernel entry point
    (per-dispatch latency + JIT hit/miss + the executable registry)."""
    import time as _time

    import jax.numpy as jnp

    from ..metrics import record_kernel_dispatch

    stacked = jnp.stack(dev_words)
    fn = _intersect_jit()
    t0 = _time.perf_counter()
    before = fn._cache_size()
    out_dev = fn(stacked)
    m_, w_ = stacked.shape
    record_kernel_dispatch(
        "postings_intersect", _time.perf_counter() - t0,
        compiled=fn._cache_size() > before,
        key={"variant": "general", "shapes": f"M{m_}xW{w_}"},
        result=out_dev,
    )
    out = np.asarray(out_dev)
    return np.ascontiguousarray(out).view(np.uint64)


_jit_cache = {}


def _intersect_jit():
    import jax

    intersect_words = _jit_cache.get("intersect_words")
    if intersect_words is None:
        def _and_reduce(ws):
            out = ws[0]
            # static leading dim: unrolled at trace time, ONE fused kernel
            for i in range(1, ws.shape[0]):
                out = out & ws[i]
            return out

        intersect_words = _jit_cache["intersect_words"] = jax.jit(_and_reduce)
        from ..obs.kernels import KERNELS

        KERNELS.register_jits("ops.postings_kernels",
                              intersect_words=intersect_words)
    return intersect_words
