"""Cross-series aggregation kernels (reference L4: query/exec/aggregator/ —
RowAggregator SPI with Sum/Min/Max/Count/Avg/Stddev/Stdvar/TopK/Quantile/
CountValues/Group over RangeVectors, AggrOverRangeVectors.scala mapReduce).

The reference map-reduces per-series rows through per-aggregator state
machines; here ``sum by (labels)`` is a masked segment-reduce over the
``[S, J]`` result grid — one jit call for all steps and all groups — and
cross-shard merging becomes a psum over the mesh (parallel/).

NaN = absence everywhere: a NaN sample doesn't contribute, and a group with
no members at a step yields NaN.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

SIMPLE_AGG_OPS = ("sum", "count", "avg", "min", "max", "stddev", "stdvar", "group")


def segment_aggregate(op: str, values, group_ids, num_groups: int):
    """values [S, J] (NaN = absent), group_ids [S] int32 -> [G, J].

    Instrumented entry point: per-op dispatch latency + JIT cache hit/miss
    (metrics.record_kernel_dispatch) around the jitted kernel."""
    import time as _time

    from ..metrics import record_kernel_dispatch

    t0 = _time.perf_counter()
    before = _segment_aggregate_jit._cache_size()
    out = _segment_aggregate_jit(op, values, group_ids, num_groups)
    s_, j_ = np.shape(values)
    record_kernel_dispatch(
        f"segment_{op}", _time.perf_counter() - t0,
        compiled=_segment_aggregate_jit._cache_size() > before,
        key={"variant": "general", "epilogue": f"agg:{op}",
             "shapes": f"S{s_}xJ{j_}xG{num_groups}"},
        result=out,
    )
    return out


@functools.partial(jax.jit, static_argnames=("op", "num_groups"))
def _segment_aggregate_jit(op: str, values, group_ids, num_groups: int):
    valid = ~jnp.isnan(values)
    v0 = jnp.where(valid, values, 0.0)
    count = jax.ops.segment_sum(valid.astype(values.dtype), group_ids, num_groups)
    has = count > 0
    if op == "count":
        return jnp.where(has, count, jnp.nan)
    if op == "group":
        return jnp.where(has, 1.0, jnp.nan)
    if op in ("sum", "avg", "stddev", "stdvar"):
        s = jax.ops.segment_sum(v0, group_ids, num_groups)
        if op == "sum":
            return jnp.where(has, s, jnp.nan)
        mean = s / jnp.maximum(count, 1.0)
        if op == "avg":
            return jnp.where(has, mean, jnp.nan)
        dev = jnp.where(valid, (values - mean[group_ids]) ** 2, 0.0)
        var = jax.ops.segment_sum(dev, group_ids, num_groups) / jnp.maximum(count, 1.0)
        return jnp.where(has, var if op == "stdvar" else jnp.sqrt(var), jnp.nan)
    if op in ("min", "max"):
        big = jnp.inf if op == "min" else -jnp.inf
        vm = jnp.where(valid, values, big)
        r = (
            jax.ops.segment_min(vm, group_ids, num_groups)
            if op == "min"
            else jax.ops.segment_max(vm, group_ids, num_groups)
        )
        return jnp.where(has, r, jnp.nan)
    raise ValueError(f"unknown aggregation {op}")


def _segment_psum_axis(op: str, grid, gids, num_groups: int, axis: str):
    """Local segment-reduce + collective combine over a mesh axis: the
    device-local half of ``segment_aggregate`` followed by psum/pmin/pmax,
    so a series-sharded [S_local, J] grid reduces to the REPLICATED [G, J]
    partials inside one program. Semantics mirror _segment_aggregate_jit
    exactly (NaN = absence; a group with no members anywhere yields NaN).
    The ONE definition shared by the sharded fused path and the parallel/
    mesh engines (parallel.mesh._segment_psum delegates here)."""
    valid = ~jnp.isnan(grid)
    v0 = jnp.where(valid, grid, 0.0)
    psum = jax.lax.psum
    c = psum(
        jax.ops.segment_sum(valid.astype(jnp.float32), gids, num_groups), axis
    )
    if op in ("sum", "avg", "count"):
        s = psum(jax.ops.segment_sum(v0, gids, num_groups), axis)
        if op == "sum":
            return jnp.where(c > 0, s, jnp.nan)
        if op == "count":
            return jnp.where(c > 0, c, jnp.nan)
        return jnp.where(c > 0, s / jnp.maximum(c, 1.0), jnp.nan)
    if op in ("min", "max"):
        big = jnp.inf if op == "min" else -jnp.inf
        vm = jnp.where(valid, grid, big)
        if op == "min":
            r = jax.lax.pmin(jax.ops.segment_min(vm, gids, num_groups), axis)
        else:
            r = jax.lax.pmax(jax.ops.segment_max(vm, gids, num_groups), axis)
        return jnp.where(c > 0, r, jnp.nan)
    raise ValueError(f"unsupported sharded aggregation {op}")


# ---------------------------------------------------------------------------
# fused range-function -> segment-aggregate (single-dispatch cross-shard path)
# ---------------------------------------------------------------------------

# range functions the fused MXU variant handles directly (the subset of
# mxu_kernels.MXU_FUNCS that needs no extra lazily-built window structures)
FUSED_MXU_FUNCS = {
    "sum_over_time", "count_over_time", "avg_over_time", "last",
    "last_over_time", "first_over_time", "present_over_time",
    "stddev_over_time", "stdvar_over_time", "z_score",
    "rate", "increase", "delta", "idelta", "irate",
}

# range functions the fused JITTER/MASKED variants handle: the mxu_jitter
# set plus min/max_over_time, which ride dedicated fused minmax programs
# (tile hierarchy + edge one-hots, built lazily via wm.ensure_minmax) —
# jittered/holey grids stay ONE fast fused dispatch for them too
FUSED_JITTER_FUNCS = FUSED_MXU_FUNCS | {"min_over_time", "max_over_time"}


def _grid_variant(block, func: str, is_delta: bool):
    """Kernel-variant ladder for one fused dispatch, decided from the
    (super)block's grid classification (staging.grid_class) and the
    function: ``mxu`` (exact shared grid, window matmuls) > ``jitter``
    (near-regular: certain-membership matmul + per-series boundary
    corrections, ops/mxu_jitter) > ``masked`` (near-regular with missed
    scrapes: validity-masked sidecar) > ``general``. The ONE selection
    shared by the single-query dispatch (_fused_dispatch) and the
    cross-query batcher (fused_batched_scalar) — a batched lane MUST
    compute through the same variant its unbatched execution would, or
    batched-vs-sequential parity breaks.

    Returns ``(variant, degrade_reason)``: ``degrade_reason`` is a
    fused-fallback taxonomy entry (``grid_jitter``/``grid_holes``) set only
    when a jittered/holey grid is truly unsupported by its fast variant
    (function outside FUSED_JITTER_FUNCS) and the dispatch degrades to the
    multi-pass general kernel — still ONE fused dispatch, just slower."""
    if not (is_delta and func in ("irate", "idelta")):
        if block.regular_ts is not None:
            if func in FUSED_MXU_FUNCS:
                return "mxu", None
        elif block.nominal_ts is not None:
            if func in FUSED_JITTER_FUNCS:
                return "jitter", None
            return "general", "grid_jitter"
        elif getattr(block, "mgrid", None) is not None:
            if func in FUSED_JITTER_FUNCS:
                return "masked", None
            return "general", "grid_holes"
    return "general", None


def _pallas_variant(block, func: str, mesh) -> bool:
    """Whether a general-path dispatch should promote to the fused Pallas
    gather-scan backend: single-device, a truly IRREGULAR grid (regular /
    near-regular / masked grids have cheaper structured variants), a
    function the Pallas finisher models, and the shared FILODB_PALLAS
    policy (pallas_kernels.pallas_enabled — the same predicate the legacy
    range-function dispatch applies, so the two paths can't drift)."""
    if mesh is not None:
        return False
    if (block.regular_ts is not None or block.nominal_ts is not None
            or getattr(block, "mgrid", None) is not None):
        return False
    from .pallas_kernels import PALLAS_FUNCS, pallas_enabled

    return func in PALLAS_FUNCS and pallas_enabled()


def batch_variant_supported(block, func: str, kind: str, is_delta: bool,
                            mesh) -> bool:
    """Whether the batched program set models this dispatch's kernel
    variant. The scheduler consults this BEFORE grouping
    (FusedAggregateExec._dispatch_fused): a structurally-unbatchable
    request runs unbatched immediately instead of paying the batch window
    and a guaranteed-to-raise launch (which would also mint
    ``outcome="fallback"`` dispatches operators are told to investigate).
    The raises inside fused_batched_scalar/fused_batched_hist remain as
    the defensive backstop for window-dependent cases (a merged window
    failing the jitter safety bound)."""
    if kind == "hist":
        # jittered hist grids take the unbatched jitter variant
        return block.regular_ts is not None or block.nominal_ts is None
    variant, reason = _grid_variant(block, func, is_delta)
    if variant in ("jitter", "masked") and func in (
        "min_over_time", "max_over_time"
    ):
        # the fused minmax programs (tile hierarchy + edge one-hots) have
        # no batched twin — the query still runs ONE fused dispatch, it
        # just doesn't coalesce with other lanes
        return False
    if variant == "general" and reason is None and _pallas_variant(
        block, func, mesh
    ):
        return False
    return True


def _jwm_args(wm) -> tuple:
    """The jitter window structure as ONE flat tuple in
    jitter_range_kernel's positional order (a pytree jit argument — one
    signature for the plain/sharded/batched fused jitter programs)."""
    return (wm.d_W0, wm.d_SEL, wm.d_idx, wm.d_count0, wm.d_c0pos,
            wm.d_c0ge2, wm.d_has_klo, wm.d_has_khi, wm.d_F0_rel,
            wm.d_L0_rel, wm.d_L2_rel, wm.d_Klo_rel, wm.d_Khi_rel,
            wm.d_blo_rel, wm.d_ehi_rel)


def _mwm_args(wm) -> tuple:
    """Masked-grid window structure tuple (jitter_masked_kernel order)."""
    return (wm.d_W0, wm.d_SEL, wm.d_idx, wm.d_c0pos, wm.d_has_klo,
            wm.d_has_khi, wm.d_F0_rel, wm.d_L0_rel, wm.d_Klo_rel,
            wm.d_Khi_rel, wm.d_blo_rel, wm.d_ehi_rel)


def _jmm_args(wm) -> tuple:
    """The minmax window structure as ONE flat tuple in jitter_minmax's
    positional order (requires wm.ensure_minmax() first — the tile/edge
    structures build lazily)."""
    return (wm.d_SEL, wm.d_idx, wm.d_tile_mask, wm.d_edge_onehot,
            wm.d_edge_valid, wm.d_edge_idx, wm.d_count0, wm.d_has_klo,
            wm.d_has_khi, wm.d_blo_rel, wm.d_ehi_rel)


def _mmm_args(wm) -> tuple:
    """Masked-grid minmax structure tuple (jitter_masked_minmax order:
    the grid-level c0pos replaces the per-window certain count)."""
    return (wm.d_SEL, wm.d_idx, wm.d_tile_mask, wm.d_edge_onehot,
            wm.d_edge_valid, wm.d_edge_idx, wm.d_c0pos, wm.d_has_klo,
            wm.d_has_khi, wm.d_blo_rel, wm.d_ehi_rel)


def _mgrid_args(g) -> tuple:
    """A block's masked sidecar arrays as ONE flat tuple in
    jitter_masked_kernel's positional order (vals..bfraw)."""
    raw = g.raw if g.raw is not None else g.vals
    bfraw = g.bfraw if g.bfraw is not None else g.bfv
    return (g.vals, g.dev, raw, g.valid, g.cc, g.ffv, g.ffd, g.bfv, g.bfd,
            g.ff2v, g.ff2d, bfraw)


def _apply_epilogue(sj, epilogue: tuple, gids, n_real, qv, num_groups: int):
    """Device-side epilogue over the [S, J] range grid, INSIDE the same
    compiled program as the range kernel. ``epilogue`` is a static tuple:

      ("agg", op)          -> [G, J] segment aggregate
      ("topk", k, bottom)  -> ([k, J] values, [k, J] i32 series indices):
                              per-step top/bottom-k across series, the
                              compact form of ``topk_mask`` — only O(k*J)
                              crosses to the host, never [S, J]
      ("quantile",)        -> [G, J] per-(group, step) quantile at ``qv``
                              (``segment_quantile`` inside the jit boundary)

    ``gids`` follows the trash-group contract (padded rows -> group
    ``num_groups``); ``n_real`` additionally masks padded rows for the
    non-segmented epilogues (count/present-style functions yield REAL
    values on padded rows in the MXU kernel variant, which a top-k would
    otherwise happily select)."""
    kind = epilogue[0]
    if kind == "agg":
        return _segment_aggregate_jit(epilogue[1], sj, gids, num_groups + 1)[:num_groups]
    S, J = sj.shape
    rows = jax.lax.broadcasted_iota(jnp.int32, (S, J), 0)
    sj = jnp.where(rows < n_real, sj, jnp.nan)
    if kind == "topk":
        _, k, bottom = epilogue
        v = jnp.where(jnp.isnan(sj), jnp.inf if bottom else -jnp.inf, sj)
        vt = v.T if not bottom else -v.T  # [J, S], larger = better
        top_vals, top_idx = jax.lax.top_k(vt, min(k, S))  # [J, kk]
        vals = jnp.where(
            jnp.isfinite(top_vals),
            top_vals if not bottom else -top_vals,
            jnp.nan,
        )
        return vals.T, top_idx.T.astype(jnp.int32)  # [kk, J] each
    if kind == "quantile":
        return segment_quantile(sj, gids, num_groups + 1, qv)[:num_groups]
    raise ValueError(f"unknown fused epilogue {epilogue}")


@functools.partial(jax.jit, static_argnames=(
    "func", "epilogue", "num_steps", "num_groups", "is_counter", "is_delta"
))
def _fused_general_jit(func, epilogue, ts, vals, lens, baseline, raw, gids,
                       n_real, qv, start_off, step_ms, window,
                       num_steps: int, num_groups: int, is_counter: bool,
                       is_delta: bool):
    """range_kernel -> epilogue as ONE compiled program: only the [G, J]
    group partials (or [k, J] top-k rows) ever exist as program outputs —
    no [S, J] grid reaches the host, and no second dispatch happens. See
    _apply_epilogue for the trash-group / padded-row contract."""
    from .kernels import range_kernel

    sj = range_kernel(
        func, ts, vals, lens, baseline, raw, start_off, step_ms, window,
        num_steps, is_counter=is_counter, is_delta=is_delta,
    )
    return _apply_epilogue(sj, epilogue, gids, n_real, qv, num_groups)


@functools.partial(jax.jit, static_argnames=(
    "func", "epilogue", "num_groups", "is_counter", "is_delta", "fetch"
))
def _fused_mxu_jit(func, epilogue, vals, raw, baseline, W, F, L, L2, count,
                   t_first, t_last, t_last2, out_t, window_ms, idx, gids,
                   n_real, qv, num_groups: int, is_counter: bool,
                   is_delta: bool, fetch: str):
    """Regular-grid fused variant: the MXU window-matmul kernel and the
    epilogue in one compiled program (see _apply_epilogue for the
    trash-group / padded-row contract)."""
    from .mxu_kernels import mxu_range_kernel

    sj = mxu_range_kernel(
        func, vals, raw, baseline, W, F, L, L2, count, t_first, t_last,
        t_last2, out_t, window_ms, idx=idx, is_counter=is_counter,
        is_delta=is_delta, fetch=fetch,
    )
    return _apply_epilogue(sj, epilogue, gids, n_real, qv, num_groups)


@functools.partial(jax.jit, static_argnames=(
    "func", "epilogue", "num_groups", "is_counter", "is_delta", "fetch"
))
def _fused_jitter_jit(func, epilogue, vals, dev, raw, jwm, window_ms, gids,
                      n_real, qv, num_groups: int, is_counter: bool,
                      is_delta: bool, fetch: str):
    """Near-regular-grid fused variant: the jitter kernel (certain-window
    matmul + per-series boundary corrections, ops/mxu_jitter) and the
    epilogue in ONE compiled program — a jittered scrape grid stays a
    single warm dispatch instead of paying the multi-pass general path.
    ``jwm`` is the flat window-structure tuple (_jwm_args)."""
    from .mxu_jitter import jitter_range_kernel

    sj = jitter_range_kernel(
        func, vals, dev, raw, *jwm, window_ms,
        is_counter=is_counter, is_delta=is_delta, fetch=fetch,
    )
    return _apply_epilogue(sj, epilogue, gids, n_real, qv, num_groups)


@functools.partial(jax.jit, static_argnames=(
    "func", "epilogue", "num_groups", "is_counter", "is_delta", "fetch"
))
def _fused_masked_jit(func, epilogue, mba, mwm, window_ms, maxdev, gids,
                      n_real, qv, num_groups: int, is_counter: bool,
                      is_delta: bool, fetch: str):
    """Missing-scrape fused variant: the validity-masked jitter kernel over
    the block's slot-aligned sidecar (staging.MaskedGrid) + epilogue, one
    program. ``mba`` = _mgrid_args sidecar tuple, ``mwm`` = _mwm_args;
    ``maxdev`` enables the kernel's lean gather plan."""
    from .mxu_jitter import jitter_masked_kernel

    sj = jitter_masked_kernel(
        func, *mba, *mwm, window_ms,
        is_counter=is_counter, is_delta=is_delta, fetch=fetch,
        maxdev=maxdev,
    )
    return _apply_epilogue(sj, epilogue, gids, n_real, qv, num_groups)


@functools.partial(jax.jit, static_argnames=(
    "func", "epilogue", "num_groups", "n_valid", "fetch"
))
def _fused_jitter_minmax_jit(func, epilogue, vals, dev, jmm, gids, n_real,
                             qv, num_groups: int, n_valid: int, fetch: str):
    """min/max_over_time on a near-regular grid: the tile-hierarchy minmax
    kernel (ops/mxu_jitter.jitter_minmax) + epilogue in ONE compiled
    program — min/max no longer degrade jittered grids to the multi-pass
    general path. ``jmm`` is the flat minmax structure tuple (_jmm_args,
    built lazily via wm.ensure_minmax BEFORE the timed span)."""
    from .mxu_jitter import jitter_minmax

    sj = jitter_minmax(
        vals, dev, *jmm, n_valid=n_valid,
        is_min=(func == "min_over_time"), fetch=fetch,
    )
    return _apply_epilogue(sj, epilogue, gids, n_real, qv, num_groups)


@functools.partial(jax.jit, static_argnames=(
    "func", "epilogue", "num_groups", "fetch"
))
def _fused_masked_minmax_jit(func, epilogue, vals, dev, valid, cc, mmm,
                             gids, n_real, qv, num_groups: int, fetch: str):
    """Missing-scrape min/max fused variant: the validity-masked tile
    hierarchy (jitter_masked_minmax) + epilogue in one program. ``mmm`` =
    _mmm_args (after wm.ensure_minmax)."""
    from .mxu_jitter import jitter_masked_minmax

    sj = jitter_masked_minmax(
        vals, dev, valid, cc, *mmm,
        is_min=(func == "min_over_time"), fetch=fetch,
    )
    return _apply_epilogue(sj, epilogue, gids, n_real, qv, num_groups)


@functools.partial(jax.jit, static_argnames=(
    "func", "epilogue", "j_pad", "num_groups", "is_counter", "is_delta",
    "interpret"
))
def _fused_pallas_jit(func, epilogue, ts, vals, raw, lens, gids, n_real, qv,
                      start_off, step_ms, window, j_pad: int,
                      num_groups: int, is_counter: bool, is_delta: bool,
                      interpret: bool):
    """Truly-irregular-grid fused variant: the one-pass Pallas window-stats
    kernel (ops/pallas_kernels.window_aggregates, VMEM-tiled gather-scan) +
    its finisher + the epilogue behind the SAME jit boundary — interpret
    mode on CPU (tier-1), compiled on TPU. The Pallas grid pads S/J up to
    its tile sizes; slice back to the block's own padding before the
    epilogue so the trash-group/gids contract is unchanged."""
    from .pallas_kernels import finish, window_aggregates

    agg = window_aggregates(
        ts, vals, raw, lens, start_off, step_ms, window, j_pad,
        interpret=interpret,
    )
    sj = finish(func, agg, start_off, step_ms, window,
                is_counter=is_counter, is_delta=is_delta)
    sj = sj[: vals.shape[0], :j_pad]
    return _apply_epilogue(sj, epilogue, gids, n_real, qv, num_groups)


def _sharded_epilogue(sj, epilogue: tuple, gids_l, n_real, qv,
                      num_groups: int, axis: str):
    """Device-local half of _apply_epilogue inside a shard_map body, with
    the cross-device combine fused into the SAME program:

      ("agg", op)          -> local segment reduce + psum/pmin/pmax -> [G, J]
      ("topk", k, bottom)  -> local top-k winners (values + GLOBAL series
                              indices), all_gather'd and re-reduced to the
                              global [k, J] winner set — O(D*k*J) on the
                              interconnect, never the [ΣS, J] grid
      ("quantile",)        -> exact quantile needs the full value multiset
                              per group: all_gather the [S_l, J] rows (the
                              one epilogue that moves O(ΣS*J) over ICI,
                              still inside the single program) and sort

    Padded-row handling matches the single-device contract: trash-group
    gids for segment reduces; GLOBAL row index vs ``n_real`` for the
    non-segmented epilogues (a device's local rows map to global rows
    ``axis_index * S_local + i``)."""
    kind = epilogue[0]
    if kind == "agg":
        return _segment_psum_axis(
            epilogue[1], sj, gids_l, num_groups + 1, axis
        )[:num_groups]
    S_l, J = sj.shape
    d = jax.lax.axis_index(axis)
    rows = jax.lax.broadcasted_iota(jnp.int32, (S_l, J), 0) + d * S_l
    sj = jnp.where(rows < n_real, sj, jnp.nan)
    if kind == "topk":
        _, k, bottom = epilogue
        v = jnp.where(jnp.isnan(sj), jnp.inf if bottom else -jnp.inf, sj)
        vt = v.T if not bottom else -v.T  # [J, S_l], larger = better
        kk = min(k, S_l)
        lv, li = jax.lax.top_k(vt, kk)  # [J, kk] local winners
        gi = li.astype(jnp.int32) + d * S_l  # global series indices
        av = jax.lax.all_gather(lv, axis)  # [D, J, kk]
        ai = jax.lax.all_gather(gi, axis)
        D = av.shape[0]
        av = jnp.transpose(av, (1, 0, 2)).reshape(J, D * kk)
        ai = jnp.transpose(ai, (1, 0, 2)).reshape(J, D * kk)
        k2 = min(k, D * kk)  # == single-device min(k, S_pad)
        fv, fi = jax.lax.top_k(av, k2)  # [J, k2] global winners
        gidx = jnp.take_along_axis(ai, fi, axis=1)
        vals = jnp.where(
            jnp.isfinite(fv), fv if not bottom else -fv, jnp.nan
        )
        return vals.T, gidx.T.astype(jnp.int32)  # [k2, J] each
    if kind == "quantile":
        full = jax.lax.all_gather(sj, axis).reshape(-1, J)  # [ΣS, J]
        full_g = jax.lax.all_gather(gids_l, axis).reshape(-1)
        return segment_quantile(full, full_g, num_groups + 1, qv)[:num_groups]
    raise ValueError(f"unknown fused epilogue {epilogue}")


def _sharded_out_specs(epilogue: tuple):
    from jax.sharding import PartitionSpec as P

    return (P(), P()) if epilogue[0] == "topk" else P()


@functools.partial(jax.jit, static_argnames=(
    "mesh", "func", "epilogue", "num_steps", "num_groups", "is_counter",
    "is_delta"
))
def _fused_sharded_general_jit(mesh, func, epilogue, ts, vals, lens, baseline,
                               raw, gids, n_real, qv, start_off, step_ms,
                               window, num_steps: int, num_groups: int,
                               is_counter: bool, is_delta: bool):
    """Series-sharded twin of _fused_general_jit: the row-wise range kernel
    runs on each device's row band and the epilogue combines across the
    mesh (psum / gathered winner state) INSIDE the same compiled program —
    one dispatch spans every device, and only replicated [G, J] / [k, J]
    outputs exist."""
    from jax.sharding import PartitionSpec as P

    from ..jax_compat import shard_map
    from .kernels import range_kernel

    axis = mesh.axis_names[0]

    def local(ts_l, vals_l, lens_l, base_l, raw_l, gids_l):
        sj = range_kernel(
            func, ts_l, vals_l, lens_l, base_l, raw_l, start_off, step_ms,
            window, num_steps, is_counter=is_counter, is_delta=is_delta,
        )
        return _sharded_epilogue(sj, epilogue, gids_l, n_real, qv,
                                 num_groups, axis)

    row, vec = P(axis, None), P(axis)
    return shard_map(
        local, mesh=mesh,
        in_specs=(row, row, vec, vec, row, vec),
        out_specs=_sharded_out_specs(epilogue),
        check=False,
    )(ts, vals, lens, baseline, raw, gids)


@functools.partial(jax.jit, static_argnames=(
    "mesh", "func", "epilogue", "num_groups", "is_counter", "is_delta",
    "fetch"
))
def _fused_sharded_mxu_jit(mesh, func, epilogue, vals, raw, baseline, W, F, L,
                           L2, count, t_first, t_last, t_last2, out_t,
                           window_ms, idx, gids, n_real, qv,
                           num_groups: int, is_counter: bool, is_delta: bool,
                           fetch: str):
    """Series-sharded twin of _fused_mxu_jit: replicated [T, J] window
    matrices ride the closure (committed replicated at build), the matmul
    kernel runs per row band, and the epilogue combines over the mesh in
    the same program."""
    from jax.sharding import PartitionSpec as P

    from ..jax_compat import shard_map
    from .mxu_kernels import mxu_range_kernel

    axis = mesh.axis_names[0]

    def local(vals_l, raw_l, base_l, gids_l):
        sj = mxu_range_kernel(
            func, vals_l, raw_l, base_l, W, F, L, L2, count, t_first, t_last,
            t_last2, out_t, window_ms, idx=idx, is_counter=is_counter,
            is_delta=is_delta, fetch=fetch,
        )
        return _sharded_epilogue(sj, epilogue, gids_l, n_real, qv,
                                 num_groups, axis)

    row, vec = P(axis, None), P(axis)
    return shard_map(
        local, mesh=mesh,
        in_specs=(row, row, vec, vec),
        out_specs=_sharded_out_specs(epilogue),
        check=False,
    )(vals, raw, baseline, gids)


@functools.partial(jax.jit, static_argnames=(
    "mesh", "func", "epilogue", "num_groups", "is_counter", "is_delta",
    "fetch"
))
def _fused_sharded_jitter_jit(mesh, func, epilogue, vals, dev, raw, jwm,
                              window_ms, gids, n_real, qv, num_groups: int,
                              is_counter: bool, is_delta: bool, fetch: str):
    """Series-sharded twin of _fused_jitter_jit: the replicated window
    structure rides the closure (committed mesh-replicated at build, like
    the MXU matrices), the jitter kernel runs per row band, and the
    epilogue combines over the mesh in the same program — mesh + jitter no
    longer drops to the sharded general kernel (the PR 8 remainder)."""
    from jax.sharding import PartitionSpec as P

    from ..jax_compat import shard_map
    from .mxu_jitter import jitter_range_kernel

    axis = mesh.axis_names[0]

    def local(vals_l, dev_l, raw_l, gids_l):
        sj = jitter_range_kernel(
            func, vals_l, dev_l, raw_l, *jwm, window_ms,
            is_counter=is_counter, is_delta=is_delta, fetch=fetch,
        )
        return _sharded_epilogue(sj, epilogue, gids_l, n_real, qv,
                                 num_groups, axis)

    row, vec = P(axis, None), P(axis)
    return shard_map(
        local, mesh=mesh,
        in_specs=(row, row, row, vec),
        out_specs=_sharded_out_specs(epilogue),
        check=False,
    )(vals, dev, raw, gids)


@functools.partial(jax.jit, static_argnames=(
    "mesh", "func", "epilogue", "num_groups", "is_counter", "is_delta",
    "fetch"
))
def _fused_sharded_masked_jit(mesh, func, epilogue, mba, mwm, window_ms,
                              maxdev, gids, n_real, qv, num_groups: int,
                              is_counter: bool, is_delta: bool, fetch: str):
    """Series-sharded twin of _fused_masked_jit: every [S, T'] sidecar
    array is a row band (staging pins them with the block's placement),
    the replicated masked window structure rides the closure."""
    from jax.sharding import PartitionSpec as P

    from ..jax_compat import shard_map
    from .mxu_jitter import jitter_masked_kernel

    axis = mesh.axis_names[0]

    def local(mba_l, gids_l):
        sj = jitter_masked_kernel(
            func, *mba_l, *mwm, window_ms,
            is_counter=is_counter, is_delta=is_delta, fetch=fetch,
            maxdev=maxdev,
        )
        return _sharded_epilogue(sj, epilogue, gids_l, n_real, qv,
                                 num_groups, axis)

    row, vec = P(axis, None), P(axis)
    return shard_map(
        local, mesh=mesh,
        in_specs=(tuple(row for _ in mba), vec),
        out_specs=_sharded_out_specs(epilogue),
        check=False,
    )(mba, gids)


@functools.partial(jax.jit, static_argnames=(
    "mesh", "func", "epilogue", "num_groups", "n_valid", "fetch"
))
def _fused_sharded_jitter_minmax_jit(mesh, func, epilogue, vals, dev, jmm,
                                     gids, n_real, qv, num_groups: int,
                                     n_valid: int, fetch: str):
    """Series-sharded twin of _fused_jitter_minmax_jit: the replicated
    minmax structures ride the closure, the tile-hierarchy kernel runs per
    row band (``n_valid`` masks the TIME axis, unchanged by series
    sharding), and the epilogue combines over the mesh in one program."""
    from jax.sharding import PartitionSpec as P

    from ..jax_compat import shard_map
    from .mxu_jitter import jitter_minmax

    axis = mesh.axis_names[0]

    def local(vals_l, dev_l, gids_l):
        sj = jitter_minmax(
            vals_l, dev_l, *jmm, n_valid=n_valid,
            is_min=(func == "min_over_time"), fetch=fetch,
        )
        return _sharded_epilogue(sj, epilogue, gids_l, n_real, qv,
                                 num_groups, axis)

    row, vec = P(axis, None), P(axis)
    return shard_map(
        local, mesh=mesh,
        in_specs=(row, row, vec),
        out_specs=_sharded_out_specs(epilogue),
        check=False,
    )(vals, dev, gids)


@functools.partial(jax.jit, static_argnames=(
    "mesh", "func", "epilogue", "num_groups", "fetch"
))
def _fused_sharded_masked_minmax_jit(mesh, func, epilogue, vals, dev, valid,
                                     cc, mmm, gids, n_real, qv,
                                     num_groups: int, fetch: str):
    """Series-sharded twin of _fused_masked_minmax_jit (row-band sidecar
    arrays, replicated minmax structures in the closure)."""
    from jax.sharding import PartitionSpec as P

    from ..jax_compat import shard_map
    from .mxu_jitter import jitter_masked_minmax

    axis = mesh.axis_names[0]

    def local(vals_l, dev_l, valid_l, cc_l, gids_l):
        sj = jitter_masked_minmax(
            vals_l, dev_l, valid_l, cc_l, *mmm,
            is_min=(func == "min_over_time"), fetch=fetch,
        )
        return _sharded_epilogue(sj, epilogue, gids_l, n_real, qv,
                                 num_groups, axis)

    row, vec = P(axis, None), P(axis)
    return shard_map(
        local, mesh=mesh,
        in_specs=(row, row, row, row, vec),
        out_specs=_sharded_out_specs(epilogue),
        check=False,
    )(vals, dev, valid, cc, gids)


def _exec_key_parts(variant: str, epilogue, block, j_pad: int,
                    num_groups: int, mesh=None, batch: str | None = None):
    """Executable-key parts for the kernel observatory (obs/kernels.py
    KEY_DIMS): the static signature that selects the XLA executable —
    kernel variant, epilogue statics, PADDED device shapes, mesh width and
    batched-lane composition. Metadata reads only (shape tuples), shared
    by every fused dispatch site so the key vocabulary has ONE builder."""
    shape = tuple(np.shape(block.vals))
    dims = f"S{shape[0]}xT{shape[1] if len(shape) > 1 else 1}"
    if len(shape) > 2:
        dims += f"xB{shape[2]}"
    ep = (":".join(str(x) for x in epilogue) if isinstance(epilogue, tuple)
          else str(epilogue))
    return {
        "variant": variant,
        "epilogue": ep or None,
        "shapes": f"{dims}xJ{j_pad}xG{num_groups}",
        "mesh": mesh.devices.size if mesh is not None else None,
        "batch": batch,
    }


def _fused_dispatch(func: str, epilogue: tuple, block, gids_padded,
                    num_groups: int, params, qv, is_counter: bool,
                    is_delta: bool, name: str, mesh=None):
    """Shared kernel-variant selection (_grid_variant ladder: mxu > jitter >
    masked > pallas > general) + instrumentation for every fused scalar
    entry point (one dispatch, one latency observation, one JIT hit/miss
    account). With ``mesh`` (a 1-D device mesh matching the block's
    series-sharded placement) the same program shape dispatches ONCE across
    every device via shard_map — every variant has a sharded twin except
    pallas (irregular mesh grids run the sharded general kernel)."""
    import time as _time

    from ..metrics import record_fused_fallback, record_kernel_dispatch
    from .kernels import pad_steps

    j_pad = pad_steps(params.num_steps)
    raw = block.raw if block.raw is not None else block.vals
    n_real = np.int32(block.n_series)
    start_off = int(params.start_ms - block.base_ms)
    variant, reason = _grid_variant(block, func, is_delta)
    # window structures build (memoized per block) BEFORE the timed span,
    # for every variant alike — the dispatch-latency observation must
    # compare kernel cost across grid classes, not host-side build
    # placement
    wm = None
    if variant == "mxu":
        from .mxu_kernels import window_matrices

        # window_matrices reads block.placement: a sharded block's set is
        # committed mesh-replicated at build, so no per-dispatch broadcast
        wm = window_matrices(
            block, start_off, params.step_ms, j_pad, params.window_ms
        )
    elif variant == "jitter":
        from .mxu_jitter import jitter_window_matrices

        wm = jitter_window_matrices(
            block, start_off, params.step_ms, j_pad, params.window_ms
        )
        if not wm.ok:  # window not wider than the deviation band
            variant, reason = "general", "grid_jitter"
    elif variant == "masked":
        from .mxu_jitter import masked_window_matrices

        wm = masked_window_matrices(
            block, start_off, params.step_ms, j_pad, params.window_ms
        )
        if not wm.ok:
            variant, reason = "general", "grid_holes"
    if (variant in ("jitter", "masked")
            and func in ("min_over_time", "max_over_time")):
        # min/max tile/edge structures build lazily on the memoized window
        # structure (only these two functions read them) — still host-side
        # build work, so it stays outside the timed span
        wm.ensure_minmax()
    if variant == "general" and reason is None and _pallas_variant(
        block, func, mesh
    ):
        variant = "pallas"
    if reason is not None:
        # degraded-kernel taxonomy: the dispatch STAYS one fused program
        # (the general kernel), it just lost the jitter-tolerant fast
        # variant — reserved for truly unsupported shapes (doc/perf.md)
        record_fused_fallback(reason)
    t0 = _time.perf_counter()
    if mesh is not None:
        name = "mesh_" + name
    if variant == "mxu":
        from .mxu_kernels import fetch_strategy

        if mesh is not None:
            fn = _fused_sharded_mxu_jit
            args = (
                mesh, func, epilogue, block.vals, raw, block.baseline,
                wm.dW, wm.dF, wm.dL, wm.dL2, wm.d_count, wm.d_tf, wm.d_tl,
                wm.d_tl2, wm.d_out_t, np.float32(params.window_ms), wm.d_idx,
                gids_padded, n_real, qv, num_groups, is_counter, is_delta,
                fetch_strategy(),
            )
        else:
            fn = _fused_mxu_jit
            args = (
                func, epilogue, block.vals, raw, block.baseline,
                wm.dW, wm.dF, wm.dL, wm.dL2, wm.d_count, wm.d_tf, wm.d_tl,
                wm.d_tl2, wm.d_out_t, np.float32(params.window_ms), wm.d_idx,
                gids_padded, n_real, qv, num_groups, is_counter, is_delta,
                fetch_strategy(),
            )
    elif variant == "jitter":
        from .mxu_kernels import fetch_strategy

        if func in ("min_over_time", "max_over_time"):
            common = (
                func, epilogue, block.vals, block.ts_dev, _jmm_args(wm),
                gids_padded, n_real, qv, num_groups,
                int(np.asarray(block.lens)[0]), fetch_strategy(),
            )
            if mesh is not None:
                fn, args = _fused_sharded_jitter_minmax_jit, (mesh,) + common
            else:
                fn, args = _fused_jitter_minmax_jit, common
        else:
            common = (
                func, epilogue, block.vals, block.ts_dev, raw, _jwm_args(wm),
                np.float32(params.window_ms), gids_padded, n_real, qv,
                num_groups, is_counter, is_delta, fetch_strategy(),
            )
            if mesh is not None:
                fn, args = _fused_sharded_jitter_jit, (mesh,) + common
            else:
                fn, args = _fused_jitter_jit, common
    elif variant == "masked":
        from .mxu_kernels import fetch_strategy

        if func in ("min_over_time", "max_over_time"):
            g = block.mgrid
            common = (
                func, epilogue, g.vals, g.dev, g.valid, g.cc, _mmm_args(wm),
                gids_padded, n_real, qv, num_groups, fetch_strategy(),
            )
            if mesh is not None:
                fn, args = _fused_sharded_masked_minmax_jit, (mesh,) + common
            else:
                fn, args = _fused_masked_minmax_jit, common
        else:
            common = (
                func, epilogue, _mgrid_args(block.mgrid), _mwm_args(wm),
                np.float32(params.window_ms),
                np.float32(block.mgrid.maxdev_ms), gids_padded, n_real, qv,
                num_groups, is_counter, is_delta, fetch_strategy(),
            )
            if mesh is not None:
                fn, args = _fused_sharded_masked_jit, (mesh,) + common
            else:
                fn, args = _fused_masked_jit, common
    elif variant == "pallas":
        fn = _fused_pallas_jit
        args = (
            func, epilogue, block.ts, block.vals, raw, block.lens,
            gids_padded, n_real, qv, np.int32(start_off),
            np.int32(params.step_ms), np.int32(params.window_ms), j_pad,
            num_groups, is_counter, is_delta,
            jax.devices()[0].platform in ("cpu",),
        )
    elif mesh is not None:
        fn = _fused_sharded_general_jit
        args = (
            mesh, func, epilogue, block.ts, block.vals, block.lens,
            block.baseline, raw, gids_padded, n_real, qv,
            np.int32(start_off), np.int32(params.step_ms),
            np.int32(params.window_ms), j_pad, num_groups, is_counter,
            is_delta,
        )
    else:
        fn = _fused_general_jit
        args = (
            func, epilogue, block.ts, block.vals, block.lens, block.baseline,
            raw, gids_padded, n_real, qv, np.int32(start_off),
            np.int32(params.step_ms), np.int32(params.window_ms), j_pad,
            num_groups, is_counter, is_delta,
        )
    before = fn._cache_size()
    out = fn(*args)
    record_kernel_dispatch(
        name, _time.perf_counter() - t0, compiled=fn._cache_size() > before,
        key=_exec_key_parts(variant, epilogue, block, j_pad, num_groups,
                            mesh),
        result=out,
    )
    return out


def fused_range_aggregate(func: str, op: str, block, gids_padded,
                          num_groups: int, params, is_counter: bool = False,
                          is_delta: bool = False, mesh=None):
    """One device dispatch for ``op by (...) (func(selector[w]))`` over a
    staged (super)block: returns the [G, J_pad] group partials on device.

    ``gids_padded`` is [S_padded] int32 with padded rows assigned the trash
    group ``num_groups``. Regular shared grids ride the MXU window-matrix
    kernel (matrices cached device-resident on the block); everything else
    runs the general compare-and-reduce kernel. With ``mesh`` (the block's
    series-sharded placement) the body runs under shard_map with a
    psum-combined [G, J] — ONE dispatch across the whole mesh. Instrumented
    like every other kernel entry (per-dispatch latency + JIT hit/miss)."""
    return _fused_dispatch(
        func, ("agg", op), block, gids_padded, num_groups, params,
        np.float32(0.0), is_counter, is_delta, name=f"fused_{op}_{func}",
        mesh=mesh,
    )


def zero_gids(block):
    """All-zeros trash-group vector for epilogues that need no label
    grouping (global topk/bottomk): unused by the epilogue math but part of
    the shared jit signature. Memoized device-resident per block (co-placed
    with a sharded block's series axis); also handed to the cross-query
    batcher so identical-lane dedup keys on ONE object per block."""
    from ..singleflight import memo_on
    from .staging import series_put

    s_pad = np.asarray(block.lens).shape[0]
    return memo_on(
        block, "_zero_gids", s_pad,
        lambda: series_put(getattr(block, "placement", None))(
            np.zeros(s_pad, dtype=np.int32)
        ),
    )


def fused_topk(func: str, block, k: int, bottom: bool, params,
               is_counter: bool = False, is_delta: bool = False, mesh=None):
    """One device dispatch for global ``topk(k, func(selector[w]))``:
    returns ([k, J_pad] values, [k, J_pad] i32 series indices) on device —
    the compact per-step winner set, O(k*J) on the wire instead of the
    [S, J] grid AggregatePresentExec gathers. Needs no label grouping at
    all (global top-k), so the O(S) group pass is skipped too. With
    ``mesh`` the per-device winner state combines across devices inside
    the same program (all_gather of [k, J] candidates + re-reduce)."""
    gids = zero_gids(block)
    return _fused_dispatch(
        func, ("topk", int(k), bool(bottom)), block, gids, 1, params,
        np.float32(0.0), is_counter, is_delta,
        name=f"fused_{'bottomk' if bottom else 'topk'}_{func}", mesh=mesh,
    )


def fused_quantile(func: str, block, gids_padded, num_groups: int, q: float,
                   params, is_counter: bool = False, is_delta: bool = False,
                   mesh=None):
    """One device dispatch for ``quantile(q, func(selector[w])) by (...)``:
    range kernel -> segment_quantile inside one compiled program; only the
    [G, J_pad] quantile grid reaches the host. ``q`` rides as a dynamic
    argument so dashboards sweeping quantiles share one executable. With
    ``mesh`` the exact per-group multiset is all_gather'd across devices
    inside the same program before the sort (see _sharded_epilogue)."""
    return _fused_dispatch(
        func, ("quantile",), block, gids_padded, num_groups, params,
        np.float32(q), is_counter, is_delta, name=f"fused_quantile_{func}",
        mesh=mesh,
    )


def _hist_jwm_args(wm) -> tuple:
    """Jitter window structure in hist_kernels._hist_range_jitter's order:
    shared certain-range boundaries + the uncertain-slot selections."""
    return (wm.d_clo, wm.d_chi, wm.d_idx, wm.d_count0, wm.d_c0pos,
            wm.d_has_klo, wm.d_has_khi, wm.d_F0_rel, wm.d_L0_rel,
            wm.d_Klo_rel, wm.d_Khi_rel, wm.d_blo_rel, wm.d_ehi_rel)


def _hist_shared_windows(block, params, j_pad: int, mesh):
    """Host-precomputed [J] searchsorted window-boundary vectors for a
    shared-regular-grid histogram (super)block, memoized device-resident on
    the block (the O(S*J*T) per-series boundary compare never runs for
    scraped histograms). ONE definition shared by the single-query fused
    hist path and the cross-query batched dispatch — both must index the
    block identically or batched-vs-sequential parity breaks."""
    from ..singleflight import memo_on
    from .staging import replicated_put

    start_off = int(params.start_ms - block.base_ms)
    key = (start_off, int(params.step_ms), j_pad, int(params.window_ms),
           mesh is not None)

    def build_windows():
        m = int(np.asarray(block.lens)[0])
        tsv = np.asarray(block.regular_ts)[:m].astype(np.int64)
        out_t = start_off + np.arange(j_pad, dtype=np.int64) * int(
            params.step_ms
        )
        hi = np.searchsorted(tsv, out_t, side="right").astype(np.int32)
        lo = np.searchsorted(
            tsv, out_t - int(params.window_ms), side="right"
        ).astype(np.int32)
        t_first = tsv[np.minimum(lo, m - 1)].astype(np.int32)
        t_last = tsv[np.minimum(hi - 1, m - 1)].astype(np.int32)
        put = replicated_put(mesh)
        return (put(lo), put(hi), put(t_first), put(t_last),
                put(out_t.astype(np.int32)))

    return memo_on(block, "_hist_win_cache", key, build_windows)


def fused_hist_range_aggregate(func: str, block, gids_padded,
                               num_groups: int, params, les,
                               q: float | None = None,
                               is_delta: bool = False, mesh=None):
    """One device dispatch for ``sum by (...) (hist_fn(selector[w]))`` over
    a 3-D histogram (super)block — optionally with the device-side
    ``histogram_quantile`` interpolation epilogue fused into the same
    program (q != None). Returns [G, J_pad, B] group bucket partials, or
    [G, J_pad] quantiles. ``les`` is the (unified) [B] bound vector.

    Shared regular grids (the overwhelmingly common scraped-histogram case)
    use the shared-window variant: [J] boundary vectors precomputed
    host-side and memoized device-resident on the block, skipping the
    O(S*J*T) per-series boundary compare entirely.

    With ``mesh`` (the block's [ΣS, T, B] series-sharded placement) the
    hist range_fn -> per-bucket segment-sum -> psum -> (quantile) body
    runs under shard_map — one dispatch across the mesh, with the quantile
    interpolation evaluated on the replicated [G, J, B] partials inside
    the same program."""
    import time as _time

    from ..metrics import record_fused_fallback, record_kernel_dispatch
    from .hist_kernels import (
        _fused_hist_jit,
        _fused_hist_jitter_jit,
        _fused_hist_jitter_sharded_jit,
        _fused_hist_sharded_jit,
        _fused_hist_shared_jit,
        _fused_hist_shared_sharded_jit,
    )
    from .kernels import pad_steps

    j_pad = pad_steps(params.num_steps)
    qv = np.float32(q if q is not None else 0.0)
    start_off = int(params.start_ms - block.base_ms)
    name = f"fused_hist_{'quantile_' if q is not None else ''}sum_{func}"
    if mesh is not None:
        name = "mesh_" + name
    # near-regular (jittered scrape) grids ride the shared-boundary jitter
    # variant; a grid failing the window safety bound degrades to the
    # general per-series kernel (still one dispatch), counted grid_jitter
    jwm = None
    if block.regular_ts is None and block.nominal_ts is not None:
        from .mxu_jitter import jitter_window_matrices

        jwm = jitter_window_matrices(
            block, start_off, params.step_ms, j_pad, params.window_ms
        )
        if not jwm.ok:
            jwm = None
            record_fused_fallback("grid_jitter")
    # window-boundary structures build (memoized) before the timed span,
    # like every other fused variant
    shared_win = (
        _hist_shared_windows(block, params, j_pad, mesh)
        if block.regular_ts is not None else None
    )
    t0 = _time.perf_counter()
    if block.regular_ts is not None:
        lo, hi, t_first, t_last, out_t = shared_win
        if mesh is not None:
            before = _fused_hist_shared_sharded_jit._cache_size()
            out = _fused_hist_shared_sharded_jit(
                mesh, func, block.vals, lo, hi, t_first, t_last, out_t,
                np.int32(params.window_ms), gids_padded, les, qv,
                num_groups, is_delta, q is not None,
            )
            compiled = _fused_hist_shared_sharded_jit._cache_size() > before
        else:
            before = _fused_hist_shared_jit._cache_size()
            out = _fused_hist_shared_jit(
                func, block.vals, lo, hi, t_first, t_last, out_t,
                np.int32(params.window_ms), gids_padded, les, qv,
                num_groups, is_delta, q is not None,
            )
            compiled = _fused_hist_shared_jit._cache_size() > before
    elif jwm is not None:
        hwa = _hist_jwm_args(jwm)
        if mesh is not None:
            before = _fused_hist_jitter_sharded_jit._cache_size()
            out = _fused_hist_jitter_sharded_jit(
                mesh, func, block.vals, block.ts_dev, hwa,
                np.int32(params.window_ms), gids_padded, les, qv,
                num_groups, is_delta, q is not None,
            )
            compiled = _fused_hist_jitter_sharded_jit._cache_size() > before
        else:
            before = _fused_hist_jitter_jit._cache_size()
            out = _fused_hist_jitter_jit(
                func, block.vals, block.ts_dev, hwa,
                np.int32(params.window_ms), gids_padded, les, qv,
                num_groups, is_delta, q is not None,
            )
            compiled = _fused_hist_jitter_jit._cache_size() > before
    elif mesh is not None:
        before = _fused_hist_sharded_jit._cache_size()
        out = _fused_hist_sharded_jit(
            mesh, func, block.ts, block.vals, block.lens, gids_padded, les,
            qv, np.int32(start_off), np.int32(params.step_ms),
            np.int32(params.window_ms), j_pad, num_groups, is_delta,
            q is not None,
        )
        compiled = _fused_hist_sharded_jit._cache_size() > before
    else:
        before = _fused_hist_jit._cache_size()
        out = _fused_hist_jit(
            func, block.ts, block.vals, block.lens, gids_padded, les, qv,
            np.int32(start_off), np.int32(params.step_ms),
            np.int32(params.window_ms), j_pad, num_groups, is_delta,
            q is not None,
        )
        compiled = _fused_hist_jit._cache_size() > before
    hist_variant = ("hist_shared" if block.regular_ts is not None
                    else "hist_jitter" if jwm is not None else "hist_general")
    record_kernel_dispatch(
        name, _time.perf_counter() - t0, compiled=compiled,
        key=_exec_key_parts(
            hist_variant, ("hist", "quantile" if q is not None else "sum"),
            block, j_pad, num_groups, mesh,
        ),
        result=out,
    )
    return out


# ---------------------------------------------------------------------------
# cross-query batched dispatch (query/scheduler.py): ONE kernel launch for Q
# concurrent fused queries sharing a (super)block + grid/epilogue signature
# ---------------------------------------------------------------------------
#
# The batched programs run the SAME per-query computation the single-query
# jits run, restructured for cross-query sharing (the Storyboard move —
# PAPERS.md): the expensive range kernel evaluates ONCE per UNIQUE
# (start, step, window) among the lanes — sj_u [U, S, J] — and each lane's
# epilogue (its own group-by vector, its own q) gathers its grid by index.
# Q dashboard panels differing only in group-by pay ONE range computation;
# panels differing in window pay one each, inside one launch. Per-lane math
# is identical to the single-query program, so lane i of the batched output
# is bit-equal to the unbatched dispatch of query i (asserted in
# tests/test_scheduler.py).
#
# ``num_groups`` is the MAX across lanes: a lane with G_i < G_max routes its
# padded rows to its own trash group G_i, whose output row the caller
# discards by slicing [:G_i] — segment reduces are independent per segment,
# so the extra empty segments change nothing.
#
# Lane and unique-window counts pad to powers of two (repeating lane/window
# 0) so fluctuating live group sizes reuse a handful of executables instead
# of recompiling per width; the stacked device inputs are memoized on the
# block per (sorted) batch composition, so a recurring dashboard round pays
# ZERO host->device copies after its first occurrence.


def _pow2(n: int, lo: int = 1) -> int:
    q = max(lo, 1)
    while q < n:
        q *= 2
    return q


def _pad_lanes(lanes) -> list:
    """Pad the lane list to the next power of two (min 2) by repeating
    lane 0; callers index only their real lane, so pad outputs are simply
    never read."""
    lanes = list(lanes)
    lanes.extend(lanes[0] for _ in range(_pow2(len(lanes), 2) - len(lanes)))
    return lanes


def _unique_windows(lanes, base_ms: int):
    """(u_idx per lane, pow2-padded unique (start_off, step, window) list)."""
    uniq: dict[tuple, int] = {}
    u_idx = []
    for l in lanes:
        k = (int(l[2].start_ms - base_ms), int(l[2].step_ms),
             int(l[2].window_ms))
        u_idx.append(uniq.setdefault(k, len(uniq)))
    ukeys = list(uniq)
    ukeys.extend(ukeys[0] for _ in range(_pow2(len(ukeys)) - len(ukeys)))
    return u_idx, ukeys


# The batched programs UNROLL over lanes (static lane count + static
# lane->unique-window map) instead of vmapping: each lane's subgraph is the
# EXACT single-query computation — bit-equality is structural, not a
# property of vmap batching rules — while XLA CSEs the work lanes share
# (the unique-window range grids, and the NaN-validity masks lanes with the
# same grid recompute). vmap was measured 3-10x slower here: its
# segment-reduce batching rules materialize per-lane [S, J] operand copies.


@functools.partial(jax.jit, static_argnames=(
    "func", "epilogue", "u_map", "num_steps", "num_groups", "is_counter",
    "is_delta"
))
def _batched_general_jit(func, epilogue, ts, vals, lens, baseline, raw,
                         gids_q, n_real, qv_q, so_u, sm_u, w_u,
                         u_map: tuple, num_steps: int, num_groups: int,
                         is_counter: bool, is_delta: bool):
    from .kernels import range_kernel

    sj_u = [
        range_kernel(
            func, ts, vals, lens, baseline, raw, so_u[u], sm_u[u], w_u[u],
            num_steps, is_counter=is_counter, is_delta=is_delta,
        )
        for u in range(max(u_map) + 1)
    ]
    outs = [
        _apply_epilogue(sj_u[u_map[i]], epilogue, gids_q[i], n_real,
                        qv_q[i], num_groups)
        for i in range(len(u_map))
    ]
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *outs)


@functools.partial(jax.jit, static_argnames=(
    "func", "epilogue", "u_map", "num_groups", "is_counter", "is_delta",
    "fetch"
))
def _batched_mxu_jit(func, epilogue, vals, raw, baseline, W_u, F_u, L_u,
                     L2_u, count_u, tf_u, tl_u, tl2_u, out_t_u, window_ms_u,
                     idx_u, gids_q, n_real, qv_q, u_map: tuple,
                     num_groups: int, is_counter: bool, is_delta: bool,
                     fetch: str):
    from .mxu_kernels import mxu_range_kernel

    sj_u = [
        mxu_range_kernel(
            func, vals, raw, baseline, W_u[u], F_u[u], L_u[u], L2_u[u],
            count_u[u], tf_u[u], tl_u[u], tl2_u[u], out_t_u[u],
            window_ms_u[u], idx=idx_u[u], is_counter=is_counter,
            is_delta=is_delta, fetch=fetch,
        )
        for u in range(max(u_map) + 1)
    ]
    outs = [
        _apply_epilogue(sj_u[u_map[i]], epilogue, gids_q[i], n_real,
                        qv_q[i], num_groups)
        for i in range(len(u_map))
    ]
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *outs)


@functools.partial(jax.jit, static_argnames=(
    "func", "epilogue", "u_map", "num_groups", "is_counter", "is_delta",
    "fetch"
))
def _batched_jitter_jit(func, epilogue, vals, dev, raw, wm_u, window_ms_u,
                        gids_q, n_real, qv_q, u_map: tuple,
                        num_groups: int, is_counter: bool, is_delta: bool,
                        fetch: str):
    """Batched twin of _fused_jitter_jit: the jitter kernel evaluates once
    per UNIQUE window from the stacked window-structure tuple ``wm_u``
    (each field [U, ...]; sliced per unrolled window), per-lane epilogues
    as in _batched_general_jit — lane math identical to the single-query
    jitter program, so batched lanes stay bit-equal to unbatched."""
    from .mxu_jitter import jitter_range_kernel

    sj_u = [
        jitter_range_kernel(
            func, vals, dev, raw, *(a[u] for a in wm_u), window_ms_u[u],
            is_counter=is_counter, is_delta=is_delta, fetch=fetch,
        )
        for u in range(max(u_map) + 1)
    ]
    outs = [
        _apply_epilogue(sj_u[u_map[i]], epilogue, gids_q[i], n_real,
                        qv_q[i], num_groups)
        for i in range(len(u_map))
    ]
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *outs)


@functools.partial(jax.jit, static_argnames=(
    "func", "epilogue", "u_map", "num_groups", "is_counter", "is_delta",
    "fetch"
))
def _batched_masked_jit(func, epilogue, mba, wm_u, window_ms_u, maxdev,
                        gids_q, n_real, qv_q, u_map: tuple, num_groups: int,
                        is_counter: bool, is_delta: bool, fetch: str):
    """Batched twin of _fused_masked_jit (masked sidecar shared across
    windows, masked window structures stacked per unique window)."""
    from .mxu_jitter import jitter_masked_kernel

    sj_u = [
        jitter_masked_kernel(
            func, *mba, *(a[u] for a in wm_u), window_ms_u[u],
            is_counter=is_counter, is_delta=is_delta, fetch=fetch,
            maxdev=maxdev,
        )
        for u in range(max(u_map) + 1)
    ]
    outs = [
        _apply_epilogue(sj_u[u_map[i]], epilogue, gids_q[i], n_real,
                        qv_q[i], num_groups)
        for i in range(len(u_map))
    ]
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *outs)


@functools.partial(jax.jit, static_argnames=(
    "mesh", "func", "epilogue", "u_map", "num_steps", "num_groups",
    "is_counter", "is_delta"
))
def _batched_sharded_general_jit(mesh, func, epilogue, ts, vals, lens,
                                 baseline, raw, gids_q, n_real, qv_q,
                                 so_u, sm_u, w_u, u_map: tuple,
                                 num_steps: int, num_groups: int,
                                 is_counter: bool, is_delta: bool):
    """Series-sharded twin of _batched_general_jit: the unique-window range
    grids and the unrolled per-lane epilogues run INSIDE the shard_map
    body, so one multi-device program serves every lane."""
    from jax.sharding import PartitionSpec as P

    from ..jax_compat import shard_map
    from .kernels import range_kernel

    axis = mesh.axis_names[0]

    def local(ts_l, vals_l, lens_l, base_l, raw_l, gids_ql):
        sj_u = [
            range_kernel(
                func, ts_l, vals_l, lens_l, base_l, raw_l, so_u[u],
                sm_u[u], w_u[u], num_steps, is_counter=is_counter,
                is_delta=is_delta,
            )
            for u in range(max(u_map) + 1)
        ]
        outs = [
            _sharded_epilogue(sj_u[u_map[i]], epilogue, gids_ql[i], n_real,
                              qv_q[i], num_groups, axis)
            for i in range(len(u_map))
        ]
        return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *outs)

    row, vec = P(axis, None), P(axis)
    return shard_map(
        local, mesh=mesh,
        in_specs=(row, row, vec, vec, row, P(None, axis)),
        out_specs=_sharded_out_specs(epilogue),
        check=False,
    )(ts, vals, lens, baseline, raw, gids_q)


@functools.partial(jax.jit, static_argnames=(
    "mesh", "func", "epilogue", "u_map", "num_groups", "is_counter",
    "is_delta", "fetch"
))
def _batched_sharded_mxu_jit(mesh, func, epilogue, vals, raw, baseline, W_u,
                             F_u, L_u, L2_u, count_u, tf_u, tl_u, tl2_u,
                             out_t_u, window_ms_u, idx_u, gids_q, n_real,
                             qv_q, u_map: tuple, num_groups: int,
                             is_counter: bool, is_delta: bool, fetch: str):
    from jax.sharding import PartitionSpec as P

    from ..jax_compat import shard_map
    from .mxu_kernels import mxu_range_kernel

    axis = mesh.axis_names[0]

    def local(vals_l, raw_l, base_l, gids_ql):
        sj_u = [
            mxu_range_kernel(
                func, vals_l, raw_l, base_l, W_u[u], F_u[u], L_u[u],
                L2_u[u], count_u[u], tf_u[u], tl_u[u], tl2_u[u],
                out_t_u[u], window_ms_u[u], idx=idx_u[u],
                is_counter=is_counter, is_delta=is_delta, fetch=fetch,
            )
            for u in range(max(u_map) + 1)
        ]
        outs = [
            _sharded_epilogue(sj_u[u_map[i]], epilogue, gids_ql[i], n_real,
                              qv_q[i], num_groups, axis)
            for i in range(len(u_map))
        ]
        return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *outs)

    row, vec = P(axis, None), P(axis)
    return shard_map(
        local, mesh=mesh,
        in_specs=(row, row, vec, P(None, axis)),
        out_specs=_sharded_out_specs(epilogue),
        check=False,
    )(vals, raw, baseline, gids_q)


@functools.partial(jax.jit, static_argnames=(
    "mesh", "func", "epilogue", "u_map", "num_groups", "is_counter",
    "is_delta", "fetch"
))
def _batched_sharded_jitter_jit(mesh, func, epilogue, vals, dev, raw, wm_u,
                                window_ms_u, gids_q, n_real, qv_q,
                                u_map: tuple, num_groups: int,
                                is_counter: bool, is_delta: bool,
                                fetch: str):
    """Series-sharded twin of _batched_jitter_jit: the replicated stacked
    window structures ride the closure and the unrolled per-lane epilogues
    combine over the mesh inside ONE multi-device program — mesh + jitter
    lanes coalesce instead of dropping to per-lane dispatch."""
    from jax.sharding import PartitionSpec as P

    from ..jax_compat import shard_map
    from .mxu_jitter import jitter_range_kernel

    axis = mesh.axis_names[0]

    def local(vals_l, dev_l, raw_l, gids_ql):
        sj_u = [
            jitter_range_kernel(
                func, vals_l, dev_l, raw_l, *(a[u] for a in wm_u),
                window_ms_u[u], is_counter=is_counter, is_delta=is_delta,
                fetch=fetch,
            )
            for u in range(max(u_map) + 1)
        ]
        outs = [
            _sharded_epilogue(sj_u[u_map[i]], epilogue, gids_ql[i], n_real,
                              qv_q[i], num_groups, axis)
            for i in range(len(u_map))
        ]
        return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *outs)

    row = P(axis, None)
    return shard_map(
        local, mesh=mesh,
        in_specs=(row, row, row, P(None, axis)),
        out_specs=_sharded_out_specs(epilogue),
        check=False,
    )(vals, dev, raw, gids_q)


@functools.partial(jax.jit, static_argnames=(
    "mesh", "func", "epilogue", "u_map", "num_groups", "is_counter",
    "is_delta", "fetch"
))
def _batched_sharded_masked_jit(mesh, func, epilogue, mba, wm_u,
                                window_ms_u, maxdev, gids_q, n_real, qv_q,
                                u_map: tuple, num_groups: int,
                                is_counter: bool, is_delta: bool,
                                fetch: str):
    """Series-sharded twin of _batched_masked_jit (row-band sidecar
    arrays, replicated stacked masked window structures in the closure)."""
    from jax.sharding import PartitionSpec as P

    from ..jax_compat import shard_map
    from .mxu_jitter import jitter_masked_kernel

    axis = mesh.axis_names[0]

    def local(mba_l, gids_ql):
        sj_u = [
            jitter_masked_kernel(
                func, *mba_l, *(a[u] for a in wm_u), window_ms_u[u],
                is_counter=is_counter, is_delta=is_delta, fetch=fetch,
                maxdev=maxdev,
            )
            for u in range(max(u_map) + 1)
        ]
        outs = [
            _sharded_epilogue(sj_u[u_map[i]], epilogue, gids_ql[i], n_real,
                              qv_q[i], num_groups, axis)
            for i in range(len(u_map))
        ]
        return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *outs)

    row = P(axis, None)
    return shard_map(
        local, mesh=mesh,
        in_specs=(tuple(row for _ in mba), P(None, axis)),
        out_specs=_sharded_out_specs(epilogue),
        check=False,
    )(mba, gids_q)


_BATCH_STACK_MEMO_MAX = 64


def _batched_stacks(block, lanes, j_pad: int, variant: str, hist: bool,
                    mesh):
    """Device-resident stacked batch inputs, memoized on the block per
    (sorted) batch composition: group-id stack [Q_pad, S], lane->unique
    window index vector, and the unique windows' parameter vectors (or MXU
    window-matrix / jitter-structure / hist boundary stacks). A recurring
    dashboard round — the steady state the batcher exists for — pays ZERO
    host->device copies after its first occurrence. qv is NOT part of the
    memo (built per call): quantile sweeps must reuse the same stacks.

    The memo key embeds the kernel ``variant`` (mxu|jitter|masked|general —
    the grid metadata half of the cache identity: a jittered block's
    stacks can never serve a regular-grid program shape or vice versa) and
    id(gids_dev) per lane; those arrays are themselves memoized on the
    block (group_ids_memo / zero_gids), so ids are stable for the block's
    lifetime and the key can never alias across variants."""
    from ..singleflight import memo_on

    sig = tuple(
        (int(l[2].start_ms - block.base_ms), int(l[2].step_ms),
         int(l[2].window_ms), id(l[0]))
        for l in lanes
    )
    key = (variant, hist, j_pad, mesh is not None, sig)
    cache = block.__dict__.get("_batch_stacks")
    if cache is not None and len(cache) > _BATCH_STACK_MEMO_MAX:
        cache.clear()  # bounded: stacks rebuild in one call

    def build():
        padded = _pad_lanes(lanes)
        _u_idx, ukeys = _unique_windows(padded, block.base_ms)
        st = {
            "gids_q": jnp.stack([l[0] for l in padded]),
        }
        if hist and block.regular_ts is not None:
            from .kernels import RangeParams

            wins = [
                _hist_shared_windows(
                    block,
                    RangeParams(so + block.base_ms, sm, j_pad, w),
                    j_pad, mesh,
                )
                for so, sm, w in ukeys
            ]
            st.update(
                lo_u=jnp.stack([w[0] for w in wins]),
                hi_u=jnp.stack([w[1] for w in wins]),
                tf_u=jnp.stack([w[2] for w in wins]),
                tl_u=jnp.stack([w[3] for w in wins]),
                out_t_u=jnp.stack([w[4] for w in wins]),
                w_u=jnp.asarray(np.asarray(
                    [w for _, _, w in ukeys], np.int32)),
            )
        elif variant == "mxu":
            from .mxu_kernels import window_matrices

            wms = [
                window_matrices(block, so, sm, j_pad, w)
                for so, sm, w in ukeys
            ]

            def stk(attr):
                return jnp.stack([getattr(w, attr) for w in wms])

            st.update(
                W_u=stk("dW"), F_u=stk("dF"), L_u=stk("dL"),
                L2_u=stk("dL2"), count_u=stk("d_count"), tf_u=stk("d_tf"),
                tl_u=stk("d_tl"), tl2_u=stk("d_tl2"),
                out_t_u=stk("d_out_t"),
                window_ms_u=jnp.asarray(np.asarray(
                    [w for _, _, w in ukeys], np.float32)),
                idx_u=stk("d_idx"),
            )
        elif variant in ("jitter", "masked"):
            from .mxu_jitter import (
                jitter_window_matrices,
                masked_window_matrices,
            )

            build_wm = (jitter_window_matrices if variant == "jitter"
                        else masked_window_matrices)
            wms = [
                build_wm(block, so, sm, j_pad, w) for so, sm, w in ukeys
            ]
            if not all(w.ok for w in wms):
                # a merged window not wider than the deviation band: the
                # per-lane dispatch degrades to the general kernel, which
                # the batched program shape here does not model — raise so
                # the scheduler falls back to per-lane unbatched execution
                raise RuntimeError(
                    f"{variant} window bound fails for a batched window"
                )
            take = _jwm_args if variant == "jitter" else _mwm_args
            st.update(
                wm_u=tuple(
                    jnp.stack([take(w)[k] for w in wms])
                    for k in range(len(take(wms[0])))
                ),
                window_ms_u=jnp.asarray(np.asarray(
                    [w for _, _, w in ukeys], np.float32)),
            )
        else:
            st.update(
                so_u=jnp.asarray(np.asarray(
                    [s for s, _, _ in ukeys], np.int32)),
                sm_u=jnp.asarray(np.asarray(
                    [s for _, s, _ in ukeys], np.int32)),
                w_u=jnp.asarray(np.asarray(
                    [w for _, _, w in ukeys], np.int32)),
            )
        return st

    return memo_on(block, "_batch_stacks", key, build)


def fused_batched_scalar(func: str, epilogue: tuple, block, lanes,
                         num_groups: int, j_pad: int, is_counter: bool,
                         is_delta: bool, mesh=None):
    """ONE device dispatch serving Q concurrent scalar fused queries over
    the SAME (super)block. ``lanes`` is a sequence of
    ``(gids_padded_dev, qv, params)`` triples — the per-query dynamics;
    everything else (func, epilogue statics, kernel variant, j_pad) is
    uniform across the group by construction of the coalescing key
    (query/scheduler.py). Returns the stacked [Q_pad, ...] outputs; callers
    take lane i's ``[:G_i]`` rows (or its [k, J] winner pair). Kernel
    variant selection matches _fused_dispatch exactly (_grid_variant) so a
    batched lane computes through the same kernel variant as its unbatched
    execution would. Combinations the batched program set does not model —
    min/max_over_time on jitter/masked grids (dedicated fused minmax
    programs), pallas-promoted irregular grids, a merged window failing
    the jitter safety bound — RAISE, which the scheduler turns into
    per-lane unbatched execution (batching is an optimization, never a
    correctness risk)."""
    import time as _time

    from ..metrics import record_kernel_dispatch

    raw = block.raw if block.raw is not None else block.vals
    n_real = np.int32(block.n_series)
    variant, _reason = _grid_variant(block, func, is_delta)
    if not batch_variant_supported(block, func, epilogue[0], is_delta, mesh):
        # defensive backstop — the scheduler consults the same predicate
        # before grouping, so this fires only for requests that bypassed it
        raise RuntimeError(
            f"batched programs do not model the {variant} variant here: "
            "per-lane dispatch"
        )
    if _reason is not None:
        # batched lanes degrade to the general kernel exactly like their
        # unbatched executions would — keep the grid_* taxonomy counting
        # (once per launch) so batched deployments don't undercount it
        from ..metrics import record_fused_fallback

        record_fused_fallback(_reason)
    st = _batched_stacks(block, lanes, j_pad, variant, False, mesh)
    padded = _pad_lanes(lanes)
    u_idx, _ukeys = _unique_windows(padded, block.base_ms)
    u_map = tuple(u_idx)
    qv_q = jnp.asarray(np.asarray([l[1] for l in padded], np.float32))
    kind = epilogue[1] if epilogue[0] == "agg" else epilogue[0]
    name = f"batch_{'mesh_' if mesh is not None else ''}fused_{kind}_{func}"
    t0 = _time.perf_counter()
    if variant == "mxu":
        from .mxu_kernels import fetch_strategy

        args = (
            func, epilogue, block.vals, raw, block.baseline, st["W_u"],
            st["F_u"], st["L_u"], st["L2_u"], st["count_u"], st["tf_u"],
            st["tl_u"], st["tl2_u"], st["out_t_u"], st["window_ms_u"],
            st["idx_u"], st["gids_q"], n_real, qv_q, u_map,
            num_groups, is_counter, is_delta, fetch_strategy(),
        )
        fn = _batched_sharded_mxu_jit if mesh is not None else _batched_mxu_jit
    elif variant == "jitter":
        from .mxu_kernels import fetch_strategy

        args = (
            func, epilogue, block.vals, block.ts_dev, raw, st["wm_u"],
            st["window_ms_u"], st["gids_q"], n_real, qv_q, u_map,
            num_groups, is_counter, is_delta, fetch_strategy(),
        )
        fn = (_batched_sharded_jitter_jit if mesh is not None
              else _batched_jitter_jit)
    elif variant == "masked":
        from .mxu_kernels import fetch_strategy

        args = (
            func, epilogue, _mgrid_args(block.mgrid), st["wm_u"],
            st["window_ms_u"], np.float32(block.mgrid.maxdev_ms),
            st["gids_q"], n_real, qv_q, u_map,
            num_groups, is_counter, is_delta, fetch_strategy(),
        )
        fn = (_batched_sharded_masked_jit if mesh is not None
              else _batched_masked_jit)
    else:
        args = (
            func, epilogue, block.ts, block.vals, block.lens, block.baseline,
            raw, st["gids_q"], n_real, qv_q, st["so_u"],
            st["sm_u"], st["w_u"], u_map, j_pad, num_groups, is_counter,
            is_delta,
        )
        fn = (_batched_sharded_general_jit if mesh is not None
              else _batched_general_jit)
    if mesh is not None:
        args = (mesh,) + args
    before = fn._cache_size()
    out = fn(*args)
    record_kernel_dispatch(
        name, _time.perf_counter() - t0, compiled=fn._cache_size() > before,
        key=_exec_key_parts(
            variant, epilogue, block, j_pad, num_groups, mesh,
            batch=f"Q{len(padded)}xU{len(_ukeys)}",
        ),
        result=out,
    )
    return out


def fused_batched_hist(func: str, block, lanes, num_groups: int, j_pad: int,
                       les, quantile: bool, is_delta: bool, mesh=None):
    """Batched twin of fused_hist_range_aggregate: ONE dispatch returns the
    stacked [Q_pad, G, J, B] bucket partials (or [Q_pad, G, J] interpolated
    quantiles) for Q concurrent hist queries over one 3-D superblock.
    Shared regular grids evaluate the hist range grid once per unique
    window ([U, S, J, B]) with the per-lane [J] boundary vectors stacked
    from the _hist_shared_windows memo; per-lane q rides the dynamic qv
    axis so dashboards sweeping quantiles share one program AND one range
    grid."""
    import time as _time

    from ..metrics import record_kernel_dispatch
    from .hist_kernels import (
        _batched_hist_jit,
        _batched_hist_shared_jit,
        _batched_hist_shared_sharded_jit,
        _batched_hist_sharded_jit,
    )

    shared = block.regular_ts is not None
    if not batch_variant_supported(block, func, "hist", is_delta, mesh):
        # unbatched hist dispatch takes the jitter shared-boundary variant
        # on near-regular grids (fused_hist_range_aggregate); the batched
        # program set does not model it — defensive backstop behind the
        # scheduler's pre-grouping check (same predicate)
        raise RuntimeError("jittered hist grid: per-lane dispatch")
    st = _batched_stacks(block, lanes, j_pad, "general", True, mesh)
    padded = _pad_lanes(lanes)
    u_idx, _ukeys = _unique_windows(padded, block.base_ms)
    u_map = tuple(u_idx)
    qv_q = jnp.asarray(np.asarray([l[1] for l in padded], np.float32))
    name = (f"batch_{'mesh_' if mesh is not None else ''}fused_hist_"
            f"{'quantile_' if quantile else ''}sum_{func}")
    t0 = _time.perf_counter()
    if shared:
        args = (func, block.vals, st["lo_u"], st["hi_u"], st["tf_u"],
                st["tl_u"], st["out_t_u"], st["w_u"], st["gids_q"], les,
                qv_q, u_map, num_groups, is_delta, quantile)
        fn = (_batched_hist_shared_sharded_jit if mesh is not None
              else _batched_hist_shared_jit)
    else:
        args = (func, block.ts, block.vals, block.lens, st["gids_q"], les,
                qv_q, st["so_u"], st["sm_u"], st["w_u"], u_map,
                j_pad, num_groups, is_delta, quantile)
        fn = (_batched_hist_sharded_jit if mesh is not None
              else _batched_hist_jit)
    if mesh is not None:
        args = (mesh,) + args
    before = fn._cache_size()
    out = fn(*args)
    record_kernel_dispatch(
        name, _time.perf_counter() - t0, compiled=fn._cache_size() > before,
        key=_exec_key_parts(
            "hist_shared" if shared else "hist_general",
            ("hist", "quantile" if quantile else "sum"), block, j_pad,
            num_groups, mesh, batch=f"Q{len(padded)}xU{len(_ukeys)}",
        ),
        result=out,
    )
    return out


# ---------------------------------------------------------------------------
# standing-query delta maintenance (filodb_tpu/standing/): retained [G, J]
# partials + suffix-only re-dispatch + bitwise splice
# ---------------------------------------------------------------------------
#
# A standing query's [G, J] output grid decomposes PER STEP: every fused
# epilogue computes step j from the samples inside window j alone, so steps
# are independent panes (the delta-summation move, PAPERS.md, with pane ==
# output step and bitwise-exact combination). On a live-edge append the
# appended columns can only touch the step SUFFIX whose windows reach the
# append interval — the delta refresh re-dispatches ONLY those steps
# through the SAME fused program ladder (same superblock object, same
# kernel variant, same per-step math) and splices the retained prefix back
# in. Two facts make the splice bit-exact rather than merely close, both
# pinned by tests/test_standing.py across regular/jitter/holes grids:
#
# - a suffix-grid dispatch over the SAME staged superblock produces
#   bit-identical per-step values to the full-grid dispatch (each step's
#   window reduce runs over the identical [S, T] operand rows; the output
#   grid start/count only select which independent reduces run);
# - steps whose windows closed before an in-place extension are bit-stable
#   across it (appended columns land masked-out of closed windows, and
#   extension never rewrites resident columns — PR 6's consistency model).
#
# True sample-level partial combination (old_sum + appended_sum) was
# rejected: float addition does not re-associate, so combined open-window
# partials could never be bit-equal to a full re-evaluation — and bit
# parity with the normal query path is the property the whole fused engine
# asserts everywhere else (batched lanes, sharded twins).

# epilogues whose [G, J] output splices per step: exactly the ("agg", op)
# segment reduces. topk ([k, J] winner rows whose label reconstruction is
# per-refresh), quantile and fused histogram_quantile keep full re-dispatch
# (fallback taxonomy: standing_nondecomposable).
STANDING_DELTA_OPS = frozenset(SIMPLE_AGG_OPS)


def standing_delta_eligible(op: str, params=(),
                            hist_quantile=None) -> bool:
    """Whether a fused aggregate's epilogue supports standing delta
    maintenance (per-step retained-partial splicing). Ineligible shapes
    demote cleanly to full re-dispatch, counted
    ``filodb_fused_fallback_total{reason="standing_nondecomposable"}``."""
    return (op in STANDING_DELTA_OPS and not params
            and hist_quantile is None)


def shift_partials(retained: np.ndarray, shift: int,
                   num_steps: int) -> np.ndarray:
    """Slide retained [G, J] partials left by ``shift`` whole steps onto a
    ``num_steps``-wide grid (the dashboard window advancing): steps falling
    off the front drop, steps not yet computed arrive as NaN (absence) for
    the delta dispatch to fill."""
    G = retained.shape[0]
    out = np.full((G, num_steps), np.nan, dtype=retained.dtype)
    if shift < retained.shape[1]:
        keep = retained[:, shift:]
        n = min(keep.shape[1], num_steps)
        out[:, :n] = keep[:, :n]
    return out


def splice_partials(retained: np.ndarray, fresh: np.ndarray,
                    k0: int) -> np.ndarray:
    """Combine a delta dispatch's [G, J-k0] suffix partials into the
    retained [G, J] grid in place at step ``k0``. The ONE combination rule
    of the standing delta path — callers must have verified the group axis
    matches (same group_ids_memo labels); a mismatch means the block was
    restaged with a different row set and the refresh must reset instead."""
    if fresh.shape[0] != retained.shape[0]:
        raise ValueError(
            f"standing splice group mismatch: retained G={retained.shape[0]} "
            f"vs fresh G={fresh.shape[0]}"
        )
    n = retained.shape[1] - k0
    retained[:, k0:] = fresh[:, :n]
    return retained


def group_ids_memo(block, series_labels, by, without,
                   strip_metric: bool = False):
    """``group_ids_for`` memoized on the (super)block object: repeated
    dashboard queries over an unchanged block skip the O(S) python
    regrouping, the label stripping that feeds it, AND the group-id device
    upload. Sound because a staged block's series set is immutable for its
    lifetime — the superblock cache hands out a NEW block whenever any
    member shard's version moves. Keyed by (by, without, strip).

    Returns ``(gids_padded_dev, num_groups, group_labels)`` where
    gids_padded_dev is a device-resident [S_padded] int32 with padded rows
    routed to the trash group ``num_groups`` (the fused_range_aggregate
    contract). Misses build through the shared keyed single-flight
    (filodb_tpu/singleflight.memo_on): concurrent same-key queries must not
    each pay the O(S) regroup + device upload, nor clobber the memo dict."""
    from ..singleflight import memo_on

    key = (
        tuple(by) if by else None,
        tuple(without) if without else None,
        bool(strip_metric),
    )

    def build():
        from .staging import series_put

        labels = series_labels
        if strip_metric:
            from ..core.schemas import METRIC_TAG

            labels = [
                {k: v for k, v in l.items()
                 if k not in (METRIC_TAG, "__name__")}
                for l in labels
            ]
        gids, group_labels = group_ids_for(
            labels, list(by) if by else None,
            list(without) if without else None,
        )
        G = len(group_labels)
        s_pad = np.asarray(block.lens).shape[0]
        gids_padded = np.full(s_pad, G, dtype=np.int32)
        gids_padded[: len(gids)] = gids
        # co-placed with the block: a series-sharded superblock's gids
        # shard the same axis so the fused program needs no resharding
        put = series_put(getattr(block, "placement", None))
        return (put(gids_padded), G, group_labels)

    return memo_on(block, "_gid_cache", key, build)


@functools.partial(jax.jit, static_argnames=("k", "bottom"))
def topk_mask(values, k: int, bottom: bool = False):
    """values [S, J] -> [S, J] keeping only per-step top-k (rest NaN).

    Prometheus topk: at each step, the k highest series survive with their own
    labels (reference TopBottomKRowAggregator with its k-heap per step).
    Ties broken by series index for determinism.
    """
    S, J = values.shape
    v = jnp.where(jnp.isnan(values), -jnp.inf if not bottom else jnp.inf, values)
    vt = v.T if not bottom else -v.T  # [J, S], larger = better
    kk = min(k, S)
    top_vals, top_idx = jax.lax.top_k(vt, kk)  # [J, kk]
    sel = jnp.zeros((J, S), dtype=bool)
    sel = sel.at[jnp.arange(J)[:, None], top_idx].set(True)
    keep = sel.T & jnp.isfinite(v)
    return jnp.where(keep, values, jnp.nan)


@functools.partial(jax.jit, static_argnames=("num_groups",))
def segment_quantile(values, group_ids, num_groups: int, q):
    """Per (group, step) quantile across series: [S, J] -> [G, J].

    Sorts within groups by composite key (group asc, value asc); absent
    values sort to the group's end. (reference QuantileRowAggregator uses
    t-digest sketches; exact sort is affordable on device.)
    """
    S, J = values.shape
    valid = ~jnp.isnan(values)
    count = jax.ops.segment_sum(valid.astype(jnp.float32), group_ids, num_groups)  # [G,J]
    # sort per step by (group, value) — put NaN/absent at +inf within group.
    # lexsort as two stable argsorts (least-significant key first)
    v = jnp.where(valid, values, jnp.inf)
    gcol = jnp.broadcast_to(group_ids[:, None], (S, J))
    ord1 = jnp.argsort(v, axis=0, stable=True)
    g1 = jnp.take_along_axis(gcol, ord1, axis=0)
    ord2 = jnp.argsort(g1, axis=0, stable=True)
    order = jnp.take_along_axis(ord1, ord2, axis=0)  # [S, J]
    sorted_v = jnp.take_along_axis(v, order, axis=0)
    # start offset of each group in the sorted column = cumulative counts of
    # all series (valid or not) in earlier groups — series count per group is
    # step-independent
    sizes = jax.ops.segment_sum(jnp.ones_like(group_ids, dtype=jnp.int32), group_ids, num_groups)
    starts = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(sizes)[:-1]])  # [G]
    rank = jnp.clip(q, 0.0, 1.0) * jnp.maximum(count - 1.0, 0.0)  # [G, J]
    lo_i = starts[:, None] + jnp.floor(rank).astype(jnp.int32)
    hi_i = starts[:, None] + jnp.ceil(rank).astype(jnp.int32)
    frac = rank - jnp.floor(rank)
    v_lo = jnp.take_along_axis(sorted_v, jnp.clip(lo_i, 0, S - 1), axis=0)
    v_hi = jnp.take_along_axis(sorted_v, jnp.clip(hi_i, 0, S - 1), axis=0)
    out = v_lo + (v_hi - v_lo) * frac
    return jnp.where(count > 0, out, jnp.nan)


def count_values(values: np.ndarray, decimals: int = 10) -> dict[str, np.ndarray]:
    """Host-side count_values: value-string -> [J] counts (reference
    CountValuesRowAggregator; inherently dynamic-cardinality, stays on host)."""
    vals = np.asarray(values)
    out: dict[str, np.ndarray] = {}
    J = vals.shape[1]
    for j in range(J):
        col = vals[:, j]
        col = col[~np.isnan(col)]
        for x in col:
            key = f"{x:.{decimals}g}".rstrip("0").rstrip(".") if "." in f"{x:.{decimals}g}" else f"{x:.{decimals}g}"
            arr = out.setdefault(key, np.full(J, np.nan))
            arr[j] = (0.0 if np.isnan(arr[j]) else arr[j]) + 1.0
    return out


def group_ids_for(series_labels: list[dict], by: list[str] | None, without: list[str] | None):
    """Host-side grouping: label subset -> contiguous group ids + group labels.

    by=None, without=None -> one global group (classic `sum(...)`).
    """
    keys = []
    for lbls in series_labels:
        if by is not None:
            key = tuple((k, lbls.get(k, "")) for k in sorted(by))
        elif without:
            drop = set(without) | {"_metric_", "__name__"}
            key = tuple(sorted((k, v) for k, v in lbls.items() if k not in drop))
        else:
            key = ()
        keys.append(key)
    uniq: dict[tuple, int] = {}
    gids = np.empty(len(keys), dtype=np.int32)
    group_labels: list[dict] = []
    for i, k in enumerate(keys):
        if k not in uniq:
            uniq[k] = len(uniq)
            group_labels.append(dict(k))
        gids[i] = uniq[k]
    return gids, group_labels


# -- kernel observatory registration (obs/kernels.py) -----------------------
# every jit wrapper in this module registers with the executable registry so
# the observatory can report live in-process cache sizes per wrapper and
# tools/check_metrics.py can lint that no jit entry point dispatches outside
# the observatory (a new kernel added without registration fails the lint)
def _register_kernel_observatory() -> None:
    from ..obs.kernels import KERNELS

    KERNELS.register_jits(
        "ops.aggregations",
        _segment_aggregate_jit=_segment_aggregate_jit,
        _fused_general_jit=_fused_general_jit,
        _fused_mxu_jit=_fused_mxu_jit,
        _fused_jitter_jit=_fused_jitter_jit,
        _fused_masked_jit=_fused_masked_jit,
        _fused_jitter_minmax_jit=_fused_jitter_minmax_jit,
        _fused_masked_minmax_jit=_fused_masked_minmax_jit,
        _fused_pallas_jit=_fused_pallas_jit,
        _fused_sharded_general_jit=_fused_sharded_general_jit,
        _fused_sharded_mxu_jit=_fused_sharded_mxu_jit,
        _fused_sharded_jitter_jit=_fused_sharded_jitter_jit,
        _fused_sharded_masked_jit=_fused_sharded_masked_jit,
        _fused_sharded_jitter_minmax_jit=_fused_sharded_jitter_minmax_jit,
        _fused_sharded_masked_minmax_jit=_fused_sharded_masked_minmax_jit,
        _batched_general_jit=_batched_general_jit,
        _batched_mxu_jit=_batched_mxu_jit,
        _batched_jitter_jit=_batched_jitter_jit,
        _batched_masked_jit=_batched_masked_jit,
        _batched_sharded_general_jit=_batched_sharded_general_jit,
        _batched_sharded_mxu_jit=_batched_sharded_mxu_jit,
        _batched_sharded_jitter_jit=_batched_sharded_jitter_jit,
        _batched_sharded_masked_jit=_batched_sharded_masked_jit,
        topk_mask=topk_mask,
        segment_quantile=segment_quantile,
    )


_register_kernel_observatory()
