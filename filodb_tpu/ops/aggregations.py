"""Cross-series aggregation kernels (reference L4: query/exec/aggregator/ —
RowAggregator SPI with Sum/Min/Max/Count/Avg/Stddev/Stdvar/TopK/Quantile/
CountValues/Group over RangeVectors, AggrOverRangeVectors.scala mapReduce).

The reference map-reduces per-series rows through per-aggregator state
machines; here ``sum by (labels)`` is a masked segment-reduce over the
``[S, J]`` result grid — one jit call for all steps and all groups — and
cross-shard merging becomes a psum over the mesh (parallel/).

NaN = absence everywhere: a NaN sample doesn't contribute, and a group with
no members at a step yields NaN.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

SIMPLE_AGG_OPS = ("sum", "count", "avg", "min", "max", "stddev", "stdvar", "group")


def segment_aggregate(op: str, values, group_ids, num_groups: int):
    """values [S, J] (NaN = absent), group_ids [S] int32 -> [G, J].

    Instrumented entry point: per-op dispatch latency + JIT cache hit/miss
    (metrics.record_kernel_dispatch) around the jitted kernel."""
    import time as _time

    from ..metrics import record_kernel_dispatch

    t0 = _time.perf_counter()
    before = _segment_aggregate_jit._cache_size()
    out = _segment_aggregate_jit(op, values, group_ids, num_groups)
    record_kernel_dispatch(
        f"segment_{op}", _time.perf_counter() - t0,
        compiled=_segment_aggregate_jit._cache_size() > before,
    )
    return out


@functools.partial(jax.jit, static_argnames=("op", "num_groups"))
def _segment_aggregate_jit(op: str, values, group_ids, num_groups: int):
    valid = ~jnp.isnan(values)
    v0 = jnp.where(valid, values, 0.0)
    count = jax.ops.segment_sum(valid.astype(values.dtype), group_ids, num_groups)
    has = count > 0
    if op == "count":
        return jnp.where(has, count, jnp.nan)
    if op == "group":
        return jnp.where(has, 1.0, jnp.nan)
    if op in ("sum", "avg", "stddev", "stdvar"):
        s = jax.ops.segment_sum(v0, group_ids, num_groups)
        if op == "sum":
            return jnp.where(has, s, jnp.nan)
        mean = s / jnp.maximum(count, 1.0)
        if op == "avg":
            return jnp.where(has, mean, jnp.nan)
        dev = jnp.where(valid, (values - mean[group_ids]) ** 2, 0.0)
        var = jax.ops.segment_sum(dev, group_ids, num_groups) / jnp.maximum(count, 1.0)
        return jnp.where(has, var if op == "stdvar" else jnp.sqrt(var), jnp.nan)
    if op in ("min", "max"):
        big = jnp.inf if op == "min" else -jnp.inf
        vm = jnp.where(valid, values, big)
        r = (
            jax.ops.segment_min(vm, group_ids, num_groups)
            if op == "min"
            else jax.ops.segment_max(vm, group_ids, num_groups)
        )
        return jnp.where(has, r, jnp.nan)
    raise ValueError(f"unknown aggregation {op}")


@functools.partial(jax.jit, static_argnames=("k", "bottom"))
def topk_mask(values, k: int, bottom: bool = False):
    """values [S, J] -> [S, J] keeping only per-step top-k (rest NaN).

    Prometheus topk: at each step, the k highest series survive with their own
    labels (reference TopBottomKRowAggregator with its k-heap per step).
    Ties broken by series index for determinism.
    """
    S, J = values.shape
    v = jnp.where(jnp.isnan(values), -jnp.inf if not bottom else jnp.inf, values)
    vt = v.T if not bottom else -v.T  # [J, S], larger = better
    kk = min(k, S)
    top_vals, top_idx = jax.lax.top_k(vt, kk)  # [J, kk]
    sel = jnp.zeros((J, S), dtype=bool)
    sel = sel.at[jnp.arange(J)[:, None], top_idx].set(True)
    keep = sel.T & jnp.isfinite(v)
    return jnp.where(keep, values, jnp.nan)


@functools.partial(jax.jit, static_argnames=("num_groups",))
def segment_quantile(values, group_ids, num_groups: int, q):
    """Per (group, step) quantile across series: [S, J] -> [G, J].

    Sorts within groups by composite key (group asc, value asc); absent
    values sort to the group's end. (reference QuantileRowAggregator uses
    t-digest sketches; exact sort is affordable on device.)
    """
    S, J = values.shape
    valid = ~jnp.isnan(values)
    count = jax.ops.segment_sum(valid.astype(jnp.float32), group_ids, num_groups)  # [G,J]
    # sort per step by (group, value) — put NaN/absent at +inf within group.
    # lexsort as two stable argsorts (least-significant key first)
    v = jnp.where(valid, values, jnp.inf)
    gcol = jnp.broadcast_to(group_ids[:, None], (S, J))
    ord1 = jnp.argsort(v, axis=0, stable=True)
    g1 = jnp.take_along_axis(gcol, ord1, axis=0)
    ord2 = jnp.argsort(g1, axis=0, stable=True)
    order = jnp.take_along_axis(ord1, ord2, axis=0)  # [S, J]
    sorted_v = jnp.take_along_axis(v, order, axis=0)
    # start offset of each group in the sorted column = cumulative counts of
    # all series (valid or not) in earlier groups — series count per group is
    # step-independent
    sizes = jax.ops.segment_sum(jnp.ones_like(group_ids, dtype=jnp.int32), group_ids, num_groups)
    starts = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(sizes)[:-1]])  # [G]
    rank = jnp.clip(q, 0.0, 1.0) * jnp.maximum(count - 1.0, 0.0)  # [G, J]
    lo_i = starts[:, None] + jnp.floor(rank).astype(jnp.int32)
    hi_i = starts[:, None] + jnp.ceil(rank).astype(jnp.int32)
    frac = rank - jnp.floor(rank)
    v_lo = jnp.take_along_axis(sorted_v, jnp.clip(lo_i, 0, S - 1), axis=0)
    v_hi = jnp.take_along_axis(sorted_v, jnp.clip(hi_i, 0, S - 1), axis=0)
    out = v_lo + (v_hi - v_lo) * frac
    return jnp.where(count > 0, out, jnp.nan)


def count_values(values: np.ndarray, decimals: int = 10) -> dict[str, np.ndarray]:
    """Host-side count_values: value-string -> [J] counts (reference
    CountValuesRowAggregator; inherently dynamic-cardinality, stays on host)."""
    vals = np.asarray(values)
    out: dict[str, np.ndarray] = {}
    J = vals.shape[1]
    for j in range(J):
        col = vals[:, j]
        col = col[~np.isnan(col)]
        for x in col:
            key = f"{x:.{decimals}g}".rstrip("0").rstrip(".") if "." in f"{x:.{decimals}g}" else f"{x:.{decimals}g}"
            arr = out.setdefault(key, np.full(J, np.nan))
            arr[j] = (0.0 if np.isnan(arr[j]) else arr[j]) + 1.0
    return out


def group_ids_for(series_labels: list[dict], by: list[str] | None, without: list[str] | None):
    """Host-side grouping: label subset -> contiguous group ids + group labels.

    by=None, without=None -> one global group (classic `sum(...)`).
    """
    keys = []
    for lbls in series_labels:
        if by is not None:
            key = tuple((k, lbls.get(k, "")) for k in sorted(by))
        elif without:
            drop = set(without) | {"_metric_", "__name__"}
            key = tuple(sorted((k, v) for k, v in lbls.items() if k not in drop))
        else:
            key = ()
        keys.append(key)
    uniq: dict[tuple, int] = {}
    gids = np.empty(len(keys), dtype=np.int32)
    group_labels: list[dict] = []
    for i, k in enumerate(keys):
        if k not in uniq:
            uniq[k] = len(uniq)
            group_labels.append(dict(k))
        gids[i] = uniq[k]
    return gids, group_labels
