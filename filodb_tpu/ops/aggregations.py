"""Cross-series aggregation kernels (reference L4: query/exec/aggregator/ —
RowAggregator SPI with Sum/Min/Max/Count/Avg/Stddev/Stdvar/TopK/Quantile/
CountValues/Group over RangeVectors, AggrOverRangeVectors.scala mapReduce).

The reference map-reduces per-series rows through per-aggregator state
machines; here ``sum by (labels)`` is a masked segment-reduce over the
``[S, J]`` result grid — one jit call for all steps and all groups — and
cross-shard merging becomes a psum over the mesh (parallel/).

NaN = absence everywhere: a NaN sample doesn't contribute, and a group with
no members at a step yields NaN.
"""

from __future__ import annotations

import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np

# serializes group_ids_memo misses (O(S) python regroup + device upload):
# racing same-key queries must compute once, not clobber each other.
# Deliberately ONE process-wide lock: misses happen once per (block,
# grouping) lifetime, so cross-key contention is a cold-path-only cost not
# worth per-key lock bookkeeping (ROADMAP notes consolidating the tree's
# single-flight helpers).
_GID_MEMO_LOCK = threading.Lock()

SIMPLE_AGG_OPS = ("sum", "count", "avg", "min", "max", "stddev", "stdvar", "group")


def segment_aggregate(op: str, values, group_ids, num_groups: int):
    """values [S, J] (NaN = absent), group_ids [S] int32 -> [G, J].

    Instrumented entry point: per-op dispatch latency + JIT cache hit/miss
    (metrics.record_kernel_dispatch) around the jitted kernel."""
    import time as _time

    from ..metrics import record_kernel_dispatch

    t0 = _time.perf_counter()
    before = _segment_aggregate_jit._cache_size()
    out = _segment_aggregate_jit(op, values, group_ids, num_groups)
    record_kernel_dispatch(
        f"segment_{op}", _time.perf_counter() - t0,
        compiled=_segment_aggregate_jit._cache_size() > before,
    )
    return out


@functools.partial(jax.jit, static_argnames=("op", "num_groups"))
def _segment_aggregate_jit(op: str, values, group_ids, num_groups: int):
    valid = ~jnp.isnan(values)
    v0 = jnp.where(valid, values, 0.0)
    count = jax.ops.segment_sum(valid.astype(values.dtype), group_ids, num_groups)
    has = count > 0
    if op == "count":
        return jnp.where(has, count, jnp.nan)
    if op == "group":
        return jnp.where(has, 1.0, jnp.nan)
    if op in ("sum", "avg", "stddev", "stdvar"):
        s = jax.ops.segment_sum(v0, group_ids, num_groups)
        if op == "sum":
            return jnp.where(has, s, jnp.nan)
        mean = s / jnp.maximum(count, 1.0)
        if op == "avg":
            return jnp.where(has, mean, jnp.nan)
        dev = jnp.where(valid, (values - mean[group_ids]) ** 2, 0.0)
        var = jax.ops.segment_sum(dev, group_ids, num_groups) / jnp.maximum(count, 1.0)
        return jnp.where(has, var if op == "stdvar" else jnp.sqrt(var), jnp.nan)
    if op in ("min", "max"):
        big = jnp.inf if op == "min" else -jnp.inf
        vm = jnp.where(valid, values, big)
        r = (
            jax.ops.segment_min(vm, group_ids, num_groups)
            if op == "min"
            else jax.ops.segment_max(vm, group_ids, num_groups)
        )
        return jnp.where(has, r, jnp.nan)
    raise ValueError(f"unknown aggregation {op}")


# ---------------------------------------------------------------------------
# fused range-function -> segment-aggregate (single-dispatch cross-shard path)
# ---------------------------------------------------------------------------

# range functions the fused MXU variant handles directly (the subset of
# mxu_kernels.MXU_FUNCS that needs no extra lazily-built window structures)
FUSED_MXU_FUNCS = {
    "sum_over_time", "count_over_time", "avg_over_time", "last",
    "last_over_time", "first_over_time", "present_over_time",
    "stddev_over_time", "stdvar_over_time", "z_score",
    "rate", "increase", "delta", "idelta", "irate",
}


@functools.partial(jax.jit, static_argnames=(
    "func", "op", "num_steps", "num_groups", "is_counter", "is_delta"
))
def _fused_general_jit(func, op, ts, vals, lens, baseline, raw, gids,
                       start_off, step_ms, window, num_steps: int,
                       num_groups: int, is_counter: bool, is_delta: bool):
    """range_kernel -> segment aggregate as ONE compiled program: only the
    [G, J] group partials ever exist as program outputs — no [S, J] grid
    reaches the host, and no second dispatch happens. ``gids`` maps padded
    rows to the trash group ``num_groups`` (padded rows yield NaN from value
    functions but real values from count_over_time/present_over_time, so
    they must never share a segment with real series)."""
    from .kernels import range_kernel

    sj = range_kernel(
        func, ts, vals, lens, baseline, raw, start_off, step_ms, window,
        num_steps, is_counter=is_counter, is_delta=is_delta,
    )
    return _segment_aggregate_jit(op, sj, gids, num_groups + 1)[:num_groups]


@functools.partial(jax.jit, static_argnames=(
    "func", "op", "num_groups", "is_counter", "is_delta", "fetch"
))
def _fused_mxu_jit(func, op, vals, raw, baseline, W, F, L, L2, count,
                   t_first, t_last, t_last2, out_t, window_ms, idx, gids,
                   num_groups: int, is_counter: bool, is_delta: bool,
                   fetch: str):
    """Regular-grid fused variant: the MXU window-matmul kernel and the
    segment reduce in one compiled program (see _fused_general_jit for the
    trash-group contract on ``gids``)."""
    from .mxu_kernels import mxu_range_kernel

    sj = mxu_range_kernel(
        func, vals, raw, baseline, W, F, L, L2, count, t_first, t_last,
        t_last2, out_t, window_ms, idx=idx, is_counter=is_counter,
        is_delta=is_delta, fetch=fetch,
    )
    return _segment_aggregate_jit(op, sj, gids, num_groups + 1)[:num_groups]


def fused_range_aggregate(func: str, op: str, block, gids_padded,
                          num_groups: int, params, is_counter: bool = False,
                          is_delta: bool = False):
    """One device dispatch for ``op by (...) (func(selector[w]))`` over a
    staged (super)block: returns the [G, J_pad] group partials on device.

    ``gids_padded`` is [S_padded] int32 with padded rows assigned the trash
    group ``num_groups``. Regular shared grids ride the MXU window-matrix
    kernel (matrices cached device-resident on the block); everything else
    runs the general compare-and-reduce kernel. Instrumented like every
    other kernel entry (per-dispatch latency + JIT hit/miss)."""
    import time as _time

    from ..metrics import record_kernel_dispatch
    from .kernels import pad_steps

    j_pad = pad_steps(params.num_steps)
    raw = block.raw if block.raw is not None else block.vals
    t0 = _time.perf_counter()
    use_mxu = (
        block.regular_ts is not None
        and func in FUSED_MXU_FUNCS
        and not (is_delta and func in ("irate", "idelta"))
    )
    if use_mxu:
        from .mxu_kernels import fetch_strategy, window_matrices

        wm = window_matrices(
            block, int(params.start_ms - block.base_ms), params.step_ms,
            j_pad, params.window_ms,
        )
        before = _fused_mxu_jit._cache_size()
        out = _fused_mxu_jit(
            func, op, block.vals, raw, block.baseline,
            wm.dW, wm.dF, wm.dL, wm.dL2, wm.d_count, wm.d_tf, wm.d_tl,
            wm.d_tl2, wm.d_out_t, np.float32(params.window_ms), wm.d_idx,
            gids_padded, num_groups, is_counter, is_delta, fetch_strategy(),
        )
        compiled = _fused_mxu_jit._cache_size() > before
    else:
        before = _fused_general_jit._cache_size()
        out = _fused_general_jit(
            func, op, block.ts, block.vals, block.lens, block.baseline, raw,
            gids_padded, np.int32(params.start_ms - block.base_ms),
            np.int32(params.step_ms), np.int32(params.window_ms), j_pad,
            num_groups, is_counter, is_delta,
        )
        compiled = _fused_general_jit._cache_size() > before
    record_kernel_dispatch(
        f"fused_{op}_{func}", _time.perf_counter() - t0, compiled=compiled
    )
    return out


def group_ids_memo(block, series_labels, by, without,
                   strip_metric: bool = False):
    """``group_ids_for`` memoized on the (super)block object: repeated
    dashboard queries over an unchanged block skip the O(S) python
    regrouping, the label stripping that feeds it, AND the group-id device
    upload. Sound because a staged block's series set is immutable for its
    lifetime — the superblock cache hands out a NEW block whenever any
    member shard's version moves. Keyed by (by, without, strip).

    Returns ``(gids_padded_dev, num_groups, group_labels)`` where
    gids_padded_dev is a device-resident [S_padded] int32 with padded rows
    routed to the trash group ``num_groups`` (the fused_range_aggregate
    contract)."""
    key = (
        tuple(by) if by else None,
        tuple(without) if without else None,
        bool(strip_metric),
    )
    cache = getattr(block, "_gid_cache", None)
    hit = cache.get(key) if cache is not None else None
    if hit is None:
        # miss path under a lock: concurrent same-key queries must not each
        # pay the O(S) regroup + device upload, nor clobber the cache dict
        with _GID_MEMO_LOCK:
            cache = getattr(block, "_gid_cache", None)
            if cache is None:
                cache = {}
                block._gid_cache = cache
            hit = cache.get(key)
            if hit is None:
                import jax

                labels = series_labels
                if strip_metric:
                    from ..core.schemas import METRIC_TAG

                    labels = [
                        {k: v for k, v in l.items()
                         if k not in (METRIC_TAG, "__name__")}
                        for l in labels
                    ]
                gids, group_labels = group_ids_for(
                    labels, list(by) if by else None,
                    list(without) if without else None,
                )
                G = len(group_labels)
                s_pad = np.asarray(block.lens).shape[0]
                gids_padded = np.full(s_pad, G, dtype=np.int32)
                gids_padded[: len(gids)] = gids
                hit = (jax.device_put(gids_padded), G, group_labels)
                cache[key] = hit
    return hit


@functools.partial(jax.jit, static_argnames=("k", "bottom"))
def topk_mask(values, k: int, bottom: bool = False):
    """values [S, J] -> [S, J] keeping only per-step top-k (rest NaN).

    Prometheus topk: at each step, the k highest series survive with their own
    labels (reference TopBottomKRowAggregator with its k-heap per step).
    Ties broken by series index for determinism.
    """
    S, J = values.shape
    v = jnp.where(jnp.isnan(values), -jnp.inf if not bottom else jnp.inf, values)
    vt = v.T if not bottom else -v.T  # [J, S], larger = better
    kk = min(k, S)
    top_vals, top_idx = jax.lax.top_k(vt, kk)  # [J, kk]
    sel = jnp.zeros((J, S), dtype=bool)
    sel = sel.at[jnp.arange(J)[:, None], top_idx].set(True)
    keep = sel.T & jnp.isfinite(v)
    return jnp.where(keep, values, jnp.nan)


@functools.partial(jax.jit, static_argnames=("num_groups",))
def segment_quantile(values, group_ids, num_groups: int, q):
    """Per (group, step) quantile across series: [S, J] -> [G, J].

    Sorts within groups by composite key (group asc, value asc); absent
    values sort to the group's end. (reference QuantileRowAggregator uses
    t-digest sketches; exact sort is affordable on device.)
    """
    S, J = values.shape
    valid = ~jnp.isnan(values)
    count = jax.ops.segment_sum(valid.astype(jnp.float32), group_ids, num_groups)  # [G,J]
    # sort per step by (group, value) — put NaN/absent at +inf within group.
    # lexsort as two stable argsorts (least-significant key first)
    v = jnp.where(valid, values, jnp.inf)
    gcol = jnp.broadcast_to(group_ids[:, None], (S, J))
    ord1 = jnp.argsort(v, axis=0, stable=True)
    g1 = jnp.take_along_axis(gcol, ord1, axis=0)
    ord2 = jnp.argsort(g1, axis=0, stable=True)
    order = jnp.take_along_axis(ord1, ord2, axis=0)  # [S, J]
    sorted_v = jnp.take_along_axis(v, order, axis=0)
    # start offset of each group in the sorted column = cumulative counts of
    # all series (valid or not) in earlier groups — series count per group is
    # step-independent
    sizes = jax.ops.segment_sum(jnp.ones_like(group_ids, dtype=jnp.int32), group_ids, num_groups)
    starts = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(sizes)[:-1]])  # [G]
    rank = jnp.clip(q, 0.0, 1.0) * jnp.maximum(count - 1.0, 0.0)  # [G, J]
    lo_i = starts[:, None] + jnp.floor(rank).astype(jnp.int32)
    hi_i = starts[:, None] + jnp.ceil(rank).astype(jnp.int32)
    frac = rank - jnp.floor(rank)
    v_lo = jnp.take_along_axis(sorted_v, jnp.clip(lo_i, 0, S - 1), axis=0)
    v_hi = jnp.take_along_axis(sorted_v, jnp.clip(hi_i, 0, S - 1), axis=0)
    out = v_lo + (v_hi - v_lo) * frac
    return jnp.where(count > 0, out, jnp.nan)


def count_values(values: np.ndarray, decimals: int = 10) -> dict[str, np.ndarray]:
    """Host-side count_values: value-string -> [J] counts (reference
    CountValuesRowAggregator; inherently dynamic-cardinality, stays on host)."""
    vals = np.asarray(values)
    out: dict[str, np.ndarray] = {}
    J = vals.shape[1]
    for j in range(J):
        col = vals[:, j]
        col = col[~np.isnan(col)]
        for x in col:
            key = f"{x:.{decimals}g}".rstrip("0").rstrip(".") if "." in f"{x:.{decimals}g}" else f"{x:.{decimals}g}"
            arr = out.setdefault(key, np.full(J, np.nan))
            arr[j] = (0.0 if np.isnan(arr[j]) else arr[j]) + 1.0
    return out


def group_ids_for(series_labels: list[dict], by: list[str] | None, without: list[str] | None):
    """Host-side grouping: label subset -> contiguous group ids + group labels.

    by=None, without=None -> one global group (classic `sum(...)`).
    """
    keys = []
    for lbls in series_labels:
        if by is not None:
            key = tuple((k, lbls.get(k, "")) for k in sorted(by))
        elif without:
            drop = set(without) | {"_metric_", "__name__"}
            key = tuple(sorted((k, v) for k, v in lbls.items() if k not in drop))
        else:
            key = ()
        keys.append(key)
    uniq: dict[tuple, int] = {}
    gids = np.empty(len(keys), dtype=np.int32)
    group_labels: list[dict] = []
    for i, k in enumerate(keys):
        if k not in uniq:
            uniq[k] = len(uniq)
            group_labels.append(dict(k))
        gids[i] = uniq[k]
    return gids, group_labels
