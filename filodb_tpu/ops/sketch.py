"""Log-linear quantile sketches for distributed quantile pushdown
(reference: QuantileRowAggregator ships t-digest sketches between nodes,
query/exec/aggregator/RowAggregator; design informed by the Circllhist
log-linear histogram paper surfaced in PAPERS.md).

A sketch is a fixed ``[B]`` histogram over log-spaced bins: sign x octave x
SUB sub-bins per octave, plus a zero bin. Sketches are mergeable by
addition (psum across mesh shards, += across clusters); quantiles read off
the merged sketch with log-linear interpolation. Worst-case relative error
is 2^(1/SUB)-1 (~2.2% at SUB=32), the classic log-linear trade.

Device side is all elementwise + segment_sum — no sorts, no gathers.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..jax_compat import shard_map

SUB = 32  # sub-bins per octave
E_MIN = -24  # 2^-24 ~ 6e-8: smaller magnitudes collapse to the zero bin
E_MAX = 40  # 2^40 ~ 1e12
OCTAVES = E_MAX - E_MIN
HALF = OCTAVES * SUB  # bins per sign
B = 2 * HALF + 1  # [negative bins | zero | positive bins]
ZERO_BIN = HALF


def _bin_of(values):
    """values [*] -> bin ids [*] (NaN -> -1, excluded by caller)."""
    mag = jnp.abs(values)
    log = jnp.log2(jnp.maximum(mag, 1e-300))
    pos = jnp.clip(((log - E_MIN) * SUB).astype(jnp.int32), 0, HALF - 1)
    tiny = mag < 2.0**E_MIN
    bin_pos = jnp.where(tiny, 0, pos + 1)  # offset from zero bin
    b = jnp.where(values >= 0, ZERO_BIN + bin_pos, ZERO_BIN - bin_pos)
    b = jnp.where(tiny, ZERO_BIN, b)
    return jnp.where(jnp.isnan(values), -1, b)


def bin_centers() -> np.ndarray:
    """Representative value per bin (log-linear midpoint)."""
    idx = np.arange(HALF)
    mags = 2.0 ** (E_MIN + (idx + 0.5) / SUB)
    return np.concatenate([-mags[::-1], [0.0], mags])


def bin_of_np(values: np.ndarray) -> np.ndarray:
    """Host (numpy) mirror of :func:`_bin_of` — the rollup maintainer bins
    raw samples into per-period sketches on the ingest path without a
    device round trip. NaN -> -1 (caller excludes)."""
    values = np.asarray(values, dtype=np.float64)
    mag = np.abs(np.nan_to_num(values, nan=1.0))
    with np.errstate(divide="ignore"):
        log = np.log2(np.maximum(mag, 1e-300))
    pos = np.clip(((log - E_MIN) * SUB).astype(np.int64), 0, HALF - 1)
    tiny = mag < 2.0**E_MIN
    bin_pos = np.where(tiny, 0, pos + 1)
    b = np.where(values >= 0, ZERO_BIN + bin_pos, ZERO_BIN - bin_pos)
    b = np.where(tiny, ZERO_BIN, b)
    return np.where(np.isnan(values), -1, b).astype(np.int64)


@functools.partial(jax.jit, static_argnames=("num_groups",))
def build_sketch(values, gids, num_groups: int):
    """values [S, J] (NaN absent) -> sketch counts [G, J, B] (f32)."""
    S, J = values.shape
    bins = _bin_of(values)  # [S, J]
    valid = bins >= 0
    # accumulate counts without one-hot blowup: scan over sub-blocks of B
    BLK = 64

    def block(counts, b0):
        ids = b0 + jnp.arange(BLK)[None, None, :]  # [1, 1, BLK]
        m = (bins[:, :, None] == ids) & valid[:, :, None]  # [S, J, BLK]
        part = jax.ops.segment_sum(m.astype(jnp.float32), gids, num_groups)
        # blocks cover disjoint bin ranges: plain write, no accumulate
        return jax.lax.dynamic_update_slice(counts, part, (0, 0, b0)), None

    n_blocks = -(-B // BLK)
    init = jnp.zeros((num_groups, J, n_blocks * BLK), jnp.float32)
    starts = jnp.arange(n_blocks) * BLK
    out, _ = jax.lax.scan(block, init, starts)
    return out[:, :, :B]


def sketch_quantile(counts: np.ndarray, q: float) -> np.ndarray:
    """Merged sketch [G, J, B] -> quantile values [G, J] (host, tiny)."""
    counts = np.asarray(counts, dtype=np.float64)
    total = counts.sum(-1)
    cum = np.cumsum(counts, axis=-1)
    # rank >= 1 sample: q=0 must read the first POPULATED bin (the min),
    # not the empty bottom of the bin axis
    rank = np.maximum(np.clip(q, 0.0, 1.0) * total, np.minimum(total, 1.0))
    # first bin with cum >= rank
    idx = (cum < rank[..., None]).sum(-1)
    idx = np.minimum(idx, B - 1)
    centers = bin_centers()
    out = centers[idx]
    return np.where(total > 0, out, np.nan)


@functools.partial(
    jax.jit, static_argnames=("mesh", "func", "num_steps", "num_groups", "is_counter", "is_delta")
)
def distributed_sketch_quantile(
    mesh,
    func: str,
    ts, vals, lens, baseline, raw, gids,
    start_off, step_ms, window,
    num_steps: int,
    num_groups: int,
    is_counter: bool = False,
    is_delta: bool = False,
):
    """Per-shard range function -> per-shard sketch -> psum merge: the
    mesh-distributed form of quantile(q, range_fn(...)). Returns merged
    sketch [G, J, B]; the (tiny) quantile read-off happens on host."""
    from jax.sharding import PartitionSpec as P

    from . import kernels as K

    def local(ts_l, vals_l, lens_l, base_l, raw_l, gids_l):
        grid = K.range_kernel(
            func, ts_l, vals_l, lens_l, base_l, raw_l,
            start_off, step_ms, window, num_steps,
            is_counter=is_counter, is_delta=is_delta,
        )
        sk = build_sketch(grid, gids_l, num_groups)
        return jax.lax.psum(sk, "shard")

    shard = P("shard")
    row = P("shard", None)
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(row, row, shard, shard, row, shard),
        out_specs=P(),
        check=False,
    )(ts, vals, lens, baseline, raw, gids)


# ---------------------------------------------------------------------------
# Rollup-tier kernels (doc/perf.md "Sketch rollup tier"): long-range queries
# read per-period summary blocks maintained by downsample/rollup.py instead
# of raw samples. A rollup block stores, per series per period, a COMPACTED
# sketch (the [lo, hi] slice of the full bin axis actually populated — the
# read-off is exact-equivalent because bins stay sorted by value) plus
# min/max/sum/count/corrected-last moments. Serving merges periods (cumsum
# gather) or series (segment_sum / psum) on device; only [S, J] / [G, J]
# grids reach the host.
# ---------------------------------------------------------------------------


def _sketch_readoff(w, centers, q):
    """Windowed sketch counts [..., Bc] -> quantile values [...]: cumulative
    rank scan + log-linear bin-center read-off (the device form of
    sketch_quantile)."""
    total = w.sum(-1)
    cum = jnp.cumsum(w, -1)
    # rank >= 1 sample: q=0 reads the first POPULATED bin (see
    # sketch_quantile, the host twin)
    rank = jnp.maximum(jnp.clip(q, 0.0, 1.0) * total,
                       jnp.minimum(total, 1.0))
    idx = jnp.minimum((cum < rank[..., None]).sum(-1), w.shape[-1] - 1)
    return jnp.where(total > 0, centers[idx], jnp.nan)


@functools.partial(jax.jit, static_argnames=("win_p",))
def rollup_sketch_quantile(counts, centers, starts, q, win_p: int):
    """Per-series quantile_over_time from a rollup sketch block.

    counts [S, P, Bc] per-series-per-period bin counts; centers [Bc]
    compacted bin centers (ascending); starts [J] first period index of
    each output step's window; win_p periods per window. Returns [S, J].
    O(S*P*Bc) summary reads — never O(raw samples)."""
    cs = jnp.cumsum(counts.astype(jnp.float32), axis=1)
    cs = jnp.pad(cs, ((0, 0), (1, 0), (0, 0)))
    w = cs[:, starts + win_p, :] - cs[:, starts, :]  # [S, J, Bc]
    return _sketch_readoff(w, centers, q)


def _windowed(x, init, op, win_p: int, step_p: int):
    """[S, Pw] -> [S, J] sliding reduce over the period axis."""
    return jax.lax.reduce_window(
        x, init, op, window_dimensions=(1, win_p),
        window_strides=(1, step_p), padding="VALID",
    )


def _moment_vals(func: str, mn, mx, sm, cnt, clast, win_p: int, step_p: int,
                 window_s: float):
    """Per-series per-step values [S, J] of a moment-servable range function
    evaluated from rollup moments. All inputs are [S, Pw+1] with ONE lead
    period at index 0 (counter diffs need the pre-window corrected last);
    window j covers local periods [1 + j*step_p, 1 + j*step_p + win_p)."""
    cntw = _windowed(cnt[:, 1:], 0.0, jax.lax.add, win_p, step_p)
    present = cntw > 0
    if func in ("rate", "increase"):
        j = jnp.arange((cnt.shape[1] - 1 - win_p) // step_p + 1) * step_p
        inc = clast[:, j + win_p] - clast[:, j]
        out = inc / window_s if func == "rate" else inc
    elif func == "min_over_time":
        out = _windowed(mn[:, 1:], jnp.inf, jax.lax.min, win_p, step_p)
    elif func == "max_over_time":
        out = _windowed(mx[:, 1:], -jnp.inf, jax.lax.max, win_p, step_p)
    elif func == "sum_over_time":
        out = _windowed(sm[:, 1:], 0.0, jax.lax.add, win_p, step_p)
    elif func == "count_over_time":
        out = cntw
    elif func == "avg_over_time":
        sw = _windowed(sm[:, 1:], 0.0, jax.lax.add, win_p, step_p)
        out = sw / jnp.maximum(cntw, 1.0)
    else:
        raise ValueError(f"not a moment-servable function: {func}")
    return jnp.where(present, out, jnp.nan)


@functools.partial(
    jax.jit, static_argnames=("func", "win_p", "step_p")
)
def rollup_moment_range(func: str, mn, mx, sm, cnt, clast,
                        win_p: int, step_p: int, window_s: float):
    """Per-series range function from rollup moments -> [S, J]."""
    return _moment_vals(func, mn, mx, sm, cnt, clast, win_p, step_p, window_s)


@functools.partial(
    jax.jit, static_argnames=("func", "op", "num_groups", "win_p", "step_p")
)
def rollup_moment_aggregate(func: str, op: str, mn, mx, sm, cnt, clast, gids,
                            num_groups: int, win_p: int, step_p: int,
                            window_s: float):
    """``op by (...) (func(selector[w]))`` from rollup moments: per-series
    values then one masked segment reduce -> [G, J]."""
    vals = _moment_vals(func, mn, mx, sm, cnt, clast, win_p, step_p, window_s)
    valid = jnp.isfinite(vals)
    nvalid = jax.ops.segment_sum(valid.astype(jnp.float32), gids, num_groups)
    if op == "sum":
        out = jax.ops.segment_sum(jnp.where(valid, vals, 0.0), gids, num_groups)
    elif op == "count":
        out = nvalid
    elif op == "avg":
        tot = jax.ops.segment_sum(jnp.where(valid, vals, 0.0), gids, num_groups)
        out = tot / jnp.maximum(nvalid, 1.0)
    elif op == "min":
        out = jax.ops.segment_min(
            jnp.where(valid, vals, jnp.inf), gids, num_groups
        )
    elif op == "max":
        out = jax.ops.segment_max(
            jnp.where(valid, vals, -jnp.inf), gids, num_groups
        )
    else:
        raise ValueError(f"not a moment-servable aggregate: {op}")
    return jnp.where(nvalid > 0, out, jnp.nan)


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "func", "num_groups", "win_p", "step_p"),
)
def rollup_agg_sketch_quantile(func: str, mn, mx, sm, cnt, clast, gids, q,
                               num_groups: int, win_p: int, step_p: int,
                               window_s: float, mesh=None):
    """``quantile(q, func(selector[w]))`` from rollup moments via the
    merge-sketches -> epilogue program: per-series values sketch by group
    (build_sketch), sketches MERGE BY ADDITION — psum across the mesh's
    shard axis under shard_map when ``mesh`` is set, exactly the
    fused_hist_range_aggregate pattern — and the quantile reads off the
    merged sketch on device. Only [G, J] reaches the host."""
    centers = jnp.asarray(bin_centers(), jnp.float32)

    def local(mn_l, mx_l, sm_l, cnt_l, clast_l, gids_l):
        vals = _moment_vals(
            func, mn_l, mx_l, sm_l, cnt_l, clast_l, win_p, step_p, window_s
        )
        sk = build_sketch(vals, gids_l, num_groups)  # [G, J, B]
        if mesh is not None:
            sk = jax.lax.psum(sk, "shard")
        return sk

    if mesh is None:
        merged = local(mn, mx, sm, cnt, clast, gids)
    else:
        from jax.sharding import PartitionSpec as P

        row = P("shard", None)
        merged = shard_map(
            local,
            mesh=mesh,
            in_specs=(row, row, row, row, row, P("shard")),
            out_specs=P(),
            check=False,
        )(mn, mx, sm, cnt, clast, gids)
    return _sketch_readoff(merged, centers, q)


# kernel-observatory registration (obs/kernels.py; linted by
# tools/check_metrics.py — every jit wrapper here must register)
def _register_kernel_observatory() -> None:
    from ..obs.kernels import KERNELS

    KERNELS.register_jits(
        "ops.sketch",
        build_sketch=build_sketch,
        distributed_sketch_quantile=distributed_sketch_quantile,
        rollup_sketch_quantile=rollup_sketch_quantile,
        rollup_moment_range=rollup_moment_range,
        rollup_moment_aggregate=rollup_moment_aggregate,
        rollup_agg_sketch_quantile=rollup_agg_sketch_quantile,
    )


_register_kernel_observatory()
