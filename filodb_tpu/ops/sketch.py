"""Log-linear quantile sketches for distributed quantile pushdown
(reference: QuantileRowAggregator ships t-digest sketches between nodes,
query/exec/aggregator/RowAggregator; design informed by the Circllhist
log-linear histogram paper surfaced in PAPERS.md).

A sketch is a fixed ``[B]`` histogram over log-spaced bins: sign x octave x
SUB sub-bins per octave, plus a zero bin. Sketches are mergeable by
addition (psum across mesh shards, += across clusters); quantiles read off
the merged sketch with log-linear interpolation. Worst-case relative error
is 2^(1/SUB)-1 (~2.2% at SUB=32), the classic log-linear trade.

Device side is all elementwise + segment_sum — no sorts, no gathers.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..jax_compat import shard_map

SUB = 32  # sub-bins per octave
E_MIN = -24  # 2^-24 ~ 6e-8: smaller magnitudes collapse to the zero bin
E_MAX = 40  # 2^40 ~ 1e12
OCTAVES = E_MAX - E_MIN
HALF = OCTAVES * SUB  # bins per sign
B = 2 * HALF + 1  # [negative bins | zero | positive bins]
ZERO_BIN = HALF


def _bin_of(values):
    """values [*] -> bin ids [*] (NaN -> -1, excluded by caller)."""
    mag = jnp.abs(values)
    log = jnp.log2(jnp.maximum(mag, 1e-300))
    pos = jnp.clip(((log - E_MIN) * SUB).astype(jnp.int32), 0, HALF - 1)
    tiny = mag < 2.0**E_MIN
    bin_pos = jnp.where(tiny, 0, pos + 1)  # offset from zero bin
    b = jnp.where(values >= 0, ZERO_BIN + bin_pos, ZERO_BIN - bin_pos)
    b = jnp.where(tiny, ZERO_BIN, b)
    return jnp.where(jnp.isnan(values), -1, b)


def bin_centers() -> np.ndarray:
    """Representative value per bin (log-linear midpoint)."""
    idx = np.arange(HALF)
    mags = 2.0 ** (E_MIN + (idx + 0.5) / SUB)
    return np.concatenate([-mags[::-1], [0.0], mags])


@functools.partial(jax.jit, static_argnames=("num_groups",))
def build_sketch(values, gids, num_groups: int):
    """values [S, J] (NaN absent) -> sketch counts [G, J, B] (f32)."""
    S, J = values.shape
    bins = _bin_of(values)  # [S, J]
    valid = bins >= 0
    # accumulate counts without one-hot blowup: scan over sub-blocks of B
    BLK = 64

    def block(counts, b0):
        ids = b0 + jnp.arange(BLK)[None, None, :]  # [1, 1, BLK]
        m = (bins[:, :, None] == ids) & valid[:, :, None]  # [S, J, BLK]
        part = jax.ops.segment_sum(m.astype(jnp.float32), gids, num_groups)
        # blocks cover disjoint bin ranges: plain write, no accumulate
        return jax.lax.dynamic_update_slice(counts, part, (0, 0, b0)), None

    n_blocks = -(-B // BLK)
    init = jnp.zeros((num_groups, J, n_blocks * BLK), jnp.float32)
    starts = jnp.arange(n_blocks) * BLK
    out, _ = jax.lax.scan(block, init, starts)
    return out[:, :, :B]


def sketch_quantile(counts: np.ndarray, q: float) -> np.ndarray:
    """Merged sketch [G, J, B] -> quantile values [G, J] (host, tiny)."""
    counts = np.asarray(counts, dtype=np.float64)
    total = counts.sum(-1)
    cum = np.cumsum(counts, axis=-1)
    rank = np.clip(q, 0.0, 1.0) * total
    # first bin with cum >= rank
    idx = (cum < rank[..., None]).sum(-1)
    idx = np.minimum(idx, B - 1)
    centers = bin_centers()
    out = centers[idx]
    return np.where(total > 0, out, np.nan)


@functools.partial(
    jax.jit, static_argnames=("mesh", "func", "num_steps", "num_groups", "is_counter", "is_delta")
)
def distributed_sketch_quantile(
    mesh,
    func: str,
    ts, vals, lens, baseline, raw, gids,
    start_off, step_ms, window,
    num_steps: int,
    num_groups: int,
    is_counter: bool = False,
    is_delta: bool = False,
):
    """Per-shard range function -> per-shard sketch -> psum merge: the
    mesh-distributed form of quantile(q, range_fn(...)). Returns merged
    sketch [G, J, B]; the (tiny) quantile read-off happens on host."""
    from jax.sharding import PartitionSpec as P

    from . import kernels as K

    def local(ts_l, vals_l, lens_l, base_l, raw_l, gids_l):
        grid = K.range_kernel(
            func, ts_l, vals_l, lens_l, base_l, raw_l,
            start_off, step_ms, window, num_steps,
            is_counter=is_counter, is_delta=is_delta,
        )
        sk = build_sketch(grid, gids_l, num_groups)
        return jax.lax.psum(sk, "shard")

    shard = P("shard")
    row = P("shard", None)
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(row, row, shard, shard, row, shard),
        out_specs=P(),
        check=False,
    )(ts, vals, lens, baseline, raw, gids)


# kernel-observatory registration (obs/kernels.py; linted by
# tools/check_metrics.py — every jit wrapper here must register)
def _register_kernel_observatory() -> None:
    from ..obs.kernels import KERNELS

    KERNELS.register_jits(
        "ops.sketch",
        build_sketch=build_sketch,
        distributed_sketch_quantile=distributed_sketch_quantile,
    )


_register_kernel_observatory()
