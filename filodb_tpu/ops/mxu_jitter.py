"""Near-regular (jittered) grid range kernels on the MXU.

Real Prometheus scrape timestamps jitter around the scrape interval; the
exact-shared-grid MXU path (mxu_kernels.py) requires identical timestamps
across series, so jittered data used to drop to the ~40x-slower gather path.
This module keeps it on the MXU with EXACT semantics (the window-membership
contract of the reference's window iterators, PeriodicSamplesMapper.scala:256):

Staging detects blocks where every series has the same sample count and each
sample lies within half a nominal interval of a shared nominal grid
(staging.StagedBlock.nominal_ts / ts_dev / maxdev_ms). Then for any window
boundary at most ONE nominal slot has per-series-uncertain membership:

- slots with nominal time in (b + maxdev, e - maxdev] are in the window for
  EVERY series -> one shared certain-membership matrix W0 (an MXU matmul);
- the <=1 uncertain slot per boundary (klo at the lower edge, khi at the
  upper) is resolved per series from the staged deviations: its value/time
  is fetched with a one-hot MATMUL (an MXU-speed gather) and its membership
  is an elementwise compare of the deviation against the boundary offset.

So sum/count/first/last/rate/... become `certain part (shared matmul) +
per-series boundary corrections (elementwise)`, and the whole evaluation
stays matmul-dominated. Precision: boundary times are computed RELATIVE to
each window's start in f32 ms (exact below ~4.6h windows; beyond that the
sub-10ms rounding is far inside the oracle tolerance).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .staging import StagedBlock

# supported under jitter; everything else falls back to the general kernels
JITTER_FUNCS = {
    "sum_over_time", "count_over_time", "avg_over_time", "last",
    "last_over_time", "first_over_time", "present_over_time",
    "absent_over_time", "stddev_over_time", "stdvar_over_time", "z_score",
    "rate", "increase", "delta", "idelta", "irate",
    "min_over_time", "max_over_time",
}

_TILE = 16  # tile width for the min/max hierarchy (matches mxu_kernels)


class JitterWindowMatrices:
    """Host-precomputed certain/uncertain window structure for one
    (nominal grid, output grid, window) triple."""

    def __init__(self, nominal_ts: np.ndarray, n_valid: int, maxdev_ms: int,
                 start_off: int, step_ms: int, num_steps: int, window_ms: int,
                 put=None):
        R = nominal_ts[:n_valid].astype(np.int64)
        T = len(nominal_ts)
        J = num_steps
        m = n_valid
        out_t = start_off + np.arange(J, dtype=np.int64) * step_ms
        b = out_t - window_ms
        e = out_t
        md = int(maxdev_ms)
        # klo == khi (a single sample uncertain at BOTH boundaries) is only
        # possible for windows not wider than the deviation band; caller
        # falls back to the general path
        self.ok = window_ms > 2 * md
        if not self.ok:
            return
        clo = np.searchsorted(R, b + md, side="right")
        chi = np.searchsorted(R, e - md, side="right")
        count0 = np.maximum(chi - clo, 0)
        klo_a = np.searchsorted(R, b - md, side="right")
        klo_b = np.searchsorted(R, b + md, side="right")
        khi_a = np.searchsorted(R, e - md, side="right")
        khi_b = np.searchsorted(R, e + md, side="right")
        # staging guarantees 2*maxdev < min nominal interval, so each
        # boundary band contains at most one slot
        has_klo = (klo_b - klo_a) == 1
        has_khi = (khi_b - khi_a) == 1
        klo = np.where(has_klo, klo_a, 0)
        khi = np.where(has_khi, khi_a, 0)
        chi = np.minimum(chi, m)
        c0pos = count0 > 0
        c0ge2 = count0 >= 2

        tidx = np.arange(T)[:, None]
        W0 = ((tidx >= clo[None, :]) & (tidx < chi[None, :])).astype(np.float32)

        def onehot(idx, mask):
            M = np.zeros((T, J), dtype=np.float32)
            cols = np.nonzero(mask)[0]
            M[idx[cols], cols] = 1.0
            return M

        F0 = onehot(clo, c0pos)
        L0 = onehot(chi - 1, c0pos)
        L2 = onehot(chi - 2, c0ge2)
        Klo = onehot(klo, has_klo)
        Khi = onehot(khi, has_khi)
        # certain-membership matrix: the ONE matmul every sum-family function
        # needs (same cost class as the regular-grid path's W)
        self.W0 = W0
        # the five boundary/edge selections, in BOTH fetch forms: stacked
        # one-hots for an MXU matmul (TPU), and gather indices for jnp.take
        # (CPU, where a take is ~100x cheaper than the matmul). The kernel
        # slices out only the rows the requested function needs, so e.g.
        # rate never pays for an L2 fetch and count pays for no vals fetch
        # at all. Clipped positions yield garbage exactly where the one-hot
        # column is all-zero — every use is gated by the c0pos/has_* masks.
        self.SEL = np.stack([F0, L0, L2, Klo, Khi], axis=1).reshape(T, 5 * J)
        self.idx = np.stack([
            np.clip(clo, 0, T - 1),
            np.clip(chi - 1, 0, T - 1),
            np.clip(chi - 2, 0, T - 1),
            np.clip(klo, 0, T - 1),
            np.clip(khi, 0, T - 1),
        ]).astype(np.int32)

        def rel(idx, mask):
            """nominal time of slot idx relative to each window's start b."""
            r = R[np.clip(idx, 0, m - 1)] - b
            return np.where(mask, r, 0).astype(np.float32)

        self.count0 = count0.astype(np.float32)
        self.c0pos = c0pos
        self.c0ge2 = c0ge2
        self.has_klo = has_klo
        self.has_khi = has_khi
        self.F0_rel = rel(clo, c0pos)
        self.L0_rel = rel(chi - 1, c0pos)
        self.L2_rel = rel(chi - 2, c0ge2)
        self.Klo_rel = rel(klo, has_klo)
        self.Khi_rel = rel(khi, has_khi)
        # membership thresholds for the uncertain slots, as deviation bounds:
        # klo in window  <=>  ts > b  <=>  dev > b - R[klo]
        # khi in window  <=>  ts <= e <=>  dev <= e - R[khi]
        self.blo_rel = np.where(
            has_klo, b - R[np.clip(klo, 0, m - 1)], 2 * md + 1
        ).astype(np.float32)
        self.ehi_rel = np.where(
            has_khi, e - R[np.clip(khi, 0, m - 1)], -(2 * md) - 1
        ).astype(np.float32)

        # certain-range boundary indices in plain [J] form: the histogram
        # jitter variant fetches [S, J, B] rows at these SHARED indices
        # (jnp.take along T) instead of building [T, J] one-hots per bucket
        self.clo = np.clip(clo, 0, T).astype(np.int32)
        self.chi = np.clip(chi, 0, T).astype(np.int32)

        # min/max tile hierarchy + edge one-hots build LAZILY (the edge
        # matrix is [T, 2*_TILE*J] — by far the biggest structure here, and
        # only min/max_over_time reads it)
        self._clo, self._chi, self._T, self._J = clo, chi, T, J
        self._minmax_built = False

        put = self._put = put if put is not None else jax.device_put
        self.d_clo = put(self.clo)
        self.d_chi = put(self.chi)
        self.d_W0 = put(self.W0)
        self.d_SEL = put(self.SEL)
        self.d_count0 = put(self.count0)
        self.d_c0pos = put(self.c0pos)
        self.d_c0ge2 = put(self.c0ge2)
        self.d_has_klo = put(self.has_klo)
        self.d_has_khi = put(self.has_khi)
        self.d_F0_rel = put(self.F0_rel)
        self.d_L0_rel = put(self.L0_rel)
        self.d_L2_rel = put(self.L2_rel)
        self.d_Klo_rel = put(self.Klo_rel)
        self.d_Khi_rel = put(self.Khi_rel)
        self.d_blo_rel = put(self.blo_rel)
        self.d_ehi_rel = put(self.ehi_rel)
        self.d_idx = put(self.idx)

    def ensure_minmax(self):
        """min/max tile hierarchy over the certain range [clo, chi) plus
        the <=2*_TILE edge-sample selections (lazy; shared builder with the
        regular-grid matrices)."""
        if self._minmax_built:
            return
        from .mxu_kernels import build_minmax_structures

        (self.tile_mask, self.edge_onehot, self.edge_valid,
         self.edge_idx) = build_minmax_structures(
            self._clo, self._chi, self._T, self._J
        )
        put = self._put
        self.d_tile_mask = put(self.tile_mask)
        self.d_edge_onehot = put(self.edge_onehot)
        self.d_edge_valid = put(self.edge_valid)
        self.d_edge_idx = put(self.edge_idx)
        self._minmax_built = True


def _cached_window_matrices(block, cache_attr: str, nominal_ts, n_valid: int,
                            maxdev_ms: int, start_off: int, step_ms: int,
                            num_steps: int, window_ms: int) -> JitterWindowMatrices:
    """One per-block memoization discipline for both the aligned-jitter and
    masked grid sources (keyed on the query window parameters), via the
    shared keyed single-flight so racing builders construct once. A
    series-sharded block (mesh superblock) uploads the matrices REPLICATED
    across its mesh — the placement the shard_map fused program consumes,
    committed once at build (same contract as mxu_kernels.window_matrices)."""
    from ..singleflight import memo_on
    from .staging import replicated_put

    mesh = getattr(block, "placement", None)
    key = (int(start_off), int(step_ms), int(num_steps), int(window_ms))
    return memo_on(
        block, cache_attr, key,
        lambda: JitterWindowMatrices(
            np.asarray(nominal_ts), n_valid, maxdev_ms,
            start_off, step_ms, num_steps, window_ms,
            put=replicated_put(mesh) if mesh is not None else None,
        ),
    )


def jitter_window_matrices(block: StagedBlock, start_off: int, step_ms: int,
                           num_steps: int, window_ms: int) -> JitterWindowMatrices:
    return _cached_window_matrices(
        block, "_jwm_cache", block.nominal_ts, int(np.asarray(block.lens)[0]),
        block.maxdev_ms, start_off, step_ms, num_steps, window_ms,
    )


# rows of SEL / idx, by name
_F0, _L0, _L2, _KLO, _KHI = range(5)


@functools.partial(
    jax.jit, static_argnames=("func", "is_counter", "is_delta", "fetch")
)
def jitter_range_kernel(
    func: str,
    vals,  # [S, T] f32
    dev,  # [S, T] f32 per-sample deviation from the nominal grid (ms)
    raw,  # [S, T] f32 (counters; == vals otherwise)
    W0,  # [T, J] certain-membership matrix
    SEL,  # [T, 5J]: F0|L0|L2|Klo|Khi one-hot stack
    idx,  # [5, J] i32 gather-form of the same selections (or None)
    count0, c0pos, c0ge2, has_klo, has_khi,  # [J]
    F0_rel, L0_rel, L2_rel, Klo_rel, Khi_rel, blo_rel, ehi_rel,  # [J] f32
    window_ms,
    is_counter: bool = False,
    is_delta: bool = False,
    fetch: str = "auto",
):
    """Each branch fetches ONLY the selections it needs — the certain-window
    matmul (x @ W0) is paid only by the sum family, and rate/irate reduce to
    a handful of one-hot fetches + elementwise math, the same cost class as
    the regular-grid kernel. ``fetch`` picks the selection strategy: "matmul"
    (MXU one-hots), "gather" (jnp.take — far cheaper on CPU), or "auto"
    (backend-chosen at trace time)."""
    f32 = jnp.float32
    nan = jnp.nan
    from .mxu_kernels import use_gather_fetch

    S, T = vals.shape
    J = W0.shape[1]
    use_gather = use_gather_fetch(fetch, idx)
    # gather mode: ONE five-row gather per source plane, memoized at trace
    # time — XLA's CPU gather streams the source plane per op, so two
    # gathers of different rows from one plane cost two plane passes while
    # the full [5, J] index set costs barely more than either (5*S*J
    # fetched vs the S*T plane read). Branches slice the rows they need;
    # values are bit-identical to per-row gathers.
    _planes: dict = {}

    def sel(x, rows):
        """Fetch the named selection rows of x as [S, len(rows), J]."""
        r = np.array(rows)
        if use_gather:
            full = _planes.get(id(x))
            if full is None:
                full = jnp.take(x, idx.reshape(-1), axis=1).reshape(S, 5, J)
                _planes[id(x)] = full
            return full[:, r, :]
        M = SEL.reshape(T, 5, J)[:, r, :].reshape(T, len(rows) * J)
        a = jax.lax.dot(x, M, precision=jax.lax.Precision.HIGHEST)
        return a.reshape(S, len(rows), J)

    def mmW0(x):
        return jax.lax.dot(x, W0, precision=jax.lax.Precision.HIGHEST)

    # boundary membership: needed by every function
    dKlo, dKhi = (a for a in sel(dev, (_KLO, _KHI)).swapaxes(0, 1))
    in_lo = has_klo[None, :] & (dKlo > blo_rel[None, :])
    in_hi = has_khi[None, :] & (dKhi <= ehi_rel[None, :])
    cnt = count0[None, :] + in_lo + in_hi
    has = cnt > 0
    w_s = window_ms.astype(f32) * 1e-3

    def w3(m1, a, m2, b_, c):
        return jnp.where(m1, a, jnp.where(m2, b_, c))

    # the one definition of the ordered last-sample selection rule
    # ([klo?] certain[clo..chi) [khi?]); first/prev variants stay inline at
    # their single use sites
    def vlast(vL0, vKlo, vKhi):
        return w3(in_hi, vKhi, c0pos[None, :], vL0, vKlo)

    def tlast(dL0):
        return w3(in_hi, Khi_rel[None, :] + dKhi, c0pos[None, :],
                  L0_rel[None, :] + dL0, Klo_rel[None, :] + dKlo)

    if func == "sum_over_time" or (is_delta and func in ("rate", "increase")):
        vKlo, vKhi = (a for a in sel(vals, (_KLO, _KHI)).swapaxes(0, 1))
        s = mmW0(vals) + jnp.where(in_lo, vKlo, 0.0) + jnp.where(in_hi, vKhi, 0.0)
        if func == "rate":
            s = s / w_s
        return jnp.where(has, s, nan)
    if func == "count_over_time":
        return jnp.where(has, cnt, nan)
    if func == "avg_over_time":
        vKlo, vKhi = (a for a in sel(vals, (_KLO, _KHI)).swapaxes(0, 1))
        s = mmW0(vals) + jnp.where(in_lo, vKlo, 0.0) + jnp.where(in_hi, vKhi, 0.0)
        return jnp.where(has, s / jnp.maximum(cnt, 1.0), nan)
    if func == "present_over_time":
        return jnp.where(has, 1.0, nan)
    if func == "absent_over_time":
        return jnp.where(has, nan, 1.0)
    if func in ("stddev_over_time", "stdvar_over_time", "z_score"):
        if func == "z_score":
            vL0, vKlo, vKhi = (
                a for a in sel(vals, (_L0, _KLO, _KHI)).swapaxes(0, 1)
            )
        else:
            vKlo, vKhi = (a for a in sel(vals, (_KLO, _KHI)).swapaxes(0, 1))
        s = mmW0(vals) + jnp.where(in_lo, vKlo, 0.0) + jnp.where(in_hi, vKhi, 0.0)
        s2 = (
            mmW0(vals * vals)
            + jnp.where(in_lo, vKlo * vKlo, 0.0)
            + jnp.where(in_hi, vKhi * vKhi, 0.0)
        )
        c = jnp.maximum(cnt, 1.0)
        mean = s / c
        var = jnp.maximum(s2 / c - mean * mean, 0.0)
        if func == "stdvar_over_time":
            return jnp.where(has, var, nan)
        sd = jnp.sqrt(var)
        if func == "stddev_over_time":
            return jnp.where(has, sd, nan)
        return jnp.where(
            has, (vlast(vL0, vKlo, vKhi) - mean) / jnp.maximum(sd, 1e-30), nan
        )

    # ordered in-window sample selection: [klo?] certain[clo..chi) [khi?]
    if func == "first_over_time":
        vF0, vKlo, vKhi = (
            a for a in sel(vals, (_F0, _KLO, _KHI)).swapaxes(0, 1)
        )
        return jnp.where(has, w3(in_lo, vKlo, c0pos[None, :], vF0, vKhi), nan)
    if func in ("last", "last_over_time"):
        vL0, vKlo, vKhi = (
            a for a in sel(vals, (_L0, _KLO, _KHI)).swapaxes(0, 1)
        )
        return jnp.where(has, vlast(vL0, vKlo, vKhi), nan)
    if func in ("rate", "increase", "delta"):
        vF0, vL0, vKlo, vKhi = (
            a for a in sel(vals, (_F0, _L0, _KLO, _KHI)).swapaxes(0, 1)
        )
        dF0, dL0 = (a for a in sel(dev, (_F0, _L0)).swapaxes(0, 1))
        v_first = w3(in_lo, vKlo, c0pos[None, :], vF0, vKhi)
        v_last = vlast(vL0, vKlo, vKhi)
        tf_rel = w3(in_lo, Klo_rel[None, :] + dKlo, c0pos[None, :],
                    F0_rel[None, :] + dF0, Khi_rel[None, :] + dKhi)
        tl_rel = tlast(dL0)
        dlt = v_last - v_first
        sampled = (tl_rel - tf_rel) * 1e-3
        dur_start = tf_rel * 1e-3
        dur_end = (window_ms.astype(f32) - tl_rel) * 1e-3
        avg_dur = sampled / jnp.maximum(cnt - 1.0, 1.0)
        thresh = avg_dur * 1.1
        if is_counter and func != "delta":
            rF0, rKlo, rKhi = (
                a for a in sel(raw, (_F0, _KLO, _KHI)).swapaxes(0, 1)
            )
            v_first_raw = w3(in_lo, rKlo, c0pos[None, :], rF0, rKhi)
            dur_zero = jnp.where(
                dlt > 0, sampled * (v_first_raw / jnp.maximum(dlt, 1e-30)), jnp.inf
            )
            ds = jnp.minimum(dur_start, jnp.where(v_first_raw >= 0, dur_zero, jnp.inf))
        else:
            ds = dur_start
        ds = jnp.where(ds >= thresh, avg_dur / 2.0, ds)
        de = jnp.where(dur_end >= thresh, avg_dur / 2.0, dur_end)
        factor = (sampled + ds + de) / jnp.maximum(sampled, 1e-30)
        res = dlt * factor
        if func == "rate":
            res = res / w_s
        return jnp.where(cnt >= 2, res, nan)
    if func in ("irate", "idelta"):
        ok2 = cnt >= 2
        if func == "idelta" and is_counter and not is_delta:
            # diff-encoded counters: the staged value AT the last in-window
            # sample is already the f64-exact last-pair difference
            vL0, vKlo, vKhi = (
                a for a in sel(vals, (_L0, _KLO, _KHI)).swapaxes(0, 1)
            )
            return jnp.where(ok2, vlast(vL0, vKlo, vKhi), nan)
        vL0, vL2, vKlo, vKhi = (
            a for a in sel(vals, (_L0, _L2, _KLO, _KHI)).swapaxes(0, 1)
        )
        v_last = vlast(vL0, vKlo, vKhi)
        dL0, dL2 = (a for a in sel(dev, (_L0, _L2)).swapaxes(0, 1))
        tl_rel = tlast(dL0)
        v_prev = jnp.where(
            in_hi,
            jnp.where(c0pos[None, :], vL0, vKlo),
            jnp.where(c0ge2[None, :], vL2, vKlo),
        )
        tp_rel = jnp.where(
            in_hi,
            jnp.where(c0pos[None, :], L0_rel[None, :] + dL0, Klo_rel[None, :] + dKlo),
            jnp.where(c0ge2[None, :], L2_rel[None, :] + dL2, Klo_rel[None, :] + dKlo),
        )
        dt_s = (tl_rel - tp_rel) * 1e-3
        dv = v_last - v_prev
        r = dv / jnp.maximum(dt_s, 1e-30) if func == "irate" else dv
        return jnp.where(ok2, r, nan)
    raise ValueError(f"jitter kernel does not support {func}")


@functools.partial(jax.jit, static_argnames=("n_valid", "is_min", "fetch"))
def jitter_minmax(vals, dev, SEL, idx, tile_mask, edge_onehot, edge_valid,
                  edge_idx, count0, has_klo, has_khi, blo_rel, ehi_rel,
                  n_valid: int, is_min: bool = True, fetch: str = "auto"):
    """min/max over the certain range via the tile hierarchy + edge one-hots
    (mxu_kernels.mxu_minmax structure), then fold in the <=2 per-series
    uncertain boundary samples. ``fetch`` as in jitter_range_kernel."""
    from .mxu_kernels import use_gather_fetch

    S, T = vals.shape
    Lt = _TILE
    J = tile_mask.shape[0]
    use_gather = use_gather_fetch(fetch, idx)
    v = vals if is_min else -vals
    sentinel = jnp.float32(3e38)
    lane = jax.lax.broadcasted_iota(jnp.int32, (S, T), 1)
    vm = jnp.where(lane < n_valid, v, sentinel)
    tmin = vm.reshape(S, T // Lt, Lt).min(-1)
    certain = jnp.where(tile_mask[None, :, :], tmin[:, None, :], sentinel).min(-1)
    if use_gather and edge_idx is not None:
        edges = jnp.take(vm, edge_idx.reshape(-1), axis=1)
    else:
        edges = jax.lax.dot(vm, edge_onehot, precision=jax.lax.Precision.HIGHEST)
    edges = edges.reshape(S, J, 2 * Lt)
    edges = jnp.where(edge_valid[None, :, :], edges, sentinel).min(-1)
    r = jnp.minimum(certain, edges)

    def sel_kk(x):
        if use_gather:
            return jnp.take(x, idx[3:5].reshape(-1), axis=1).reshape(S, 2, J)
        M = SEL.reshape(T, 5, J)[:, 3:5, :].reshape(T, 2 * J)
        return jax.lax.dot(
            x, M, precision=jax.lax.Precision.HIGHEST
        ).reshape(S, 2, J)

    A = sel_kk(v)
    vKlo, vKhi = A[:, 0, :], A[:, 1, :]
    D = sel_kk(dev)
    dKlo, dKhi = D[:, 0, :], D[:, 1, :]
    in_lo = has_klo[None, :] & (dKlo > blo_rel[None, :])
    in_hi = has_khi[None, :] & (dKhi <= ehi_rel[None, :])
    r = jnp.minimum(r, jnp.where(in_lo, vKlo, sentinel))
    r = jnp.minimum(r, jnp.where(in_hi, vKhi, sentinel))
    cnt = count0[None, :] + in_lo + in_hi
    r = r if is_min else -r
    return jnp.where(cnt > 0, r, jnp.nan)


@functools.partial(
    jax.jit, static_argnames=("func", "is_counter", "is_delta", "fetch")
)
def jitter_masked_kernel(
    func: str,
    vals,  # [S, T] f32 slot-aligned, 0 at holes
    dev,  # [S, T] f32 deviation from nominal, 0 at holes
    raw,  # [S, T] f32 raw (counters; == vals otherwise)
    valid,  # [S, T] f32 1.0 = real sample
    cc,  # [S, T] f32 cumulative valid count
    ffv, ffd, bfv, bfd, ff2v, ff2d, bfraw,  # [S, T] host-precomputed fills
    W0,  # [T, J]
    SEL,  # [T, 5J]
    idx,  # [5, J] i32 or None
    c0pos_g,  # [J] bool: grid-level certain range non-empty
    has_klo, has_khi,  # [J] bool
    F0_rel, L0_rel, Klo_rel, Khi_rel, blo_rel, ehi_rel,  # [J] f32
    window_ms,
    is_counter: bool = False,
    is_delta: bool = False,
    fetch: str = "auto",
    maxdev=None,
):
    """Missing-scrape variant of jitter_range_kernel: per-slot validity masks
    replace the equal-count assumption. Per-series window counts come from
    the validity prefix sum (cc[chi-1] - cc[clo] + valid[clo], shared-index
    fetches — no extra matmul), and first/last selections read the
    host-precomputed forward/backward fills at SHARED slot indices — so a
    dropped scrape costs a few fetches, not a fall to the general path.
    Same window-semantics contract: PeriodicSamplesMapper.scala:256.

    With ``maxdev`` (the grid's maxdev_ms) the GATHER mode runs a LEAN
    fetch plan exploiting the time-fill invariant (staging.masked_fills):
    at a valid slot ffd == bfd == dev (|.| <= maxdev) while a hole pushes
    ffd below -maxdev and bfd above it, so boundary membership, slot
    validity and the boundary values all come from the fill planes — no
    validity fetches at all, and the hot counter-rate path drops from 11
    gather ops over 16 rows to 6 ops over 14 rows. Selected values are bit-identical to the classic plan
    (fills COPY the staged values at valid slots), so gather-vs-matmul
    parity is preserved; gathers on the CPU backend are the dominant cost
    of this kernel, which is what the jitter+holes bench ratio gates."""
    from .mxu_kernels import use_gather_fetch

    f32 = jnp.float32
    nan = jnp.nan
    S, T = vals.shape
    J = W0.shape[1]
    use_gather = use_gather_fetch(fetch, idx)
    lean = use_gather and maxdev is not None
    # exact-row gather memo: gathers dominate this kernel's cost on CPU
    # (roughly linear in fetched rows, with a per-op floor), so identical
    # (plane, rows) fetches dedup at trace time and the LEAN plan below
    # fetches each plane's row UNION once
    _memo: dict = {}

    def sel(x, rows):
        r = np.array(rows)
        if use_gather:
            key = (id(x), tuple(rows))
            got = _memo.get(key)
            if got is None:
                got = jnp.take(x, idx[r].reshape(-1), axis=1).reshape(
                    S, len(rows), J)
                _memo[key] = got
            return got
        M = SEL.reshape(T, 5, J)[:, r, :].reshape(T, len(rows) * J)
        a = jax.lax.dot(x, M, precision=jax.lax.Precision.HIGHEST)
        return a.reshape(S, len(rows), J)

    def mmW0(x):
        return jax.lax.dot(x, W0, precision=jax.lax.Precision.HIGHEST)

    if lean:
        # membership + validity from the time fills alone: ffd@klo is dev
        # at a valid klo and <= -(interval - maxdev) < blo_rel at a hole
        # (symmetrically bfd@khi vs ehi_rel), and |ffd@clo| <= maxdev is
        # exactly valid[clo]. Fetch each plane's full row union here —
        # the rate family reuses ffd@L0 / bfd@F0 for its window-edge
        # times, and the sel memo makes the reuse free
        Fd = sel(ffd, (_F0, _L0, _KLO))
        ffdF0, ffdL0, dKlo = Fd[:, 0, :], Fd[:, 1, :], Fd[:, 2, :]
        Bd = sel(bfd, (_F0, _KHI))
        bfdF0, dKhi = Bd[:, 0, :], Bd[:, 1, :]
        in_lo = has_klo[None, :] & (dKlo > blo_rel[None, :])
        in_hi = has_khi[None, :] & (dKhi <= ehi_rel[None, :])
        vaF0 = jnp.where(jnp.abs(ffdF0) <= maxdev, f32(1.0), f32(0.0))
    else:
        dKlo, dKhi = (a for a in sel(dev, (_KLO, _KHI)).swapaxes(0, 1))
        vaKlo, vaKhi = (a for a in sel(valid, (_KLO, _KHI)).swapaxes(0, 1))
        in_lo = has_klo[None, :] & (dKlo > blo_rel[None, :]) & (vaKlo > 0)
        in_hi = has_khi[None, :] & (dKhi <= ehi_rel[None, :]) & (vaKhi > 0)
        vaF0 = sel(valid, (_F0,))[:, 0, :]
    # per-series certain-range sample count from the validity prefix sum:
    # count over [clo, chi) = cc[chi-1] - cc[clo] + valid[clo]; the gather
    # form reads clipped garbage where the grid's certain range is empty, so
    # gate on the grid-level c0pos (the matmul's zero columns do the same)
    ccF0, ccL0 = (a for a in sel(cc, (_F0, _L0)).swapaxes(0, 1))
    cnt0v = jnp.where(c0pos_g[None, :], ccL0 - ccF0 + vaF0, 0.0)
    cnt = cnt0v + in_lo + in_hi
    has = cnt > 0
    c0pos = cnt0v > 0
    c0ge2 = cnt0v >= 2
    w_s = window_ms.astype(f32) * 1e-3

    def w3(m1, a, m2, b_, c):
        return jnp.where(m1, a, jnp.where(m2, b_, c))

    def vlast(vL0f, vKlo, vKhi):
        return w3(in_hi, vKhi, c0pos, vL0f, vKlo)

    if func == "sum_over_time" or (is_delta and func in ("rate", "increase")):
        vKlo, vKhi = (a for a in sel(vals, (_KLO, _KHI)).swapaxes(0, 1))
        s = mmW0(vals) + jnp.where(in_lo, vKlo, 0.0) + jnp.where(in_hi, vKhi, 0.0)
        if func == "rate":
            s = s / w_s
        return jnp.where(has, s, nan)
    if func == "count_over_time":
        return jnp.where(has, cnt, nan)
    if func == "avg_over_time":
        vKlo, vKhi = (a for a in sel(vals, (_KLO, _KHI)).swapaxes(0, 1))
        s = mmW0(vals) + jnp.where(in_lo, vKlo, 0.0) + jnp.where(in_hi, vKhi, 0.0)
        return jnp.where(has, s / jnp.maximum(cnt, 1.0), nan)
    if func == "present_over_time":
        return jnp.where(has, 1.0, nan)
    if func == "absent_over_time":
        return jnp.where(has, nan, 1.0)
    if func in ("stddev_over_time", "stdvar_over_time", "z_score"):
        vKlo, vKhi = (a for a in sel(vals, (_KLO, _KHI)).swapaxes(0, 1))
        s = mmW0(vals) + jnp.where(in_lo, vKlo, 0.0) + jnp.where(in_hi, vKhi, 0.0)
        s2 = (
            mmW0(vals * vals)
            + jnp.where(in_lo, vKlo * vKlo, 0.0)
            + jnp.where(in_hi, vKhi * vKhi, 0.0)
        )
        c = jnp.maximum(cnt, 1.0)
        mean = s / c
        var = jnp.maximum(s2 / c - mean * mean, 0.0)
        if func == "stdvar_over_time":
            return jnp.where(has, var, nan)
        sd = jnp.sqrt(var)
        if func == "stddev_over_time":
            return jnp.where(has, sd, nan)
        ffvL0 = sel(ffv, (_L0,))[:, 0, :]
        v_last = vlast(ffvL0, vKlo, vKhi)
        return jnp.where(has, (v_last - mean) / jnp.maximum(sd, 1e-30), nan)
    if func == "first_over_time":
        vKlo, vKhi = (a for a in sel(vals, (_KLO, _KHI)).swapaxes(0, 1))
        bfvF0 = sel(bfv, (_F0,))[:, 0, :]
        return jnp.where(has, w3(in_lo, vKlo, c0pos, bfvF0, vKhi), nan)
    if func in ("last", "last_over_time"):
        vKlo, vKhi = (a for a in sel(vals, (_KLO, _KHI)).swapaxes(0, 1))
        ffvL0 = sel(ffv, (_L0,))[:, 0, :]
        return jnp.where(has, vlast(ffvL0, vKlo, vKhi), nan)
    if func in ("rate", "increase", "delta"):
        if lean:
            # the backward fill at a VALID klo/khi IS the staged value
            # there (fills copy), so ONE bfv fetch serves all three
            # first/last selection sources; every selected site is valid
            # by its gate, so values stay bit-identical to the classic
            # plan. The window-edge times (bfd@F0, ffd@L0) were already
            # fetched with the membership rows above.
            Bv = sel(bfv, (_F0, _KLO, _KHI))
            bfvF0, vKlo, vKhi = Bv[:, 0, :], Bv[:, 1, :], Bv[:, 2, :]
        else:
            vKlo, vKhi = (a for a in sel(vals, (_KLO, _KHI)).swapaxes(0, 1))
            bfvF0 = sel(bfv, (_F0,))[:, 0, :]
            bfdF0 = sel(bfd, (_F0,))[:, 0, :]
            ffdL0 = sel(ffd, (_L0,))[:, 0, :]
        ffvL0 = sel(ffv, (_L0,))[:, 0, :]
        v_first = w3(in_lo, vKlo, c0pos, bfvF0, vKhi)
        v_last = vlast(ffvL0, vKlo, vKhi)
        tf_rel = w3(in_lo, Klo_rel[None, :] + dKlo, c0pos,
                    F0_rel[None, :] + bfdF0, Khi_rel[None, :] + dKhi)
        tl_rel = w3(in_hi, Khi_rel[None, :] + dKhi, c0pos,
                    L0_rel[None, :] + ffdL0, Klo_rel[None, :] + dKlo)
        dlt = v_last - v_first
        sampled = (tl_rel - tf_rel) * 1e-3
        dur_start = tf_rel * 1e-3
        dur_end = (window_ms.astype(f32) - tl_rel) * 1e-3
        avg_dur = sampled / jnp.maximum(cnt - 1.0, 1.0)
        thresh = avg_dur * 1.1
        if is_counter and func != "delta":
            if lean:
                Br = sel(bfraw, (_F0, _KLO, _KHI))
                bfrawF0, rKlo, rKhi = Br[:, 0, :], Br[:, 1, :], Br[:, 2, :]
            else:
                rKlo, rKhi = (a for a in sel(raw, (_KLO, _KHI)).swapaxes(0, 1))
                bfrawF0 = sel(bfraw, (_F0,))[:, 0, :]
            v_first_raw = w3(in_lo, rKlo, c0pos, bfrawF0, rKhi)
            dur_zero = jnp.where(
                dlt > 0, sampled * (v_first_raw / jnp.maximum(dlt, 1e-30)), jnp.inf
            )
            ds = jnp.minimum(dur_start, jnp.where(v_first_raw >= 0, dur_zero, jnp.inf))
        else:
            ds = dur_start
        ds = jnp.where(ds >= thresh, avg_dur / 2.0, ds)
        de = jnp.where(dur_end >= thresh, avg_dur / 2.0, dur_end)
        factor = (sampled + ds + de) / jnp.maximum(sampled, 1e-30)
        res = dlt * factor
        if func == "rate":
            res = res / w_s
        return jnp.where(cnt >= 2, res, nan)
    if func in ("irate", "idelta"):
        ok2 = cnt >= 2
        vKlo, vKhi = (a for a in sel(vals, (_KLO, _KHI)).swapaxes(0, 1))
        ffvL0 = sel(ffv, (_L0,))[:, 0, :]
        v_last = vlast(ffvL0, vKlo, vKhi)
        if func == "idelta" and is_counter and not is_delta:
            # diff-encoded counters: the staged value AT the last in-window
            # sample is already the f64-exact last-pair difference
            return jnp.where(ok2, v_last, nan)
        ffdL0 = sel(ffd, (_L0,))[:, 0, :]
        ff2vL0 = sel(ff2v, (_L0,))[:, 0, :]
        ff2dL0 = sel(ff2d, (_L0,))[:, 0, :]
        tl_rel = w3(in_hi, Khi_rel[None, :] + dKhi, c0pos,
                    L0_rel[None, :] + ffdL0, Klo_rel[None, :] + dKlo)
        v_prev = jnp.where(
            in_hi,
            jnp.where(c0pos, ffvL0, vKlo),
            jnp.where(c0ge2, ff2vL0, vKlo),
        )
        tp_rel = jnp.where(
            in_hi,
            jnp.where(c0pos, L0_rel[None, :] + ffdL0, Klo_rel[None, :] + dKlo),
            jnp.where(c0ge2, L0_rel[None, :] + ff2dL0, Klo_rel[None, :] + dKlo),
        )
        dt_s = (tl_rel - tp_rel) * 1e-3
        dv = v_last - v_prev
        r = dv / jnp.maximum(dt_s, 1e-30) if func == "irate" else dv
        return jnp.where(ok2, r, nan)
    raise ValueError(f"masked jitter kernel does not support {func}")


@functools.partial(jax.jit, static_argnames=("is_min", "fetch"))
def jitter_masked_minmax(vals, dev, valid, cc, SEL, idx, tile_mask,
                         edge_onehot, edge_valid, edge_idx, c0pos_g,
                         has_klo, has_khi, blo_rel, ehi_rel,
                         is_min: bool = True, fetch: str = "auto"):
    """Missing-scrape min/max: validity-masked tile hierarchy + edge fetches
    over the certain range, then the <=2 per-series boundary samples. Holes
    carry the sentinel, so validity gating is automatic for value fetches."""
    from .mxu_kernels import use_gather_fetch

    S, T = vals.shape
    Lt = _TILE
    J = tile_mask.shape[0]
    use_gather = use_gather_fetch(fetch, idx)
    v = vals if is_min else -vals
    sentinel = jnp.float32(3e38)
    vm = jnp.where(valid > 0, v, sentinel)
    tmin = vm.reshape(S, T // Lt, Lt).min(-1)
    certain = jnp.where(tile_mask[None, :, :], tmin[:, None, :], sentinel).min(-1)
    if use_gather and edge_idx is not None:
        edges = jnp.take(vm, edge_idx.reshape(-1), axis=1)
    else:
        # matmul fetch reads 0 at holes, not the sentinel: re-mask with a
        # fetched validity so holes can't contaminate the minimum
        edges = jax.lax.dot(vm * jnp.where(valid > 0, 1.0, 0.0), edge_onehot,
                            precision=jax.lax.Precision.HIGHEST)
        eva = jax.lax.dot(valid, edge_onehot,
                          precision=jax.lax.Precision.HIGHEST)
        edges = jnp.where(eva > 0, edges, sentinel)
    edges = edges.reshape(S, J, 2 * Lt)
    edges = jnp.where(edge_valid[None, :, :], edges, sentinel).min(-1)
    r = jnp.minimum(certain, edges)

    def sel_rows(x, lo, hi):
        if use_gather:
            return jnp.take(x, idx[lo:hi].reshape(-1), axis=1).reshape(
                S, hi - lo, J)
        M = SEL.reshape(T, 5, J)[:, lo:hi, :].reshape(T, (hi - lo) * J)
        return jax.lax.dot(
            x, M, precision=jax.lax.Precision.HIGHEST
        ).reshape(S, hi - lo, J)

    def sel_kk(x):
        return sel_rows(x, 3, 5)

    D = sel_kk(dev)
    dKlo, dKhi = D[:, 0, :], D[:, 1, :]
    VA = sel_kk(valid)
    vaKlo, vaKhi = VA[:, 0, :], VA[:, 1, :]
    in_lo = has_klo[None, :] & (dKlo > blo_rel[None, :]) & (vaKlo > 0)
    in_hi = has_khi[None, :] & (dKhi <= ehi_rel[None, :]) & (vaKhi > 0)
    A = sel_kk(v)
    vKlo, vKhi = A[:, 0, :], A[:, 1, :]
    r = jnp.minimum(r, jnp.where(in_lo, vKlo, sentinel))
    r = jnp.minimum(r, jnp.where(in_hi, vKhi, sentinel))
    # per-series certain count via the validity prefix sum (see
    # jitter_masked_kernel)
    CF = sel_rows(cc, 0, 2)
    vaF0 = sel_rows(valid, 0, 1)[:, 0, :]
    cnt0v = jnp.where(
        c0pos_g[None, :], CF[:, 1, :] - CF[:, 0, :] + vaF0, 0.0
    )
    cnt = cnt0v + in_lo + in_hi
    r = r if is_min else -r
    return jnp.where(cnt > 0, r, jnp.nan)


def masked_window_matrices(block: StagedBlock, start_off: int, step_ms: int,
                           num_steps: int, window_ms: int) -> JitterWindowMatrices:
    g = block.mgrid
    return _cached_window_matrices(
        block, "_mwm_cache", g.nominal_ts, g.n_valid, g.maxdev_ms,
        start_off, step_ms, num_steps, window_ms,
    )


def run_masked_jitter_range_function(func, block: StagedBlock, params,
                                     is_counter=False, is_delta=False,
                                     args=()):
    """Entry: dispatch one missing-scrape range function over block.mgrid.
    Returns a device array [S, J_padded], or None when this (window, grid)
    combination can't use the masked path (caller falls back)."""
    from .kernels import pad_steps
    from .mxu_kernels import fetch_strategy

    g = block.mgrid
    J = pad_steps(params.num_steps)
    start_off = int(params.start_ms - block.base_ms)
    wm = masked_window_matrices(block, start_off, params.step_ms, J,
                                params.window_ms)
    if not wm.ok:
        return None
    fetch = fetch_strategy()
    if func in ("min_over_time", "max_over_time"):
        wm.ensure_minmax()
        return jitter_masked_minmax(
            g.vals, g.dev, g.valid, g.cc, wm.d_SEL, wm.d_idx,
            wm.d_tile_mask, wm.d_edge_onehot, wm.d_edge_valid, wm.d_edge_idx,
            wm.d_c0pos, wm.d_has_klo, wm.d_has_khi, wm.d_blo_rel,
            wm.d_ehi_rel,
            is_min=(func == "min_over_time"), fetch=fetch,
        )
    raw = g.raw if g.raw is not None else g.vals
    bfraw = g.bfraw if g.bfraw is not None else g.bfv
    return jitter_masked_kernel(
        func, g.vals, g.dev, raw, g.valid, g.cc,
        g.ffv, g.ffd, g.bfv, g.bfd, g.ff2v, g.ff2d, bfraw,
        wm.d_W0, wm.d_SEL, wm.d_idx,
        wm.d_c0pos, wm.d_has_klo, wm.d_has_khi,
        wm.d_F0_rel, wm.d_L0_rel, wm.d_Klo_rel, wm.d_Khi_rel,
        wm.d_blo_rel, wm.d_ehi_rel,
        np.float32(params.window_ms),
        is_counter=is_counter, is_delta=is_delta, fetch=fetch,
        maxdev=np.float32(g.maxdev_ms),
    )


def run_jitter_range_function(func, block: StagedBlock, params,
                              is_counter=False, is_delta=False, args=()):
    """Entry: dispatch one jittered-grid range function. Returns a device
    array [S, J_padded], or None when this (window, grid) combination can't
    use the jitter path (caller falls back to the general kernels)."""
    from .kernels import pad_steps

    J = pad_steps(params.num_steps)
    start_off = int(params.start_ms - block.base_ms)
    wm = jitter_window_matrices(block, start_off, params.step_ms, J, params.window_ms)
    if not wm.ok:
        return None
    from .mxu_kernels import fetch_strategy

    dev = block.ts_dev
    fetch = fetch_strategy()
    if func in ("min_over_time", "max_over_time"):
        wm.ensure_minmax()
        return jitter_minmax(
            jnp.asarray(block.vals), dev, wm.d_SEL, wm.d_idx, wm.d_tile_mask,
            wm.d_edge_onehot, wm.d_edge_valid, wm.d_edge_idx, wm.d_count0,
            wm.d_has_klo, wm.d_has_khi, wm.d_blo_rel, wm.d_ehi_rel,
            n_valid=int(np.asarray(block.lens)[0]),
            is_min=(func == "min_over_time"),
            fetch=fetch,
        )
    raw = block.raw if block.raw is not None else block.vals
    return jitter_range_kernel(
        func,
        block.vals,
        dev,
        raw,
        wm.d_W0,
        wm.d_SEL,
        wm.d_idx,
        wm.d_count0, wm.d_c0pos, wm.d_c0ge2, wm.d_has_klo, wm.d_has_khi,
        wm.d_F0_rel, wm.d_L0_rel, wm.d_L2_rel, wm.d_Klo_rel, wm.d_Khi_rel,
        wm.d_blo_rel, wm.d_ehi_rel,
        np.float32(params.window_ms),
        is_counter=is_counter,
        is_delta=is_delta,
        fetch=fetch,
    )


# kernel-observatory registration (obs/kernels.py; linted by
# tools/check_metrics.py — every jit wrapper here must register)
def _register_kernel_observatory() -> None:
    from ..obs.kernels import KERNELS

    KERNELS.register_jits(
        "ops.mxu_jitter",
        jitter_range_kernel=jitter_range_kernel,
        jitter_minmax=jitter_minmax,
        jitter_masked_kernel=jitter_masked_kernel,
        jitter_masked_minmax=jitter_masked_minmax,
    )


_register_kernel_observatory()
