"""Regular-grid range kernels on the MXU.

When every staged series shares one timestamp vector (the overwhelmingly
common case for scraped metrics — one batch, one interval), the per-window
sample-membership and boundary-selection matrices are series-INDEPENDENT:

    sum_over_time  = vals @ W        W[t, j] = 1 if sample t in window j
    v_first        = vals @ F        F = one-hot of each window's first sample
    v_last         = vals @ L        L = one-hot of each window's last sample

i.e. the whole range-function evaluation becomes a handful of [S,T] x [T,J]
matmuls — exactly what the TPU MXU systolic array is built for — instead of
the gather/scatter-heavy general path (kernels.py), which this backend
executes orders of magnitude slower. The [T, J] matrices are built host-side
per query in O(T·J) (sub-millisecond) and cached on the staged block.

This is the TPU-first answer to the reference's chunked range functions
(rangefn/RangeFunction.scala:84): their per-chunk running aggregates exploit
chunk layout; we exploit the shared scrape grid.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .staging import StagedBlock

# functions the MXU path supports; everything else falls back to the
# general kernel
MXU_FUNCS = {
    "sum_over_time", "count_over_time", "avg_over_time", "last",
    "last_over_time", "first_over_time", "present_over_time",
    "absent_over_time", "timestamp", "stddev_over_time", "stdvar_over_time",
    "z_score", "rate", "increase", "delta", "idelta", "irate", "changes",
    "resets", "deriv", "predict_linear", "min_over_time", "max_over_time",
}

_TILE = 16  # tile width for the min/max hierarchy


def build_minmax_structures(lo, hi, T: int, J: int):
    """The ONE builder for the min/max window structure shared by the
    regular-grid and jittered-grid matrices: per-window full-_TILE tile
    masks plus the <=2*_TILE edge-sample one-hots/indices over the certain
    range [lo, hi) per window. Returns (tile_mask [J, T/_TILE],
    edge_onehot [T, J*2*_TILE], edge_valid [J, 2*_TILE], edge_idx i32)."""
    Lt = _TILE
    n_tiles = T // Lt
    t_lo = -(-lo // Lt)  # ceil
    t_hi = hi // Lt
    full = np.arange(n_tiles)[None, :]
    tile_mask = (
        (full >= t_lo[:, None]) & (full < t_hi[:, None]) & (t_lo < t_hi)[:, None]
    )
    E = np.zeros((T, J * 2 * Lt), dtype=np.float32)
    edge_valid = np.zeros((J, 2 * Lt), dtype=bool)
    edge_idx = np.zeros((J, 2 * Lt), dtype=np.int32)
    for j in range(J):
        if hi[j] <= lo[j]:
            continue
        if t_lo[j] >= t_hi[j]:  # window inside <2 tiles: all samples are edges
            left = np.arange(lo[j], hi[j])
            right = np.empty(0, dtype=np.int64)
        else:
            left = np.arange(lo[j], t_lo[j] * Lt)
            right = np.arange(t_hi[j] * Lt, hi[j])
        for slot, pos in enumerate(np.concatenate([left, right])[: 2 * Lt]):
            E[pos, j * 2 * Lt + slot] = 1.0
            edge_valid[j, slot] = True
            edge_idx[j, slot] = pos
    return tile_mask, E, edge_valid, edge_idx


def fetch_strategy(override: str | None = None) -> str:
    """Resolve the one-hot-selection fetch strategy for the MXU kernels.

    "matmul" fetches via one-hot matmuls (MXU-speed gathers on TPU);
    "gather" via jnp.take (~100x cheaper on the CPU backend); "auto" picks
    per backend at trace time. FILODB_MXU_FETCH forces a strategy globally —
    the parity test suite uses it to execute the TPU matmul path on CPU.
    The result is a static jit argument, so a forced run never reuses a
    cached auto-mode executable."""
    import os

    f = override or os.environ.get("FILODB_MXU_FETCH", "auto")
    if f not in ("auto", "matmul", "gather"):
        raise ValueError(f"bad fetch strategy {f!r}")
    return f


def use_gather_fetch(fetch: str, idx) -> bool:
    """Resolve a fetch strategy to a concrete choice at trace time (the one
    shared rule for all MXU kernels): gather when forced, or in auto mode on
    the CPU backend where jnp.take beats the one-hot matmul. A forced
    "gather" at a call site that supplies no gather indices is a miswiring —
    raise rather than silently compare the matmul path against itself."""
    if idx is None:
        if fetch == "gather":
            raise ValueError(
                "fetch='gather' forced but this call site provides no gather "
                "indices (idx=None)"
            )
        return False
    return fetch == "gather" or (
        fetch == "auto" and jax.default_backend() == "cpu"
    )


class WindowMatrices:
    """Host-precomputed per-(grid, window) matrices for one shared ts.

    ``put`` overrides the device placement of every device-resident copy
    (default: plain device_put). A series-sharded block passes a
    mesh-REPLICATED put so the matrices upload once with the placement the
    shard_map program wants — never a dead single-device copy."""

    def __init__(self, ts1: np.ndarray, n_valid: int, start_off: int, step_ms: int,
                 num_steps: int, window_ms: int, put=None):
        ts = ts1[:n_valid].astype(np.int64)
        T = len(ts1)
        J = num_steps
        out_t = start_off + np.arange(J, dtype=np.int64) * step_ms
        hi = np.searchsorted(ts, out_t, side="right")
        lo = np.searchsorted(ts, out_t - window_ms, side="right")
        cnt = (hi - lo).astype(np.float32)
        tidx = np.arange(T)[:, None]
        W = ((tidx >= lo[None, :]) & (tidx < hi[None, :])).astype(np.float32)
        F = np.zeros((T, J), dtype=np.float32)
        L = np.zeros((T, J), dtype=np.float32)
        L2 = np.zeros((T, J), dtype=np.float32)
        has = cnt > 0
        has2 = cnt >= 2
        F[lo[has], np.nonzero(has)[0]] = 1.0
        L[hi[has] - 1, np.nonzero(has)[0]] = 1.0
        L2[hi[has2] - 2, np.nonzero(has2)[0]] = 1.0
        pad = np.full(J, np.nan)
        self.W, self.F, self.L, self.L2 = W, F, L, L2
        self.count = cnt
        self.t_first = np.where(has, ts[np.minimum(lo, len(ts) - 1)], np.nan)
        self.t_last = np.where(has, ts[np.minimum(hi - 1, len(ts) - 1)], pad)
        self.t_last2 = np.where(has2, ts[np.clip(hi - 2, 0, len(ts) - 1)], pad)
        self.out_t = out_t.astype(np.float64)
        self.window_ms = window_ms
        self._ts1 = ts1
        self._lo, self._hi, self._T, self._J = lo, hi, T, J
        # gather-form of the one-hot selections for backends where a gather
        # beats a matmul (CPU; the TPU branch keeps the MXU one-hots):
        # row 0 = first-sample, 1 = last, 2 = second-to-last positions.
        # Out-of-range windows clip to valid positions; every use is gated
        # by has/count masks, matching the one-hot's all-zero columns.
        self.idx = np.stack([
            np.clip(lo, 0, T - 1),
            np.clip(hi - 1, 0, T - 1),
            np.clip(hi - 2, 0, T - 1),
        ]).astype(np.int32)
        # device-resident copies (transferred once, reused every query)
        import jax

        put = self._put = put if put is not None else jax.device_put
        self.dW, self.dF, self.dL, self.dL2 = map(put, (W, F, L, L2))
        self.d_count = put(cnt)
        self.d_tf = put(np.nan_to_num(self.t_first, nan=0.0).astype(np.float32))
        self.d_tl = put(np.nan_to_num(self.t_last, nan=0.0).astype(np.float32))
        self.d_tl2 = put(np.nan_to_num(self.t_last2, nan=0.0).astype(np.float32))
        self.d_out_t = put(self.out_t.astype(np.float32))
        self.d_idx = put(self.idx)
        # the heavyweight structures below (min/max edge one-hots ~ [T, 32J],
        # pair membership, regression moments) build LAZILY on first use:
        # sum/rate dashboards never pay for them, and live-edge append
        # repairs rebuild window matrices on every grid extension
        self._pairs_built = False
        self._minmax_built = False
        self._regression_built = False

    def ensure_pairs(self):
        """P: pair-membership for changes/resets (lazy)."""
        if self._pairs_built:
            return
        import jax

        tidx = np.arange(self._T)[:, None]
        P = ((tidx > self._lo[None, :]) & (tidx < self._hi[None, :])).astype(np.float32)
        self.P = P
        self.dP = self._put(P)
        self._pairs_built = True

    def ensure_regression(self):
        """Centered time moments for deriv/predict_linear (lazy)."""
        if self._regression_built:
            return
        import jax

        tc = (self._ts1.astype(np.float64)[:, None] - self.out_t[None, :]) * 1e-3
        self.Wt = (self.W * tc).astype(np.float32)
        self.st = self.Wt.sum(0)
        self.stt = (self.W * tc * tc).sum(0).astype(np.float64)
        self.dWt = self._put(self.Wt)
        self.d_st = self._put(self.st)
        self.d_stt = self._put(self.stt.astype(np.float32))
        self._regression_built = True

    def ensure_minmax(self):
        """min/max tile hierarchy + edge one-hots (lazy — the edge matrix is
        [T, 2*_TILE*J], by far the biggest structure here)."""
        if self._minmax_built:
            return
        import jax

        (self.tile_mask, self.edge_onehot, self.edge_valid,
         self.edge_idx) = build_minmax_structures(
            self._lo, self._hi, self._T, self._J
        )
        put = self._put
        self.d_tile_mask = put(self.tile_mask)
        self.d_edge_onehot = put(self.edge_onehot)
        self.d_edge_valid = put(self.edge_valid)
        self.d_edge_idx = put(self.edge_idx)
        self._minmax_built = True


def window_matrices(block: StagedBlock, start_off: int, step_ms: int,
                    num_steps: int, window_ms: int) -> WindowMatrices:
    """Per-(block, query-params) WindowMatrices, memoized on the block via
    the shared keyed single-flight (filodb_tpu/singleflight.memo_on): two
    racing same-key misses would each upload the full device-resident
    matrix set and the loser's copy would linger until GC. A series-sharded
    block (mesh superblock) uploads them REPLICATED across its mesh — the
    placement the shard_map program consumes, committed once at build."""
    from ..singleflight import memo_on
    from .staging import replicated_put

    key = (int(start_off), int(step_ms), int(num_steps), int(window_ms))
    mesh = getattr(block, "placement", None)
    return memo_on(
        block, "_wm_cache", key,
        lambda: WindowMatrices(block.regular_ts, int(block.lens[0]),
                               start_off, step_ms, num_steps, window_ms,
                               put=replicated_put(mesh) if mesh is not None
                               else None),
    )


@functools.partial(
    jax.jit, static_argnames=("func", "is_counter", "is_delta", "fetch")
)
def mxu_range_kernel(
    func: str,
    vals,  # [S, T] f32
    raw,  # [S, T] f32 (counters; == vals otherwise)
    baseline,  # [S]
    W, F, L, L2,  # [T, J] f32
    count, t_first, t_last, t_last2,  # [J]
    out_t,  # [J] f64 ms
    window_ms,
    idx=None,  # [3, J] i32 first/last/last2 positions (CPU gather form)
    is_counter: bool = False,
    is_delta: bool = False,
    arg0=0.0,
    fetch: str = "auto",
):
    """Compute [S, J] results with matmuls on the MXU.

    The F/L/L2 one-hot matmuls are MXU-speed gathers on TPU; on the CPU
    backend a real gather (jnp.take with the idx rows) is ~100x cheaper, so
    the fetch strategy is chosen per backend at trace time. Gathered values
    at clipped positions are garbage exactly where the one-hot column is
    all-zero — both are discarded by the has/count gates."""
    f32 = jnp.float32
    has = count > 0
    w_s = window_ms.astype(f32) * 1e-3
    nan = jnp.nan

    def mm(x, M):
        return jax.lax.dot(x, M, precision=jax.lax.Precision.HIGHEST)

    if use_gather_fetch(fetch, idx):
        gF = lambda x: jnp.take(x, idx[0], axis=1)
        gL = lambda x: jnp.take(x, idx[1], axis=1)
        gL2 = lambda x: jnp.take(x, idx[2], axis=1)
    else:
        gF = lambda x: mm(x, F)
        gL = lambda x: mm(x, L)
        gL2 = lambda x: mm(x, L2)

    if func == "sum_over_time" or (is_delta and func in ("rate", "increase")):
        s = mm(vals, W)
        if func == "rate":
            s = s / w_s
        return jnp.where(has, s, nan)
    if func == "count_over_time":
        return jnp.where(has, count, nan)[None, :] * jnp.ones_like(vals[:, :1])
    if func == "avg_over_time":
        return jnp.where(has, mm(vals, W) / jnp.maximum(count, 1.0), nan)
    if func in ("last", "last_over_time"):
        return jnp.where(has, gL(vals), nan)
    if func == "first_over_time":
        return jnp.where(has, gF(vals), nan)
    if func == "present_over_time":
        return jnp.where(has, 1.0, nan)[None, :] * jnp.ones_like(vals[:, :1])
    if func == "absent_over_time":
        return jnp.where(has, nan, 1.0)[None, :] * jnp.ones_like(vals[:, :1])
    if func == "timestamp":
        return jnp.where(has, t_last.astype(f32), nan)[None, :] * jnp.ones_like(vals[:, :1])
    if func in ("stddev_over_time", "stdvar_over_time", "z_score"):
        s = mm(vals, W)
        s2 = mm(vals * vals, W)
        c = jnp.maximum(count, 1.0)
        mean = s / c
        var = jnp.maximum(s2 / c - mean * mean, 0.0)
        if func == "stdvar_over_time":
            return jnp.where(has, var, nan)
        sd = jnp.sqrt(var)
        if func == "stddev_over_time":
            return jnp.where(has, sd, nan)
        vl = gL(vals)
        return jnp.where(has, (vl - mean) / jnp.maximum(sd, 1e-30), nan)
    if func in ("rate", "increase", "delta"):
        vf = gF(vals)
        vl = gL(vals)
        dlt = vl - vf
        tf = t_first.astype(f32) * 1e-3
        tl = t_last.astype(f32) * 1e-3
        sampled = tl - tf
        range_start = (out_t.astype(f32) - window_ms.astype(f32)) * 1e-3
        range_end = out_t.astype(f32) * 1e-3
        dur_start = tf - range_start
        dur_end = range_end - tl
        avg_dur = sampled / jnp.maximum(count - 1.0, 1.0)
        thresh = avg_dur * 1.1
        if is_counter and func != "delta":
            v_first_raw = gF(raw)
            dur_zero = jnp.where(
                dlt > 0, sampled[None, :] * (v_first_raw / jnp.maximum(dlt, 1e-30)), jnp.inf
            )
            ds = jnp.minimum(dur_start[None, :], jnp.where(v_first_raw >= 0, dur_zero, jnp.inf))
        else:
            ds = jnp.broadcast_to(dur_start[None, :], dlt.shape)
        ds = jnp.where(ds >= thresh[None, :], (avg_dur / 2.0)[None, :], ds)
        de = jnp.where(dur_end >= thresh, avg_dur / 2.0, dur_end)[None, :]
        factor = (sampled[None, :] + ds + de) / jnp.maximum(sampled, 1e-30)[None, :]
        res = dlt * factor
        if func == "rate":
            res = res / w_s
        return jnp.where((count >= 2)[None, :], res, nan)
    if func in ("irate", "idelta"):
        ok = count >= 2
        if func == "idelta" and is_counter and not is_delta:
            # counter blocks arrive diff-encoded: last pair's diff via one-hot
            return jnp.where(ok[None, :], gL(vals), nan)
        vl = gL(vals)
        vp = gL2(vals)
        dt_s = (t_last - t_last2).astype(f32) * 1e-3
        dv = vl - vp
        r = dv / jnp.maximum(dt_s, 1e-30)[None, :] if func == "irate" else dv
        return jnp.where(ok[None, :], r, nan)
    raise ValueError(f"mxu kernel does not support {func}")


@functools.partial(jax.jit, static_argnames=())
def mxu_pair_count(flagged, P, has):
    """changes/resets: flagged [S,T] pair indicators @ P [T,J]."""
    n = jax.lax.dot(flagged, P, precision=jax.lax.Precision.HIGHEST)
    return jnp.where(has, n, jnp.nan)


@functools.partial(jax.jit, static_argnames=("n_valid", "is_min", "fetch"))
def mxu_minmax(vals, tile_mask, edge_onehot, edge_valid, count,
               n_valid: int, is_min: bool = True, edge_idx=None,
               fetch: str = "auto"):
    """min/max_over_time on the regular grid: tile-hierarchy + edge samples
    via selection matmul (gathers are pathologically slow on the TPU
    backend; on CPU the gather form via edge_idx is far cheaper than the
    wide [T, J*2L] matmul). vals [S, T]; tile_mask [J, T/L];
    edge_onehot [T, J*2L]; edge_valid [J, 2L]; edge_idx [J, 2L] i32."""
    S, T = vals.shape
    L = _TILE
    J = tile_mask.shape[0]
    v = vals if is_min else -vals
    sentinel = jnp.float32(3e38)
    lane = jax.lax.broadcasted_iota(jnp.int32, (S, T), 1)
    vm = jnp.where(lane < n_valid, v, sentinel)
    tmin = vm.reshape(S, T // L, L).min(-1)  # [S, T/L]
    full = jnp.where(tile_mask[None, :, :], tmin[:, None, :], sentinel).min(-1)  # [S, J]
    if use_gather_fetch(fetch, edge_idx):
        edges = jnp.take(vm, edge_idx.reshape(-1), axis=1)
    else:
        edges = jax.lax.dot(vm, edge_onehot, precision=jax.lax.Precision.HIGHEST)
    edges = edges.reshape(S, J, 2 * L)
    edges = jnp.where(edge_valid[None, :, :], edges, sentinel).min(-1)  # [S, J]
    r = jnp.minimum(full, edges)
    r = r if is_min else -r
    return jnp.where((count > 0)[None, :], r, jnp.nan)


@functools.partial(jax.jit, static_argnames=("predict",))
def mxu_regression(vals, W, Wt, st, stt, count, has, lead, predict: bool = False):
    """deriv / predict_linear via least squares with host-precomputed
    time moments (tc centered at each window's out_t)."""
    sv = jax.lax.dot(vals, W, precision=jax.lax.Precision.HIGHEST)
    stv = jax.lax.dot(vals, Wt, precision=jax.lax.Precision.HIGHEST)
    n = count[None, :]
    denom = (n * stt[None, :] - (st * st)[None, :]).astype(jnp.float32)
    slope = (n * stv - st[None, :] * sv) / jnp.where(jnp.abs(denom) < 1e-30, 1.0, denom)
    ok = (count >= 2)[None, :] & (jnp.abs(denom) >= 1e-30)
    if not predict:
        return jnp.where(ok, slope, jnp.nan)
    intercept = (sv - slope * st[None, :]) / jnp.maximum(n, 1.0)
    return jnp.where(ok, intercept + slope * lead, jnp.nan)


def run_mxu_range_function(func, block: StagedBlock, params, is_counter=False,
                           is_delta=False, args=()):
    """Entry: dispatch one MXU-path range function. Caller guarantees
    block.regular_ts is set and func in MXU_FUNCS."""
    from .kernels import pad_steps

    J = pad_steps(params.num_steps)
    start_off = int(params.start_ms - block.base_ms)
    wm = window_matrices(block, start_off, params.step_ms, J, params.window_ms)
    if func in ("changes", "resets"):
        # must see raw value movement — corrected counter vals are monotone,
        # so resets()/changes() must not read them (kernels.py has the same
        # rule). Counter blocks arrive diff-encoded (staging mode "diff");
        # gauges compare raw values.
        wm.ensure_pairs()
        vals = jnp.asarray(block.raw if block.raw is not None else block.vals)
        if is_counter and not is_delta:
            flag = (vals != 0) if func == "changes" else (vals < 0)
        else:
            prev = jnp.concatenate([vals[:, :1], vals[:, :-1]], axis=1)
            flag = (vals != prev) if func == "changes" else (vals < prev)
        return mxu_pair_count(flag.astype(jnp.float32), wm.dP, wm.d_count > 0)
    if func in ("min_over_time", "max_over_time"):
        wm.ensure_minmax()
        return mxu_minmax(
            jnp.asarray(block.vals), wm.d_tile_mask, wm.d_edge_onehot,
            wm.d_edge_valid, wm.d_count,
            n_valid=int(block.lens[0]), is_min=(func == "min_over_time"),
            edge_idx=wm.d_edge_idx, fetch=fetch_strategy(),
        )
    if func in ("deriv", "predict_linear"):
        wm.ensure_regression()
        lead = np.float32(args[0]) if args else np.float32(0.0)
        return mxu_regression(
            block.vals, wm.dW, wm.dWt, wm.d_st, wm.d_stt,
            wm.d_count, wm.d_count > 0, lead,
            predict=(func == "predict_linear"),
        )
    raw = block.raw if block.raw is not None else block.vals
    return mxu_range_kernel(
        func,
        block.vals,
        raw,
        block.baseline,
        wm.dW, wm.dF, wm.dL, wm.dL2,
        wm.d_count,
        wm.d_tf,
        wm.d_tl,
        wm.d_tl2,
        wm.d_out_t,
        np.float32(params.window_ms),
        idx=wm.d_idx,
        is_counter=is_counter,
        is_delta=is_delta,
        fetch=fetch_strategy(),
    )


# kernel-observatory registration (obs/kernels.py; linted by
# tools/check_metrics.py — every jit wrapper here must register)
def _register_kernel_observatory() -> None:
    from ..obs.kernels import KERNELS

    KERNELS.register_jits(
        "ops.mxu_kernels",
        mxu_range_kernel=mxu_range_kernel,
        mxu_pair_count=mxu_pair_count,
        mxu_minmax=mxu_minmax,
        mxu_regression=mxu_regression,
    )


_register_kernel_observatory()
