"""Native-histogram kernels (reference L0/L4: format/vectors/Histogram.scala
quantile math :64-130, HistogramQuantileMapper, RateFunctions hist rate :367).

Native histograms stage as ``[S, T, B]`` cumulative bucket-count blocks —
already the ideal TPU layout. Per-bucket rate/increase/sum reuse the same
boundary-index machinery as scalar kernels (broadcast over B);
histogram_quantile is a vectorized interpolation over the bucket axis.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .kernels import RangeParams, _bounds, pad_steps
from .staging import StagedBlock


def _gather3(arr, idx):
    """arr [S, T, B], idx [S, J] -> [S, J, B]."""
    T = arr.shape[1]
    return jnp.take_along_axis(arr, jnp.clip(idx, 0, T - 1)[:, :, None], axis=1)


@functools.partial(jax.jit, static_argnames=("func", "num_steps", "is_delta"))
def hist_range_kernel(
    func: str,
    ts,  # [S, T] i32
    vals,  # [S, T, B] f32 bucket counts (cumulative; baseline-subtracted)
    lens,  # [S] i32
    start_off,
    step_ms,
    window,
    num_steps: int,
    is_delta: bool = False,
):
    """[S, num_steps, B] per-bucket results for hist rate/increase/last/sum."""
    out_t = start_off + jnp.arange(num_steps, dtype=jnp.int32) * step_ms
    lo, hi = _bounds(ts, lens, out_t, window)
    count = (hi - lo).astype(jnp.float32)[:, :, None]
    has = count > 0
    if func in ("last", "last_over_time"):
        return jnp.where(has, _gather3(vals, hi - 1), jnp.nan)
    if func == "sum_over_time" or (is_delta and func in ("rate", "increase")):
        cs = jnp.cumsum(vals, axis=1)
        cs = jnp.concatenate([jnp.zeros_like(cs[:, :1]), cs], axis=1)
        s = _gather3(cs, hi) - _gather3(cs, lo)
        if func == "rate":
            s = s / (window.astype(jnp.float32) * 1e-3)
        return jnp.where(has, s, jnp.nan)
    if func in ("rate", "increase", "delta"):
        # cumulative histograms: per-bucket extrapolated increase, same
        # Prometheus window-edge extrapolation as scalars (no zero cap —
        # bucket counts are far from zero-crossing concerns; reference hist
        # rate RateFunctions.scala:367 likewise extrapolates per bucket)
        t_first = jnp.take_along_axis(ts, jnp.clip(lo, 0, ts.shape[1] - 1), axis=1)
        t_last = jnp.take_along_axis(ts, jnp.clip(hi - 1, 0, ts.shape[1] - 1), axis=1)
        v_first = _gather3(vals, lo)
        v_last = _gather3(vals, hi - 1)
        dlt = v_last - v_first  # [S, J, B]
        f32 = vals.dtype
        tf = t_first.astype(f32) * 1e-3
        tl = t_last.astype(f32) * 1e-3
        sampled = tl - tf
        cnt = (hi - lo).astype(f32)
        range_start = (out_t - window)[None, :].astype(f32) * 1e-3
        range_end = out_t[None, :].astype(f32) * 1e-3
        dur_start = tf - range_start
        dur_end = range_end - tl
        avg_dur = sampled / jnp.maximum(cnt - 1.0, 1.0)
        thresh = avg_dur * 1.1
        dur_start = jnp.where(dur_start >= thresh, avg_dur / 2.0, dur_start)
        dur_end = jnp.where(dur_end >= thresh, avg_dur / 2.0, dur_end)
        factor = (sampled + dur_start + dur_end) / jnp.maximum(sampled, 1e-30)
        res = dlt * factor[:, :, None]
        if func == "rate":
            res = res / (window.astype(f32) * 1e-3)
        return jnp.where((cnt >= 2)[:, :, None], res, jnp.nan)
    raise ValueError(f"unknown histogram range function {func}")


@functools.partial(jax.jit, static_argnames=("even",))
def histogram_quantile(q, buckets, les, even: bool = False):
    """Prometheus histogram_quantile over bucket-count/rate grids.

    buckets [..., B] cumulative counts per le; les [B] upper bounds with
    les[-1] = +inf. Linear interpolation within the located bucket; lower
    bound of the first bucket is 0 when its le > 0 (promql semantics, and
    reference Histogram.scala:64-130 quantile()). ``even`` assumes samples
    spread evenly over count+1 positions (reference evenDistribution,
    Histogram.scala:96).
    """
    B = buckets.shape[-1]
    total = buckets[..., -1]
    ok = (total > 0) & jnp.isfinite(total)
    rank = jnp.clip(q, 0.0, 1.0) * total
    # first bucket index with count >= rank
    meets = buckets >= rank[..., None]
    idx = jnp.argmax(meets, axis=-1)
    idx = jnp.where(meets.any(-1), idx, B - 1)
    c_hi = jnp.take_along_axis(buckets, idx[..., None], axis=-1)[..., 0]
    c_lo = jnp.where(idx > 0, jnp.take_along_axis(buckets, jnp.maximum(idx - 1, 0)[..., None], axis=-1)[..., 0], 0.0)
    le_hi = les[idx]
    le_lo = jnp.where(idx > 0, les[jnp.maximum(idx - 1, 0)], jnp.where(les[0] > 0, 0.0, -jnp.inf))
    # top (+inf) bucket: return the highest finite bound (promql behavior)
    highest_finite = jnp.where(B >= 2, les[B - 2], les[0])
    in_top = idx == B - 1
    denom = (c_hi - c_lo + 1.0) if even else (c_hi - c_lo)
    frac = (rank - c_lo) / jnp.maximum(denom, 1e-30)
    val = le_lo + (le_hi - le_lo) * frac
    # q<=0 -> lower bound of first bucket; q>=1 -> highest bound
    val = jnp.where(in_top, highest_finite, val)
    val = jnp.where(jnp.isneginf(le_lo), le_hi, val)  # le[0] <= 0 edge
    out = jnp.where(ok, val, jnp.nan)
    out = jnp.where(q < 0, -jnp.inf, out)
    out = jnp.where(q > 1, jnp.inf, out)
    return out


@jax.jit
def histogram_fraction(lower, upper, buckets, les):
    """promql histogram_fraction(lower, upper, h): fraction of observations in
    [lower, upper] (reference Histogram.scala fraction math)."""

    def cum_at(x):
        # interpolated cumulative count at value x
        B = buckets.shape[-1]
        xb = jnp.searchsorted(les, x)  # first le >= x
        xb = jnp.clip(xb, 0, B - 1)
        c_hi = jnp.take_along_axis(buckets, jnp.broadcast_to(xb, buckets.shape[:-1])[..., None], axis=-1)[..., 0]
        c_lo = jnp.where(
            xb > 0,
            jnp.take_along_axis(buckets, jnp.broadcast_to(jnp.maximum(xb - 1, 0), buckets.shape[:-1])[..., None], axis=-1)[..., 0],
            0.0,
        )
        le_hi = les[xb]
        le_lo = jnp.where(xb > 0, les[jnp.maximum(xb - 1, 0)], jnp.where(les[0] > 0, 0.0, -jnp.inf))
        w = jnp.where(jnp.isfinite(le_hi - le_lo), (x - le_lo) / jnp.maximum(le_hi - le_lo, 1e-30), 1.0)
        w = jnp.clip(w, 0.0, 1.0)
        return c_lo + (c_hi - c_lo) * w

    total = buckets[..., -1]
    frac = (cum_at(upper) - cum_at(lower)) / jnp.maximum(total, 1e-30)
    return jnp.where(total > 0, jnp.clip(frac, 0.0, 1.0), jnp.nan)


# histogram range functions the fused single-dispatch path supports (the
# hist_range_kernel dispatch set; "last" is the plain-selector read)
FUSED_HIST_FUNCS = frozenset({
    "rate", "increase", "delta", "sum_over_time", "last", "last_over_time",
})


def _hist_range_shared(func, vals, lo, hi, t_first, t_last, out_t, window,
                       is_delta: bool):
    """Shared-regular-grid form of hist_range_kernel: every series shares
    ONE timestamp vector, so window boundaries are series-INDEPENDENT [J]
    vectors precomputed host-side (np.searchsorted) — no O(S*J*T) compare.
    Same math as hist_range_kernel over identical indices, so results are
    bit-identical to the general path on shared grids. Padded series rows
    get garbage values (count is series-independent); the fused epilogue's
    trash-group contract discards them."""
    f32 = vals.dtype
    T = vals.shape[1]
    cnt = (hi - lo).astype(f32)  # [J]
    has = (cnt > 0)[None, :, None]

    def gidx(idx):  # [S, J, B] gather at shared [J] sample indices
        return jnp.take(vals, jnp.clip(idx, 0, T - 1), axis=1)

    if func in ("last", "last_over_time"):
        return jnp.where(has, gidx(hi - 1), jnp.nan)
    if func == "sum_over_time" or (is_delta and func in ("rate", "increase")):
        cs = jnp.cumsum(vals, axis=1)
        cs = jnp.concatenate([jnp.zeros_like(cs[:, :1]), cs], axis=1)
        s = (jnp.take(cs, jnp.clip(hi, 0, T), axis=1)
             - jnp.take(cs, jnp.clip(lo, 0, T), axis=1))
        if func == "rate":
            s = s / (window.astype(f32) * 1e-3)
        return jnp.where(has, s, jnp.nan)
    if func in ("rate", "increase", "delta"):
        v_first = gidx(lo)
        v_last = gidx(hi - 1)
        dlt = v_last - v_first  # [S, J, B]
        tf = t_first.astype(f32) * 1e-3  # [J]
        tl = t_last.astype(f32) * 1e-3
        sampled = tl - tf
        range_start = (out_t - window).astype(f32) * 1e-3
        range_end = out_t.astype(f32) * 1e-3
        dur_start = tf - range_start
        dur_end = range_end - tl
        avg_dur = sampled / jnp.maximum(cnt - 1.0, 1.0)
        thresh = avg_dur * 1.1
        dur_start = jnp.where(dur_start >= thresh, avg_dur / 2.0, dur_start)
        dur_end = jnp.where(dur_end >= thresh, avg_dur / 2.0, dur_end)
        factor = (sampled + dur_start + dur_end) / jnp.maximum(sampled, 1e-30)
        res = dlt * factor[None, :, None]
        if func == "rate":
            res = res / (window.astype(f32) * 1e-3)
        return jnp.where((cnt >= 2)[None, :, None], res, jnp.nan)
    raise ValueError(f"unknown histogram range function {func}")


def _hist_range_jitter(func, vals, dev, hwa, window, is_delta: bool):
    """Near-regular (jittered) grid form of hist_range_kernel: the SHARED
    certain-range boundary vectors [J] (clo/chi from the nominal grid,
    mxu_jitter.JitterWindowMatrices) replace the O(S*J*T) per-series
    boundary compare, and the <=1 uncertain slot per window boundary is
    resolved per series from the staged deviations — a handful of [S, J, B]
    gathers at shared slot indices. Window membership is EXACT (the same
    certain/uncertain decomposition as the scalar jitter kernel;
    PeriodicSamplesMapper.scala:256 contract), so results match the general
    kernel on the same data. ``hwa`` is the flat structure tuple
    (aggregations-side _hist_jwm_args order)."""
    (clo, chi, idx, count0, c0pos, has_klo, has_khi, F0_rel, L0_rel,
     Klo_rel, Khi_rel, blo_rel, ehi_rel) = hwa
    f32 = vals.dtype
    T = vals.shape[1]
    nan = jnp.nan

    def tk(x, i):  # x [S, T(, B)], shared [J] indices -> [S, J(, B)]
        return jnp.take(x, jnp.clip(i, 0, T - 1), axis=1)

    dKlo, dKhi = tk(dev, idx[3]), tk(dev, idx[4])
    in_lo = has_klo[None, :] & (dKlo > blo_rel[None, :])
    in_hi = has_khi[None, :] & (dKhi <= ehi_rel[None, :])
    cnt = count0[None, :] + in_lo + in_hi  # [S, J]
    has3 = (cnt > 0)[:, :, None]
    il3, ih3 = in_lo[:, :, None], in_hi[:, :, None]
    c0 = c0pos[None, :]
    c03 = c0pos[None, :, None]

    def w3(m1, a, m2, b_, c):
        return jnp.where(m1, a, jnp.where(m2, b_, c))

    if func in ("last", "last_over_time"):
        vL0, vKlo, vKhi = tk(vals, idx[1]), tk(vals, idx[3]), tk(vals, idx[4])
        return jnp.where(has3, w3(ih3, vKhi, c03, vL0, vKlo), nan)
    if func == "sum_over_time" or (is_delta and func in ("rate", "increase")):
        cs = jnp.cumsum(vals, axis=1)
        cs = jnp.concatenate([jnp.zeros_like(cs[:, :1]), cs], axis=1)
        s = (jnp.take(cs, jnp.clip(chi, 0, T), axis=1)
             - jnp.take(cs, jnp.clip(clo, 0, T), axis=1))
        vKlo, vKhi = tk(vals, idx[3]), tk(vals, idx[4])
        s = s + jnp.where(il3, vKlo, 0.0) + jnp.where(ih3, vKhi, 0.0)
        if func == "rate":
            s = s / (window.astype(f32) * 1e-3)
        return jnp.where(has3, s, nan)
    if func in ("rate", "increase", "delta"):
        vF0, vL0 = tk(vals, idx[0]), tk(vals, idx[1])
        vKlo, vKhi = tk(vals, idx[3]), tk(vals, idx[4])
        dF0, dL0 = tk(dev, idx[0]), tk(dev, idx[1])
        v_first = w3(il3, vKlo, c03, vF0, vKhi)
        v_last = w3(ih3, vKhi, c03, vL0, vKlo)
        # boundary times RELATIVE to each window's start (f32 ms — same
        # precision contract as the scalar jitter kernel)
        tf_rel = w3(in_lo, Klo_rel[None, :] + dKlo, c0,
                    F0_rel[None, :] + dF0, Khi_rel[None, :] + dKhi)
        tl_rel = w3(in_hi, Khi_rel[None, :] + dKhi, c0,
                    L0_rel[None, :] + dL0, Klo_rel[None, :] + dKlo)
        dlt = v_last - v_first  # [S, J, B]
        sampled = (tl_rel - tf_rel) * 1e-3
        dur_start = tf_rel * 1e-3
        dur_end = (window.astype(f32) - tl_rel) * 1e-3
        avg_dur = sampled / jnp.maximum(cnt - 1.0, 1.0)
        thresh = avg_dur * 1.1
        ds = jnp.where(dur_start >= thresh, avg_dur / 2.0, dur_start)
        de = jnp.where(dur_end >= thresh, avg_dur / 2.0, dur_end)
        factor = (sampled + ds + de) / jnp.maximum(sampled, 1e-30)
        res = dlt * factor[:, :, None]
        if func == "rate":
            res = res / (window.astype(f32) * 1e-3)
        return jnp.where((cnt >= 2)[:, :, None], res, nan)
    raise ValueError(f"unknown histogram range function {func}")


@functools.partial(jax.jit, static_argnames=(
    "func", "num_groups", "is_delta", "quantile"
))
def _fused_hist_jitter_jit(func, vals, dev, hwa, window, gids, les, qv,
                           num_groups: int, is_delta: bool, quantile: bool):
    """Jitter-grid twin of _fused_hist_shared_jit: shared certain-range
    boundaries + per-series one-slot corrections, epilogue in-program."""
    from .aggregations import _segment_aggregate_jit

    sjb = _hist_range_jitter(func, vals, dev, hwa, window, is_delta)
    S, J, B = sjb.shape
    gjb = _segment_aggregate_jit(
        "sum", sjb.reshape(S, J * B), gids, num_groups + 1
    )[:num_groups].reshape(num_groups, J, B)
    if quantile:
        return histogram_quantile(qv, gjb, les)
    return gjb


@functools.partial(jax.jit, static_argnames=(
    "mesh", "func", "num_groups", "is_delta", "quantile"
))
def _fused_hist_jitter_sharded_jit(mesh, func, vals, dev, hwa, window, gids,
                                   les, qv, num_groups: int, is_delta: bool,
                                   quantile: bool):
    """Series-sharded twin of _fused_hist_jitter_jit (replicated window
    structure rides the closure; [S, T, B] vals and [S, T] dev row bands)."""
    from jax.sharding import PartitionSpec as P

    from ..jax_compat import shard_map

    axis = mesh.axis_names[0]

    def local(vals_l, dev_l, gids_l):
        sjb = _hist_range_jitter(func, vals_l, dev_l, hwa, window, is_delta)
        return _hist_sharded_combine(
            sjb, gids_l, les, qv, num_groups, quantile, axis
        )

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(axis, None, None), P(axis, None), P(axis)),
        out_specs=P(), check=False,
    )(vals, dev, gids)


@functools.partial(jax.jit, static_argnames=(
    "func", "num_groups", "is_delta", "quantile"
))
def _fused_hist_shared_jit(func, vals, lo, hi, t_first, t_last, out_t,
                           window, gids, les, qv, num_groups: int,
                           is_delta: bool, quantile: bool):
    """Shared-grid twin of _fused_hist_jit (same program shape, cheaper
    window machinery)."""
    from .aggregations import _segment_aggregate_jit

    sjb = _hist_range_shared(
        func, vals, lo, hi, t_first, t_last, out_t, window, is_delta
    )
    S, J, B = sjb.shape
    gjb = _segment_aggregate_jit(
        "sum", sjb.reshape(S, J * B), gids, num_groups + 1
    )[:num_groups].reshape(num_groups, J, B)
    if quantile:
        return histogram_quantile(qv, gjb, les)
    return gjb


@functools.partial(jax.jit, static_argnames=(
    "func", "num_steps", "num_groups", "is_delta", "quantile"
))
def _fused_hist_jit(func, ts, vals, lens, gids, les, qv, start_off, step_ms,
                    window, num_steps: int, num_groups: int, is_delta: bool,
                    quantile: bool):
    """hist range_fn -> per-bucket segment-sum -> (optional) device-side
    histogram_quantile interpolation as ONE compiled program: only the
    [G, J, B] group partials — or just the [G, J] quantile grid — exist as
    program outputs; no [S, J, B] grid ever reaches the host. ``gids``
    follows the trash-group contract (padded rows -> group ``num_groups``);
    per-bucket summation is the flattened [S, J*B] form of the same segment
    reduce the reference partial-merge path runs, so the two paths agree
    bit-for-bit on identical schemes."""
    from .aggregations import _segment_aggregate_jit

    sjb = hist_range_kernel(
        func, ts, vals, lens, start_off, step_ms, window, num_steps,
        is_delta=is_delta,
    )
    S, J, B = sjb.shape
    gjb = _segment_aggregate_jit(
        "sum", sjb.reshape(S, J * B), gids, num_groups + 1
    )[:num_groups].reshape(num_groups, J, B)
    if quantile:
        return histogram_quantile(qv, gjb, les)
    return gjb


def _hist_sharded_combine(sjb, gids_l, les, qv, num_groups: int,
                          quantile: bool, axis: str):
    """Local per-bucket segment-sum + psum over the mesh axis, then the
    (optional) histogram_quantile interpolation on the REPLICATED [G, J, B]
    partials — all inside the shard_map body, so the whole hist pipeline
    stays one multi-device program. NaN-absence semantics match
    _segment_aggregate_jit's "sum" (a group with no members anywhere is
    NaN), via psum'd validity counts."""
    S, J, B = sjb.shape
    flat = sjb.reshape(S, J * B)
    valid = ~jnp.isnan(flat)
    s = jax.ops.segment_sum(
        jnp.where(valid, flat, 0.0), gids_l, num_groups + 1
    )
    c = jax.ops.segment_sum(valid.astype(flat.dtype), gids_l, num_groups + 1)
    s = jax.lax.psum(s, axis)
    c = jax.lax.psum(c, axis)
    gjb = jnp.where(c > 0, s, jnp.nan)[:num_groups].reshape(
        num_groups, J, B
    )
    if quantile:
        return histogram_quantile(qv, gjb, les)
    return gjb


@functools.partial(jax.jit, static_argnames=(
    "mesh", "func", "num_groups", "is_delta", "quantile"
))
def _fused_hist_shared_sharded_jit(mesh, func, vals, lo, hi, t_first, t_last,
                                   out_t, window, gids, les, qv,
                                   num_groups: int, is_delta: bool,
                                   quantile: bool):
    """Series-sharded twin of _fused_hist_shared_jit: the shared-grid hist
    range kernel runs on each device's [S_l, T, B] row band (the [J]
    boundary vectors are replicated closures) and the per-bucket partials
    psum across the mesh inside the same program."""
    from jax.sharding import PartitionSpec as P

    from ..jax_compat import shard_map

    axis = mesh.axis_names[0]

    def local(vals_l, gids_l):
        sjb = _hist_range_shared(
            func, vals_l, lo, hi, t_first, t_last, out_t, window, is_delta
        )
        return _hist_sharded_combine(
            sjb, gids_l, les, qv, num_groups, quantile, axis
        )

    return shard_map(
        local, mesh=mesh, in_specs=(P(axis, None, None), P(axis)),
        out_specs=P(), check=False,
    )(vals, gids)


@functools.partial(jax.jit, static_argnames=(
    "mesh", "func", "num_steps", "num_groups", "is_delta", "quantile"
))
def _fused_hist_sharded_jit(mesh, func, ts, vals, lens, gids, les, qv,
                            start_off, step_ms, window, num_steps: int,
                            num_groups: int, is_delta: bool, quantile: bool):
    """Series-sharded twin of _fused_hist_jit (general per-series window
    boundaries)."""
    from jax.sharding import PartitionSpec as P

    from ..jax_compat import shard_map

    axis = mesh.axis_names[0]

    def local(ts_l, vals_l, lens_l, gids_l):
        sjb = hist_range_kernel(
            func, ts_l, vals_l, lens_l, start_off, step_ms, window,
            num_steps, is_delta=is_delta,
        )
        return _hist_sharded_combine(
            sjb, gids_l, les, qv, num_groups, quantile, axis
        )

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None, None), P(axis), P(axis)),
        out_specs=P(), check=False,
    )(ts, vals, lens, gids)


# -- cross-query batched twins (query/scheduler.py; see the batched-dispatch
# -- contract in ops/aggregations.py: lanes UNROLL with the exact
# -- single-query math, range grids computed once per unique window,
# -- num_groups = the group's shared pow2 bucket) ---------------------------


def _hist_epilogue(sjb, gids, les, qv, num_groups: int, quantile: bool):
    """One lane's per-bucket segment-sum (+ optional quantile
    interpolation) — the identical computation _fused_hist_jit runs."""
    from .aggregations import _segment_aggregate_jit

    S, J, B = sjb.shape
    gjb = _segment_aggregate_jit(
        "sum", sjb.reshape(S, J * B), gids, num_groups + 1
    )[:num_groups].reshape(num_groups, J, B)
    if quantile:
        return histogram_quantile(qv, gjb, les)
    return gjb


@functools.partial(jax.jit, static_argnames=(
    "func", "u_map", "num_groups", "is_delta", "quantile"
))
def _batched_hist_shared_jit(func, vals, lo_u, hi_u, tf_u, tl_u, out_t_u,
                             w_u, gids_q, les, qv_q, u_map: tuple,
                             num_groups: int, is_delta: bool,
                             quantile: bool):
    sjb_u = [
        _hist_range_shared(
            func, vals, lo_u[u], hi_u[u], tf_u[u], tl_u[u], out_t_u[u],
            w_u[u], is_delta
        )
        for u in range(max(u_map) + 1)
    ]
    return jnp.stack([
        _hist_epilogue(sjb_u[u_map[i]], gids_q[i], les, qv_q[i],
                       num_groups, quantile)
        for i in range(len(u_map))
    ])


@functools.partial(jax.jit, static_argnames=(
    "func", "u_map", "num_steps", "num_groups", "is_delta", "quantile"
))
def _batched_hist_jit(func, ts, vals, lens, gids_q, les, qv_q, so_u, sm_u,
                      w_u, u_map: tuple, num_steps: int, num_groups: int,
                      is_delta: bool, quantile: bool):
    sjb_u = [
        hist_range_kernel(
            func, ts, vals, lens, so_u[u], sm_u[u], w_u[u], num_steps,
            is_delta=is_delta,
        )
        for u in range(max(u_map) + 1)
    ]
    return jnp.stack([
        _hist_epilogue(sjb_u[u_map[i]], gids_q[i], les, qv_q[i],
                       num_groups, quantile)
        for i in range(len(u_map))
    ])


@functools.partial(jax.jit, static_argnames=(
    "mesh", "func", "u_map", "num_groups", "is_delta", "quantile"
))
def _batched_hist_shared_sharded_jit(mesh, func, vals, lo_u, hi_u, tf_u,
                                     tl_u, out_t_u, w_u, gids_q, les, qv_q,
                                     u_map: tuple, num_groups: int,
                                     is_delta: bool, quantile: bool):
    from jax.sharding import PartitionSpec as P

    from ..jax_compat import shard_map

    axis = mesh.axis_names[0]

    def local(vals_l, gids_ql):
        sjb_u = [
            _hist_range_shared(
                func, vals_l, lo_u[u], hi_u[u], tf_u[u], tl_u[u],
                out_t_u[u], w_u[u], is_delta
            )
            for u in range(max(u_map) + 1)
        ]
        return jnp.stack([
            _hist_sharded_combine(
                sjb_u[u_map[i]], gids_ql[i], les, qv_q[i], num_groups,
                quantile, axis
            )
            for i in range(len(u_map))
        ])

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(axis, None, None), P(None, axis)),
        out_specs=P(), check=False,
    )(vals, gids_q)


@functools.partial(jax.jit, static_argnames=(
    "mesh", "func", "u_map", "num_steps", "num_groups", "is_delta",
    "quantile"
))
def _batched_hist_sharded_jit(mesh, func, ts, vals, lens, gids_q, les, qv_q,
                              so_u, sm_u, w_u, u_map: tuple,
                              num_steps: int, num_groups: int,
                              is_delta: bool, quantile: bool):
    from jax.sharding import PartitionSpec as P

    from ..jax_compat import shard_map

    axis = mesh.axis_names[0]

    def local(ts_l, vals_l, lens_l, gids_ql):
        sjb_u = [
            hist_range_kernel(
                func, ts_l, vals_l, lens_l, so_u[u], sm_u[u], w_u[u],
                num_steps, is_delta=is_delta,
            )
            for u in range(max(u_map) + 1)
        ]
        return jnp.stack([
            _hist_sharded_combine(
                sjb_u[u_map[i]], gids_ql[i], les, qv_q[i], num_groups,
                quantile, axis
            )
            for i in range(len(u_map))
        ])

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None, None), P(axis),
                  P(None, axis)),
        out_specs=P(), check=False,
    )(ts, vals, lens, gids_q)


def run_hist_range_function(
    func: str, block: StagedBlock, params: RangeParams, is_delta: bool = False
):
    j_pad = pad_steps(params.num_steps)
    start_off = np.int32(params.start_ms - block.base_ms)
    return hist_range_kernel(
        func,
        block.ts,
        block.vals,
        block.lens,
        start_off,
        np.int32(params.step_ms),
        np.int32(params.window_ms),
        j_pad,
        is_delta=is_delta,
    )


# kernel-observatory registration (obs/kernels.py; linted by
# tools/check_metrics.py — every jit wrapper here must register)
def _register_kernel_observatory() -> None:
    from ..obs.kernels import KERNELS

    KERNELS.register_jits(
        "ops.hist_kernels",
        hist_range_kernel=hist_range_kernel,
        histogram_quantile=histogram_quantile,
        histogram_fraction=histogram_fraction,
        _fused_hist_jit=_fused_hist_jit,
        _fused_hist_shared_jit=_fused_hist_shared_jit,
        _fused_hist_jitter_jit=_fused_hist_jitter_jit,
        _fused_hist_jitter_sharded_jit=_fused_hist_jitter_sharded_jit,
        _fused_hist_shared_sharded_jit=_fused_hist_shared_sharded_jit,
        _fused_hist_sharded_jit=_fused_hist_sharded_jit,
        _batched_hist_jit=_batched_hist_jit,
        _batched_hist_shared_jit=_batched_hist_shared_jit,
        _batched_hist_shared_sharded_jit=_batched_hist_shared_sharded_jit,
        _batched_hist_sharded_jit=_batched_hist_sharded_jit,
    )


_register_kernel_observatory()
