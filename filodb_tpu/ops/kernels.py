"""TPU range-function kernels (reference L4 hot path re-designed for XLA).

The reference evaluates PromQL range functions per series per output step with
iterator state machines (rangefn/RangeFunction.scala:84, RateFunctions.scala:230,
AggrOverTimeFunctions.scala) plus Rust SIMD for inner sums
(simd_vectors.rs:174). Here ONE jit kernel computes the whole ``[S, J]``
output grid (S series x J output steps) from a staged ``[S, T]`` block:

- Window boundaries resolve by compare-and-reduce contractions
  (``#{ts <= t_j}``) which XLA fuses — no per-window iterators, no dynamic
  shapes, no data-dependent control flow.
- sum/count family reads prefix sums at the boundary indices (the parallel
  form of the reference's chunked running aggregates).
- Counter reset correction happens HOST-SIDE in f64 at staging
  (staging.counter_correct — the prefix-scan form of
  CounterChunkedRangeFunction's per-chunk carry); staged counter values are
  already corrected, so the device needs no correction pass.
- rate/increase/delta implement Prometheus extrapolation semantics
  (promql extrapolatedRate), which the reference's ChunkedRateFunctionBase
  also follows.
- Functions needing per-window sample *sets* (quantile_over_time, mad) sort
  masked windows in step blocks via lax.map to bound memory.

Everything is shape-static: S, T, J are padded-bucketed by staging, so the
jit cache stays tiny across queries.

Empty windows yield NaN; the serialization layer treats NaN as "no sample"
(Prometheus absence). Inputs are NaN-free by staging contract.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from .staging import StagedBlock

_NAN = jnp.nan


@dataclass(frozen=True)
class RangeParams:
    """Output grid + window spec. start/step/window ride as dynamic args;
    num_steps is static (padded to 64s by the caller via pad_steps)."""

    start_ms: int  # absolute ms of first output step
    step_ms: int
    num_steps: int
    window_ms: int


def pad_steps(j: int) -> int:
    return max(64, ((j + 63) // 64) * 64)


# ---------------------------------------------------------------------------
# shared window machinery (all [S, J] index math)
# ---------------------------------------------------------------------------


def _bounds(ts, lens, out_t, window):
    """hi/lo sample-count indices per (series, step).

    Window j = (out_t[j] - window, out_t[j]]. Returns (lo, hi): sample i is in
    the window iff lo <= i < hi. Padding slots carry TS_PAD and never match.
    """
    T = ts.shape[1]
    valid = jnp.arange(T, dtype=jnp.int32)[None, :] < lens[:, None]
    le = (ts[:, None, :] <= out_t[None, :, None]) & valid[:, None, :]
    hi = le.sum(-1, dtype=jnp.int32)
    lo_bound = out_t - window
    le2 = (ts[:, None, :] <= lo_bound[None, :, None]) & valid[:, None, :]
    lo = le2.sum(-1, dtype=jnp.int32)
    return lo, hi


def _gather(arr, idx):
    """arr [S, T], idx [S, J] -> [S, J] (idx clipped; caller masks validity)."""
    T = arr.shape[1]
    return jnp.take_along_axis(arr, jnp.clip(idx, 0, T - 1), axis=1)


def _prefix(vals):
    """[S, T] -> [S, T+1] exclusive prefix sum in f32."""
    cs = jnp.cumsum(vals, axis=1)
    return jnp.concatenate([jnp.zeros_like(cs[:, :1]), cs], axis=1)


def _window_mask(ts, lens, out_t, window):
    T = ts.shape[1]
    valid = jnp.arange(T, dtype=jnp.int32)[None, :] < lens[:, None]
    in_win = (
        (ts[:, None, :] <= out_t[None, :, None])
        & (ts[:, None, :] > (out_t - window)[None, :, None])
        & valid[:, None, :]
    )
    return in_win  # [S, J, T] — consumers must fuse-reduce, never materialize


def _extrapolated(delta, t_first, t_last, count, v_first_raw, out_t, window, is_counter, as_rate):
    """Prometheus extrapolatedRate: extrapolate the in-window delta to the
    window edges, capped at 1.1x the average sample spacing (and at the
    zero-crossing for counters)."""
    f32 = delta.dtype
    w_s = window.astype(f32) * 1e-3
    range_start = (out_t - window)[None, :].astype(f32) * 1e-3
    range_end = out_t[None, :].astype(f32) * 1e-3
    tf = t_first.astype(f32) * 1e-3
    tl = t_last.astype(f32) * 1e-3
    sampled = tl - tf
    cnt = count.astype(f32)
    dur_start = tf - range_start
    dur_end = range_end - tl
    avg_dur = sampled / jnp.maximum(cnt - 1.0, 1.0)
    if is_counter:
        dur_zero = jnp.where(delta > 0, sampled * (v_first_raw / jnp.maximum(delta, 1e-30)), jnp.inf)
        dur_start = jnp.minimum(dur_start, jnp.where(v_first_raw >= 0, dur_zero, jnp.inf))
    thresh = avg_dur * 1.1
    dur_start = jnp.where(dur_start >= thresh, avg_dur / 2.0, dur_start)
    dur_end = jnp.where(dur_end >= thresh, avg_dur / 2.0, dur_end)
    factor = (sampled + dur_start + dur_end) / jnp.maximum(sampled, 1e-30)
    result = delta * factor
    if as_rate:
        result = result / w_s
    return jnp.where(count >= 2, result, _NAN)


# ---------------------------------------------------------------------------
# the kernel: one jit per (func, S, T, J)
# ---------------------------------------------------------------------------



@functools.partial(
    jax.jit, static_argnames=("func", "num_steps", "is_counter", "is_delta")
)
def range_kernel(
    func: str,
    ts,  # [S, T] i32
    vals,  # [S, T] f32 (counters: reset-corrected minus baseline by staging)
    lens,  # [S] i32
    baseline,  # [S] f32
    raw,  # [S, T] f32 raw-minus-baseline (== vals for non-counters)
    start_off,  # scalar i32: first output step (offset ms)
    step_ms,  # scalar i32
    window,  # scalar i32
    num_steps: int,
    is_counter: bool = False,
    is_delta: bool = False,
    arg0=0.0,  # function scalar arg (quantile q, holt sf, predict horizon s)
    arg1=0.0,  # second scalar arg (holt tf)
):
    """Compute [S, num_steps] results for one range function."""
    S, T = ts.shape
    out_t = start_off + jnp.arange(num_steps, dtype=jnp.int32) * step_ms
    lo, hi = _bounds(ts, lens, out_t, window)
    count = (hi - lo).astype(jnp.float32)
    has = count > 0

    def prefix_sum_of(x):
        p = _prefix(x)  # [S, T+1] exclusive; sum over [lo, hi) = p[hi]-p[lo]
        return _gather(p, hi) - _gather(p, lo)

    # boundary samples
    t_first = _gather(ts, lo)
    t_last = _gather(ts, hi - 1)
    v_last = _gather(vals, hi - 1)
    v_first = _gather(vals, lo)

    if func in ("sum_over_time", "avg_over_time"):
        # masked in-window reduce, NOT a prefix-sum difference: prefix sums
        # accumulate the full history, so p[hi]-p[lo] catastrophically cancels
        # in f32 for large-magnitude (e.g. raw counter) values; summing only
        # in-window samples keeps the error relative to the window sum (XLA
        # fuses the mask into the reduction — nothing [S,J,T] materializes)
        m = _window_mask(ts, lens, out_t, window)
        s = jnp.where(m, vals[:, None, :], 0.0).sum(-1)
        if func == "avg_over_time":
            s = s / count
        return jnp.where(has, s, _NAN)
    if func == "count_over_time":
        return jnp.where(has, count, _NAN)
    if func in ("last", "last_over_time"):
        return jnp.where(has, v_last, _NAN)
    if func == "first_over_time":
        return jnp.where(has, v_first, _NAN)
    if func == "timestamp":
        # returns ms offsets; host adds base_ms and converts to seconds (f64)
        return jnp.where(has, t_last.astype(jnp.float32), _NAN)
    if func == "present_over_time":
        return jnp.where(has, 1.0, _NAN)
    if func == "absent_over_time":
        # 1.0 where NO sample; presenter turns it into an absent-vector
        return jnp.where(has, _NAN, 1.0)
    if func in ("min_over_time", "max_over_time"):
        m = _window_mask(ts, lens, out_t, window)
        big = jnp.float32(np.inf if func == "min_over_time" else -np.inf)
        w = jnp.where(m, vals[:, None, :], big)
        r = w.min(-1) if func == "min_over_time" else w.max(-1)
        return jnp.where(has, r, _NAN)
    if func in ("stddev_over_time", "stdvar_over_time", "z_score"):
        s = prefix_sum_of(vals)
        mean = s / jnp.maximum(count, 1.0)
        m = _window_mask(ts, lens, out_t, window)
        dev = jnp.where(m, (vals[:, None, :] - mean[:, :, None]) ** 2, 0.0)
        var = dev.sum(-1) / jnp.maximum(count, 1.0)
        if func == "stdvar_over_time":
            return jnp.where(has, var, _NAN)
        sd = jnp.sqrt(var)
        if func == "z_score":
            return jnp.where(has, (v_last - mean) / jnp.maximum(sd, 1e-30), _NAN)
        return jnp.where(has, sd, _NAN)
    if func in ("changes", "resets"):
        # MUST see raw (uncorrected) value movement: corrected counter vals
        # are monotone, so resets() over them would always be 0 and changes()
        # would miss every reset. Counter blocks stage f64-exact adjacent
        # diffs (staging mode "diff" — f32 values can't preserve tiny changes
        # next to 1e9 reset cliffs); gauges compare raw values directly.
        if is_counter and not is_delta:
            flag = (vals != 0) if func == "changes" else (vals < 0)
        else:
            prev = jnp.concatenate([raw[:, :1], raw[:, :-1]], axis=1)
            flag = (raw != prev) if func == "changes" else (raw < prev)
        idx = jnp.arange(T, dtype=jnp.int32)[None, None, :]
        pair_in = (idx > lo[:, :, None]) & (idx < hi[:, :, None])
        n = (pair_in & flag[:, None, :]).sum(-1).astype(jnp.float32)
        return jnp.where(has, n, _NAN)
    if func in ("deriv", "predict_linear"):
        # least-squares slope over (t - out_t) seconds, per window
        m = _window_mask(ts, lens, out_t, window)
        tc = (ts[:, None, :] - out_t[None, :, None]).astype(jnp.float32) * 1e-3
        tc = jnp.where(m, tc, 0.0)
        vm = jnp.where(m, vals[:, None, :], 0.0)
        st = tc.sum(-1)
        sv = vm.sum(-1)
        stt = (tc * tc).sum(-1)
        stv = (tc * vm).sum(-1)
        n = count
        denom = n * stt - st * st
        slope = (n * stv - st * sv) / jnp.where(jnp.abs(denom) < 1e-30, 1.0, denom)
        intercept = (sv - slope * st) / jnp.maximum(n, 1.0)
        ok = (count >= 2) & (jnp.abs(denom) >= 1e-30)
        if func == "deriv":
            return jnp.where(ok, slope, _NAN)
        return jnp.where(ok, intercept + slope * arg0, _NAN)
    if func == "double_exponential_smoothing":
        return _holt_winters(ts, vals, lens, out_t, window, lo, hi, arg0, arg1)

    # counter family ------------------------------------------------------
    if func in ("rate", "increase", "delta"):
        if is_delta:
            # delta-temporality counters: each sample IS the increase
            s = prefix_sum_of(vals)
            if func == "rate":
                r = s / (window.astype(jnp.float32) * 1e-3)
            else:
                r = s
            return jnp.where(has, r, _NAN)
        # vals are already reset-corrected by staging for counters, so the
        # plain in-window difference IS the corrected increase
        dlt = v_last - v_first
        v_first_raw = _gather(raw, lo)  # only read when is_counter (zero cap)
        use_counter = is_counter and func != "delta"
        return _extrapolated(
            dlt, t_first, t_last, count, v_first_raw, out_t, window,
            is_counter=use_counter, as_rate=(func == "rate"),
        )
    if func in ("irate", "idelta"):
        ok = (hi - lo) >= 2
        if func == "idelta" and is_counter and not is_delta:
            # counter idelta reads the staged f64-exact diff of the last pair
            return jnp.where(ok, _gather(vals, hi - 1), _NAN)
        t_prev = _gather(ts, hi - 2)
        v_prev = _gather(vals, hi - 2)
        dt_s = (t_last - t_prev).astype(jnp.float32) * 1e-3
        # irate on counters: corrected-value difference across a reset equals
        # the post-reset raw reading — Prometheus reset semantics, no branch
        dv = v_last - v_prev
        r = dv / jnp.maximum(dt_s, 1e-30) if func == "irate" else dv
        return jnp.where(ok, r, _NAN)

    raise ValueError(f"unknown range function {func}")


def _holt_winters(ts, vals, lens, out_t, window, lo, hi, sf, tf):
    """Holt's double exponential smoothing per window (reference
    RangeFunction.scala holt-winters). Sequential in samples: lax.scan over T
    carrying (level, trend) per (series, step)."""
    S, T = vals.shape
    J = out_t.shape[0]
    idx = jnp.arange(T, dtype=jnp.int32)

    def body(carry, t_i):
        # promql holtWinters recurrence: level0 = x0; the 2nd sample sets
        # trend = x1 - x0 and leaves level = x1; then the standard update.
        level, trend, n_seen = carry
        in_win = (t_i >= lo) & (t_i < hi)  # [S, J]
        x = vals[:, t_i][:, None]  # [S, 1]
        new_level = sf * x + (1 - sf) * (level + trend)
        new_trend = tf * (new_level - level) + (1 - tf) * trend
        lvl = jnp.where(
            in_win,
            jnp.where(n_seen == 0, x, jnp.where(n_seen == 1, x, new_level)),
            level,
        )
        trd = jnp.where(
            in_win,
            jnp.where(
                n_seen == 0,
                jnp.zeros_like(trend),
                jnp.where(n_seen == 1, x - level, new_trend),
            ),
            trend,
        )
        n2 = jnp.where(in_win, n_seen + 1, n_seen)
        return (lvl, trd, n2), None

    init = (
        jnp.zeros((S, J), vals.dtype),
        jnp.zeros((S, J), vals.dtype),
        jnp.zeros((S, J), jnp.int32),
    )
    (level, trend, n_seen), _ = jax.lax.scan(body, init, idx)
    return jnp.where(n_seen >= 2, level, _NAN)


# quantile / mad: need per-window sorts — run in step blocks to bound memory
@functools.partial(jax.jit, static_argnames=("func", "num_steps", "block"))
def sorted_window_kernel(
    func: str, ts, vals, lens, start_off, step_ms, window, num_steps: int,
    q=0.5, arg1=0.0, block: int = 16
):
    S, T = ts.shape
    out_t_all = start_off + jnp.arange(num_steps, dtype=jnp.int32) * step_ms

    def one_block(out_t):
        lo, hi = _bounds(ts, lens, out_t, window)
        count = (hi - lo).astype(jnp.float32)
        m = _window_mask(ts, lens, out_t, window)
        w = jnp.where(m, vals[:, None, :], jnp.inf)
        sw = jnp.sort(w, axis=-1)

        def interp_at(sorted_w, rank):
            lo_i = jnp.floor(rank).astype(jnp.int32)
            hi_i = jnp.ceil(rank).astype(jnp.int32)
            frac = rank - lo_i.astype(jnp.float32)
            v_lo = jnp.take_along_axis(sorted_w, lo_i[..., None], axis=-1)[..., 0]
            v_hi = jnp.take_along_axis(sorted_w, hi_i[..., None], axis=-1)[..., 0]
            return v_lo + (v_hi - v_lo) * frac

        def mad_of(cnt):
            med_rank = 0.5 * jnp.maximum(cnt - 1.0, 0.0)
            med = interp_at(sw, med_rank)
            dev = jnp.where(m, jnp.abs(vals[:, None, :] - med[:, :, None]), jnp.inf)
            sd = jnp.sort(dev, axis=-1)
            return med, interp_at(sd, med_rank)

        if func == "quantile_over_time":
            rank = jnp.clip(q, 0.0, 1.0) * jnp.maximum(count - 1.0, 0.0)
            r = interp_at(sw, rank)
        elif func == "median_absolute_deviation_over_time":
            _, r = mad_of(count)
        elif func == "last_over_time_is_mad_outlier":
            # (tolerance=q, bounds=arg1): emit the last value iff it lies
            # outside median +/- tolerance*MAD per the bounds mode
            # (reference LastOverTimeIsMadOutlierFunction,
            # AggrOverTimeFunctions.scala:488)
            med, mad = mad_of(count)
            tmax = jnp.where(m, ts[:, None, :], -(2**31) + 1).max(-1)
            lastv = jnp.where(m & (ts[:, None, :] == tmax[:, :, None]), vals[:, None, :], 0.0).sum(-1)
            lower = med - q * mad
            upper = med + q * mad
            is_out = ((lastv < lower) & (arg1 <= 1)) | ((lastv > upper) & (arg1 >= 1))
            r = jnp.where(is_out, lastv, _NAN)
        else:
            raise ValueError(func)
        return jnp.where(count > 0, r, _NAN)

    blocks = out_t_all.reshape(num_steps // block, block)
    out = jax.lax.map(one_block, blocks)  # [nb, S, block]
    return jnp.moveaxis(out, 0, 1).reshape(S, num_steps)


SORTED_FUNCS = {
    "quantile_over_time",
    "median_absolute_deviation_over_time",
    "last_over_time_is_mad_outlier",
}


# ---------------------------------------------------------------------------
# host-facing entry
# ---------------------------------------------------------------------------


def _host_timestamp(block: StagedBlock, params: RangeParams) -> np.ndarray:
    """timestamp() computed host-side from the int32 ts array in f64.

    The device grid is f32, which represents integer ms offsets exactly only
    up to 2^24 (~4.6h); Prometheus returns exact sample timestamps, so this
    function never goes through the f32 kernel path. Returns absolute
    seconds [S, J_pad] f64 (NaN = no sample in window)."""
    j_pad = pad_steps(params.num_steps)
    out_t = (
        np.int64(params.start_ms - block.base_ms)
        + np.arange(j_pad, dtype=np.int64) * params.step_ms
    )
    lens_np = np.asarray(block.lens)
    S = np.asarray(block.ts).shape[0]
    out = np.full((S, j_pad), np.nan)

    def row_for(ts1: np.ndarray) -> np.ndarray:
        hi = np.searchsorted(ts1, out_t, side="right")
        lo = np.searchsorted(ts1, out_t - params.window_ms, side="right")
        has = hi > lo
        t_last = ts1[np.minimum(hi - 1, len(ts1) - 1)]
        return np.where(has, (t_last + block.base_ms) / 1e3, np.nan)

    if block.regular_ts is not None and block.n_series > 0:
        ts1 = np.asarray(block.regular_ts)[: int(lens_np[0])].astype(np.int64)
        out[: block.n_series] = row_for(ts1)[None, :]
        return out
    # irregular grids: one batched searchsorted over all series via per-row
    # offsets (rows are sorted and TS_PAD sorts after every real offset)
    n = block.n_series
    if n == 0:
        return out
    ts_np = np.asarray(block.ts)[:n].astype(np.int64)
    T = ts_np.shape[1]
    lens_n = lens_np[:n].astype(np.int64)
    stride = np.int64(1) << 33  # > any int32 ms offset incl. TS_PAD
    row_off = (np.arange(n, dtype=np.int64) * stride)[:, None]
    flat = (ts_np + row_off).ravel()
    hi = np.searchsorted(flat, (out_t[None, :] + row_off).ravel(), side="right")
    lo = np.searchsorted(
        flat, ((out_t - params.window_ms)[None, :] + row_off).ravel(), side="right"
    )
    hi = np.minimum(hi.reshape(n, -1) - np.arange(n)[:, None] * T, lens_n[:, None])
    lo = np.minimum(lo.reshape(n, -1) - np.arange(n)[:, None] * T, lens_n[:, None])
    has = hi > lo
    t_last = np.take_along_axis(ts_np, np.maximum(hi - 1, 0), axis=1)
    out[:n] = np.where(has, (t_last + block.base_ms) / 1e3, np.nan)
    return out


def _jit_cache_size() -> int:
    """Combined compile-cache size of the kernels run_range_function can
    dispatch to — a growth across one dispatch means a compile happened
    (the hit/miss signal for filodb_jit_cache; SURVEY §7 calls
    recompilation the #1 risk, so hits/misses must be observable in
    production).

    Best-effort attribution under concurrency: a sibling thread's compile
    during this dispatch is counted as this dispatch's miss, and two racing
    first-dispatches of one shape may both count. Misses are therefore an
    UPPER bound — but a miss can only register while some cache genuinely
    grew, so the steady-state signal (misses must go to zero) is exact."""
    total = range_kernel._cache_size() + sorted_window_kernel._cache_size()
    try:
        from .mxu_jitter import jitter_masked_kernel, jitter_range_kernel
        from .mxu_kernels import mxu_minmax, mxu_range_kernel

        total += mxu_range_kernel._cache_size() + mxu_minmax._cache_size()
        total += jitter_range_kernel._cache_size() + jitter_masked_kernel._cache_size()
    except Exception:  # noqa: BLE001 — accounting must never break dispatch
        pass
    return total


def run_range_function(
    func: str,
    block: StagedBlock,
    params: RangeParams,
    is_counter: bool = False,
    is_delta: bool = False,
    args: tuple = (),
):
    """Dispatch one range function over a staged block (instrumented entry
    point: per-kernel dispatch latency + JIT cache hit/miss). Returns a
    device array [S, J_padded]; caller slices [:n_series, :num_steps]."""
    import time as _time

    from ..metrics import record_kernel_dispatch

    t0 = _time.perf_counter()
    before = _jit_cache_size()
    out, variant = _dispatch_range_function(
        func, block, params, is_counter=is_counter, is_delta=is_delta, args=args
    )
    s_, t_ = np.shape(block.ts)
    record_kernel_dispatch(
        func, _time.perf_counter() - t0, compiled=_jit_cache_size() > before,
        key={"variant": variant,
             "shapes": f"S{s_}xT{t_}xJ{pad_steps(params.num_steps)}"},
        result=out,
    )
    return out


def _dispatch_range_function(
    func: str,
    block: StagedBlock,
    params: RangeParams,
    is_counter: bool = False,
    is_delta: bool = False,
    args: tuple = (),
):
    """Returns ``(grid, variant)``: the variant is the ladder rung that
    actually served the dispatch — the observatory's executable-key
    ``variant`` dimension, reported by the rung that ran rather than
    re-derived (the jitter/masked fast paths can decline at runtime)."""
    from .mxu_kernels import MXU_FUNCS, run_mxu_range_function

    if func == "timestamp":
        return _host_timestamp(block, params), "host"
    if (
        block.regular_ts is not None
        and func in MXU_FUNCS
        and not (is_delta and func in ("irate", "idelta"))
    ):
        # shared-scrape-grid fast path: window reduction as MXU matmuls
        return run_mxu_range_function(
            func, block, params, is_counter=is_counter, is_delta=is_delta, args=args
        ), "mxu"
    if (
        block.nominal_ts is not None
        and not (is_delta and func in ("irate", "idelta"))
        and not args
    ):
        from .mxu_jitter import JITTER_FUNCS, run_jitter_range_function

        if func in JITTER_FUNCS:
            # near-regular (jittered scrape) fast path: certain-membership
            # matmul + per-series boundary corrections (mxu_jitter.py)
            res = run_jitter_range_function(
                func, block, params, is_counter=is_counter, is_delta=is_delta
            )
            if res is not None:
                return res, "jitter"
    if (
        block.mgrid is not None
        and not (is_delta and func in ("irate", "idelta"))
        and not args
    ):
        from .mxu_jitter import JITTER_FUNCS, run_masked_jitter_range_function

        if func in JITTER_FUNCS:
            # missing-scrape fast path: validity masks on the nominal grid
            # (a dropped scrape must not cost the 40x general-path penalty)
            res = run_masked_jitter_range_function(
                func, block, params, is_counter=is_counter, is_delta=is_delta
            )
            if res is not None:
                return res, "masked"
    from .pallas_kernels import (
        PALLAS_FUNCS,
        pallas_enabled,
        run_pallas_range_function,
    )

    if func in PALLAS_FUNCS and not args and pallas_enabled():
        import jax as _jax

        # the ONE FILODB_PALLAS policy (pallas_kernels.pallas_enabled),
        # shared with the fused dispatch ladder: the one-pass VMEM kernel
        # on real hardware, interpret mode on CPU only when forced
        return run_pallas_range_function(
            func, block, params, is_counter=is_counter, is_delta=is_delta,
            interpret=_jax.devices()[0].platform in ("cpu",),
        ), "pallas"
    j_pad = pad_steps(params.num_steps)
    start_off = np.int32(params.start_ms - block.base_ms)
    if func in SORTED_FUNCS:
        return sorted_window_kernel(
            func,
            block.ts,
            block.vals,
            block.lens,
            start_off,
            np.int32(params.step_ms),
            np.int32(params.window_ms),
            j_pad,
            q=np.float32(args[0]) if args else np.float32(0.5),
            arg1=np.float32(args[1]) if len(args) > 1 else np.float32(0.0),
        ), "sorted"
    a0 = np.float32(args[0]) if len(args) > 0 else np.float32(0.0)
    a1 = np.float32(args[1]) if len(args) > 1 else np.float32(0.0)
    return range_kernel(
        func,
        block.ts,
        block.vals,
        block.lens,
        block.baseline,
        block.raw if block.raw is not None else block.vals,
        start_off,
        np.int32(params.step_ms),
        np.int32(params.window_ms),
        j_pad,
        is_counter=is_counter,
        is_delta=is_delta,
        arg0=a0,
        arg1=a1,
    ), "general"


# kernel-observatory registration (obs/kernels.py; linted by
# tools/check_metrics.py — every jit wrapper here must register)
def _register_kernel_observatory() -> None:
    from ..obs.kernels import KERNELS

    KERNELS.register_jits(
        "ops.kernels",
        range_kernel=range_kernel,
        sorted_window_kernel=sorted_window_kernel,
    )


_register_kernel_observatory()
