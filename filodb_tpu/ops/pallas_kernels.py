"""Pallas TPU kernel: fused window aggregation for irregular series.

The general (non-shared-grid) path in kernels.py makes several passes over
the staged ``[S, T]`` block (bounds, prefix sums, boundary gathers). This
Pallas kernel computes ALL per-window statistics — count, sum, min, max,
first/last timestamp, first/last value, first raw value — in ONE pass with
the block resident in VMEM, tiled ``(BS series x BJ steps)`` over a grid
that reuses the series block across step tiles (the block index map keeps
ts/vals constant along the step axis, so Pallas skips the re-fetch DMA).

A small jit finisher then derives any range function from these statistics
(Prometheus extrapolation for rate/increase/delta). Runs in interpret mode
on CPU for tests; compiled on TPU via ``interpret=False``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .staging import StagedBlock

BS = 64   # series per tile (second-to-last block dim: multiple of 8)
BJ = 128  # steps per tile (last block dim: hardware requires a multiple of 128)
NEG = -3.0e38  # python literals: jnp scalars would be captured consts
POS = 3.0e38


def _window_agg_kernel(params_ref, ts_ref, vals_ref, raw_ref, lens_ref,
                       cnt_ref, sum_ref, min_ref, max_ref,
                       tf_ref, tl_ref, vf_ref, vl_ref, rf_ref):
    start = params_ref[0]
    step = params_ref[1]
    window = params_ref[2]
    j0 = pl.program_id(1) * BJ
    ts = ts_ref[:]  # [BS, T] i32
    vals = vals_ref[:]
    raw = raw_ref[:]
    lens = lens_ref[:]  # [BS, 1]
    T = ts.shape[1]
    lane = jax.lax.broadcasted_iota(jnp.int32, (ts.shape[0], T), 1)
    valid = lane < lens
    IMAX = jnp.int32(2**31 - 1)
    IMIN = jnp.int32(-(2**31) + 1)
    # column one-hot accumulation: per step jj compute [BS] stats and add
    # stat ⊗ onehot(jj) into [BS, BJ] carries — vector-only ops (no dynamic
    # stores), so Mosaic lowers it; a BJ=128 static unroll would explode
    # compile time and a (BS, <128) output block is rejected by hardware
    col = jax.lax.broadcasted_iota(jnp.int32, (1, BJ), 1)

    def body(jj, accs):
        t_j = start + (j0 + jj) * step
        m = (ts <= t_j) & (ts > t_j - window) & valid
        mf = m.astype(jnp.float32)
        cnt = mf.sum(axis=1)
        s = jnp.where(m, vals, 0.0).sum(axis=1)
        mn = jnp.where(m, vals, POS).min(axis=1)
        mx = jnp.where(m, vals, NEG).max(axis=1)
        # boundary selection in exact int32 time (f32 would round >2^24 ms)
        tmin = jnp.where(m, ts, IMAX).min(axis=1)
        tmax = jnp.where(m, ts, IMIN).max(axis=1)
        first_m = m & (ts == tmin[:, None])
        last_m = m & (ts == tmax[:, None])
        vf = jnp.where(first_m, vals, 0.0).sum(axis=1)
        vl = jnp.where(last_m, vals, 0.0).sum(axis=1)
        rf = jnp.where(first_m, raw, 0.0).sum(axis=1)
        hot = col == jj  # [1, BJ] bool
        new = (cnt, s, mn, mx, tmin.astype(jnp.float32), tmax.astype(jnp.float32), vf, vl, rf)
        # select, don't multiply: NaN stats (stale markers, parsed 'NaN'
        # samples) must stay confined to their own step (NaN * 0 == NaN)
        return tuple(a + jnp.where(hot, v[:, None], 0.0) for a, v in zip(accs, new))

    zero = jnp.zeros((ts.shape[0], BJ), jnp.float32)
    accs = jax.lax.fori_loop(0, BJ, body, (zero,) * 9)
    for ref, acc in zip(
        (cnt_ref, sum_ref, min_ref, max_ref, tf_ref, tl_ref, vf_ref, vl_ref, rf_ref), accs
    ):
        ref[:] = acc


@functools.partial(jax.jit, static_argnames=("num_steps", "interpret"))
def window_aggregates(ts, vals, raw, lens, start_off, step_ms, window_ms,
                      num_steps: int, interpret: bool = True):
    """[S, T] staged block -> dict of [S, num_steps] per-window statistics."""
    S, T = ts.shape
    S_pad = ((S + BS - 1) // BS) * BS
    J = ((num_steps + BJ - 1) // BJ) * BJ
    if S_pad != S:
        pad = ((0, S_pad - S), (0, 0))
        ts = jnp.pad(ts, pad, constant_values=2**31 - 1)
        vals = jnp.pad(vals, pad)
        raw = jnp.pad(raw, pad)
        lens = jnp.pad(lens, ((0, S_pad - S),))
    from jax.experimental.pallas import tpu as pltpu

    params = jnp.stack([start_off, step_ms, window_ms]).astype(jnp.int32)
    lens2 = lens[:, None].astype(jnp.int32)
    grid = (S_pad // BS, J // BJ)
    # index maps receive the scalar-prefetch ref as a trailing arg
    row_spec = pl.BlockSpec((BS, T), lambda i, j, *_: (i, 0))
    out_spec = pl.BlockSpec((BS, BJ), lambda i, j, *_: (i, j))
    out_shape = [jax.ShapeDtypeStruct((S_pad, J), jnp.float32)] * 9
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,  # params land in SMEM before the pipeline
        grid=grid,
        in_specs=[row_spec, row_spec, row_spec, pl.BlockSpec((BS, 1), lambda i, j, *_: (i, 0))],
        out_specs=[out_spec] * 9,
    )
    outs = pl.pallas_call(
        _window_agg_kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(params, ts, vals, raw, lens2)
    names = ("count", "sum", "min", "max", "t_first", "t_last", "v_first", "v_last", "raw_first")
    return dict(zip(names, outs))


PALLAS_FUNCS = {
    "sum_over_time", "count_over_time", "avg_over_time", "min_over_time",
    "max_over_time", "last", "last_over_time", "first_over_time",
    "present_over_time", "absent_over_time", "rate", "increase", "delta",
}


def pallas_enabled() -> bool:
    """The ONE FILODB_PALLAS policy, shared by the legacy range-function
    dispatch (kernels._dispatch_range_function) and the fused variant
    ladder (aggregations._pallas_variant): "0" disables outright; "auto"
    (default) selects the one-pass VMEM kernel on real accelerators only
    (measured ~23% over the multi-pass general path on irregular blocks,
    BENCH_LOCAL.json pallas_vs_general); "1" forces it everywhere —
    interpret mode on CPU, which is for tests."""
    import os

    mode = os.environ.get("FILODB_PALLAS", "auto")
    if mode == "0":
        return False
    return jax.devices()[0].platform not in ("cpu",) or mode == "1"


@functools.partial(jax.jit, static_argnames=("func", "is_counter", "is_delta"))
def finish(func: str, agg: dict, start_off, step_ms, window_ms,
           is_counter: bool = False, is_delta: bool = False):
    """Derive a range function from the fused window statistics."""
    cnt = agg["count"]
    has = cnt > 0
    nan = jnp.nan
    if func == "sum_over_time" or (is_delta and func in ("rate", "increase")):
        r = agg["sum"]
        if func == "rate":
            r = r / (window_ms.astype(jnp.float32) * 1e-3)
        return jnp.where(has, r, nan)
    if func == "count_over_time":
        return jnp.where(has, cnt, nan)
    if func == "avg_over_time":
        return jnp.where(has, agg["sum"] / jnp.maximum(cnt, 1.0), nan)
    if func == "min_over_time":
        return jnp.where(has, agg["min"], nan)
    if func == "max_over_time":
        return jnp.where(has, agg["max"], nan)
    if func in ("last", "last_over_time"):
        return jnp.where(has, agg["v_last"], nan)
    if func == "first_over_time":
        return jnp.where(has, agg["v_first"], nan)
    if func == "present_over_time":
        return jnp.where(has, 1.0, nan)
    if func == "absent_over_time":
        return jnp.where(has, nan, 1.0)
    if func in ("rate", "increase", "delta"):
        J = cnt.shape[1]
        out_t = (start_off + jnp.arange(J, dtype=jnp.int32) * step_ms).astype(jnp.float32)
        f32 = jnp.float32
        w_s = window_ms.astype(f32) * 1e-3
        tf = agg["t_first"] * 1e-3
        tl = agg["t_last"] * 1e-3
        dlt = agg["v_last"] - agg["v_first"]
        sampled = tl - tf
        dur_start = tf - (out_t - window_ms.astype(f32))[None, :] * 1e-3
        dur_end = out_t[None, :] * 1e-3 - tl
        avg_dur = sampled / jnp.maximum(cnt - 1.0, 1.0)
        thresh = avg_dur * 1.1
        if is_counter and func != "delta":
            dur_zero = jnp.where(dlt > 0, sampled * (agg["raw_first"] / jnp.maximum(dlt, 1e-30)), jnp.inf)
            dur_start = jnp.minimum(dur_start, jnp.where(agg["raw_first"] >= 0, dur_zero, jnp.inf))
        dur_start = jnp.where(dur_start >= thresh, avg_dur / 2.0, dur_start)
        dur_end = jnp.where(dur_end >= thresh, avg_dur / 2.0, dur_end)
        factor = (sampled + dur_start + dur_end) / jnp.maximum(sampled, 1e-30)
        res = dlt * factor
        if func == "rate":
            res = res / w_s
        return jnp.where(cnt >= 2, res, nan)
    raise ValueError(f"pallas path does not support {func}")


def run_pallas_range_function(func: str, block: StagedBlock, params,
                              is_counter=False, is_delta=False, interpret=True):
    from .kernels import pad_steps

    J = pad_steps(params.num_steps)
    start_off = np.int32(params.start_ms - block.base_ms)
    raw = block.raw if block.raw is not None else block.vals
    agg = window_aggregates(
        block.ts, block.vals, raw, block.lens,
        start_off, np.int32(params.step_ms), np.int32(params.window_ms), J,
        interpret=interpret,
    )
    return finish(func, agg, start_off, np.int32(params.step_ms), np.int32(params.window_ms),
                  is_counter=is_counter, is_delta=is_delta)


# kernel-observatory registration (obs/kernels.py; linted by
# tools/check_metrics.py — every jit wrapper here must register)
def _register_kernel_observatory() -> None:
    from ..obs.kernels import KERNELS

    KERNELS.register_jits(
        "ops.pallas_kernels",
        window_aggregates=window_aggregates,
        finish=finish,
    )


_register_kernel_observatory()
