"""Chunk codecs: the numeric substrate (reference L0, filodb.memory.format).

The reference stores each sealed chunk column as an immutable off-heap
BinaryVector (BinaryVector.scala:19) in one of several wire formats
(WireFormat.scala:8-38): delta-delta longs (DeltaDeltaVector.scala:28),
NibblePack'd XOR doubles (NibblePack.scala:12, doc/compression.md:33-69),
bit-packed ints (IntBinaryVector.scala), and 2D-delta histograms
(HistogramVector.scala). This module re-designs those codecs for a host that
stages *decoded fixed-shape arrays* to TPU HBM: codecs are vectorized numpy
transforms over whole chunks (encode once at seal time, decode once at stage
time) instead of per-element cursors. Formats are our own — byte-compatibility
with the reference is a non-goal.

Wire formats implemented here:

- ``DeltaDelta``  — int64 sequences as base + slope + zigzag residuals,
                    NibblePack'd; constant-slope shortcut (reference
                    DeltaDeltaVector.scala:46-60 "const vector").
- ``XorDouble``   — float64 as u64 XOR-with-previous streams, NibblePack'd
                    (reference packDoubles, NibblePack.scala:73).
- ``NibblePack``  — groups of 8 u64: nonzero bitmask byte + (trailing-zero
                    nibbles, nibble count) header + packed nibbles (reference
                    NibblePack.scala:108 pack8). Python impl here; C++
                    acceleration in native/codecs.cpp behind the same API.
- ``Delta2DHist`` — histogram chunks [T, B]: delta over time then over bucket
                    axis, zigzag + NibblePack (reference HistogramVector 2DDELTA).

Every codec round-trips exactly (lossless), including NaN payloads for
doubles — NaN is Prometheus staleness and is load-bearing (SURVEY.md §7).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np

# Wire-format tags (our analog of WireFormat.scala vector type/subtype tags).
FMT_CONST_DELTA = 1  # perfectly linear int64 sequence: base+slope only
FMT_DELTA_DELTA = 2  # int64: base+slope+nibblepacked zigzag residuals
FMT_XOR_DOUBLE = 3  # float64: xor-prev, nibblepacked
FMT_RAW_I64 = 4  # fallback
FMT_RAW_F64 = 5  # fallback
FMT_DELTA2D_HIST = 6  # [T, B] int64 histogram: 2D delta, nibblepacked
FMT_INT_PACK = 7  # small ints bit-packed to minimal nbits

_HEADER = struct.Struct("<BxHI")  # fmt, pad, reserved, n_elements


def _zigzag(v: np.ndarray) -> np.ndarray:
    """Map signed int64 -> unsigned u64 with small magnitudes staying small."""
    v = v.astype(np.int64)
    return ((v << np.int64(1)) ^ (v >> np.int64(63))).astype(np.uint64)


def _unzigzag(u: np.ndarray) -> np.ndarray:
    u = u.astype(np.uint64)
    return ((u >> np.uint64(1)).astype(np.int64)) ^ -(u & np.uint64(1)).astype(np.int64)


# ---------------------------------------------------------------------------
# NibblePack: 8-at-a-time nibble packing of u64 streams.
# Group layout: [bitmask u8] then, if bitmask != 0:
#   [header u8: low nibble = nnibbles-1, high nibble = trailing-zero nibbles]
#   then nnibbles nibbles per nonzero value, low-nibble-first, byte-padded
#   per group.
# ---------------------------------------------------------------------------


def nibble_pack(values: np.ndarray) -> bytes:
    """Pack a u64 array. Dispatches to the C++ library (native/codecs.cpp)
    when built; the Python group loop below is the reference fallback."""
    from ..native import nibble_pack_native

    out = nibble_pack_native(values)
    if out is not None:
        return out
    return _nibble_pack_py(values)


def _nibble_pack_py(values: np.ndarray) -> bytes:
    v = np.ascontiguousarray(values, dtype=np.uint64)
    n = len(v)
    out = bytearray()
    for g0 in range(0, n, 8):
        grp = v[g0 : g0 + 8]
        nz = grp != 0
        bitmask = 0
        for i, x in enumerate(nz):
            if x:
                bitmask |= 1 << i
        out.append(bitmask)
        if bitmask == 0:
            continue
        nzvals = grp[nz]
        # trailing / leading zero nibbles across all nonzero values
        tz_bits = 64
        lz_bits = 64
        for x in nzvals:
            xi = int(x)
            tz_bits = min(tz_bits, (xi & -xi).bit_length() - 1)
            lz_bits = min(lz_bits, 64 - xi.bit_length())
        tz_nib = tz_bits // 4
        lz_nib = lz_bits // 4
        nnib = max(1, 16 - tz_nib - lz_nib)
        out.append(((tz_nib & 0xF) << 4) | (nnib - 1))
        # emit nibbles low-first
        acc = 0
        acc_n = 0
        for x in nzvals:
            xi = int(x) >> (tz_nib * 4)
            for k in range(nnib):
                acc |= ((xi >> (4 * k)) & 0xF) << (4 * acc_n)
                acc_n += 1
                if acc_n == 2:
                    out.append(acc)
                    acc = 0
                    acc_n = 0
        if acc_n:
            out.append(acc)
    return bytes(out)


def nibble_unpack(data: bytes, n: int) -> np.ndarray:
    """Inverse of :func:`nibble_pack`; returns u64 array of length n."""
    from ..native import nibble_unpack_native

    out = nibble_unpack_native(data, n)
    if out is not None:
        return out
    return _nibble_unpack_py(data, n)


def _nibble_unpack_py(data: bytes, n: int) -> np.ndarray:
    out = np.zeros(n, dtype=np.uint64)
    pos = 0
    i = 0
    mv = memoryview(data)
    while i < n:
        glen = min(8, n - i)
        bitmask = mv[pos]
        pos += 1
        if bitmask == 0:
            i += glen
            continue
        hdr = mv[pos]
        pos += 1
        tz_nib = hdr >> 4
        nnib = (hdr & 0xF) + 1
        n_nz = bin(bitmask).count("1")
        total_nibbles = n_nz * nnib
        nbytes = (total_nibbles + 1) // 2
        chunk = int.from_bytes(mv[pos : pos + nbytes], "little")
        pos += nbytes
        vi = 0
        mask_nib = (1 << (4 * nnib)) - 1
        for b in range(glen):
            if bitmask & (1 << b):
                val = (chunk >> (4 * nnib * vi)) & mask_nib
                out[i + b] = np.uint64((val << (4 * tz_nib)) & 0xFFFFFFFFFFFFFFFF)
                vi += 1
        i += glen
    return out


# ---------------------------------------------------------------------------
# Column codecs
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Encoded:
    """An encoded chunk column: wire format tag + payload bytes + length."""

    fmt: int
    n: int
    payload: bytes

    def to_bytes(self) -> bytes:
        return _HEADER.pack(self.fmt, 0, self.n) + self.payload

    @staticmethod
    def from_bytes(b: bytes) -> "Encoded":
        fmt, _, n = _HEADER.unpack_from(b)
        return Encoded(fmt, n, bytes(b[_HEADER.size :]))

    @property
    def nbytes(self) -> int:
        return _HEADER.size + len(self.payload)


def encode_int64(ts: np.ndarray) -> Encoded:
    """Delta-delta encode int64 (timestamps, integral doubles, counts).

    Mirrors DeltaDeltaVector.scala:28 — base + per-step slope + residuals —
    with the const shortcut of :46-60 when the sequence is exactly linear.
    """
    ts = np.ascontiguousarray(ts, dtype=np.int64)
    n = len(ts)
    if n == 0:
        return Encoded(FMT_CONST_DELTA, 0, struct.pack("<qq", 0, 0))
    base = int(ts[0])
    slope = int(round((int(ts[-1]) - base) / (n - 1))) if n > 1 else 0
    pred = base + slope * np.arange(n, dtype=np.int64)
    resid = ts - pred
    if not resid.any():
        return Encoded(FMT_CONST_DELTA, n, struct.pack("<qq", base, slope))
    packed = nibble_pack(_zigzag(resid))
    if len(packed) >= 8 * n:  # incompressible
        return Encoded(FMT_RAW_I64, n, ts.tobytes())
    return Encoded(FMT_DELTA_DELTA, n, struct.pack("<qq", base, slope) + packed)


def encode_double(vals: np.ndarray) -> Encoded:
    """Encode float64 values.

    Integral-valued runs auto-promote to delta-delta int64 (the reference does
    the same, DoubleVector.scala:86-99); otherwise XOR-with-previous then
    NibblePack (NibblePack.scala:73 packDoubles). NaNs round-trip bit-exactly.
    """
    vals = np.ascontiguousarray(vals, dtype=np.float64)
    n = len(vals)
    finite = np.isfinite(vals)
    if n and finite.all():
        as_int = vals.astype(np.int64)
        if (as_int == vals).all() and np.abs(vals).max() < 2**53:
            enc = encode_int64(as_int)
            if enc.fmt != FMT_RAW_I64:
                return enc
    bits = vals.view(np.uint64)
    xored = np.empty_like(bits)
    if n:
        xored[0] = bits[0]
        xored[1:] = bits[1:] ^ bits[:-1]
    packed = nibble_pack(xored)
    if len(packed) >= 8 * n:
        return Encoded(FMT_RAW_F64, n, vals.tobytes())
    return Encoded(FMT_XOR_DOUBLE, n, packed)


def encode_hist(counts: np.ndarray) -> Encoded:
    """Encode a histogram chunk ``[T, B]`` of cumulative bucket counts.

    2D delta (reference HistogramVector.scala 2DDELTA subtype): delta along
    time then along bucket axis leaves near-zero residuals for smooth
    cumulative histograms; zigzag + NibblePack.
    """
    counts = np.ascontiguousarray(counts, dtype=np.int64)
    t, b = counts.shape
    d_time = np.diff(counts, axis=0, prepend=counts[:1] * 0)
    d_time[0] = counts[0]
    d2 = np.diff(d_time, axis=1, prepend=d_time[:, :1] * 0)
    d2[:, 0] = d_time[:, 0]
    packed = nibble_pack(_zigzag(d2.ravel()))
    return Encoded(FMT_DELTA2D_HIST, t * b, struct.pack("<ii", t, b) + packed)


def encode_int_packed(vals: np.ndarray) -> Encoded:
    """Bit-packed small ints (reference IntBinaryVector.scala: 1/2/4/8/16/32
    nbits minimal-width packing). Values are offset by min then packed at the
    smallest power-of-two bit width that fits."""
    v = np.ascontiguousarray(vals, dtype=np.int64)
    n = len(v)
    if n == 0:
        return Encoded(FMT_INT_PACK, 0, struct.pack("<qB", 0, 8))
    base = int(v.min())
    u = (v - base).astype(np.uint64)
    vmax = int(u.max())
    nbits = 1
    for cand in (1, 2, 4, 8, 16, 32, 64):
        if vmax < (1 << cand):
            nbits = cand
            break
    if nbits == 64:
        return encode_int64(vals)
    if nbits >= 8:
        packed = u.astype({8: np.uint8, 16: np.uint16, 32: np.uint32}[nbits]).tobytes()
    else:
        per_byte = 8 // nbits
        pad = (-n) % per_byte
        up = np.concatenate([u, np.zeros(pad, np.uint64)]).astype(np.uint8)
        up = up.reshape(-1, per_byte)
        shifts = (np.arange(per_byte, dtype=np.uint8) * nbits).astype(np.uint8)
        packed = np.bitwise_or.reduce(up << shifts, axis=1).astype(np.uint8).tobytes()
    return Encoded(FMT_INT_PACK, n, struct.pack("<qB", base, nbits) + packed)


FMT_DICT_UTF8 = 8  # dictionary-encoded strings


def encode_utf8_dict(strings: list) -> Encoded:
    """Dictionary-encoded UTF8 column (reference DictUTF8Vector.scala):
    unique blob table + per-row codes (bit-packed)."""
    uniq: dict[str, int] = {}
    codes = np.empty(len(strings), dtype=np.int64)
    for i, s in enumerate(strings):
        c = uniq.setdefault(s, len(uniq))
        codes[i] = c
    blob = b"\x00".join(s.encode() for s in uniq)
    code_enc = encode_int_packed(codes)
    payload = struct.pack("<II", len(uniq), len(blob)) + blob + code_enc.to_bytes()
    return Encoded(FMT_DICT_UTF8, len(strings), payload)


def decode_utf8_dict(enc: Encoded) -> list:
    n_uniq, blob_len = struct.unpack_from("<II", enc.payload)
    blob = enc.payload[8 : 8 + blob_len]
    table = [b.decode() for b in blob.split(b"\x00")] if n_uniq else []
    codes = decode(Encoded.from_bytes(enc.payload[8 + blob_len :]))
    return [table[c] for c in codes]


class CorruptVectorError(ValueError):
    """Decode failure on a damaged payload (reference CorruptVectorException,
    ChunkSetInfo.scala:424 — detect, don't crash the process)."""


def decode(enc: Encoded) -> np.ndarray:
    """Decode any Encoded column back to its numpy array. Malformed payloads
    raise CorruptVectorError."""
    try:
        return _decode(enc)
    except CorruptVectorError:
        raise
    except (struct.error, IndexError, ValueError, ZeroDivisionError) as e:
        raise CorruptVectorError(f"corrupt vector (fmt={enc.fmt}, n={enc.n}): {e}") from e


def _decode(enc: Encoded) -> np.ndarray:
    if enc.fmt == FMT_CONST_DELTA:
        base, slope = struct.unpack_from("<qq", enc.payload)
        return base + slope * np.arange(enc.n, dtype=np.int64)
    if enc.fmt == FMT_DELTA_DELTA:
        base, slope = struct.unpack_from("<qq", enc.payload)
        resid = _unzigzag(nibble_unpack(enc.payload[16:], enc.n))
        return base + slope * np.arange(enc.n, dtype=np.int64) + resid
    if enc.fmt == FMT_XOR_DOUBLE:
        xored = nibble_unpack(enc.payload, enc.n)
        bits = np.bitwise_xor.accumulate(xored)
        return bits.view(np.float64).copy()
    if enc.fmt == FMT_RAW_I64:
        return np.frombuffer(enc.payload, dtype=np.int64, count=enc.n).copy()
    if enc.fmt == FMT_RAW_F64:
        return np.frombuffer(enc.payload, dtype=np.float64, count=enc.n).copy()
    if enc.fmt == FMT_DELTA2D_HIST:
        t, b = struct.unpack_from("<ii", enc.payload)
        d2 = _unzigzag(nibble_unpack(enc.payload[8:], t * b)).reshape(t, b)
        d_time = np.cumsum(d2, axis=1)
        return np.cumsum(d_time, axis=0)
    if enc.fmt == FMT_INT_PACK:
        base, nbits = struct.unpack_from("<qB", enc.payload)
        data = enc.payload[9:]
        n = enc.n
        if n == 0:
            return np.empty(0, dtype=np.int64)
        if nbits >= 8:
            dt = {8: np.uint8, 16: np.uint16, 32: np.uint32}[nbits]
            u = np.frombuffer(data, dtype=dt, count=n).astype(np.int64)
        else:
            per_byte = 8 // nbits
            raw = np.frombuffer(data, dtype=np.uint8)
            shifts = (np.arange(per_byte, dtype=np.uint8) * nbits).astype(np.uint8)
            mask = np.uint8((1 << nbits) - 1)
            u = ((raw[:, None] >> shifts) & mask).reshape(-1)[:n].astype(np.int64)
        return base + u
    raise ValueError(f"unknown wire format {enc.fmt}")


def decode_double(enc: Encoded) -> np.ndarray:
    """Decode to float64 regardless of the on-wire integer promotion."""
    return decode(enc).astype(np.float64, copy=False)
