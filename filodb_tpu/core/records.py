"""Ingestion records (reference L1: binaryrecord2/RecordBuilder.scala:34,
RecordContainer.scala:28).

The reference streams BinaryRecords into off-heap containers that are also
the Kafka message format. Here the unit of ingest is a columnar
``RecordBatch``: numpy arrays per column plus per-record series tags — the
natural bulk form for both the host ingest loop and eventual TPU staging.
A ``SeriesBatch`` is the grouped form (one series, many samples) that the
memstore ingest hot path consumes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Mapping, Sequence

import numpy as np

from .schemas import Schema, canonical_partkey, partkey_hash, shard_for


@dataclass
class RecordBatch:
    """Columnar batch of ingestion records sharing one schema.

    values maps column name -> array of shape [N] (DOUBLE/LONG) or [N, B]
    (HISTOGRAM). ``tags[i]`` is record i's full series tag map.
    """

    schema: Schema
    timestamps: np.ndarray
    values: dict[str, np.ndarray]
    tags: Sequence[Mapping[str, str]]
    bucket_les: np.ndarray | None = None  # histogram schemas only

    def __len__(self) -> int:
        return len(self.timestamps)

    def group_by_series(self) -> "list[SeriesBatch]":
        """Group records by partition key, preserving time order within series.

        Hot path: producers typically repeat the same tags OBJECT for every
        sample of a series AND emit each series' samples contiguously, so
        grouping walks runs of identical objects (one identity check per
        row) instead of paying per-row dict ops, and partkeys memoize by
        object identity before falling back to canonical hashing. Batches
        with fresh dicts per row or interleaved series degrade gracefully
        to per-row runs.

        Contract: a single-run (contiguous) series returns slice VIEWS of
        the batch columns — callers must not mutate either side after
        grouping (every in-repo consumer copies on ingest)."""
        groups: dict[bytes, list] = {}
        keys: dict[bytes, Mapping[str, str]] = {}
        memo: dict[int, bytes] = {}
        tags = self.tags
        n = len(tags)
        i = 0
        while i < n:
            t = tags[i]
            j = i + 1
            while j < n and tags[j] is t:
                j += 1
            pk = memo.get(id(t))
            if pk is None:
                pk = canonical_partkey(t)
                memo[id(t)] = pk
            runs = groups.get(pk)
            if runs is None:
                groups[pk] = [(i, j)]
                keys[pk] = t
            else:
                runs.append((i, j))
            i = j
        out = []
        for pk, runs in groups.items():
            if len(runs) == 1:
                lo, hi = runs[0]
                ix = slice(lo, hi)
            elif all(hi - lo == 1 for lo, hi in runs):
                # fresh-dict-per-row producers (CSV/TCP/JSONL gateways):
                # every row is its own run — index directly, no per-row
                # arange allocations
                ix = np.asarray([lo for lo, _ in runs])
            else:
                ix = np.concatenate(
                    [np.arange(lo, hi) for lo, hi in runs]
                )
            out.append(
                SeriesBatch(
                    schema=self.schema,
                    tags=dict(keys[pk]),
                    timestamps=self.timestamps[ix],
                    values={k: v[ix] for k, v in self.values.items()},
                    bucket_les=self.bucket_les,
                )
            )
        return out

    def shard_split(self, spread: int, num_shards: int, options=None) -> dict[int, "RecordBatch"]:
        """Partition a batch by destination shard (gateway shardingPipeline
        analog, GatewayServer.scala:335). Shard memoized per tags object.
        ``options`` (DatasetOptions) selects the shard-key columns."""
        from .schemas import DatasetOptions

        options = options or DatasetOptions()
        memo: dict[int, int] = {}

        def shard_memo(t):
            s = memo.get(id(t))
            if s is None:
                s = shard_for(t, spread, num_shards, options)
                memo[id(t)] = s
            return s

        shard_of = np.array([shard_memo(t) for t in self.tags])
        out: dict[int, RecordBatch] = {}
        for s in np.unique(shard_of):
            ix = np.nonzero(shard_of == s)[0]
            out[int(s)] = RecordBatch(
                self.schema,
                self.timestamps[ix],
                {k: v[ix] for k, v in self.values.items()},
                [self.tags[i] for i in ix],
                self.bucket_les,
            )
        return out


@dataclass
class SeriesBatch:
    """Samples for a single series (one partition key), time-ordered."""

    schema: Schema
    tags: Mapping[str, str]
    timestamps: np.ndarray
    values: dict[str, np.ndarray]
    bucket_les: np.ndarray | None = None

    @property
    def partkey(self) -> bytes:
        return canonical_partkey(self.tags)

    @property
    def partkey_hash(self) -> int:
        return partkey_hash(self.tags)


def gauge_batch(
    metric: str,
    samples: Iterable[tuple[Mapping[str, str], int, float]],
    schema: Schema | None = None,
) -> RecordBatch:
    """Convenience builder: (tags, ts_ms, value) triples -> RecordBatch."""
    from .schemas import GAUGE, METRIC_TAG

    schema = schema or GAUGE
    tags_list, ts, vals = [], [], []
    for tags, t, v in samples:
        full = dict(tags)
        full.setdefault(METRIC_TAG, metric)
        tags_list.append(full)
        ts.append(t)
        vals.append(v)
    col = schema.value_column
    return RecordBatch(
        schema,
        np.asarray(ts, dtype=np.int64),
        {col: np.asarray(vals, dtype=np.float64)},
        tags_list,
    )
