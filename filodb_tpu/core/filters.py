"""Column filters for series selection (reference core/.../query/Filter —
Equals / NotEquals / EqualsRegex / NotEqualsRegex / In / NotIn over tag values).

PromQL matcher semantics: regex matchers are fully anchored (^...$).
"""

from __future__ import annotations

import re
from dataclasses import dataclass


@dataclass(frozen=True)
class ColumnFilter:
    column: str
    op: str  # "=", "!=", "=~", "!~", "in", "not in"
    value: str | tuple[str, ...]

    def matches(self, v: str | None) -> bool:
        val = v if v is not None else ""
        if self.op == "=":
            return val == self.value
        if self.op == "!=":
            return val != self.value
        if self.op == "=~":
            return re.fullmatch(self.value, val) is not None
        if self.op == "!~":
            return re.fullmatch(self.value, val) is None
        if self.op == "in":
            return val in self.value
        if self.op == "not in":
            return val not in self.value
        raise ValueError(f"unknown filter op {self.op}")


def equals(column: str, value: str) -> ColumnFilter:
    return ColumnFilter(column, "=", value)


def regex(column: str, pattern: str) -> ColumnFilter:
    return ColumnFilter(column, "=~", pattern)
