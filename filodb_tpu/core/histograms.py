"""Histogram bucket schemes (reference L0 filodb.memory format/vectors/
Histogram.scala:609-899 — Geometric, Custom, Base2Exponential schemes;
quantile/fraction math at :64-130 moves to ops/kernels.py on device).

A histogram sample is a vector of cumulative bucket counts aligned to a
bucket scheme; the top bucket is +Inf. Native histograms are first-class:
chunks store ``[T, B]`` count arrays (ideal TPU layout), and
histogram_quantile runs as a vectorized kernel over ``[S, T, B]`` blocks.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class BucketScheme:
    """Bucket upper bounds (``le`` values), last = +inf."""

    les: tuple[float, ...]

    @property
    def num_buckets(self) -> int:
        return len(self.les)

    def bounds(self) -> np.ndarray:
        return np.asarray(self.les, dtype=np.float64)


def custom_buckets(les) -> BucketScheme:
    les = tuple(float(x) for x in les)
    if les[-1] != np.inf:
        les = les + (np.inf,)
    return BucketScheme(les)


def geometric_buckets(first: float, multiplier: float, num: int) -> BucketScheme:
    """reference GeometricBuckets (Histogram.scala:609)."""
    les = tuple(first * multiplier**i for i in range(num)) + (np.inf,)
    return BucketScheme(les)


def base2_exp_buckets(scale: int, start_index: int, num: int) -> BucketScheme:
    """OTel base-2 exponential scheme (reference Base2ExpHistogramBuckets,
    Histogram.scala:684): bucket i upper bound = 2^((start+i+1) * 2^-scale),
    with a zero bucket first."""
    base = 2.0 ** (2.0**-scale)
    les = (0.0,) + tuple(base ** (start_index + i + 1) for i in range(num)) + (np.inf,)
    return BucketScheme(les)


# The reference's default Prometheus-style scheme used by test fixtures
PROM_DEFAULT = custom_buckets(
    [0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10]
)


# -- bucket-scheme unification (heterogeneous schemes across shards) --------
#
# The reference resizes histograms onto a common scheme before HistSum
# (Histogram.scala HistogramWithBuckets add/convert); here the same
# unification runs host-side on [.., B]-shaped cumulative count arrays so
# BOTH aggregation paths — the fused superblock concat and the reference
# partial-merge — share one definition and stay numerically identical.

_LE_TOL = 1e-10  # same bound-match tolerance as histogram_bucket selection


def same_scheme(a, b) -> bool:
    """True when two ``le`` bound vectors describe the same bucket scheme:
    equal length, every bound within _LE_TOL (equal +Inf top buckets
    match). The ONE equality rule for every fused/reference unification
    site — keep them on this helper so the tolerance can't drift apart."""
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    if len(a) != len(b):
        return False
    with np.errstate(invalid="ignore"):
        diff = np.abs(a - b)  # inf - inf -> nan: equal infinite tops match
    return not (diff > _LE_TOL).any()


def union_les(les_list) -> np.ndarray:
    """Union bucket scheme of several ``le`` bound vectors: sorted unique
    finite bounds (within _LE_TOL) plus the +Inf top bucket every scheme
    carries."""
    bounds: list[float] = []
    for les in les_list:
        for x in np.asarray(les, dtype=np.float64):
            if np.isinf(x):
                continue
            if not any(abs(x - b) < _LE_TOL for b in bounds):
                bounds.append(float(x))
    return np.asarray(sorted(bounds) + [np.inf], dtype=np.float64)


def bucket_mapping(src_les, dst_les) -> np.ndarray:
    """For each dst bound, the index of the matching src bound, or the
    largest src bound strictly below it (-1 when none). Cumulative counts
    at a bound a scheme doesn't carry take the count of the nearest LOWER
    bound it does (0 below the first): the exact lower-bound completion of
    a cumulative distribution, and monotone by construction."""
    src = np.asarray(src_les, dtype=np.float64)
    out = np.empty(len(dst_les), dtype=np.int64)
    for i, x in enumerate(np.asarray(dst_les, dtype=np.float64)):
        hit = np.nonzero(
            np.isclose(src, x, rtol=0.0, atol=_LE_TOL)
            | (np.isinf(src) & np.isinf([x] * len(src)))
        )[0]
        if len(hit):
            out[i] = hit[0]
        else:
            below = np.nonzero(src < x - _LE_TOL)[0]
            out[i] = below[-1] if len(below) else -1
    return out


def unify_schemes(arrays, les_list):
    """Remap several [..., B_i]-shaped cumulative-count arrays onto the
    union of their bucket schemes (union_les + remap_buckets — the ONE
    unification rule, shared by the fused superblock concat and both
    reference partial-merge sites). Returns (arrays', union, changed);
    arrays already on the union scheme pass through as the SAME objects,
    and changed=False means every one did."""
    les64 = [np.asarray(l, dtype=np.float64) for l in les_list]
    union = union_les(les64)
    out = [remap_buckets(a, l, union) for a, l in zip(arrays, les64)]
    changed = any(o is not a for o, a in zip(out, arrays))
    return out, union, changed


def remap_buckets(arr: np.ndarray, src_les, dst_les) -> np.ndarray:
    """Remap an [..., B_src] cumulative-count array onto ``dst_les``:
    matching bounds copy through, missing bounds take the nearest lower
    bound's count (0 when below the scheme's first bound). Exact identity
    when the schemes already agree."""
    src = np.asarray(src_les, dtype=np.float64)
    dst = np.asarray(dst_les, dtype=np.float64)
    if len(src) == len(dst) and np.allclose(
        src[:-1], dst[:-1], rtol=0.0, atol=_LE_TOL
    ):
        return arr
    m = bucket_mapping(src, dst)
    a = np.asarray(arr)
    out = np.zeros(a.shape[:-1] + (len(dst),), dtype=a.dtype)
    have = m >= 0
    out[..., have] = a[..., m[have]]
    return out
