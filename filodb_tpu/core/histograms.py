"""Histogram bucket schemes (reference L0 filodb.memory format/vectors/
Histogram.scala:609-899 — Geometric, Custom, Base2Exponential schemes;
quantile/fraction math at :64-130 moves to ops/kernels.py on device).

A histogram sample is a vector of cumulative bucket counts aligned to a
bucket scheme; the top bucket is +Inf. Native histograms are first-class:
chunks store ``[T, B]`` count arrays (ideal TPU layout), and
histogram_quantile runs as a vectorized kernel over ``[S, T, B]`` blocks.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class BucketScheme:
    """Bucket upper bounds (``le`` values), last = +inf."""

    les: tuple[float, ...]

    @property
    def num_buckets(self) -> int:
        return len(self.les)

    def bounds(self) -> np.ndarray:
        return np.asarray(self.les, dtype=np.float64)


def custom_buckets(les) -> BucketScheme:
    les = tuple(float(x) for x in les)
    if les[-1] != np.inf:
        les = les + (np.inf,)
    return BucketScheme(les)


def geometric_buckets(first: float, multiplier: float, num: int) -> BucketScheme:
    """reference GeometricBuckets (Histogram.scala:609)."""
    les = tuple(first * multiplier**i for i in range(num)) + (np.inf,)
    return BucketScheme(les)


def base2_exp_buckets(scale: int, start_index: int, num: int) -> BucketScheme:
    """OTel base-2 exponential scheme (reference Base2ExpHistogramBuckets,
    Histogram.scala:684): bucket i upper bound = 2^((start+i+1) * 2^-scale),
    with a zero bucket first."""
    base = 2.0 ** (2.0**-scale)
    les = (0.0,) + tuple(base ** (start_index + i + 1) for i in range(num)) + (np.inf,)
    return BucketScheme(les)


# The reference's default Prometheus-style scheme used by test fixtures
PROM_DEFAULT = custom_buckets(
    [0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10]
)
