"""Schemas & datasets (reference L1: filodb.core metadata/Schemas.scala:171,264,
Column.scala, Dataset.scala:38).

FiloDB is multi-schema: each time series carries a schema id chosen at ingest
by its column layout (gauge vs counter vs native histogram ...), and the query
engine picks decode/correction behavior per schema (filodb-defaults.conf:220-400
defines the standard set). We keep that model: a ``Schema`` is a named tuple of
typed data columns plus semantic flags (counter drop-detection, downsample
links); the registry below mirrors the reference's standard schemas.

Partition keys: a series identity is its tag map (including ``__name__``/
``_metric_``) under shard-key columns ``_ws_``/``_ns_``/``_metric_``
(Dataset.scala:73). Hashing for shard routing reproduces the reference's
spread model (ShardMapper.scala): the top bits of the shard come from the
shard-key hash (so one metric lands on 2^spread shards) and the low ``spread``
bits from the full partition-key hash.
"""

from __future__ import annotations

import enum
import hashlib
from dataclasses import dataclass, field
from typing import Mapping, Sequence

SHARD_KEY_TAGS = ("_ws_", "_ns_", "_metric_")
METRIC_TAG = "_metric_"
PROM_METRIC_TAG = "__name__"


class ColumnType(enum.Enum):
    TIMESTAMP = "ts"
    DOUBLE = "double"
    LONG = "long"
    HISTOGRAM = "hist"
    STRING = "string"


@dataclass(frozen=True)
class Column:
    name: str
    ctype: ColumnType
    # counter semantics: monotonically-increasing, detect resets at ingest
    # (reference Column params detectDrops, Schemas prom-counter)
    is_counter: bool = False
    # delta temporality (OTel delta counters/histograms): values are already
    # per-interval increases, no correction needed
    is_delta: bool = False


@dataclass(frozen=True)
class DownsampleSpec:
    """Ingest-time downsample functions per column (reference
    downsample/ChunkDownsampler.scala:38 dMin/dMax/dSum/dCount/dAvg/tTime)."""

    funcs: Sequence[str] = ()
    target_schema: str = ""


@dataclass(frozen=True)
class Schema:
    name: str
    columns: Sequence[Column]
    value_column: str  # the default column queries read
    downsample: DownsampleSpec | None = None

    @property
    def schema_id(self) -> int:
        # stable 16-bit id from name+layout hash (reference Schemas.scala hashes
        # column definitions into a schemaID embedded in part keys)
        h = hashlib.blake2b(
            (self.name + "|" + ",".join(f"{c.name}:{c.ctype.value}" for c in self.columns)).encode(),
            digest_size=2,
        ).digest()
        return int.from_bytes(h, "little")

    def column(self, name: str) -> Column:
        for c in self.columns:
            if c.name == name:
                return c
        raise KeyError(f"schema {self.name} has no column {name}")

    @property
    def has_histogram(self) -> bool:
        return any(c.ctype == ColumnType.HISTOGRAM for c in self.columns)


def _ts() -> Column:
    return Column("timestamp", ColumnType.TIMESTAMP)


# The standard schema registry (reference filodb-defaults.conf:220-400).
SCHEMAS: dict[str, Schema] = {}


def _register(s: Schema) -> Schema:
    SCHEMAS[s.name] = s
    return s


GAUGE = _register(
    Schema(
        "gauge",
        [_ts(), Column("value", ColumnType.DOUBLE)],
        "value",
        DownsampleSpec(("dMin", "dMax", "dSum", "dCount", "dAvg"), "ds-gauge"),
    )
)
UNTYPED = _register(Schema("untyped", [_ts(), Column("value", ColumnType.DOUBLE)], "value"))
PROM_COUNTER = _register(
    Schema(
        "prom-counter",
        [_ts(), Column("count", ColumnType.DOUBLE, is_counter=True)],
        "count",
        DownsampleSpec(("tTime", "dLast"), "prom-counter"),
    )
)
DELTA_COUNTER = _register(
    Schema(
        "delta-counter",
        [_ts(), Column("count", ColumnType.DOUBLE, is_delta=True)],
        "count",
        DownsampleSpec(("tTime", "dSum"), "delta-counter"),
    )
)
PROM_HISTOGRAM = _register(
    Schema(
        "prom-histogram",
        [
            _ts(),
            Column("sum", ColumnType.DOUBLE, is_counter=True),
            Column("count", ColumnType.DOUBLE, is_counter=True),
            Column("h", ColumnType.HISTOGRAM, is_counter=True),
        ],
        "h",
        DownsampleSpec(("tTime", "dLast", "dLast", "hLast"), "prom-histogram"),
    )
)
DELTA_HISTOGRAM = _register(
    Schema(
        "delta-histogram",
        [
            _ts(),
            Column("sum", ColumnType.DOUBLE, is_delta=True),
            Column("count", ColumnType.DOUBLE, is_delta=True),
            Column("h", ColumnType.HISTOGRAM, is_delta=True),
        ],
        "h",
    )
)
OTEL_CUMULATIVE_HISTOGRAM = _register(
    Schema(
        "otel-cumulative-histogram",
        [
            _ts(),
            Column("sum", ColumnType.DOUBLE, is_counter=True),
            Column("count", ColumnType.DOUBLE, is_counter=True),
            Column("h", ColumnType.HISTOGRAM, is_counter=True),
            Column("min", ColumnType.DOUBLE),
            Column("max", ColumnType.DOUBLE),
        ],
        "h",
    )
)
OTEL_DELTA_HISTOGRAM = _register(
    Schema(
        "otel-delta-histogram",
        [
            _ts(),
            Column("sum", ColumnType.DOUBLE, is_delta=True),
            Column("count", ColumnType.DOUBLE, is_delta=True),
            Column("h", ColumnType.HISTOGRAM, is_delta=True),
            Column("min", ColumnType.DOUBLE),
            Column("max", ColumnType.DOUBLE),
        ],
        "h",
    )
)
OTEL_EXP_DELTA_HISTOGRAM = _register(
    Schema(
        "otel-exp-delta-histogram",
        [
            _ts(),
            Column("sum", ColumnType.DOUBLE, is_delta=True),
            Column("count", ColumnType.DOUBLE, is_delta=True),
            Column("h", ColumnType.HISTOGRAM, is_delta=True),
        ],
        "h",
    )
)


def schema_by_id(sid: int) -> Schema:
    for s in SCHEMAS.values():
        if s.schema_id == sid:
            return s
    raise KeyError(f"unknown schema id {sid}")


@dataclass(frozen=True)
class DatasetOptions:
    shard_key_columns: Sequence[str] = SHARD_KEY_TAGS
    metric_column: str = METRIC_TAG


@dataclass
class Dataset:
    """dataset = name + allowed schemas + options (reference Dataset.scala:38)."""

    name: str
    schemas: Sequence[Schema] = field(default_factory=lambda: list(SCHEMAS.values()))
    options: DatasetOptions = field(default_factory=DatasetOptions)


# ---------------------------------------------------------------------------
# Partition / shard key hashing
# ---------------------------------------------------------------------------


def canonical_partkey(tags: Mapping[str, str]) -> bytes:
    """Canonical byte form of a series identity: sorted tag pairs.

    Prometheus ``__name__`` is normalized to ``_metric_`` (reference
    PrometheusInputRecord conversion, gateway/.../InputRecord.scala:15).
    """
    items = []
    for k, v in tags.items():
        if k == PROM_METRIC_TAG:
            k = METRIC_TAG
        items.append((k, v))
    items.sort()
    return "\x00".join(f"{k}\x01{v}" for k, v in items).encode()


def hash64(data: bytes) -> int:
    return int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(), "little")


def partkey_hash(tags: Mapping[str, str]) -> int:
    return hash64(canonical_partkey(tags))


def shardkey_hash(tags: Mapping[str, str], options: DatasetOptions = DatasetOptions()) -> int:
    """Hash of only the shard-key columns (RecordBuilder.shardKeyHash analog)."""
    norm = {(METRIC_TAG if k == PROM_METRIC_TAG else k): v for k, v in tags.items()}
    parts = "\x00".join(f"{c}\x01{norm.get(c, '')}" for c in options.shard_key_columns)
    return hash64(parts.encode())


def ingestion_shard(shard_key_hash: int, part_key_hash: int, spread: int, num_shards: int) -> int:
    """Shard routing with spread (reference ShardMapper.ingestionShard):
    high bits select the 2^spread shard group for the shard key, low ``spread``
    bits distribute the group's series by full partition hash."""
    mask = (1 << spread) - 1
    return (((shard_key_hash & ~mask) | (part_key_hash & mask)) & 0x7FFFFFFF) % num_shards


def shard_for(
    tags: Mapping[str, str], spread: int, num_shards: int,
    options: DatasetOptions = DatasetOptions(),
) -> int:
    return ingestion_shard(shardkey_hash(tags, options), partkey_hash(tags), spread, num_shards)


def shard_group(shard_key_hash: int, spread: int, num_shards: int) -> set[int]:
    """All shards a given shard-key hash can route to: the low ``spread`` bits
    range over the full 2^spread group (reference queryShardsFromShardKey).
    The single source of truth for query-side pruning — must stay the exact
    image of ``ingestion_shard`` over all partition hashes."""
    return {
        ingestion_shard(shard_key_hash, low, spread, num_shards)
        for low in range(1 << spread)
    }
