"""Python client for a filodb-tpu server (reference L5 client package:
client/LocalClient.scala QueryOps/ClusterOps ask-pattern wrappers — here a
thin typed wrapper over the HTTP API with the same hardened transport the
cluster uses internally: gzip, bearer auth, bounded retries).

    from filodb_tpu.client import FiloClient
    c = FiloClient("http://localhost:9090", token="...")
    c.ingest_prom('http_requests_total{job="api"} 42 1600000000000')
    ts, series = c.query_range('rate(http_requests_total[5m])', 1600000350, 1600000590, 60)

With ``grpc_endpoint`` set, query_range/query ride the binary gRPC
RemoteExec transport (columnar grid frames — no JSON parse of O(series x
steps) samples); ingest/metadata/admin stay on HTTP:

    c = FiloClient("http://host:9090", grpc_endpoint="grpc://host:7777")
"""

from __future__ import annotations

import json
import urllib.parse
import urllib.request
from typing import Mapping, Sequence

import numpy as np

from .coordinator.planners import RemoteFetchError, fetch_json


def _public_labels(lbls: Mapping[str, str]) -> dict:
    """Internal tags -> Prometheus form (the JSON edge's mapping)."""
    from .core.schemas import METRIC_TAG

    return {("__name__" if k == METRIC_TAG else k): v for k, v in lbls.items()}


class FiloClient:
    def __init__(self, endpoint: str, token: str | None = None, timeout: float = 60,
                 grpc_endpoint: str | None = None,
                 failover_endpoints: Sequence[str] = (),
                 columnar: bool = True):
        self.endpoint = endpoint.rstrip("/")
        self.token = token
        self.timeout = timeout
        self.grpc_endpoint = grpc_endpoint
        # sibling frontends (replicated shard plane): when the primary
        # endpoint fails at the transport level, reads retry against each
        # in turn — the client-side half of replica failover
        self.failover_endpoints = tuple(e.rstrip("/") for e in failover_endpoints)
        # columnar=True negotiates Arrow IPC result frames on query_range
        # (bit-exact floats, no O(series x steps) JSON parse); servers or
        # installs without the columnar edge transparently answer JSON
        self.columnar = columnar

    # -- queries (reference QueryOps) --------------------------------------

    def _url(self, path: str, **params) -> str:
        qs = urllib.parse.urlencode(
            [(k, v) for k, vs in params.items() for v in (vs if isinstance(vs, (list, tuple)) else [vs]) if v is not None],
        )
        return f"{path}" + (f"?{qs}" if qs else "")

    def _failover(self, fetch):
        """Run ``fetch(base)`` against the primary then each failover
        sibling, moving on only for transport-level failures."""
        last = None
        for base in (self.endpoint, *self.failover_endpoints):
            try:
                return fetch(base)
            except (RemoteFetchError, ConnectionError, TimeoutError, OSError) as e:
                last = e
                continue
        raise last

    def _get(self, path: str, **params):
        suffix = self._url(path, **params)
        return self._failover(lambda base: fetch_json(
            f"{base}{suffix}", auth_token=self.token, timeout=self.timeout))

    def query_range(self, promql: str, start_s: float, end_s: float, step_s: float):
        """-> (times_s[np.ndarray], [{"metric": labels, "values": np.ndarray}]).
        Values align on the shared step grid; missing steps are NaN."""
        # integer-ms grid arithmetic, matching the server (float floor-div
        # would drop the last step: 0.3 // 0.1 == 2.0)
        step_ms = max(round(step_s * 1000), 1)
        n = round((end_s - start_s) * 1000) // step_ms + 1
        times = start_s + np.arange(n) * (step_ms / 1000.0)
        if self.grpc_endpoint:
            res = self._grpc_exec(promql, start_s, end_s, step_ms)
            return times, self._result_series(res, n, round(start_s * 1000), step_ms)
        data = None
        if self.columnar:
            # columnar-by-default hop: Arrow IPC result frames when the
            # server speaks them, the JSON envelope otherwise (older server
            # or arrow-less install — same negotiation as peer scatter legs)
            from .coordinator.planners import fetch_result

            suffix = self._url("/api/v1/query_range", query=promql,
                               start=start_s, end=end_s, step=step_s)
            fetched = self._failover(lambda base: fetch_result(
                f"{base}{suffix}", auth_token=self.token, timeout=self.timeout))
            if not isinstance(fetched, dict):
                return times, self._result_series(fetched, n,
                                                  round(start_s * 1000), step_ms)
            data = fetched["data"]
        if data is None:
            data = self._get(
                "/api/v1/query_range", query=promql, start=start_s, end=end_s, step=step_s
            )
        t2i = {round(float(t) * 1000): i for i, t in enumerate(times)}
        series = []
        for s in data.get("result", []):
            row = np.full(n, np.nan)
            for t, v in s.get("values", []):
                i = t2i.get(round(float(t) * 1000))
                if i is not None:
                    row[i] = float(v)
            series.append({"metric": s.get("metric", {}), "values": row})
        return times, series

    @staticmethod
    def _result_series(res, n: int, req_start_ms: int, step_ms: int) -> list:
        """Align a columnar QueryResult (gRPC or Arrow-HTTP leg) onto the
        client grid: a grid may start offset from the request or carry fewer
        steps (offset/lookback edges) — place by timestamp, NaN-pad gaps."""
        series = []
        if res.scalar is not None:  # scalar expression, e.g. 1+1
            row = np.full(n, np.nan)
            sv = np.asarray(res.scalar.values)[:n]
            row[: len(sv)] = sv
            series.append({"metric": {}, "values": row})
        for g in res.grids:
            vals = g.values_np()
            gt = g.step_times_ms()
            idx = (gt - req_start_ms) // step_ms
            ok = ((gt - req_start_ms) % step_ms == 0) & (idx >= 0) & (idx < n)
            src = np.nonzero(ok)[0]
            dst = idx[ok]
            for i, lbls in enumerate(g.labels):
                row = np.full(n, np.nan)
                row[dst] = vals[i, src].astype(np.float64)
                series.append({"metric": _public_labels(lbls), "values": row})
        return series

    def _grpc_exec(self, promql, start_s, end_s, step_ms, instant=False):
        from .api.grpc_exec import exec_promql
        from .query.proto_plan import RemoteExecError

        # grpc:// failover endpoints are sibling frontends for this
        # transport; endpoint-health failures move to the next one
        cands = [self.grpc_endpoint] + [
            e for e in self.failover_endpoints if e.startswith("grpc://")
        ]
        last = None
        for ep in cands:
            try:
                return exec_promql(
                    ep, promql,
                    round(start_s * 1000), round(end_s * 1000), step_ms,
                    auth_token=self.token, instant=instant, timeout_s=self.timeout,
                )
            except RemoteExecError as e:
                last = e
                if getattr(e, "endpoint_failure", False):
                    continue
                raise
        raise last

    def query(self, promql: str, time_s: float | None = None):
        """Instant query -> raw Prometheus ``data`` payload."""
        if self.grpc_endpoint:
            import time as _time

            t = time_s if time_s is not None else _time.time()
            res = self._grpc_exec(promql, t, t, 1000, instant=True)
            if res.scalar is not None:
                sv = np.asarray(res.scalar.values)
                v = sv[np.isfinite(sv)][-1] if np.isfinite(sv).any() else float("nan")
                return {"resultType": "scalar", "result": [t, str(v)]}
            result = []
            for g in res.grids:
                vals = g.values_np()
                ts = g.step_times_ms()
                for i, lbls in enumerate(g.labels):
                    fin = np.isfinite(vals[i])
                    if fin.any():
                        j = int(np.nonzero(fin)[0][-1])
                        result.append({"metric": _public_labels(lbls),
                                       "value": [ts[j] / 1000.0, str(vals[i, j])]})
            return {"resultType": "vector", "result": result}
        return self._get("/api/v1/query", query=promql, time=time_s)

    def labels(self, match: str | None = None) -> list[str]:
        return self._get("/api/v1/labels", **{"match[]": match})

    def label_values(self, label: str, match: str | None = None, limit: int | None = None) -> list[str]:
        return self._get(f"/api/v1/label/{urllib.parse.quote(label)}/values",
                         **{"match[]": match, "limit": limit})

    def series(self, match: str) -> list[Mapping[str, str]]:
        return self._get("/api/v1/series", **{"match[]": match})

    def metadata(self) -> Mapping[str, list]:
        return self._get("/api/v1/metadata")

    def cardinality(self, prefix: Sequence[str] = (), depth: int | None = None):
        return self._get("/api/v1/cardinality", prefix=",".join(prefix) or None, depth=depth)

    def exemplars(self, promql: str, start_s: float, end_s: float):
        return self._get("/api/v1/query_exemplars", query=promql, start=start_s, end=end_s)

    # -- ingest / admin (reference ClusterOps) ------------------------------

    def _post(self, path: str, body: bytes, content_type: str = "text/plain"):
        headers = {"Content-Type": content_type}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        req = urllib.request.Request(
            f"{self.endpoint}{path}", data=body, headers=headers, method="POST"
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            payload = json.loads(r.read())
        if payload.get("status") != "success":
            raise RuntimeError(f"ingest failed: {payload}")
        return payload["data"]

    def ingest_prom(self, exposition_text: str) -> int:
        """Prometheus text exposition (supports # TYPE + OpenMetrics
        exemplars). Returns rows ingested."""
        return self._post("/ingest/prom", exposition_text.encode())["ingested"]

    def ingest_influx(self, lines: str) -> int:
        return self._post("/ingest/influx", lines.encode())["ingested"]

    def ingest_rows(self, rows: Sequence[Mapping]) -> int:
        """JSON-lines ingest: {"tags": {...}, "ts_ms": int, "value": float}."""
        body = "\n".join(json.dumps(dict(r)) for r in rows).encode()
        return self._post("/ingest", body, "application/json")["ingested"]

    def health(self) -> Mapping:
        url = f"{self.endpoint}/admin/health"
        with urllib.request.urlopen(url, timeout=self.timeout) as r:
            return json.loads(r.read())
