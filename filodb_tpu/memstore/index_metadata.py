"""Index lifecycle metadata store (reference L2:
memstore/IndexMetadataStore.scala — file-system & ephemeral impls tracking
per-shard index state: Empty/Building/Synced/Refreshing + checkpoint
timestamps; used by DownsampleIndexBootstrapper and
DownsampleIndexCheckpointer.java to make index rebuilds restartable)."""

from __future__ import annotations

import enum
import json
import os
import time
from dataclasses import dataclass


class IndexState(enum.Enum):
    EMPTY = "empty"
    BUILDING = "building"
    SYNCED = "synced"
    REFRESHING = "refreshing"
    TRIGGER_REBUILD = "trigger_rebuild"


@dataclass
class IndexMetadata:
    state: IndexState
    checkpoint_ms: int  # data watermark the index covers
    updated_at: float


class EphemeralIndexMetadataStore:
    """In-memory impl (reference EphemeralIndexMetadataStore)."""

    def __init__(self):
        self._state: dict[tuple[str, int], IndexMetadata] = {}

    def get(self, dataset: str, shard: int) -> IndexMetadata:
        return self._state.get(
            (dataset, shard), IndexMetadata(IndexState.EMPTY, 0, 0.0)
        )

    def update(self, dataset: str, shard: int, state: IndexState, checkpoint_ms: int) -> None:
        self._state[(dataset, shard)] = IndexMetadata(state, checkpoint_ms, time.time())


class FileIndexMetadataStore(EphemeralIndexMetadataStore):
    """File-backed impl (reference FileSystemBasedIndexMetadataStore /
    DownsampleIndexCheckpointer): survives restarts so an interrupted index
    build resumes from its checkpoint."""

    def __init__(self, root: str):
        super().__init__()
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._load()

    def _path(self) -> str:
        return os.path.join(self.root, "index_metadata.json")

    def _load(self) -> None:
        if not os.path.exists(self._path()):
            return
        with open(self._path()) as f:
            for rec in json.load(f):
                self._state[(rec["dataset"], rec["shard"])] = IndexMetadata(
                    IndexState(rec["state"]), rec["checkpoint_ms"], rec["updated_at"]
                )

    def update(self, dataset: str, shard: int, state: IndexState, checkpoint_ms: int) -> None:
        super().update(dataset, shard, state, checkpoint_ms)
        data = [
            {"dataset": d, "shard": s, "state": m.state.value,
             "checkpoint_ms": m.checkpoint_ms, "updated_at": m.updated_at}
            for (d, s), m in self._state.items()
        ]
        tmp = self._path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, self._path())
