"""One time series in memory (reference L2: memstore/TimeSeriesPartition.scala:64).

The reference appends rows into per-column off-heap write buffers, then
``switchBuffers`` (:232) seals them into immutable encoded BinaryVectors via
``optimize()``. Here a partition appends into growable numpy buffers and seals
fixed-max-size ``Chunk``s; sealed chunks optionally hold their codec-encoded
form (for flush/persistence and memory savings) and/or the decoded arrays (for
zero-cost query staging). Chunk metadata mirrors ChunkSetInfo (store/
ChunkSetInfo.scala:60): id = start time, numRows, endTime.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping

import numpy as np

from ..core.encodings import Encoded, decode, encode_double, encode_hist, encode_int64
from ..core.schemas import Column, ColumnType, Schema

DEFAULT_MAX_CHUNK_SIZE = 400  # samples per chunk (reference store config default)


@dataclass
class Chunk:
    """Immutable sealed chunk: one time range of one series, all columns."""

    start_ts: int
    end_ts: int
    n: int
    # decoded columns (None if evicted to encoded-only form)
    arrays: dict[str, np.ndarray] | None
    # encoded columns (populated at seal when encode=True, or at flush)
    encoded: dict[str, Encoded] | None = None

    def column(self, name: str) -> np.ndarray:
        if self.arrays is not None:
            return self.arrays[name]
        assert self.encoded is not None
        return decode(self.encoded[name])

    def ensure_encoded(self, schema: Schema) -> dict[str, Encoded]:
        if self.encoded is None:
            assert self.arrays is not None
            self.encoded = _encode_columns(schema, self.arrays)
        return self.encoded

    def drop_decoded(self, schema: Schema) -> None:
        """Keep only the compressed form (reference: post-optimize() state)."""
        self.ensure_encoded(schema)
        self.arrays = None

    @property
    def nbytes_encoded(self) -> int:
        return sum(e.nbytes for e in self.encoded.values()) if self.encoded else 0


def _encode_columns(schema: Schema, arrays: Mapping[str, np.ndarray]) -> dict[str, Encoded]:
    out = {}
    for col in schema.columns:
        if col.name not in arrays:
            continue
        a = arrays[col.name]
        if col.ctype == ColumnType.TIMESTAMP or col.ctype == ColumnType.LONG:
            out[col.name] = encode_int64(a)
        elif col.ctype == ColumnType.DOUBLE:
            out[col.name] = encode_double(a)
        elif col.ctype == ColumnType.HISTOGRAM:
            out[col.name] = encode_hist(a)
    return out


class TimeSeriesPartition:
    """Write buffers + sealed chunk list for one series."""

    __slots__ = (
        "part_id",
        "tags",
        "schema",
        "partkey",
        "chunks",
        "_buf",
        "_buf_len",
        "max_chunk_size",
        "encode_on_seal",
        "bucket_les",
        "flushed_until",
        "_hwm",
        "exemplars",
    )

    MAX_EXEMPLARS = 64  # ring-buffer cap per series (OpenMetrics exemplars)

    def __init__(
        self,
        part_id: int,
        tags: Mapping[str, str],
        schema: Schema,
        partkey: bytes,
        max_chunk_size: int = DEFAULT_MAX_CHUNK_SIZE,
        encode_on_seal: bool = False,
        bucket_les: np.ndarray | None = None,
    ):
        self.part_id = part_id
        self.tags = dict(tags)
        self.schema = schema
        self.partkey = partkey
        self.chunks: list[Chunk] = []
        self._buf: dict[str, np.ndarray] | None = None
        self._buf_len = 0
        self.max_chunk_size = max_chunk_size
        self.encode_on_seal = encode_on_seal
        self.bucket_les = bucket_les
        self.flushed_until: int = -(2**62)  # flush watermark (ts)
        # ingest high-water mark: survives chunk eviction so the
        # out-of-order/duplicate guard stays intact after tier-2 reclaim
        self._hwm: int = -(2**62)
        # OpenMetrics exemplars: (ts_ms, value, labels) ring buffer
        self.exemplars: list[tuple[int, float, dict]] = []

    def add_exemplar(self, ts_ms: int, value: float, labels: dict) -> None:
        self.exemplars.append((int(ts_ms), float(value), dict(labels)))
        if len(self.exemplars) > self.MAX_EXEMPLARS:
            del self.exemplars[: len(self.exemplars) - self.MAX_EXEMPLARS]

    # -- ingest ------------------------------------------------------------

    def _alloc_buf(self, values: Mapping[str, np.ndarray]) -> None:
        cap = self.max_chunk_size
        buf: dict[str, np.ndarray] = {"timestamp": np.empty(cap, dtype=np.int64)}
        for name, arr in values.items():
            if arr.ndim == 2:
                buf[name] = np.empty((cap, arr.shape[1]), dtype=arr.dtype)
            else:
                buf[name] = np.empty(cap, dtype=arr.dtype)
        self._buf = buf
        self._buf_len = 0

    def ingest(self, timestamps: np.ndarray, values: Mapping[str, np.ndarray]) -> int:
        """Append a time-ordered sample run; seals full chunks as it goes.
        Returns number of rows ingested (out-of-order rows are dropped, as the
        reference does — TimeSeriesPartition ingest drops rows older than the
        latest ingested timestamp)."""
        if len(timestamps) == 0:
            return 0
        last = self.latest_ts()
        if timestamps[0] <= last:
            keep = timestamps > last
            if not keep.any():
                return 0
            timestamps = timestamps[keep]
            values = {k: v[keep] for k, v in values.items()}
        n = len(timestamps)
        written = 0
        while written < n:
            if self._buf is None:
                self._alloc_buf(values)
            room = self.max_chunk_size - self._buf_len
            take = min(room, n - written)
            sl = slice(written, written + take)
            dst = slice(self._buf_len, self._buf_len + take)
            self._buf["timestamp"][dst] = timestamps[sl]
            for k, v in values.items():
                self._buf[k][dst] = v[sl]
            self._buf_len += take
            written += take
            if self._buf_len >= self.max_chunk_size:
                self.switch_buffers()
        self._hwm = max(self._hwm, int(timestamps[-1]))
        return n

    def latest_ts(self) -> int:
        # local snapshot: a concurrent seal nulls self._buf AFTER appending
        # the chunk, and readers don't hold the shard lock (the "check then
        # subscript" TOCTOU crashed queries racing ingest)
        buf, n = self._buf, self._buf_len
        if buf is not None and n:
            return max(int(buf["timestamp"][n - 1]), self._hwm)
        if self.chunks:
            return max(self.chunks[-1].end_ts, self._hwm)
        return self._hwm

    def earliest_ts(self) -> int:
        if self.chunks:
            return self.chunks[0].start_ts
        buf, n = self._buf, self._buf_len
        if buf is not None and n:
            return int(buf["timestamp"][0])
        return 2**62

    def switch_buffers(self) -> Chunk | None:
        """Seal the current write buffer into a chunk (reference
        switchBuffers:232 -> encodeAndReleaseBuffers:317)."""
        if self._buf is None or self._buf_len == 0:
            return None
        n = self._buf_len
        arrays = {k: v[:n].copy() for k, v in self._buf.items()}
        chunk = Chunk(
            start_ts=int(arrays["timestamp"][0]),
            end_ts=int(arrays["timestamp"][-1]),
            n=n,
            arrays=arrays,
        )
        if self.encode_on_seal:
            chunk.ensure_encoded(self.schema)
        self.chunks.append(chunk)
        self._buf = None
        self._buf_len = 0
        return chunk

    # -- read --------------------------------------------------------------

    def num_samples(self) -> int:
        return sum(c.n for c in self.chunks) + self._buf_len

    def chunks_in_range(self, t0: int, t1: int) -> list[Chunk]:
        return [c for c in self.chunks if c.end_ts >= t0 and c.start_ts <= t1]

    def samples_in_range(self, t0: int, t1: int, col: str) -> tuple[np.ndarray, np.ndarray]:
        """All samples with t0 <= ts <= t1 for one column, including the open
        write buffer. Returns (ts[int64], vals)."""
        ts_parts: list[np.ndarray] = []
        val_parts: list[np.ndarray] = []
        # snapshot order matters: queries read without the shard lock while
        # ingest can seal the buffer into a chunk mid-call (switch_buffers
        # appends the chunk, THEN nulls self._buf, THEN zeroes _buf_len).
        # Reading (len, buf, chunks) in that order — each exactly once; the
        # old re-read of self._buf crashed with a NoneType subscript —
        # covers every interleaving: a seal completing before the buf read
        # leaves buf=None and the chunk list (read after) holds the sealed
        # rows; a seal completing after it leaves the pre-seal buf ref
        # valid, and the sealed_end clamp below drops any buffer rows a
        # seen chunk already covers (per-series timestamps are monotone
        # across seal points), so sealed rows are neither lost nor counted
        # twice. A stale len against a freshly re-allocated buf fails the
        # ts[-1] >= t0 gate (trailing zeros) and skips the buffer — the
        # same slightly-stale-but-consistent view as querying a moment
        # earlier.
        n = self._buf_len
        buf = self._buf
        chunk_list = list(self.chunks)  # real copy: no mid-iteration appends
        sealed_end = chunk_list[-1].end_ts if chunk_list else -(2**62)
        for c in chunk_list:
            if c.end_ts < t0 or c.start_ts > t1:
                continue
            ts = c.column("timestamp")
            lo, hi = np.searchsorted(ts, [t0, t1 + 1])
            if hi > lo:
                ts_parts.append(ts[lo:hi])
                val_parts.append(c.column(col)[lo:hi])
        if buf is not None and n:
            ts = buf["timestamp"][:n]
            if ts[-1] >= t0 and ts[0] <= t1:
                lo, hi = np.searchsorted(ts, [max(t0, sealed_end + 1), t1 + 1])
                if hi > lo:
                    ts_parts.append(ts[lo:hi].copy())
                    val_parts.append(buf[col][lo:hi].copy())
        if not ts_parts:
            ncol = self._hist_width(col)
            empty_v = np.empty((0, ncol)) if ncol else np.empty(0)
            return np.empty(0, dtype=np.int64), empty_v
        return np.concatenate(ts_parts), np.concatenate(val_parts)

    def tail_samples(self, t0: int, t1: int, col: str) -> tuple[np.ndarray, np.ndarray]:
        """Lean ``samples_in_range`` for the live-edge append window
        (ops/staging._append_to_parts calls this once per partition per
        repair, so per-call overhead is the whole cost at 100k series).
        When every requested sample lives in the open write buffer it
        returns VIEWS — no chunk scan, no copies, no concatenate. The
        views are only stable until the next ingest into this partition:
        callers must consume (stack/copy) them before releasing whatever
        ordering guarantees they hold; appends land at rows >= the
        snapshotted length so the returned slice itself is never
        rewritten. Falls back to samples_in_range whenever any chunk
        reaches into [t0, t1] or the seal race is in play."""
        n = self._buf_len
        buf = self._buf
        chunks = self.chunks
        sealed_end = chunks[-1].end_ts if chunks else -(2**62)
        if buf is None or not n or sealed_end >= t0:
            return self.samples_in_range(t0, t1, col)
        ts = buf["timestamp"][:n]
        if ts[-1] < t0 or ts[0] > t1:
            ncol = self._hist_width(col)
            empty_v = np.empty((0, ncol)) if ncol else np.empty(0)
            return np.empty(0, dtype=np.int64), empty_v
        lo, hi = np.searchsorted(ts, [t0, t1 + 1])
        return ts[lo:hi], buf[col][lo:hi]

    def _hist_width(self, col: str) -> int | None:
        try:
            c = self.schema.column(col)
        except KeyError:
            return None
        if c.ctype == ColumnType.HISTOGRAM and self.bucket_les is not None:
            return len(self.bucket_les)
        return None

    # -- flush / eviction ---------------------------------------------------

    def unflushed_chunks(self) -> list[Chunk]:
        return [c for c in self.chunks if c.start_ts > self.flushed_until]

    def mark_flushed(self, until_ts: int) -> None:
        self.flushed_until = max(self.flushed_until, until_ts)

    def resident_bytes(self) -> int:
        """Host-memory footprint of this series: open write buffer + decoded
        chunk arrays + encoded forms (reference: per-TSP write buffers +
        block-memory chunk bytes)."""
        n = 0
        buf = self._buf
        if buf is not None:
            n += sum(a.nbytes for a in buf.values())
        for c in self.chunks:
            if c.arrays is not None:
                n += sum(a.nbytes for a in c.arrays.values())
            n += c.nbytes_encoded
        return n

    def drop_decoded_flushed(self) -> int:
        """Tier-1 reclaim: keep only the encoded form of flushed chunks
        (reference: optimized BinaryVectors stay, decoded staging is
        rebuildable). Returns bytes freed."""
        freed = 0
        for c in self.chunks:
            if c.end_ts <= self.flushed_until and c.arrays is not None:
                decoded = sum(a.nbytes for a in c.arrays.values())
                had_enc = c.nbytes_encoded
                c.drop_decoded(self.schema)
                freed += decoded - (c.nbytes_encoded - had_enc)
        return freed

    def drop_flushed_chunks(self) -> int:
        """Tier-2 reclaim: remove flushed chunks from memory entirely — ODP
        pages them back from the column store on demand (reference
        evictPartitions + DemandPagedChunkStore). Returns bytes freed."""
        freed = 0
        keep = []
        for c in self.chunks:
            if c.end_ts <= self.flushed_until:
                if c.arrays is not None:
                    freed += sum(a.nbytes for a in c.arrays.values())
                freed += c.nbytes_encoded
            else:
                keep.append(c)
        self.chunks = keep
        return freed

    def evict_before(self, cutoff_ts: int) -> int:
        """Drop whole chunks ending before cutoff; returns samples dropped."""
        dropped = 0
        keep = []
        for c in self.chunks:
            if c.end_ts < cutoff_ts:
                dropped += c.n
            else:
                keep.append(c)
        self.chunks = keep
        return dropped
