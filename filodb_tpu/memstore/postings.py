"""Posting containers & packed-bitmap math for the part-key index.

Roaring-style layout (reference analog: the posting lists inside Lucene /
tantivy that back PartKeyLuceneIndex / PartKeyTantivyIndex): each
(label, value) pair owns ONE container holding the part ids carrying that
value —

- **sparse**: a sorted ``int32`` id array (plus an unsorted append buffer
  merged lazily, so the ingest path is O(1) per id);
- **dense**: packed ``uint64`` words (bit *i* set = part id *i* present),
  promoted to when the sorted array would outweigh the bitmap
  (``4*len > nbits/8``, i.e. the value covers > 1/32 of the id universe).

Query results flow through the same two shapes: a *posting view* is a
``(kind, data)`` pair with kind ``"s"`` (sorted id array) or ``"d"``
(packed words). AND/OR/ANDNOT pick the cheapest combination — sparse∧dense
is a vectorized bit probe, dense∧dense is one word-wise ``&`` over
``nbits/64`` words — and nothing materializes a dense bitmap to ids unless
the FINAL result is dense. All math is numpy on host metadata; nothing here
touches a device.

Bit order contract: words are little-endian ``uint64`` viewed as bytes for
pack/unpack, so part id ``i`` lives at word ``i >> 6``, bit ``i & 63`` —
the same layout ``ops/postings_kernels.intersect_words`` consumes after a
``view(uint32)`` reinterpretation (bitwise AND is invariant under the word
split).
"""

from __future__ import annotations

import numpy as np

ID_DTYPE = np.int32
EMPTY_IDS = np.empty(0, dtype=ID_DTYPE)
_U64_ONE = np.uint64(1)


def nwords(nbits: int) -> int:
    """Packed words covering an id universe of ``nbits`` ids."""
    return (int(nbits) + 63) >> 6


def grow_words(words: np.ndarray, nw: int) -> np.ndarray:
    if len(words) >= nw:
        return words
    out = np.zeros(nw, dtype=np.uint64)
    out[: len(words)] = words
    return out


def set_bit(words: np.ndarray, pid: int) -> None:
    words[pid >> 6] |= _U64_ONE << np.uint64(pid & 63)


def clear_bit(words: np.ndarray, pid: int) -> None:
    words[pid >> 6] &= ~(_U64_ONE << np.uint64(pid & 63))


def test_bits(words: np.ndarray, ids: np.ndarray) -> np.ndarray:
    """Boolean membership of each id in the packed bitmap (vectorized)."""
    if not len(ids):
        return np.zeros(0, dtype=bool)
    idx = np.asarray(ids, dtype=np.int64)
    w = words[idx >> 6]
    return (np.right_shift(w, (idx & 63).astype(np.uint64)) & _U64_ONE) != 0


def ids_to_dense(ids: np.ndarray, nw: int) -> np.ndarray:
    """Sorted-or-not id array -> packed uint64 words of length ``nw``."""
    words = np.zeros(nw, dtype=np.uint64)
    if not len(ids):
        return words
    idx = np.asarray(ids, dtype=np.int64)
    if len(idx) * 16 >= nw * 64:
        # dense enough that one vectorized pack beats scattered or.at
        u8 = np.zeros(nw * 64, dtype=np.uint8)
        u8[idx] = 1
        return np.packbits(u8, bitorder="little").view(np.uint64)
    np.bitwise_or.at(
        words.view(np.uint8), idx >> 3,
        np.left_shift(1, (idx & 7)).astype(np.uint8),
    )
    return words


def dense_to_ids(words: np.ndarray) -> np.ndarray:
    """Packed words -> sorted int64 id array (touches only nonzero words)."""
    nz = np.flatnonzero(words)
    if not len(nz):
        return np.empty(0, dtype=np.int64)
    sub = np.unpackbits(
        np.ascontiguousarray(words[nz]).view(np.uint8), bitorder="little"
    ).reshape(len(nz), 64)
    w, b = np.nonzero(sub)  # row-major -> sorted ids
    return (nz[w] << 6) + b


def popcount(words: np.ndarray) -> int:
    if not len(words):
        return 0
    return int(np.unpackbits(words.view(np.uint8)).sum())


class ValueContainer:
    """Posting container for one (label, value): sparse sorted-array or
    promoted dense bitmap. Adds buffer into ``pending`` (O(1)); reads
    finalize lazily. The owning index serializes mutation vs finalize with
    its lock — the container itself is not thread-safe."""

    __slots__ = ("arr", "words", "count", "pending")

    # promote to dense words when the sorted array would be bigger than the
    # bitmap: 4 bytes/id vs nbits/8 bytes
    PROMOTE_RATIO = 32

    def __init__(self):
        self.arr: np.ndarray | None = EMPTY_IDS  # None once dense
        self.words: np.ndarray | None = None
        self.count = 0  # exact ids held (pending included)
        self.pending: list[int] | None = None

    def __len__(self) -> int:
        return self.count

    def add(self, pid: int, nbits: int = 0) -> None:
        """``nbits`` (the owner's current id-universe capacity) bounds dense
        growth so bitmap width never exceeds — and stays amortized by — the
        universe's own doubling."""
        if self.words is not None:
            w, b = pid >> 6, np.uint64(pid & 63)
            if w >= len(self.words):  # universe grew past this bitmap
                self.words = grow_words(
                    self.words, max(w + 1, nwords(nbits))
                )
            if not (self.words[w] >> b) & _U64_ONE:
                self.words[w] |= _U64_ONE << b
                self.count += 1
            return
        if self.pending is None:
            self.pending = []
        self.pending.append(pid)
        self.count += 1

    def discard_many(self, pids, nbits: int) -> int:
        """Remove the given ids; returns how many were actually present."""
        self.finalize(nbits)
        drop = np.asarray(list(pids), dtype=np.int64)
        if self.words is not None:
            self.words = grow_words(self.words, nwords(nbits))
            present = test_bits(self.words, drop)
            for pid in drop[present]:
                clear_bit(self.words, int(pid))
            self.count -= int(present.sum())
            return int(present.sum())
        keep = np.isin(self.arr, drop, invert=True)
        removed = len(self.arr) - int(keep.sum())
        if removed:
            self.arr = self.arr[keep]
            self.count = len(self.arr)
        return removed

    def finalize(self, nbits: int) -> None:
        """Merge the pending buffer and re-check dense promotion."""
        if self.pending:
            add = np.asarray(self.pending, dtype=ID_DTYPE)
            self.pending = None
            if self.words is not None:  # defensive: adds go direct when dense
                for pid in add:
                    set_bit(self.words, int(pid))
                self.count = popcount(self.words)
            else:
                arr = self.arr
                sorted_add = len(add) == 1 or bool((np.diff(add) > 0).all())
                if sorted_add and (len(arr) == 0 or add[0] > arr[-1]):
                    # ingest fast path: ids arrive in increasing order
                    self.arr = np.concatenate([arr, add]) if len(arr) else add
                else:
                    self.arr = np.union1d(arr, add).astype(ID_DTYPE)
                self.count = len(self.arr)
        if (self.words is None and
                self.count * self.PROMOTE_RATIO > max(nbits, 1)):
            self.words = ids_to_dense(self.arr, nwords(nbits))
            self.arr = None

    def view(self, nbits: int):
        """Current posting view: ('s', sorted ids) or ('d', words)."""
        self.finalize(nbits)
        if self.words is not None:
            return ("d", self.words)
        return ("s", self.arr)

    def nbytes(self) -> int:
        n = 0
        if self.arr is not None:
            n += self.arr.nbytes
        if self.words is not None:
            n += self.words.nbytes
        if self.pending:
            n += 8 * len(self.pending)
        return n


# -- posting-view algebra ---------------------------------------------------


def p_empty():
    return ("s", EMPTY_IDS)


def p_count(p) -> int:
    kind, data = p
    return len(data) if kind == "s" else popcount(data)


def p_is_empty(p) -> bool:
    kind, data = p
    if kind == "s":
        return len(data) == 0
    return not data.any()


def p_and(a, b, nw: int):
    ka, da = a
    kb, db = b
    if ka == "s" and kb == "s":
        return ("s", np.intersect1d(da, db, assume_unique=True))
    if ka == "s":  # sparse ∧ dense: probe bits
        return ("s", da[test_bits(grow_words(db, nw), da)])
    if kb == "s":
        return ("s", db[test_bits(grow_words(da, nw), db)])
    # dense widths may differ (bitmaps grown at different capacities);
    # high words beyond either operand are zero, so align to the widest
    nw = max(nw, len(da), len(db))
    return ("d", grow_words(da, nw) & grow_words(db, nw))


def p_andnot(a, b, nw: int):
    """a \\ b."""
    ka, da = a
    kb, db = b
    if ka == "s":
        if kb == "s":
            return ("s", np.setdiff1d(da, db, assume_unique=True))
        return ("s", da[~test_bits(grow_words(db, nw), da)])
    if kb == "s":
        nw = max(nw, len(da))
        return ("d", grow_words(da, nw) & ~ids_to_dense(db, nw))
    nw = max(nw, len(da), len(db))
    return ("d", grow_words(da, nw) & ~grow_words(db, nw))


def p_or_views(views, nw: int):
    """OR a list of posting views; keeps the result sparse when cheap."""
    if not views:
        return p_empty()
    dense = [d for k, d in views if k == "d"]
    sparse = [d for k, d in views if k == "s" and len(d)]
    if dense:
        nw = max([nw] + [len(d) for d in dense])
        out = np.zeros(nw, dtype=np.uint64)
        for d in dense:
            out[: len(d)] |= d
        if sparse:
            out |= ids_to_dense(np.concatenate(sparse), nw)
        return ("d", out)
    if not sparse:
        return p_empty()
    if len(sparse) == 1:
        return ("s", sparse[0])
    cat = np.concatenate(sparse)
    if len(cat) * 16 >= nw * 64:
        return ("d", ids_to_dense(cat, nw))
    return ("s", np.unique(cat))


def p_to_ids(p) -> np.ndarray:
    """Posting view -> sorted id array (sparse views pass through without a
    copy — callers must not mutate)."""
    kind, data = p
    if kind == "s":
        return data
    return dense_to_ids(data)
