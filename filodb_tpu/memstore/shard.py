"""One shard of the in-memory store (reference L2: memstore/TimeSeriesShard.scala:268
— ingest loop :939, partition creation :1193, flush pipeline :1273-1636,
eviction :1709-1799, label queries :1908, lookup :2097).

A shard owns: partkey -> partition map, the tag index, flush-group assignment,
and retention/eviction. The reference's ingest hot loop is a per-record Scala
loop over BinaryRecords; here ingest consumes columnar ``RecordBatch``es and
amortizes partition lookup by grouping records per series with numpy.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Mapping, Sequence

import numpy as np

from ..core.filters import ColumnFilter
from ..core.records import RecordBatch, SeriesBatch
from ..core.schemas import Schema, canonical_partkey
from .index import PartKeyIndex
from .partition import DEFAULT_MAX_CHUNK_SIZE, TimeSeriesPartition

NUM_FLUSH_GROUPS = 16  # reference groups-per-shard default


@dataclass
class ShardStats:
    """reference TimeSeriesShardStats (TimeSeriesShard.scala:41-150)."""

    rows_ingested: int = 0
    rows_skipped: int = 0
    partitions_created: int = 0
    partitions_evicted: int = 0
    chunks_flushed: int = 0
    encoded_bytes: int = 0
    headroom_evictions: int = 0
    bytes_reclaimed: int = 0


@dataclass
class StoreConfig:
    """Per-dataset store tuning (reference store/IngestionConfig.scala,
    conf/timeseries-dev-source.conf:43-120)."""

    max_chunk_size: int = DEFAULT_MAX_CHUNK_SIZE
    flush_interval_ms: int = 3_600_000
    retention_ms: int = 3 * 24 * 3_600_000
    encode_on_seal: bool = False
    groups_per_shard: int = NUM_FLUSH_GROUPS
    max_partitions: int = 1_000_000
    # "python" (vectorized posting-bitmap index, the default) | "native"
    # (the C++ posting-list core, reference's tantivy analog; falls back
    # when unbuilt) | "set" (the original set-arithmetic index, retained as
    # the property-test oracle / escape hatch)
    index_backend: str = "python"
    # opt-in HBM tier for hot posting bitmaps (memstore/index_device.py):
    # all-equality selectors whose matchers are staged resolve as one tiny
    # jit intersection program. Default OFF — with it off the index never
    # touches a device and the warm fused query stays ONE kernel dispatch.
    index_device_postings: bool = False
    index_device_min_hits: int = 16
    index_device_max_bytes: int = 64 << 20
    # staging-cache byte budget per shard (HBM/working-set guard; reference
    # analog: BlockManager reclaim under memory pressure)
    stage_cache_bytes: int = 2 << 30
    # resident chunk-memory budget per shard; crossing it triggers headroom
    # eviction (reference shard-mem-size + ensureHeadroom watermarks)
    max_resident_bytes: int = 8 << 30
    # eviction drives residency down to this fraction of the budget
    evict_target_fraction: float = 0.75


class EvictablePartIdQueueSet:
    """Dedup FIFO of headroom-eviction candidates (reference
    memstore/EvictablePartIdQueueSet.scala — offer dedups; eviction consumes
    from the head). Partitions enter when a flush task is cut for them (they
    will soon have flushed chunks to reclaim) or when ODP pages chunks back
    in; they leave when tier-2 eviction reclaims them or the partition is
    removed. ``evict_for_headroom`` walks ONLY this set — partitions that
    never flushed anything (nothing reclaimable) are never touched, and no
    per-call sort of the whole partition map happens. A re-offer moves the
    entry to the BACK, so the head is the least-recently-flushed (coldest)
    partition — hot series that flush every cycle keep migrating away from
    the eviction front."""

    __slots__ = ("_q",)

    def __init__(self):
        self._q: dict[int, None] = {}  # insertion-ordered dedup set

    def offer(self, part_id: int) -> None:
        self._q.pop(part_id, None)  # move-to-back on re-offer
        self._q[part_id] = None

    def remove(self, part_id: int) -> None:
        self._q.pop(part_id, None)

    def snapshot(self) -> list[int]:
        return list(self._q)

    def __len__(self) -> int:
        return len(self._q)

    def __contains__(self, part_id: int) -> bool:
        return part_id in self._q


@dataclass
class StageEntry:
    """One staging-cache entry: an HBM-resident staged block plus a dirty
    flag set by in-range ingests since it was built. Dirty entries get
    incrementally repaired — or restaged when repair preconditions fail —
    at next use (query/exec/plans.py); ``repairing`` marks an in-flight
    repair so concurrent same-key queries restage instead of serving the
    pre-repair block. ``dirty_lo``/``dirty_hi`` accumulate the union of
    the ACCEPTED-sample intervals (absolute ms, inclusive) of the ingests
    that dirtied the entry since it was last clean; the repair declines —
    forcing a restage — when ``dirty_lo`` reaches below the staged heads
    (ops/staging._append_to_parts), guarding the append-only repair's
    monotone-ingest assumption. Reset when a repair claims the entry."""

    block: object
    nbytes: int
    dirty: bool = False
    repairing: bool = False
    dirty_lo: int | None = None
    dirty_hi: int | None = None


# how many per-version ingest effect intervals a shard retains: the proof
# window for insert-time overlap re-checks and superblock revalidation. At a
# pathological 1000 version bumps/s this still covers ~1s of history — far
# longer than a stage runs; a reader older than the window is treated
# conservatively (as if everything changed).
EFFECT_LOG_MAX = 1024


def _stage_cache_walker(shard) -> int:
    """Cold recount of the shard staging cache's true device footprint —
    the ledger drift check's ground truth (must stay byte-identical to the
    accounting at insert: both use ops/staging.staged_nbytes)."""
    from ..ops.staging import staged_nbytes

    with shard._lock:
        return sum(staged_nbytes(e.block) for e in shard.stage_cache.values())


class TimeSeriesShard:
    def __init__(self, dataset: str, shard_num: int, config: StoreConfig | None = None):
        self.dataset = dataset
        self.shard_num = shard_num
        self.config = config or StoreConfig()
        self.index = self._make_index()
        self.partitions: dict[int, TimeSeriesPartition] = {}
        self._by_partkey: dict[bytes, int] = {}
        self._next_part_id = 0
        self.stats = ShardStats()
        from .cardinality import CardinalityTracker

        self.cardinality = CardinalityTracker()
        self._lock = threading.RLock()
        self._ingested_offset = -1  # stream offset watermark (Kafka analog)
        # per-version ingest effect log: (version, lo_ms, hi_ms, full). One
        # entry per version bump, so a consumer holding an older version can
        # PROVE a staged range untouched (ingest_effects_since) instead of
        # conservatively discarding its work — the interval-aware half of
        # the staging-cache invalidation contract.
        self._effects: deque = deque(maxlen=EFFECT_LOG_MAX)
        # append listeners (standing/maintainer.py): fired outside the
        # shard lock after each ingest commits — wake signals, not truth
        self._append_listeners: list[Callable] = []
        # entries are StageEntry objects (block + bytes + dirty/repairing)
        # data version for query-side staging caches: bumped on every ingest
        # so cached HBM-resident blocks invalidate (reference analog: block
        # memory reclaim + chunk seal versioning)
        self.version = 0
        self.stage_cache: dict = {}
        # device-resource ledger account (filodb_tpu/ledger.py): every
        # stage-cache insert/evict/clear debits/credits it, and the drift
        # check recounts via the walker below (weakly bound — a dead shard
        # must not be pinned by process-global accounting)
        from ..ledger import LEDGER

        self.ledger = LEDGER.register(
            self, "staged_block", _stage_cache_walker,
            name=f"{dataset}/shard-{shard_num}",
        )
        # on-demand paging source: set to the ColumnStore to transparently
        # page evicted chunks back in at query time (reference
        # OnDemandPagingShard.scala:26 + DemandPagedChunkStore)
        self.odp_store = None
        self.odp_stats_pages = 0
        # headroom-eviction candidates (reference EvictablePartIdQueueSet)
        self.evictable = EvictablePartIdQueueSet()
        # index time-lifecycle state (reference TimeSeriesShard.scala:987-993
        # updateIndexWithEndTime): part ids currently marked "ended" in the
        # index, and the latest-sample watermark seen at the previous flush —
        # a partition whose watermark is unchanged across a flush cycle has
        # stopped ingesting and gets a real end time in the index.
        self._ended: set[int] = set()
        self._flush_watermark: dict[int, int] = {}
        # evicted-partkey set (reference evictedPartKeys BloomFilter,
        # TimeSeriesShard.scala:540): partkeys whose flushed chunk data was
        # reclaimed under memory pressure. The residency check in odp_page_in
        # (earliest_ts) already routes their queries to ODP; this set is the
        # retention pass's signal for which empty shells still have pageable
        # data, and surfaces as the evicted-series stat.
        self.evicted_keys: set[bytes] = set()
        self._ingests_since_headroom_check = 0
        # cheap residency accounting: last measured value + bytes ingested
        # since, so the O(partitions) walk runs only when the estimate nears
        # the budget (reference keeps an exact counter in block memory)
        self._resident_last = 0
        self._approx_new_bytes = 0

    def _make_index(self) -> PartKeyIndex:
        idx = None
        if self.config.index_backend == "native":
            try:
                from .index_native import NativePartKeyIndex, native_index_available

                if native_index_available():
                    idx = NativePartKeyIndex()
            except Exception:
                pass
        elif self.config.index_backend == "set":
            from .index import SetBasedPartKeyIndex

            return SetBasedPartKeyIndex()
        if idx is None:
            idx = PartKeyIndex()
        if self.config.index_device_postings:
            if type(idx) is not PartKeyIndex:
                # the native backend answers all-equality selectors in C++
                # and never reaches the bitmap tier hook — attaching a tier
                # there would be a silent no-op holding a ledger account
                import logging

                logging.getLogger("filodb_tpu.memstore").warning(
                    "index_device_postings ignored: backend %r resolves "
                    "equality selectors outside the bitmap path (use "
                    "index_backend=\"python\")", self.config.index_backend,
                )
            else:
                from .index_device import DevicePostingsTier

                idx.device_tier = DevicePostingsTier(
                    idx,
                    min_hits=self.config.index_device_min_hits,
                    max_bytes=self.config.index_device_max_bytes,
                    name=f"{self.dataset}/shard-{self.shard_num}/index",
                )
        return idx

    def index_stats(self) -> dict:
        """Introspection for /debug/index + the filodb_index_* gauges (the
        set-based escape-hatch backend reports a minimal shape)."""
        if hasattr(self.index, "postings_stats"):
            return self.index.postings_stats()
        return {"num_part_keys": len(self.index), "labels": {},
                "postings_bytes": 0, "dictionary_size": 0, "device": None}

    # -- ingest ------------------------------------------------------------

    def _record_effect(self, lo, hi, full: bool) -> None:
        """Append this version bump's effect to the bounded effect log.
        ``full`` marks events that can change ANY cached block (new series,
        eviction, ODP page-in, flush/recovery — resident data moved in
        place). Caller holds the shard lock and has already bumped
        ``version``; every bump must record exactly one effect so the log's
        versions stay consecutive (ingest_effects_since relies on it to
        detect truncation)."""
        self._effects.append((self.version, lo, hi, full))

    def ingest_effects_since(self, since_version: int, lo: int, hi: int):
        """Classify what happened between ``since_version`` and the current
        version w.r.t. the absolute-ms interval [lo, hi].

        Returns None when the effect log PROVES every bump since left the
        interval untouched (disjoint-range ingest only); else a reason
        string: ``"overlap"`` (some ingest's effect interval intersects),
        ``"full_clear"`` (new series / eviction / ODP / recovery — cached
        row sets or resident data may have changed), or ``"log_truncated"``
        (the bounded log no longer reaches back that far — conservatively
        treated as changed)."""
        with self._lock:
            return self._ingest_effects_since_locked(since_version, lo, hi)

    def ingest_effects_interval_since(self, since_version: int, lo: int,
                                      hi: int):
        """Like :meth:`ingest_effects_since`, but additionally returns the
        UNION interval of the overlapping effects:
        ``(reason, eff_lo, eff_hi)`` with ``eff_lo``/``eff_hi`` None unless
        reason is ``"overlap"``. The standing-query maintainer uses the
        interval to bound which retained grid steps the appended samples
        can have touched — a live-edge append dirties only the step
        SUFFIX whose windows reach ``eff_lo``, so a delta refresh
        recomputes O(touched steps) instead of the whole grid."""
        with self._lock:
            return self._ingest_effects_interval_locked(since_version, lo, hi)

    def _ingest_effects_interval_locked(self, since_version: int, lo, hi):
        """The ONE effect-log scan (classification + overlap interval)
        behind both public forms — the staging-cache path and the
        standing-delta path must never disagree on what counts as
        covered."""
        if self.version == since_version:
            return None, None, None
        if not self._effects or self._effects[0][0] > since_version + 1:
            return "log_truncated", None, None
        eff_lo = eff_hi = None
        for v, elo, ehi, full in self._effects:
            if v <= since_version:
                continue
            if full:
                return "full_clear", None, None
            if elo <= hi and ehi >= lo:
                eff_lo = elo if eff_lo is None else min(eff_lo, elo)
                eff_hi = ehi if eff_hi is None else max(eff_hi, ehi)
        if eff_lo is None:
            return None, None, None
        return "overlap", int(eff_lo), int(eff_hi)

    # -- append notification (standing/maintainer.py wake signal) ----------

    def add_append_listener(self, cb: Callable) -> None:
        """Register ``cb(dataset, shard_num, lo_ms, hi_ms, full)`` fired
        AFTER each ingest commits (outside the shard lock — listeners must
        never run under it; a listener that re-enters shard APIs would
        deadlock otherwise). The standing-query maintainer uses this as a
        WAKE signal only: correctness derives from the effect log
        (ingest_effects_interval_since), so a lost or duplicated
        notification is harmless."""
        self._append_listeners.append(cb)

    def remove_append_listener(self, cb: Callable) -> None:
        try:
            self._append_listeners.remove(cb)
        except ValueError:
            pass

    def _notify_append(self, lo, hi, full: bool) -> None:
        for cb in list(self._append_listeners):
            try:
                cb(self.dataset, self.shard_num, lo, hi, full)
            except Exception:  # noqa: BLE001 — a sick listener must not break ingest
                pass

    def _ingest_effects_since_locked(self, since_version: int, lo, hi):
        return self._ingest_effects_interval_locked(since_version, lo, hi)[0]

    def _clear_stage_cache(self, reason: str = "invalidate") -> None:
        """Wholesale staging-cache clear, crediting the device ledger for
        every dropped entry (callers hold the shard lock). The ONE clear
        path — a bare ``stage_cache.clear()`` would leak ledger balance."""
        if self.stage_cache:
            freed = sum(e.nbytes for e in self.stage_cache.values())
            self.ledger.free(freed, reason=reason, count=len(self.stage_cache))
            self.stage_cache.clear()

    def _invalidate_stage_range(self, min_ts, max_ts, new_series: bool,
                                raw_lo=None) -> None:
        """Dirty-mark (not drop) the staging-cache entries the new samples
        can affect.

        A dashboard's historical panels must not pay a full re-stage for
        every live scrape that lands BEYOND their range: an entry staged
        for [start, end] stays valid unless (a) the ingest's EFFECT
        interval overlaps it, or (b) a NEW series appeared (it might match
        the entry's filters — conservative full clear). The effect interval
        of an append to an existing series starts at the series' PREVIOUS
        newest sample, not at the new sample: extending a gap series' span
        can pull it into a cached range it previously missed entirely, and
        the cached block's row set would no longer match a fresh lookup.

        Overlapping entries are marked DIRTY and accumulate the effect
        interval (StageEntry.dirty_lo/hi) instead of being deleted: the
        next query attempts an INCREMENTAL append repair
        (ops/staging.append_to_block — live-edge panels pay only the tail,
        reference's equivalent is serving straight from write buffers) and
        falls back to a full re-stage when repair preconditions fail.
        Eviction/ODP paths still clear wholesale (they change resident data
        in place). Every call also records the effect in the shard's effect
        log so later consumers (insert-time overlap re-check, superblock
        revalidation) can prove disjointness. Caller holds the shard
        lock."""
        if new_series or min_ts is None:
            self._record_effect(0, 0, True)
            self._clear_stage_cache()
            return
        self._record_effect(int(min_ts), int(max_ts), False)
        # entries accumulate the ACCEPTED-sample interval (not the
        # prev_end-widened one the effect log records): the widening exists
        # for the index-span-pull hazard, which the repair's part-refs
        # check covers; a widened lo would make every append to a lagging
        # series read as below-head dirt and needlessly force restages
        dlo = int(min_ts) if raw_lo is None else int(raw_lo)
        for k, entry in self.stage_cache.items():
            if k[1] <= max_ts and k[2] >= min_ts:  # k = (filters, start, end, ...)
                entry.dirty = True
                entry.dirty_lo = (dlo if entry.dirty_lo is None
                                  else min(entry.dirty_lo, dlo))
                entry.dirty_hi = (int(max_ts) if entry.dirty_hi is None
                                  else max(entry.dirty_hi, int(max_ts)))

    def _prev_end_of(self, partkey) -> int | None:
        """Newest sample ts of an existing series (None for a new one)."""
        pid = self._by_partkey.get(partkey)
        if pid is None:
            return None
        try:
            return int(self.partitions[pid].latest_ts())
        except (KeyError, ValueError):
            return None

    def ingest(self, batch: RecordBatch, offset: int = -1) -> int:
        """Ingest a columnar record batch (reference ingest:939). Returns rows
        ingested. Records are grouped by series then appended in bulk."""
        n = 0
        with self._lock:
            np0 = len(self.partitions)
            min_ts = max_ts = raw_min = None
            for sb in batch.group_by_series():
                prev_end = self._prev_end_of(sb.partkey)
                n += self._ingest_series(sb)
                if len(sb.timestamps):
                    raw, hi = int(sb.timestamps.min()), int(sb.timestamps.max())
                    lo = raw if prev_end is None else min(raw, prev_end)
                    # entry-dirt floor counts ACCEPTED rows only: rows at or
                    # below prev_end are dropped by the partition's
                    # out-of-order guard and change nothing, and counting
                    # them would make one stale duplicate per scrape
                    # permanently veto the append repair
                    acc = raw if prev_end is None else max(raw, prev_end + 1)
                    raw_min = acc if raw_min is None else min(raw_min, acc)
                    min_ts = lo if min_ts is None else min(min_ts, lo)
                    max_ts = hi if max_ts is None else max(max_ts, hi)
            if offset >= 0:
                self._ingested_offset = max(self._ingested_offset, offset)
            self.version += 1
            new_series = len(self.partitions) != np0
            self._invalidate_stage_range(min_ts, max_ts, new_series,
                                         raw_lo=raw_min)
        if n and self._append_listeners:
            self._notify_append(min_ts, max_ts, new_series or min_ts is None)
        self.stats.rows_ingested += n
        # periodic headroom check on the ingest path (reference
        # ensureFreeSpace runs inside the ingest loop). The full O(partitions)
        # walk runs only when the estimate (last measurement + bytes since)
        # could plausibly be over budget.
        self._approx_new_bytes += n * 24  # ts8 + value8 + overhead slack
        self._ingests_since_headroom_check += 1
        if self._ingests_since_headroom_check >= 64:
            self._ingests_since_headroom_check = 0
            if self._resident_last + self._approx_new_bytes > self.config.max_resident_bytes:
                self.evict_for_headroom()
        return n

    def ingest_series(self, sb: SeriesBatch) -> int:
        lo = hi = None
        full = True
        with self._lock:
            self.version += 1
            np0 = len(self.partitions)
            prev_end = self._prev_end_of(sb.partkey)
            n = self._ingest_series(sb)
            if len(sb.timestamps):
                raw = int(sb.timestamps.min())
                lo = raw if prev_end is None else min(raw, prev_end)
                hi = int(sb.timestamps.max())
                # accepted-rows floor, as in ingest(): dropped out-of-order
                # rows must not veto the append repair
                acc = raw if prev_end is None else max(raw, prev_end + 1)
                full = len(self.partitions) != np0
                self._invalidate_stage_range(lo, hi, full, raw_lo=acc)
            else:
                self._record_effect(0, 0, True)
                self._clear_stage_cache()
        if n and self._append_listeners:
            self._notify_append(lo, hi, full)
        return n

    def _ingest_series(self, sb: SeriesBatch) -> int:
        pk = sb.partkey
        pid = self._by_partkey.get(pk)
        if pid is None:
            pid = self._create_partition(
                sb.tags, sb.schema, pk, sb.bucket_les,
                start_ts=int(sb.timestamps.min()) if len(sb.timestamps) else 0,
            )
        elif pid in self._ended:
            # series resumed ingesting: back to the "still ingesting" sentinel
            # (reference re-activation in getOrAddPartitionAndIngest)
            self.index.update_end_time(pid, 2**62)
            self._ended.discard(pid)
        part = self.partitions[pid]
        # enforce time order within the run
        ts = sb.timestamps
        if len(ts) > 1 and not (np.diff(ts) >= 0).all():
            order = np.argsort(ts, kind="stable")
            ts = ts[order]
            sb = SeriesBatch(sb.schema, sb.tags, ts, {k: v[order] for k, v in sb.values.items()}, sb.bucket_les)
        got = part.ingest(ts, sb.values)
        self.stats.rows_skipped += len(ts) - got
        return got

    def _create_partition(
        self, tags: Mapping[str, str], schema: Schema, pk: bytes, bucket_les=None,
        start_ts: int = 0, end_ts: int = 2**62,
    ) -> int:
        """reference createNewPartition:1193 + index addPartKey + cardinality.
        ``start_ts`` is the real first-sample time (reference passes the ingest
        record's timestamp to addPartKey); ``end_ts`` defaults to the
        still-ingesting sentinel."""
        if len(self.partitions) >= self.config.max_partitions:
            raise MemoryError(f"shard {self.shard_num}: partition limit reached")
        # quota enforcement happens BEFORE any state mutates (reference
        # CardinalityTracker.modifyCount at createNewPartition)
        self.cardinality.series_created(tags)
        pid = self._next_part_id
        self._next_part_id += 1
        part = TimeSeriesPartition(
            pid,
            tags,
            schema,
            pk,
            max_chunk_size=self.config.max_chunk_size,
            encode_on_seal=self.config.encode_on_seal,
            bucket_les=bucket_les,
        )
        self.partitions[pid] = part
        self._by_partkey[pk] = pid
        self.index.add_partkey(pid, dict(tags), start_ts=start_ts, end_ts=end_ts)
        if end_ts < 2**62:
            self._ended.add(pid)
        self.stats.partitions_created += 1
        return pid

    # -- query lookup --------------------------------------------------------

    def lookup_partitions(
        self, filters: Sequence[ColumnFilter], start_ts: int, end_ts: int, limit: int | None = None
    ) -> np.ndarray:
        """reference lookupPartitions:2097 -> PartLookupResult."""
        return self.index.part_ids_from_filters(filters, start_ts, end_ts, limit)

    def partition(self, part_id: int) -> TimeSeriesPartition:
        return self.partitions[int(part_id)]

    def label_values(self, filters, label, start_ts, end_ts, limit=None):
        return self.index.label_values(filters, label, start_ts, end_ts, limit)

    def label_names(self, filters, start_ts, end_ts):
        return self.index.label_names(filters, start_ts, end_ts)

    def partkeys(self, filters, start_ts, end_ts, limit=None):
        return self.index.partkeys_from_filters(filters, start_ts, end_ts, limit)

    # -- flush / eviction ----------------------------------------------------

    def flush_group_of(self, part_id: int) -> int:
        """Partitions are flushed in groups round-robin (reference
        prepareFlushGroup:1273; group = partId % groups)."""
        return part_id % self.config.groups_per_shard

    def create_flush_task(self, group: int):
        """Collect sealed-but-unflushed chunks for one flush group; the store
        layer persists them and then calls mark_flushed (doFlushSteps:1462)."""
        out = []
        with self._lock:
            for pid, part in self.partitions.items():
                if pid % self.config.groups_per_shard != group:
                    continue
                part.switch_buffers()
                chunks = part.unflushed_chunks()
                if chunks:
                    out.append((part, chunks))
                    self.evictable.offer(pid)  # reclaimable once persisted
        return out

    def update_index_end_times(self) -> int:
        """Mark partitions that stopped ingesting with a real end time in the
        index (reference updateIndexWithEndTime, TimeSeriesShard.scala:987-993
        + PartKeyLuceneIndex.updatePartKeyWithEndTime:628). Called once per
        flush cycle: a partition whose latest-sample watermark is unchanged
        since the previous flush is no longer ingesting. Returns the number of
        partitions newly marked ended."""
        n = 0
        with self._lock:
            for pid, part in self.partitions.items():
                if pid in self._ended:
                    continue
                latest = part.latest_ts()
                if latest <= -(2**61):
                    continue  # never ingested
                if self._flush_watermark.get(pid) == latest:
                    self.index.update_end_time(pid, latest)
                    self._ended.add(pid)
                    n += 1
                else:
                    self._flush_watermark[pid] = latest
        return n

    def evict_for_retention(self, now_ms: int | None = None) -> int:
        """Drop chunks older than retention; remove fully-empty partitions
        (reference evictPartitions:1709)."""
        now_ms = now_ms if now_ms is not None else int(time.time() * 1000)
        cutoff = now_ms - self.config.retention_ms
        dropped = 0
        dead: list[int] = []
        with self._lock:
            for pid, part in self.partitions.items():
                dropped += part.evict_before(cutoff)
                if part.num_samples() != 0:
                    continue
                # an empty partition is removed (with its index entry) only
                # when nothing within retention could be paged back: either
                # there is no ODP store, or its last sample predates the
                # cutoff. Tier-2-evicted/live series keep their shell so the
                # index can route queries to ODP.
                if self.odp_store is None or self.index.end_time(pid) < cutoff:
                    dead.append(pid)
            for pid in dead:
                part = self.partitions.pop(pid)
                self._by_partkey.pop(part.partkey, None)
                self.index.remove([pid])
                self.cardinality.series_removed(part.tags)
                self._ended.discard(pid)
                self._flush_watermark.pop(pid, None)
                self.evicted_keys.discard(part.partkey)
                self.evictable.remove(pid)
                self.stats.partitions_evicted += 1
            if dropped or dead:
                # resident data changed in place: cached staged blocks may
                # hold evicted samples/partitions (the staging cache has no
                # version in its key — invalidation is the contract)
                self.version += 1
                self._record_effect(0, 0, True)
                self._clear_stage_cache()
        return dropped

    def add_exemplar(self, partkey: bytes, ts_ms: int, value: float, labels) -> bool:
        """Attach an exemplar to an existing series (locked: partition lookup
        and append race eviction otherwise). Returns False when the series
        does not exist — exemplars never create series."""
        with self._lock:
            pid = self._by_partkey.get(partkey)
            if pid is None:
                return False
            self.partitions[pid].add_exemplar(ts_ms, value, labels)
            return True

    def resident_bytes(self) -> int:
        """Total host-memory footprint of this shard's series data."""
        with self._lock:
            return sum(p.resident_bytes() for p in self.partitions.values())

    def evict_for_headroom(self, target_bytes: int | None = None) -> int:
        """Reclaim chunk memory until residency is under the watermark
        (reference evictForHeadroom, TimeSeriesShard.scala:1799). Two tiers,
        least-recently-flushed candidates first:

        1. drop decoded arrays of flushed chunks (encoded form stays queryable);
        2. drop flushed chunks entirely — only when an ODP store is attached,
           so queries page them back (evicted partkeys recorded in
           ``evicted_keys``, the BloomFilter analog).

        Unflushed data is never dropped. Returns bytes freed."""
        budget = self.config.max_resident_bytes
        resident = self.resident_bytes()
        self._resident_last = resident
        self._approx_new_bytes = 0
        if target_bytes is None:
            if resident <= budget:
                return 0
            target = int(budget * self.config.evict_target_fraction)
        else:
            target = target_bytes
            if resident <= target:
                return 0
        freed = 0
        with self._lock:
            # walk ONLY the evictable candidate set (dedup FIFO ~
            # least-recently-flushed), never the whole partition map
            # (reference EvictablePartIdQueueSet consumption)
            cands = [self.partitions[pid] for pid in self.evictable.snapshot()
                     if pid in self.partitions]
            for part in cands:
                if resident - freed <= target:
                    break
                freed += part.drop_decoded_flushed()
            if resident - freed > target and self.odp_store is not None:
                for part in cands:
                    if resident - freed <= target:
                        break
                    got = part.drop_flushed_chunks()
                    if got:
                        freed += got
                        self.evicted_keys.add(part.partkey)
                        # fully reclaimed: re-enters the queue at next flush
                        self.evictable.remove(part.part_id)
            if freed:
                self._resident_last = resident - freed
                self.version += 1
                self._record_effect(0, 0, True)
                self._clear_stage_cache()
                self.stats.headroom_evictions += 1
                self.stats.bytes_reclaimed += freed
        return freed

    def odp_page_in(self, part_ids, start_ms: int, end_ms: int) -> int:
        """Page persisted chunks for the given partitions back into memory
        when the query range precedes what is resident (reference
        scanPartitions ODP override, OnDemandPagingShard.scala:147).
        Returns chunks paged in."""
        if self.odp_store is None:
            return 0
        from ..core.encodings import decode
        from ..core.schemas import canonical_partkey

        need: dict[bytes, TimeSeriesPartition] = {}
        for pid in part_ids:
            part = self.partitions.get(int(pid))
            if part is not None and part.earliest_ts() > start_ms:
                need[part.partkey] = part
        if not need:
            return 0
        n = 0
        with self._lock:
            # manifest-seek read: only frames of the NEEDED partitions in the
            # queried range are touched (reference OnDemandPagingShard:147 —
            # bytes read scale with the query, not the store)
            for header, schema_name, encs in self.odp_store.read_chunks_selective(
                self.dataset, self.shard_num, list(need.keys()), start_ms, end_ms
            ):
                pk = canonical_partkey(header["tags"])
                part = need.get(pk)
                if part is None:
                    continue
                if any(c.start_ts == header["start"] for c in part.chunks):
                    continue  # already resident
                from .partition import Chunk

                arrays = {
                    col: decode(enc) for col, enc in zip(header["cols"], encs)
                }
                part.chunks.append(
                    Chunk(header["start"], header["end"], header["n"], arrays,
                          dict(zip(header["cols"], encs)))
                )
                part.mark_flushed(header["end"])
                n += 1
            for part in need.values():
                part.chunks.sort(key=lambda c: c.start_ts)
                if n:
                    # the merge-commit downsample layout stores overlapping
                    # batch + streaming chunks side by side and relies on
                    # read-side reconciliation (store/flush); a page-in
                    # must apply it like recover_shard does, or overlapped
                    # timestamps double-count
                    from ..store.flush import _reconcile_chunks

                    _reconcile_chunks(part)
                self.evictable.offer(part.part_id)  # paged-in = re-evictable
            if n:
                self.version += 1
                self._record_effect(0, 0, True)
                self._clear_stage_cache()
                self.odp_stats_pages += n
        return n

    @property
    def num_partitions(self) -> int:
        return len(self.partitions)

    @property
    def ingested_offset(self) -> int:
        return self._ingested_offset
