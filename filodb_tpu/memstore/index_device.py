"""Opt-in device tier for hot posting bitmaps (ISSUE 14 tentpole; Tailwind:
treat the accelerator boundary as explicit dataflow and stage the hot
working STRUCTURES, not just samples; Storyboard: let the observed workload
choose what gets precomputed).

For huge tenants, multi-matcher selector resolution is repeated AND over
the same few posting bitmaps (``_ws_``/``_ns_``/``_metric_`` equality). The
tier watches the index's observed equality-selector traffic
(``PartKeyIndex.traffic``, fed by the lookup path — the same selector
stream the PR 12 query-log fingerprints record per query) and stages the
hottest (label, value) bitmaps to HBM as packed words. An all-equality
lookup whose matchers are ALL staged then resolves as ONE tiny jit
intersection program (ops/postings_kernels.py) instead of host set math.

Accounting: every staged bitmap debits the process device ledger under the
``index_postings`` kind; drops/invalidations credit it back, and the
ledger's drift check recounts via :func:`_tier_walker` — the device tier
can never hold untracked HBM.

Consistency: a staged entry records the label's ``post_version`` at staging
time. Any posting change under that label (ingest of a new series, removal)
bumps the version; the entry is then DROPPED at next use and re-staged by
the next ``maintain()`` pass. Stale device bitmaps are never consulted.

Default OFF (``StoreConfig.index_device_postings``): with the tier disabled
the index never touches a device and the warm fused query stays exactly ONE
kernel dispatch. Enabling it trades one extra (tiny) dispatch per resolved
selector for vectorized intersection off the host.
"""

from __future__ import annotations

import threading

import numpy as np


def _tier_walker(tier: "DevicePostingsTier") -> int:
    """Ledger drift-check ground truth: recount staged device bytes."""
    with tier._lock:
        return sum(e.nbytes for e in tier._staged.values())


class _Entry:
    __slots__ = ("dev", "nbytes", "post_version", "hits")

    def __init__(self, dev, nbytes: int, post_version: int):
        self.dev = dev
        self.nbytes = int(nbytes)
        self.post_version = post_version
        self.hits = 0


class DevicePostingsTier:
    """Hot posting bitmaps staged to HBM for one shard's index."""

    def __init__(self, index, min_hits: int = 16, max_bytes: int = 64 << 20,
                 name: str = ""):
        from ..ledger import LEDGER

        self.index = index
        self.min_hits = int(min_hits)
        self.max_bytes = int(max_bytes)
        self._staged: dict[tuple[str, str], _Entry] = {}
        self._lock = threading.Lock()
        self.stats = {"intersections": 0, "host_fallbacks": 0,
                      "staged": 0, "dropped": 0}
        self._maintaining = False
        # steady-state guard for the opportunistic sweep: never more often
        # than this — a warm lookup storm must not pay the sort+probe walk
        # (or a thread spawn) every 256th call for a no-op
        self.sweep_min_interval_s = 2.0
        self._last_sweep = 0.0
        self.ledger = LEDGER.register(
            self, "index_postings", _tier_walker,
            name=name or "index-device-tier",
        )

    # -- staging policy ----------------------------------------------------

    def maintain(self, max_stage: int = 8) -> int:
        """Stage up to ``max_stage`` of the hottest not-yet-staged posting
        bitmaps (traffic >= min_hits), hottest first, within the byte
        budget; drop version-stale entries. Returns entries staged. Called
        opportunistically (every 256th lookup) and directly by tests/ops —
        NOT on the lookup fast path itself."""
        from ..ops.postings_kernels import host_words_to_device
        from . import postings as P

        idx = self.index
        staged = 0
        with idx._lock:
            hot = sorted(
                ((hits, key) for key, hits in idx.traffic.items()
                 if hits >= self.min_hits),
                reverse=True,
            )
            snapshots = []
            for hits, (label, value) in hot:
                if staged + len(snapshots) >= max_stage:
                    break
                with self._lock:
                    cur = self._staged.get((label, value))
                L = idx._labels.get(label)
                c = L.containers.get(value) if L is not None else None
                if c is None:
                    continue
                if cur is not None and cur.post_version == L.post_version:
                    continue  # fresh copy already resident
                view = c.view(idx._nbits)
                words = (view[1] if view[0] == "d"
                         else P.ids_to_dense(view[1], P.nwords(idx._nbits)))
                snapshots.append(
                    ((label, value), words.copy(), L.post_version)
                )
            nbits = idx._nbits
        # device_put outside the index lock: staging must never stall
        # concurrent lookups/ingest
        for key, words, pv in snapshots:
            nbytes = words.nbytes
            with self._lock:
                held = sum(e.nbytes for e in self._staged.values())
                if held + nbytes > self.max_bytes:
                    break
            dev = host_words_to_device(words)
            with self._lock:
                # re-check the budget under the lock: concurrent sweeps
                # (the in-flight flag is advisory; tests/ops call
                # maintain() directly) must not compound past max_bytes
                old = self._staged.get(key)
                held = sum(e.nbytes for e in self._staged.values()) \
                    - (old.nbytes if old is not None else 0)
                if held + nbytes > self.max_bytes:
                    break
                if old is not None:
                    self.ledger.free(old.nbytes, reason="replace")
                self._staged[key] = _Entry(dev, nbytes, pv)
                self.ledger.alloc(nbytes)
                self.stats["staged"] += 1
            staged += 1
        return staged

    def drop(self, key: tuple[str, str], reason: str = "drop") -> None:
        with self._lock:
            e = self._staged.pop(key, None)
            if e is not None:
                self.ledger.free(e.nbytes, reason=reason)
                self.stats["dropped"] += 1

    def clear(self) -> None:
        with self._lock:
            freed = sum(e.nbytes for e in self._staged.values())
            if self._staged:
                self.ledger.free(freed, reason="invalidate",
                                 count=len(self._staged))
            self._staged.clear()

    # -- lookup path -------------------------------------------------------

    def try_intersect(self, classed):
        """Resolve an all-equality selector from staged bitmaps: returns the
        AND'd host uint64 words, or None when any matcher is unstaged /
        stale (host path takes over). Caller holds the index lock."""
        idx = self.index
        if idx.lookups % 256 == 0 and not self._maintaining:
            # opportunistic re-staging sweep, amortized off the hot path
            # and rate-limited: a warm steady-state lookup storm pays one
            # monotonic-clock read here, with at most one sweep (sort +
            # freshness probes, ~ms) per interval. (One in-flight sweep at
            # a time; the flag is advisory — a duplicate sweep is wasted
            # work, never wrong.)
            import time

            now = time.monotonic()
            if now - self._last_sweep >= self.sweep_min_interval_s:
                self._maintaining = True
                self._last_sweep = now

                def _sweep():
                    try:
                        self.maintain()
                    finally:
                        self._maintaining = False

                threading.Thread(target=_sweep, daemon=True).start()
        # only pure non-empty equality selectors: a {k=""} matcher also
        # matches series MISSING the tag (host path adds `all &~ tagged`),
        # which a staged posting bitmap alone cannot represent
        if not classed or any(
            c != "eq" or f.value == "" for f, c in classed
        ):
            return None
        entries = []
        for f, _c in classed:
            L = idx._labels.get(f.column)
            if L is None:
                return None
            with self._lock:
                e = self._staged.get((f.column, f.value))
            if e is None:
                self.stats["host_fallbacks"] += 1
                return None
            if e.post_version != L.post_version:
                # postings moved under the staged copy: drop, host resolves
                self.drop((f.column, f.value), reason="invalidate")
                self.stats["host_fallbacks"] += 1
                return None
            e.hits += 1
            entries.append(e)
        from ..ops.postings_kernels import intersect_on_device

        if len(entries) == 1:
            out = np.ascontiguousarray(
                np.asarray(entries[0].dev)
            ).view(np.uint64)
        else:
            # staged bitmaps may span different capacities (the universe
            # grew between stagings) — versions being current guarantees
            # equal length here, but guard anyway
            if len({e.dev.shape[0] for e in entries}) != 1:
                self.stats["host_fallbacks"] += 1
                return None
            out = intersect_on_device([e.dev for e in entries])
        self.stats["intersections"] += 1
        return out

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            entries = [
                {"label": k[0], "value": k[1], "bytes": e.nbytes,
                 "hits": e.hits}
                for k, e in sorted(self._staged.items())
            ]
        return {
            "staged": entries,
            "staged_bytes": sum(e["bytes"] for e in entries),
            "ledger_bytes": self.ledger.bytes,
            "stats": dict(self.stats),
        }
