"""Part-key tag index (reference L2: memstore/PartKeyIndex.scala traits,
PartKeyLuceneIndex.scala:70 / PartKeyTantivyIndex.scala:38 + 6.3k Rust).

The reference indexes each series' tag map in Lucene or Tantivy and answers
``partIdsFromFilters`` (:655), label-names/values, and start/end-time
queries. This is the vectorized host-side successor of the original
set-arithmetic index (retained below as :class:`SetBasedPartKeyIndex`, the
property-test oracle and the ``index_backend="set"`` escape hatch):

- every (label, value) owns a **posting container** (memstore/postings.py:
  roaring-style — sorted ``int32`` id arrays for sparse values, packed
  ``uint64`` bitmap words once a value covers >1/32 of the id universe);
- ``part_ids_from_filters`` is AND/OR/ANDNOT over those containers
  (word-wise numpy for dense operands, vectorized bit probes for
  sparse∧dense), never Python set arithmetic;
- the PromQL missing-tag rule (a matcher satisfied by the EMPTY string also
  matches series without the tag: ``{k!="v"}``, ``{k=~".*"}``) is ONE
  bitmap op, ``all &~ tagged[k]``, off the per-label ``tagged`` bitmap
  maintained at ingest;
- regex / negative matchers batch over the per-label **value dictionary**:
  the anchored pattern's literal prefix binary-searches the sorted value
  list down to a candidate slice (the reference tantivy_utils "range-aware
  regex"), the compiled regex runs over the surviving candidate VALUES
  (never per part key), and the matched values' containers OR together;
  negative matchers reuse the positive machinery and finish with
  ``tagged &~ positive``;
- start/end times live in flat int64 arrays so interval overlap + ``limit``
  are one vectorized mask over the candidate ids;
- repeated selector storms (Grafana variable queries) hit a per-label
  match cache keyed by pattern and invalidated by the label's dictionary /
  postings versions.

An opt-in device tier (memstore/index_device.py) stages the hottest posting
bitmaps to HBM — chosen from observed selector traffic, Storyboard-style —
and resolves all-equality selectors with one tiny jit intersection program,
ledger-accounted under the ``index_postings`` kind. Default OFF: the warm
fused query path stays exactly ONE kernel dispatch.

The C++ fast path (native/index.cpp) still plugs in behind the same class
(memstore/index_native.py) when built.
"""

from __future__ import annotations

import re
import threading
import time
from collections import OrderedDict
from typing import Iterable, Mapping, Sequence

import numpy as np

from ..core.filters import ColumnFilter
from . import postings as P

# alternations of pure literals only: '.' and '+' are regex metacharacters
# ('ab+' must regex-match 'abb', never look up the literal value "ab+")
_LITERAL_ALT = re.compile(r"^[\w-]+(\|[\w-]*)*$")

# first regex metacharacter ends the literal prefix (conservative: a
# backslash escape also stops it)
_META = re.compile(r"[.^$*+?()[\]{}|\\]")

END_SENTINEL = 2**62  # "still ingesting" (Long.MaxValue analog)


def regex_literal_prefix(pattern: str) -> tuple[str, str]:
    """Split an anchored regex into (safe literal prefix, remainder) — the
    range-aware regex trick (reference tantivy_utils): ``http_5.*`` scans
    only the ``http_5``-prefixed slice of the value dictionary.

    Safety: every full match MUST start with the returned prefix. A
    quantifier right after the literal run makes its last char optional
    (``ab*`` matches "a"), so it is dropped; an alternation anywhere can
    bypass the prefix entirely (``abc|z``), so the prefix collapses to ""."""
    if "|" in pattern:
        return "", pattern
    m = _META.search(pattern)
    if m is None:
        return pattern, ""
    prefix, remainder = pattern[: m.start()], pattern[m.start():]
    if remainder[:1] in ("*", "?", "{") and prefix:
        prefix = prefix[:-1]
    return prefix, remainder


def filter_op_class(f: ColumnFilter) -> str:
    """Coarse cost class of one matcher: eq | in | prefix | regex | neg
    (the ``filodb_index_lookup_seconds{op_class}`` taxonomy; a multi-filter
    lookup reports its most expensive class)."""
    if f.op == "=":
        return "eq"
    if f.op == "in":
        return "in"
    if f.op == "=~":
        if not isinstance(f.value, str):
            return "regex"
        if _LITERAL_ALT.match(f.value):
            return "in"
        _, rem = regex_literal_prefix(f.value)
        return "prefix" if rem in ("", ".*") else "regex"
    return "neg"


_CLASS_RANK = {"eq": 0, "in": 1, "prefix": 2, "regex": 3, "neg": 4}

# per-filter memos for the two hot per-lookup predicates (ColumnFilter is a
# frozen dataclass, hashable unless an "in" filter carries a list value —
# those fall through to a direct compute)
_OP_CLASS_MEMO: dict = {}
_MISSING_MEMO: dict = {}


def _op_class_cached(f: ColumnFilter) -> str:
    try:
        c = _OP_CLASS_MEMO.get(f)
    except TypeError:
        return filter_op_class(f)
    if c is None:
        c = filter_op_class(f)
        if len(_OP_CLASS_MEMO) > 4096:
            _OP_CLASS_MEMO.clear()
        _OP_CLASS_MEMO[f] = c
    return c


def _matches_missing(f: ColumnFilter) -> bool:
    """Memoized ``f.matches(None)`` — the PromQL missing-tag predicate costs
    a regex engine call per evaluation otherwise."""
    try:
        m = _MISSING_MEMO.get(f)
    except TypeError:
        return f.matches(None)
    if m is None:
        m = f.matches(None)
        if len(_MISSING_MEMO) > 4096:
            _MISSING_MEMO.clear()
        _MISSING_MEMO[f] = m
    return m

# lookup-latency histograms per op class, resolved once (the registry dict
# lookup + lock is measurable at 100k lookups/s)
_LOOKUP_HIST: dict[str, object] = {}


def _observe_lookup(op_class: str, seconds: float) -> None:
    h = _LOOKUP_HIST.get(op_class)
    if h is None:
        from ..metrics import REGISTRY

        h = REGISTRY.micro_histogram(
            "filodb_index_lookup_seconds", op_class=op_class
        )
        _LOOKUP_HIST[op_class] = h
    h.observe(seconds)


class _LabelIndex:
    """Per-label state: value -> container, the label's ``tagged`` bitmap
    (any-value-present), the lazily sorted value dictionary, and two
    monotonic versions — ``dict_version`` bumps when a value appears or
    vanishes (invalidates cached VALUE matches), ``post_version`` bumps on
    every posting change (invalidates cached merged postings and the
    device-staged copies)."""

    __slots__ = ("containers", "tagged", "values_sorted",
                 "dict_version", "post_version")

    def __init__(self, nw: int):
        self.containers: dict[str, P.ValueContainer] = {}
        self.tagged = np.zeros(nw, dtype=np.uint64)
        self.values_sorted: list[str] | None = None
        self.dict_version = 0
        self.post_version = 0

    def sorted_values(self) -> list[str]:
        if self.values_sorted is None:
            self.values_sorted = sorted(self.containers)
        return self.values_sorted


def _prefix_slice(vals: list[str], prefix: str) -> tuple[int, int]:
    """[lo, hi) slice of the sorted value list whose entries start with
    ``prefix`` (binary search; no per-value scan)."""
    import bisect

    if not prefix:
        return 0, len(vals)
    lo = bisect.bisect_left(vals, prefix)
    # smallest string > every prefixed value: bump the last char that can
    # still be bumped (chr 0x10FFFF is the ceiling)
    hi_key = None
    for i in range(len(prefix) - 1, -1, -1):
        c = ord(prefix[i])
        if c < 0x10FFFF:
            hi_key = prefix[:i] + chr(c + 1)
            break
    hi = bisect.bisect_left(vals, hi_key, lo) if hi_key else len(vals)
    return lo, hi


class PartKeyIndex:
    """Inverted bitmap index over one shard's partition keys."""

    REGEX_CACHE_MAX = 256

    def __init__(self):
        self._tags: dict[int, Mapping[str, str]] = {}
        self._labels: dict[str, _LabelIndex] = {}
        self._nbits = 0  # id-universe capacity (multiple of 64)
        self._all = np.zeros(0, dtype=np.uint64)
        self._start = np.zeros(0, dtype=np.int64)
        self._end = np.zeros(0, dtype=np.int64)
        self._lock = threading.RLock()
        # (label, pattern) -> (dict_version, matched values tuple,
        #                      post_version, merged posting view | None)
        self._regex_cache: OrderedDict = OrderedDict()
        # observed equality-selector traffic per (label, value): the device
        # tier's hot-postings chooser input (Storyboard: let the workload
        # pick what gets precomputed/staged). Bounded: coldest half pruned
        # when it overflows.
        self.traffic: dict[tuple[str, str], int] = {}
        self.TRAFFIC_MAX = 4096
        self.device_tier = None  # DevicePostingsTier when opted in
        self.lookups = 0
        # postings_stats amortization: per-label aggregates cached by
        # (dict_version, post_version), whole snapshot TTL'd — the metrics
        # scrape must not hold the index lock for an O(dictionary) walk
        self._label_stats_cache: dict[str, tuple] = {}
        self._stats_snapshot: tuple[float, dict] | None = None

    # -- write -------------------------------------------------------------

    def _grow(self, pid: int) -> None:
        nbits = max(self._nbits * 2, (pid + 64) & ~63, 1024)
        nw = P.nwords(nbits)
        self._all = P.grow_words(self._all, nw)
        ns = np.zeros(nbits, dtype=np.int64)
        ns[: len(self._start)] = self._start
        ne = np.zeros(nbits, dtype=np.int64)
        ne[: len(self._end)] = self._end
        self._start, self._end = ns, ne
        for L in self._labels.values():
            L.tagged = P.grow_words(L.tagged, nw)
        self._nbits = nbits

    def add_partkey(self, part_id: int, tags: Mapping[str, str], start_ts: int,
                    end_ts: int = END_SENTINEL) -> None:
        """reference addPartKey (PartKeyLuceneIndex.scala:505). end defaults
        to 'still ingesting' (Long.MaxValue analog)."""
        if part_id < 0:
            raise ValueError("part ids must be non-negative")
        with self._lock:
            if part_id >= self._nbits:
                self._grow(part_id)
            nw = P.nwords(self._nbits)
            self._tags[part_id] = tags
            self._start[part_id] = start_ts
            self._end[part_id] = min(end_ts, END_SENTINEL)
            P.set_bit(self._all, part_id)
            for k, v in tags.items():
                L = self._labels.get(k)
                if L is None:
                    L = self._labels[k] = _LabelIndex(nw)
                c = L.containers.get(v)
                if c is None:
                    c = L.containers[v] = P.ValueContainer()
                    L.values_sorted = None
                    L.dict_version += 1
                c.add(part_id, self._nbits)
                P.set_bit(L.tagged, part_id)
                L.post_version += 1

    def update_end_time(self, part_id: int, end_ts: int) -> None:
        """reference updatePartKeyWithEndTime:628 (series stopped
        ingesting)."""
        with self._lock:
            if 0 <= part_id < self._nbits:
                self._end[part_id] = min(end_ts, END_SENTINEL)

    def remove(self, part_ids: Iterable[int]) -> None:
        with self._lock:
            by_container: dict[tuple[str, str], list[int]] = {}
            for pid in part_ids:
                pid = int(pid)
                tags = self._tags.pop(pid, None)
                if tags is None:
                    continue
                P.clear_bit(self._all, pid)
                for k, v in tags.items():
                    by_container.setdefault((k, v), []).append(pid)
                    P.clear_bit(self._labels[k].tagged, pid)
            for (k, v), pids in by_container.items():
                L = self._labels[k]
                c = L.containers.get(v)
                if c is None:
                    continue
                c.discard_many(pids, self._nbits)
                L.post_version += 1
                if not len(c):
                    del L.containers[v]
                    L.values_sorted = None
                    L.dict_version += 1

    # -- matcher -> posting view -------------------------------------------

    def _container_view(self, L: _LabelIndex, value: str):
        c = L.containers.get(value)
        return c.view(self._nbits) if c is not None else None

    def _values_posting(self, L: _LabelIndex, values) -> tuple:
        views = []
        for v in values:
            view = self._container_view(L, v)
            if view is not None:
                views.append(view)
        return P.p_or_views(views, P.nwords(self._nbits))

    def _regex_posting(self, L: _LabelIndex, label: str, pattern: str):
        """Dictionary-batched anchored regex -> posting view. One pass over
        the label's sorted value list, prefix-narrowed by binary search;
        matched values' containers OR together. Results cache under
        (label, pattern): matched VALUES survive until the dictionary
        changes, the merged posting until any posting under the label
        changes."""
        key = (label, pattern)
        hit = self._regex_cache.get(key)
        if hit is not None:
            dv, values, pv, merged = hit
            if dv == L.dict_version:
                self._regex_cache.move_to_end(key)
                if pv == L.post_version and merged is not None:
                    return merged
                merged = self._values_posting(L, values)
                self._regex_cache[key] = (dv, values, L.post_version, merged)
                return merged
            del self._regex_cache[key]
        if _LITERAL_ALT.match(pattern):
            values = tuple(v for v in pattern.split("|") if v in L.containers)
        else:
            vals = L.sorted_values()
            prefix, rem = regex_literal_prefix(pattern)
            lo, hi = _prefix_slice(vals, prefix)
            if rem == "":
                values = (prefix,) if prefix in L.containers else ()
            elif rem == ".*":
                values = tuple(vals[lo:hi])
            else:
                rx = re.compile(pattern)
                values = tuple(v for v in vals[lo:hi] if rx.fullmatch(v))
        merged = self._values_posting(L, values)
        self._regex_cache[key] = (L.dict_version, values,
                                  L.post_version, merged)
        while len(self._regex_cache) > self.REGEX_CACHE_MAX:
            self._regex_cache.popitem(last=False)
        return merged

    def _positive_posting(self, f: ColumnFilter, L: _LabelIndex | None):
        """Posting of TAGGED parts whose value satisfies the POSITIVE form
        of the matcher (callers layer the missing-tag rule / negation)."""
        if L is None:
            return P.p_empty()
        if f.op in ("=", "!="):
            view = self._container_view(L, f.value)
            return view if view is not None else P.p_empty()
        if f.op in ("in", "not in"):
            return self._values_posting(L, f.value)
        # "=~" / "!~"
        return self._regex_posting(L, f.column, f.value)

    def _posting_for_filter(self, f: ColumnFilter):
        L = self._labels.get(f.column)
        nw = P.nwords(self._nbits)
        pos = self._positive_posting(f, L)
        if f.op in ("=", "in", "=~"):
            out = pos
        else:
            # negative matcher: tagged &~ positive — ONE dictionary pass +
            # one ANDNOT, never a per-part-key walk
            tagged = ("d", L.tagged) if L is not None else P.p_empty()
            out = P.p_andnot(tagged, pos, nw)
        if _matches_missing(f):
            # PromQL: a matcher satisfied by the EMPTY string also matches
            # series missing the tag entirely ({k!="v"}, {k=~".*"}, {k=""})
            untagged = (P.p_andnot(("d", self._all), ("d", L.tagged), nw)
                        if L is not None else ("d", self._all))
            out = P.p_or_views([out, untagged], nw)
        return out

    # -- query -------------------------------------------------------------

    def part_ids_from_filters(
        self, filters: Sequence[ColumnFilter], start_ts: int, end_ts: int,
        limit: int | None = None,
    ) -> np.ndarray:
        """AND of filters + [start,end] overlap (reference
        partIdsFromFilters), all vectorized over posting views."""
        t0 = time.perf_counter()
        op_class = "eq"
        with self._lock:
            self.lookups += 1
            nw = P.nwords(self._nbits)
            res = None
            if filters:
                classed = [(f, _op_class_cached(f)) for f in filters]
                if len(classed) == 1:
                    op_class = classed[0][1]
                else:
                    op_class = max(
                        (c for _, c in classed), key=_CLASS_RANK.__getitem__
                    )
                    # cheapest, most selective classes first: an empty AND
                    # short-circuits before any regex pass runs
                    classed.sort(key=lambda fc: _CLASS_RANK[fc[1]])
                tier = self.device_tier
                if tier is not None:
                    self._record_traffic(classed)
                    dev = tier.try_intersect(classed)
                    if dev is not None:
                        res = ("d", dev)
                if res is None:
                    for f, _c in classed:
                        p = self._posting_for_filter(f)
                        res = p if res is None else P.p_and(res, p, nw)
                        if P.p_is_empty(res):
                            _observe_lookup(op_class,
                                            time.perf_counter() - t0)
                            return np.empty(0, dtype=np.int32)
            ids = P.p_to_ids(res) if res is not None else P.dense_to_ids(self._all)
            if len(ids) and (start_ts > 0 or end_ts < END_SENTINEL):
                # vectorized [start, end] overlap; skipped for the
                # whole-retention probes metadata endpoints issue
                keep = (self._start[ids] <= end_ts) & (self._end[ids] >= start_ts)
                ids = ids[keep]
            if limit is not None:
                ids = ids[:limit]
            # int32 view at the API edge; boolean indexing above already
            # copied, and sparse pass-throughs are container-owned arrays
            # callers treat as read-only (the original returned fresh
            # arrays, but every consumer only reads/iterates)
            out = np.asarray(ids, dtype=np.int32)
        _observe_lookup(op_class, time.perf_counter() - t0)
        return out

    def _record_traffic(self, classed) -> None:
        tr = self.traffic
        for f, c in classed:
            # {k=""} equality also matches series MISSING the tag (the
            # missing-tag rule below) — a staged posting bitmap alone can't
            # answer it, so it must never become a device-tier candidate
            if c == "eq" and f.value != "":
                key = (f.column, f.value)
                tr[key] = tr.get(key, 0) + 1
        if len(tr) > self.TRAFFIC_MAX:
            keep = sorted(tr.items(), key=lambda kv: -kv[1])[: self.TRAFFIC_MAX // 2]
            self.traffic = dict(keep)

    def label_names(self, filters: Sequence[ColumnFilter], start_ts: int,
                    end_ts: int) -> list[str]:
        """reference labelNamesEfficient:397."""
        with self._lock:
            if not filters:
                return sorted(k for k, L in self._labels.items() if L.containers)
            pids = self.part_ids_from_filters(filters, start_ts, end_ts)
            if not len(pids):
                return []
            nw = P.nwords(self._nbits)
            return sorted(
                k for k, L in self._labels.items()
                if L.containers and bool(
                    P.test_bits(P.grow_words(L.tagged, nw), pids).any()
                )
            )

    def label_values(
        self, filters: Sequence[ColumnFilter], label: str, start_ts: int,
        end_ts: int, limit: int | None = None,
    ) -> list[str]:
        """reference indexValues:445 / labelValuesEfficient."""
        with self._lock:
            if not filters:
                L = self._labels.get(label)
                vals = list(L.sorted_values()) if L is not None else []
            else:
                pids = self.part_ids_from_filters(filters, start_ts, end_ts)
                vset = {self._tags[int(p)].get(label) for p in pids}
                vals = sorted(v for v in vset if v is not None)
            return vals[:limit] if limit else vals

    def partkeys_from_filters(
        self, filters: Sequence[ColumnFilter], start_ts: int, end_ts: int,
        limit: int | None = None,
    ) -> list[Mapping[str, str]]:
        return [self._tags[int(p)]
                for p in self.part_ids_from_filters(filters, start_ts, end_ts, limit)]

    def start_time(self, part_id: int) -> int:
        return int(self._start[part_id])

    def end_time(self, part_id: int) -> int:
        return int(self._end[part_id])

    def tags_of(self, part_id: int) -> Mapping[str, str]:
        return self._tags[part_id]

    def __len__(self) -> int:
        return len(self._tags)

    def cardinality(self, label: str) -> int:
        L = self._labels.get(label)
        return len(L.containers) if L is not None else 0

    def value_counts(self, label: str) -> dict[str, int]:
        """value -> live-series count for one label, O(values) straight off
        the container lengths (no posting walk) — the cardinality report's
        per-label feed (memstore/cardinality.py label_top_values)."""
        with self._lock:
            L = self._labels.get(label)
            if L is None:
                return {}
            return {v: len(c) for v, c in L.containers.items()}

    # -- introspection (metrics + /debug/index) ----------------------------

    def postings_stats(self, max_age_s: float = 5.0) -> dict:
        """Per-label dictionary size + postings footprint, and totals —
        the /debug/index payload and the filodb_index_* gauge feed.

        Amortized two ways so a /metrics scrape never stalls lookups or
        ingest behind an O(dictionary) walk under the index lock: each
        label's aggregate is cached against its (dict_version,
        post_version) pair (quiescent labels — the common case — cost one
        dict probe), and the assembled snapshot is served for
        ``max_age_s`` before any recount happens at all (pass 0 to force
        a fresh walk, e.g. in tests)."""
        snap = self._stats_snapshot
        now = time.monotonic()
        if snap is not None and now - snap[0] < max_age_s:
            out = dict(snap[1])
            out["lookups"] = self.lookups  # always fresh, O(1)
            return out
        with self._lock:
            labels = {}
            total_bytes = total_values = 0
            cache = self._label_stats_cache
            for k, L in self._labels.items():
                ver = (L.dict_version, L.post_version)
                hit = cache.get(k)
                if hit is None or hit[0] != ver:
                    rec = {
                        "values": len(L.containers),
                        # ndarray.nbytes / len() are O(1) per container
                        "postings_bytes": sum(
                            c.nbytes() for c in L.containers.values()
                        ) + L.tagged.nbytes,
                        "dense_containers": sum(
                            1 for c in L.containers.values()
                            if c.words is not None
                        ),
                    }
                    cache[k] = hit = (ver, rec)
                labels[k] = hit[1]
                total_bytes += hit[1]["postings_bytes"]
                total_values += hit[1]["values"]
            for dead in [k for k in cache if k not in self._labels]:
                del cache[dead]
            total_bytes += (self._all.nbytes + self._start.nbytes
                            + self._end.nbytes)
            tier = self.device_tier
            out = {
                "num_part_keys": len(self._tags),
                "capacity_bits": self._nbits,
                "labels": labels,
                "postings_bytes": total_bytes,
                "dictionary_size": total_values,
                "lookups": self.lookups,
                "device": tier.snapshot() if tier is not None else None,
            }
            self._stats_snapshot = (now, out)
            return out


class SetBasedPartKeyIndex:
    """The original pure-Python set-arithmetic index, retained as (a) the
    randomized property-test ORACLE the bitmap index is proven against
    (tests/test_index_bitmap.py) and (b) the ``index_backend="set"``
    escape hatch. One fix over the original: ``remove`` drops a label
    whose last value vanishes, so ``label_names`` agrees with the bitmap
    index instead of leaking dead labels forever."""

    def __init__(self):
        self._postings: dict[str, dict[str, set[int]]] = {}
        self._tags: dict[int, Mapping[str, str]] = {}
        self._start: dict[int, int] = {}
        self._end: dict[int, int] = {}
        self._all: set[int] = set()

    # -- write -------------------------------------------------------------

    def add_partkey(self, part_id: int, tags: Mapping[str, str], start_ts: int,
                    end_ts: int = END_SENTINEL) -> None:
        self._tags[part_id] = tags
        self._start[part_id] = start_ts
        self._end[part_id] = end_ts
        self._all.add(part_id)
        for k, v in tags.items():
            self._postings.setdefault(k, {}).setdefault(v, set()).add(part_id)

    def update_end_time(self, part_id: int, end_ts: int) -> None:
        self._end[part_id] = end_ts

    def remove(self, part_ids: Iterable[int]) -> None:
        for pid in part_ids:
            tags = self._tags.pop(pid, None)
            if tags is None:
                continue
            self._all.discard(pid)
            self._start.pop(pid, None)
            self._end.pop(pid, None)
            for k, v in tags.items():
                s = self._postings.get(k, {}).get(v)
                if s is not None:
                    s.discard(pid)
                    if not s:
                        del self._postings[k][v]
                        if not self._postings[k]:
                            # keep label_names parity with the bitmap
                            # index: a label with no live values is gone
                            del self._postings[k]

    # -- query -------------------------------------------------------------

    def _ids_for_filter(self, f: ColumnFilter) -> set[int]:
        vals = self._postings.get(f.column, {})
        if f.op == "=":
            out = set(vals.get(f.value, ()))
        elif f.op == "in":
            out = set()
            for v in f.value:
                out |= vals.get(v, set())
        elif f.op == "=~" and isinstance(f.value, str) and _LITERAL_ALT.match(f.value):
            out = set()
            for v in f.value.split("|"):
                out |= vals.get(v, set())
        else:
            # negative / general-regex filters scan the value dictionary
            out = set()
            for v, ids in vals.items():
                if f.matches(v):
                    out |= ids
        # PromQL: a matcher satisfied by the EMPTY string also matches series
        # missing the tag entirely ({k!="v"}, {k=~".*"}, {k=""} ...)
        if f.matches(None):
            tagged = set()
            for ids in vals.values():
                tagged |= ids
            out |= self._all - tagged
        return out

    def part_ids_from_filters(
        self, filters: Sequence[ColumnFilter], start_ts: int, end_ts: int,
        limit: int | None = None,
    ) -> np.ndarray:
        ids: set[int] | None = None
        # apply equality filters first — cheapest and most selective
        ordered = sorted(filters, key=lambda f: 0 if f.op in ("=", "in") else 1)
        for f in ordered:
            s = self._ids_for_filter(f)
            ids = s if ids is None else ids & s
            if not ids:
                return np.empty(0, dtype=np.int32)
        if ids is None:
            ids = set(self._all)
        out = [p for p in ids if self._start[p] <= end_ts and self._end[p] >= start_ts]
        out.sort()
        if limit is not None:
            out = out[:limit]
        return np.asarray(out, dtype=np.int32)

    def label_names(self, filters: Sequence[ColumnFilter], start_ts: int,
                    end_ts: int) -> list[str]:
        if not filters:
            return sorted(self._postings.keys())
        pids = self.part_ids_from_filters(filters, start_ts, end_ts)
        names: set[str] = set()
        for p in pids:
            names |= set(self._tags[int(p)].keys())
        return sorted(names)

    def label_values(
        self, filters: Sequence[ColumnFilter], label: str, start_ts: int,
        end_ts: int, limit: int | None = None,
    ) -> list[str]:
        if not filters:
            vals = sorted(self._postings.get(label, {}).keys())
        else:
            pids = self.part_ids_from_filters(filters, start_ts, end_ts)
            vset = {self._tags[int(p)].get(label) for p in pids}
            vals = sorted(v for v in vset if v is not None)
        return vals[:limit] if limit else vals

    def partkeys_from_filters(
        self, filters: Sequence[ColumnFilter], start_ts: int, end_ts: int,
        limit: int | None = None,
    ) -> list[Mapping[str, str]]:
        return [self._tags[int(p)]
                for p in self.part_ids_from_filters(filters, start_ts, end_ts, limit)]

    def start_time(self, part_id: int) -> int:
        return self._start[part_id]

    def end_time(self, part_id: int) -> int:
        return self._end[part_id]

    def tags_of(self, part_id: int) -> Mapping[str, str]:
        return self._tags[part_id]

    def __len__(self) -> int:
        return len(self._all)

    def cardinality(self, label: str) -> int:
        return len(self._postings.get(label, {}))

    def value_counts(self, label: str) -> dict[str, int]:
        return {v: len(s) for v, s in self._postings.get(label, {}).items()}
