"""Part-key tag index (reference L2: memstore/PartKeyIndex.scala traits,
PartKeyLuceneIndex.scala:70 / PartKeyTantivyIndex.scala:38 + 6.3k Rust).

The reference indexes each series' tag map in Lucene or Tantivy and answers
``partIdsFromFilters`` (:655), label-names/values, and start/end-time queries.
This is a host-side inverted index re-designed for the query shapes PromQL
actually issues: per (tag key -> value -> posting set) with anchored-regex and
time-overlap filtering. Pure-Python posting sets here; the C++ fast path
(native/index.cpp) plugs in behind the same class when built.

Regex fast path: patterns that are pure alternations of literals
(``a|b|c``) expand to set unions without scanning values (the reference's
tantivy_utils has the same "range-aware regex" optimization).
"""

from __future__ import annotations

import re
from typing import Iterable, Mapping, Sequence

import numpy as np

from ..core.filters import ColumnFilter

# alternations of pure literals only: '.' and '+' are regex metacharacters
# ('ab+' must regex-match 'abb', never look up the literal value "ab+")
_LITERAL_ALT = re.compile(r"^[\w-]+(\|[\w-]*)*$")


class PartKeyIndex:
    """Inverted index over one shard's partition keys."""

    def __init__(self):
        self._postings: dict[str, dict[str, set[int]]] = {}
        self._tags: dict[int, Mapping[str, str]] = {}
        self._start: dict[int, int] = {}
        self._end: dict[int, int] = {}
        self._all: set[int] = set()

    # -- write -------------------------------------------------------------

    def add_partkey(self, part_id: int, tags: Mapping[str, str], start_ts: int, end_ts: int = 2**62) -> None:
        """reference addPartKey (PartKeyLuceneIndex.scala:505). end defaults to
        'still ingesting' (Long.MaxValue analog)."""
        self._tags[part_id] = tags
        self._start[part_id] = start_ts
        self._end[part_id] = end_ts
        self._all.add(part_id)
        for k, v in tags.items():
            self._postings.setdefault(k, {}).setdefault(v, set()).add(part_id)

    def update_end_time(self, part_id: int, end_ts: int) -> None:
        """reference updatePartKeyWithEndTime:628 (series stopped ingesting)."""
        self._end[part_id] = end_ts

    def remove(self, part_ids: Iterable[int]) -> None:
        for pid in part_ids:
            tags = self._tags.pop(pid, None)
            if tags is None:
                continue
            self._all.discard(pid)
            self._start.pop(pid, None)
            self._end.pop(pid, None)
            for k, v in tags.items():
                s = self._postings.get(k, {}).get(v)
                if s is not None:
                    s.discard(pid)
                    if not s:
                        del self._postings[k][v]

    # -- query -------------------------------------------------------------

    def _ids_for_filter(self, f: ColumnFilter) -> set[int]:
        vals = self._postings.get(f.column, {})
        if f.op == "=":
            out = set(vals.get(f.value, ()))
        elif f.op == "in":
            out = set()
            for v in f.value:
                out |= vals.get(v, set())
        elif f.op == "=~" and isinstance(f.value, str) and _LITERAL_ALT.match(f.value):
            out = set()
            for v in f.value.split("|"):
                out |= vals.get(v, set())
        else:
            # negative / general-regex filters scan the value dictionary
            out = set()
            for v, ids in vals.items():
                if f.matches(v):
                    out |= ids
        # PromQL: a matcher satisfied by the EMPTY string also matches series
        # missing the tag entirely ({k!="v"}, {k=~".*"}, {k=""} ...)
        if f.matches(None):
            tagged = set()
            for ids in vals.values():
                tagged |= ids
            out |= self._all - tagged
        return out

    def part_ids_from_filters(
        self, filters: Sequence[ColumnFilter], start_ts: int, end_ts: int, limit: int | None = None
    ) -> np.ndarray:
        """AND of filters + [start,end] overlap (reference partIdsFromFilters)."""
        ids: set[int] | None = None
        # apply equality filters first — cheapest and most selective
        ordered = sorted(filters, key=lambda f: 0 if f.op in ("=", "in") else 1)
        for f in ordered:
            s = self._ids_for_filter(f)
            ids = s if ids is None else ids & s
            if not ids:
                return np.empty(0, dtype=np.int32)
        if ids is None:
            ids = set(self._all)
        out = [p for p in ids if self._start[p] <= end_ts and self._end[p] >= start_ts]
        out.sort()
        if limit is not None:
            out = out[:limit]
        return np.asarray(out, dtype=np.int32)

    def label_names(self, filters: Sequence[ColumnFilter], start_ts: int, end_ts: int) -> list[str]:
        """reference labelNamesEfficient:397."""
        if not filters:
            return sorted(self._postings.keys())
        pids = self.part_ids_from_filters(filters, start_ts, end_ts)
        names: set[str] = set()
        for p in pids:
            names |= set(self._tags[int(p)].keys())
        return sorted(names)

    def label_values(
        self, filters: Sequence[ColumnFilter], label: str, start_ts: int, end_ts: int, limit: int | None = None
    ) -> list[str]:
        """reference indexValues:445 / labelValuesEfficient."""
        if not filters:
            vals = sorted(self._postings.get(label, {}).keys())
        else:
            pids = self.part_ids_from_filters(filters, start_ts, end_ts)
            vset = {self._tags[int(p)].get(label) for p in pids}
            vals = sorted(v for v in vset if v is not None)
        return vals[:limit] if limit else vals

    def partkeys_from_filters(
        self, filters: Sequence[ColumnFilter], start_ts: int, end_ts: int, limit: int | None = None
    ) -> list[Mapping[str, str]]:
        return [self._tags[int(p)] for p in self.part_ids_from_filters(filters, start_ts, end_ts, limit)]

    def start_time(self, part_id: int) -> int:
        return self._start[part_id]

    def end_time(self, part_id: int) -> int:
        return self._end[part_id]

    def tags_of(self, part_id: int) -> Mapping[str, str]:
        return self._tags[part_id]

    def __len__(self) -> int:
        return len(self._all)

    def cardinality(self, label: str) -> int:
        return len(self._postings.get(label, {}))
