"""Part-key index backed by the C++ posting-list core (reference analog:
PartKeyTantivyIndex.scala:38 + the 6.3k-line Rust tantivy crate — the
drop-in second implementation of the PartKeyIndex API, exercised by the
same shared-behavior test suite as the Python index, mirroring the
reference's PartKeyIndexRawSpec pattern).

Equality-AND + time-overlap queries run in C++; regex/negative matchers and
label introspection use the Python-side tag mirror (the reference keeps
tantivy's term dictionaries for the same purpose).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterable, Mapping, Sequence

import numpy as np

from ..core.filters import ColumnFilter
from .index import _LITERAL_ALT, PartKeyIndex

_HERE = os.path.join(os.path.dirname(__file__), "..", "native")
_SO = os.path.abspath(os.path.join(_HERE, "libfilodbindex.so"))
_SRC = os.path.abspath(os.path.join(_HERE, "index.cpp"))
_lock = threading.Lock()
_lib = None
_tried = False


def _load():
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            try:
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", _SRC, "-o", _SO],
                    check=True, capture_output=True, timeout=120,
                )
            except Exception:
                return None
        try:
            L = ctypes.CDLL(_SO)
        except OSError:
            return None
        c_charpp = ctypes.POINTER(ctypes.c_char_p)
        c_longp = ctypes.POINTER(ctypes.c_long)
        c_i32p = ctypes.POINTER(ctypes.c_int32)
        L.fdb_idx_new.restype = ctypes.c_void_p
        L.fdb_idx_free.argtypes = [ctypes.c_void_p]
        L.fdb_idx_add.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
            c_charpp, c_longp, c_charpp, c_longp, ctypes.c_int64, ctypes.c_int64,
        ]
        L.fdb_idx_update_end.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64]
        L.fdb_idx_remove.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32, c_charpp, c_longp, c_charpp, c_longp,
        ]
        L.fdb_idx_query.restype = ctypes.c_long
        L.fdb_idx_query.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, c_charpp, c_longp, c_charpp, c_longp,
            ctypes.c_int64, ctypes.c_int64, c_i32p, ctypes.c_long,
        ]
        L.fdb_idx_all.restype = ctypes.c_long
        L.fdb_idx_all.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, c_i32p, ctypes.c_long]
        L.fdb_idx_size.restype = ctypes.c_long
        L.fdb_idx_size.argtypes = [ctypes.c_void_p]
        _lib = L
        return _lib


def native_index_available() -> bool:
    return _load() is not None


def _pack_pairs(tags: Mapping[str, str]):
    keys = [k.encode() for k in tags.keys()]
    vals = [v.encode() for v in tags.values()]
    n = len(keys)
    KeyArr = ctypes.c_char_p * n
    LenArr = ctypes.c_long * n
    return (
        n,
        KeyArr(*keys), LenArr(*[len(k) for k in keys]),
        KeyArr(*vals), LenArr(*[len(v) for v in vals]),
    )


class NativePartKeyIndex(PartKeyIndex):
    """PartKeyIndex with the hot equality path in C++.

    Inherits the Python postings for regex/label APIs (kept in sync) but
    answers pure-equality AND queries from the native core.
    """

    def __init__(self):
        super().__init__()
        L = _load()
        if L is None:
            raise RuntimeError("native index library unavailable")
        self._L = L
        self._h = L.fdb_idx_new()

    def __del__(self):
        try:
            self._L.fdb_idx_free(self._h)
        except Exception:
            pass

    # -- writes kept in both stores ---------------------------------------

    def add_partkey(self, part_id, tags, start_ts, end_ts=2**62):
        super().add_partkey(part_id, tags, start_ts, end_ts)
        n, k, kl, v, vl = _pack_pairs(tags)
        self._L.fdb_idx_add(self._h, part_id, n, k, kl, v, vl, start_ts, min(end_ts, 2**62))

    def update_end_time(self, part_id, end_ts):
        super().update_end_time(part_id, end_ts)
        self._L.fdb_idx_update_end(self._h, part_id, end_ts)

    def remove(self, part_ids: Iterable[int]):
        for pid in list(part_ids):
            tags = self._tags.get(pid)
            if tags is not None:
                n, k, kl, v, vl = _pack_pairs(tags)
                self._L.fdb_idx_remove(self._h, pid, n, k, kl, v, vl)
            super().remove([pid])

    # -- queries ------------------------------------------------------------

    def part_ids_from_filters(self, filters: Sequence[ColumnFilter], start_ts, end_ts, limit=None):
        # equality with "" matches missing tags too (PromQL) — python path
        eq = [f for f in filters if f.op == "=" and f.value != ""]
        rest = [f for f in filters if not (f.op == "=" and f.value != "")]
        if eq and not rest:
            out = self._query_native(eq, start_ts, end_ts)
            if limit is not None:
                out = out[:limit]
            return out
        if eq:
            cands = self._query_native(eq, start_ts, end_ts)
            keep = [
                p for p in cands.tolist()
                if all(f.matches(self._tags[p].get(f.column)) for f in rest)
            ]
            if limit is not None:
                keep = keep[:limit]
            return np.asarray(keep, dtype=np.int32)
        return super().part_ids_from_filters(filters, start_ts, end_ts, limit)

    def _query_native(self, eq_filters, start_ts, end_ts) -> np.ndarray:
        n = len(eq_filters)
        keys = [f.column.encode() for f in eq_filters]
        vals = [f.value.encode() for f in eq_filters]
        KeyArr = ctypes.c_char_p * n
        LenArr = ctypes.c_long * n
        cap = max(len(self._all), 1)
        out = np.empty(cap, dtype=np.int32)
        got = self._L.fdb_idx_query(
            self._h, n,
            KeyArr(*keys), LenArr(*[len(k) for k in keys]),
            KeyArr(*vals), LenArr(*[len(v) for v in vals]),
            start_ts, end_ts,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), cap,
        )
        if got < 0:
            return super().part_ids_from_filters(eq_filters, start_ts, end_ts)
        return np.sort(out[: min(got, cap)])
