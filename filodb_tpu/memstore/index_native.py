"""Part-key index backed by the C++ posting-list core (reference analog:
PartKeyTantivyIndex.scala:38 + the 6.3k-line Rust tantivy crate — the
drop-in second implementation of the PartKeyIndex API, exercised by the
same shared-behavior test suite as the Python index, mirroring the
reference's PartKeyIndexRawSpec pattern).

Equality-AND + time-overlap queries run in C++; regex/negative matchers and
label introspection use the Python-side tag mirror (the reference keeps
tantivy's term dictionaries for the same purpose).
"""

from __future__ import annotations

import ctypes
import os
import re
import subprocess
import threading
from typing import Iterable, Mapping, Sequence

import numpy as np

from ..core.filters import ColumnFilter
from .index import _LITERAL_ALT, PartKeyIndex, regex_literal_prefix

_HERE = os.path.join(os.path.dirname(__file__), "..", "native")
_SO = os.path.abspath(os.path.join(_HERE, "libfilodbindex.so"))
_SRC = os.path.abspath(os.path.join(_HERE, "index.cpp"))
_lock = threading.Lock()
_lib = None
_tried = False


def _load():
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            try:
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", _SRC, "-o", _SO],
                    check=True, capture_output=True, timeout=120,
                )
            except Exception:
                return None
        try:
            L = ctypes.CDLL(_SO)
        except OSError:
            return None
        c_charpp = ctypes.POINTER(ctypes.c_char_p)
        c_longp = ctypes.POINTER(ctypes.c_long)
        c_i32p = ctypes.POINTER(ctypes.c_int32)
        L.fdb_idx_new.restype = ctypes.c_void_p
        L.fdb_idx_free.argtypes = [ctypes.c_void_p]
        L.fdb_idx_add.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
            c_charpp, c_longp, c_charpp, c_longp, ctypes.c_int64, ctypes.c_int64,
        ]
        L.fdb_idx_update_end.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64]
        L.fdb_idx_remove.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32, c_charpp, c_longp, c_charpp, c_longp,
        ]
        L.fdb_idx_query.restype = ctypes.c_long
        L.fdb_idx_query.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, c_charpp, c_longp, c_charpp, c_longp,
            ctypes.c_int64, ctypes.c_int64, c_i32p, ctypes.c_long,
        ]
        L.fdb_idx_all.restype = ctypes.c_long
        L.fdb_idx_all.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, c_i32p, ctypes.c_long]
        L.fdb_idx_size.restype = ctypes.c_long
        L.fdb_idx_size.argtypes = [ctypes.c_void_p]
        L.fdb_idx_values_prefix.restype = ctypes.c_long
        L.fdb_idx_values_prefix.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long,
            ctypes.c_char_p, ctypes.c_long,
            ctypes.c_char_p, ctypes.c_long, c_longp,
        ]
        L.fdb_idx_union.restype = ctypes.c_long
        L.fdb_idx_union.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long,
            ctypes.c_int32, c_charpp, c_longp,
            ctypes.c_int64, ctypes.c_int64, c_i32p, ctypes.c_long,
        ]
        L.fdb_idx_union_prefix.restype = ctypes.c_long
        L.fdb_idx_union_prefix.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long,
            ctypes.c_char_p, ctypes.c_long,
            ctypes.c_int64, ctypes.c_int64, c_i32p, ctypes.c_long,
        ]
        _lib = L
        return _lib


# regex_literal_prefix moved to memstore/index.py (the bitmap index's
# dictionary-batched regex path uses the same prefix split); re-exported
# here for backward compatibility.


def native_index_available() -> bool:
    return _load() is not None


def _pack_pairs(tags: Mapping[str, str]):
    keys = [k.encode() for k in tags.keys()]
    vals = [v.encode() for v in tags.values()]
    n = len(keys)
    KeyArr = ctypes.c_char_p * n
    LenArr = ctypes.c_long * n
    return (
        n,
        KeyArr(*keys), LenArr(*[len(k) for k in keys]),
        KeyArr(*vals), LenArr(*[len(v) for v in vals]),
    )


class NativePartKeyIndex(PartKeyIndex):
    """PartKeyIndex with the hot equality path in C++.

    Inherits the Python postings for regex/label APIs (kept in sync) but
    answers pure-equality AND queries from the native core.
    """

    def __init__(self):
        super().__init__()
        L = _load()
        if L is None:
            raise RuntimeError("native index library unavailable")
        self._L = L
        self._h = L.fdb_idx_new()

    def __del__(self):
        try:
            self._L.fdb_idx_free(self._h)
        except Exception:
            pass

    # -- writes kept in both stores ---------------------------------------

    def add_partkey(self, part_id, tags, start_ts, end_ts=2**62):
        super().add_partkey(part_id, tags, start_ts, end_ts)
        n, k, kl, v, vl = _pack_pairs(tags)
        self._L.fdb_idx_add(self._h, part_id, n, k, kl, v, vl, start_ts, min(end_ts, 2**62))

    def update_end_time(self, part_id, end_ts):
        super().update_end_time(part_id, end_ts)
        self._L.fdb_idx_update_end(self._h, part_id, end_ts)

    def remove(self, part_ids: Iterable[int]):
        for pid in list(part_ids):
            tags = self._tags.get(pid)
            if tags is not None:
                n, k, kl, v, vl = _pack_pairs(tags)
                self._L.fdb_idx_remove(self._h, pid, n, k, kl, v, vl)
            super().remove([pid])

    # -- queries ------------------------------------------------------------

    def part_ids_from_filters(self, filters: Sequence[ColumnFilter], start_ts, end_ts, limit=None):
        # equality with "" matches missing tags too (PromQL) — python path
        eq = [f for f in filters if f.op == "=" and f.value != ""]
        # positive anchored regexes that can't match a MISSING tag take the
        # native prefix-range path; everything else stays python
        rex = [
            f for f in filters
            if f.op == "=~" and isinstance(f.value, str) and not f.matches(None)
        ]
        rest = [f for f in filters if not (f.op == "=" and f.value != "") and f not in rex]
        if not eq and not rex:
            return super().part_ids_from_filters(filters, start_ts, end_ts, limit)
        cands = None
        if eq:
            cands = self._query_native(eq, start_ts, end_ts)
        for f in rex:
            ids = self._query_regex_native(f, start_ts, end_ts)
            cands = ids if cands is None else np.intersect1d(
                cands, ids, assume_unique=True
            )
            if not len(cands):
                return np.empty(0, dtype=np.int32)
        if rest:
            keep = [
                p for p in cands.tolist()
                if all(f.matches(self._tags[p].get(f.column)) for f in rest)
            ]
            cands = np.asarray(keep, dtype=np.int32)
        if limit is not None:
            cands = cands[:limit]
        return cands

    def _query_regex_native(self, f: ColumnFilter, start_ts, end_ts) -> np.ndarray:
        """Range-aware anchored regex: narrow the value dictionary to the
        literal-prefix slice in C++, regex-match only that slice, union the
        postings natively (reference tantivy_utils range-aware regex;
        PartKeyTantivyIndex.scala:38)."""
        pattern = f.value
        key = f.column.encode()
        cap = max(len(self._tags), 1)
        out = np.empty(cap, dtype=np.int32)
        optr = out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        if _LITERAL_ALT.match(pattern):
            # pure literal alternation (a|b|c): native union, no regex
            enc = [v.encode() for v in pattern.split("|")]
            n = len(enc)
            got = self._L.fdb_idx_union(
                self._h, key, len(key), n,
                (ctypes.c_char_p * n)(*enc),
                (ctypes.c_long * n)(*[len(v) for v in enc]),
                start_ts, end_ts, optr, cap,
            )
            return out[: min(got, cap)]
        prefix, remainder = regex_literal_prefix(pattern)
        if remainder == "" or remainder == ".*":
            # pure literal (handled as exact value) or pure prefix match:
            # no per-value regex anywhere
            if remainder == "":
                got = self._L.fdb_idx_union(
                    self._h, key, len(key), 1,
                    (ctypes.c_char_p * 1)(prefix.encode()),
                    (ctypes.c_long * 1)(len(prefix.encode())),
                    start_ts, end_ts, optr, cap,
                )
            else:
                p = prefix.encode()
                got = self._L.fdb_idx_union_prefix(
                    self._h, key, len(key), p, len(p),
                    start_ts, end_ts, optr, cap,
                )
            return out[: min(got, cap)]
        # general anchored regex: fetch the prefix-narrowed candidate
        # values, regex-match them host-side, union the survivors natively
        rx = re.compile(pattern)
        values = self._values_with_prefix(key, prefix.encode())
        matched = [v for v in values if rx.fullmatch(v) is not None]
        if not matched:
            return np.empty(0, dtype=np.int32)
        enc = [v.encode() for v in matched]
        n = len(enc)
        got = self._L.fdb_idx_union(
            self._h, key, len(key), n,
            (ctypes.c_char_p * n)(*enc),
            (ctypes.c_long * n)(*[len(v) for v in enc]),
            start_ts, end_ts, optr, cap,
        )
        return out[: min(got, cap)]

    def _values_with_prefix(self, key: bytes, prefix: bytes) -> list[str]:
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            used = ctypes.c_long(0)
            n = self._L.fdb_idx_values_prefix(
                self._h, key, len(key), prefix, len(prefix),
                buf, cap, ctypes.byref(used),
            )
            if used.value <= cap:
                break
            cap = used.value + 16
        out = []
        raw = buf.raw
        off = 0
        for _ in range(n):
            ln = int.from_bytes(raw[off : off + 4], "little")
            out.append(raw[off + 4 : off + 4 + ln].decode())
            off += 4 + ln
        return out

    def _query_native(self, eq_filters, start_ts, end_ts) -> np.ndarray:
        n = len(eq_filters)
        keys = [f.column.encode() for f in eq_filters]
        vals = [f.value.encode() for f in eq_filters]
        KeyArr = ctypes.c_char_p * n
        LenArr = ctypes.c_long * n
        cap = max(len(self._tags), 1)
        out = np.empty(cap, dtype=np.int32)
        got = self._L.fdb_idx_query(
            self._h, n,
            KeyArr(*keys), LenArr(*[len(k) for k in keys]),
            KeyArr(*vals), LenArr(*[len(v) for v in vals]),
            start_ts, end_ts,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), cap,
        )
        if got < 0:
            return super().part_ids_from_filters(eq_filters, start_ts, end_ts)
        return np.sort(out[: min(got, cap)])
