"""Cross-cluster part-key index synchronization (reference L2:
memstore/synchronization/ PartKeyUpdatesPublisher — shards publish partkey
create/update events to an updates log which peer clusters (e.g. the
downsample cluster's index) consume to keep their indexes fresh without
full rebuilds).

The log here is any object with ``append(record)``; consumers poll
``PartKeyUpdatesConsumer.apply_to_index``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Mapping


@dataclass
class PartKeyUpdate:
    shard: int
    tags: Mapping[str, str]
    start_ts: int
    end_ts: int
    ts: float = field(default_factory=time.time)


class PartKeyUpdatesPublisher:
    """Attach to a shard: records partkey adds and end-time updates."""

    def __init__(self, shard_num: int, capacity: int = 100_000):
        self.shard_num = shard_num
        self.updates: list[PartKeyUpdate] = []
        self.capacity = capacity
        self.dropped = 0

    def publish(self, tags, start_ts, end_ts=2**62) -> None:
        if len(self.updates) >= self.capacity:
            self.dropped += 1
            return
        self.updates.append(PartKeyUpdate(self.shard_num, dict(tags), start_ts, end_ts))

    def drain(self) -> list[PartKeyUpdate]:
        out, self.updates = self.updates, []
        return out


class PartKeyUpdatesConsumer:
    """Applies drained updates to a peer index (reference DSIndexJob's
    incremental path)."""

    def __init__(self, index):
        self.index = index
        self._next_id = 10_000_000  # ids disjoint from locally-created parts

    def apply(self, updates) -> int:
        n = 0
        for u in updates:
            from ..core.schemas import canonical_partkey

            self.index.add_partkey(self._next_id, dict(u.tags), u.start_ts, u.end_ts)
            self._next_id += 1
            n += 1
        return n
