"""Cardinality tracking & quotas (reference L2 ratelimit/:
CardinalityTracker.scala:35 — a trie over shard-key prefixes counting active
and total time series, with per-prefix quotas enforced at partition
creation; RocksDbCardinalityStore persistence; CardinalityManager;
TenantIngestionMetering emits per-tenant metrics).

Host-side trie keyed by (_ws_, _ns_, _metric_) prefixes. The store here is
in-memory with JSON snapshot persistence (RocksDB analog).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Mapping, Sequence

from ..core.schemas import METRIC_TAG, SHARD_KEY_TAGS


class QuotaExceededError(Exception):
    def __init__(self, prefix, quota):
        super().__init__(f"cardinality quota {quota} exceeded at prefix {prefix}")
        self.prefix = prefix
        self.quota = quota


@dataclass
class CardinalityRecord:
    """Counts at one trie node (reference CardinalityRecord)."""

    prefix: tuple[str, ...]
    ts_count: int = 0  # total series ever
    active_ts_count: int = 0  # currently ingesting
    children: int = 0  # immediate child prefixes


class CardinalityTracker:
    """Trie of shard-key prefixes -> counts + quotas."""

    def __init__(self, shard_key_len: int = 3):
        self.shard_key_len = shard_key_len
        self._counts: dict[tuple[str, ...], CardinalityRecord] = {}
        self._child_names: dict[tuple[str, ...], set[str]] = {}
        self._quotas: dict[tuple[str, ...], int] = {}
        self.default_quota: int | None = None

    def _prefixes(self, tags: Mapping[str, str]):
        keys = [tags.get(k, "") for k in SHARD_KEY_TAGS[: self.shard_key_len]]
        for i in range(self.shard_key_len + 1):
            yield tuple(keys[:i])

    def set_quota(self, prefix: Sequence[str], quota: int) -> None:
        self._quotas[tuple(prefix)] = quota

    def quota_of(self, prefix: tuple[str, ...]) -> int | None:
        return self._quotas.get(prefix, self.default_quota if prefix else None)

    # -- updates ----------------------------------------------------------

    def series_created(self, tags: Mapping[str, str]) -> None:
        """Called at partition creation (reference modifyCount). Raises
        QuotaExceededError BEFORE counting when a prefix is at quota."""
        prefixes = list(self._prefixes(tags))
        for p in prefixes:
            q = self.quota_of(p)
            rec = self._counts.get(p)
            if q is not None and rec is not None and rec.ts_count >= q:
                raise QuotaExceededError(p, q)
        for i, p in enumerate(prefixes):
            rec = self._counts.get(p)
            if rec is None:
                rec = CardinalityRecord(p)
                self._counts[p] = rec
                if i > 0:
                    parent = prefixes[i - 1]
                    names = self._child_names.setdefault(parent, set())
                    if p[-1] not in names:
                        names.add(p[-1])
                        self._counts[parent].children += 1
            rec.ts_count += 1
            rec.active_ts_count += 1

    def series_stopped(self, tags: Mapping[str, str]) -> None:
        for p in self._prefixes(tags):
            rec = self._counts.get(p)
            if rec and rec.active_ts_count > 0:
                rec.active_ts_count -= 1

    def series_removed(self, tags: Mapping[str, str]) -> None:
        for p in self._prefixes(tags):
            rec = self._counts.get(p)
            if rec:
                rec.ts_count = max(rec.ts_count - 1, 0)
                rec.active_ts_count = max(rec.active_ts_count - 1, 0)

    # -- queries (reference TsCardinalities exec) -------------------------

    def scan(self, prefix: Sequence[str], depth: int) -> list[CardinalityRecord]:
        """All records at the given depth under prefix."""
        prefix = tuple(prefix)
        out = []
        for p, rec in self._counts.items():
            if len(p) == depth and p[: len(prefix)] == prefix:
                out.append(rec)
        out.sort(key=lambda r: -r.ts_count)
        return out

    def record_of(self, prefix: Sequence[str]) -> CardinalityRecord | None:
        return self._counts.get(tuple(prefix))

    # -- persistence (RocksDB store analog) -------------------------------

    def save(self, path: str) -> None:
        data = {
            "quotas": {"|".join(k): v for k, v in self._quotas.items()},
            "counts": [
                {"p": list(r.prefix), "t": r.ts_count, "a": r.active_ts_count, "c": r.children}
                for r in self._counts.values()
            ],
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str, shard_key_len: int = 3) -> "CardinalityTracker":
        t = cls(shard_key_len)
        if not os.path.exists(path):
            return t
        with open(path) as f:
            data = json.load(f)
        for k, v in data.get("quotas", {}).items():
            t._quotas[tuple(k.split("|")) if k else ()] = v
        for rec in data.get("counts", []):
            p = tuple(rec["p"])
            t._counts[p] = CardinalityRecord(p, rec["t"], rec["a"], rec["c"])
        return t


def label_top_values(index, label: str, k: int = 20) -> list[dict]:
    """Top-K values of one label by live-series count, straight off the
    part-key index's posting containers (container length is O(1) — no
    posting walk, no tag-map scan). Complements the shard-key trie above:
    the trie answers ws/ns/metric quotas, this answers "which VALUE of this
    label is exploding" for /debug/index?label= drill-downs."""
    counts = index.value_counts(label)
    top = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))[: int(k)]
    return [{"value": v, "series": n} for v, n in top]
