"""Memstore facade: per-dataset shard map (reference L2:
memstore/TimeSeriesMemStore.scala:26 — setup:85, ingest:148, startIngestion:154).

This is also the ChunkSource the query engine reads (reference
store/ChunkSource.scala:87,161): lookup + staging of series windows.
"""

from __future__ import annotations

from typing import Mapping, Sequence

import numpy as np

from ..core.filters import ColumnFilter
from ..core.records import RecordBatch
from ..core.schemas import Dataset
from .shard import StoreConfig, TimeSeriesShard


class TimeSeriesMemStore:
    def __init__(self, store_config: StoreConfig | None = None):
        self._datasets: dict[str, dict[int, TimeSeriesShard]] = {}
        self._dataset_meta: dict[str, Dataset] = {}
        self.store_config = store_config or StoreConfig()

    # -- lifecycle -----------------------------------------------------------

    def setup(self, dataset: Dataset, shard_nums: Sequence[int]) -> None:
        shards = self._datasets.setdefault(dataset.name, {})
        self._dataset_meta[dataset.name] = dataset
        for s in shard_nums:
            if s not in shards:
                shards[s] = TimeSeriesShard(dataset.name, s, self.store_config)

    def shard(self, dataset: str, shard_num: int) -> TimeSeriesShard:
        return self._datasets[dataset][shard_num]

    def shards(self, dataset: str) -> list[TimeSeriesShard]:
        return list(self._datasets.get(dataset, {}).values())

    def shard_nums(self, dataset: str) -> list[int]:
        return sorted(self._datasets.get(dataset, {}).keys())

    def dataset(self, name: str) -> Dataset:
        return self._dataset_meta[name]

    # -- ingest --------------------------------------------------------------

    def ingest(self, dataset: str, shard_num: int, batch: RecordBatch, offset: int = -1) -> int:
        return self.shard(dataset, shard_num).ingest(batch, offset)

    def ingest_routed(self, dataset: str, batch: RecordBatch, spread: int) -> int:
        """Route a mixed batch to owned shards by shard-key hash (gateway path)."""
        shards = self._datasets[dataset]
        n = 0
        for snum, sub in batch.shard_split(spread, max(shards) + 1).items():
            if snum in shards:
                n += shards[snum].ingest(sub)
        return n

    # -- query side ----------------------------------------------------------

    def lookup(
        self, dataset: str, filters: Sequence[ColumnFilter], start_ts: int, end_ts: int,
        shard_nums: Sequence[int] | None = None, limit: int | None = None,
    ) -> list[tuple[int, np.ndarray]]:
        """(shard_num, part_ids) per shard with matches."""
        out = []
        for s in shard_nums if shard_nums is not None else self.shard_nums(dataset):
            pids = self.shard(dataset, s).lookup_partitions(filters, start_ts, end_ts, limit)
            if len(pids):
                out.append((s, pids))
        return out

    def label_values(self, dataset, filters, label, start_ts, end_ts, limit=None) -> list[str]:
        vals: set[str] = set()
        for sh in self.shards(dataset):
            vals.update(sh.label_values(filters, label, start_ts, end_ts, limit))
        out = sorted(vals)
        return out[:limit] if limit else out

    def label_names(self, dataset, filters, start_ts, end_ts) -> list[str]:
        names: set[str] = set()
        for sh in self.shards(dataset):
            names.update(sh.label_names(filters, start_ts, end_ts))
        return sorted(names)

    def series(self, dataset, filters, start_ts, end_ts, limit=None) -> list[Mapping[str, str]]:
        out: list[Mapping[str, str]] = []
        for sh in self.shards(dataset):
            out.extend(sh.partkeys(filters, start_ts, end_ts, limit))
            if limit and len(out) >= limit:
                return out[:limit]
        return out
