"""Memstore facade: per-dataset shard map (reference L2:
memstore/TimeSeriesMemStore.scala:26 — setup:85, ingest:148, startIngestion:154).

This is also the ChunkSource the query engine reads (reference
store/ChunkSource.scala:87,161): lookup + staging of series windows.
"""

from __future__ import annotations

from typing import Mapping, Sequence

import numpy as np

from ..core.filters import ColumnFilter
from ..core.records import RecordBatch
from ..core.schemas import Dataset
from .shard import StoreConfig, TimeSeriesShard


class TimeSeriesMemStore:
    def __init__(self, store_config: StoreConfig | None = None):
        self._datasets: dict[str, dict[int, TimeSeriesShard]] = {}
        self._dataset_meta: dict[str, Dataset] = {}
        self._total_shards: dict[str, int] = {}
        self.store_config = store_config or StoreConfig()

    # -- lifecycle -----------------------------------------------------------

    def setup(self, dataset: Dataset, shard_nums: Sequence[int],
              total_shards: int | None = None) -> None:
        """``total_shards`` is the CLUSTER's shard count (the routing
        modulus); REQUIRED whenever ``shard_nums`` is a partial slice
        (multi-host), else inferred from the owned set."""
        shards = self._datasets.setdefault(dataset.name, {})
        self._dataset_meta[dataset.name] = dataset
        nums = list(shard_nums)
        self._total_shards[dataset.name] = max(
            total_shards or 0, (max(nums) + 1) if nums else 0,
            self._total_shards.get(dataset.name, 0),
        )
        for s in nums:
            if s not in shards:
                shards[s] = TimeSeriesShard(dataset.name, s, self.store_config)

    def total_shards(self, dataset: str) -> int:
        return self._total_shards[dataset]

    def shard(self, dataset: str, shard_num: int) -> TimeSeriesShard:
        return self._datasets[dataset][shard_num]

    def shards(self, dataset: str) -> list[TimeSeriesShard]:
        return list(self._datasets.get(dataset, {}).values())

    def shard_nums(self, dataset: str) -> list[int]:
        return sorted(self._datasets.get(dataset, {}).keys())

    def dataset(self, name: str) -> Dataset:
        return self._dataset_meta[name]

    # -- ingest --------------------------------------------------------------

    def ingest(self, dataset: str, shard_num: int, batch: RecordBatch, offset: int = -1) -> int:
        return self.shard(dataset, shard_num).ingest(batch, offset)

    def ingest_routed(self, dataset: str, batch: RecordBatch, spread: int) -> int:
        """Route a mixed batch to owned shards by shard-key hash (gateway path;
        the dataset's options pick the shard-key columns)."""
        shards = self._datasets[dataset]
        options = self._dataset_meta[dataset].options
        n = 0
        for snum, sub in batch.shard_split(
            spread, self.total_shards(dataset), options
        ).items():
            if snum in shards:
                n += shards[snum].ingest(sub)
        return n

    # -- query side ----------------------------------------------------------

    def lookup(
        self, dataset: str, filters: Sequence[ColumnFilter], start_ts: int, end_ts: int,
        shard_nums: Sequence[int] | None = None, limit: int | None = None,
    ) -> list[tuple[int, np.ndarray]]:
        """(shard_num, part_ids) per shard with matches."""
        out = []
        for s in shard_nums if shard_nums is not None else self.shard_nums(dataset):
            pids = self.shard(dataset, s).lookup_partitions(filters, start_ts, end_ts, limit)
            if len(pids):
                out.append((s, pids))
        return out

    def label_values(self, dataset, filters, label, start_ts, end_ts, limit=None) -> list[str]:
        vals: set[str] = set()
        for sh in self.shards(dataset):
            vals.update(sh.label_values(filters, label, start_ts, end_ts, limit))
        out = sorted(vals)
        return out[:limit] if limit else out

    def label_names(self, dataset, filters, start_ts, end_ts) -> list[str]:
        names: set[str] = set()
        for sh in self.shards(dataset):
            names.update(sh.label_names(filters, start_ts, end_ts))
        return sorted(names)

    def series(self, dataset, filters, start_ts, end_ts, limit=None) -> list[Mapping[str, str]]:
        out: list[Mapping[str, str]] = []
        for sh in self.shards(dataset):
            out.extend(sh.partkeys(filters, start_ts, end_ts, limit))
            if limit and len(out) >= limit:
                return out[:limit]
        return out

    def metric_metadata(self, dataset: str) -> dict[str, list[dict]]:
        """Prometheus /api/v1/metadata payload derived from the live schemas:
        one entry per metric with its type (counter/gauge/histogram) taken
        from the schema of a representative series (reference: the schemas
        registry drives PrometheusModel metadata)."""
        from ..core.filters import equals
        from ..core.schemas import METRIC_TAG

        out: dict[str, list[dict]] = {}
        for sh in self.shards(dataset):
            for metric in sh.label_values([], METRIC_TAG, 0, 2**62):
                if metric in out:
                    continue
                pids = sh.lookup_partitions([equals(METRIC_TAG, metric)], 0, 2**62, limit=1)
                if not len(pids):
                    continue
                schema = sh.partition(int(pids[0])).schema
                name = schema.name
                if "histogram" in name:
                    mtype = "histogram"
                elif "counter" in name:
                    mtype = "counter"
                elif name == "untyped":
                    mtype = "unknown"
                else:
                    mtype = "gauge"
                out[metric] = [{"type": mtype, "help": "", "unit": ""}]
        return dict(sorted(out.items()))

    # -- exemplars (OpenMetrics) ---------------------------------------------

    def add_exemplars(self, dataset: str, spread: int, items) -> int:
        """Attach exemplars to their series (items: (tags, ts_ms, value,
        exemplar_labels)). Series that don't exist yet are skipped — exemplars
        ride alongside samples, they never create series."""
        from ..core.schemas import canonical_partkey, shard_for

        shards = self._datasets[dataset]
        options = self._dataset_meta[dataset].options
        num_shards = self.total_shards(dataset)
        n = 0
        for tags, ts_ms, value, ex_labels in items:
            snum = shard_for(tags, spread, num_shards, options)
            sh = shards.get(snum)
            if sh is None:
                continue
            if sh.add_exemplar(canonical_partkey(tags), ts_ms, value, ex_labels):
                n += 1
        return n

    def query_exemplars(self, dataset, filters, start_ms: int, end_ms: int) -> list[dict]:
        """Prometheus /api/v1/query_exemplars shape: per matching series, the
        exemplars within [start, end]."""
        out = []
        for sh in self.shards(dataset):
            for pid in sh.lookup_partitions(filters, start_ms, end_ms):
                part = sh.partition(int(pid))
                exs = [
                    {
                        "labels": lbls,
                        "value": f"{val:g}",
                        "timestamp": ts / 1000.0,
                    }
                    for ts, val, lbls in part.exemplars
                    if start_ms <= ts <= end_ms
                ]
                if exs:
                    out.append({"seriesLabels": dict(part.tags), "exemplars": exs})
        return out
