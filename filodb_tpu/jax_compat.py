"""Version shims for the pinned jax.

``jax.shard_map`` became a top-level API (with ``check_vma``) only in newer
jax; the image's jax still ships it as ``jax.experimental.shard_map`` with
the older ``check_rep`` spelling. Every shard_map call site in the tree goes
through this one wrapper so the mesh execution paths (parallel/, ops/sketch,
downsample) run on either version.
"""

from __future__ import annotations

import jax

try:
    _shard_map = jax.shard_map
    _CHECK_KW = "check_vma"
except AttributeError:  # older jax: experimental namespace + check_rep
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check: bool = False):
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_CHECK_KW: check},
    )
