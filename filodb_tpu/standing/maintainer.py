"""Standing-query maintainer: delta-refreshed dashboards + recording rules.

The engine between the dispatch scheduler and the fused engine (ROADMAP
"standing-query engine: delta-maintained dashboards at fan-out scale").
:class:`StandingEngine` owns:

- **promotion** — a promoter scans the dispatch scheduler's retained
  per-key recurrence ring (:class:`~filodb_tpu.query.scheduler.KeyStatsRing`)
  and promotes hot live-edge keys into registered standing queries, with
  hysteresis: promotion needs a BURST (``promote_min_count`` recurrences
  inside ``promote_window_s``), demotion needs a long idle
  (``demote_idle_s``) with zero subscribers — the two thresholds never
  chase each other. Nondecomposable epilogues are remembered as demoted so
  the promoter never flaps on them.

- **delta maintenance** — each registered query keeps its ``[G, J]``
  aggregation partials warm. A refresh classifies what ingest did since the
  partials were computed via the shard effect log
  (``ingest_effects_interval_since``): disjoint → serve retained with ZERO
  dispatches; a live-edge append → re-dispatch ONLY the step suffix whose
  windows reach the appended interval, through the same fused program over
  the same superblock (which extends in place under the append — PR 6),
  and splice (``ops/aggregations.splice_partials``). The delta path is
  bit-equal to full re-evaluation (the per-step independence argument in
  ops/aggregations.py, pinned by tests/test_standing.py across
  regular/jitter/holes grids and under concurrent extension). Epilogues
  that cannot splice per step (topk, quantile, fused histogram_quantile)
  demote to full re-dispatch, counted
  ``filodb_fused_fallback_total{reason="standing_nondecomposable"}``.

- **push fan-out** — every refresh renders its payload ONCE and the
  :class:`~filodb_tpu.standing.hub.SubscriptionHub` fans the same bytes to
  every SSE subscriber (api/http.py ``/api/v1/standing/subscribe``).

- **recording rules** — a standing query with a ``rule_name`` writes its
  newest closed steps back into the memstore as a real series
  (``rule_name{group labels}``), evaluated on ``eval_interval_s`` ticks —
  the recording-rules engine the ROADMAP said falls out for free.

- **alerting rules** — a standing query with an ``alert_sink`` feeds the
  newest closed step's per-group column to the alerting state machine
  (obs/alerting.py) after every refresh: the alert condition is evaluated
  from the partials the maintainer already keeps, never a separate
  dispatch plane.

Refreshes bypass admission control (they are the system's own standing
obligation, not ad-hoc tenant load) but their resources ARE attributed: the
owning tenant (resolved from the query's selector filters at registration)
is charged wall/kernel/staged-bytes through the same
``filodb_tenant_*_total`` counters ad-hoc queries pay into, and retained
partials are a first-class ledger kind
(``filodb_device_bytes{kind="standing_state"}``).
"""

from __future__ import annotations

import json
import logging
import math
import threading
import time

import numpy as np

from ..metrics import REGISTRY, record_fused_fallback
from .hub import SubscriptionHub
from .registry import StandingQuery, StandingRegistry, _new_qid

log = logging.getLogger("filodb_tpu.standing")

DEFAULTS = {
    "enabled": True,
    "promote_min_count": 8,
    "promote_window_s": 120.0,
    "promote_live_lag_ms": 120_000,
    "demote_idle_s": 600.0,
    "demote_retry_s": 3600.0,
    "max_standing": 64,
    "max_subscribers": 64,
    "refresh_debounce_ms": 250,
    "key_ring_max": 512,
    "default_span_ms": 1_800_000,
    "align_ms": 300_000,
    "tick_s": 0.5,
    # serve ordinary query_range calls matching a registered standing
    # query straight from its retained matrix (path=standing:serve)
    "serve_range": True,
}


class StandingEngine:
    """Registry + maintainer + promoter + hub, bound to one QueryEngine."""

    def __init__(self, engine, config: dict | None = None, hub=None,
                 clock=time.time):
        cfg = {**DEFAULTS, **(config or {})}
        self.cfg = cfg
        self.engine = engine
        self.dataset = engine.dataset
        self.clock = clock
        params = engine.planner.params
        sched = getattr(params, "dispatch_scheduler", None)
        if sched is None:
            # batching may be off (window 0) — the scheduler still exists
            # so the recurrence ring observes every fused dispatch
            from ..query.scheduler import DispatchScheduler

            sched = DispatchScheduler(
                params.batch_window_ms, params.batch_max,
                key_ring_max=int(cfg["key_ring_max"]),
            )
            params.dispatch_scheduler = sched
        self.scheduler = sched
        self.registry = StandingRegistry(int(cfg["max_standing"]))
        self.hub = hub or SubscriptionHub(int(cfg["max_subscribers"]))
        self.align_ms = int(cfg["align_ms"])
        self.debounce_s = float(cfg["refresh_debounce_ms"]) / 1e3
        # qid -> {(cache, sb_key)} pinned against eviction for that
        # standing query; reconciled after each dispatch so a rolled
        # aligned range does not leave its predecessor pinned forever
        self._sb_pins: dict[str, set] = {}
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None
        self._listening: list = []  # (shard, cb) pairs for teardown

    # -- registration ------------------------------------------------------

    def register(self, promql: str, step_ms: int, span_ms: int | None = None,
                 source: str = "manual", key=None, rule_name: str | None = None,
                 eval_interval_s: float | None = None,
                 alert_sink=None) -> StandingQuery:
        """Register one standing query. Probes the planned exec to decide
        the maintenance mode: ``delta`` (fused aggregate with a spliceable
        epilogue) or ``full`` (nondecomposable epilogue or a plan shape the
        fused engine doesn't serve — every refresh re-dispatches, counted
        in the fallback taxonomy). Raises on unparseable PromQL or a full
        registry."""
        from ..ops import aggregations as AGG
        from ..query.exec.plans import FusedAggregateExec

        step_ms = max(int(step_ms), 1)
        span_ms = int(span_ms if span_ms else self.cfg["default_span_ms"])
        span_ms = max(span_ms - span_ms % step_ms, step_ms)
        now_ms = int(self.clock() * 1000)
        end = now_ms - now_ms % step_ms
        ex, _plan, tenant = self._materialize(
            promql, end - span_ms, end, step_ms
        )
        mode, mode_reason = "full", "not_fused"
        window_ms = offset_ms = 0
        if isinstance(ex, FusedAggregateExec):
            window_ms, offset_ms = ex.window_ms, ex.offset_ms
            if AGG.standing_delta_eligible(ex.op, ex.params,
                                           ex.hist_quantile):
                mode, mode_reason = "delta", None
            else:
                mode_reason = "standing_nondecomposable"
        sq = StandingQuery(
            qid=_new_qid(), promql=promql, dataset=self.dataset,
            step_ms=step_ms, span_ms=span_ms, source=source, key=key,
            mode=mode, mode_reason=mode_reason, ws=tenant[0], ns=tenant[1],
            rule_name=rule_name, eval_interval_s=eval_interval_s,
            alert_sink=alert_sink,
            window_ms=window_ms, offset_ms=offset_ms,
        )
        self.registry.add(sq)
        if key is not None:
            self.registry.forget_demoted(key)
        REGISTRY.counter("filodb_standing_promotions",
                         event="promote" if source == "promoted"
                         else "register").inc()
        self._wake.set()
        return sq

    def unregister(self, qid: str, reason: str = "unregistered"):
        sq = self.registry.remove(qid)
        if sq is None:
            return None
        self._sb_pins.pop(qid, None)
        cache = getattr(self.engine.memstore, "_superblock_cache", None)
        if cache is not None:
            cache.unpin_owner(qid)  # release superblock eviction pins
        self.hub.close(qid)
        if sq.source == "promoted":
            self.registry.note_demoted(sq.key, reason)
        REGISTRY.counter("filodb_standing_promotions", event="demote").inc()
        return sq

    def get(self, qid: str) -> StandingQuery | None:
        return self.registry.get(qid)

    # -- refresh (the delta path) ------------------------------------------

    def _materialize(self, promql: str, start_ms: int, end_ms: int,
                     step_ms: int):
        """(exec plan, logical plan, (ws, ns)) for one evaluation grid."""
        from ..metering import tenant_of_plan
        from ..query.promql import query_range_to_logical_plan

        plan = query_range_to_logical_plan(
            promql, start_ms / 1000.0, end_ms / 1000.0, step_ms / 1000.0,
            self.engine.planner.params.lookback_ms,
        )
        return self.engine.planner.materialize(plan), plan, tenant_of_plan(plan)

    def _pin_raw_range(self, ex, aligned: tuple) -> None:
        """Pin a fused exec's staging range to the standing query's aligned
        (lo, hi): every refresh of every grid position then resolves to ONE
        superblock cache entry — the warm entry live-edge appends EXTEND in
        place — instead of staging a fresh near-identical superblock per
        refresh. Staging a superset is safe (result windows derive from
        query params, planner._fused_raw_range's argument)."""
        ex.raw_start_ms, ex.raw_end_ms = aligned

    def _aligned_raw(self, ex) -> tuple:
        """Quantized staging range: lo floors to the alignment; hi floors
        then adds TWO alignment periods — at least one full period of
        live-edge headroom, so the pinned range (and with it the
        superblock cache key and the retained partials) stays stable while
        the grid end advances within one alignment bucket. The range rolls
        — and the standing state resets — once per ``align_ms`` of wall
        time; every refresh in between is delta or retained."""
        a = self.align_ms
        return (ex.raw_start_ms - ex.raw_start_ms % a,
                ex.raw_end_ms - ex.raw_end_ms % a + 2 * a)

    def _execute(self, ex, owner: str | None = None):
        """Run one (suffix or full) dispatch on the engine's context —
        admission is bypassed (standing work is the server's own standing
        obligation), attribution is not (caller meters the tenant)."""
        from ..obs.querylog import PhaseRecorder

        ctx = self.engine.context()
        ctx.standing_refresh = True  # keep maintainer dispatches out of the ring
        pinned: list = []
        if owner is not None:
            # pin whatever superblock key(s) the dispatch resolves to
            # against ad-hoc eviction; stale pins (rolled aligned range)
            # are released below, the rest on unregister
            def _pin(cache, key, _o=owner, _l=pinned):
                cache.pin(key, _o)
                _l.append((cache, key))

            ctx.superblock_pin_sink = _pin
        # phase capture for the refresh's querylog record: the maintainer
        # calls the exec tree outside the HTTP/engine entry points, so it
        # attaches the recorder itself (stage/dispatch decompose as usual)
        ctx.phases = PhaseRecorder()
        res = ex.execute(ctx)
        if owner is not None and pinned:
            # reconcile: new pins are already held, so dropping the ones
            # this dispatch did NOT touch never leaves a gap
            new = set(pinned)
            for cache, key in self._sb_pins.get(owner, set()) - new:
                cache.unpin(key, owner)
            self._sb_pins[owner] = new
        return ctx, res

    def refresh(self, sq: StandingQuery, now_ms: int | None = None,
                force_full: bool = False) -> bytes | None:
        """One refresh: classify ingest since the retained partials were
        computed, re-dispatch the minimal step suffix (or nothing), splice,
        render once, fan out, write back rule series. Returns the rendered
        payload (None when the refresh errored)."""
        t0 = time.perf_counter()
        if now_ms is None:
            now_ms = int(self.clock() * 1000)
        with sq.lock:
            if sq.removed:
                # unregister won the race: its ledger credit is final —
                # touching state here would re-grow what was freed
                return None
            try:
                payload, outcome, ctx, evalv = self._refresh_locked(
                    sq, now_ms, force_full
                )
            except Exception as e:  # noqa: BLE001 — maintenance must not die
                sq.stats["errors"] += 1
                sq.last_error = f"{type(e).__name__}: {e}"
                REGISTRY.counter("filodb_standing_refreshes",
                                 outcome="error").inc()
                if sq.alert_sink is not None:
                    # the alert rule's condition could not be evaluated
                    # this interval — surfaced in the alerting health
                    # family, not just the standing one
                    REGISTRY.counter(
                        "filodb_alert_eval_failures",
                        rule=getattr(sq.alert_sink, "rule", "unknown"),
                    ).inc()
                log.exception("standing refresh failed: %s", sq.promql)
                self._observe_querylog(sq, "error", None,
                                       time.perf_counter() - t0,
                                       status="error",
                                       error=f"{type(e).__name__}: {e}")
                return None
            sq.last_error = None
        elapsed = time.perf_counter() - t0
        sq.last_eval_duration_s = elapsed
        REGISTRY.counter("filodb_standing_refreshes", outcome=outcome).inc()
        REGISTRY.histogram("filodb_standing_refresh_seconds").observe(elapsed)
        if ctx is not None:
            from ..metering import record_tenant_query

            record_tenant_query(
                sq.ws, sq.ns, elapsed, ctx.stats.kernel_ns / 1e9,
                ctx.stats.bytes_staged,
            )
        # query-observatory record (obs/querylog.py): refreshes used to
        # bypass the querylog entirely (the maintainer calls the exec tree
        # outside the engine's HTTP entry points), leaving the busiest
        # recurring work invisible to the observatory — every refresh now
        # publishes a cost record under path standing:delta|standing:full
        self._observe_querylog(sq, outcome, ctx, elapsed)
        if payload is not None:
            self.hub.publish(sq.qid, payload)
        if sq.alert_sink is not None and evalv is not None:
            # feed the newest closed step to the alerting state machine —
            # OUTSIDE sq.lock (the sink writes ALERTS back through the
            # ingest path, which pokes the append listeners)
            try:
                sq.alert_sink(sq, evalv[0], evalv[1])
            except Exception:  # noqa: BLE001 — alerting must not kill refresh
                log.exception("alert sink failed: %s", sq.promql)
        return payload

    def _observe_querylog(self, sq: StandingQuery, outcome: str, ctx,
                          elapsed_s: float, status: str = "ok",
                          error: str | None = None) -> None:
        """One exemplar-level cost record per refresh. Path vocabulary:
        ``standing:delta`` covers the delta-maintained dispositions
        (suffix-only re-dispatch AND the zero-dispatch retained serve),
        ``standing:full`` the full re-evaluations (nondecomposable/unfused
        demotions and grid resets); an ERRORED refresh is labeled by the
        query's registered maintenance mode — the plane that was being
        attempted — so delta-path failures never masquerade as full
        refreshes in path-filtered dashboards (status=error tells the
        rest). The record carries the same executable_key/compile_miss
        join the ad-hoc path gets — the fused suffix dispatch stamped
        them on the context's obs annotations."""
        from ..obs.querylog import QUERY_LOG, PhaseRecorder

        phases = getattr(ctx, "phases", None) if ctx is not None else None
        if phases is None:
            phases = PhaseRecorder()
        info = dict(getattr(ctx, "obs", None) or {}) if ctx is not None else {}
        if status == "error":
            delta = sq.mode == "delta"
        else:
            delta = outcome in ("delta", "retained")
        info["path"] = "standing:delta" if delta else "standing:full"
        retained = sq.retained
        result_series = int(retained.shape[0]) if retained is not None else 0
        result_samples = int(retained.size) if retained is not None else 0
        # unique per refresh (sq.seq does not advance on retained serves):
        # the ring's id index must never alias two refreshes' records
        serial = int(sq.stats.get("refreshes", 0)) + int(
            sq.stats.get("errors", 0)
        )
        QUERY_LOG.publish(
            query_id=f"{sq.qid}:{serial}", dataset=sq.dataset,
            promql=sq.promql, ws=sq.ws, ns=sq.ns, step_ms=sq.step_ms,
            span_ms=sq.span_ms, start_s=sq.grid_start_ms / 1000.0,
            end_s=sq.grid_end_ms / 1000.0, phases=phases,
            elapsed_s=elapsed_s,
            stats=ctx.stats if ctx is not None else None,
            path_info=info, result_series=result_series,
            result_samples=result_samples, status=status, error=error,
        )

    def _refresh_locked(self, sq: StandingQuery, now_ms: int,
                        force_full: bool):
        from ..ops import aggregations as AGG
        from ..query.exec.plans import FusedAggregateExec

        step = sq.step_ms
        end = now_ms - now_ms % step
        start = end - sq.span_ms
        J = (end - start) // step + 1
        if sq.mode != "delta":
            return self._refresh_full(sq, start, end, J)
        ex, _plan, _tenant = self._materialize(sq.promql, start, end, step)
        if not isinstance(ex, FusedAggregateExec):
            # the plan stopped being fusable (e.g. config flipped
            # fused_aggregate off): demote this query to full mode and
            # release its delta state — full refreshes never read it, and
            # a dead [G, J] array must not stay resident and
            # ledger-counted for the query's lifetime
            sq.mode, sq.mode_reason = "full", "not_fused"
            self._drop_state(sq)
            return self._refresh_full(sq, start, end, J)
        aligned = self._aligned_raw(ex)
        self._pin_raw_range(ex, aligned)
        shard_nums = tuple(ex.shard_nums)
        memstore = self.engine.memstore
        # versions read BEFORE the dispatch: anything landing mid-dispatch
        # classifies as dirty next refresh — conservative, never stale
        versions_now = tuple(
            memstore.shard(sq.dataset, s).version for s in shard_nums
        )
        reset = (force_full or sq.retained is None or sq.versions is None
                 or sq.raw_range != aligned or sq.shard_nums != shard_nums
                 or len(sq.versions) != len(shard_nums))
        dirty_lo = None
        if not reset:
            for s, vold in zip(shard_nums, sq.versions):
                reason, lo, _hi = memstore.shard(
                    sq.dataset, s
                ).ingest_effects_interval_since(vold, aligned[0], aligned[1])
                if reason in ("full_clear", "log_truncated"):
                    reset = True
                    break
                if reason == "overlap":
                    dirty_lo = lo if dirty_lo is None else min(dirty_lo, lo)
        retained = None
        if not reset:
            shift = (start - sq.grid_start_ms) // step
            if shift < 0:
                reset = True  # clock moved backwards: state is ahead of now
            else:
                retained = AGG.shift_partials(sq.retained, int(shift), J)
                # first NEW step (beyond the old grid end)
                k_new = (sq.grid_end_ms - start) // step + 1
                k_new = min(max(int(k_new), 0), J)
                # first step whose window can contain the appended samples:
                # window j = (out_t - offset - window, out_t - offset], so
                # the append interval [dirty_lo, ...] reaches every step
                # with out_t >= dirty_lo + offset
                if dirty_lo is None:
                    k_dirty = J
                else:
                    k_dirty = math.ceil(
                        (dirty_lo + sq.offset_ms - start) / step
                    )
                    k_dirty = min(max(int(k_dirty), 0), J)
                k0 = min(k_new, k_dirty)
        if reset:
            k0 = 0
            retained = None
        ctx = None
        if k0 >= J and retained is not None:
            # fully warm: the appended data (if any) was provably disjoint
            # from every window AND the grid did not advance (k_new >= J),
            # so the content is byte-identical to the last refresh — ZERO
            # dispatches, and no render/publish either: re-pushing an
            # identical frame on every disjoint-ingest wake would make
            # JSON encode the dominant standing-engine cost. Only the
            # version vector commits (so the same effects aren't
            # re-classified next time).
            sq.versions = versions_now
            sq.stats["refreshes"] += 1
            sq.stats["retained"] += 1
            sq.stats["steps_retained"] += J
            REGISTRY.counter("filodb_standing_steps", kind="retained").inc(J)
            sq.last_refresh_s = self.clock()
            evalv = None
            if sq.alert_sink is not None:
                # the condition still gets its evaluation tick even when
                # zero dispatches ran — absence must resolve alerts
                evalv = self._eval_col(sq.retained, sq.labels,
                                       sq.grid_end_ms)
            return None, "retained", None, evalv
        else:
            if k0 > 0:
                # the delta dispatch: ONLY the touched suffix re-computes,
                # through the same fused program over the same superblock
                ex_d, _p, _t = self._materialize(
                    sq.promql, start + k0 * step, end, step
                )
                if isinstance(ex_d, FusedAggregateExec):
                    self._pin_raw_range(ex_d, aligned)
                else:  # plan shape changed underfoot — recompute fully
                    ex_d, k0 = ex, 0
            else:
                ex_d = ex
            ctx, res = self._execute(ex_d, owner=sq.qid)
            fresh, fresh_labels = self._grid_arrays(res, J - k0)
            if k0 > 0 and sq.labels != fresh_labels:
                # the group set changed (restage with new/removed series
                # raced the classification): the spliced halves would
                # disagree on the group axis — redo the whole grid. The
                # discarded suffix dispatch's resources still attribute:
                # its stats merge into the context the caller meters.
                prev = ctx
                ctx, res = self._execute(ex, owner=sq.qid)
                ctx.stats.merge(prev.stats)
                fresh, fresh_labels = self._grid_arrays(res, J)
                k0 = 0
                retained = None
            if k0 > 0:
                retained = AGG.splice_partials(retained, fresh, k0)
                labels = sq.labels
                outcome = "delta"
                sq.stats["delta"] += 1
            else:
                retained = fresh
                labels = fresh_labels
                outcome = "reset" if reset else "full"
                sq.stats["reset" if reset else "full"] += 1
            sq.stats["steps_computed"] += J - k0
            sq.stats["steps_retained"] += k0
            REGISTRY.counter("filodb_standing_steps",
                             kind="computed").inc(J - k0)
            if k0:
                REGISTRY.counter("filodb_standing_steps",
                                 kind="retained").inc(k0)
        old_nb = sq.state_nbytes()
        sq.retained = retained
        sq.labels = labels
        sq.grid_start_ms, sq.grid_end_ms = start, end
        sq.raw_range = aligned
        sq.versions = versions_now
        sq.shard_nums = shard_nums
        sq.seq += 1
        sq.stats["refreshes"] += 1
        sq.last_refresh_s = self.clock()
        self.registry.account_state(old_nb, sq.state_nbytes())
        payload = self._render(sq, start, end, J, retained, labels or [])
        if sq.rule_name:
            self._write_rule(sq, start, end, J, retained, labels or [])
        evalv = None
        if sq.alert_sink is not None:
            evalv = self._eval_col(retained, labels, end)
        return payload, outcome, ctx, evalv

    def _drop_state(self, sq: StandingQuery) -> None:
        """Release a query's retained delta state (caller holds sq.lock):
        credit the ledger and clear the arrays + coverage markers."""
        nb = sq.state_nbytes()
        if nb:
            self.registry.account_state(nb, 0)
        sq.retained = None
        sq.labels = None
        sq.versions = None
        sq.raw_range = None

    def _refresh_full(self, sq: StandingQuery, start: int, end: int, J: int):
        """Full re-dispatch refresh for nondecomposable/unfusable standing
        queries — the clean demotion path: the query stays registered and
        served by push, it just pays the whole grid each refresh (counted
        in the fused-fallback taxonomy when the epilogue is why)."""
        if sq.mode_reason == "standing_nondecomposable":
            record_fused_fallback("standing_nondecomposable")
        ex, _plan, _tenant = self._materialize(sq.promql, start, end,
                                               sq.step_ms)
        ctx, res = self._execute(ex, owner=sq.qid)
        from ..api import promjson as PJ

        data = PJ.render_matrix(res)
        sq.grid_start_ms, sq.grid_end_ms = start, end
        sq.seq += 1
        sq.stats["refreshes"] += 1
        sq.stats["full"] += 1
        sq.stats["steps_computed"] += J
        REGISTRY.counter("filodb_standing_steps", kind="computed").inc(J)
        sq.stats["renders"] += 1
        sq.last_refresh_s = self.clock()
        payload = json.dumps({
            "id": sq.qid, "seq": sq.seq, "dataset": sq.dataset, **data,
        }).encode()
        sq.last_payload = payload
        if sq.rule_name and res.grids:
            g = res.grids[0]
            self._write_rule(
                sq, start, end, J,
                np.asarray(g.values_np(), dtype=np.float32), list(g.labels),
            )
        evalv = None
        if sq.alert_sink is not None:
            vals, labels = self._grid_arrays(res, J)
            evalv = self._eval_col(vals, labels, end)
        return payload, "full", ctx, evalv

    @staticmethod
    def _eval_col(vals, labels, end_ms: int):
        """``(end_ms, [(labels, value), ...])`` for the newest closed step
        — the alert sink's input. NaN entries are absent series (a
        comparison filtered them out, or the window is empty): absence is
        what RESOLVES an alert, so they are dropped, not forwarded."""
        vec = []
        if vals is not None and vals.size and labels:
            col = vals[:, -1]
            for gi, lbl in enumerate(labels):
                v = float(col[gi])
                if not math.isnan(v):
                    vec.append((dict(lbl), v))
        return (int(end_ms), vec)

    @staticmethod
    def _grid_arrays(res, num_steps: int):
        """([G, num_steps] float32 copy, [G] labels) from a QueryResult —
        an empty selection is a zero-group grid, not an error."""
        if not res.grids:
            return np.zeros((0, num_steps), np.float32), []
        g = res.grids[0]
        vals = np.array(g.values_np(), dtype=np.float32, copy=True)
        if vals.shape[1] < num_steps:  # defensive: never under-fill
            pad = np.full((vals.shape[0], num_steps - vals.shape[1]),
                          np.nan, np.float32)
            vals = np.concatenate([vals, pad], axis=1)
        return vals[:, :num_steps], list(g.labels)

    def _render(self, sq: StandingQuery, start: int, end: int, J: int,
                retained, labels) -> bytes:
        """ONE materialization per refresh: the payload every subscriber
        receives (and the SSE initial frame) is rendered exactly once."""
        from ..api import promjson as PJ
        from ..query.rangevector import Grid, QueryResult

        vals = retained if retained is not None else np.zeros(
            (0, J), np.float32
        )
        res = QueryResult(grids=[Grid(list(labels), start, sq.step_ms, J,
                                      vals)])
        data = PJ.render_matrix(res)
        payload = json.dumps({
            "id": sq.qid, "seq": sq.seq, "dataset": sq.dataset, **data,
        }).encode()
        sq.last_payload = payload
        sq.stats["renders"] += 1
        return payload

    def _write_rule(self, sq: StandingQuery, start: int, end: int, J: int,
                    vals, labels) -> None:
        """Recording-rule write-back: the newest CLOSED steps (those not
        yet written) land as real samples of ``rule_name{group labels}``
        through the production ingest path — the rule's output is then
        queryable, flushable and downsample-able like any series."""
        from ..core.records import gauge_batch
        from ..core.schemas import METRIC_TAG

        first = max(sq.last_rule_write_ms + sq.step_ms, start)
        if sq.last_rule_write_ms <= 0:
            first = end  # first eval writes the newest step, no backfill
        if first > end or vals is None or not len(labels):
            sq.last_rule_write_ms = max(sq.last_rule_write_ms, end)
            return
        recs = []
        for j in range((first - start) // sq.step_ms, J):
            t = start + j * sq.step_ms
            col = vals[:, j]
            for gi, lbl in enumerate(labels):
                v = float(col[gi])
                if not math.isnan(v):
                    tags = {k: v2 for k, v2 in dict(lbl).items()
                            if k not in (METRIC_TAG, "__name__")}
                    recs.append((tags, int(t), v))
        if recs:
            try:
                n = self.engine.memstore.ingest_routed(
                    sq.dataset, gauge_batch(sq.rule_name, recs),
                    spread=self.engine.planner.params.spread,
                )
                REGISTRY.counter("filodb_standing_rule_samples").inc(n)
            except Exception:  # noqa: BLE001 — quota/cardinality shed
                log.exception("recording-rule write-back failed: %s",
                              sq.rule_name)
        sq.last_rule_write_ms = end

    def current_payload(self, qid: str) -> bytes | None:
        sq = self.registry.get(qid)
        return sq.last_payload if sq is not None else None

    # -- edge serving (ordinary query_range from retained state) -----------

    def serve_range(self, promql: str, start_s: float, end_s: float,
                    step_s: float):
        """Answer an ordinary ``query_range`` from a registered standing
        query's retained matrix — the ROADMAP leftover: only SSE
        subscribers rode standing state before. Returns a QueryResult
        (querylog record attached under path ``standing:serve``) when a
        delta-maintained query matches promql + step and its retained grid
        covers the requested range phase-aligned; None otherwise (the
        caller falls through to the engine). A grid that has fallen behind
        the requested end refreshes first — the delta path makes that a
        suffix-only (often zero-dispatch) catch-up."""
        if not self.cfg.get("serve_range", True):
            return None
        t0 = time.perf_counter()
        step_ms = max(int(round(step_s * 1000)), 1)
        start_ms = int(round(start_s * 1000))
        end_ms = int(round(end_s * 1000))
        if start_ms % step_ms or (end_ms - start_ms) % step_ms:
            return None  # phase-misaligned with the standing grid
        sq = None
        for cand in self.registry.list():
            if (cand.promql == promql and cand.step_ms == step_ms
                    and cand.mode == "delta"):
                sq = cand
                break
        if sq is None:
            return None
        if sq.retained is None or end_ms > sq.grid_end_ms:
            self.refresh(sq)  # catch the grid up to now before slicing
        from ..query.rangevector import Grid, QueryResult

        with sq.lock:
            if (sq.removed or sq.retained is None or sq.labels is None
                    or start_ms < sq.grid_start_ms
                    or end_ms > sq.grid_end_ms
                    or (start_ms - sq.grid_start_ms) % step_ms):
                return None
            j0 = (start_ms - sq.grid_start_ms) // step_ms
            j1 = (end_ms - sq.grid_start_ms) // step_ms
            vals = np.array(sq.retained[:, j0:j1 + 1], copy=True)
            labels = [dict(lbl) for lbl in sq.labels]
        J = j1 - j0 + 1
        res = QueryResult(grids=[Grid(labels, start_ms, step_ms, J, vals)])
        sq.stats["serves"] = sq.stats.get("serves", 0) + 1
        from ..obs.querylog import QUERY_LOG, PhaseRecorder

        res.query_log = QUERY_LOG.publish(
            query_id=_new_qid(), dataset=sq.dataset, promql=promql,
            ws=sq.ws, ns=sq.ns, step_ms=step_ms,
            span_ms=end_ms - start_ms, start_s=start_ms / 1000.0,
            end_s=end_ms / 1000.0, phases=PhaseRecorder(),
            elapsed_s=time.perf_counter() - t0,
            path_info={"path": "standing:serve"},
            result_series=len(labels), result_samples=int(vals.size),
        )
        return res

    # -- promotion / demotion ----------------------------------------------

    def promote_tick(self, now_s: float | None = None) -> int:
        """Scan the recurrence ring; promote keys that burst. Returns the
        number promoted (the unit tests drive this directly)."""
        from ..ops import aggregations as AGG

        if now_s is None:
            now_s = self.clock()
        cfg = self.cfg
        n_min = int(cfg["promote_min_count"])
        promoted = 0
        for key, e in self.scheduler.key_ring.entries():
            desc = e.get("desc") or {}
            promql = desc.get("promql")
            if not promql or desc.get("dataset") != self.dataset:
                continue
            if self.registry.by_key(key) is not None:
                continue
            reason = self.registry.demoted_reason(key)
            if reason == "standing_nondecomposable":
                continue  # sticky: the epilogue will never decompose
            if reason is not None:
                at = self.registry.demoted.get(key, {}).get("at_s", 0)
                if now_s - at < float(cfg["demote_retry_s"]):
                    continue
                self.registry.forget_demoted(key)
            recent = list(e["recent"])
            if len(recent) < n_min:
                continue
            if recent[-1] - recent[-n_min] > float(cfg["promote_window_s"]):
                continue
            if abs(desc.get("end_lag_ms", 1e18)) > float(
                cfg["promote_live_lag_ms"]
            ):
                continue  # historical scan, not a live-edge dashboard
            if not AGG.standing_delta_eligible(
                desc.get("op", ""), desc.get("params", ()),
                desc.get("hist_quantile"),
            ):
                # remember, count, never flap
                self.registry.note_demoted(key, "standing_nondecomposable")
                record_fused_fallback("standing_nondecomposable")
                REGISTRY.counter("filodb_standing_promotions",
                                 event="demote").inc()
                continue
            if len(self.registry.list()) >= self.registry.max_standing:
                # transient capacity, not a property of the KEY: don't
                # remember it as demoted (that would block this hot key
                # for demote_retry_s after slots free) — just retry on a
                # later tick
                log.warning("standing registry full; promotion of %s "
                            "deferred", promql)
                continue
            try:
                self.register(
                    promql, desc["step_ms"],
                    span_ms=desc.get("span_ms"), source="promoted", key=key,
                )
                promoted += 1
            except Exception as exc:  # noqa: BLE001 — unparseable/invalid
                log.warning("standing promotion failed for %s: %s",
                            promql, exc)
                self.registry.note_demoted(key, "error")
        return promoted

    def demote_tick(self, now_s: float | None = None) -> int:
        """Demote auto-promoted queries whose recurrence went quiet AND
        that nobody subscribes to (hysteresis: the idle bound is far above
        the promotion window, so promote/demote can never oscillate)."""
        if now_s is None:
            now_s = self.clock()
        idle_s = float(self.cfg["demote_idle_s"])
        demoted = 0
        for sq in self.registry.list():
            if sq.source != "promoted":
                continue
            e = self.scheduler.key_ring.get(sq.key)
            last = e["last_s"] if e is not None else sq.created_s
            if now_s - max(last, sq.created_s) <= idle_s:
                continue
            if self.hub.count(sq.qid) > 0:
                continue
            self.unregister(sq.qid, reason="idle")
            demoted += 1
        return demoted

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        # append notifications: wake signals only — correctness derives
        # from the effect log at refresh time
        for sh in self.engine.memstore.shards(self.dataset):
            cb = self._on_append
            sh.add_append_listener(cb)
            self._listening.append((sh, cb))
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="filodb-standing"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        for sh, cb in self._listening:
            sh.remove_append_listener(cb)
        self._listening.clear()
        if self._thread is not None:
            self._thread.join(timeout=2)
        for sq in self.registry.list():
            self.hub.close(sq.qid)

    def _on_append(self, _dataset, _shard, _lo, _hi, _full) -> None:
        self._wake.set()

    def _run(self) -> None:
        tick = float(self.cfg["tick_s"])
        last_promo = 0.0
        while not self._stop.is_set():
            woke = self._wake.wait(tick)
            if self._stop.is_set():
                return
            if woke:
                self._wake.clear()
                if self.debounce_s > 0:
                    # debounce: let the scrape burst land before refreshing
                    self._stop.wait(self.debounce_s)
            now_s = self.clock()
            for sq in self.registry.list():
                try:
                    if (sq.rule_name or sq.alert_sink is not None) \
                            and sq.eval_interval_s:
                        # rules evaluate on their own clock, not per append
                        if now_s - sq.last_refresh_s >= sq.eval_interval_s:
                            self.refresh(sq)
                    elif woke and (now_s - sq.last_refresh_s
                                   >= self.debounce_s):
                        self.refresh(sq)
                except Exception:  # noqa: BLE001
                    log.exception("standing maintenance failed")
            if now_s - last_promo >= 2.0:
                last_promo = now_s
                try:
                    self.promote_tick(now_s)
                    self.demote_tick(now_s)
                except Exception:  # noqa: BLE001
                    log.exception("standing promotion scan failed")

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> dict:
        """The /debug/standing rendering: registry + demotions + the
        scheduler's retained recurrence ring + subscriber counts."""
        return {
            **self.registry.snapshot(),
            "subscribers": self.hub.snapshot(),
            "key_ring": self.scheduler.key_ring.snapshot(),
        }

    def rules_payload(self) -> dict:
        """Prometheus ``/api/v1/rules`` shape for the registered recording
        rules (one synthetic ``standing`` group holds the file-less,
        runtime-registered ones; file-backed rules are listed by
        obs/alerting.py's rules_payload instead)."""
        from ..obs.alerting import rfc3339

        rl = self.registry.rules()
        rules = [{
            "name": sq.rule_name,
            "query": sq.promql,
            "health": "err" if sq.last_error else "ok",
            "lastError": sq.last_error or "",
            "evaluationTime": float(sq.last_eval_duration_s),
            "lastEvaluation": rfc3339(int(sq.last_refresh_s * 1000)),
            "type": "recording",
            "labels": {},
        } for sq in rl]
        if not rules:
            return {"groups": []}
        return {"groups": [{
            "name": "standing", "file": "", "interval": 0,
            "evaluationTime": sum(
                float(sq.last_eval_duration_s) for sq in rl
            ),
            "lastEvaluation": rfc3339(
                int(max(sq.last_refresh_s for sq in rl) * 1000)
            ),
            "rules": rules,
        }]}
