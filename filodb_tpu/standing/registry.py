"""Standing-query registry: the set of PromQL expressions this process
keeps continuously evaluated (ROADMAP "standing-query engine").

A :class:`StandingQuery` is one registered expression plus its maintenance
state — the retained ``[G, J]`` partials the delta path splices into, the
shard version vector proving what the partials cover, and the grid/raw
ranges pinning one superblock cache entry across refreshes. Entries arrive
three ways:

- ``manual`` — registered over the API (``POST /api/v1/standing/register``);
- ``promoted`` — the promoter observed a hot recurring coalescing key in
  the dispatch scheduler's :class:`~filodb_tpu.query.scheduler.KeyStatsRing`
  and promoted it (Tailwind's explicit-dispatch framing: recurring work is
  admitted as a standing obligation instead of re-arriving as ad-hoc load);
- ``rule`` — a recording rule (``POST /api/v1/rules/record``): a standing
  query whose newest closed steps write back into the memstore as a real
  series under the rule's name;
- ``alert`` — an alerting rule (``obs/alerting.py``): a standing query
  whose newest closed step feeds a per-labelset threshold state machine
  instead of (or in addition to) a series write-back — the ``alert_sink``
  callback receives ``(sq, end_ms, vec)`` after every refresh.

Demotion is remembered: a key demoted for a sticky reason (e.g.
``standing_nondecomposable`` — topk/quantile/hist_quantile epilogues whose
output cannot splice per step) lands in the ``demoted`` map so the promoter
never flaps on it; idle-demoted keys age out and may re-promote once they
get hot again (hysteresis — promotion needs a burst, demotion needs a long
idle).
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field

import numpy as np

from ..metrics import REGISTRY

# demotion reason taxonomy surfaced at /debug/standing. The
# ``standing_nondecomposable`` entry is ALSO a fused-fallback taxonomy
# member (metrics.FUSED_FALLBACK_REASONS — linted by tools/check_metrics.py
# against doc/perf.md): every full re-dispatch a nondecomposable standing
# query pays is counted there.
DEMOTE_REASONS = frozenset({
    "standing_nondecomposable",  # epilogue can't splice: sticky, never re-promotes
    "idle",                      # recurrence stopped and no subscribers remain
    "unregistered",              # explicit API unregister
    "error",                     # refresh kept failing
})


def _new_qid() -> str:
    return uuid.uuid4().hex[:12]


@dataclass
class StandingQuery:
    """One registered standing query + its delta-maintenance state.
    Mutable maintenance fields are guarded by ``lock`` (one refresh at a
    time per query; the maintainer is the only writer)."""

    qid: str
    promql: str
    dataset: str
    step_ms: int
    span_ms: int
    source: str = "manual"  # manual | promoted | rule | alert
    key: object = None  # the KeyStatsRing key (promoted entries)
    # delta eligibility, decided at registration by probing the planned
    # exec (ops/aggregations.standing_delta_eligible): "delta" refreshes
    # splice retained partials; "full" re-dispatches the whole grid each
    # time, counted standing_nondecomposable when the epilogue is why
    mode: str = "delta"
    mode_reason: str | None = None
    ws: str = "unknown"
    ns: str = "unknown"
    # recording rule: results write back as series `rule_name{group labels}`
    rule_name: str | None = None
    eval_interval_s: float | None = None
    # alerting rule: called with (sq, end_ms, eval_vec) after every refresh
    # — eval_vec is the newest closed step's [(labels, value)] column
    # (obs/alerting.py AlertingEngine._make_sink)
    alert_sink: object = field(default=None, repr=False)
    created_s: float = field(default_factory=time.time)
    # set (under ``lock``) by StandingRegistry.remove: refreshes racing the
    # unregister bail instead of re-growing state the ledger already
    # credited back
    removed: bool = False

    # -- maintenance state (lock-guarded, maintainer-owned) ----------------
    lock: threading.Lock = field(default_factory=threading.Lock, repr=False)
    retained: np.ndarray | None = field(default=None, repr=False)  # [G, J]
    labels: list | None = field(default=None, repr=False)  # [G] group labels
    grid_start_ms: int = 0  # absolute out_t of retained[:, 0]
    grid_end_ms: int = 0
    raw_range: tuple | None = None  # aligned (lo, hi) pinning the superblock
    versions: tuple | None = None  # shard version vector the partials cover
    shard_nums: tuple = ()
    window_ms: int = 0
    offset_ms: int = 0
    seq: int = 0  # refresh sequence number (rides every pushed payload)
    last_refresh_s: float = 0.0
    last_eval_duration_s: float = 0.0
    last_error: str | None = None
    last_payload: bytes | None = field(default=None, repr=False)
    last_rule_write_ms: int = 0
    stats: dict = field(default_factory=lambda: {
        "refreshes": 0, "delta": 0, "full": 0, "retained": 0, "reset": 0,
        "errors": 0, "steps_computed": 0, "steps_retained": 0, "renders": 0,
    })

    def num_steps(self) -> int:
        if self.grid_end_ms < self.grid_start_ms:
            return 0
        return int((self.grid_end_ms - self.grid_start_ms)
                   // self.step_ms) + 1

    def state_nbytes(self) -> int:
        """Retained-partial footprint (the ledger's standing_state kind)."""
        return int(self.retained.nbytes) if self.retained is not None else 0

    def snapshot(self) -> dict:
        return {
            "id": self.qid,
            "promql": self.promql,
            "dataset": self.dataset,
            "source": self.source,
            "mode": self.mode,
            "mode_reason": self.mode_reason,
            "step_ms": self.step_ms,
            "span_ms": self.span_ms,
            "window_ms": self.window_ms,
            "ws": self.ws,
            "ns": self.ns,
            "rule_name": self.rule_name,
            "eval_interval_s": self.eval_interval_s,
            "seq": self.seq,
            "groups": (len(self.labels) if self.labels is not None else 0),
            "steps": self.num_steps(),
            "state_bytes": self.state_nbytes(),
            "last_refresh_s": self.last_refresh_s,
            "last_error": self.last_error,
            "stats": dict(self.stats),
        }


def _standing_state_walker(registry) -> int:
    """Cold recount of every registered query's retained-partial bytes —
    the ledger drift check's ground truth for the standing_state kind."""
    return sum(sq.state_nbytes() for sq in registry.list())


class StandingRegistry:
    """Process-local store of registered standing queries + the demotion
    memory the promoter's hysteresis needs."""

    def __init__(self, max_standing: int = 64):
        self.max_standing = max(int(max_standing), 1)
        self._queries: dict[str, StandingQuery] = {}
        self._by_key: dict = {}  # ring key -> qid (promoted entries)
        # demoted keys: key -> {"reason", "at_s"}; sticky reasons never
        # re-promote, idle demotions age out (maintainer.DEMOTE_RETRY_S)
        self.demoted: dict = {}
        self._lock = threading.Lock()
        # device-resource ledger account for retained partials — the
        # standing engine's state is a first-class accounted consumer like
        # every cache (filodb_device_bytes{kind="standing_state"})
        from ..ledger import LEDGER

        self.ledger = LEDGER.register(
            self, "standing_state", _standing_state_walker, name="standing",
        )

    def add(self, sq: StandingQuery) -> StandingQuery:
        with self._lock:
            if len(self._queries) >= self.max_standing:
                raise ValueError(
                    f"standing registry at max_standing={self.max_standing}"
                )
            self._queries[sq.qid] = sq
            if sq.key is not None:
                self._by_key[sq.key] = sq.qid
        self._publish_gauges()
        return sq

    def remove(self, qid: str) -> StandingQuery | None:
        with self._lock:
            sq = self._queries.pop(qid, None)
            if sq is not None and sq.key is not None:
                self._by_key.pop(sq.key, None)
        if sq is not None:
            # quiesce: an in-flight refresh holds sq.lock and will adjust
            # the account when it commits — credit the state back only
            # AFTER it finishes, and mark the query removed so later
            # refreshes bail instead of re-growing freed state (else the
            # ledger balance drifts from the walker forever)
            with sq.lock:
                sq.removed = True
                nb = sq.state_nbytes()
                sq.retained = None
                sq.labels = None
            if nb:
                # count=0: standing state allocs/frees are byte
                # adjustments, never entry counts (matches account_state)
                self.ledger.free(nb, reason="drop", count=0)
            self._publish_gauges()
        return sq

    def account_state(self, old_nbytes: int, new_nbytes: int) -> None:
        """Debit/credit the ledger for a retained-partial resize (the
        maintainer calls this around every refresh that changes state)."""
        if new_nbytes > old_nbytes:
            self.ledger.alloc(new_nbytes - old_nbytes, count=0)
        elif old_nbytes > new_nbytes:
            self.ledger.free(old_nbytes - new_nbytes, reason="replace",
                             count=0)

    def get(self, qid: str) -> StandingQuery | None:
        with self._lock:
            return self._queries.get(qid)

    def by_key(self, key) -> StandingQuery | None:
        with self._lock:
            qid = self._by_key.get(key)
            return self._queries.get(qid) if qid is not None else None

    def list(self) -> list[StandingQuery]:
        with self._lock:
            return list(self._queries.values())

    def rules(self) -> list[StandingQuery]:
        return [sq for sq in self.list() if sq.rule_name]

    def note_demoted(self, key, reason: str) -> None:
        if key is None:
            return
        with self._lock:
            self.demoted[key] = {"reason": reason, "at_s": time.time()}
            # bounded: oldest demotion memories age out first
            while len(self.demoted) > 256:
                self.demoted.pop(next(iter(self.demoted)))

    def demoted_reason(self, key) -> str | None:
        with self._lock:
            e = self.demoted.get(key)
            return e["reason"] if e else None

    def forget_demoted(self, key) -> None:
        with self._lock:
            self.demoted.pop(key, None)

    def _publish_gauges(self) -> None:
        with self._lock:
            by_mode: dict[str, int] = {}
            for sq in self._queries.values():
                by_mode[sq.mode] = by_mode.get(sq.mode, 0) + 1
        for mode in ("delta", "full"):
            REGISTRY.gauge("filodb_standing_queries", mode=mode).set(
                float(by_mode.get(mode, 0))
            )

    def snapshot(self) -> dict:
        with self._lock:
            queries = [sq.snapshot() for sq in self._queries.values()]
            demoted = [
                {"key": repr(k), **v} for k, v in self.demoted.items()
            ]
        return {
            "queries": queries,
            "count": len(queries),
            "max_standing": self.max_standing,
            "demoted": demoted,
        }
