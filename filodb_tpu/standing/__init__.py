"""Standing-query engine: delta-maintained dashboards with push fan-out
and recording rules (doc/operations.md "Standing queries & recording
rules"). See maintainer.py for the architecture overview."""

from .hub import CLOSED, Subscription, SubscriptionHub, SubscriptionLimit
from .maintainer import DEFAULTS as STANDING_DEFAULTS
from .maintainer import StandingEngine
from .registry import DEMOTE_REASONS, StandingQuery, StandingRegistry

__all__ = [
    "CLOSED",
    "DEMOTE_REASONS",
    "STANDING_DEFAULTS",
    "StandingEngine",
    "StandingQuery",
    "StandingRegistry",
    "Subscription",
    "SubscriptionHub",
    "SubscriptionLimit",
]
