"""Subscription hub: ONE materialization fanned out to N subscribers.

The serving half of the standing-query engine (ROADMAP "serve results by
push"): the maintainer renders each refresh payload EXACTLY ONCE (one JSON
encode of the [G, J] partials) and :meth:`SubscriptionHub.publish` hands the
same immutable bytes object to every subscriber queue — N dashboard clients
cost one materialization plus N socket writes, never N query executions or
N renders. Subscribers are bounded per standing query
(``standing.max_subscribers``): past the limit, new subscriptions shed with
:class:`SubscriptionLimit` (HTTP 429 at the SSE edge, the same overload
contract admission control uses).

Queues are bounded too (a stalled SSE client must not buffer unboundedly):
when a subscriber's queue is full the OLDEST payload drops — dashboards
want the freshest frame, not a backlog — counted in
``filodb_standing_pushes_total{outcome="dropped"}``.
"""

from __future__ import annotations

import queue
import threading

from ..metrics import REGISTRY

# sentinel delivered on close so blocked SSE writers wake and exit
CLOSED = object()


class SubscriptionLimit(Exception):
    """Subscription shed: the standing query is at its subscriber bound."""


class Subscription:
    """One subscriber's bounded frame queue."""

    __slots__ = ("qid", "_q", "closed")

    def __init__(self, qid: str, depth: int = 8):
        self.qid = qid
        self._q: queue.Queue = queue.Queue(maxsize=max(int(depth), 1))
        self.closed = False

    def get(self, timeout: float | None = None):
        """Next payload (bytes), or raises queue.Empty on timeout, or
        returns :data:`CLOSED` when the hub shut the subscription down."""
        return self._q.get(timeout=timeout)

    def _offer(self, payload) -> bool:
        """Enqueue newest-wins: a full queue drops its OLDEST frame first.
        Returns False when a frame was dropped to make room."""
        dropped = False
        while True:
            try:
                self._q.put_nowait(payload)
                return not dropped
            except queue.Full:
                try:
                    self._q.get_nowait()
                    dropped = True
                except queue.Empty:
                    pass


class SubscriptionHub:
    """Per-standing-query subscriber registry with publish-once fan-out."""

    def __init__(self, max_subscribers: int = 64, queue_depth: int = 8):
        self.max_subscribers = max(int(max_subscribers), 1)
        self.queue_depth = max(int(queue_depth), 1)
        self._subs: dict[str, list[Subscription]] = {}
        self._lock = threading.Lock()

    def subscribe(self, qid: str) -> Subscription:
        with self._lock:
            subs = self._subs.setdefault(qid, [])
            if len(subs) >= self.max_subscribers:
                raise SubscriptionLimit(
                    f"standing query {qid} at max_subscribers="
                    f"{self.max_subscribers}"
                )
            sub = Subscription(qid, self.queue_depth)
            subs.append(sub)
        REGISTRY.gauge("filodb_standing_subscribers").set(float(self.total()))
        return sub

    def unsubscribe(self, sub: Subscription) -> None:
        with self._lock:
            subs = self._subs.get(sub.qid)
            if subs is not None:
                try:
                    subs.remove(sub)
                except ValueError:
                    pass
                if not subs:
                    self._subs.pop(sub.qid, None)
        sub.closed = True
        REGISTRY.gauge("filodb_standing_subscribers").set(float(self.total()))

    def publish(self, qid: str, payload: bytes) -> int:
        """Fan one rendered payload out to every subscriber of ``qid`` (the
        SAME bytes object lands in every queue — zero per-subscriber
        copies). Returns the number of subscribers reached."""
        with self._lock:
            subs = list(self._subs.get(qid, ()))
        sent = dropped = 0
        for sub in subs:
            if sub._offer(payload):
                sent += 1
            else:
                sent += 1
                dropped += 1
        if sent:
            REGISTRY.counter(
                "filodb_standing_pushes", outcome="sent"
            ).inc(sent)
        if dropped:
            REGISTRY.counter(
                "filodb_standing_pushes", outcome="dropped"
            ).inc(dropped)
        return sent

    def close(self, qid: str) -> None:
        """Shut every subscription of ``qid`` down (unregister/demote):
        blocked SSE writers receive :data:`CLOSED` and exit."""
        with self._lock:
            subs = self._subs.pop(qid, [])
        for sub in subs:
            sub.closed = True
            sub._offer(CLOSED)
        if subs:
            REGISTRY.gauge("filodb_standing_subscribers").set(
                float(self.total())
            )

    def count(self, qid: str) -> int:
        with self._lock:
            return len(self._subs.get(qid, ()))

    def total(self) -> int:
        with self._lock:
            return sum(len(s) for s in self._subs.values())

    def snapshot(self) -> dict:
        with self._lock:
            return {qid: len(subs) for qid, subs in self._subs.items()}
