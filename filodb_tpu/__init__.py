"""filodb_tpu — a TPU-native, Prometheus-compatible, distributed time-series
database framework with the capabilities of FiloDB (reference: filodb/FiloDB).

Architecture (see SURVEY.md for the reference layer map this mirrors):

- ``core``      — chunk encodings, schemas, ingestion records, partition keys
                  (reference L0/L1: filodb.memory format/*, binaryrecord2/*).
- ``memstore``  — sharded in-memory store: partitions, write buffers, sealed
                  chunks, tag index, flush & eviction (reference L2).
- ``store``     — persistence API + local column store, checkpoints
                  (reference L3: store/*, cassandra/*).
- ``query``     — PromQL front end, LogicalPlan, ExecPlan tree, range-vector
                  model (reference L4/L6: query/*, prometheus/*).
- ``ops``       — the TPU compute path: jit window kernels over staged
                  ``[series, time]`` chunk blocks (replaces the reference's
                  per-series iterator hot loops + Rust SIMD).
- ``parallel``  — device mesh, sharded cross-series reduction (replaces
                  Akka/Arrow-Flight scatter-gather with psum over ICI).
- ``coordinator`` — planners, shard mapping, dispatch (reference L5).
- ``api``       — Prometheus HTTP API (reference L6 http/).
- ``gateway``   — line-protocol ingest parsers (reference L7 gateway/).
"""

__version__ = "0.1.0"
