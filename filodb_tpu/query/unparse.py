"""LogicalPlan -> PromQL string (reference
coordinator/.../queryplanner/LogicalPlanParser.scala:286 — used by HA /
multi-partition planners to ship subplans to peer clusters as PromQL over
HTTP instead of serialized exec trees)."""

from __future__ import annotations

from ..core.filters import ColumnFilter
from ..core.schemas import METRIC_TAG
from . import logical as L
from .functions import RANGE_FUNCTIONS

# kernel name -> surface name (first surface name wins)
_KERNEL_TO_SURFACE: dict[str, str] = {}
for surface, (kernel, _, _) in RANGE_FUNCTIONS.items():
    _KERNEL_TO_SURFACE.setdefault(kernel, surface)


def _ms_dur(ms: int) -> str:
    if ms % 3_600_000 == 0:
        return f"{ms // 3_600_000}h"
    if ms % 60_000 == 0:
        return f"{ms // 60_000}m"
    if ms % 1000 == 0:
        return f"{ms // 1000}s"
    return f"{ms}ms"


def _selector(filters, window_ms=None, offset_ms=0, at_ms=None) -> str:
    metric = ""
    matchers = []
    for f in filters:
        if f.column == METRIC_TAG and f.op == "=":
            metric = f.value
        else:
            col = "__name__" if f.column == METRIC_TAG else f.column
            matchers.append(f'{col}{f.op}"{f.value}"')
    s = metric + ("{" + ",".join(matchers) + "}" if matchers else "")
    if window_ms:
        s += f"[{_ms_dur(window_ms)}]"
    if offset_ms:
        s += f" offset {_ms_dur(offset_ms)}"
    if at_ms is not None:
        # full decimal form — %g would render 1.6e+09, which @ can't parse
        at = f"{at_ms / 1000:.3f}".rstrip("0").rstrip(".")
        s += f" @ {at}"
    return s


def _args_str(args) -> str:
    return ",".join(f"{a:g}" if isinstance(a, float) else str(a) for a in args)


def to_promql(p: L.LogicalPlan) -> str:
    """Render a plan subtree back to PromQL. Raises on nodes with no PromQL
    surface form (those stay local)."""
    if isinstance(p, L.RawSeries):
        w = p.end_ms - p.start_ms
        return _selector(p.filters, window_ms=w, offset_ms=p.offset_ms)
    if isinstance(p, L.PeriodicSeries):
        return _selector(p.raw.filters, offset_ms=p.offset_ms, at_ms=p.at_ms)
    if isinstance(p, L.PeriodicSeriesWithWindowing):
        surface = _KERNEL_TO_SURFACE.get(p.function, p.function)
        _, n_scalar, scalars_first = RANGE_FUNCTIONS.get(surface, (p.function, 0, False))
        sel = _selector(p.raw.filters, window_ms=p.window_ms, offset_ms=p.offset_ms,
                        at_ms=p.at_ms)
        args = list(p.function_args)
        if args and scalars_first:
            return f"{surface}({_args_str(args)},{sel})"
        if args:
            return f"{surface}({sel},{_args_str(args)})"
        return f"{surface}({sel})"
    if isinstance(p, L.Aggregate):
        inner = to_promql(p.inner)
        mod = ""
        if p.by is not None:
            mod = f" by ({','.join(p.by)}) "
        elif p.without is not None:
            mod = f" without ({','.join(p.without)}) "
        if p.params:
            param = p.params[0]
            ps = f'"{param}",' if isinstance(param, str) else f"{param:g},"
            return f"{p.op}{mod}({ps}{inner})"
        return f"{p.op}{mod}({inner})"
    if isinstance(p, L.BinaryJoin):
        mod = ""
        if p.on is not None:
            mod += f" on ({','.join(p.on)})"
        elif p.ignoring:
            mod += f" ignoring ({','.join(p.ignoring)})"
        if p.cardinality == "many-to-one":
            mod += f" group_left ({','.join(p.include)})" if p.include else " group_left"
        elif p.cardinality == "one-to-many":
            mod += f" group_right ({','.join(p.include)})" if p.include else " group_right"
        b = " bool" if p.return_bool else ""
        return f"({to_promql(p.lhs)} {p.op}{b}{mod} {to_promql(p.rhs)})"
    if isinstance(p, L.ScalarVectorBinaryOperation):
        sc = to_promql(p.scalar)
        vec = to_promql(p.vector)
        b = " bool" if p.return_bool else ""
        return f"({sc} {p.op}{b} {vec})" if p.scalar_is_lhs else f"({vec} {p.op}{b} {sc})"
    if isinstance(p, L.ApplyInstantFunction):
        inner = to_promql(p.inner)
        if p.args:
            from .functions import RANGE_FUNCTIONS as _RF

            # histogram_quantile-style: scalar args lead
            if p.function in ("histogram_quantile", "histogram_fraction", "histogram_max_quantile"):
                return f"{p.function}({_args_str(p.args)},{inner})"
            return f"{p.function}({inner},{_args_str(p.args)})"
        return f"{p.function}({inner})"
    if isinstance(p, L.ApplyMiscellaneousFunction):
        strs = ",".join(f'"{s}"' for s in p.str_args)
        return f"{p.function}({to_promql(p.inner)},{strs})"
    if isinstance(p, L.ApplySortFunction):
        return f"{'sort_desc' if p.descending else 'sort'}({to_promql(p.inner)})"
    if isinstance(p, L.ApplyAbsentFunction):
        return f"absent({to_promql(p.inner)})"
    if isinstance(p, L.ApplyLimitFunction):
        return to_promql(p.inner)
    if isinstance(p, L.ScalarFixedDoublePlan):
        return f"{p.value:g}"
    if isinstance(p, L.ScalarTimeBasedPlan):
        return f"{p.function}()"
    if isinstance(p, L.ScalarBinaryOperation):
        lhs = to_promql(p.lhs) if isinstance(p.lhs, L.LogicalPlan) else f"{p.lhs:g}"
        rhs = to_promql(p.rhs) if isinstance(p.rhs, L.LogicalPlan) else f"{p.rhs:g}"
        return f"({lhs} {p.op} {rhs})"
    if isinstance(p, L.ScalarVaryingDoublePlan):
        return f"{p.function}({to_promql(p.inner)})"
    if isinstance(p, L.SubqueryWithWindowing):
        surface = _KERNEL_TO_SURFACE.get(p.function, p.function)
        inner = to_promql(p.inner)
        sq = f"{inner}[{_ms_dur(p.window_ms)}:{_ms_dur(p.sub_step_ms)}]"
        if p.offset_ms:
            sq += f" offset {_ms_dur(p.offset_ms)}"
        if p.function_args:
            return f"{surface}({_args_str(p.function_args)},{sq})"
        return f"{surface}({sq})"
    if isinstance(p, L.TopLevelSubquery):
        return to_promql(p.inner)
    raise ValueError(f"no PromQL form for {type(p).__name__}")
