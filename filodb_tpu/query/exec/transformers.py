"""RangeVectorTransformers (reference query/exec/RangeVectorTransformer.scala
+ PeriodicSamplesMapper.scala:61 — the operator stages folded onto a leaf
exec's output; here each transformer maps grid batches, keeping values on
device until the serving edge).
"""

from __future__ import annotations

import calendar
import datetime as _dt
import re
from dataclasses import dataclass, field
from typing import Sequence

import jax.numpy as jnp
import numpy as np

from ...core.schemas import METRIC_TAG
from ...ops import aggregations as AGG
from ...ops import hist_kernels as HK
from ...ops import kernels as K
from ..rangevector import Grid, QueryResult, RawGrid, ScalarResult

_DROP_NAME_KEEP = {"last_over_time", "timestamp"}  # fns that keep _metric_


class QueryError(ValueError):
    pass


class QueryDeadlineExceeded(QueryError):
    """Cooperative deadline abort (reference query timeout); the HTTP edge
    maps it to 503 like Prometheus timeouts."""


def _strip_metric(labels: dict) -> dict:
    return {k: v for k, v in labels.items() if k not in (METRIC_TAG, "__name__")}


@dataclass
class PeriodicSamplesMapper:
    """Materialize regular-step samples from staged raw windows: the single
    jit kernel call replacing the reference's per-series window iterators."""

    start_ms: int
    end_ms: int
    step_ms: int
    function: str | None = None  # None => instant lookback (gauge last)
    window_ms: int | None = None
    lookback_ms: int = 300_000
    offset_ms: int = 0
    at_ms: int | None = None
    args: tuple = ()

    def num_steps(self) -> int:
        return int((self.end_ms - self.start_ms) // self.step_ms) + 1

    def apply_raw(self, raws: list[RawGrid]) -> list[Grid]:
        from ...metrics import span

        out: list[Grid] = []
        nsteps = self.num_steps()
        for rg in raws:
            func = self.function or "last"
            window = self.window_ms if self.window_ms is not None else self.lookback_ms
            eval_start = (self.at_ms if self.at_ms is not None else self.start_ms) - self.offset_ms
            eval_steps = 1 if self.at_ms is not None else nsteps
            params = K.RangeParams(eval_start, self.step_ms, eval_steps, window)
            if rg.is_histogram:
                if func not in ("rate", "increase", "delta", "sum_over_time", "last", "last_over_time"):
                    raise QueryError(
                        f"function {self.function} is not supported on native histograms"
                    )
                with span(f"kernel:hist_{func}", schema=rg.schema_name):
                    vals = HK.run_hist_range_function(
                        func, rg.block, params, is_delta=rg.is_delta
                    )
                scalar_vals = vals[..., -1] * jnp.nan  # placeholder [S,J]
                g = Grid(
                    labels=list(rg.labels),
                    start_ms=self.start_ms,
                    step_ms=self.step_ms,
                    num_steps=nsteps,
                    values=scalar_vals,
                    hist=vals,
                    les=rg.les,
                )
            else:
                with span(f"kernel:{func}", schema=rg.schema_name):
                    vals = K.run_range_function(
                        func,
                        rg.block,
                        params,
                        is_counter=rg.is_counter,
                        is_delta=rg.is_delta,
                        args=self.args,
                    )
                g = Grid(
                    labels=list(rg.labels),
                    start_ms=self.start_ms,
                    step_ms=self.step_ms,
                    num_steps=nsteps,
                    values=vals,
                )
            if self.at_ms is not None:
                # @ fixes evaluation time: broadcast the single step across grid
                v = np.asarray(g.values)[:, :1]
                g = g.with_values(np.repeat(v, max(nsteps, 1), axis=1))
                if g.hist is not None:
                    h = np.asarray(g.hist)[:, :1]
                    g = g.with_values(g.values, np.repeat(h, max(nsteps, 1), axis=1))
            if self.function and self.function not in _DROP_NAME_KEEP:
                g.labels = [_strip_metric(l) for l in g.labels]
            if self.function == "absent_over_time":
                g = self._absent_reduce(g)
            out.append(g)
        return out

    def _absent_reduce(self, g: Grid) -> Grid:
        # absent iff NO series present at the step
        v = g.values_np()
        if v.shape[0] == 0:
            vals = np.ones((1, g.num_steps), dtype=np.float32)
        else:
            present = (~np.isnan(v)).any(axis=0)
            vals = np.where(present, np.nan, 1.0)[None, :].astype(np.float32)
        return Grid([{}], g.start_ms, g.step_ms, g.num_steps, vals)


# ---------------------------------------------------------------------------
# instant functions
# ---------------------------------------------------------------------------

_ELEMENTWISE = {
    "abs": jnp.abs, "ceil": jnp.ceil, "floor": jnp.floor, "exp": jnp.exp,
    "ln": jnp.log, "log2": jnp.log2, "log10": jnp.log10, "sqrt": jnp.sqrt,
    "sgn": jnp.sign, "acos": jnp.arccos, "acosh": jnp.arccosh,
    "asin": jnp.arcsin, "asinh": jnp.arcsinh, "atan": jnp.arctan,
    "atanh": jnp.arctanh, "cos": jnp.cos, "cosh": jnp.cosh, "sin": jnp.sin,
    "sinh": jnp.sinh, "tan": jnp.tan, "tanh": jnp.tanh,
    "deg": jnp.degrees, "rad": jnp.radians,
}

_TIME_COMPONENT = {
    "minute": lambda d: d.minute, "hour": lambda d: d.hour,
    "month": lambda d: d.month, "year": lambda d: d.year,
    "day_of_month": lambda d: d.day, "day_of_week": lambda d: (d.weekday() + 1) % 7,
    "day_of_year": lambda d: d.timetuple().tm_yday,
    "days_in_month": lambda d: calendar.monthrange(d.year, d.month)[1],
}


def classic_histogram_quantile(q: float, labels, values):
    """``histogram_quantile`` over CLASSIC bucket series (scalar rows
    carrying ``le`` labels — e.g. a self-scraped ``*_bucket`` family in
    ``_system``, or any Prometheus-style ingest): pivot each label-group's
    le-sorted rows into a ``[1, J, B]`` cumulative grid and interpolate
    with the SAME kernel the native-histogram path uses
    (ops/hist_kernels.histogram_quantile — one rule, both schemas).
    Returns ``(labels_without_le, [G', J] values)``; raises QueryError
    when the rows carry no ``le`` at all (the historical error)."""
    vals = np.asarray(values, dtype=np.float32)
    J = vals.shape[1] if vals.ndim == 2 else 0
    groups: dict = {}
    order: list = []
    for i, l in enumerate(labels):
        le_s = l.get("le")
        if le_s is None:
            raise QueryError(
                "histogram_quantile needs native-histogram input or "
                "le-labeled classic bucket series"
            )
        le = (float("inf") if str(le_s) in ("+Inf", "Inf", "inf")
              else float(le_s))
        key = tuple(sorted(
            (k, v) for k, v in l.items() if k != "le"
        ))
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append((le, i))
    # groups sharing one bucket scheme stack into a single [G, J, B]
    # kernel call (it already takes a leading group axis) — ONE dispatch
    # per distinct scheme, not one per group, so a 50-tenant by-(le,ws)
    # quantile costs the same launches as a 1-tenant one
    by_scheme: dict = {}
    for key in order:
        members = sorted(groups[key], key=lambda m: m[0])
        scheme = tuple(m[0] for m in members)
        by_scheme.setdefault(scheme, []).append(
            (key, [m[1] for m in members])
        )
    results: dict = {}
    for scheme, entries in by_scheme.items():
        les = np.array(scheme, dtype=np.float32)
        # [G, J, B]: le-ordered cumulative bucket rows per group
        h = np.stack([vals[idx].T for _key, idx in entries])
        out = np.asarray(HK.histogram_quantile(
            np.float32(q), jnp.asarray(h), jnp.asarray(les)
        ))
        for (key, _idx), row in zip(entries, out):
            results[key] = row
    out_labels = [dict(key) for key in order]
    rows = [results[key] for key in order]
    return out_labels, (np.stack(rows).astype(np.float32) if rows
                        else np.zeros((0, J), np.float32))


@dataclass
class InstantVectorFunctionMapper:
    """reference InstantVectorFunctionMapper + InstantFunction.scala."""

    function: str
    args: tuple = ()

    def apply(self, grids: list[Grid]) -> list[Grid]:
        out = []
        for g in grids:
            out.append(self._one(g))
        return out

    def _one(self, g: Grid) -> Grid:
        f = self.function
        if f == "histogram_quantile":
            q = np.float32(self.args[0])
            if g.hist is None:
                # classic-bucket path: le-labeled scalar rows (the shape
                # every self-scraped *_bucket family in _system has)
                out_labels, vals = classic_histogram_quantile(
                    q, g.labels, g.values_np()
                )
                return Grid([_strip_metric(l) for l in out_labels],
                            g.start_ms, g.step_ms, g.num_steps, vals)
            vals = HK.histogram_quantile(q, g.hist, jnp.asarray(g.les, dtype=jnp.float32))
            return Grid([_strip_metric(l) for l in g.labels], g.start_ms, g.step_ms, g.num_steps, vals)
        if f == "histogram_fraction":
            if g.hist is None:
                raise QueryError("histogram_fraction needs native-histogram input")
            lo, hi = np.float32(self.args[0]), np.float32(self.args[1])
            vals = HK.histogram_fraction(lo, hi, g.hist, jnp.asarray(g.les, dtype=jnp.float32))
            return Grid([_strip_metric(l) for l in g.labels], g.start_ms, g.step_ms, g.num_steps, vals)
        if f in ("histogram_max_quantile", "histogram_max_quantile_even"):
            q = np.float32(self.args[0])
            vals = HK.histogram_quantile(
                q, g.hist, jnp.asarray(g.les, dtype=jnp.float32),
                even=(f == "histogram_max_quantile_even"),
            )
            return Grid([_strip_metric(l) for l in g.labels], g.start_ms, g.step_ms, g.num_steps, vals)
        if f == "histogram_bucket":
            # select one bucket's counts; le must match a bucket bound exactly
            # (reference HistogramBucketImpl: 1e-10 tolerance, NaN otherwise,
            # +Inf selects the top bucket)
            if g.hist is None:
                raise QueryError("histogram_bucket needs native-histogram input")
            le = float(self.args[0])
            les = np.asarray(g.les, dtype=np.float64)
            if np.isinf(le):
                idx = len(les) - 1
            else:
                matches = np.nonzero(np.abs(les - le) < 1e-10)[0]
                idx = int(matches[0]) if len(matches) else -1
            if idx < 0:
                vals = np.full((g.n_series, g.num_steps), np.nan, np.float32)
            else:
                vals = jnp.asarray(g.hist)[..., idx]
            le_str = "+Inf" if idx >= 0 and np.isinf(les[idx]) else f"{le:g}"
            labels = [dict(_strip_metric(l), le=le_str) for l in g.labels]
            return Grid(labels, g.start_ms, g.step_ms, g.num_steps, vals)
        if f == "hist_to_prom_vectors":
            return self._hist_to_prom(g)
        if f == "clamp":
            v = jnp.clip(g.values, self.args[0], self.args[1])
        elif f == "clamp_min":
            v = jnp.maximum(g.values, self.args[0])
        elif f == "clamp_max":
            v = jnp.minimum(g.values, self.args[0])
        elif f == "round":
            to = self.args[0] if self.args else 1.0
            v = jnp.round(jnp.asarray(g.values) / to) * to
        elif f == "or_vector":
            # NaN samples replaced by the default scalar (reference OrVectorImpl)
            v = jnp.where(jnp.isnan(jnp.asarray(g.values)), self.args[0], jnp.asarray(g.values))
        elif f == "timestamp":
            t = g.step_times_ms().astype(np.float64) / 1e3
            vn = g.values_np()
            v = np.where(np.isnan(vn), np.nan, t[None, :])
        elif f in _TIME_COMPONENT:
            times = g.step_times_ms()
            comp = np.array(
                [_TIME_COMPONENT[f](_dt.datetime.fromtimestamp(t / 1e3, _dt.timezone.utc)) for t in times],
                dtype=np.float64,
            )
            vn = g.values_np()
            v = np.where(np.isnan(vn), np.nan, comp[None, :])
        elif f in _ELEMENTWISE:
            v = _ELEMENTWISE[f](jnp.asarray(g.values))
        else:
            raise QueryError(f"unknown instant function {f}")
        return Grid([_strip_metric(l) for l in g.labels], g.start_ms, g.step_ms, g.num_steps, v)

    def _hist_to_prom(self, g: Grid) -> Grid:
        """Explode native histogram into classic _bucket series (reference
        HistToPromSeriesMapper)."""
        if g.hist is None:
            return g
        h = g.hist_np()
        S, J, B = h.shape
        labels = []
        rows = []
        for i, l in enumerate(g.labels):
            for b in range(B):
                le = g.les[b]
                lb = dict(l)
                lb["le"] = "+Inf" if np.isinf(le) else f"{le:g}"
                labels.append(lb)
                rows.append(h[i, :, b])
        vals = np.stack(rows) if rows else np.zeros((0, J), dtype=np.float32)
        return Grid(labels, g.start_ms, g.step_ms, g.num_steps, vals)


# ---------------------------------------------------------------------------
# scalar ops
# ---------------------------------------------------------------------------

_BINOPS = {
    "+": lambda a, b: a + b,
    "-": lambda a, b: a - b,
    "*": lambda a, b: a * b,
    "/": lambda a, b: a / b,
    "%": lambda a, b: jnp.where(b != 0, a - jnp.floor(a / b) * b, jnp.nan),
    "^": lambda a, b: a**b,
    "atan2": lambda a, b: jnp.arctan2(a, b),
}
_CMPOPS = {
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    ">": lambda a, b: a > b,
    "<": lambda a, b: a < b,
    ">=": lambda a, b: a >= b,
    "<=": lambda a, b: a <= b,
}


def apply_binop(op: str, lhs, rhs, return_bool: bool):
    """Elementwise arithmetic/comparison with promql filter semantics."""
    if op in _BINOPS:
        return _BINOPS[op](lhs, rhs)
    cmp = _CMPOPS[op](lhs, rhs)
    if return_bool:
        both = ~(jnp.isnan(lhs) | jnp.isnan(rhs))
        return jnp.where(both, cmp.astype(jnp.float32), jnp.nan)
    return jnp.where(cmp, lhs, jnp.nan)


@dataclass
class ScalarOperationMapper:
    """vector op scalar (reference ScalarOperationMapper)."""

    op: str
    scalar: ScalarResult | float
    scalar_is_lhs: bool
    return_bool: bool = False

    def apply(self, grids: list[Grid]) -> list[Grid]:
        out = []
        for g in grids:
            s = self.scalar
            sv = s.values[None, : np.asarray(g.values).shape[1]] if isinstance(s, ScalarResult) else s
            if isinstance(sv, np.ndarray) and sv.shape[-1] < np.asarray(g.values).shape[1]:
                sv = np.pad(sv, ((0, 0), (0, np.asarray(g.values).shape[1] - sv.shape[1])), constant_values=np.nan)
            a, b = (sv, g.values) if self.scalar_is_lhs else (g.values, sv)
            v = apply_binop(self.op, jnp.asarray(a, dtype=jnp.float32), jnp.asarray(b, dtype=jnp.float32), self.return_bool)
            keep_name = self.op in _CMPOPS and not self.return_bool
            labels = g.labels if keep_name else [_strip_metric(l) for l in g.labels]
            out.append(Grid(labels, g.start_ms, g.step_ms, g.num_steps, v))
        return out


# ---------------------------------------------------------------------------
# misc / labels / sort / limit / absent
# ---------------------------------------------------------------------------


@dataclass
class MiscellaneousFunctionMapper:
    function: str
    str_args: tuple = ()

    def apply(self, grids: list[Grid]) -> list[Grid]:
        if self.function in ("optimize_with_agg", "no_optimize"):
            return grids  # planner-level markers; no-op at execution
        if self.function == "label_replace":
            dst, repl, src, regex_s = self.str_args
            pat = re.compile(regex_s)
            for g in grids:
                new_labels = []
                for l in g.labels:
                    m = pat.fullmatch(l.get(src, ""))
                    l2 = dict(l)
                    if m:
                        val = m.expand(repl.replace("$", "\\"))
                        if val:
                            l2[dst] = val
                        else:
                            l2.pop(dst, None)
                    new_labels.append(l2)
                g.labels = new_labels
            return grids
        if self.function == "label_join":
            dst, sep, *srcs = self.str_args
            for g in grids:
                g.labels = [
                    {**l, dst: sep.join(l.get(s, "") for s in srcs)} for l in g.labels
                ]
            return grids
        raise QueryError(f"unknown misc function {self.function}")


@dataclass
class SortFunctionMapper:
    """sort()/sort_desc(): orders series by value (instant queries)."""

    descending: bool = False

    def apply(self, grids: list[Grid]) -> list[Grid]:
        out = []
        for g in grids:
            v = g.values_np()
            key = np.where(np.isnan(v[:, -1]), -np.inf if not self.descending else np.inf, v[:, -1])
            order = np.argsort(-key if self.descending else key, kind="stable")
            out.append(
                Grid([g.labels[i] for i in order], g.start_ms, g.step_ms, g.num_steps, v[order],
                     None if g.hist is None else g.hist_np()[order], g.les)
            )
        return out


@dataclass
class LimitFunctionMapper:
    limit: int

    def apply(self, grids: list[Grid]) -> list[Grid]:
        out = []
        budget = self.limit
        for g in grids:
            if budget <= 0:
                break
            take = min(budget, g.n_series)
            v = g.values_np()[:take]
            out.append(Grid(g.labels[:take], g.start_ms, g.step_ms, g.num_steps, v))
            budget -= take
        return out


@dataclass
class AbsentFunctionMapper:
    """absent(v): 1 when no series has a value at the step (reference
    AbsentFunctionMapper); output labels from equality matchers."""

    filters: tuple = ()
    start_ms: int = 0
    step_ms: int = 1
    num_steps: int = 1

    def apply(self, grids: list[Grid]) -> list[Grid]:
        start_ms, step_ms, num_steps = self.start_ms, self.step_ms, self.num_steps
        if grids:
            start_ms, step_ms, num_steps = grids[0].start_ms, grids[0].step_ms, grids[0].num_steps
        present = np.zeros(num_steps, dtype=bool)
        for g in grids:
            v = g.values_np()
            if v.size:
                present |= (~np.isnan(v)).any(axis=0)
        vals = np.where(present, np.nan, 1.0)[None, :].astype(np.float32)
        labels = {
            f.column: f.value
            for f in self.filters
            if getattr(f, "op", "") == "=" and f.column not in (METRIC_TAG, "__name__")
        }
        return [Grid([labels], start_ms, step_ms, num_steps, vals)]


@dataclass
class TopkCandidateFilter:
    """Per-shard map phase for root topk/bottomk (reference
    TopBottomKRowAggregator's per-node k-heaps spilled via RecordContainers):
    drop series that are NOT in this shard's per-(group, step) top-k at any
    step. Exact, not approximate — if a series misses its shard-local top-k
    at step j there are already >= k shard-local series beating it there, so
    it cannot be in the global top-k at step j either; shipping a SUPERSET
    of candidates never changes the root's exact reduction. Cuts the root
    gather from O(series) to O(shards * k) rows per group."""

    k: int
    bottom: bool = False
    by: tuple | None = None
    without: tuple | None = None

    def apply(self, grids: list[Grid]) -> list[Grid]:
        from ...ops import aggregations as AGG

        out = []
        for g in grids:
            if g.hist is not None or g.n_series <= self.k:
                out.append(g)
                continue
            vals = g.values_np()
            gids, group_labels = AGG.group_ids_for(
                g.labels, list(self.by) if self.by else None,
                list(self.without) if self.without else None,
            )
            keep = np.zeros(g.n_series, dtype=bool)
            fill = np.inf if self.bottom else -np.inf
            for gi in range(len(group_labels)):
                rows = np.nonzero(gids == gi)[0]
                if len(rows) <= self.k:
                    keep[rows] = True
                    continue
                v = vals[rows]
                vv = np.where(np.isnan(v), fill, v)
                # kth best per step; >= / <= keeps ties (superset: still exact)
                if self.bottom:
                    thresh = np.partition(vv, self.k - 1, axis=0)[self.k - 1]
                    cand = (vv <= thresh) & np.isfinite(v)
                else:
                    thresh = np.partition(vv, -self.k, axis=0)[-self.k]
                    cand = (vv >= thresh) & np.isfinite(v)
                keep[rows] |= cand.any(axis=1)
            rows = np.nonzero(keep)[0]
            out.append(Grid([g.labels[i] for i in rows], g.start_ms, g.step_ms,
                            g.num_steps, vals[rows]))
        return out


@dataclass
class CountValuesMapReduce:
    """Per-shard map phase for root count_values (reference
    CountValuesRowAggregator's per-node count maps spilled via
    RecordContainers): emit one row per (group, value-string) holding this
    shard's per-step counts. Shards own disjoint series, so the root merge
    is an exact SUM of identical-label rows — O(groups x distinct-values)
    crosses the gather, not O(series)."""

    label: str
    by: tuple | None = None
    without: tuple | None = None

    def apply(self, grids: list[Grid]) -> list[Grid]:
        from ...ops import aggregations as AGG

        if not grids:
            return grids
        all_labels = [l for g in grids for l in g.labels]
        if not all_labels:
            return [grids[0]]
        J = max(g.values_np().shape[1] for g in grids)
        vals = np.full((len(all_labels), J), np.nan, np.float32)
        r0 = 0
        for g in grids:
            v = g.values_np()
            vals[r0:r0 + v.shape[0], : v.shape[1]] = v
            r0 += v.shape[0]
        gids, group_labels = AGG.group_ids_for(
            all_labels, list(self.by) if self.by else None,
            list(self.without) if self.without else None,
        )
        meta = grids[0]
        out_labels, out_rows = [], []
        for gi, gl in enumerate(group_labels):
            for valstr, row in AGG.count_values(vals[gids == gi]).items():
                out_labels.append(dict(gl, **{self.label: valstr}))
                out_rows.append(row[: meta.num_steps])
        v = (np.stack(out_rows).astype(np.float32) if out_rows
             else np.zeros((0, meta.num_steps), np.float32))
        return [Grid(out_labels, meta.start_ms, meta.step_ms, meta.num_steps, v)]
