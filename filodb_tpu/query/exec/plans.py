"""ExecPlan tree (reference query/exec/ExecPlan.scala — execute:356 runs the
leaf's doExecute then folds transformers; NonLeafExecPlan:674 scatter-gathers
children. Here children run via a dispatcher abstraction so the same tree
shape serves in-process, mesh-sharded, and (later) remote execution).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ...core.filters import ColumnFilter
from ...core.schemas import METRIC_TAG, ColumnType
from ...ops import aggregations as AGG
from ...ops import staging as ST
from ..rangevector import Grid, QueryResult, QueryStats, RawGrid, ScalarResult
from .transformers import (
    _DROP_NAME_KEEP,
    AbsentFunctionMapper,
    PeriodicSamplesMapper,
    QueryError,
    _strip_metric,
    apply_binop,
)


@dataclass
class QueryContext:
    """Per-query execution context (reference QueryContext/QuerySession)."""

    memstore: Any  # TimeSeriesMemStore
    dataset: str
    max_series: int = 1_000_000
    max_samples: int = 500_000_000
    max_result_bytes: int = 1 << 30
    deadline_s: float = 60.0
    stats: QueryStats = field(default_factory=QueryStats)
    # fault tolerance (query/faults.py): tolerate lost children in merge
    # nodes, collecting structured warnings instead of aborting
    allow_partial_results: bool = False
    warnings: list = field(default_factory=list)
    # dispatch hooks: dispatcher wraps child execution (fault injection),
    # retry_policy/breakers override the defaults for remote children
    dispatcher: Any = None
    retry_policy: Any = None
    breakers: Any = None
    # tracing (metrics.py): the query's root Span. ExecPlan.execute falls
    # back to it as parent when a thread has no active span — the scheduler
    # pool hop between the engine and the root plan node
    trace_root: Any = None
    # cross-query micro-batching (query/scheduler.DispatchScheduler):
    # FusedAggregateExec routes its kernel launch through it so concurrent
    # queries sharing a superblock coalesce into ONE batched dispatch.
    # None (or a disabled scheduler) = the plain unbatched launch.
    dispatch_scheduler: Any = None
    # query observatory (obs/querylog.py): the per-query PhaseRecorder the
    # engine attaches (ExecPlan.execute re-binds it per thread alongside
    # stats) and the free-form path annotations (fused/fallback/batched/
    # grid class) execution drops for the query's cost record
    phases: Any = None
    obs: dict = field(default_factory=dict)
    _start_time: float = field(default_factory=time.monotonic)

    def check_deadline(self) -> None:
        """Enforced between plan nodes (reference per-plan enforcedLimits +
        query timeout)."""
        elapsed = time.monotonic() - self._start_time
        if elapsed > self.deadline_s:
            from .transformers import QueryDeadlineExceeded

            raise QueryDeadlineExceeded(
                f"query exceeded deadline: {elapsed:.1f}s > {self.deadline_s:.1f}s"
            )

    def remaining_deadline_s(self) -> float:
        """Unspent deadline budget — what retries and per-RPC timeouts must
        fit inside (never the full deadline_s)."""
        return max(0.0, self.deadline_s - (time.monotonic() - self._start_time))


class ExecPlan:
    """Base: leaf plans implement do_execute; transformers fold after."""

    transformers: list

    def __init__(self):
        self.transformers = []

    def execute(self, ctx: QueryContext) -> QueryResult:
        from ...metrics import (
            Span, activate_phases, activate_stats, current_span, span,
        )

        t0 = time.perf_counter_ns()
        ctx.check_deadline()
        # parent: the thread's active span (nested execution, or a pool
        # worker re-activated via metrics.activate), else the query's root
        # span (the engine -> scheduler-pool hop)
        parent = current_span() or ctx.trace_root
        # bind the query's stats as this thread's kernel-attribution target:
        # ops/ dispatch wrappers bump kernel_ns on it without any context
        # threading (pool workers re-enter here per child, so they bind
        # too); the phase recorder binds identically so phase-tagged spans
        # and the fused dispatch path decompose into the right query
        with activate_stats(ctx.stats), \
                activate_phases(getattr(ctx, "phases", None)), \
                span(type(self).__name__, parent=parent) as s:
            args = self.args_str()
            if args:
                s.tags["plan"] = args
            before = ctx.stats.snapshot()
            peer_stats = None
            res = self.do_execute(ctx)
            if res.stats is not ctx.stats and not res.stats.is_empty():
                # a remote child returns the peer's QueryStats in-band:
                # merge them into the query-wide stats exactly once, here,
                # then alias so a parent re-returning this result object
                # cannot double-merge
                peer_stats = res.stats.as_dict()
                ctx.stats.merge(res.stats)
                res.stats = ctx.stats
            rt = res.trace
            if rt is not None and not isinstance(rt, Span):
                # a remote child's span tree (rendered dict): stitch it
                # under this node's span, rewriting linkage into the local
                # trace — the cross-node half of trace propagation
                s.children.append(
                    Span.from_dict(rt, trace_id=s.trace_id, parent_id=s.span_id)
                )
                res.trace = None
            if res.warnings:
                # remote children return their own partial-result warnings
                # in-band; hoist them onto the context so they survive
                # transformer folding (which rebuilds QueryResults) and
                # reach the query's final result. Remote children run on
                # pool threads, so this must be a single atomic extend —
                # dedup happens once at the engine edge.
                ctx.warnings.extend(res.warnings)
            for tr in self.transformers:
                with span(type(tr).__name__) as ts:
                    targs = tr_args(tr)
                    if targs:
                        ts.tags["plan"] = targs
                    res = apply_transformer(tr, res, ctx)
            # remote child: the peer's own stats are exact attribution; local
            # nodes get the (inclusive, best-effort across concurrent
            # siblings) delta of the query-wide stats
            s.stats = peer_stats if peer_stats is not None else ctx.stats.delta_since(before)
        ctx.stats.bump(cpu_ns=time.perf_counter_ns() - t0)
        return res

    def do_execute(self, ctx: QueryContext) -> QueryResult:
        raise NotImplementedError

    def children(self) -> Sequence["ExecPlan"]:
        return ()

    # -- plan printing (reference printTree golden tests) -----------------

    def args_str(self) -> str:
        return ""

    def print_tree(self, level: int = 0) -> str:
        pad = "-" * level
        lines = []
        for tr in reversed(self.transformers):
            lines.append(f"{pad}T~{type(tr).__name__}({tr_args(tr)})")
            pad = "-" * (level + len(lines))
        lines.append(f"{pad}E~{type(self).__name__}({self.args_str()})")
        for c in self.children():
            lines.append(c.print_tree(level + len(lines)))
        return "\n".join(lines)


def tr_args(tr) -> str:
    if isinstance(tr, PeriodicSamplesMapper):
        return f"fn={tr.function} window={tr.window_ms} step={tr.step_ms}"
    return ""


def apply_transformer(tr, res: QueryResult, ctx: QueryContext) -> QueryResult:
    if isinstance(tr, PeriodicSamplesMapper):
        return QueryResult(grids=tr.apply_raw(res.raw_grids), stats=res.stats)
    if isinstance(tr, AbsentFunctionMapper):
        return QueryResult(grids=tr.apply(res.grids), stats=res.stats)
    out_grids = tr.apply(res.grids)
    return QueryResult(grids=out_grids, scalar=res.scalar, stats=res.stats)


# ---------------------------------------------------------------------------
# Leaf: select raw partitions from one shard and stage to device
# ---------------------------------------------------------------------------

# Counter staging is FUNCTION-driven (the reference applies counter correction
# only inside rate-family RangeFunctions — RateFunctions.scala:230 — never at
# the read path; a plain selector over a counter returns raw samples):
#   corrected — reset-corrected minus baseline; only these functions may read it
_CORRECTED_FNS = frozenset({"rate", "increase", "irate"})
#   shifted — raw minus per-series baseline (no correction): shift-invariant
#   functions get exact f32 math even on 1e15-magnitude counters
_SHIFTED_FNS = frozenset({
    "delta", "deriv",
    "stddev_over_time", "stdvar_over_time", "z_score",
    "median_absolute_deviation_over_time",
})
# value-independent functions (count/present/absent_over_time, timestamp)
# deliberately fall through to "raw": they never read staged values, so they
# share the plain-selector block and its cache entry
#   diff — f64-exact adjacent differences: these are pure functions of the
#   diff sequence, and no f32 shift of the values preserves both tiny
#   adjacent changes and a 1e9-magnitude reset cliff
_DIFF_FNS = frozenset({"changes", "resets", "idelta"})
#   everything else (plain selector/last, min/max/sum/avg_over_time,
#   quantile_over_time, ...) stages raw values


def _stage_mode_for_function(func: str | None) -> str:
    """Staging mode for a counter column given the range function that will
    read it (default: raw selector read)."""
    if func in _CORRECTED_FNS:
        return "corrected"
    if func in _SHIFTED_FNS:
        return "shifted"
    if func in _DIFF_FNS:
        return "diff"
    return "raw"


def _counter_stage_mode(transformers) -> str:
    """Pick the staging mode for a counter column from the range function the
    leaf's PeriodicSamplesMapper will apply (default: raw selector read)."""
    func = None
    for tr in transformers:
        if isinstance(tr, PeriodicSamplesMapper):
            func = tr.function
            break
    return _stage_mode_for_function(func)


def staged_block_for(ctx: "QueryContext", shard, ids, cache_key, col_name: str,
                     start_ms: int, end_ms: int, stage_mode: str):
    """Get a shard's HBM-resident staged block for a selection THROUGH the
    shard's staging cache: serve a clean hit, incrementally repair a dirty
    one (ST.append_to_block — live-edge panels pay only the tail), else
    stage fresh and insert under the shard-version check. The ONE cached
    staging path, shared by SelectRawPartitionsExec and the fused
    single-dispatch aggregate's superblock builder so both have identical
    repair/invalidation semantics.

    Cache-key layout ``(filters, start_ms, end_ms, ...)`` is load-bearing:
    the shard's selective invalidation (_invalidate_stage_range) reads
    k[1]/k[2] as the staged range for its overlap check."""
    with shard._lock:
        hit = shard.stage_cache.get(cache_key)
        version_at_stage = shard.version
        claimed = False
        if hit is not None and hit.repairing:
            # another thread is mid-repair: serving its pre-repair block
            # would miss acknowledged samples — restage fresh
            hit = None
        elif hit is not None and hit.dirty:
            dirty_lo = hit.dirty_lo
            hit.dirty = False
            hit.dirty_lo = hit.dirty_hi = None  # interval consumed by repair
            hit.repairing = True
            claimed = True
    if hit is not None and claimed:
        # in-range ingest landed since this block was staged: try the
        # incremental append repair; on failure fall through to a fresh
        # stage. The repair returns a NEW block (old one stays consistent
        # for in-flight readers) swapped in atomically.
        repaired = None
        try:
            repaired = ST.append_to_block(
                shard, hit.block, ids, col_name, end_ms, stage_mode,
                dirty_lo=dirty_lo,
            )
        finally:
            new_nbytes = (ST.staged_nbytes(repaired)
                          if repaired is not None else 0)
            with shard._lock:
                hit.repairing = False
                if repaired is not None:
                    hit.block = repaired
                    if new_nbytes != hit.nbytes:
                        # the repaired block's device arrays may be wider:
                        # keep entry bytes (and with them the ledger and
                        # the eviction budget) true to what is pinned. Only
                        # adjust the ledger while the entry is still CACHED
                        # — a concurrent clear/eviction during the unlocked
                        # repair already credited the old bytes, and this
                        # block is then transient (never ledger-pinned)
                        if shard.stage_cache.get(cache_key) is hit:
                            shard.ledger.free(hit.nbytes, reason="replace")
                            shard.ledger.alloc(new_nbytes)
                        hit.nbytes = new_nbytes
                elif shard.stage_cache.get(cache_key) is hit:
                    # failed (or raised): never leave a stale entry
                    del shard.stage_cache[cache_key]
                    shard.ledger.free(hit.nbytes, reason="drop")
        if repaired is None:
            hit = None
        else:
            ctx.stats.bump(cache_extends=1)
    if hit is not None:
        if not claimed:
            ctx.stats.bump(cache_hits=1)
        return hit.block
    block = ST.stage_from_shard(
        shard, ids, col_name, start_ms, end_ms, mode=stage_mode,
    )
    # true device footprint (ops/staging.staged_nbytes): the SAME number the
    # cache entry, the byte-budget eviction, and the device ledger account
    # — the drift check walks the cache with this exact function
    nbytes = ST.staged_nbytes(block)
    ctx.stats.bump(bytes_staged=nbytes, cache_misses=1)
    block.to_device(keep_host=True)  # mirrors enable append repair
    # byte-budgeted eviction, oldest entry first (the staging analog of
    # BlockManager reclaim under memory pressure). All cache mutations run
    # under the shard lock (the shard's selective invalidation iterates the
    # dict under it). The insert guard is INTERVAL-AWARE: an ingest that
    # landed mid-stage ran its invalidation before this entry existed, so
    # the entry may only be cached when the shard's effect log PROVES every
    # version bump since version_at_stage was disjoint from the staged
    # range (otherwise sustained fine-grained ingest — many small batches —
    # would drop every insert and starve the cache forever, re-paying full
    # stages despite the selective-invalidation machinery).
    with shard._lock:
        drop_reason = None
        if shard.version != version_at_stage:
            drop_reason = shard._ingest_effects_since_locked(
                version_at_stage, start_ms, end_ms
            )
        if drop_reason is None:
            from ...memstore.shard import StageEntry

            budget = getattr(shard.config, "stage_cache_bytes", 2 << 30)
            # a racing same-key stage (two queries sharing a leaf selector
            # both missed) may have inserted already: credit its entry or
            # the overwrite below would leak its ledger balance forever
            raced = shard.stage_cache.pop(cache_key, None)
            if raced is not None:
                shard.ledger.free(raced.nbytes, reason="replace")
            used = sum(e.nbytes for e in shard.stage_cache.values())
            while shard.stage_cache and used + nbytes > budget:
                oldest = next(iter(shard.stage_cache))
                evicted = shard.stage_cache.pop(oldest)
                used -= evicted.nbytes
                shard.ledger.free(evicted.nbytes, reason="evict")
            shard.stage_cache[cache_key] = StageEntry(block, nbytes)
            shard.ledger.alloc(nbytes)
    if drop_reason is not None:
        from ...metrics import record_stage_insert_drop

        record_stage_insert_drop(drop_reason)
    return block


class SelectRawPartitionsExec(ExecPlan):
    """reference MultiSchemaPartitionsExec:26 + SelectRawPartitionsExec:161 —
    schema discovery, partition lookup, then staging (rangeVectors analog).

    Produces a QueryResult carrying RawGrids (one per schema found)."""

    def __init__(
        self,
        shard_num: int,
        filters: Sequence[ColumnFilter],
        start_ms: int,
        end_ms: int,
        column: Optional[str] = None,
    ):
        super().__init__()
        self.shard_num = shard_num
        self.filters = tuple(filters)
        self.start_ms = start_ms
        self.end_ms = end_ms
        self.column = column

    def args_str(self) -> str:
        fs = ",".join(f"{f.column}{f.op}{f.value}" for f in self.filters)
        return f"shard={self.shard_num} filters=[{fs}] range=[{self.start_ms},{self.end_ms}]"

    def do_execute(self, ctx: QueryContext) -> QueryResult:
        shard = ctx.memstore.shard(ctx.dataset, self.shard_num)
        pids = shard.lookup_partitions(self.filters, self.start_ms, self.end_ms)
        column_override = None
        hist_bucket_le = None
        if not len(pids):
            # classic-histogram suffix rewrite (reference
            # MultiSchemaPartitionsExec :49-80): m_sum / m_count map to the
            # histogram schema's sum/count columns; m_bucket{le=...} selects
            # one bucket of the native histogram
            rewritten, column_override, hist_bucket_le = _histogram_suffix_rewrite(self.filters)
            if rewritten is not None:
                pids = shard.lookup_partitions(rewritten, self.start_ms, self.end_ms)
        if len(pids) > ctx.max_series:
            raise QueryError(f"query selects {len(pids)} series > limit {ctx.max_series}")
        if shard.odp_store is not None and len(pids):
            shard.odp_page_in(pids, self.start_ms, self.end_ms)
        # group by schema (multi-schema metric support)
        by_schema: dict[str, list[int]] = {}
        for pid in pids:
            part = shard.partition(int(pid))
            by_schema.setdefault(part.schema.name, []).append(int(pid))
        res = QueryResult()
        res.raw_grids = []
        for schema_name, ids in by_schema.items():
            # long local scans must respect the query deadline between
            # schema groups, not just at plan entry
            ctx.check_deadline()
            parts = [shard.partition(p) for p in ids]
            schema = parts[0].schema
            col_name = self.column or column_override or schema.value_column
            try:
                col = schema.column(col_name)
            except KeyError:
                col_name = schema.value_column
                col = schema.column(col_name)
            is_hist = col.ctype == ColumnType.HISTOGRAM
            is_counter = col.is_counter
            is_delta = col.is_delta
            stage_mode = (
                _counter_stage_mode(self.transformers)
                if is_counter and not is_delta and not is_hist
                else "raw"
            )
            # staging cache: repeated queries over the same selection reuse
            # the HBM-resident decoded block until new data LANDS IN RANGE
            # (the north-star "decoded chunk windows staged to HBM"; the
            # shard invalidates overlapping entries selectively on ingest —
            # shard._invalidate_stage_range — so live scrapes beyond a
            # historical panel's range never force a re-stage).
            cache_key = (
                self.filters, self.start_ms, self.end_ms, col_name, schema_name,
                stage_mode,
            )
            block = staged_block_for(
                ctx, shard, ids, cache_key, col_name, self.start_ms,
                self.end_ms, stage_mode,
            )
            ctx.stats.bump(
                series_scanned=len(ids),
                samples_scanned=int(np.asarray(block.lens).sum()),
            )
            if ctx.stats.samples_scanned > ctx.max_samples:
                raise QueryError(
                    f"query would scan {ctx.stats.samples_scanned} samples > "
                    f"limit {ctx.max_samples}"
                )
            les = parts[0].bucket_les if is_hist else None
            labels = [dict(p.tags) for p in parts]
            if is_hist and hist_bucket_le is not None and les is not None:
                # m_bucket{le=...}: slice one bucket into a scalar block
                sliced = _slice_bucket(block, les, hist_bucket_le)
                if sliced is None:
                    continue  # no such bucket
                block, le_str = sliced
                labels = [dict(l, le=le_str) for l in labels]
                is_hist = False
                is_counter = True
            res.raw_grids.append(
                RawGrid(
                    block=block,
                    labels=labels,
                    schema_name=schema_name,
                    value_column=col_name,
                    is_counter=is_counter,
                    is_delta=is_delta,
                    is_histogram=is_hist,
                    les=les if is_hist else None,
                )
            )
        return res


class EmptyResultExec(ExecPlan):
    def do_execute(self, ctx: QueryContext) -> QueryResult:
        return QueryResult()


class ChunkMetaExec(ExecPlan):
    """Chunk metadata debug query (reference SelectChunkInfosExec /
    _filodb_chunkmeta_all): per-series list of resident chunks."""

    def __init__(self, shard_num, filters, start_ms, end_ms):
        super().__init__()
        self.shard_num = shard_num
        self.filters = tuple(filters)
        self.start_ms = start_ms
        self.end_ms = end_ms

    def do_execute(self, ctx: QueryContext) -> QueryResult:
        shard = ctx.memstore.shard(ctx.dataset, self.shard_num)
        pids = shard.lookup_partitions(self.filters, self.start_ms, self.end_ms)
        out = []
        for pid in pids:
            part = shard.partition(int(pid))
            out.append(
                {
                    "labels": dict(part.tags),
                    "schema": part.schema.name,
                    "numChunks": len(part.chunks),
                    "bufferedSamples": part.num_samples() - sum(c.n for c in part.chunks),
                    "chunks": [
                        {"startTime": c.start_ts, "endTime": c.end_ts, "numRows": c.n,
                         "encodedBytes": c.nbytes_encoded}
                        for c in part.chunks_in_range(self.start_ms, self.end_ms)
                    ],
                }
            )
        res = QueryResult(metadata=out)
        res.result_type = "metadata"
        return res


class RawChunkExportExec(ExecPlan):
    """Top-level m[5m] raw export (reference SelectRawPartitionsExec without
    periodic mapping): returns actual samples."""

    def __init__(self, shard_num, filters, start_ms, end_ms, column=None):
        super().__init__()
        self.shard_num = shard_num
        self.filters = tuple(filters)
        self.start_ms = start_ms
        self.end_ms = end_ms
        self.column = column

    def do_execute(self, ctx: QueryContext) -> QueryResult:
        shard = ctx.memstore.shard(ctx.dataset, self.shard_num)
        pids = shard.lookup_partitions(self.filters, self.start_ms, self.end_ms)
        raw = []
        for pid in pids:
            part = shard.partition(int(pid))
            col = self.column or part.schema.value_column
            ts, vals = part.samples_in_range(self.start_ms, self.end_ms, col)
            if len(ts):
                raw.append((dict(part.tags), ts, vals))
        res = QueryResult(raw=raw)
        res.result_type = "matrix"
        return res


def _histogram_suffix_rewrite(filters):
    """m_sum/m_count/m_bucket -> base histogram metric + column/bucket
    selection. Returns (rewritten_filters | None, column | None, le | None)."""
    from ...core.schemas import METRIC_TAG

    metric = None
    for f in filters:
        if f.column == METRIC_TAG and f.op == "=":
            metric = f.value
    if metric is None:
        return None, None, None
    for suffix, col in (("_sum", "sum"), ("_count", "count"), ("_bucket", None)):
        if metric.endswith(suffix):
            base = metric[: -len(suffix)]
            le = None
            out = []
            for f in filters:
                if f.column == METRIC_TAG and f.op == "=":
                    out.append(ColumnFilter(METRIC_TAG, "=", base))
                elif suffix == "_bucket" and f.column == "le" and f.op == "=":
                    le = float("inf") if f.value in ("+Inf", "Inf") else float(f.value)
                else:
                    out.append(f)
            return tuple(out), col, le
    return None, None, None


# ---------------------------------------------------------------------------
# Non-leaf plans
# ---------------------------------------------------------------------------


class NonLeafExecPlan(ExecPlan):
    # merge nodes whose semantics tolerate losing a child under
    # ctx.allow_partial_results (shard/peer partials are mergeable);
    # structural nodes (joins, scalar ops, stitches) keep all-or-nothing
    supports_partial = False

    def __init__(self, child_plans: Sequence[ExecPlan]):
        super().__init__()
        self.child_plans = list(child_plans)

    def children(self):
        return self.child_plans

    @staticmethod
    def _annotate_child_error(child: ExecPlan, e: Exception) -> Exception:
        """Wrap the first child failure with the child's identity so a
        scatter-gather error names its shard/endpoint; the exception TYPE is
        preserved (deadline/rejection still map to their status codes)."""
        note = f"{type(child).__name__}({child.args_str()})"
        msg = str(e.args[0]) if e.args else str(e)
        if note not in msg:
            e.args = (f"{msg} [child {note}]",) + tuple(e.args[1:])
        return e

    def execute_children(self, ctx: QueryContext) -> list[QueryResult]:
        """Children execute in order, EXCEPT network-bound children (remote
        execs mark ``is_remote``) which dispatch concurrently on IO threads —
        the reference runs children as concurrent monix Tasks; here local
        children share the device serially while peer round-trips overlap.

        All execution flows through faults.dispatch_child (fault-injection
        hook + per-endpoint breaker/retries for remote children). The first
        failure cancels remaining in-flight futures and re-raises annotated
        with the child's args_str(); under ctx.allow_partial_results, merge
        nodes (supports_partial) instead record a structured warning per
        lost child and return the survivors."""
        from ...metrics import activate, current_span
        from ..faults import child_warning, dispatch_child
        from .transformers import QueryDeadlineExceeded

        children = self.child_plans
        allow_partial = (
            self.supports_partial and getattr(ctx, "allow_partial_results", False)
        )
        remote_idx = [
            i for i, c in enumerate(children) if getattr(c, "is_remote", False)
        ]
        results: dict[int, QueryResult] = {}
        failures: list[tuple[int, Exception]] = []
        pool = futs = None
        # capture the dispatching span: pool workers have no thread-local
        # trace context, so each re-activates it before executing — child
        # spans attach under this node instead of starting orphan traces
        parent_span = current_span()

        def dispatch_traced(child):
            with activate(parent_span):
                return dispatch_child(child, ctx)

        if remote_idx and len(children) >= 2:
            from concurrent.futures import ThreadPoolExecutor

            pool = ThreadPoolExecutor(max_workers=min(8, len(remote_idx)),
                                      thread_name_prefix="filodb-remote")
            futs = {i: pool.submit(dispatch_traced, children[i])
                    for i in remote_idx}
        try:
            for i, c in enumerate(children):
                if futs is not None and i in futs:
                    continue
                try:
                    results[i] = dispatch_child(c, ctx)
                except QueryDeadlineExceeded as e:
                    # OUR spent budget is a query-level condition, never a
                    # "lost child": a timeout must not degrade into a 200
                    # partial success. But a child's deadline error while
                    # origin budget remains (a peer with a stricter local
                    # deadline) is just a slow child — partial-eligible.
                    if not allow_partial or ctx.remaining_deadline_s() <= 0:
                        raise self._annotate_child_error(c, e)
                    failures.append((i, e))
                except Exception as e:  # noqa: BLE001 — classified below
                    failures.append((i, e))
                    if not allow_partial:
                        raise self._annotate_child_error(c, e)
            if futs is not None:
                from concurrent.futures import as_completed

                fut_to_idx = {f: i for i, f in futs.items()}
                # consume in COMPLETION order: a failed future surfaces
                # immediately instead of blocking behind slower siblings
                for f in as_completed(futs.values()):
                    i = fut_to_idx[f]
                    try:
                        results[i] = f.result()
                    except QueryDeadlineExceeded as e:
                        if not allow_partial or ctx.remaining_deadline_s() <= 0:
                            raise self._annotate_child_error(children[i], e)
                        failures.append((i, e))
                    except Exception as e:  # noqa: BLE001
                        failures.append((i, e))
                        if not allow_partial:
                            raise self._annotate_child_error(children[i], e)
        finally:
            if pool is not None:
                # on error: unstarted futures never run, and we do NOT block
                # waiting for hung RPCs — in-flight calls finish on their own
                # per-RPC deadlines (always <= the remaining query budget)
                pool.shutdown(wait=False, cancel_futures=True)
        if failures:
            if len(failures) == len(children):
                # nothing survived: a fully-failed merge is an error even
                # under allow_partial_results
                i, e = failures[0]
                raise self._annotate_child_error(children[i], e)
            for i, e in failures:
                w = child_warning(children[i], e)
                ctx.warnings.append(w)
                if parent_span is not None:
                    # partial-result drops annotate the merge node's span so
                    # EXPLAIN ANALYZE / the slow-query log show which
                    # children were lost and why
                    parent_span.tags.setdefault("lost_children", []).append(w)
        return [results[i] for i in sorted(results)]


class DistConcatExec(NonLeafExecPlan):
    """Concatenate child results (reference DistConcatExec)."""

    supports_partial = True  # shard-disjoint series: survivors are exact

    def do_execute(self, ctx: QueryContext) -> QueryResult:
        out = QueryResult()
        out.raw_grids = []
        for r in self.execute_children(ctx):
            out.grids.extend(r.grids)
            if getattr(r, "raw_grids", None):
                out.raw_grids.extend(r.raw_grids)
            if r.raw:
                out.raw = (out.raw or []) + r.raw
            if r.scalar is not None:
                out.scalar = r.scalar
            if r.metadata is not None:
                out.metadata = (out.metadata or []) + r.metadata
                out.result_type = r.result_type
        return out


class StitchRvsExec(NonLeafExecPlan):
    """Merge results of time-split children: same series, disjoint step
    ranges (reference StitchRvsExec:177)."""

    def do_execute(self, ctx: QueryContext) -> QueryResult:
        results = self.execute_children(ctx)
        results = [r for r in results if r.grids]
        if not results:
            return QueryResult()
        # build the union step grid
        key_to_row: dict[tuple, dict] = {}
        step = results[0].grids[0].step_ms
        starts = [g.start_ms for r in results for g in r.grids]
        ends = [g.start_ms + (g.num_steps - 1) * g.step_ms for r in results for g in r.grids]
        start, end = min(starts), max(ends)
        nsteps = int((end - start) // step) + 1
        for r in results:
            for g in r.grids:
                v = g.values_np()
                off = int((g.start_ms - start) // step)
                for i, lbls in enumerate(g.labels):
                    key = tuple(sorted(lbls.items()))
                    row = key_to_row.setdefault(key, {"labels": lbls, "vals": np.full(nsteps, np.nan, np.float32)})
                    row["vals"][off : off + g.num_steps] = np.where(
                        np.isnan(row["vals"][off : off + g.num_steps]), v[i], row["vals"][off : off + g.num_steps]
                    )
        labels = [r["labels"] for r in key_to_row.values()]
        vals = np.stack([r["vals"] for r in key_to_row.values()]) if key_to_row else np.zeros((0, nsteps), np.float32)
        return QueryResult(grids=[Grid(labels, start, step, nsteps, vals)])


# ---------------------------------------------------------------------------
# Aggregation
# ---------------------------------------------------------------------------

# ops whose partial state is mergeable across shards: op -> components
_PARTIAL_COMPONENTS = {
    "sum": ("sum",),
    "count": ("count",),
    "min": ("min",),
    "max": ("max",),
    "group": ("group",),
    "avg": ("sum", "count"),
    "stddev": ("sum", "sumsq", "count"),
    "stdvar": ("sum", "sumsq", "count"),
}


def _partial_aggregate(op: str, grids: list[Grid], by, without):
    """Leaf-side map phase: per-grid segment reduce into label groups.
    Returns (group_labels, components dict name -> [G, J] np arrays, grid
    meta). Native-histogram sums additionally carry a "hist" [G, J, B]
    component (reference HistSumRowAggregator)."""
    if not grids:
        return [], {}, None
    meta = grids[0]
    all_labels: list[dict] = []
    hists = [] if any(g.hist is not None for g in grids) else None
    if len(grids) == 1 and hists is None:
        # single-grid fast path: slice on device, never fetch the full
        # [S, J] grid to host — only the [G, J] partials come back
        g = grids[0]
        all_labels = list(g.labels)
        vals = g.values[: g.n_series, : g.num_steps]
    else:
        mats: list[np.ndarray] = []
        for g in grids:
            all_labels.extend(g.labels)
            mats.append(g.values_np())
            if hists is not None:
                h = g.hist_np()
                if h is None:
                    raise QueryError("cannot aggregate histogram and scalar series together")
                hists.append(h)
        J = max(m.shape[1] for m in mats)
        vals = np.full((len(all_labels), J), np.nan, np.float32)
        r = 0
        for m in mats:
            vals[r : r + m.shape[0], : m.shape[1]] = m
            r += m.shape[0]
    gids, group_labels = AGG.group_ids_for(all_labels, list(by) if by else None, list(without) if without else None)
    G = len(group_labels)
    comps: dict[str, np.ndarray] = {}
    need = _PARTIAL_COMPONENTS[op]
    for comp in need:
        if comp == "sumsq":
            out = np.asarray(AGG.segment_aggregate("sum", jnp.asarray(vals) ** 2, gids, G))
        elif comp == "group":
            out = np.asarray(AGG.segment_aggregate("group", vals, gids, G))
        else:
            out = np.asarray(AGG.segment_aggregate(comp, vals, gids, G))
        comps[comp] = out
    if hists is not None:
        if op != "sum":
            raise QueryError(f"aggregation {op} not supported over native histograms (use sum)")
        from ...core.histograms import unify_schemes

        les_list = [g.les for g in grids if g.les is not None]
        if len(les_list) == len(grids):
            # heterogeneous bucket schemes in one gather: unify onto the
            # union bounds (same rule as the fused superblock concat)
            unified, union, changed = unify_schemes(hists, les_list)
            if changed:
                from dataclasses import replace as _replace

                hists = unified
                meta = _replace(meta, les=union)
        H = np.concatenate(hists, axis=0)  # [S, J, B]
        S, Jh, B = H.shape
        flat = np.asarray(
            AGG.segment_aggregate("sum", jnp.asarray(H.reshape(S, Jh * B)), gids, G)
        )
        comps["hist"] = flat.reshape(G, Jh, B)
    return group_labels, comps, meta


def _unify_hist_partials(partials):
    """Pre-pass for _merge_partials: shard/peer partials carrying ``hist``
    components on DIFFERENT bucket schemes remap onto the union bounds
    (core.histograms.remap_buckets — the one unification rule, shared with
    the fused superblock concat) so the component-wise merge below adds
    aligned buckets."""
    hist_idx = [
        i for i, (_, comps, m) in enumerate(partials)
        if "hist" in comps and m is not None and m.les is not None
    ]
    if len(hist_idx) <= 1:
        return partials
    from ...core.histograms import unify_schemes

    unified, union, changed = unify_schemes(
        [partials[i][1]["hist"] for i in hist_idx],
        [partials[i][2].les for i in hist_idx],
    )
    if not changed:
        return partials
    from dataclasses import replace as _replace

    out = list(partials)
    for i, h in zip(hist_idx, unified):
        gl, comps, m = partials[i]
        comps = dict(comps)
        comps["hist"] = h
        out[i] = (gl, comps, _replace(m, les=union))
    return out


def _merge_partials(op: str, partials):
    """Reduce phase: merge shard partials by group label key."""
    key_to: dict[tuple, dict] = {}
    meta = None
    partials = _unify_hist_partials(partials)
    for group_labels, comps, m in partials:
        if m is not None:
            meta = m
        for gi, lbls in enumerate(group_labels):
            key = tuple(sorted(lbls.items()))
            slot = key_to.setdefault(key, {"labels": lbls, "comps": {}})
            for name, arr in comps.items():
                cur = slot["comps"].get(name)
                row = arr[gi]
                if cur is None:
                    slot["comps"][name] = row.copy()
                else:
                    if name in ("sum", "count", "sumsq", "hist", "sketch"):
                        slot["comps"][name] = np.where(
                            np.isnan(cur), row, np.where(np.isnan(row), cur, cur + row)
                        )
                    elif name == "min":
                        slot["comps"][name] = np.fmin(cur, row)
                    elif name == "max":
                        slot["comps"][name] = np.fmax(cur, row)
                    elif name == "group":
                        slot["comps"][name] = np.fmax(cur, row)
    return key_to, meta


def _present(op: str, key_to, meta) -> QueryResult:
    if meta is None:
        return QueryResult()
    labels, rows, hist_rows = [], [], []
    has_hist = False
    for slot in key_to.values():
        c = slot["comps"]
        if "hist" in c:
            has_hist = True
            hist_rows.append(c["hist"])
            v = np.full(c["hist"].shape[0], np.nan, np.float32)
        elif op in ("sum", "count", "min", "max", "group"):
            v = c[op]
        elif op == "avg":
            v = c["sum"] / c["count"]
        elif op in ("stddev", "stdvar"):
            mean = c["sum"] / c["count"]
            var = c["sumsq"] / c["count"] - mean**2
            var = np.maximum(var, 0.0)
            v = var if op == "stdvar" else np.sqrt(var)
        labels.append(slot["labels"])
        rows.append(v)
    vals = np.stack(rows) if rows else np.zeros((0, meta.num_steps), np.float32)
    hist = np.stack(hist_rows) if has_hist and hist_rows else None
    return QueryResult(
        grids=[Grid(labels, meta.start_ms, meta.step_ms, meta.num_steps, vals,
                    hist=hist, les=meta.les if has_hist else None)]
    )


@dataclass
class AggregateMapReduce:
    """Transformer form of the map phase, pushed onto shard leaves
    (reference AggregateMapReduce)."""

    op: str
    by: tuple | None
    without: tuple | None

    def apply(self, grids: list[Grid]) -> list[Grid]:
        # emits a "partial grid" whose values are the partial components,
        # encoded as stacked rows with __comp__ labels
        group_labels, comps, meta = _partial_aggregate(self.op, grids, self.by, self.without)
        return partials_to_grids(group_labels, comps, meta)


# component names whose [G, J, B] payload rides the Grid.hist field
_CUBE_COMPS = ("hist", "sketch")


def partials_to_grids(group_labels, comps, meta) -> list[Grid]:
    """Encode per-group partial components as ``__comp__``-labeled grids —
    the ONE wire/in-memory form for mergeable aggregation state, shared by
    the shard map phase, the peer-level PartialAggregate executor, and the
    gRPC result frames (reference: serialized RangeVectorAggregator partial
    AggregateItems)."""
    if meta is None:
        return []
    out = []
    for name, arr in comps.items():
        is_cube = name in _CUBE_COMPS
        out.append(
            Grid(
                [dict(l, __comp__=name) for l in group_labels],
                meta.start_ms,
                meta.step_ms,
                meta.num_steps,
                arr if not is_cube else np.full(arr.shape[:2], np.nan, np.float32),
                hist=arr if is_cube else None,
                les=meta.les if name == "hist" else None,
            )
        )
    return out


def collect_partials(result: QueryResult, default_op: str):
    """Decode a child's ``__comp__``-labeled grids back into the
    (group_labels, comps, meta) partial form (inverse of
    partials_to_grids). Rows without a __comp__ label are treated as
    already-final values of ``default_op`` — the exact-re-aggregation form
    sum/min/max/group peers return."""
    meta = None
    comp_rows: dict[str, dict[tuple, np.ndarray]] = {}
    labels_by_key: dict[tuple, dict] = {}
    for g in result.grids:
        if g.les is not None or meta is None:
            meta = g
        v = g.values_np()
        h = g.hist_np()
        for i, l in enumerate(g.labels):
            comp = l.get("__comp__", default_op)
            base = {k: x for k, x in l.items() if k != "__comp__"}
            key = tuple(sorted(base.items()))
            labels_by_key[key] = base
            comp_rows.setdefault(comp, {})[key] = (
                h[i] if comp in _CUBE_COMPS else v[i]
            )
    if meta is None:
        return None
    keys = list(labels_by_key)
    group_labels = [labels_by_key[k] for k in keys]
    comps = {}
    for comp, rows in comp_rows.items():
        proto = next(iter(rows.values()))
        comps[comp] = np.stack([
            rows.get(k, np.full(proto.shape, np.nan, np.float32)) for k in keys
        ])
    return group_labels, comps, meta


class ReduceAggregateExec(NonLeafExecPlan):
    """reference ReduceAggregateExec + RangeVectorAggregator.mapReduce."""

    supports_partial = True  # __comp__ partials merge over any child subset

    def __init__(self, child_plans, op: str, by=None, without=None):
        super().__init__(child_plans)
        self.op = op
        self.by = by
        self.without = without

    def args_str(self) -> str:
        return f"op={self.op} by={self.by} without={self.without}"

    def do_execute(self, ctx: QueryContext) -> QueryResult:
        partials = []
        for r in self.execute_children(ctx):
            # children emit partial grids tagged with __comp__ (rows without
            # the tag are exact-re-aggregation peer results of self.op)
            p = collect_partials(r, self.op)
            if p is not None:
                partials.append(p)
        key_to, meta = _merge_partials(self.op, partials)
        return _present(self.op, key_to, meta)


@dataclass
class SuperblockEntry:
    """One cached cross-shard superblock + everything do_execute needs to
    dispatch on it (SuperblockCache value)."""

    block: Any  # ST.StagedBlock, [ΣS, T] or [ΣS, T, B] device-resident
    labels: list  # [ΣS] per-series label dicts
    is_counter: bool
    is_delta: bool
    samples: int  # scanned sample count (stats/limit accounting; PRE-slice,
    # like the reference path — a le= slice that drops a shard still scanned it)
    max_shard_series: int  # per-shard limit re-enforcement on cache hits
    series: int = 0  # scanned series count (pre-slice, see samples)
    is_hist: bool = False
    les: Any = None  # [B] unified bucket bounds (histogram blocks)
    les_dev: Any = None  # device f32 copy for the fused quantile epilogue
    # incremental-extension inputs (ST.extend_superblock): the resolved
    # value column and staging mode the member blocks were staged with.
    # stage_mode None marks entries that can never extend (le=-sliced
    # bucket superblocks) — they still revalidate on disjoint ingest.
    col_name: str | None = None
    stage_mode: str | None = None


def _unify_hist_blocks(blocks, block_les):
    """Put per-shard histogram blocks on ONE bucket scheme: the union of the
    shards' ``le`` bounds, missing bounds completed from the nearest lower
    bound (core.histograms.remap_buckets — the same rule the reference
    partial-merge path applies, so fused and reference stay bit-identical).
    Returns (blocks', union_les); blocks with the union scheme pass through
    untouched."""
    from ...core.histograms import remap_buckets, unify_schemes

    vals_in = [np.asarray(b.vals) for b in blocks]
    vals_out, union, changed = unify_schemes(vals_in, block_les)
    if not changed:
        return blocks, union
    out = []
    for b, v_in, v_out, l in zip(blocks, vals_in, vals_out, block_les):
        if v_out is v_in:  # already on the union scheme
            out.append(b)
            continue
        baseline = np.asarray(b.baseline)
        if baseline.ndim == 2:
            baseline = remap_buckets(baseline, l, union)
        # remapping touches only the bucket axis: the shared regular time
        # grid (the fused shared-window fast path) survives verbatim
        out.append(ST.StagedBlock(
            np.asarray(b.ts), v_out, np.asarray(b.lens), b.base_ms, baseline,
            b.n_series, list(b.part_refs), regular_ts=b.regular_ts,
        ))
    return out, union


def _uniform_scheme(parts, les) -> bool:
    """True when every partition in a shard carries the SAME bucket scheme
    (core.histograms.same_scheme). A shard mixing schemes (mid-rollout
    bound change) cannot stage as one [S, T, B] block with a single ``le``
    vector — the fused path must fall back rather than silently attribute
    one scheme's counts to another's bounds."""
    from ...core.histograms import same_scheme

    if les is None:
        return False
    for p in parts[1:]:
        other = p.bucket_les
        if other is None:
            return False
        if other is not les and not same_scheme(other, les):
            return False
    return True


def _slice_bucket(block, les, bucket_le: float):
    """``m_bucket{le=...}``: slice one bucket of a staged [S, T, B] block
    into a scalar counter block — the ONE definition of le-selection
    semantics, shared by the fused builder and SelectRawPartitionsExec.
    Returns (block, le_label) or None when the scheme has no such bound
    (same tolerance as histogram_bucket)."""
    from ...core.histograms import _LE_TOL

    if les is None:
        return None
    les64 = np.asarray(les, dtype=np.float64)
    if np.isinf(bucket_le):
        b_idx = len(les64) - 1
    else:
        hits = np.nonzero(np.abs(les64 - bucket_le) < _LE_TOL)[0]
        b_idx = int(hits[0]) if len(hits) else -1
    if b_idx < 0:
        return None
    vals3 = np.asarray(block.vals)
    scalar_vals = np.ascontiguousarray(vals3[..., b_idx])
    baseline = np.asarray(block.baseline)
    sliced = ST.StagedBlock(
        block.ts, scalar_vals, block.lens, block.base_ms,
        baseline[..., b_idx] if baseline.ndim == 2 else baseline,
        block.n_series, block.part_refs, raw=scalar_vals,
        regular_ts=block.regular_ts,
        # a jittered hist block's grid metadata survives the slice so the
        # scalar jitter fused variant stays available for m_bucket{le=...}
        nominal_ts=block.nominal_ts, ts_dev=block.ts_dev,
        maxdev_ms=block.maxdev_ms,
    )
    le_str = "+Inf" if np.isinf(les64[b_idx]) else f"{les64[b_idx]:g}"
    return sliced, le_str


# incremental superblock extension under live ingest (escape hatch: set
# FILODB_SUPERBLOCK_EXTEND=0 to restore invalidate-and-rebuild; also skips
# the superblock's host mirrors, halving its host-memory footprint)
_SUPERBLOCK_EXTEND = os.environ.get("FILODB_SUPERBLOCK_EXTEND", "1") != "0"

# aggregation ops the fused single-dispatch path computes exactly as one
# on-device segment reduce (ops/aggregations.fused_range_aggregate)
FUSED_AGG_OPS = frozenset({"sum", "count", "avg", "min", "max"})

# aggregation ops the fused path computes as a device-side EPILOGUE fused
# into the same program (ops/aggregations.fused_topk / fused_quantile):
# only [k, J] / [G, J] arrays ever reach the host
FUSED_EPI_OPS = frozenset({"topk", "bottomk", "quantile"})

# range functions the fused path supports: everything the shape-static range
# kernels compute on device, minus host-path timestamp, per-window sorts,
# absent_over_time (needs the presence reduce, not a value aggregate), and
# arg-taking functions (the planner also rejects function_args)
FUSED_FUNCS = frozenset({
    "rate", "increase", "delta", "irate", "idelta",
    "sum_over_time", "avg_over_time", "count_over_time", "min_over_time",
    "max_over_time", "last", "last_over_time", "first_over_time",
    "present_over_time", "stddev_over_time", "stdvar_over_time", "z_score",
    "changes", "resets", "deriv",
})


def fused_mesh_supported(mesh, op: str, function) -> bool:
    """Whether the mesh-sharded fused program models this aggregate: a 1-D
    device mesh, a fused op (simple aggregates psum their [G, J] partials;
    topk/quantile epilogues combine winner/multiset state across devices),
    and a fused range function. The ONE gate shared by the planner, the
    parallel/ engines' delegation, and FusedAggregateExec's runtime check
    (fallback reason ``mesh_unsupported``)."""
    if mesh is None or len(getattr(mesh, "axis_names", ())) != 1:
        return False
    if op not in FUSED_AGG_OPS and op not in FUSED_EPI_OPS:
        return False
    return function is None or function in FUSED_FUNCS


class FusedAggregateExec(ExecPlan):
    """Single-dispatch cross-shard aggregate (the tentpole of the
    superblock path): ``op by (...) (func(selector[w]))`` over local shards
    executes as ONE compiled program over ONE device-resident superblock —
    O(1) kernel launches instead of O(shards) stage->kernel->partial-merge
    round trips, and only the [G, J] group partials ever reach the host.

    The superblock (ops/staging.concat_blocks) is cached on the memstore
    keyed by the member shards' version vector (ops/staging.SuperblockCache);
    per-shard blocks flow through the SAME cached staging path as
    SelectRawPartitionsExec (staged_block_for), so dirty shards repair
    incrementally via append_to_block before re-concatenation. Label
    grouping memoizes on the superblock (ops/aggregations.group_ids_memo).

    Histogram schemas run the 3-D variant: per-shard ``[S, T, B]`` bucket
    blocks concatenate into one ``[ΣS, T, B]`` superblock (heterogeneous
    ``le`` schemes unified onto the union bounds first,
    core.histograms.remap_buckets) and one compiled hist range_fn ->
    per-bucket segment-sum program returns [G, J, B] partials — or, with
    ``hist_quantile`` set (the planner recognized
    ``histogram_quantile(q, sum by (...) (rate(m_bucket[w])))``), just the
    [G, J] interpolated quantile grid. ``topk``/``bottomk``/``quantile``
    aggregates fuse their epilogue the same way (FUSED_EPI_OPS).

    ``fallback`` is the reference tree
    (ReduceAggregateExec -> N x SelectRawPartitionsExec); execution falls
    back to it — annotating the span with the reason and bumping
    ``filodb_fused_fallback_total{reason=...}`` — for partial-results
    mode, fault-injection dispatchers, mixed schemas, or anything else the
    fused kernel doesn't model (doc/perf.md lists the reason taxonomy). It
    is passed as a zero-arg factory and materialized lazily on first use:
    the happy path must not pay plan-time construction of O(shards) leaves
    it discards (at 128 shards that is exactly the linear cost this node
    removes)."""

    def __init__(self, shard_nums, filters, raw_start_ms: int, raw_end_ms: int,
                 column, op: str, by, without, function,
                 start_ms: int, end_ms: int, step_ms: int, window_ms: int,
                 offset_ms: int, fallback, params=(),
                 hist_quantile: float | None = None, mesh=None):
        super().__init__()
        self.shard_nums = list(shard_nums)
        self.filters = tuple(filters)
        self.raw_start_ms = raw_start_ms
        self.raw_end_ms = raw_end_ms
        self.column = column
        self.op = op
        self.by = by
        self.without = without
        self.function = function  # None = plain selector (lookback last)
        self.start_ms = start_ms
        self.end_ms = end_ms
        self.step_ms = step_ms
        self.window_ms = window_ms
        self.offset_ms = offset_ms
        self.params = tuple(params)  # k for topk/bottomk, q for quantile
        self.hist_quantile = hist_quantile  # fused histogram_quantile(q, ..)
        # 1-D device mesh (parallel.mesh.series_mesh): the superblock's
        # series axis partitions across it and the fused program runs under
        # shard_map — ONE dispatch spanning every device. None = the
        # single-device fused path.
        self.mesh = mesh
        self._fallback_factory = fallback
        self._fallback: ExecPlan | None = None

    @property
    def fallback(self) -> ExecPlan:
        if self._fallback is None:
            self._fallback = self._fallback_factory()
        return self._fallback

    def args_str(self) -> str:
        fs = ",".join(f"{f.column}{f.op}{f.value}" for f in self.filters)
        extra = f" params={self.params}" if self.params else ""
        if self.hist_quantile is not None:
            extra += f" hist_q={self.hist_quantile}"
        if self.mesh is not None:
            extra += f" mesh={self.mesh.devices.size}"
        return (
            f"op={self.op} fn={self.function} by={self.by} "
            f"without={self.without} shards={self.shard_nums} filters=[{fs}]"
            f"{extra}"
        )

    def _fall(self, ctx: QueryContext, reason: str) -> QueryResult:
        from ...metrics import current_span, record_fused_fallback

        s = current_span()
        if s is not None:
            s.tags["fused_fallback"] = reason
        # query-observatory path annotation: the cost record carries WHY
        # this query left the fused path (obs/querylog.py)
        obs = getattr(ctx, "obs", None)
        if obs is not None:
            obs["path"] = "fallback"
            obs["fallback"] = reason
        record_fused_fallback(reason)
        return self.fallback.execute(ctx)

    def num_steps(self) -> int:
        return int((self.end_ms - self.start_ms) // self.step_ms) + 1

    def _unsupported_shape(self, is_hist: bool) -> str | None:
        """Fallback reason when the fused kernels don't model this
        op/function on the resolved schema, or None when fused dispatch can
        proceed. Decided BEFORE the stats bump (the reference tree bumps
        its own scan stats — deciding later would double-count against
        per-request limits) and, on cold builds, before any staging (a
        discarded [ΣS, T, B] build would evict cache entries for nothing)."""
        from ...ops.hist_kernels import FUSED_HIST_FUNCS

        if self.raw_end_ms - self.raw_start_ms > ST.MAX_STAGE_SPAN_MS:
            # staged timestamps are int32 ms offsets from the selector start
            # (ops/staging.py): a wider selection cannot be represented —
            # offsets would wrap and searchsorted over the no-longer-sorted
            # vector silently empties late windows. The reference tree
            # windows over the same staged offsets, so falling back does
            # NOT help; Planner.materialize time-slices such ranges before
            # any exec is built, making this a defense-in-depth guard for
            # plans assembled outside materialize. (Spans this wide are
            # the rollup tier's job.)
            return "stage_span"
        if is_hist:
            # hist kernel models only plain sum over the hist range funcs
            if self.op != "sum" or self.params:
                return "hist_op"
            if (self.function or "last") not in FUSED_HIST_FUNCS:
                return "hist_func"
        elif self.hist_quantile is not None:
            # planner recognized histogram_quantile over this aggregate but
            # the selection resolved to a scalar schema. With ``le`` in the
            # grouping these are CLASSIC bucket series (e.g. a self-scraped
            # *_bucket family in _system): the fused agg computes the
            # by-(le,...) partials as ONE dispatch and the classic
            # interpolation folds them on host (transformers.
            # classic_histogram_quantile — same kernel as the native path).
            # Without ``le`` the shape is unanswerable; the reference tree
            # raises the proper "needs native-histogram input" QueryError.
            if "le" not in tuple(self.by or ()):
                return "hist_quantile_scalar"
        return None

    def _serve_hit(self, ctx: QueryContext, hit: "SuperblockEntry"):
        """Limit + stats enforcement for a cached superblock: limits are
        PER REQUEST (execute_plan narrows them), so a cache hit must never
        serve a query whose limits the build path would have rejected.
        Returns a fallback-reason string instead when this query's op/func
        can't dispatch on the cached block's schema."""
        reason = self._unsupported_shape(hit.is_hist)
        if reason is not None:
            return reason
        if hit.max_shard_series > ctx.max_series:
            raise QueryError(
                f"query selects {hit.max_shard_series} series > limit "
                f"{ctx.max_series}"
            )
        ctx.stats.bump(series_scanned=hit.series or hit.block.n_series,
                       samples_scanned=hit.samples)
        if ctx.stats.samples_scanned > ctx.max_samples:
            raise QueryError(
                f"query would scan {ctx.stats.samples_scanned} samples > "
                f"limit {ctx.max_samples}"
            )
        return hit

    def _superblock(self, ctx: QueryContext, stage_mode: str):
        """SuperblockEntry from the shard-version-keyed superblock cache,
        rebuilding through the per-shard cached staging path on miss.
        Returns a fallback-reason string instead when the selection needs
        the reference tree, or None for an empty selection."""
        cache = getattr(ctx.memstore, "_superblock_cache", None)
        if cache is None:
            cache = ST.SuperblockCache()
            ctx.memstore._superblock_cache = cache
        # resolved-mode keying: for non-counter columns every function
        # stages "raw", so keying purely on the function-derived mode would
        # cache byte-identical superblocks under distinct keys. The schema
        # hint learned on first build collapses them; actual staging modes
        # always re-derive from the live schema.
        hints = getattr(ctx.memstore, "_fused_mode_hints", None)
        if hints is None:
            hints = {}
            ctx.memstore._fused_mode_hints = hints
        hint_key = (ctx.dataset, self.filters, self.column)
        hint = hints.get(hint_key)
        key_mode = stage_mode
        if hint is not None and not (hint[0] and not hint[1]):
            key_mode = "raw"  # known gauge / delta-temporality column
        # sharded and single-device superblocks are distinct cache entries:
        # placement (and the mesh-divisible padding) differs even over the
        # identical selection, and engines sharing one memstore may run both
        mesh_desc = (
            None if self.mesh is None
            else (self.mesh.axis_names[0],
                  tuple(d.id for d in self.mesh.devices.flat))
        )
        sb_key = (
            ctx.dataset, tuple(self.shard_nums), self.filters,
            self.raw_start_ms, self.raw_end_ms, self.column, key_mode,
            mesh_desc,
        )
        # standing-query refresh contexts carry a pin sink: the maintainer
        # pins the key it resolves to (by standing qid) so ad-hoc eviction
        # storms can't churn the entry its delta refresh extends in place
        pin_sink = getattr(ctx, "superblock_pin_sink", None)
        if pin_sink is not None:
            pin_sink(cache, sb_key)
        versions = tuple(
            ctx.memstore.shard(ctx.dataset, s).version for s in self.shard_nums
        )
        hit = cache.get(sb_key, versions)
        if hit is not None:
            ctx.stats.bump(cache_hits=1)
            return self._serve_hit(ctx, hit)
        # single-flight per key: N identical cold queries must not each
        # concatenate + upload the full superblock (the same duplicate-
        # construction class as the _get_wm / window_matrices races)
        with cache.build_lock(sb_key):
            versions = tuple(
                ctx.memstore.shard(ctx.dataset, s).version
                for s in self.shard_nums
            )
            hit = cache.get(sb_key, versions)
            if hit is not None:
                ctx.stats.bump(cache_hits=1)
                return self._serve_hit(ctx, hit)
            refreshed = self._refresh_superblock(ctx, cache, sb_key, versions)
            if refreshed is not None:
                return refreshed
            return self._build_superblock(
                ctx, stage_mode, cache, sb_key, versions, hints, hint_key
            )

    def _refresh_superblock(self, ctx: QueryContext, cache, sb_key,
                            versions: tuple):
        """Interval-aware maintenance of a version-stale cached superblock
        (runs under the per-key build lock). Three outcomes, cheapest
        first:

        - every member shard's effects since the entry was stamped were
          provably DISJOINT from the staged range → re-stamp (revalidate)
          and serve the entry untouched — disjoint-range ingest no longer
          evicts superblocks;
        - only overlapping interval effects (live-edge appends) and the
          row set is provably unchanged → EXTEND the device superblock in
          place (_extend_superblock) and serve it — the warm query stays
          one dispatch under live ingest;
        - anything else (new series, eviction, ODP, effect-log truncation,
          extension precondition failure) → return None and let the caller
          pay the full rebuild.

        Returns what do_execute expects from _superblock (an entry, a
        fallback-reason string from _serve_hit, or None for rebuild)."""
        from ...metrics import record_superblock_event

        stale = cache.peek(sb_key)
        if stale is None:
            return None
        old_versions, entry, _ = stale
        if len(old_versions) != len(versions):
            return None
        overlap = False
        for s, ov in zip(self.shard_nums, old_versions):
            shard = ctx.memstore.shard(ctx.dataset, s)
            reason = shard.ingest_effects_since(
                ov, self.raw_start_ms, self.raw_end_ms
            )
            if reason == "overlap":
                overlap = True
            elif reason is not None:
                # full_clear / log_truncated: the entry can never be
                # revalidated or extended, and put() is gated on a stable
                # version vector that sustained ingest keeps moving — drop
                # it now or it pins device + host-mirror bytes forever
                # (eviction only runs inside put).
                cache.drop(sb_key)
                record_superblock_event("restage")
                return None
        if not overlap:
            if cache.revalidate(sb_key, old_versions, versions):
                record_superblock_event("revalidate")
                cache.note(sb_key, "revalidate")
                ctx.stats.bump(cache_hits=1)
                return self._serve_hit(ctx, entry)
            return None
        if not _SUPERBLOCK_EXTEND or entry.stage_mode is None:
            record_superblock_event("restage")
            cache.note(sb_key, "restage")
            return None
        return self._extend_superblock(ctx, cache, sb_key, entry, versions)

    def _extend_superblock(self, ctx: QueryContext, cache, sb_key,
                           entry: "SuperblockEntry", versions: tuple):
        """Absorb overlapping live-edge appends into the cached superblock
        via ST.extend_superblock (append_to_block lifted to the superblock
        level), then commit with versions re-read AFTER the extension and
        the effect log classifying whatever landed mid-extension:

        - nothing in-range → commit at the post-extension vector;
        - interval OVERLAPS only (live-edge appends racing the extension
          reads) → commit at the PRE-extension vector. The extension is
          still internally consistent — _append_to_parts rejects torn
          cross-epoch reads via its uniform-count/timestamp checks BEFORE
          mutating anything, and each series' content is a true prefix of
          its store state re-extendable from its own head — it just may
          not include the racing samples, so the entry stays version-stale
          and the NEXT query extends again from the new head instead of
          the whole cache paying a rebuild storm;
        - full effects (new series, eviction, ODP, truncation) → DROP the
          entry: resident data or the row set may have changed under the
          reads, and the mutated host mirrors must never be served again."""
        from ...metrics import record_superblock_event

        # row-set proof: a fresh lookup per shard must return exactly the
        # entry's part refs, in order. This is the superblock analog of
        # append_to_block's part_refs check — it catches the gap-series
        # hazard (an append BEYOND the range extending a series' index
        # span across it) that version vectors alone cannot distinguish
        # from a plain live-edge append.
        rewritten, _co, bucket_le = _histogram_suffix_rewrite(self.filters)
        if bucket_le is not None:
            # le=-sliced bucket superblocks are built by slicing a staged
            # [S, T, B] block — there is nothing to append onto
            record_superblock_event("restage")
            return None
        refs = []
        for s in self.shard_nums:
            shard = ctx.memstore.shard(ctx.dataset, s)
            pids = shard.lookup_partitions(
                self.filters, self.raw_start_ms, self.raw_end_ms
            )
            if not len(pids) and rewritten is not None:
                pids = shard.lookup_partitions(
                    rewritten, self.raw_start_ms, self.raw_end_ms
                )
            refs.extend((s, int(p)) for p in pids)
        if refs != list(entry.block.part_refs):
            record_superblock_event("restage")
            return None
        try:
            nb = ST.extend_superblock(
                ctx.memstore, ctx.dataset, entry.block, entry.col_name,
                self.raw_end_ms, entry.stage_mode,
                les=entry.les if entry.is_hist else None,
            )
        except Exception:
            cache.drop(sb_key)  # mirrors possibly torn mid-mutation
            record_superblock_event("extend_abort")
            return None
        if nb is None:
            record_superblock_event("restage")
            cache.note(sb_key, "restage")
            return None
        versions_now = tuple(
            ctx.memstore.shard(ctx.dataset, s).version for s in self.shard_nums
        )
        commit_versions = versions_now
        if versions_now != versions:
            for s, ov in zip(self.shard_nums, versions):
                reason = ctx.memstore.shard(ctx.dataset, s).ingest_effects_since(
                    ov, self.raw_start_ms, self.raw_end_ms
                )
                if reason == "overlap":
                    # live-edge appends raced the extension reads: the
                    # extension is consistent (see docstring) but may not
                    # include them — commit STALE at the pre-extension
                    # vector so the next query extends again
                    commit_versions = versions
                elif reason is not None:
                    cache.drop(sb_key)
                    record_superblock_event("extend_abort")
                    return None
        if nb is entry.block:
            # nothing new was readable in range (e.g. the overlapping
            # effect's samples were all dropped as out-of-order, or they
            # landed after the reads): the entry is untouched and valid
            # as-is at the commit vector
            stale = cache.peek(sb_key)
            if stale is not None and stale[1] is entry:
                cache.revalidate(sb_key, stale[0], commit_versions)
            record_superblock_event("revalidate")
            cache.note(sb_key, "revalidate")
            ctx.stats.bump(cache_hits=1)
            return self._serve_hit(ctx, entry)
        samples = int(np.asarray(nb.h_lens).sum())
        new_entry = SuperblockEntry(
            nb, entry.labels, entry.is_counter, entry.is_delta, samples,
            entry.max_shard_series, series=entry.series,
            is_hist=entry.is_hist, les=entry.les, les_dev=entry.les_dev,
            col_name=entry.col_name, stage_mode=entry.stage_mode,
        )
        cache.put(sb_key, commit_versions, new_entry, ST.staged_nbytes(nb))
        record_superblock_event("extend")
        cache.note(sb_key, "extend")
        ctx.stats.bump(cache_extends=1)
        return self._serve_hit(ctx, new_entry)

    def _build_superblock(self, ctx: QueryContext, stage_mode: str, cache,
                          sb_key, versions, hints, hint_key):
        rewritten, col_override, bucket_le = _histogram_suffix_rewrite(
            self.filters
        )
        blocks, labels, block_les = [], [], []
        schema_name = None
        is_counter = is_delta = is_hist = sliced_hist = False
        total = max_shard_series = dropped_samples = 0
        for s in self.shard_nums:
            ctx.check_deadline()
            shard = ctx.memstore.shard(ctx.dataset, s)
            pids = shard.lookup_partitions(
                self.filters, self.raw_start_ms, self.raw_end_ms
            )
            suffixed = False
            if not len(pids) and rewritten is not None:
                # classic-histogram suffix selector (m_sum / m_count /
                # m_bucket): stage the base histogram schema's columns, same
                # per-shard rewrite SelectRawPartitionsExec applies
                pids = shard.lookup_partitions(
                    rewritten, self.raw_start_ms, self.raw_end_ms
                )
                suffixed = len(pids) > 0
            if not len(pids):
                continue
            if len(pids) > ctx.max_series:
                # same per-shard limit semantics as SelectRawPartitionsExec
                raise QueryError(
                    f"query selects {len(pids)} series > limit {ctx.max_series}"
                )
            # pre-slice accounting, matching the reference path (it bumps
            # stats and enforces the per-shard limit before le= slicing)
            total += len(pids)
            max_shard_series = max(max_shard_series, len(pids))
            if shard.odp_store is not None:
                shard.odp_page_in(pids, self.raw_start_ms, self.raw_end_ms)
            parts = [shard.partition(int(p)) for p in pids]
            names = {p.schema.name for p in parts}
            if len(names) > 1 or (schema_name is not None
                                  and names != {schema_name}):
                return "mixed_schemas"
            schema_name = parts[0].schema.name
            schema = parts[0].schema
            col_name = self.column or (suffixed and col_override) \
                or schema.value_column
            try:
                col = schema.column(col_name)
            except KeyError:
                col_name = schema.value_column
                col = schema.column(col_name)
            hist_col = col.ctype == ColumnType.HISTOGRAM
            # op/func support is decidable as soon as the schema resolves —
            # bail before staging uploads a [S, T, B] block only to discard
            # it (a le= slice lands scalar, so it follows the scalar rules)
            reason = self._unsupported_shape(hist_col and bucket_le is None)
            if reason is not None:
                return reason
            is_counter = col.is_counter
            is_delta = col.is_delta
            # histogram columns always stage raw (reference: correction only
            # inside rate-family RangeFunctions; hist kernels window raw
            # cumulative bucket counts directly)
            mode = (
                stage_mode if is_counter and not is_delta and not hist_col
                else "raw"
            )
            cache_key = (
                self.filters, self.raw_start_ms, self.raw_end_ms, col_name,
                schema_name, mode,
            )
            block = staged_block_for(
                ctx, shard, pids, cache_key, col_name, self.raw_start_ms,
                self.raw_end_ms, mode,
            )
            part_labels = [dict(p.tags) for p in parts]
            les = parts[0].bucket_les if hist_col else None
            if hist_col and not _uniform_scheme(parts, les):
                # no scheme at all, or partitions WITHIN this shard disagree
                # on bounds: one [S, T, B] block can't represent them (the
                # union remap is per-shard) — keep the pre-fusion behavior
                return "hist_scheme"
            if hist_col and bucket_le is not None:
                # m_bucket{le=...}: slice ONE bucket into a scalar block
                # (same selection semantics as SelectRawPartitionsExec)
                sliced = _slice_bucket(block, les, bucket_le)
                if sliced is None:
                    # no such bucket on this shard: it contributes no rows,
                    # but its series/samples were scanned — count them, as
                    # the reference path does (it bumps before slicing)
                    dropped_samples += int(np.asarray(block.lens).sum())
                    continue
                block, le_str = sliced
                part_labels = [dict(l, le=le_str) for l in part_labels]
                les = None
                hist_col = False
                sliced_hist = True
                is_counter, is_delta = True, False
            if hist_col != is_hist and blocks:
                return "mixed_schemas"  # scalar + histogram blocks can't mix
            is_hist = hist_col
            if np.asarray(block.vals).ndim != (3 if hist_col else 2):
                return "mixed_schemas"
            blocks.append(block)
            block_les.append(les)
            labels.extend(part_labels)
        if schema_name is not None:
            if len(hints) >= 1024:
                hints.clear()  # bounded: hints are one dict lookup to relearn
            # histogram columns always stage raw — including a le= slice of
            # one (sliced AFTER raw staging) — so key them like gauges: one
            # superblock serves every range function over the selector
            hints[hint_key] = (is_counter and not is_hist and not sliced_hist,
                               is_delta)
        if not blocks:
            return None  # empty selection: empty result, not a fallback
        samples = dropped_samples + int(
            sum(int(np.asarray(b.lens).sum()) for b in blocks)
        )
        ctx.stats.bump(series_scanned=total, samples_scanned=samples,
                       cache_misses=1)
        if ctx.stats.samples_scanned > ctx.max_samples:
            raise QueryError(
                f"query would scan {ctx.stats.samples_scanned} samples > "
                f"limit {ctx.max_samples}"
            )
        les = None
        if is_hist:
            blocks, les = _unify_hist_blocks(blocks, block_les)
        # host mirrors ride along so live-edge ingest can EXTEND the
        # superblock in place (ST.extend_superblock) instead of paying
        # concat + full re-upload per append — the delta-summation move.
        # With a mesh, the series axis pads to a mesh-divisible ΣS (the
        # existing trash-group masking keeps the extra rows inert) and the
        # arrays pin SHARDED (PartitionSpec(axis) row bands) so the fused
        # program spans every device without a gather.
        multiple = self.mesh.devices.size if self.mesh is not None else 1
        super_block = ST.concat_blocks(
            blocks, series_multiple=multiple
        ).to_device(keep_host=_SUPERBLOCK_EXTEND, mesh=self.mesh)
        nbytes = ST.staged_nbytes(super_block)

        resolved_mode = (
            stage_mode if is_counter and not is_delta and not is_hist
            else "raw"
        )
        value = SuperblockEntry(
            super_block, labels, is_counter, is_delta, samples,
            max_shard_series, series=total, is_hist=is_hist, les=les,
            les_dev=(ST.replicated_put(self.mesh)(
                np.asarray(les, dtype=np.float32))
                if les is not None else None),
            col_name=col_name,
            stage_mode=None if sliced_hist else resolved_mode,
        )
        # versions re-read AFTER staging: an ingest that landed mid-build
        # makes the entry unservable for the next query (version mismatch),
        # so only cache when nothing moved
        versions_now = tuple(
            ctx.memstore.shard(ctx.dataset, s).version for s in self.shard_nums
        )
        if versions_now == versions:
            cache.put(sb_key, versions, value, nbytes)
        return value

    def _mesh_desc(self) -> tuple | None:
        """Hashable mesh identity for the batching coalescing key (mirrors
        the superblock cache key's mesh descriptor)."""
        if self.mesh is None:
            return None
        return (self.mesh.axis_names[0],
                tuple(d.id for d in self.mesh.devices.flat))

    def _dispatch_fused(self, ctx: QueryContext, request) -> Any:
        """Route one fused kernel launch through the query dispatch
        scheduler (query/scheduler.py) when the context carries an enabled
        one — concurrent queries sharing this superblock + grid/epilogue
        signature coalesce into ONE batched launch — else run the plain
        unbatched dispatch. Disabled batching is byte-identical to the
        pre-scheduler path. Kernel variants the batched program set does
        not model (AGG.batch_variant_supported: mesh + jitter/masked
        grids, pallas-promoted irregular grids, jittered hist) skip the
        scheduler outright — paying the batch window for a launch that is
        guaranteed to fall back per-lane would be pure added latency."""
        import time as _time

        from ...metrics import current_phases

        sched = getattr(ctx, "dispatch_scheduler", None)
        if sched is not None and hasattr(sched, "observe_key"):
            # recurrence feed for standing-query promotion: every fused
            # dispatch counts, batching enabled or not (the ring is the
            # retained per-key state the batch groups used to drop at
            # close) — see query/scheduler.KeyStatsRing
            self._observe_key(ctx, sched)
        # phase decomposition (obs/querylog.py): time around the launch is
        # split into "admission" (batch-window queue wait — the scheduler
        # stamps the group's actual kernel seconds on the request) and
        # "dispatch" (the launch itself). Pure host-side perf_counter
        # bookkeeping: no device sync is added around the (async) dispatch.
        rec = current_phases()
        obs = getattr(ctx, "obs", None)
        # the cost model's prediction rides the request: the scheduler's
        # adaptive batch window widens/narrows on the decayed sum of these
        request.predicted_cost_s = float(
            getattr(ctx, "predicted_cost_s", 0.0) or 0.0
        )
        t0 = _time.perf_counter()
        if (sched is not None and getattr(sched, "enabled", False)
                and AGG.batch_variant_supported(
                    request.block, request.func, request.kind,
                    request.is_delta, request.mesh)):
            request.timeout_s = ctx.remaining_deadline_s()
            if obs is not None:
                obs["batched"] = True
            out = sched.dispatch(request)
            if rec is not None:
                total = _time.perf_counter() - t0
                exec_s = request.exec_seconds
                if exec_s is not None:
                    exec_s = min(max(float(exec_s), 0.0), total)
                    rec.add("admission", total - exec_s)
                    rec.add("dispatch", exec_s)
                else:
                    # a coalesced duplicate lane: its own request object
                    # never reached the executing leader — the shared wait
                    # is indivisible, attribute it all to dispatch
                    rec.add("dispatch", total)
            if obs is not None and request.executable_key is not None:
                # kernel-observatory join key (obs/kernels.py): the leader
                # stamped the executable that served this lane (a
                # coalesced duplicate lane's own request stays None —
                # mirroring exec_seconds)
                obs["executable_key"] = request.executable_key
                obs["compile_miss"] = request.compile_miss
            return out
        if obs is not None:
            obs.setdefault("batched", False)
        out = request.run_single()
        if rec is not None:
            rec.add("dispatch", _time.perf_counter() - t0)
        if obs is not None:
            # solo path: the launch ran on THIS thread — read the
            # executable identity straight from the registry's capture
            from ...obs.kernels import KERNELS

            info = KERNELS.last_dispatch()
            if info:
                obs["executable_key"] = info.get("executable_key")
                obs["compile_miss"] = info.get("compile_miss")
        return out

    def _observe_key(self, ctx: QueryContext, sched) -> None:
        """Record this dispatch in the scheduler's per-key recurrence ring.
        The key normalizes away the sliding live-edge times (a dashboard
        re-issuing the same panel with a fresh ``end=now`` must count as
        ONE recurring key): dataset + the root span's PromQL + grid shape.
        The descriptor carries what the standing promoter needs to
        re-register the query; ``end_lag_ms`` (wall clock minus the grid
        end) distinguishes live-edge dashboards from historical scans."""
        import time as _time

        if getattr(ctx, "standing_refresh", False):
            # the maintainer's own refresh dispatches must not feed the
            # ring — a standing query would keep itself "hot" forever
            return
        root = getattr(ctx, "trace_root", None)
        promql = root.tags.get("promql") if root is not None else None
        if root is not None and root.parent_id is not None:
            # a remote child's leg: the ORIGIN observes the query once
            return
        key = (
            ctx.dataset, promql, self.step_ms, self.window_ms,
            self.end_ms - self.start_ms,
        ) if promql else (
            ctx.dataset, self.op, self.function, self.filters,
            tuple(self.by or ()), tuple(self.without or ()),
            self.step_ms, self.window_ms, self.end_ms - self.start_ms,
        )
        now_ms = _time.time() * 1000.0
        sched.observe_key(key, {
            "promql": promql,
            "dataset": ctx.dataset,
            "op": self.op,
            "function": self.function,
            "params": self.params,
            "hist_quantile": self.hist_quantile,
            "step_ms": self.step_ms,
            "window_ms": self.window_ms,
            "span_ms": self.end_ms - self.start_ms,
            "end_lag_ms": now_ms - float(self.end_ms),
        })

    def do_execute(self, ctx: QueryContext) -> QueryResult:
        from ...metrics import span
        from ...ops.kernels import RangeParams, pad_steps
        from ..scheduler import FusedRequest

        if getattr(ctx, "allow_partial_results", False):
            # the fused program is all-or-nothing; partial-results queries
            # need the merge tree's lost-child tolerance
            return self._fall(ctx, "partial_results")
        if getattr(ctx, "dispatcher", None) is not None:
            # a child-dispatch hook (fault injection / chaos harness) only
            # fires on per-child dispatch — run the tree it can intercept
            return self._fall(ctx, "dispatcher")
        if self.mesh is not None and not fused_mesh_supported(
            self.mesh, self.op, self.function
        ):
            # the sharded program models the fused op/function set over a
            # 1-D series mesh; anything else keeps the caller's fallback
            # (the mesh engines' legacy per-shard kernels, or the tree)
            return self._fall(ctx, "mesh_unsupported")
        func = self.function or "last"
        stage_mode = _stage_mode_for_function(self.function)
        with span("fused:stage", phase="stage"):
            got = self._superblock(ctx, stage_mode)
        if isinstance(got, str):
            return self._fall(ctx, got)
        if got is None:
            return QueryResult()
        obs = getattr(ctx, "obs", None)
        if obs is not None:
            # query-observatory path annotations: the fused path served
            # this query, over a superblock of this grid class (metadata
            # reads only — .grid_class never touches device memory)
            obs["path"] = "fused"
            obs["grid_class"] = ST.grid_class(got.block)
        nsteps = self.num_steps()
        params = RangeParams(
            self.start_ms - self.offset_ms, self.step_ms, nsteps,
            self.window_ms,
        )
        strip = self.function is not None and self.function not in _DROP_NAME_KEEP
        if got.is_hist:
            # 3-D histogram superblock: per-bucket fused sum (+ optional
            # device-side histogram_quantile interpolation epilogue).
            # op/func support was already vetted (_unsupported_shape) before
            # the superblock's stats bump, on both the hit and build paths.
            gids_dev, G, group_labels = AGG.group_ids_memo(
                got.block, got.labels, self.by, self.without,
                strip_metric=strip,
            )
            with span(f"fused:dispatch:hist_{func}"):
                out = self._dispatch_fused(ctx, FusedRequest(
                    block=got.block, func=func, kind="hist", epilogue=(),
                    gids_dev=gids_dev, G=G,
                    qv=float(self.hist_quantile or 0.0), params=params,
                    j_pad=pad_steps(nsteps), is_counter=False,
                    is_delta=got.is_delta, mesh=self.mesh,
                    mesh_desc=self._mesh_desc(), les_dev=got.les_dev,
                    hist_q=self.hist_quantile is not None,
                    run_single=lambda: AGG.fused_hist_range_aggregate(
                        func, got.block, gids_dev, G, params, got.les_dev,
                        q=self.hist_quantile, is_delta=got.is_delta,
                        mesh=self.mesh,
                    ),
                ))
            if self.hist_quantile is not None:
                # quantile fused on device: [G, J] is all that comes back
                labels = [_strip_metric(l) for l in group_labels]
                return QueryResult(grids=[
                    Grid(labels, self.start_ms, self.step_ms, nsteps, out)
                ])
            placeholder = np.full((G, nsteps), np.nan, np.float32)
            return QueryResult(grids=[
                Grid(group_labels, self.start_ms, self.step_ms, nsteps,
                     placeholder, hist=out, les=got.les)
            ])
        if self.op in ("topk", "bottomk"):
            k = max(int(self.params[0]), 1)
            with span(f"fused:dispatch:{self.op}:{func}"):
                vals_dev, idx_dev = self._dispatch_fused(ctx, FusedRequest(
                    block=got.block, func=func, kind="topk",
                    epilogue=("topk", k, self.op == "bottomk"),
                    gids_dev=AGG.zero_gids(got.block), G=1, qv=0.0,
                    params=params, j_pad=pad_steps(nsteps),
                    is_counter=got.is_counter, is_delta=got.is_delta,
                    mesh=self.mesh, mesh_desc=self._mesh_desc(),
                    run_single=lambda: AGG.fused_topk(
                        func, got.block, k, self.op == "bottomk", params,
                        is_counter=got.is_counter, is_delta=got.is_delta,
                        mesh=self.mesh,
                    ),
                ))
            return self._present_topk(
                np.asarray(vals_dev)[:, :nsteps],
                np.asarray(idx_dev)[:, :nsteps], got.labels, strip, nsteps,
            )
        gids_dev, G, group_labels = AGG.group_ids_memo(
            got.block, got.labels, self.by, self.without, strip_metric=strip
        )
        if self.op == "quantile":
            q = float(self.params[0])
            with span(f"fused:dispatch:quantile:{func}"):
                out = self._dispatch_fused(ctx, FusedRequest(
                    block=got.block, func=func, kind="quantile",
                    epilogue=("quantile",), gids_dev=gids_dev, G=G, qv=q,
                    params=params, j_pad=pad_steps(nsteps),
                    is_counter=got.is_counter, is_delta=got.is_delta,
                    mesh=self.mesh, mesh_desc=self._mesh_desc(),
                    run_single=lambda: AGG.fused_quantile(
                        func, got.block, gids_dev, G, q, params,
                        is_counter=got.is_counter, is_delta=got.is_delta,
                        mesh=self.mesh,
                    ),
                ))
            return QueryResult(grids=[
                Grid(group_labels, self.start_ms, self.step_ms, nsteps, out)
            ])
        with span(f"fused:dispatch:{func}"):
            out = self._dispatch_fused(ctx, FusedRequest(
                block=got.block, func=func, kind="agg",
                epilogue=("agg", self.op), gids_dev=gids_dev, G=G, qv=0.0,
                params=params, j_pad=pad_steps(nsteps),
                is_counter=got.is_counter, is_delta=got.is_delta,
                mesh=self.mesh, mesh_desc=self._mesh_desc(),
                run_single=lambda: AGG.fused_range_aggregate(
                    func, self.op, got.block, gids_dev, G, params,
                    is_counter=got.is_counter, is_delta=got.is_delta,
                    mesh=self.mesh,
                ),
            ))
        if self.hist_quantile is not None:
            # classic-bucket histogram_quantile (vetted in
            # _unsupported_shape: "le" is in the grouping): the [G, J]
            # by-(le,...) partials from the ONE fused dispatch above pivot
            # into per-group cumulative grids and interpolate with the
            # native path's kernel
            from .transformers import classic_histogram_quantile

            q_labels, q_vals = classic_histogram_quantile(
                self.hist_quantile, group_labels,
                np.asarray(out)[:, :nsteps],
            )
            return QueryResult(grids=[
                Grid([_strip_metric(l) for l in q_labels], self.start_ms,
                     self.step_ms, nsteps, q_vals)
            ])
        return QueryResult(
            grids=[Grid(group_labels, self.start_ms, self.step_ms, nsteps, out)]
        )

    def _present_topk(self, vals, idx, labels, strip: bool,
                      nsteps: int) -> QueryResult:
        """Reconstruct Prometheus topk/bottomk rows from the compact [k, J]
        winner set: each surviving series keeps its own labels with values
        only at steps it won (NaN elsewhere) — exactly the ``topk_mask``
        output restricted to rows that survive, built host-side in
        O(k*J)."""
        finite = np.isfinite(vals)
        used = np.unique(idx[finite])
        out_labels, rows = [], []
        for s in used:
            m = (idx == s) & finite
            row = np.full(nsteps, np.nan, np.float32)
            r_i, c_i = np.nonzero(m)
            row[c_i] = vals[r_i, c_i]
            lbls = labels[int(s)]
            out_labels.append(_strip_metric(lbls) if strip else lbls)
            rows.append(row)
        v = (np.stack(rows) if rows
             else np.zeros((0, nsteps), np.float32))
        return QueryResult(grids=[
            Grid(out_labels, self.start_ms, self.step_ms, nsteps, v)
        ])


class RollupServeExec(ExecPlan):
    """Serve a long-range query from rollup summary blocks instead of raw
    samples (doc/perf.md "Sketch rollup tier"): the planner substituted
    this node because the query's step and window are multiples of a
    registered rollup's resolution, so every answer reads O(periods)
    per-period summaries — min/max/sum/count moments, reset-corrected
    counter lasts, and mergeable log-linear sketches — rather than
    O(raw samples). Quantiles evaluate ON DEVICE from the sketch blocks
    (merge-sketches -> rank-scan epilogue, psum-mergeable across a series
    mesh via the same shard_map pattern as the fused histogram path);
    ``histogram_quantile`` over classic bucket counters folds the [G, J]
    per-``le`` rollup rates through the native interpolation kernel.

    The serve is re-validated at RUNTIME against the live entry (the
    maintainer may have rebuilt it, the chooser may have retired it, or
    the watermark may no longer cover a moved live edge): any mismatch
    delegates to ``fallback`` — the exact plan the planner would have
    built without substitution — under the ``rollup_ineligible`` taxonomy
    entry, so results never silently degrade. The querylog ``path`` field
    records ``rollup`` on success."""

    def __init__(self, rollups, rollup_key, filters, function,
                 function_args, start_ms: int, end_ms: int, step_ms: int,
                 window_ms: int, fallback, op=None, by=None, without=None,
                 params=(), hist_quantile: float | None = None, mesh=None):
        super().__init__()
        self.rollups = rollups
        self.rollup_key = rollup_key
        self.filters = tuple(filters)
        self.function = function
        self.function_args = tuple(function_args or ())
        self.start_ms = start_ms
        self.end_ms = end_ms
        self.step_ms = step_ms
        self.window_ms = window_ms
        self.op = op  # None = per-series range function (window kind)
        self.by = by
        self.without = without
        self.params = tuple(params)
        self.hist_quantile = hist_quantile
        self.mesh = mesh
        self._fallback_factory = fallback
        self._fallback: ExecPlan | None = None

    @property
    def fallback(self) -> ExecPlan:
        if self._fallback is None:
            self._fallback = self._fallback_factory()
        return self._fallback

    def args_str(self) -> str:
        fs = ",".join(f"{f.column}{f.op}{f.value}" for f in self.filters)
        extra = f" op={self.op} by={self.by}" if self.op else ""
        if self.hist_quantile is not None:
            extra += f" hist_q={self.hist_quantile}"
        return (
            f"fn={self.function} window={self.window_ms} "
            f"res={self.rollup_key[2]} filters=[{fs}]{extra}"
        )

    def num_steps(self) -> int:
        return int((self.end_ms - self.start_ms) // self.step_ms) + 1

    def _fall(self, ctx: QueryContext, reason: str) -> QueryResult:
        from ...metrics import current_span, record_fused_fallback

        s = current_span()
        if s is not None:
            s.tags["fused_fallback"] = reason
        obs = getattr(ctx, "obs", None)
        if obs is not None:
            obs["path"] = "fallback"
            obs["fallback"] = reason
        record_fused_fallback(reason)
        return self.fallback.execute(ctx)

    def do_execute(self, ctx: QueryContext) -> QueryResult:
        from ...metrics import record_rollup_serve, span
        from ...ops import sketch as SKETCH

        rollups = self.rollups
        view = None
        if rollups is not None:
            view = rollups.serve_view(
                self.rollup_key, self.function, self.window_ms,
                self.start_ms, self.end_ms, self.step_ms,
            )
        if view is None:
            # entry retired/rebuilt/behind the live edge since plan time:
            # run the exact plan the planner would have built instead
            return self._fall(ctx, "rollup_ineligible")
        entry = view["entry"]
        with span("rollup:stage", phase="stage"):
            dev = rollups.device_arrays(entry)
        S = entry.n_series
        labels = view["labels"]
        if S > ctx.max_series:
            raise QueryError(
                f"query selects {S} series > limit {ctx.max_series}"
            )
        p0, p_lo, p_hi = view["p0"], view["p_lo"], view["p_hi"]
        win_p, step_p = view["win_p"], view["step_p"]
        nsteps = self.num_steps()
        window_s = self.window_ms / 1000.0
        # the whole point: stats record O(periods) summary reads, never
        # the raw sample count the fallback would have scanned
        ctx.stats.bump(series_scanned=S,
                       samples_scanned=S * max(p_hi - p_lo, 0))
        obs = getattr(ctx, "obs", None)
        if obs is not None:
            obs["path"] = "rollup"
            obs["rollup_resolution_ms"] = view["resolution_ms"]
        if S == 0:
            return QueryResult()
        a = p_lo - 1 - p0  # moment-kernel slice start (one lead period)
        n = p_hi - p_lo + 1
        alloc_p = view["alloc_p"]
        _IDENT = {"mn": np.inf, "mx": -np.inf, "sm": 0.0, "cnt": 0.0,
                  "clast": 0.0}

        def msl(name):
            """[S, n] moment slice with index 0 = the lead period. Arrays
            only cover the entry's data edge (alloc_p local periods);
            closed-but-empty periods outside pad with the moment's IDENTITY
            value (the windowed-count mask yields NaN for all-empty
            windows), except ``clast`` which edge-pads so counter diffs
            past the data edge read 0 increase, not a reset to baseline.
            The left lead pad is never read by the window reduction
            (counter shapes require a real lead at eligibility time)."""
            arr = dev[name]
            lo, hi = a, a + n
            s = arr[:, max(lo, 0):min(hi, alloc_p)]
            left, right = max(0, -lo), max(0, hi - alloc_p)
            if not left and not right:
                return s
            parts = []
            if left:
                parts.append(jnp.full((arr.shape[0], left), _IDENT[name],
                                      arr.dtype))
            parts.append(s)
            if right:
                if name == "clast":
                    parts.append(jnp.repeat(arr[:, -1:], right, axis=1))
                else:
                    parts.append(jnp.full((arr.shape[0], right),
                                          _IDENT[name], arr.dtype))
            return jnp.concatenate(parts, axis=1)

        strip = (self.function is not None
                 and self.function not in _DROP_NAME_KEEP)
        if self.op is None:
            # per-series range function
            if self.function == "quantile_over_time":
                q = float(self.function_args[0])
                counts = dev["sketch"][:, p_lo - p0:min(p_hi - p0, alloc_p), :]
                tail = (p_hi - p_lo) - counts.shape[1]
                if tail > 0:  # implicitly-empty closed periods: zero counts
                    counts = jnp.concatenate([
                        counts,
                        jnp.zeros((counts.shape[0], tail, counts.shape[2]),
                                  counts.dtype),
                    ], axis=1)
                starts = jnp.arange(nsteps, dtype=jnp.int32) * step_p
                with span("rollup:dispatch:sketch_quantile",
                          phase="dispatch"):
                    out = SKETCH.rollup_sketch_quantile(
                        counts, dev["centers"], starts, q, win_p
                    )
            else:
                with span(f"rollup:dispatch:{self.function}",
                          phase="dispatch"):
                    out = SKETCH.rollup_moment_range(
                        self.function, msl("mn"), msl("mx"), msl("sm"),
                        msl("cnt"), msl("clast"), win_p, step_p, window_s,
                    )
            record_rollup_serve("window")
            out_labels = [_strip_metric(l) for l in labels] if strip else labels
            return QueryResult(grids=[
                Grid(out_labels, self.start_ms, self.step_ms, nsteps, out)
            ])
        gids_np, group_labels = AGG.group_ids_for(
            labels,
            list(self.by) if self.by else None,
            list(self.without) if self.without else None,
        )
        G = max(len(group_labels), 1)
        gids = jnp.asarray(gids_np)
        if self.op == "quantile":
            q = float(self.params[0])
            mesh = self.mesh
            if mesh is not None and (S == 0 or S % mesh.devices.size):
                mesh = None  # series axis not mesh-divisible: solo dispatch
            with span("rollup:dispatch:agg_sketch_quantile",
                      phase="dispatch"):
                out = SKETCH.rollup_agg_sketch_quantile(
                    self.function, msl("mn"), msl("mx"), msl("sm"),
                    msl("cnt"), msl("clast"), gids, q, G, win_p, step_p,
                    window_s, mesh=mesh,
                )
            record_rollup_serve("agg")
            return QueryResult(grids=[
                Grid(group_labels, self.start_ms, self.step_ms, nsteps, out)
            ])
        with span(f"rollup:dispatch:{self.op}:{self.function}",
                  phase="dispatch"):
            out = SKETCH.rollup_moment_aggregate(
                self.function, self.op, msl("mn"), msl("mx"), msl("sm"),
                msl("cnt"), msl("clast"), gids, G, win_p, step_p, window_s,
            )
        if self.hist_quantile is not None:
            # classic-bucket histogram_quantile: [G, J] per-``le`` rollup
            # rates interpolate through the native path's kernel
            from .transformers import classic_histogram_quantile

            q_labels, q_vals = classic_histogram_quantile(
                self.hist_quantile, group_labels, np.asarray(out)[:, :nsteps]
            )
            record_rollup_serve("hist_quantile")
            return QueryResult(grids=[
                Grid([_strip_metric(l) for l in q_labels], self.start_ms,
                     self.step_ms, nsteps, q_vals)
            ])
        record_rollup_serve("agg")
        return QueryResult(grids=[
            Grid(group_labels, self.start_ms, self.step_ms, nsteps, out)
        ])


class PartialReduceExec(NonLeafExecPlan):
    """Reduce phase WITHOUT the present phase: merges children's partial
    components and re-emits them as ``__comp__``-labeled grids. This is the
    executor of L.PartialAggregate — what a federation peer runs so only
    O(groups) mergeable components cross the wire (reference
    RowAggregator.scala:28,114; AggrOverRangeVectors.scala:224)."""

    supports_partial = True

    def __init__(self, child_plans, op: str, by=None, without=None):
        super().__init__(child_plans)
        self.op = op
        self.by = by
        self.without = without

    def args_str(self) -> str:
        return f"op={self.op} by={self.by} without={self.without}"

    def do_execute(self, ctx: QueryContext) -> QueryResult:
        partials = []
        for r in self.execute_children(ctx):
            p = collect_partials(r, self.op)
            if p is not None:
                partials.append(p)
        key_to, meta = _merge_partials(self.op, partials)
        if meta is None:
            return QueryResult()
        group_labels = [slot["labels"] for slot in key_to.values()]
        names = sorted({n for slot in key_to.values() for n in slot["comps"]})
        comps = {}
        for name in names:
            proto = next(
                slot["comps"][name] for slot in key_to.values()
                if name in slot["comps"]
            )
            comps[name] = np.stack([
                slot["comps"].get(
                    name, np.full(proto.shape, np.nan, np.float32)
                )
                for slot in key_to.values()
            ])
        return QueryResult(grids=partials_to_grids(group_labels, comps, meta))


@dataclass
class SketchMapReduce:
    """Transformer form of the quantile map phase: per-group log-linear
    sketch counts (ops/sketch.py), encoded as a ``__comp__="sketch"`` grid
    whose [G, J, B] counts ride the hist field. Sketches merge by addition
    across shards and peers (reference QuantileRowAggregator's serialized
    t-digests)."""

    by: tuple | None
    without: tuple | None

    def apply(self, grids: list[Grid]) -> list[Grid]:
        from ...ops import sketch as SK

        if not grids:
            return []
        meta = grids[0]
        all_labels = [l for g in grids for l in g.labels]
        mats = [g.values_np()[: g.n_series, : g.num_steps] for g in grids]
        vals = np.concatenate(mats, axis=0) if len(mats) > 1 else mats[0]
        gids, group_labels = AGG.group_ids_for(
            all_labels, list(self.by) if self.by else None,
            list(self.without) if self.without else None,
        )
        counts = np.asarray(
            SK.build_sketch(jnp.asarray(vals), jnp.asarray(gids), len(group_labels))
        )
        return partials_to_grids(group_labels, {"sketch": counts}, meta)


class QuantileMergeExec(NonLeafExecPlan):
    """Root merge for distributed quantile: children return per-group
    sketch partials (SketchMapReduce locally, PartialAggregate on peers);
    merged sketches present via log-linear interpolation. Cross-node
    quantile is approximate (~2.2% relative at SUB=32) exactly like the
    reference's t-digest exchange."""

    supports_partial = True

    def __init__(self, child_plans, q: float, by=None, without=None):
        super().__init__(child_plans)
        self.q = q
        self.by = by
        self.without = without

    def args_str(self) -> str:
        return f"q={self.q} by={self.by} without={self.without}"

    def do_execute(self, ctx: QueryContext) -> QueryResult:
        from ...ops import sketch as SK

        partials = []
        for r in self.execute_children(ctx):
            p = collect_partials(r, "sketch")
            if p is not None:
                partials.append(p)
        key_to, meta = _merge_partials("quantile", partials)
        if meta is None:
            return QueryResult()
        labels, rows = [], []
        for slot in key_to.values():
            counts = slot["comps"].get("sketch")
            if counts is None:
                continue
            labels.append(slot["labels"])
            rows.append(SK.sketch_quantile(counts[None], self.q)[0])
        vals = (np.stack(rows).astype(np.float32) if rows
                else np.zeros((0, meta.num_steps), np.float32))
        return QueryResult(
            grids=[Grid(labels, meta.start_ms, meta.step_ms, meta.num_steps, vals)]
        )


class CountValuesMergeExec(NonLeafExecPlan):
    """Root merge for pushed-down count_values: children's partial count
    rows (CountValuesMapReduce) merge by identical label set with SUM —
    exact because shards own disjoint series."""

    supports_partial = True

    def do_execute(self, ctx: QueryContext) -> QueryResult:
        grids = []
        for r in self.execute_children(ctx):
            grids.extend(r.grids)
        if not grids:
            return QueryResult()
        meta = grids[0]
        J = meta.num_steps
        merged: dict[tuple, np.ndarray] = {}
        keys: dict[tuple, dict] = {}
        for g in grids:
            vals = g.values_np()
            for i, lbls in enumerate(g.labels):
                key = tuple(sorted(lbls.items()))
                row = vals[i, :J]
                have = merged.get(key)
                if have is None:
                    merged[key] = np.array(row, np.float32)
                    keys[key] = lbls
                else:
                    # NaN-aware sum: count + absent = count
                    a, b = have, row
                    both = np.isfinite(a) & np.isfinite(b)
                    only_b = ~np.isfinite(a) & np.isfinite(b)
                    a[both] += b[both]
                    a[only_b] = b[only_b]
        labels = [keys[k] for k in merged]
        v = (np.stack(list(merged.values())) if merged
             else np.zeros((0, J), np.float32))
        return QueryResult(grids=[Grid(labels, meta.start_ms, meta.step_ms, J, v)])


class AggregatePresentExec(NonLeafExecPlan):
    """Root aggregation for non-mergeable ops (topk/bottomk/quantile/
    count_values): children concat full series to the root.

    Scale: topk/bottomk children carry a TopkCandidateFilter map phase (the
    reference TopkRowAggregator k-heap-spill analog) so the root gathers
    O(shards*k) candidate rows, exactly; count_values pushes per-shard
    counting (CountValuesMapReduce + CountValuesMergeExec); quantile scales
    via the mesh sketch path (MeshQuantileExec) when a mesh is configured.
    limitk and aggregates over arbitrary subtrees (joins) still gather the
    full series set (one [S, J] host array, fine through ~1M series x
    moderate steps; ctx.max_series bounds the gather)."""

    def __init__(self, child_plans, op: str, params=(), by=None, without=None):
        super().__init__(child_plans)
        self.op = op
        self.params = params
        self.by = by
        self.without = without

    def args_str(self) -> str:
        return f"op={self.op} params={self.params} by={self.by} without={self.without}"

    def do_execute(self, ctx: QueryContext) -> QueryResult:
        grids: list[Grid] = []
        for r in self.execute_children(ctx):
            grids.extend(r.grids)
        if not grids:
            return QueryResult()
        all_labels = [l for g in grids for l in g.labels]
        J = max(g.values_np().shape[1] for g in grids)
        meta = grids[0]
        vals = np.full((len(all_labels), J), np.nan, np.float32)
        r0 = 0
        for g in grids:
            v = g.values_np()
            vals[r0 : r0 + v.shape[0], : v.shape[1]] = v
            r0 += v.shape[0]
        op = self.op
        if op in _PARTIAL_COMPONENTS:
            # simple agg over an arbitrary subtree (e.g. over a join result);
            # pass grids through directly so histogram buckets survive
            partial = _partial_aggregate(op, grids, self.by, self.without)
            key_to, meta2 = _merge_partials(op, [partial])
            return _present(op, key_to, meta2)
        gids, group_labels = AGG.group_ids_for(
            all_labels, list(self.by) if self.by else None, list(self.without) if self.without else None
        )
        if op in ("topk", "bottomk", "limitk"):
            k = max(int(self.params[0]), 1)
            out_rows = []
            out_labels = []
            for gi in range(len(group_labels)):
                rows = np.nonzero(gids == gi)[0]
                sub = vals[rows]
                if op == "limitk":
                    masked = np.full_like(sub, np.nan)
                    masked[:k] = sub[:k]
                else:
                    masked = np.asarray(AGG.topk_mask(jnp.asarray(sub), min(k, sub.shape[0]), bottom=(op == "bottomk")))
                keep = ~np.all(np.isnan(masked), axis=1)
                for ri, kept in zip(rows, keep):
                    if kept:
                        out_labels.append(all_labels[ri])
                        out_rows.append(masked[np.nonzero(rows == ri)[0][0]])
            v = np.stack(out_rows) if out_rows else np.zeros((0, J), np.float32)
            return QueryResult(grids=[Grid(out_labels, meta.start_ms, meta.step_ms, meta.num_steps, v)])
        if op == "quantile":
            q = float(self.params[0])
            res = np.asarray(
                AGG.segment_quantile(jnp.asarray(vals), jnp.asarray(gids), len(group_labels), np.float32(q))
            )
            return QueryResult(grids=[Grid(group_labels, meta.start_ms, meta.step_ms, meta.num_steps, res)])
        if op == "count_values":
            label = str(self.params[0])
            out_labels, out_rows = [], []
            for gi, gl in enumerate(group_labels):
                counts = AGG.count_values(vals[gids == gi])
                for valstr, row in counts.items():
                    out_labels.append(dict(gl, **{label: valstr}))
                    out_rows.append(row[: meta.num_steps])
            v = np.stack(out_rows).astype(np.float32) if out_rows else np.zeros((0, meta.num_steps), np.float32)
            return QueryResult(grids=[Grid(out_labels, meta.start_ms, meta.step_ms, meta.num_steps, v)])
        raise QueryError(f"unsupported aggregation {op}")
