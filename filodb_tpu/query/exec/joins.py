"""Binary joins, set operators, scalar plans, subquery execution
(reference query/exec/BinaryJoinExec.scala, SetOperatorExec.scala,
binaryOp/BinaryOperatorFunction, scalar execs :816-928).

Join matching is host-side over label keys (cheap: #series, not #samples);
the value arithmetic runs on the [S, J] grids on device.
"""

from __future__ import annotations

import calendar
import datetime as _dt
from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from ...core.schemas import METRIC_TAG
from ...ops import staging as ST
from ...ops import kernels as K
from ..rangevector import Grid, QueryResult, ScalarResult
from .plans import ExecPlan, NonLeafExecPlan, QueryContext
from .transformers import QueryError, _strip_metric, apply_binop


def _match_key(labels: dict, on, ignoring) -> tuple:
    if on is not None:
        return tuple((k, labels.get(k, "")) for k in sorted(on))
    drop = set(ignoring or ()) | {METRIC_TAG, "__name__"}
    return tuple(sorted((k, v) for k, v in labels.items() if k not in drop))


def _flatten(grids: list[Grid]) -> tuple[list[dict], np.ndarray, Grid | None]:
    if not grids:
        return [], np.zeros((0, 0), np.float32), None
    meta = grids[0]
    labels = [l for g in grids for l in g.labels]
    J = max(g.values_np().shape[1] for g in grids)
    vals = np.full((len(labels), J), np.nan, np.float32)
    r = 0
    for g in grids:
        v = g.values_np()
        vals[r : r + v.shape[0], : v.shape[1]] = v
        r += v.shape[0]
    return labels, vals, meta


class BinaryJoinExec(NonLeafExecPlan):
    """Arithmetic/comparison joins with one-to-one / group_left / group_right
    cardinality (reference BinaryJoinExec)."""

    def __init__(self, lhs: ExecPlan, rhs: ExecPlan, op: str, cardinality: str,
                 on=None, ignoring=(), include=(), return_bool=False):
        super().__init__([lhs, rhs])
        self.op = op
        self.cardinality = cardinality
        self.on = on
        self.ignoring = ignoring
        self.include = include
        self.return_bool = return_bool

    def args_str(self):
        return f"op={self.op} card={self.cardinality} on={self.on} ignoring={self.ignoring}"

    def do_execute(self, ctx: QueryContext) -> QueryResult:
        lres, rres = self.execute_children(ctx)
        llabels, lvals, lmeta = _flatten(lres.grids)
        rlabels, rvals, rmeta = _flatten(rres.grids)
        meta = lmeta or rmeta
        if meta is None:
            return QueryResult()
        rindex: dict[tuple, list[int]] = {}
        for j, rl in enumerate(rlabels):
            rindex.setdefault(_match_key(rl, self.on, self.ignoring), []).append(j)

        out_labels: list[dict] = []
        lhs_rows: list[int] = []
        rhs_rows: list[int] = []
        many_side_left = self.cardinality == "many-to-one"
        one_to_one = self.cardinality == "one-to-one"
        if one_to_one:
            seen: dict[tuple, int] = {}
            for i, ll in enumerate(llabels):
                key = _match_key(ll, self.on, self.ignoring)
                js = rindex.get(key, [])
                if not js:
                    continue
                if len(js) > 1:
                    raise QueryError("many-to-many matching not allowed: use group_left/group_right")
                if key in seen:
                    raise QueryError("multiple matches for labels on left side")
                seen[key] = i
                out_labels.append(self._result_labels(ll, rlabels[js[0]]))
                lhs_rows.append(i)
                rhs_rows.append(js[0])
        else:
            # group_left: many on the left; group_right: many on the right
            many_labels, many_vals = (llabels, lvals) if many_side_left else (rlabels, rvals)
            one_labels = rlabels if many_side_left else llabels
            one_index: dict[tuple, list[int]] = {}
            for j, ol in enumerate(one_labels):
                one_index.setdefault(_match_key(ol, self.on, self.ignoring), []).append(j)
            for i, ml in enumerate(many_labels):
                key = _match_key(ml, self.on, self.ignoring)
                js = one_index.get(key, [])
                if not js:
                    continue
                if len(js) > 1:
                    raise QueryError("multiple matches on the 'one' side of a grouped join")
                j = js[0]
                lbl = dict(_strip_metric(ml))
                for inc in self.include:
                    v = one_labels[j].get(inc)
                    if v is not None:
                        lbl[inc] = v
                    else:
                        lbl.pop(inc, None)
                out_labels.append(lbl)
                if many_side_left:
                    lhs_rows.append(i)
                    rhs_rows.append(j)
                else:
                    lhs_rows.append(j)
                    rhs_rows.append(i)
        if not out_labels:
            return QueryResult()
        a = jnp.asarray(lvals[np.asarray(lhs_rows)])
        b = jnp.asarray(rvals[np.asarray(rhs_rows)])
        v = apply_binop(self.op, a, b, self.return_bool)
        return QueryResult(grids=[Grid(out_labels, meta.start_ms, meta.step_ms, meta.num_steps, v)])

    def _result_labels(self, ll: dict, rl: dict) -> dict:
        from .transformers import _CMPOPS

        keep_name = self.op in _CMPOPS and not self.return_bool
        if self.on is not None:
            base = {k: ll.get(k, "") for k in self.on if k in ll}
            # one-to-one with on(): result labels are the on() labels
            out = dict(base)
            if keep_name and METRIC_TAG in ll:
                out[METRIC_TAG] = ll[METRIC_TAG]
            return out
        out = dict(ll) if keep_name else _strip_metric(ll)
        for k in self.ignoring:
            out.pop(k, None)
        return out


class SetOperatorExec(NonLeafExecPlan):
    """and / or / unless with per-step sample semantics (reference
    SetOperatorExec.scala:406)."""

    def __init__(self, lhs: ExecPlan, rhs: ExecPlan, op: str, on=None, ignoring=()):
        super().__init__([lhs, rhs])
        self.op = op
        self.on = on
        self.ignoring = ignoring

    def args_str(self):
        return f"op={self.op} on={self.on} ignoring={self.ignoring}"

    def do_execute(self, ctx: QueryContext) -> QueryResult:
        lres, rres = self.execute_children(ctx)
        llabels, lvals, lmeta = _flatten(lres.grids)
        rlabels, rvals, rmeta = _flatten(rres.grids)
        meta = lmeta or rmeta
        if meta is None:
            return QueryResult()
        rkeys: dict[tuple, list[int]] = {}
        for j, rl in enumerate(rlabels):
            rkeys.setdefault(_match_key(rl, self.on, self.ignoring), []).append(j)
        J = lvals.shape[1] if lvals.size else rvals.shape[1]
        out_labels: list[dict] = []
        rows: list[np.ndarray] = []
        if self.op in ("and", "unless"):
            for i, ll in enumerate(llabels):
                js = rkeys.get(_match_key(ll, self.on, self.ignoring), [])
                if js:
                    present = ~np.isnan(rvals[js]).all(axis=0)
                else:
                    present = np.zeros(J, dtype=bool)
                keep = present if self.op == "and" else ~present
                row = np.where(keep, lvals[i], np.nan)
                if not np.isnan(row).all():
                    out_labels.append(ll)
                    rows.append(row)
        else:  # or
            lkeys_per_step: dict[tuple, np.ndarray] = {}
            for i, ll in enumerate(llabels):
                key = _match_key(ll, self.on, self.ignoring)
                present = ~np.isnan(lvals[i])
                cur = lkeys_per_step.get(key)
                lkeys_per_step[key] = present if cur is None else (cur | present)
                out_labels.append(ll)
                rows.append(lvals[i])
            for j, rl in enumerate(rlabels):
                key = _match_key(rl, self.on, self.ignoring)
                lpresent = lkeys_per_step.get(key)
                row = rvals[j]
                if lpresent is not None:
                    row = np.where(lpresent, np.nan, row)
                if not np.isnan(row).all():
                    out_labels.append(rl)
                    rows.append(row)
        vals = np.stack(rows) if rows else np.zeros((0, J), np.float32)
        return QueryResult(grids=[Grid(out_labels, meta.start_ms, meta.step_ms, meta.num_steps, vals)])


# ---------------------------------------------------------------------------
# scalar plans
# ---------------------------------------------------------------------------


class ScalarPlanExec(ExecPlan):
    """Evaluates ScalarFixedDoublePlan / ScalarTimeBasedPlan /
    ScalarBinaryOperation trees to a per-step scalar."""

    def __init__(self, logical, start_ms: int, step_ms: int, num_steps: int):
        super().__init__()
        self.logical = logical
        self.start_ms = start_ms
        self.step_ms = step_ms
        self.num_steps = num_steps

    def do_execute(self, ctx: QueryContext) -> QueryResult:
        vals = eval_scalar(self.logical, self.start_ms, self.step_ms, self.num_steps, ctx)
        res = QueryResult(scalar=ScalarResult(self.start_ms, self.step_ms, self.num_steps, vals))
        res.result_type = "scalar"
        return res


def eval_scalar(plan, start_ms, step_ms, num_steps, ctx) -> np.ndarray:
    from ..logical import (
        ScalarBinaryOperation,
        ScalarFixedDoublePlan,
        ScalarTimeBasedPlan,
        ScalarVaryingDoublePlan,
    )

    times_s = (start_ms + np.arange(num_steps, dtype=np.int64) * step_ms) / 1e3
    if isinstance(plan, (int, float)):
        return np.full(num_steps, float(plan))
    if isinstance(plan, ScalarFixedDoublePlan):
        return np.full(num_steps, plan.value)
    if isinstance(plan, ScalarTimeBasedPlan):
        if plan.function == "time":
            return times_s.astype(np.float64)
        from .transformers import _TIME_COMPONENT

        fn = _TIME_COMPONENT[plan.function]
        return np.array(
            [fn(_dt.datetime.fromtimestamp(t, _dt.timezone.utc)) for t in times_s], dtype=np.float64
        )
    if isinstance(plan, ScalarBinaryOperation):
        a = eval_scalar(plan.lhs, start_ms, step_ms, num_steps, ctx)
        b = eval_scalar(plan.rhs, start_ms, step_ms, num_steps, ctx)
        return np.asarray(apply_binop(plan.op, jnp.asarray(a), jnp.asarray(b), False))
    if isinstance(plan, ScalarVaryingDoublePlan):
        # scalar(vector): handled by ScalarVaryingExec via the planner
        raise QueryError("scalar(vector) must be materialized via planner")
    raise QueryError(f"cannot evaluate scalar plan {plan}")


class ScalarVaryingExec(NonLeafExecPlan):
    """scalar(v) and vector(s) wrappers."""

    def __init__(self, child: ExecPlan, function: str):
        super().__init__([child])
        self.function = function

    def do_execute(self, ctx: QueryContext) -> QueryResult:
        (r,) = self.execute_children(ctx)
        if self.function == "scalar":
            labels, vals, meta = _flatten(r.grids)
            if meta is None:
                return QueryResult(scalar=None, result_type="scalar")
            if len(labels) == 1:
                out = vals[0].astype(np.float64)
            else:
                out = np.full(vals.shape[1] if vals.size else meta.num_steps, np.nan)
            res = QueryResult(scalar=ScalarResult(meta.start_ms, meta.step_ms, meta.num_steps, out))
            res.result_type = "scalar"
            return res
        # vector(s)
        s = r.scalar
        if s is None:
            return QueryResult()
        vals = np.asarray(s.values, dtype=np.float32)[None, :]
        return QueryResult(grids=[Grid([{}], s.start_ms, s.step_ms, s.num_steps, vals)], result_type="vector")


class ScalarVectorOpExec(NonLeafExecPlan):
    """vector op scalar where the scalar side may itself be an exec
    (scalar(vector), time()-based, or scalar expression)."""

    def __init__(self, vector: ExecPlan, scalar: ExecPlan, op: str,
                 scalar_is_lhs: bool, return_bool: bool = False):
        super().__init__([vector, scalar])
        self.op = op
        self.scalar_is_lhs = scalar_is_lhs
        self.return_bool = return_bool

    def args_str(self):
        return f"op={self.op} scalar_is_lhs={self.scalar_is_lhs}"

    def do_execute(self, ctx: QueryContext) -> QueryResult:
        from .transformers import ScalarOperationMapper

        vres, sres = self.execute_children(ctx)
        scalar = sres.scalar if sres.scalar is not None else ScalarResult(0, 1, 1, np.array([np.nan]))
        mapper = ScalarOperationMapper(self.op, scalar, self.scalar_is_lhs, self.return_bool)
        return QueryResult(grids=mapper.apply(vres.grids), stats=vres.stats)


# ---------------------------------------------------------------------------
# subqueries
# ---------------------------------------------------------------------------

_COUNTERISH = {"rate", "increase", "irate"}


class SubqueryWindowExec(NonLeafExecPlan):
    """Range function over an inner expression's step grid (reference
    subquery materialization in DefaultPlanner): the inner result rows are
    re-staged as series and fed through the same window kernels."""

    def __init__(self, child: ExecPlan, function: str, window_ms: int, sub_step_ms: int,
                 start_ms: int, end_ms: int, step_ms: int, offset_ms: int = 0, args=()):
        super().__init__([child])
        self.function = function
        self.window_ms = window_ms
        self.sub_step_ms = sub_step_ms
        self.start_ms = start_ms
        self.end_ms = end_ms
        self.step_ms = step_ms
        self.offset_ms = offset_ms
        self.args = args

    def args_str(self):
        return f"fn={self.function} window={self.window_ms} substep={self.sub_step_ms}"

    def do_execute(self, ctx: QueryContext) -> QueryResult:
        (r,) = self.execute_children(ctx)
        nsteps = int((self.end_ms - self.start_ms) // self.step_ms) + 1
        out_grids = []
        for g in r.grids:
            v = g.values_np()
            times = g.step_times_ms()
            series = []
            for i in range(v.shape[0]):
                row = v[i]
                keep = ~np.isnan(row)
                series.append((times[keep].astype(np.int64), row[keep].astype(np.float64)))
            block = ST.stage_series(
                series, self.start_ms - self.window_ms - self.offset_ms,
                counter_corrected=self.function in _COUNTERISH,
            )
            params = K.RangeParams(
                self.start_ms - self.offset_ms, self.step_ms, nsteps, self.window_ms
            )
            vals = K.run_range_function(
                self.function, block, params,
                is_counter=self.function in _COUNTERISH, args=self.args,
            )
            out_grids.append(Grid(list(g.labels), self.start_ms, self.step_ms, nsteps, vals))
        return QueryResult(grids=out_grids)
