"""PromQL function registry (reference query/PlanEnums.scala — 28 range
functions :52-85, 26 instant functions :8-35, 12 aggregation ops :99-113).

Maps surface names to kernel names plus argument shapes: which positional
argument is the vector/matrix and which are scalars.
"""

from __future__ import annotations

# range functions: surface name -> (kernel name, n_scalar_args, scalars_first)
RANGE_FUNCTIONS: dict[str, tuple[str, int, bool]] = {
    "rate": ("rate", 0, False),
    "increase": ("increase", 0, False),
    "delta": ("delta", 0, False),
    "idelta": ("idelta", 0, False),
    "irate": ("irate", 0, False),
    "resets": ("resets", 0, False),
    "changes": ("changes", 0, False),
    "deriv": ("deriv", 0, False),
    "predict_linear": ("predict_linear", 1, False),  # (m[d], t)
    "avg_over_time": ("avg_over_time", 0, False),
    "min_over_time": ("min_over_time", 0, False),
    "max_over_time": ("max_over_time", 0, False),
    "sum_over_time": ("sum_over_time", 0, False),
    "count_over_time": ("count_over_time", 0, False),
    "stddev_over_time": ("stddev_over_time", 0, False),
    "stdvar_over_time": ("stdvar_over_time", 0, False),
    "last_over_time": ("last_over_time", 0, False),
    "first_over_time": ("first_over_time", 0, False),
    "present_over_time": ("present_over_time", 0, False),
    "absent_over_time": ("absent_over_time", 0, False),
    "quantile_over_time": ("quantile_over_time", 1, True),  # (q, m[d])
    "median_absolute_deviation_over_time": ("median_absolute_deviation_over_time", 0, False),
    "mad_over_time": ("median_absolute_deviation_over_time", 0, False),
    "holt_winters": ("double_exponential_smoothing", 2, False),  # (m[d], sf, tf)
    "double_exponential_smoothing": ("double_exponential_smoothing", 2, False),
    "timestamp_of_last_sample": ("timestamp", 0, False),
    "z_score": ("z_score", 0, False),
    "rate_over_delta": ("rate", 0, False),  # delta-counter rate alias
    "increase_over_delta": ("increase", 0, False),
    "avg_with_sum_and_count_over_time": ("avg_over_time", 0, False),
    # (tolerance, bounds_mode, rv) — reference LastOverTimeIsMadOutlier
    "last_over_time_is_mad_outlier": ("last_over_time_is_mad_outlier", 2, True),
}

# instant functions applied elementwise on [S, J] grids
INSTANT_FUNCTIONS = {
    "abs", "ceil", "exp", "floor", "ln", "log2", "log10", "sqrt", "sgn",
    "acos", "acosh", "asin", "asinh", "atan", "atanh", "cos", "cosh", "sin",
    "sinh", "tan", "tanh", "deg", "rad",
    "clamp", "clamp_max", "clamp_min", "round", "or_vector",
    "histogram_quantile", "histogram_fraction", "histogram_max_quantile",
    "histogram_max_quantile_even", "histogram_bucket",
    "hist_to_prom_vectors",
    "timestamp",
}

# misc functions handled host-side on labels / ordering
MISC_FUNCTIONS = {
    "label_replace", "label_join", "sort", "sort_desc", "absent", "scalar",
    "vector", "limit", "optimize_with_agg", "no_optimize",
    "_filodb_chunkmeta_all",
}

# 0-arity or optional-vector time functions
TIME_FUNCTIONS = {
    "time", "minute", "hour", "month", "year", "day_of_month", "day_of_week",
    "day_of_year", "days_in_month", "pi",
}

AGGREGATION_OPS = {
    "sum", "min", "max", "avg", "count", "stddev", "stdvar", "group",
    "topk", "bottomk", "quantile", "count_values", "limitk", "limit_ratio",
}

# aggregators with a leading parameter
AGG_WITH_PARAM = {"topk", "bottomk", "quantile", "count_values", "limitk", "limit_ratio"}

COMPARISON_OPS = {"==", "!=", ">", "<", ">=", "<="}
SET_OPS = {"and", "or", "unless"}
ARITH_OPS = {"+", "-", "*", "/", "%", "^", "atan2"}
