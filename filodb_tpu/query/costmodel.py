"""Learned per-query cost model — predicted device-seconds per fingerprint.

The scheduling plane prices work in *device-seconds*, not query counts: a
30-day ``quantile_over_time`` and a 5-minute ``rate`` are not the same
token. The predictor joins the two observability planes PRs 12/14 built:

- the query observatory's normalized promql **fingerprint**
  (obs/querylog.promql_fingerprint — dataset + query text + grid shape,
  live edge normalized away), which is stable across a dashboard panel's
  re-issues, and
- the kernel registry's per-executable warm-dispatch stats
  (obs/kernels.ExecutableRegistry device-time histograms), used to back
  fill a realized cost when a record carries no kernel time of its own
  (e.g. a fully cache-served execution).

Per fingerprint it keeps an EWMA of realized device-seconds plus a
normalized *unit cost* (device-seconds per series×step of work), updated
online from every completed querylog record. Cold fingerprints are priced
by a conservative **family prior**: the per-family unit-cost EWMA scaled
by the query's own grid work and a safety multiplier (over-estimating an
unknown query sheds it a little early; under-estimating burns another
tenant's quota). With no family evidence either, the configured flat
prior applies — the same constant used to convert legacy query-count
quotas into device-second buckets, so an unconfigured deployment behaves
exactly as before.

Consumers:

- ``AdmissionController`` (query/scheduler.py) drains the tenant bucket
  by the prediction, so ``Retry-After`` is the bucket's actual drain time;
- ``DispatchScheduler`` widens/narrows its batch window from the decayed
  sum of predicted queue cost;
- querylog records gain ``predicted_cost_s`` / ``realized_cost_s`` and
  the ``filodb_costmodel_error_ratio`` histogram tracks |log error| of
  every prediction on the self-scrape (``GET /debug/costmodel`` shows the
  per-fingerprint detail).
"""

from __future__ import annotations

import re
import threading
from collections import OrderedDict

from ..metrics import REGISTRY

# Flat prior: what one "typical" query is worth in device-seconds before
# any evidence. Doubles as the legacy-quota conversion rate (N queries/s
# -> N * prior device-seconds/s), so converting units alone changes no
# admission decision.
DEFAULT_PRIOR_COST_S = 0.05
# Cold-fingerprint predictions are scaled up: the cost of over-pricing an
# unknown query is one early shed; the cost of under-pricing it is a
# drained bucket every other tenant pays for.
DEFAULT_COLD_MULTIPLIER = 2.0
DEFAULT_ALPHA = 0.3

_RANGE_FN = re.compile(r"\b([a-z_0-9]+_over_time|rate|irate|increase|delta"
                       r"|idelta|changes|resets|deriv)\s*\(")


def family_of(promql: str) -> str:
    """Coarse workload family of a query — the outermost range function
    (``rate``, ``min_over_time``, ...) or ``instant``. Derived from the
    query text on both the predict and the observe side so the join never
    depends on which executable variant actually served the dispatch."""
    m = _RANGE_FN.search(promql or "")
    return m.group(1) if m else "instant"


class CostModel:
    """Online device-second predictor, keyed by promql fingerprint with a
    per-family fallback ladder (fingerprint EWMA -> family unit cost ×
    grid work -> flat prior). Thread-safe; all state is O(max_entries)."""

    def __init__(self, prior_cost_s: float = DEFAULT_PRIOR_COST_S,
                 alpha: float = DEFAULT_ALPHA,
                 cold_multiplier: float = DEFAULT_COLD_MULTIPLIER,
                 max_entries: int = 4096):
        self.prior_cost_s = max(float(prior_cost_s), 1e-6)
        self.alpha = min(max(float(alpha), 0.01), 1.0)
        self.cold_multiplier = max(float(cold_multiplier), 1.0)
        self._max = max(int(max_entries), 16)
        self._lock = threading.Lock()
        # fingerprint -> {cost_s, unit_cost_s, n, family,
        #                 last_predicted_s, last_realized_s, last_error_ratio}
        self._fp: OrderedDict[str, dict] = OrderedDict()
        # family -> {unit_cost_s, cost_s, n}
        self._families: dict[str, dict] = {}
        self._sources = {"fingerprint": 0, "family": 0, "prior": 0}
        self._observed = 0

    def configure(self, prior_cost_s: float | None = None,
                  alpha: float | None = None,
                  cold_multiplier: float | None = None,
                  max_entries: int | None = None) -> None:
        with self._lock:
            if prior_cost_s is not None:
                self.prior_cost_s = max(float(prior_cost_s), 1e-6)
            if alpha is not None:
                self.alpha = min(max(float(alpha), 0.01), 1.0)
            if cold_multiplier is not None:
                self.cold_multiplier = max(float(cold_multiplier), 1.0)
            if max_entries is not None:
                self._max = max(int(max_entries), 16)
                while len(self._fp) > self._max:
                    self._fp.popitem(last=False)

    # -- prediction --------------------------------------------------------

    def predict(self, fingerprint: str, steps: int = 0, series: int = 0,
                family: str | None = None) -> tuple[float, str]:
        """Predicted device-seconds for one execution of ``fingerprint``
        and the evidence tier that priced it (``fingerprint`` | ``family``
        | ``prior``). ``steps``/``series`` scale the family unit cost for
        cold fingerprints (grid shape × series count scaling); a warm
        fingerprint's own EWMA already embodies its grid."""
        work = max(int(steps), 1) * max(int(series), 1)
        with self._lock:
            e = self._fp.get(fingerprint)
            if e is not None and e["n"] > 0:
                self._fp.move_to_end(fingerprint)
                self._sources["fingerprint"] += 1
                return max(e["cost_s"], 1e-9), "fingerprint"
            fam = self._families.get(family or "")
            if fam is not None and fam["n"] > 0:
                self._sources["family"] += 1
                if work > 1 and fam["unit_cost_s"] > 0.0:
                    cost = fam["unit_cost_s"] * work
                else:
                    cost = fam["cost_s"]
                return max(cost * self.cold_multiplier, 1e-9), "family"
            self._sources["prior"] += 1
            return self.prior_cost_s, "prior"

    # -- online update -----------------------------------------------------

    def observe(self, record: dict) -> None:
        """Fold one completed querylog record back into the model. The
        realized cost is the record's own kernel device time; a record
        without any (fully cache-served) falls back to the kernel
        registry's warm p50 for the executable that served it, keeping
        the EWMA anchored to device reality instead of decaying to zero."""
        if not isinstance(record, dict) or record.get("status") == "shed":
            return
        fp = record.get("fingerprint")
        if not fp:
            return
        realized = float(record.get("realized_cost_s") or 0.0)
        if realized <= 0.0:
            realized = self._registry_device_p50(record.get("executable_key"))
        if realized <= 0.0:
            return
        stats = record.get("stats") or {}
        grid = record.get("grid") or {}
        steps = int(grid.get("steps") or 1)
        series = int(stats.get("series_scanned") or 0)
        work = max(steps, 1) * max(series, 1)
        fam_key = family_of(record.get("promql", ""))
        predicted = record.get("predicted_cost_s")
        a = self.alpha
        with self._lock:
            self._observed += 1
            e = self._fp.get(fp)
            if e is None:
                e = {"cost_s": realized, "unit_cost_s": realized / work,
                     "n": 0, "family": fam_key, "last_predicted_s": None,
                     "last_realized_s": None, "last_error_ratio": None}
                self._fp[fp] = e
                while len(self._fp) > self._max:
                    self._fp.popitem(last=False)
            else:
                e["cost_s"] += a * (realized - e["cost_s"])
                e["unit_cost_s"] += a * (realized / work - e["unit_cost_s"])
            e["n"] += 1
            e["family"] = fam_key
            e["last_realized_s"] = realized
            self._fp.move_to_end(fp)
            fam = self._families.setdefault(
                fam_key, {"unit_cost_s": 0.0, "cost_s": 0.0, "n": 0})
            if fam["n"] == 0:
                fam["cost_s"] = realized
                fam["unit_cost_s"] = realized / work
            else:
                fam["cost_s"] += a * (realized - fam["cost_s"])
                fam["unit_cost_s"] += a * (realized / work
                                           - fam["unit_cost_s"])
            fam["n"] += 1
            if predicted is not None and predicted > 0.0:
                ratio = max(predicted / realized, realized / predicted)
                e["last_predicted_s"] = float(predicted)
                e["last_error_ratio"] = round(ratio, 4)
        if predicted is not None and predicted > 0.0:
            # symmetric error ratio (>= 1.0; 1.0 = perfect) — prediction
            # quality rides the self-scrape via this histogram
            REGISTRY.histogram("filodb_costmodel_error_ratio").observe(
                max(predicted / realized, realized / predicted))

    @staticmethod
    def _registry_device_p50(executable_key: str | None) -> float:
        if not executable_key:
            return 0.0
        from ..obs.kernels import KERNELS

        ms = KERNELS.device_p50_ms(executable_key)
        return (ms or 0.0) / 1e3

    # -- introspection -----------------------------------------------------

    def error_ratio(self, fingerprint: str) -> float | None:
        """Last prediction's symmetric error ratio for ``fingerprint``
        (None until a predicted record completes) — the convergence probe
        tests/test_costmodel.py asserts on."""
        with self._lock:
            e = self._fp.get(fingerprint)
            return e["last_error_ratio"] if e else None

    def snapshot(self, limit: int = 64) -> dict:
        """``GET /debug/costmodel`` payload: predictions + realized errors
        per warm fingerprint, family priors, and which evidence tier has
        been pricing admissions."""
        with self._lock:
            fps = [
                {"fingerprint": fp, **{k: (round(v, 6)
                                           if isinstance(v, float) else v)
                                       for k, v in e.items()}}
                for fp, e in list(self._fp.items())[-max(int(limit), 0):]
            ][::-1]
            return {
                "prior_cost_s": self.prior_cost_s,
                "alpha": self.alpha,
                "cold_multiplier": self.cold_multiplier,
                "observed": self._observed,
                "prediction_sources": dict(self._sources),
                "families": {
                    k: {"unit_cost_s": round(v["unit_cost_s"], 9),
                        "cost_s": round(v["cost_s"], 6), "n": v["n"]}
                    for k, v in sorted(self._families.items())
                },
                "fingerprints": fps,
            }

    def clear(self) -> None:
        with self._lock:
            self._fp.clear()
            self._families.clear()
            self._sources = {"fingerprint": 0, "family": 0, "prior": 0}
            self._observed = 0


COST_MODEL = CostModel()
