"""LogicalPlan / QueryResult <-> protobuf converters (reference analog:
grpc/.../ProtoConverters.scala — ~4k lines of hand-written per-class
message mapping for query_service.proto).

Because every LogicalPlan node here is a frozen dataclass of primitives,
tuples, and nested plans, one reflective codec over a registry of allowed
kinds replaces all of that: encoding walks dataclass fields, decoding
validates the kind name against the registry (the wire can never
instantiate an unregistered class) and re-checks field names against the
dataclass signature. Adding a plan node type requires zero converter work.

Results travel as columnar frames: a ``[S, J]`` f32 matrix serializes as one
``tobytes()`` (the device layout), not S*J row records.
"""

from __future__ import annotations

import json
from dataclasses import fields as dc_fields
from dataclasses import is_dataclass

import numpy as np

from ..api import query_exec_pb2 as pb
from ..core.filters import ColumnFilter
from . import logical as L
from .rangevector import Grid, QueryResult, QueryStats, ScalarResult

# -- registry ---------------------------------------------------------------

# every dataclass that may appear in a plan tree on the wire
_KINDS: dict[str, type] = {"ColumnFilter": ColumnFilter}
for _name in dir(L):
    _cls = getattr(L, _name)
    if isinstance(_cls, type) and is_dataclass(_cls) and issubclass(_cls, L.LogicalPlan):
        _KINDS[_name] = _cls


class PlanDecodeError(ValueError):
    pass


# -- plan encoding ----------------------------------------------------------


def _encode_value(v, out: "pb.PlanValue") -> None:
    if v is None:
        out.none = True
    elif isinstance(v, bool):  # before int: bool is an int subclass
        out.bval = v
    elif isinstance(v, (int, np.integer)):
        out.ival = int(v)
    elif isinstance(v, (float, np.floating)):
        out.dval = float(v)
    elif isinstance(v, str):
        out.sval = v
    elif is_dataclass(v):
        out.node.CopyFrom(plan_to_proto(v))
    elif isinstance(v, (tuple, list)):
        lst = out.list
        lst.SetInParent()  # an EMPTY tuple must still mark the oneof as set
        for item in v:
            _encode_value(item, lst.items.add())
    else:
        raise TypeError(f"cannot encode plan field value {v!r} ({type(v).__name__})")


def plan_to_proto(plan) -> "pb.PlanNode":
    kind = type(plan).__name__
    if kind not in _KINDS:
        raise TypeError(f"{kind} is not a registered plan kind")
    node = pb.PlanNode(kind=kind)
    for f in dc_fields(plan):
        pf = node.fields.add(name=f.name)
        _encode_value(getattr(plan, f.name), pf.value)
    return node


def _decode_value(v: "pb.PlanValue"):
    which = v.WhichOneof("kind")
    if which == "none" or which is None:
        return None
    if which == "dval":
        return v.dval
    if which == "ival":
        return v.ival
    if which == "sval":
        return v.sval
    if which == "bval":
        return v.bval
    if which == "node":
        return proto_to_plan(v.node)
    if which == "list":
        return tuple(_decode_value(item) for item in v.list.items)
    raise PlanDecodeError(f"unknown PlanValue kind {which}")


def proto_to_plan(node: "pb.PlanNode"):
    cls = _KINDS.get(node.kind)
    if cls is None:
        raise PlanDecodeError(f"unknown plan kind {node.kind!r}")
    allowed = {f.name for f in dc_fields(cls)}
    kw = {}
    for f in node.fields:
        if f.name not in allowed:
            raise PlanDecodeError(f"{node.kind} has no field {f.name!r}")
        kw[f.name] = _decode_value(f.value)
    try:
        return cls(**kw)
    except TypeError as e:  # missing required fields etc.
        raise PlanDecodeError(f"cannot build {node.kind}: {e}") from e


def plan_to_bytes(plan) -> bytes:
    return plan_to_proto(plan).SerializeToString()


def plan_from_bytes(data: bytes):
    return proto_to_plan(pb.PlanNode.FromString(data))


# -- result framing ---------------------------------------------------------

# series rows per GridChunk: bounds per-message size (~720 steps * 4B * 256
# rows ~ 0.7 MB) under gRPC's default 4 MB cap with label headroom
CHUNK_ROWS = 256


def result_to_frames(res: QueryResult, chunk_rows: int = CHUNK_ROWS,
                     stats_ext: bool = False):
    """Yield StreamFrames for a QueryResult (header/chunks per grid, then a
    final stats frame). ``stats_ext`` additionally emits the StatsExt frame
    — origin-opt-in via call metadata, like TraceTree/PartialWarnings, so
    an older origin that doesn't know the frame type never sees it."""
    for gi, g in enumerate(res.grids):
        vals = np.ascontiguousarray(g.values_np(), np.float32)
        hist = g.hist_np()
        hdr = pb.StreamFrame()
        hdr.header.grid_index = gi
        hdr.header.start_ms = int(g.start_ms)
        hdr.header.step_ms = int(g.step_ms)
        hdr.header.num_steps = int(g.num_steps)
        hdr.header.num_series = int(g.n_series)
        hdr.header.stale = bool(g.stale)
        rows_per_chunk = chunk_rows
        if hist is not None:
            hdr.header.has_hist = True
            hdr.header.hist_bins = int(hist.shape[2])
            if g.les is not None:
                hdr.header.les.extend(float(x) for x in np.asarray(g.les))
            # wide cubes (quantile sketches: B ~ 4k bins) must not blow the
            # 4 MB message cap at the dense worst case
            dense_row = int(hist.shape[1]) * int(hist.shape[2]) * 4
            rows_per_chunk = max(1, min(chunk_rows, (3 << 20) // max(dense_row, 1)))
        yield hdr
        for lo in range(0, g.n_series, rows_per_chunk):
            hi = min(lo + rows_per_chunk, g.n_series)
            fr = pb.StreamFrame()
            ch = fr.chunk
            ch.grid_index = gi
            ch.first_series = lo
            for lbls in g.labels[lo:hi]:
                sl = ch.labels.add()
                for k in sorted(lbls):
                    sl.pairs.add(name=k, value=str(lbls[k]))
            ch.values_f32 = vals[lo:hi].tobytes()
            if hist is not None:
                cube = np.ascontiguousarray(hist[lo:hi], np.float32)
                flat = cube.ravel()
                nz = np.flatnonzero(flat)
                if nz.size * 8 < flat.size * 4:
                    # sparse cube: log-linear sketches are mostly zeros.
                    # An all-zero chunk still writes one (idx, 0.0) entry so
                    # the decoder can tell it from "no hist in this chunk".
                    if nz.size == 0:
                        nz = np.array([0], np.int64)
                    ch.hist_idx_i32 = nz.astype(np.int32).tobytes()
                    ch.hist_f32 = flat[nz].tobytes()
                else:
                    ch.hist_f32 = cube.tobytes()
            yield fr
    if res.scalar is not None:
        fr = pb.StreamFrame()
        fr.scalar.start_ms = int(res.scalar.start_ms)
        fr.scalar.step_ms = int(res.scalar.step_ms)
        fr.scalar.num_steps = int(res.scalar.num_steps)
        fr.scalar.values_f64 = np.ascontiguousarray(
            np.asarray(res.scalar.values)[: res.scalar.num_steps], np.float64
        ).tobytes()
        yield fr
    if res.metadata is not None:
        fr = pb.StreamFrame()
        fr.metadata.json = json.dumps(res.metadata)
        yield fr
    if getattr(res, "warnings", None):
        # partial-result warnings ride an error frame with the reserved
        # non-fatal type (no proto schema change needed; the decoder folds
        # it into QueryResult.warnings instead of raising)
        yield error_frame(PARTIAL_WARNINGS, json.dumps(res.warnings))
    trace = getattr(res, "trace", None)
    if trace is not None:
        # the peer's span tree returns in-band like PartialWarnings; the
        # origin stitches it under the dispatching exec node's span
        from ..metrics import trace_to_dict

        yield error_frame(TRACE_TREE, json.dumps(trace_to_dict(trace)))
    # resource-attribution stats (kernel_ns, cache hit/miss/extend) ride an
    # in-band non-fatal frame like PartialWarnings: the StatsFrame proto
    # predates them and stays wire-stable for the 5 classic fields
    if stats_ext:
        ext = {
            "kernel_ns": int(res.stats.kernel_ns),
            "cache_hits": int(res.stats.cache_hits),
            "cache_misses": int(res.stats.cache_misses),
            "cache_extends": int(res.stats.cache_extends),
        }
        if any(ext.values()):
            yield error_frame(STATS_EXT, json.dumps(ext))
    fin = pb.StreamFrame()
    st = fin.stats
    st.series_scanned = int(res.stats.series_scanned)
    st.samples_scanned = int(res.stats.samples_scanned)
    st.cpu_ns = int(res.stats.cpu_ns)
    st.device_ns = int(res.stats.device_ns)
    st.bytes_staged = int(res.stats.bytes_staged)
    st.result_type = res.result_type
    yield fin


# error_type of the NON-FATAL warnings frame (partial results protocol)
PARTIAL_WARNINGS = "PartialWarnings"

# error_type of the NON-FATAL trace frame: the peer's span tree, rendered
# (metrics.Span.to_dict), returned alongside results for cross-node stitching
TRACE_TREE = "TraceTree"

# error_type of the NON-FATAL extended-stats frame: QueryStats fields newer
# than the StatsFrame proto (kernel_ns + cache event counts), JSON-encoded
STATS_EXT = "StatsExt"


def error_frame(error_type: str, message: str) -> "pb.StreamFrame":
    fr = pb.StreamFrame()
    fr.error.error_type = error_type
    fr.error.message = message
    return fr


class RemoteExecError(RuntimeError):
    """Transport/internal remote failure. In-band TYPED errors (rejection,
    deadline, query) re-raise as their local exception classes instead, so
    the origin's API edge maps them to the same status codes as local
    failures (503 backpressure / 503 timeout / 400 bad query)."""

    # peer-health classification (query/faults.py): transport failures count
    # against the endpoint's circuit breaker; the grpc client additionally
    # marks UNAVAILABLE-class instances retryable
    endpoint_failure = True
    retryable = False

    def __init__(self, error_type: str, message: str):
        super().__init__(f"{error_type}: {message}")
        self.error_type = error_type


def _raise_remote_error(error_type: str, message: str):
    if error_type == "AdmissionRejected":
        # the peer's admission control shed this request: re-raise the
        # typed local rejection (429-at-the-origin semantics; its
        # endpoint_failure classification lets sustained shedding open the
        # peer's breaker) with the peer's structured warning payload
        from .scheduler import AdmissionRejected

        try:
            w = json.loads(message)
        except ValueError:
            w = {}
        raise AdmissionRejected(
            f"remote peer shed request: {w.get('error', message)}",
            retry_after_s=float(w.get("retry_after_s", 1.0) or 1.0),
            ws=str(w.get("ws", "unknown")), ns=str(w.get("ns", "unknown")),
            outcome="shed_remote",
        )
    if error_type == "QueryRejected":
        from ..coordinator.scheduler import QueryRejected

        raise QueryRejected(f"remote: {message}")
    if error_type == "DeadlineExceeded":
        from .exec.transformers import QueryDeadlineExceeded

        raise QueryDeadlineExceeded(f"remote: {message}")
    if error_type in ("QueryError", "PlanDecodeError"):
        from .exec.transformers import QueryError

        raise QueryError(f"remote {error_type}: {message}")
    err = RemoteExecError(error_type, message)
    # an in-band error frame means the peer ANSWERED — its executor failed
    # on this query, but the endpoint is reachable and healthy; it must not
    # count against the circuit breaker (transport failures set the class
    # default or an explicit override in the grpc client instead)
    err.endpoint_failure = False
    raise err


def frames_to_result(frames) -> QueryResult:
    """Assemble a QueryResult from a StreamFrame iterator; raises
    RemoteExecError on an in-band error frame."""
    res = QueryResult()
    headers: dict[int, pb.GridHeader] = {}
    rows: dict[int, list] = {}
    stats_ext: dict | None = None
    for fr in frames:
        which = fr.WhichOneof("frame")
        if which == "header":
            h = fr.header
            headers[h.grid_index] = h
            rows.setdefault(h.grid_index, [])
        elif which == "chunk":
            rows.setdefault(fr.chunk.grid_index, []).append(fr.chunk)
        elif which == "scalar":
            s = fr.scalar
            res.scalar = ScalarResult(
                s.start_ms, s.step_ms, s.num_steps,
                np.frombuffer(s.values_f64, np.float64).copy(),
            )
            res.result_type = "scalar"
        elif which == "metadata":
            res.metadata = json.loads(fr.metadata.json)
            res.result_type = "metadata"
        elif which == "stats":
            st = fr.stats
            res.stats = QueryStats(
                series_scanned=st.series_scanned,
                samples_scanned=st.samples_scanned,
                cpu_ns=st.cpu_ns,
                device_ns=st.device_ns,
                bytes_staged=st.bytes_staged,
            )
            if st.result_type:
                res.result_type = st.result_type
        elif which == "error":
            if fr.error.error_type == PARTIAL_WARNINGS:
                res.warnings.extend(json.loads(fr.error.message))
                res.partial = True
            elif fr.error.error_type == TRACE_TREE:
                res.trace = json.loads(fr.error.message)
            elif fr.error.error_type == STATS_EXT:
                stats_ext = json.loads(fr.error.message)
            else:
                _raise_remote_error(fr.error.error_type, fr.error.message)
    if stats_ext:
        # applied after the loop: the StatsFrame may arrive in either order
        # and rebuilds res.stats from the 5 classic fields only
        res.stats.bump(**{k: int(v) for k, v in stats_ext.items()
                          if k in QueryStats._KEYS})
    for gi in sorted(headers):
        h = headers[gi]
        nb = int(h.hist_bins) or len(h.les)
        labels: list[dict] = []
        vparts: list[np.ndarray] = []
        hparts: list[np.ndarray] = []
        for ch in sorted(rows.get(gi, ()), key=lambda c: c.first_series):
            for sl in ch.labels:
                labels.append({p.name: p.value for p in sl.pairs})
            v = np.frombuffer(ch.values_f32, np.float32)
            vparts.append(v.reshape(-1, h.num_steps) if h.num_steps else v.reshape(len(ch.labels), 0))
            if h.has_hist and ch.hist_f32:
                hn = np.frombuffer(ch.hist_f32, np.float32)
                if ch.hist_idx_i32:
                    idx = np.frombuffer(ch.hist_idx_i32, np.int32)
                    dense = np.zeros(len(ch.labels) * h.num_steps * nb, np.float32)
                    dense[idx] = hn
                    hn = dense
                hparts.append(hn.reshape(-1, h.num_steps, nb))
        if len(labels) != h.num_series:
            raise RemoteExecError(
                "Internal", f"grid {gi}: got {len(labels)} series, header says {h.num_series}"
            )
        vals = (np.concatenate(vparts) if vparts
                else np.zeros((0, h.num_steps), np.float32)).copy()
        hist = np.concatenate(hparts).copy() if hparts else None
        les = np.asarray(h.les, np.float64) if h.has_hist and nb else None
        res.grids.append(Grid(labels, h.start_ms, h.step_ms, h.num_steps, vals,
                              hist=hist, les=les, stale=h.stale))
    return res
