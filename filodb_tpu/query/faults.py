"""Fault-tolerance layer for distributed scatter-gather execution
(reference analogs: ShardStatus ADT + ingestion-error damper treat shard
failure as first-class state; PromQlRemoteExec ships sttp retries; the
query circuit-breaker limits in filodb-defaults.conf).

Three cooperating pieces, all consulted by
:meth:`NonLeafExecPlan.execute_children` via :func:`dispatch_child`:

- :class:`RetryPolicy` — exponential backoff + deterministic jitter for
  remote child plans. Budgets derive from ``QueryContext.deadline_s``: a
  retry sequence never sleeps past the query deadline and every attempt's
  RPC timeout is the *remaining* budget, so a hung peer cannot stall a
  query beyond its deadline.
- :class:`CircuitBreaker` / :class:`BreakerRegistry` — per-endpoint
  closed -> open -> half-open breaker with a failure-rate threshold over a
  sliding outcome window and a cooldown before half-open probing. State
  transitions are recorded in :mod:`filodb_tpu.metrics`.
- :func:`dispatch_child` — the one choke point child execution flows
  through. ``QueryContext.dispatcher`` (e.g. the seeded
  :class:`~filodb_tpu.testkit.FaultInjector`) wraps the raw call; the
  breaker + retry discipline layers on top for ``is_remote`` children, so
  injected faults exercise exactly the production retry/breaker path.

Error classification: an exception retries only when transport-shaped
(``ConnectionError``/``TimeoutError``/``OSError`` or a ``retryable=True``
attribute, e.g. UNAVAILABLE RemoteExecError); it counts against the
endpoint's breaker when retryable or marked ``endpoint_failure=True``.
Typed query errors (bad PromQL, limits) do neither — a bad query is not a
sick peer.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable

from .exec.transformers import QueryError

_STATE_CLOSED = "closed"
_STATE_OPEN = "open"
_STATE_HALF_OPEN = "half_open"


class CircuitOpenError(QueryError):
    """Dispatch refused: the endpoint's breaker is open (fail-fast). The
    HTTP edge maps this to 503 like other unavailability."""


def is_retryable(exc: BaseException) -> bool:
    """Transport-shaped failures worth another attempt."""
    return isinstance(exc, (ConnectionError, TimeoutError, OSError)) or bool(
        getattr(exc, "retryable", False)
    )


def is_endpoint_failure(exc: BaseException) -> bool:
    """Failures that count against the endpoint's breaker (peer health),
    as opposed to query-shaped errors the peer answered correctly."""
    return is_retryable(exc) or bool(getattr(exc, "endpoint_failure", False))


# ---------------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff + jitter, budgeted by the query deadline.

    ``seed`` makes the jitter sequence deterministic (chaos tests);
    ``sleep`` is injectable so tests can record/skip real waiting.
    """

    max_attempts: int = 3  # total tries, including the first
    base_backoff_s: float = 0.1
    multiplier: float = 2.0
    max_backoff_s: float = 2.0
    jitter: float = 0.5  # fraction of each backoff that is randomized
    seed: int | None = None
    sleep: Callable[[float], None] = time.sleep

    def backoff_s(self, retry_index: int, rng) -> float:
        b = min(self.base_backoff_s * self.multiplier**retry_index, self.max_backoff_s)
        if self.jitter <= 0:
            return b
        return b * (1.0 - self.jitter) + b * self.jitter * rng.random()

    def rng(self):
        return random.Random(self.seed) if self.seed is not None else random


DEFAULT_RETRY_POLICY = RetryPolicy()


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


class CircuitBreaker:
    """closed -> open -> half-open breaker over a sliding outcome window.

    Opens when, among the last ``window`` outcomes (and at least
    ``min_calls`` of them), the failure rate reaches ``failure_rate``.
    After ``cooldown_s`` it admits up to ``half_open_max`` probe calls:
    a probe success re-closes, a probe failure re-opens (fresh cooldown).
    """

    def __init__(self, endpoint: str, window: int = 16, failure_rate: float = 0.5,
                 min_calls: int = 4, cooldown_s: float = 15.0,
                 half_open_max: int = 1, clock: Callable[[], float] = time.monotonic):
        self.endpoint = endpoint
        self.failure_rate = failure_rate
        self.min_calls = min_calls
        self.cooldown_s = cooldown_s
        self.half_open_max = half_open_max
        self._clock = clock
        self._outcomes: deque[bool] = deque(maxlen=window)
        self._state = _STATE_CLOSED
        self._opened_at = 0.0
        self._half_open_inflight = 0
        self._lock = threading.Lock()

    # -- state ------------------------------------------------------------

    def state(self) -> str:
        with self._lock:
            self._tick()
            return self._state

    def _tick(self) -> None:
        """Lock held: open -> half-open once the cooldown elapses."""
        if self._state == _STATE_OPEN and (
            self._clock() - self._opened_at >= self.cooldown_s
        ):
            self._transition(_STATE_HALF_OPEN)
            self._half_open_inflight = 0

    def _transition(self, state: str) -> None:
        if state == self._state:
            return
        from ..metrics import record_breaker_transition

        record_breaker_transition(self.endpoint, self._state, state)
        self._state = state

    # -- consultation ------------------------------------------------------

    def allow(self) -> bool:
        """May a call be dispatched now? Half-open admits only probes."""
        with self._lock:
            self._tick()
            if self._state == _STATE_CLOSED:
                return True
            if self._state == _STATE_HALF_OPEN and self._half_open_inflight < self.half_open_max:
                self._half_open_inflight += 1
                return True
            return False

    def record_neutral(self) -> None:
        """The call completed with a query-shaped error — the peer answered,
        but it is not success evidence either. Frees a half-open probe slot
        so a typed error during probing cannot wedge the breaker."""
        with self._lock:
            if self._state == _STATE_HALF_OPEN and self._half_open_inflight > 0:
                self._half_open_inflight -= 1

    def record_success(self) -> None:
        with self._lock:
            if self._state == _STATE_HALF_OPEN:
                self._transition(_STATE_CLOSED)
                self._outcomes.clear()
                self._half_open_inflight = 0
            else:
                self._outcomes.append(True)

    def record_failure(self) -> None:
        with self._lock:
            self._tick()
            if self._state == _STATE_HALF_OPEN:
                self._opened_at = self._clock()
                self._transition(_STATE_OPEN)
                self._half_open_inflight = 0
                return
            self._outcomes.append(False)
            n = len(self._outcomes)
            fails = n - sum(self._outcomes)
            if (
                self._state == _STATE_CLOSED
                and n >= self.min_calls
                and fails / n >= self.failure_rate
            ):
                self._opened_at = self._clock()
                self._transition(_STATE_OPEN)
                self._outcomes.clear()


class BreakerRegistry:
    """One breaker per endpoint, created on demand with shared settings."""

    def __init__(self, clock: Callable[[], float] = time.monotonic, **breaker_kw):
        self._clock = clock
        self._kw = breaker_kw
        self._breakers: dict[str, CircuitBreaker] = {}
        self._lock = threading.Lock()

    def breaker_for(self, endpoint: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(endpoint)
            if br is None:
                br = CircuitBreaker(endpoint, clock=self._clock, **self._kw)
                self._breakers[endpoint] = br
            return br

    def states(self) -> dict[str, str]:
        with self._lock:
            breakers = list(self._breakers.values())
        return {b.endpoint: b.state() for b in breakers}

    def reset(self) -> None:
        with self._lock:
            self._breakers.clear()


# process-wide default registry (server deployments may build their own with
# tuned thresholds via PlannerParams.breakers)
GLOBAL_BREAKERS = BreakerRegistry()


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------


def child_warning(child, exc: BaseException) -> dict:
    """Structured warning for a child lost under allow_partial_results."""
    w = {
        "plan": type(child).__name__,
        "args": child.args_str(),
        "error": f"{type(exc).__name__}: {exc}",
    }
    shard = getattr(child, "shard_num", None)
    if shard is not None:
        w["shard"] = int(shard)
    endpoint = getattr(child, "endpoint", None)
    if endpoint:
        w["endpoint"] = endpoint
    return w


def dispatch_child(child, ctx):
    """Execute one child plan under the fault-tolerance policy.

    The context's ``dispatcher`` (fault-injection hook) wraps the raw
    execution; remote children additionally consult their endpoint's
    circuit breaker and retry transient failures within the remaining
    deadline budget.
    """
    dispatcher = getattr(ctx, "dispatcher", None)
    if dispatcher is not None:
        base = dispatcher.dispatch
    else:
        def base(c, x):
            return c.execute(x)

    if not getattr(child, "is_remote", False):
        return base(child, ctx)
    endpoint = getattr(child, "endpoint", None) or type(child).__name__
    siblings = tuple(getattr(child, "sibling_endpoints", ()) or ())
    if siblings and hasattr(child, "with_endpoint"):
        return _dispatch_with_failover(child, ctx, base, endpoint, siblings)
    res = call_with_retries(lambda: base(child, ctx), ctx, endpoint)
    _note_endpoint(ctx, endpoint)
    return res


def _note_endpoint(ctx, endpoint: str) -> None:
    """Record the serving endpoint on the query's observatory annotations so
    the querylog entry (and /api/v1/query_profile) shows who answered."""
    obs = getattr(ctx, "obs", None)
    if obs is None:
        return
    eps = obs.setdefault("endpoints", [])
    if endpoint not in eps:
        eps.append(endpoint)


def _dispatch_with_failover(child, ctx, base, endpoint, siblings):
    """Replica failover: a breaker-open or endpoint-failure result on one
    replica is a ROUTING signal — re-pin the leg to the next sibling replica
    (same plan, same shard subset) before allow_partial_results is even
    considered. Non-endpoint errors (real query errors) raise immediately:
    a sibling would answer the same way."""
    from ..metrics import record_replica_failover, record_replica_selection

    cands = (endpoint,) + tuple(s for s in siblings if s != endpoint)
    last_exc = None
    for i, ep in enumerate(cands):
        c = child if i == 0 else child.with_endpoint(ep)
        try:
            res = call_with_retries(lambda: base(c, ctx), ctx, ep)
        except CircuitOpenError as e:
            last_exc = e
            if i + 1 < len(cands):
                record_replica_failover(ep, "breaker_open")
                continue
            raise
        except Exception as e:  # noqa: BLE001 — classified below
            last_exc = e
            if is_endpoint_failure(e) and i + 1 < len(cands):
                record_replica_failover(ep, "endpoint_failure")
                continue
            raise
        record_replica_selection("primary" if i == 0 else "sibling")
        _note_endpoint(ctx, ep)
        return res
    raise last_exc


def call_with_retries(fn, ctx, endpoint: str):
    """Run ``fn`` with breaker consultation + budgeted backoff retries.

    Retry and breaker events annotate the active span (the dispatching merge
    node's — each ATTEMPT produces its own child span via the child's
    execute, so per-endpoint counters live one level up where they
    aggregate), making them visible in EXPLAIN ANALYZE output and the
    slow-query log."""
    from ..metrics import current_span, record_remote_retry

    policy: RetryPolicy = getattr(ctx, "retry_policy", None) or DEFAULT_RETRY_POLICY
    registry: BreakerRegistry = getattr(ctx, "breakers", None) or GLOBAL_BREAKERS
    breaker = registry.breaker_for(endpoint)
    sp = current_span()
    rng = policy.rng()
    attempt = 0
    while True:
        ctx.check_deadline()
        if not breaker.allow():
            if sp is not None:
                opens = sp.tags.setdefault("breaker_open", [])
                if endpoint not in opens:
                    opens.append(endpoint)
            raise CircuitOpenError(f"circuit breaker open for endpoint {endpoint}")
        state = breaker.state()
        if state != _STATE_CLOSED and sp is not None:
            sp.tags.setdefault("breaker_state", {})[endpoint] = state
        try:
            res = fn()
        except Exception as e:  # noqa: BLE001 — classified below
            if is_endpoint_failure(e):
                breaker.record_failure()
            else:
                # typed query error: the peer answered — release any
                # half-open probe slot without a state transition
                breaker.record_neutral()
            if not is_retryable(e):
                raise
            attempt += 1
            if attempt >= policy.max_attempts:
                raise
            if breaker.state() == _STATE_OPEN:
                # this failure (or a sibling's) just opened the breaker:
                # surface the REAL transport error now rather than sleeping
                # into a CircuitOpenError that would mask it
                raise
            backoff = policy.backoff_s(attempt - 1, rng)
            remaining = ctx.remaining_deadline_s()
            if backoff >= remaining:
                # sleeping would outlive the query deadline: surface the
                # last transport error now instead of burning the budget
                raise
            record_remote_retry(endpoint)
            if sp is not None:
                retries = sp.tags.setdefault("retries", {})
                retries[endpoint] = retries.get(endpoint, 0) + 1
            policy.sleep(backoff)
            continue
        breaker.record_success()
        return res
