"""Result model (reference core/.../query/RangeVector.scala:129 —
RangeVector/RawDataRangeVector:365/SerializedRangeVector:504).

TPU-native reframing: instead of per-series RangeVector iterators, results
travel as **grids** — a batch of series sharing one step grid with a dense
``[S, J]`` value matrix (NaN = absent), optionally ``[S, J, B]`` for native
histograms. Grids stay on device through transformer chains; serialization
pulls to host once at the edge.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field, replace
from typing import Any, Sequence

import numpy as np


@dataclass
class Grid:
    """A batch of series on a shared step grid."""

    labels: list[dict]  # [S] per-series label sets
    start_ms: int
    step_ms: int
    num_steps: int
    values: Any  # [S, J] (device or numpy); J >= num_steps (padding allowed)
    hist: Any | None = None  # [S, J, B] bucket values when histogram-kind
    les: np.ndarray | None = None  # [B] bucket bounds for hist
    stale: bool = False

    @property
    def n_series(self) -> int:
        return len(self.labels)

    def step_times_ms(self) -> np.ndarray:
        return self.start_ms + np.arange(self.num_steps, dtype=np.int64) * self.step_ms

    def values_np(self) -> np.ndarray:
        """[S, num_steps] numpy view (device fetch if needed)."""
        v = np.asarray(self.values)
        return v[: self.n_series, : self.num_steps]

    def hist_np(self) -> np.ndarray | None:
        if self.hist is None:
            return None
        h = np.asarray(self.hist)
        return h[: self.n_series, : self.num_steps]

    def with_values(self, values, hist=None) -> "Grid":
        return replace(self, values=values, hist=hist if hist is not None else None,
                       les=self.les if hist is not None else None)


@dataclass
class RawGrid:
    """Pre-periodic staged raw chunk windows (reference RawDataRangeVector)."""

    block: Any  # ops.staging.StagedBlock
    labels: list[dict]
    schema_name: str
    value_column: str
    is_counter: bool
    is_delta: bool
    is_histogram: bool
    les: np.ndarray | None = None


@dataclass
class ScalarResult:
    """A scalar-per-step result ([J] array) — promql scalar type."""

    start_ms: int
    step_ms: int
    num_steps: int
    values: np.ndarray  # [J]


# serializes mutations of a QueryContext's stats: local children bump() on
# the plan thread while remote children merge() from dispatch-pool threads,
# and a bare '+=' read-modify-write can lose an update across that overlap.
# One process-wide lock (never held across I/O) beats a per-instance Lock
# field, which would break dataclass replace()/equality expectations.
_STATS_LOCK = threading.Lock()


@dataclass
class QueryStats:
    """reference QuerySession.queryStats (ExecPlan.scala:430)."""

    series_scanned: int = 0
    samples_scanned: int = 0
    cpu_ns: int = 0
    device_ns: int = 0
    bytes_staged: int = 0
    # resource attribution (doc/observability.md "Resource accounting"):
    # kernel_ns sums the ops/ dispatch wall-times the query triggered
    # (record_kernel_dispatch via the activated stats); cache_* count the
    # staging/superblock cache events the query's staging path took —
    # hits (served cached), misses (full stage/build), extends (in-place
    # incremental repair/extension)
    kernel_ns: int = 0
    cache_hits: int = 0
    cache_misses: int = 0
    cache_extends: int = 0

    _KEYS = ("series_scanned", "samples_scanned", "cpu_ns", "device_ns",
             "bytes_staged", "kernel_ns", "cache_hits", "cache_misses",
             "cache_extends")

    def merge(self, other: "QueryStats") -> None:
        with _STATS_LOCK:
            for k in self._KEYS:
                setattr(self, k, getattr(self, k) + getattr(other, k))

    def bump(self, **deltas: int) -> None:
        """Atomic increment of one or more counters (the '+=' replacement
        for stats shared across scatter threads)."""
        with _STATS_LOCK:
            for k, v in deltas.items():
                setattr(self, k, getattr(self, k) + v)

    def is_empty(self) -> bool:
        return not any(getattr(self, k) for k in self._KEYS)

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in self._KEYS}

    def snapshot(self) -> tuple:
        return tuple(getattr(self, k) for k in self._KEYS)

    def delta_since(self, snap: tuple) -> dict:
        """Per-plan-node stats attribution: what this node (and, inclusively,
        its subtree) added to the query-wide stats since ``snap``."""
        now = self.snapshot()
        return {k: now[i] - snap[i] for i, k in enumerate(self._KEYS) if now[i] != snap[i]}


@dataclass
class QueryResult:
    """Exec output: grids (vector results), a scalar, or raw export data."""

    grids: list[Grid] = field(default_factory=list)
    raw_grids: list[RawGrid] = field(default_factory=list)  # pre-periodic staged
    scalar: ScalarResult | None = None
    raw: list[tuple[dict, np.ndarray, np.ndarray]] | None = None  # (labels, ts, vals)
    stats: QueryStats = field(default_factory=QueryStats)
    result_type: str = "matrix"  # matrix | vector | scalar | metadata
    metadata: list | None = None  # label values / names / series results
    # partial-results protocol (query/faults.py): structured warnings for
    # children lost under QueryContext.allow_partial_results; partial=True
    # marks a result merged from a strict subset of its shards/peers
    warnings: list[dict] = field(default_factory=list)
    partial: bool = False
    # tracing (metrics.py): the query's span tree. At the engine edge this
    # is the root Span; on a result crossing a transport it is the peer's
    # rendered dict, which ExecPlan.execute grafts into the local trace
    trace: Any | None = None

    def all_series(self):
        """Iterate (labels, ts_ms[], values[]) dropping NaN points."""
        for g in self.grids:
            vals = g.values_np()
            times = g.step_times_ms()
            for i, lbls in enumerate(g.labels):
                row = vals[i]
                m = ~np.isnan(row)
                if m.any():
                    yield lbls, times[m], row[m]
