"""Query dispatch scheduler: cross-query micro-batching + per-tenant
admission control — the layer between the planner and the fused engine
(ROADMAP "cross-query batching + admission control for high-QPS serving";
Storyboard's workload-aware sharing of precomputed aggregate work across
queries, Tailwind's explicit dispatch/admission layer in front of the
accelerator — PAPERS.md).

Two cooperating pieces:

- :class:`DispatchScheduler` — a micro-batching dispatcher for
  ``FusedAggregateExec`` kernel launches. Concurrent fused queries hitting
  the SAME device-resident superblock with the same grid/epilogue signature
  (the coalescing key) collect for a short window (config
  ``query.batch_window_ms``) and launch as ONE batched kernel — jax.vmap
  over the per-query dynamics (window length, offset, q, group-by variant)
  on the existing fused programs (ops/aggregations.fused_batched_scalar /
  fused_batched_hist) — then the stacked ``[Q, G, J]`` partials fan back
  out to each waiting query. Identical dispatch specs dedup onto one lane
  (the single-flight discipline of filodb_tpu/singleflight.py applied at
  the lane level: N identical specs share one future, never N lanes), and
  identical FULL queries never reach this layer at all — the engine-level
  SingleFlight (coordinator.scheduler, ``coalesce_identical``) already
  shares one execution among them. Lanes group per window triple for
  executable stability, but a SEALING leader re-merges every still-open
  group that is compatible on everything else (``FusedRequest.merge_key``)
  into one mixed-window launch — the ops layer's u_map machinery routes
  each lane to its own window, bit-parity per lane — counted in
  ``filodb_batch_merged_windows_total{family}``. The first arrival for a key leads: it
  holds the window open (bounded by ``max_batch``), executes, and
  distributes; a batch-path failure falls back to per-lane unbatched
  execution so batching is strictly an optimization, never a correctness
  risk.

- :class:`AdmissionController` — per-tenant token-bucket rate +
  concurrency quotas (config ``query.tenant_quotas``, tenants resolved via
  :func:`filodb_tpu.metering.tenant_of_plan`) and a global queue-depth
  bound, consulted by the QueryEngine BEFORE execution. Over-quota queries
  shed with :class:`AdmissionRejected`, which the HTTP edge maps to
  429 + ``Retry-After`` (plus a structured warning in the error envelope)
  and the gRPC edge to an in-band typed error frame + retry-after call
  metadata. A shed REMOTE child carries ``endpoint_failure=True`` so
  sustained shedding opens the origin's circuit breaker for that peer
  (query/faults.py), and under ``allow_partial_results`` merge nodes
  degrade it exactly like a faulted child — structured warning, survivors
  served.

Tenant label cardinality is bounded by the same ``MAX_TENANT_PAIRS``
overflow-bucket cap the metering counters use
(:func:`filodb_tpu.metering.bounded_tenant_pair`).
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeout
from dataclasses import dataclass
from typing import Any, Callable

from ..metrics import REGISTRY
from .exec.transformers import QueryDeadlineExceeded, QueryError

# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------


class AdmissionRejected(QueryError):
    """Query shed by admission control (over-quota tenant or a saturated
    global queue). HTTP: 429 + ``Retry-After: <retry_after_s>``; gRPC: the
    ``AdmissionRejected`` in-band error frame + ``x-filodb-retry-after``
    metadata.

    Peer-health classification (query/faults.py): NOT retryable within the
    same dispatch (retrying before ``retry_after_s`` would defeat the
    shed), but it IS endpoint-failure evidence — a peer shedding our
    scatter legs is overloaded, and sustained shedding should open its
    breaker so the origin backs off for the cooldown instead of hammering
    it."""

    retryable = False
    endpoint_failure = True

    def __init__(self, message: str, retry_after_s: float = 1.0,
                 ws: str = "unknown", ns: str = "unknown",
                 outcome: str = "shed_rate",
                 predicted_cost_s: float = 0.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)
        self.ws = ws
        self.ns = ns
        self.outcome = outcome
        self.predicted_cost_s = float(predicted_cost_s)

    def warning(self) -> dict:
        """The structured warning shape riding error envelopes and partial
        results (mirrors faults.child_warning)."""
        return {
            "reason": "admission_rejected",
            "outcome": self.outcome,
            "ws": self.ws,
            "ns": self.ns,
            "retry_after_s": round(self.retry_after_s, 3),
            "predicted_cost_s": round(self.predicted_cost_s, 6),
            "error": str(self),
        }


class TokenBucket:
    """Classic token bucket with an injectable clock (deterministic
    tests). ``rate`` tokens/second refill up to ``burst``; ``try_take``
    returns 0.0 on success or the seconds until enough tokens accrue.

    Tokens are unit-agnostic: admission runs its buckets in
    device-seconds (``try_take(predicted_cost_s)``), so an expensive
    query drains proportionally more than a cheap one. A cost above the
    bucket capacity is clamped TO the capacity — the request admits after
    a full drain-and-refill rather than starving forever, and the
    returned wait is therefore always an achievable drain time (the
    Retry-After contract: shed, wait the advertised seconds, admit).
    ``min_burst`` floors the capacity (1.0 = one legacy query token)."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic,
                 min_burst: float = 1.0):
        self.rate = float(rate)
        self.burst = max(float(burst), float(min_burst))
        self._clock = clock
        self._tokens = self.burst
        self._last = clock()
        self._lock = threading.Lock()

    def _refill(self, now: float) -> None:
        if now > self._last:
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rate
            )
            self._last = now

    def try_take(self, cost: float = 1.0) -> float:
        with self._lock:
            c = min(max(float(cost), 0.0), self.burst)
            now = self._clock()
            self._refill(now)
            # nanosecond-of-device-time tolerance: refill accumulates
            # float error at large clock values, and the Retry-After
            # contract (shed, wait the advertised seconds, admit) must
            # not fail by one ulp of (now - last) * rate
            if self._tokens >= c - 1e-9:
                self._tokens = max(self._tokens - c, 0.0)
                return 0.0
            if self.rate <= 0:
                return float("inf")
            return (c - self._tokens) / self.rate

    def balance(self) -> float:
        with self._lock:
            self._refill(self._clock())
            return self._tokens


@dataclass(frozen=True)
class TenantQuota:
    """Per-tenant admission quota. Buckets run in DEVICE-SECONDS (the
    cost model's currency): ``rate_device_s`` device-seconds/second
    refill up to ``burst_device_s``. Legacy query-count quotas
    (``rate``/``burst``) are still accepted and converted at bucket-build
    time via the cost model's flat prior — since the default per-query
    cost IS that prior, an unconfigured deployment's admission decisions
    are unchanged by the unit conversion. ``rate``/``rate_device_s`` <= 0
    disables the token bucket; ``max_concurrent`` <= 0 disables the
    concurrency cap."""

    rate: float = 0.0  # legacy: queries/second refill
    burst: float = 0.0  # legacy: capacity in queries; <= 0 -> max(rate, 1)
    max_concurrent: int = 0
    rate_device_s: float = 0.0  # device-seconds/second refill (preferred)
    burst_device_s: float = 0.0  # capacity in device-seconds

    @classmethod
    def from_config(cls, cfg: dict) -> "TenantQuota":
        return cls(
            rate=float(cfg.get("rate", 0.0) or 0.0),
            burst=float(cfg.get("burst", 0.0) or 0.0),
            max_concurrent=int(cfg.get("max_concurrent", 0) or 0),
            rate_device_s=float(cfg.get("rate_device_s", 0.0) or 0.0),
            burst_device_s=float(cfg.get("burst_device_s", 0.0) or 0.0),
        )

    def device_rate(self, prior_cost_s: float) -> float:
        """Refill rate in device-seconds/second (legacy queries/s × the
        family prior when no native device-second rate is configured)."""
        if self.rate_device_s > 0:
            return self.rate_device_s
        return self.rate * prior_cost_s

    def device_burst(self, prior_cost_s: float) -> float:
        if self.burst_device_s > 0:
            return self.burst_device_s
        if self.rate_device_s > 0:
            return max(self.rate_device_s, prior_cost_s)
        q_burst = self.burst if self.burst > 0 else max(self.rate, 1.0)
        return q_burst * prior_cost_s


class _TenantState:
    __slots__ = ("bucket", "quota", "in_flight", "shed")

    def __init__(self, quota: TenantQuota | None, clock,
                 prior_cost_s: float = 1.0):
        self.quota = quota
        self.bucket = None
        if quota is not None and (quota.rate > 0 or quota.rate_device_s > 0):
            self.bucket = TokenBucket(
                quota.device_rate(prior_cost_s),
                quota.device_burst(prior_cost_s), clock,
                # capacity floor = ONE prior-priced query, not one legacy
                # token: device-second bursts are fractions of 1.0
                min_burst=prior_cost_s,
            )
        self.in_flight = 0
        self.shed = 0


class AdmissionController:
    """Per-tenant token-bucket rate/concurrency quotas + a global
    queue-depth bound, in front of query execution.

    ``quotas`` maps ``"ws/ns"`` keys (or ``"*"`` for the default applied to
    every tenant without an explicit entry — including ``unknown``) to
    quota dicts ``{"rate", "burst", "max_concurrent"}``. ``max_queued``
    bounds admitted-and-unfinished queries process-wide (0 = unbounded).
    Shedding outcomes are counted in
    ``filodb_admission_total{outcome,ws,ns}`` with the metering module's
    overflow-bucket cardinality cap; per-tenant token balances and shed
    counts are inspectable at ``GET /debug/scheduler``."""

    def __init__(self, quotas: dict | None = None, max_queued: int = 0,
                 clock: Callable[[], float] = time.monotonic,
                 retry_after_default_s: float = 1.0,
                 prior_cost_s: float | None = None):
        from .costmodel import DEFAULT_PRIOR_COST_S

        self._quotas = {
            k: (q if isinstance(q, TenantQuota) else TenantQuota.from_config(q))
            for k, q in (quotas or {}).items()
        }
        self.max_queued = int(max_queued)
        self._clock = clock
        self.retry_after_default_s = float(retry_after_default_s)
        # the legacy-quota conversion rate AND the default price of a
        # query admitted without a prediction — one constant, so counting
        # queries and counting prior-priced device-seconds are identical
        self.prior_cost_s = max(
            float(prior_cost_s if prior_cost_s is not None
                  else DEFAULT_PRIOR_COST_S), 1e-6)
        self._states: dict[str, _TenantState] = {}
        self._in_flight = 0
        self._shed_total = 0
        self._lock = threading.Lock()

    def _quota_for(self, key: str) -> TenantQuota | None:
        return self._quotas.get(key) or self._quotas.get("*")

    def _state(self, key: str) -> _TenantState:
        st = self._states.get(key)
        if st is None:
            st = self._states[key] = _TenantState(
                self._quota_for(key), self._clock, self.prior_cost_s
            )
        return st

    def _count(self, outcome: str, ws: str, ns: str) -> None:
        REGISTRY.counter(
            "filodb_admission", outcome=outcome, ws=ws, ns=ns
        ).inc()

    def admit(self, ws: str, ns: str, cost_s: float | None = None):
        """Admit or shed one query for tenant (ws, ns), draining the
        tenant's device-second bucket by ``cost_s`` (the cost model's
        prediction; the flat prior when the caller has none). Returns a
        context manager holding the tenant + global concurrency slots;
        raises :class:`AdmissionRejected` with the bucket's ACTUAL
        predicted drain time as ``Retry-After`` when the query must
        shed."""
        from ..metering import bounded_tenant_pair

        cost = (float(cost_s) if cost_s is not None and cost_s > 0
                else self.prior_cost_s)
        ws, ns = bounded_tenant_pair(ws, ns)
        key = f"{ws}/{ns}"
        with self._lock:
            st = self._state(key)
            quota = st.quota
            if (quota is not None and quota.max_concurrent > 0
                    and st.in_flight >= quota.max_concurrent):
                st.shed += 1
                self._shed_total += 1
                self._count("shed_concurrency", ws, ns)
                raise AdmissionRejected(
                    f"tenant {key} at max_concurrent="
                    f"{quota.max_concurrent}",
                    retry_after_s=self.retry_after_default_s,
                    ws=ws, ns=ns, outcome="shed_concurrency",
                )
            if self.max_queued > 0 and self._in_flight >= self.max_queued:
                st.shed += 1
                self._shed_total += 1
                self._count("shed_queue", ws, ns)
                raise AdmissionRejected(
                    f"query queue depth {self._in_flight} at bound "
                    f"{self.max_queued}",
                    retry_after_s=self.retry_after_default_s,
                    ws=ws, ns=ns, outcome="shed_queue",
                )
            if st.bucket is not None:
                charge = cost
                if quota is not None and quota.rate_device_s <= 0:
                    # legacy query-count quota: never charge LESS than one
                    # prior-priced query — the operator said "N queries/s"
                    # and a swarm of model-priced cheap queries must not
                    # turn that into thousands/s; an expensive query still
                    # drains proportionally MORE than one
                    charge = max(cost, self.prior_cost_s)
                wait_s = st.bucket.try_take(charge)
                if wait_s > 0:
                    st.shed += 1
                    self._shed_total += 1
                    self._count("shed_rate", ws, ns)
                    raise AdmissionRejected(
                        f"tenant {key} over device-second quota "
                        f"({st.bucket.rate:g} dev-s/s; query predicted "
                        f"{cost:g} dev-s)",
                        # the bucket's computed drain time IS the hint —
                        # waiting it out admits by construction (regression
                        # tested in tests/test_costmodel.py)
                        retry_after_s=min(
                            wait_s, 60.0
                        ) if wait_s != float("inf")
                        else self.retry_after_default_s,
                        ws=ws, ns=ns, outcome="shed_rate",
                        predicted_cost_s=cost,
                    )
            st.in_flight += 1
            self._in_flight += 1
        self._count("admitted", ws, ns)
        return _Admitted(self, key)

    def _release(self, key: str) -> None:
        with self._lock:
            st = self._states.get(key)
            if st is not None and st.in_flight > 0:
                st.in_flight -= 1
            self._in_flight = max(0, self._in_flight - 1)

    def snapshot(self) -> dict:
        """The /debug/scheduler rendering: global depth + per-tenant token
        balances, in-flight counts and shed totals."""
        with self._lock:
            tenants = {
                key: {
                    "in_flight": st.in_flight,
                    "shed": st.shed,
                    "tokens": (round(st.bucket.balance(), 3)
                               if st.bucket is not None else None),
                    "rate": st.quota.rate if st.quota else None,
                    "rate_device_s": (round(st.bucket.rate, 6)
                                      if st.bucket is not None else None),
                    "burst_device_s": (round(st.bucket.burst, 6)
                                       if st.bucket is not None else None),
                    "max_concurrent": (st.quota.max_concurrent
                                       if st.quota else None),
                }
                for key, st in self._states.items()
            }
            return {
                "in_flight": self._in_flight,
                "max_queued": self.max_queued,
                "shed_total": self._shed_total,
                "unit": "device_seconds",
                "prior_cost_s": self.prior_cost_s,
                "tenants": tenants,
            }


class _Admitted:
    """Held concurrency slot; releases on exit (success or failure)."""

    __slots__ = ("_ctl", "_key")

    def __init__(self, ctl: AdmissionController, key: str):
        self._ctl = ctl
        self._key = key

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._ctl._release(self._key)
        return False


# ---------------------------------------------------------------------------
# per-key recurrence ring (standing-query promotion feed)
# ---------------------------------------------------------------------------


class KeyStatsRing:
    """Bounded per-key recurrence/age ring over fused-dispatch coalescing
    keys. The scheduler's only per-key state used to be the OPEN batch
    group, dropped the moment the group sealed — recurrence (the signal
    that millions of users watch the SAME dashboard) was thrown away every
    batch window. The ring RETAINS it: one LRU-bounded entry per
    normalized key with a cumulative count, first/last-seen wall clocks, a
    short deque of recent observation times (the promotion-burst window)
    and the latest descriptor (promql, grid shape, live-edge lag) the
    standing-query promoter needs to re-register the query
    (standing/registry.py). Observed on EVERY fused dispatch — batching
    enabled or not — so promotion works on latency-critical deployments
    that keep ``batch_window_ms`` at 0. Exposed at ``/debug/standing``
    alongside the promoted/demoted registry state."""

    RECENT_MAX = 32  # per-entry burst window (>= any sane promote_min_count)

    __slots__ = ("max_entries", "_entries", "_lock", "_clock")

    def __init__(self, max_entries: int = 512,
                 clock: Callable[[], float] = time.time):
        self.max_entries = max(int(max_entries), 1)
        self._entries: dict[Any, dict] = {}  # insertion-ordered (LRU)
        self._lock = threading.Lock()
        self._clock = clock

    def observe(self, key, desc: dict | None = None) -> None:
        """Count one recurrence of ``key``; ``desc`` (latest wins) carries
        whatever the promoter needs to act on the key."""
        now = self._clock()
        with self._lock:
            e = self._entries.pop(key, None)
            if e is None:
                e = {
                    "count": 0,
                    "first_s": now,
                    "recent": deque(maxlen=self.RECENT_MAX),
                    "desc": None,
                }
            e["count"] += 1
            e["last_s"] = now
            e["recent"].append(now)
            if desc is not None:
                e["desc"] = desc
            self._entries[key] = e  # move-to-back = most recent
            while len(self._entries) > self.max_entries:
                self._entries.pop(next(iter(self._entries)))

    @staticmethod
    def _copy(e: dict) -> dict:
        # ``recent`` rendered as an immutable tuple: observe() keeps
        # appending to the live deque from query threads, and iterating a
        # deque mid-mutation raises — callers only ever see copies
        return {
            "count": e["count"],
            "first_s": e["first_s"],
            "last_s": e["last_s"],
            "recent": tuple(e["recent"]),
            "desc": e.get("desc"),
        }

    def entries(self) -> list[tuple[Any, dict]]:
        """(key, entry-copy) pairs, most-recently-seen last. Copies taken
        under the ring's lock — safe to iterate while observe() keeps
        mutating the live entries."""
        with self._lock:
            return [(k, self._copy(e)) for k, e in self._entries.items()]

    def get(self, key) -> dict | None:
        with self._lock:
            e = self._entries.get(key)
            return self._copy(e) if e is not None else None

    def snapshot(self, limit: int = 64) -> list[dict]:
        """The /debug/standing rendering: newest-first, descriptors
        included, recent-burst deques rendered as their span."""
        now = self._clock()
        out = []
        items = self.entries()
        for key, e in reversed(items[-limit:] if limit else items):
            recent = e["recent"]
            out.append({
                "key": repr(key),
                "count": e["count"],
                "age_s": round(now - e["first_s"], 3),
                "idle_s": round(now - e["last_s"], 3),
                "recent": len(recent),
                "recent_span_s": (
                    round(recent[-1] - recent[0], 3) if len(recent) > 1
                    else 0.0
                ),
                "desc": e.get("desc"),
            })
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


# ---------------------------------------------------------------------------
# micro-batching dispatch
# ---------------------------------------------------------------------------


@dataclass
class FusedRequest:
    """One fused-kernel dispatch wish: everything the batched program needs
    from this query, plus the unbatched fallback. Built by
    ``FusedAggregateExec`` AFTER superblock resolution and group-id
    memoization, so batching composes with (and never bypasses) limits,
    stats accounting and cache maintenance — only the kernel launch itself
    is shared."""

    block: Any  # the (super)block object — group identity AND data source
    func: str
    kind: str  # "agg" | "topk" | "quantile" | "hist"
    epilogue: tuple  # scalar static epilogue; () for hist
    gids_dev: Any  # [S_pad] device group ids (trash group = G)
    G: int  # this lane's real group count
    qv: float  # quantile q / hist_quantile q; 0.0 otherwise
    params: Any  # RangeParams (start/step/window are the vmapped dynamics)
    j_pad: int
    is_counter: bool
    is_delta: bool
    mesh: Any = None
    mesh_desc: tuple | None = None
    les_dev: Any = None  # hist bucket bounds (device)
    hist_q: bool = False  # hist lane wants the quantile epilogue
    run_single: Callable[[], Any] = None
    timeout_s: float = 60.0
    # the cost model's device-second prediction for the owning query
    # (0.0 = unpriced): feeds the scheduler's decayed queue-cost
    # accumulator, which drives the adaptive batch window
    predicted_cost_s: float = 0.0
    # stamped by the executing leader (DispatchScheduler._execute) BEFORE
    # the future resolves: the group's actual kernel-launch wall seconds.
    # The waiting caller subtracts it from its total wait to split queue
    # time from launch time in the query-phase decomposition
    # (FusedAggregateExec._dispatch_fused). Batched lanes all carry the
    # SHARED launch duration (the launch is indivisible); a coalesced
    # duplicate lane's own request object stays None.
    exec_seconds: float | None = None
    # stamped alongside exec_seconds from the leader thread's
    # obs.kernels.last_dispatch(): the executable that actually served this
    # lane (batched lanes share ONE executable by construction) and
    # whether that launch compiled — the engine folds both into the
    # query's cost record (executable_key / compile_miss)
    executable_key: str | None = None
    compile_miss: bool | None = None

    def family(self) -> str:
        return self.kind

    def g_bucket(self) -> int:
        """Power-of-two bucket of this lane's group count. Part of the
        coalescing key: the batched program's static group axis is the
        group MAX, so one high-cardinality ``by (instance)`` lane would
        poison every cheap ``sum()`` lane in its group with a [G_max, J]
        output — bucketing keeps heavy and light group-bys in separate
        batches (and gives the compiler a handful of stable group widths
        instead of one per distinct G). The SAME rounding the batched
        kernels apply to their lane/window axes — one definition, or keys
        and kernel widths drift."""
        from ..ops.aggregations import _pow2

        return _pow2(self.G)

    def group_key(self) -> tuple:
        """Coalescing key: block identity + grid signature + epilogue
        family statics. Lanes in one group share the block OBJECT (verified
        again at execute time — ``id`` alone could alias across GC), the
        grid triple, the kernel variant selectors and the epilogue's static
        shape; per-query q and group-by variant ride the batch axis.

        The grid triple (start/step/window) is deliberately IN the key:
        the batched programs support mixed windows per launch (the u_map
        machinery in ops/aggregations), but live group compositions
        fluctuate with load, and every distinct lane->window pattern is a
        distinct XLA executable — pinning one window per group collapses
        the static composition space to a handful of pow2 widths, which is
        what keeps steady-state serving out of the compiler. Queries with
        near-miss windows still share everything that matters — the staged
        superblock (range alignment, planner._fused_raw_range) and each
        other's group-by epilogues within their window's group — and the
        SEALING leader re-merges compatible window-groups (merge_key) into
        one mixed-window launch, so one batch still serves them all; the
        pow2 lane/window padding keeps the merged composition space
        bounded."""
        p = self.params
        return (
            id(self.block), self.func, self.kind, self.epilogue, self.j_pad,
            p.start_ms, p.step_ms, p.window_ms,
            self.g_bucket(), self.is_counter, self.is_delta, self.hist_q,
            self.mesh_desc,
        )

    def merge_key(self) -> tuple:
        """Window-group compatibility key: group_key MINUS the grid triple.
        Groups agreeing on everything but (start, step, window) run the
        SAME batched program shape with the lane->unique-window map doing
        the routing (ops/aggregations._unique_windows) — the sealing
        leader absorbs them into one mixed-window launch, bit-parity per
        lane (each lane's subgraph is the exact single-query computation
        over its own window)."""
        return (
            id(self.block), self.func, self.kind, self.epilogue, self.j_pad,
            self.g_bucket(), self.is_counter, self.is_delta, self.hist_q,
            self.mesh_desc,
        )

    def lane_key(self) -> tuple:
        """Dedup key WITHIN a group: requests agreeing on every per-query
        dynamic share one lane (and one kernel output slice) — the
        single-flight discipline at lane granularity."""
        p = self.params
        return (p.start_ms, p.step_ms, p.num_steps, p.window_ms,
                float(self.qv), id(self.gids_dev), self.G)

    def take(self, stacked, i: int):
        """Lane ``i``'s view of the stacked batch output, shaped exactly
        like ``run_single``'s return."""
        if self.kind == "topk":
            return stacked[0][i], stacked[1][i]
        return stacked[i][: self.G]


def _run_batch(requests: list[FusedRequest]) -> list:
    """ONE batched kernel launch for the whole group; returns per-request
    outputs in run_single's shape."""
    from ..ops import aggregations as AGG

    r0 = requests[0]
    for r in requests[1:]:
        if r.block is not r0.block:
            # id-reuse alias after GC, or a superblock swap mid-window:
            # batching different blocks would serve wrong data — bail to
            # the per-lane fallback
            raise RuntimeError("batch group spans distinct blocks")
    # canonical lane order: a recurring batch composition must build ONE
    # stacked-input memo entry (ops/aggregations._batched_stacks) no matter
    # which query happened to arrive first this round
    order = sorted(range(len(requests)),
                   key=lambda i: requests[i].lane_key())
    # static group axis = the group's (shared) pow2 bucket, not the exact
    # max: stable compile widths; lanes slice their own [:G_i]
    g_max = max(r.g_bucket() for r in requests)
    lanes = [(requests[i].gids_dev, requests[i].qv, requests[i].params)
             for i in order]
    if r0.kind == "hist":
        out = AGG.fused_batched_hist(
            r0.func, r0.block, lanes, g_max, r0.j_pad, r0.les_dev,
            r0.hist_q, r0.is_delta, mesh=r0.mesh,
        )
    else:
        out = AGG.fused_batched_scalar(
            r0.func, r0.epilogue, r0.block, lanes, g_max, r0.j_pad,
            r0.is_counter, r0.is_delta, mesh=r0.mesh,
        )
    results: list = [None] * len(requests)
    for pos, i in enumerate(order):
        results[i] = requests[i].take(out, pos)
    return results


class _Group:
    # a group is "sealed" exactly when it is no longer in the scheduler's
    # _open table (removed under the lock) — joins and seal can never race.
    # ``stolen`` marks a group absorbed into another leader's mixed-window
    # batch (set under the same lock): its own leader must NOT execute —
    # its lanes' futures are settled by the absorbing leader.
    __slots__ = ("lanes", "closed", "last_join", "mkey", "stolen")

    def __init__(self, mkey: tuple = ()):
        self.lanes: dict[tuple, tuple[FusedRequest, Future]] = {}
        self.closed = threading.Event()
        self.last_join = time.monotonic()
        self.mkey = mkey
        self.stolen = False


class DispatchScheduler:
    """Micro-batching dispatcher (see module docstring).

    ``window_ms`` is the collection window the group leader holds open
    (0 = batching disabled: every dispatch runs unbatched, byte-identical
    to the pre-scheduler behavior). ``max_batch`` closes a group early.
    ``waiter`` is injectable for deterministic tests: it receives the
    group's close event and the window seconds and returns when the window
    ends (default: ``event.wait(window_s)``).

    **Adaptive window** (``window_cap_ms`` > ``window_ms`` > 0): the
    effective window tracks the decayed sum of predicted device-seconds
    recently submitted (``FusedRequest.predicted_cost_s``) — it widens
    toward the cap when predicted queue cost is high (batching pays) and
    collapses toward zero when the pipe is idle (latency wins; a lone
    query dispatches immediately). ``load_ref_cost_s`` is the queue cost
    that saturates the window at its cap. Without a cap the configured
    window is a constant, exactly the pre-adaptive behavior.

    **Pre-warm**: a QueryEngine registers a prewarmer closure; each
    ``prewarm_tick`` scans the recurrence ring for keys hot enough to be
    worth compiling ahead of demand (``prewarm_min_count`` observations;
    ANY live recompile-storm annotation from the kernel registry lowers
    the bar to 1 — shape churn means cold executables are about to be
    hot) and runs each once in the background, off the serving path, so
    the first real dispatch finds a warm jit cache."""

    def __init__(self, window_ms: float = 0.0, max_batch: int = 32,
                 waiter: Callable[[threading.Event, float], Any] | None = None,
                 key_ring_max: int = 512, window_cap_ms: float = 0.0,
                 load_ref_cost_s: float = 0.25,
                 prior_cost_s: float | None = None,
                 prewarm_min_count: int = 3,
                 clock: Callable[[], float] = time.monotonic):
        from .costmodel import DEFAULT_PRIOR_COST_S

        self.base_window_s = max(float(window_ms), 0.0) / 1e3
        self.window_cap_s = max(float(window_cap_ms), 0.0) / 1e3
        self.adaptive = self.window_cap_s > self.base_window_s > 0
        self.load_ref_cost_s = max(float(load_ref_cost_s), 1e-6)
        self.prior_cost_s = max(
            float(prior_cost_s if prior_cost_s is not None
                  else DEFAULT_PRIOR_COST_S), 1e-6)
        self.max_batch = max(int(max_batch), 1)
        self._waiter = waiter
        self._open: dict[tuple, _Group] = {}
        self._lock = threading.Lock()
        self._queued = 0
        # decayed predicted-queue-cost accumulator (device-seconds within
        # the last ~tau): its own lock so the window property never nests
        # under the group lock
        self._clock = clock
        self._load_lock = threading.Lock()
        self._load_tau_s = 2.0
        self._load_cost_s = 0.0
        self._load_stamp = clock()
        # pre-warm state: engine-registered executor + once-per-key memo
        self._prewarm_exec: Callable[[dict], Any] | None = None
        self._prewarmed: dict = {}
        self.prewarm_min_count = max(int(prewarm_min_count), 1)
        # per-key recurrence/age ring (standing-query promotion feed):
        # retained across batch close, observed on every fused dispatch
        # whether batching is enabled or not (window_ms 0 keeps the ring
        # alive with batching off)
        self.key_ring = KeyStatsRing(key_ring_max)
        # cumulative introspection counters (/debug/scheduler); the
        # Prometheus families are the operator-facing copies
        self.stats = {
            "queries": 0, "batched": 0, "solo": 0, "fallback": 0,
            "coalesced": 0, "dispatches": 0, "merged_windows": 0,
            "prewarmed": 0,
        }

    def observe_key(self, key, desc: dict | None = None) -> None:
        """Record one recurrence of a fused-dispatch key in the retained
        ring (called by FusedAggregateExec for every fused dispatch — the
        batching path and the plain unbatched path alike)."""
        self.key_ring.observe(key, desc)

    @property
    def enabled(self) -> bool:
        return self.base_window_s > 0

    @property
    def window_s(self) -> float:
        """The EFFECTIVE collection window: the configured constant, or —
        adaptive mode — the cap scaled by how loaded the queue looks
        (decayed predicted cost / ``load_ref_cost_s``, clamped to 1)."""
        if not self.adaptive:
            return self.base_window_s
        frac = min(self._load() / self.load_ref_cost_s, 1.0)
        return self.window_cap_s * frac

    def _load(self) -> float:
        """Decayed predicted queue cost (device-seconds), read-side."""
        with self._load_lock:
            dt = self._clock() - self._load_stamp
            decay = math.exp(-dt / self._load_tau_s) if dt > 0 else 1.0
            return self._load_cost_s * decay

    def _note_load(self, cost_s: float) -> None:
        with self._load_lock:
            now = self._clock()
            dt = now - self._load_stamp
            if dt > 0:
                self._load_cost_s *= math.exp(-dt / self._load_tau_s)
                self._load_stamp = now
            self._load_cost_s += max(float(cost_s), 0.0)

    # -- executable pre-warm ------------------------------------------------

    def register_prewarmer(self, fn: Callable[[dict], Any]) -> None:
        """Install the closure that traces+compiles one recurrence-ring
        descriptor off the serving path. First registration wins: several
        engines can share one scheduler, and the primary serving engine
        (constructed first) is the one whose executables matter."""
        if self._prewarm_exec is None:
            self._prewarm_exec = fn

    def prewarm_tick(self, limit: int = 2, storms: dict | None = None) -> list:
        """One background pre-warm pass: pick up to ``limit`` ring keys
        that look about-to-be-hot and run each through the registered
        executor once. ``storms`` (kernel-registry recompile-storm
        annotations; fetched live when None) lower the recurrence bar to
        a single observation — when shapes are churning, every key's
        executable is suspect. Returns the keys warmed this tick."""
        if self._prewarm_exec is None:
            return []
        if storms is None:
            from ..obs.kernels import KERNELS

            storms = KERNELS.storm_annotations()
        min_count = 1 if storms else self.prewarm_min_count
        picks = []
        for key, e in self.key_ring.entries():
            if key in self._prewarmed or e["count"] < min_count:
                continue
            desc = e.get("desc")
            if not desc or not desc.get("promql"):
                continue
            picks.append((key, desc))
            if len(picks) >= max(int(limit), 1):
                break
        warmed = []
        for key, desc in picks:
            self._prewarmed[key] = True
            while len(self._prewarmed) > 4 * self.key_ring.max_entries:
                self._prewarmed.pop(next(iter(self._prewarmed)))
            try:
                self._prewarm_exec(desc)
            except Exception:  # noqa: BLE001 — pre-warm is advisory
                REGISTRY.counter("filodb_prewarm", outcome="error").inc()
                continue
            self.stats["prewarmed"] += 1
            REGISTRY.counter("filodb_prewarm", outcome="ok").inc()
            warmed.append(key)
        return warmed

    def dispatch(self, request: FusedRequest):
        """Submit one fused dispatch; returns its kernel output (leader
        executes for the whole group, followers share)."""
        if not self.enabled:
            return request.run_single()
        # feed the adaptive window's queue-cost signal (unpriced requests
        # count at the flat prior, so load tracks arrival rate even before
        # the cost model has evidence)
        self._note_load(request.predicted_cost_s
                        if request.predicted_cost_s > 0
                        else self.prior_cost_s)
        fam = request.family()
        key = request.group_key()
        lane = request.lane_key()
        with self._lock:
            self.stats["queries"] += 1
            group = self._open.get(key)
            leader = group is None
            if leader:
                group = _Group(mkey=request.merge_key())
                self._open[key] = group
            have = group.lanes.get(lane)
            group.last_join = time.monotonic()
            if have is None:
                fut = Future()
                group.lanes[lane] = (request, fut)
                self._queued += 1
            else:
                fut = have[1]
                self.stats["coalesced"] += 1
            if len(group.lanes) >= self.max_batch:
                group.closed.set()
        REGISTRY.counter("filodb_batch_queries", family=fam).inc()
        REGISTRY.gauge("filodb_batch_queue_depth").set(float(self._queued))
        from ..metrics import current_span

        sp = current_span()
        if sp is not None:
            sp.tags["batch_role"] = "leader" if leader else "follower"
        if leader:
            if self._waiter is not None:
                self._waiter(group.closed, self.window_s)
            else:
                self._collect(group)
            merged = 0
            with self._lock:
                if group.stolen:
                    # a compatible window-group's leader absorbed this
                    # group into its mixed-window batch while we waited —
                    # it owns our lanes' futures now; just await ours
                    lanes = None
                else:
                    if self._open.get(key) is group:
                        del self._open[key]
                    lanes = list(group.lanes.values())
                    # stickier composition: absorb still-open groups that
                    # agree on everything but the window triple
                    # (merge_key) into THIS launch — the batched programs'
                    # u_map machinery routes each lane to its own window,
                    # bit-parity per lane. Those groups' waiting clients
                    # get answered by this (earlier) dispatch. max_batch
                    # bounds the MERGED launch too: it caps unrolled
                    # program width and stacked-output HBM, and absorbing
                    # past it would rebuild exactly the oversized
                    # executables the bound exists to prevent.
                    for k2 in [k for k, g in self._open.items()
                               if g.mkey == group.mkey]:
                        g2 = self._open[k2]
                        if len(lanes) + len(g2.lanes) > self.max_batch:
                            continue
                        del self._open[k2]
                        g2.stolen = True
                        g2.closed.set()
                        lanes.extend(g2.lanes.values())
                        merged += 1
                    self._queued -= len(lanes)
                    self.stats["merged_windows"] += merged
            if lanes is not None:
                if merged:
                    REGISTRY.counter(
                        "filodb_batch_merged_windows", family=fam
                    ).inc(merged)
                REGISTRY.gauge("filodb_batch_queue_depth").set(
                    float(self._queued)
                )
                self._execute(fam, lanes)
        try:
            return fut.result(timeout=max(request.timeout_s, 0.001))
        except FutureTimeout:
            raise QueryDeadlineExceeded(
                f"query exceeded deadline: {request.timeout_s:.1f}s waiting "
                "on batched dispatch"
            ) from None

    def _collect(self, group: _Group) -> None:
        """Leader-side collection: hold the window open until it elapses,
        the group hits max_batch (closed event), or joins go QUIET — no new
        lane for a quarter-window. The quiescence close is what keeps the
        window from being a flat latency tax: after a shared batch
        completes, its clients resubmit within milliseconds of each other,
        so the next round's group fills almost at once and dispatches
        immediately instead of idling out the rest of the window; a
        sporadic lone query likewise waits only the gap, not the window."""
        # capture the effective window ONCE: in adaptive mode the property
        # moves with load, and a leader must hold a consistent deadline
        w = self.window_s
        deadline = time.monotonic() + w
        gap = w / 4
        while True:
            now = time.monotonic()
            if group.closed.is_set() or now >= deadline:
                return
            idle = now - group.last_join
            if idle >= gap:
                return
            group.closed.wait(min(deadline - now, gap - idle))

    @staticmethod
    def _stamp_executable(reqs) -> None:
        """Copy the leader thread's last-dispatch identity (the executable
        registry's thread-local capture) onto the lane request(s) BEFORE
        their futures resolve — the waiting engines' threads never saw the
        launch, so the key must ride the request like exec_seconds."""
        from ..obs.kernels import KERNELS

        info = KERNELS.last_dispatch()
        if not info:
            return
        for req in reqs:
            req.executable_key = info.get("executable_key")
            req.compile_miss = info.get("compile_miss")

    def _execute(self, fam: str, lanes: list) -> None:
        """Leader-side group execution: one batched launch for Q>1 lanes,
        the plain unbatched dispatch for a solo group, per-lane unbatched
        fallback if the batched path fails."""
        if len(lanes) == 1:
            # solo group: the plain unbatched dispatch, errors propagated
            # as-is (re-running a deterministic failure would double the
            # device work exactly when the device is least healthy)
            outcome = "solo"
            req, fut = lanes[0]
            t0 = time.perf_counter()
            try:
                out = req.run_single()
                req.exec_seconds = time.perf_counter() - t0
                self._stamp_executable((req,))
                fut.set_result(out)
            except Exception as e:  # noqa: BLE001 — delivered to the caller
                req.exec_seconds = time.perf_counter() - t0
                fut.set_exception(e)
        else:
            outcome = "batched"
            results = None
            t0 = time.perf_counter()
            try:
                results = _run_batch([req for req, _ in lanes])
            except QueryError as e:
                # typed query errors (limits) are real answers — propagate
                for _, fut in lanes:
                    fut.set_exception(e)
                return
            except Exception:  # noqa: BLE001 — batching must not lose queries
                outcome = "fallback"
            if results is None:
                for req, fut in lanes:
                    t1 = time.perf_counter()
                    try:
                        out = req.run_single()
                        req.exec_seconds = time.perf_counter() - t1
                        self._stamp_executable((req,))
                        fut.set_result(out)
                    except Exception as e:  # noqa: BLE001
                        req.exec_seconds = time.perf_counter() - t1
                        fut.set_exception(e)
            else:
                # exec_seconds stamped BEFORE the futures resolve so a
                # woken waiter always reads its final value; every lane
                # carries the shared (indivisible) launch duration
                batch_s = time.perf_counter() - t0
                for req, _ in lanes:
                    req.exec_seconds = batch_s
                self._stamp_executable([req for req, _ in lanes])
                for (_, fut), res in zip(lanes, results):
                    fut.set_result(res)
        with self._lock:
            self.stats[outcome] += 1
            self.stats["dispatches"] += 1
        REGISTRY.counter(
            "filodb_batch_dispatches", family=fam, outcome=outcome
        ).inc()

    def snapshot(self) -> dict:
        """The /debug/scheduler rendering: window config, live queue state
        and cumulative batching outcomes."""
        # the window property takes the load lock — read it OUTSIDE the
        # group lock (neither is reentrant)
        eff_ms = self.window_s * 1e3
        load = self._load()
        with self._lock:
            out = {
                "window_ms": eff_ms,
                "base_window_ms": self.base_window_s * 1e3,
                "window_cap_ms": self.window_cap_s * 1e3,
                "adaptive": self.adaptive,
                "load_cost_s": round(load, 6),
                "max_batch": self.max_batch,
                "open_groups": len(self._open),
                "queued_lanes": self._queued,
                **{k: v for k, v in self.stats.items()},
            }
        out["standing_keys"] = len(self.key_ring)
        return out
