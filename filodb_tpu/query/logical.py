"""LogicalPlan ADT (reference L4: query/LogicalPlan.scala — RawSeries:233,
PeriodicSeries:419, PeriodicSeriesWithWindowing:554, Aggregate:620,
BinaryJoin:652, ScalarVectorBinaryOperation:689, ApplyInstantFunction:714,
subqueries :476/:523, metadata plans :282-343, scalar plans :816-928).

All times are absolute epoch **milliseconds**; windows/offsets are ms spans.
Plans are immutable dataclasses; planners rewrite them structurally.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional, Sequence

from ..core.filters import ColumnFilter


@dataclass(frozen=True)
class LogicalPlan:
    pass


@dataclass(frozen=True)
class RawSeries(LogicalPlan):
    """Select raw chunks for matching series over [start-lookback, end]."""

    filters: tuple[ColumnFilter, ...]
    start_ms: int
    end_ms: int
    offset_ms: int = 0
    column: Optional[str] = None  # explicit column (::min downsample rewrites)


@dataclass(frozen=True)
class PeriodicSeries(LogicalPlan):
    """Instant-vector evaluation on a regular step grid: the value at each
    step is the series' latest sample within the staleness lookback."""

    raw: RawSeries
    start_ms: int
    end_ms: int
    step_ms: int
    lookback_ms: int = 300_000
    offset_ms: int = 0
    at_ms: Optional[int] = None


@dataclass(frozen=True)
class PeriodicSeriesWithWindowing(LogicalPlan):
    """Range-function evaluation: func over (t-window, t] per step."""

    raw: RawSeries
    function: str
    window_ms: int
    start_ms: int
    end_ms: int
    step_ms: int
    offset_ms: int = 0
    at_ms: Optional[int] = None
    function_args: tuple[float, ...] = ()


@dataclass(frozen=True)
class SubqueryWithWindowing(LogicalPlan):
    """func(<expr>[window:step]) — inner expr evaluated on the subquery step
    grid, then the range function applied over its results."""

    inner: LogicalPlan
    function: str
    window_ms: int
    sub_step_ms: int
    start_ms: int
    end_ms: int
    step_ms: int
    offset_ms: int = 0
    function_args: tuple[float, ...] = ()


@dataclass(frozen=True)
class TopLevelSubquery(LogicalPlan):
    inner: LogicalPlan
    start_ms: int
    end_ms: int
    step_ms: int
    offset_ms: int = 0


@dataclass(frozen=True)
class Aggregate(LogicalPlan):
    op: str  # sum|min|max|count|avg|stddev|stdvar|topk|bottomk|quantile|count_values|group
    inner: LogicalPlan
    params: tuple = ()  # k for topk, q for quantile, label for count_values
    by: Optional[tuple[str, ...]] = None
    without: Optional[tuple[str, ...]] = None


@dataclass(frozen=True)
class PartialAggregate(LogicalPlan):
    """Map phase of an aggregation WITHOUT the present phase: the executor
    returns per-group mergeable components (``__comp__``-labeled grids —
    (sum,count) for avg, (sum,sumsq,count) for stddev, sketch counts for
    quantile) instead of finished values. Federation ships this to peers so
    O(groups) components cross the wire, not O(series) raw rows, and the
    coordinator's reduce phase merges peer partials exactly like local
    shard partials (reference RowAggregator.scala:28,114 mergeable
    aggregate items, AggrOverRangeVectors.scala:224)."""

    op: str  # any op in exec.plans._PARTIAL_COMPONENTS, or "quantile"
    inner: LogicalPlan
    params: tuple = ()
    by: Optional[tuple[str, ...]] = None
    without: Optional[tuple[str, ...]] = None


@dataclass(frozen=True)
class BinaryJoin(LogicalPlan):
    lhs: LogicalPlan
    op: str
    rhs: LogicalPlan
    cardinality: str = "one-to-one"  # one-to-one|one-to-many|many-to-one|many-to-many
    on: Optional[tuple[str, ...]] = None
    ignoring: tuple[str, ...] = ()
    include: tuple[str, ...] = ()  # group_left/right extra labels
    return_bool: bool = False


@dataclass(frozen=True)
class ScalarVectorBinaryOperation(LogicalPlan):
    op: str
    scalar: "LogicalPlan"  # ScalarPlan subtree
    vector: LogicalPlan
    scalar_is_lhs: bool
    return_bool: bool = False


@dataclass(frozen=True)
class ApplyInstantFunction(LogicalPlan):
    inner: LogicalPlan
    function: str
    args: tuple = ()  # floats or scalar plans


@dataclass(frozen=True)
class ApplyMiscellaneousFunction(LogicalPlan):
    inner: LogicalPlan
    function: str  # label_replace|label_join|sort|sort_desc|...
    str_args: tuple[str, ...] = ()


@dataclass(frozen=True)
class ApplySortFunction(LogicalPlan):
    inner: LogicalPlan
    descending: bool = False


@dataclass(frozen=True)
class ApplyAbsentFunction(LogicalPlan):
    inner: LogicalPlan
    filters: tuple[ColumnFilter, ...]
    start_ms: int = 0
    end_ms: int = 0
    step_ms: int = 0


@dataclass(frozen=True)
class ApplyLimitFunction(LogicalPlan):
    inner: LogicalPlan
    limit: int


# -- scalar plans -----------------------------------------------------------


@dataclass(frozen=True)
class ScalarFixedDoublePlan(LogicalPlan):
    value: float
    start_ms: int = 0
    end_ms: int = 0
    step_ms: int = 0


@dataclass(frozen=True)
class ScalarTimeBasedPlan(LogicalPlan):
    function: str  # time|hour|minute|month|year|day_of_month|day_of_week|day_of_year|days_in_month
    start_ms: int = 0
    end_ms: int = 0
    step_ms: int = 0


@dataclass(frozen=True)
class ScalarVaryingDoublePlan(LogicalPlan):
    """scalar(vector) / vector(scalar) wrapper plans."""

    inner: LogicalPlan
    function: str  # scalar|vector


@dataclass(frozen=True)
class ScalarBinaryOperation(LogicalPlan):
    op: str
    lhs: "LogicalPlan | float"
    rhs: "LogicalPlan | float"
    start_ms: int = 0
    end_ms: int = 0
    step_ms: int = 0


# -- metadata plans ---------------------------------------------------------


@dataclass(frozen=True)
class LabelValues(LogicalPlan):
    label: str
    filters: tuple[ColumnFilter, ...]
    start_ms: int
    end_ms: int


@dataclass(frozen=True)
class LabelNames(LogicalPlan):
    filters: tuple[ColumnFilter, ...]
    start_ms: int
    end_ms: int


@dataclass(frozen=True)
class SeriesKeysByFilters(LogicalPlan):
    filters: tuple[ColumnFilter, ...]
    start_ms: int
    end_ms: int


@dataclass(frozen=True)
class TsCardinalities(LogicalPlan):
    shard_key_prefix: tuple[str, ...]
    num_groups: int = 2


# -- helpers ----------------------------------------------------------------


def leaf_raw_series(plan: LogicalPlan) -> list[RawSeries]:
    """All RawSeries leaves of a plan tree."""
    out: list[RawSeries] = []

    def walk(p):
        if isinstance(p, RawSeries):
            out.append(p)
            return
        for f in getattr(p, "__dataclass_fields__", {}):
            v = getattr(p, f)
            if isinstance(v, LogicalPlan):
                walk(v)
    walk(plan)
    return out


def shift_time(plan: LogicalPlan, delta_ms: int) -> LogicalPlan:
    """Shift every absolute time in the tree (used by HA/failover planners)."""
    if not isinstance(plan, LogicalPlan):
        return plan
    kw = {}
    for f in plan.__dataclass_fields__:
        v = getattr(plan, f)
        if f in ("start_ms", "end_ms") and isinstance(v, int):
            kw[f] = v + delta_ms
        elif isinstance(v, LogicalPlan):
            kw[f] = shift_time(v, delta_ms)
    return replace(plan, **kw) if kw else plan


def narrow_time(plan: LogicalPlan, delta_start_ms: int,
                delta_end_ms: int) -> LogicalPlan:
    """Trim the evaluation range: ``start_ms += delta_start_ms`` and
    ``end_ms += delta_end_ms`` at EVERY node. Derived ranges (raw
    selectors, subquery inners) are the top-level range plus fixed
    window/lookback/offset margins, so one uniform trim preserves every
    per-node relationship. ``at_ms`` pins stay absolute. Used by the
    planner's over-wide-range time slicing (staged ts offsets are int32
    ms — ops/staging.MAX_STAGE_SPAN_MS)."""
    if not isinstance(plan, LogicalPlan):
        return plan
    kw = {}
    for f in plan.__dataclass_fields__:
        v = getattr(plan, f)
        if f == "start_ms" and isinstance(v, int):
            kw[f] = v + delta_start_ms
        elif f == "end_ms" and isinstance(v, int):
            kw[f] = v + delta_end_ms
        elif isinstance(v, LogicalPlan):
            kw[f] = narrow_time(v, delta_start_ms, delta_end_ms)
    return replace(plan, **kw) if kw else plan
