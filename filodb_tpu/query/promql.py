"""PromQL parser -> LogicalPlan (reference L6: prometheus/parse/Parser.scala
:183-190 entry points; grammar semantics of PromQL.g4 + ast/Vectors.scala,
Functions.scala, Expressions.scala — re-implemented as a hand-written lexer +
precedence-climbing parser, the same approach as the reference's
LegacyParser).

Coverage: vector selectors with matchers, matrix ranges ``[5m]``, subqueries
``[1h:5m]``, ``offset`` (incl. negative), ``@`` (timestamp / start() / end()),
all binary operators with PromQL precedence + ``bool`` + vector matching
(``on``/``ignoring``/``group_left``/``group_right``), aggregations with
``by``/``without`` (prefix or suffix), range/instant/misc/time functions,
number formats (hex, inf, nan, duration-style), string escapes.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass

from ..core.filters import ColumnFilter
from ..core.schemas import METRIC_TAG
from . import functions as F
from .logical import (
    Aggregate,
    ApplyAbsentFunction,
    ApplyInstantFunction,
    ApplyLimitFunction,
    ApplyMiscellaneousFunction,
    ApplySortFunction,
    BinaryJoin,
    LogicalPlan,
    PeriodicSeries,
    PeriodicSeriesWithWindowing,
    RawSeries,
    ScalarBinaryOperation,
    ScalarFixedDoublePlan,
    ScalarTimeBasedPlan,
    ScalarVaryingDoublePlan,
    ScalarVectorBinaryOperation,
    SubqueryWithWindowing,
    TopLevelSubquery,
)

DEFAULT_LOOKBACK_MS = 300_000
DEFAULT_SUBQUERY_STEP_MS = 60_000


class PromQLError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<WS>\s+)
  | (?P<NUMBER>
        0[xX][0-9a-fA-F]+
      | (?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?
    )
  | (?P<IDENT>[a-zA-Z_:][a-zA-Z0-9_:.]*)
  | (?P<STRING>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*'|`[^`]*`)
  | (?P<OP> =~|!~|==|!=|<=|>=|<<|>>|[-+*/%^(){}\[\],=<>@:])
    """,
    re.VERBOSE,
)

_DURATION_RE = re.compile(r"^(\d+(?:\.\d+)?)(ms|s|m|h|d|w|y)$")
_DURATION_SEQ_RE = re.compile(r"^(?:\d+(?:\.\d+)?(?:ms|s|m|h|d|w|y))+$")
_DUR_PART = re.compile(r"(\d+(?:\.\d+)?)(ms|s|m|h|d|w|y)")
_UNIT_MS = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000, "d": 86_400_000, "w": 604_800_000, "y": 31_536_000_000}


@dataclass
class Tok:
    kind: str  # NUMBER | IDENT | STRING | OP | DURATION | EOF
    text: str
    pos: int


def lex(q: str) -> list[Tok]:
    out: list[Tok] = []
    pos = 0
    while pos < len(q):
        m = _TOKEN_RE.match(q, pos)
        if not m:
            raise PromQLError(f"unexpected character {q[pos]!r} at {pos}")
        kind = m.lastgroup
        text = m.group()
        if kind != "WS":
            # idents may contain ':' (recording rules) but a LEADING colon is
            # always the subquery/range separator — emit it as an operator
            if kind == "IDENT" and text.startswith(":"):
                out.append(Tok("OP", ":", pos))
                pos += 1
                continue
            # duration literal: number+unit lexes as NUMBER IDENT; re-join.
            # Idents may contain ':' (recording rules) — inside [30m:1m] the
            # colon separates, so also try the pre-colon prefix.
            if kind == "IDENT" and out and out[-1].kind == "NUMBER" and pos == out[-1].pos + len(out[-1].text):
                if _DURATION_SEQ_RE.match(out[-1].text + text):
                    out[-1] = Tok("DURATION", out[-1].text + text, out[-1].pos)
                    pos = m.end()
                    continue
                prefix = text.split(":", 1)[0]
                if ":" in text and _DURATION_SEQ_RE.match(out[-1].text + prefix):
                    out[-1] = Tok("DURATION", out[-1].text + prefix, out[-1].pos)
                    pos = pos + len(prefix)  # resume at the ':'
                    continue
            out.append(Tok(kind, text, pos))
        pos = m.end()
    out.append(Tok("EOF", "", len(q)))
    return out


def parse_duration_ms(text: str) -> int:
    if _DURATION_SEQ_RE.match(text):
        return int(sum(float(n) * _UNIT_MS[u] for n, u in _DUR_PART.findall(text)))
    try:
        return int(float(text) * 1000)  # bare number = seconds (modern promql)
    except ValueError:
        raise PromQLError(f"invalid duration {text!r}")


def _unquote(s: str) -> str:
    if s[0] == "`":
        return s[1:-1]
    body = s[1:-1]
    return body.encode().decode("unicode_escape")


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------


@dataclass
class Node:
    pass


@dataclass
class NumLit(Node):
    value: float


@dataclass
class StrLit(Node):
    value: str


@dataclass
class Selector(Node):
    metric: str | None
    matchers: list[ColumnFilter]
    window_ms: int | None = None  # matrix selector
    sub_step_ms: int | None = None  # subquery: expr[w:s]
    offset_ms: int = 0
    at: str | float | None = None  # epoch seconds | "start" | "end"


@dataclass
class Subquery(Node):
    inner: Node
    window_ms: int
    sub_step_ms: int | None
    offset_ms: int = 0
    at: str | float | None = None


@dataclass
class Call(Node):
    func: str
    args: list[Node]


@dataclass
class Agg(Node):
    op: str
    expr: Node
    param: Node | None
    by: list[str] | None
    without: list[str] | None


@dataclass
class Binary(Node):
    op: str
    lhs: Node
    rhs: Node
    return_bool: bool = False
    on: list[str] | None = None
    ignoring: list[str] | None = None
    group_side: str | None = None  # "left" | "right"
    include: list[str] | None = None


@dataclass
class Unary(Node):
    op: str
    expr: Node


# precedence (higher binds tighter); ^ is right-associative
_PREC = {
    "or": 1,
    "and": 2, "unless": 2,
    "==": 3, "!=": 3, "<=": 3, "<": 3, ">=": 3, ">": 3,
    "+": 4, "-": 4,
    "*": 5, "/": 5, "%": 5, "atan2": 5,
    "^": 6,
}


class Parser:
    def __init__(self, query: str):
        self.toks = lex(query)
        self.i = 0
        self.query = query

    # -- token helpers ---------------------------------------------------

    def peek(self) -> Tok:
        return self.toks[self.i]

    def next(self) -> Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, text: str) -> Tok:
        t = self.next()
        if t.text != text:
            raise PromQLError(f"expected {text!r} at pos {t.pos}, got {t.text!r}")
        return t

    def accept(self, text: str) -> bool:
        if self.peek().text == text:
            self.i += 1
            return True
        return False

    # -- grammar ---------------------------------------------------------

    def parse(self) -> Node:
        node = self.expr(1)
        if self.peek().kind != "EOF":
            raise PromQLError(f"unexpected token {self.peek().text!r} at {self.peek().pos}")
        return node

    def expr(self, min_prec: int) -> Node:
        lhs = self.unary()
        while True:
            t = self.peek()
            op = t.text if t.kind in ("OP", "IDENT") else None
            if op not in _PREC or _PREC[op] < min_prec:
                return lhs
            self.next()
            return_bool = False
            on = ignoring = include = None
            group_side = None
            if op in F.COMPARISON_OPS and self.accept("bool"):
                return_bool = True
            if self.peek().text in ("on", "ignoring"):
                kind = self.next().text
                labels = self.label_list()
                if kind == "on":
                    on = labels
                else:
                    ignoring = labels
            if self.peek().text in ("group_left", "group_right"):
                group_side = "left" if self.next().text == "group_left" else "right"
                include = self.label_list() if self.peek().text == "(" else []
            next_min = _PREC[op] + (0 if op == "^" else 1)
            rhs = self.expr(next_min)
            lhs = Binary(op, lhs, rhs, return_bool, on, ignoring, group_side, include)

    def unary(self) -> Node:
        t = self.peek()
        if t.text in ("-", "+"):
            self.next()
            inner = self.unary()
            return inner if t.text == "+" else Unary("-", inner)
        return self.postfix(self.atom())

    def postfix(self, node: Node) -> Node:
        """Attach [range], [w:s], offset, @ to selectors/expressions."""
        while True:
            t = self.peek()
            if t.text == "[":
                self.next()
                w = self.next()
                if w.kind not in ("DURATION", "NUMBER"):
                    raise PromQLError(f"expected duration at {w.pos}")
                window = parse_duration_ms(w.text)
                if self.accept(":"):
                    sub_step = None
                    if self.peek().kind in ("DURATION", "NUMBER"):
                        sub_step = parse_duration_ms(self.next().text)
                    self.expect("]")
                    node = Subquery(node, window, sub_step)
                else:
                    self.expect("]")
                    if not isinstance(node, Selector) or node.window_ms is not None:
                        raise PromQLError("range selector on non-instant-selector; use a subquery [w:s]")
                    node.window_ms = window
            elif t.text == "offset":
                self.next()
                neg = self.accept("-")
                d = self.next()
                off = parse_duration_ms(d.text) * (-1 if neg else 1)
                tgt = node
                if isinstance(tgt, (Selector, Subquery)):
                    tgt.offset_ms += off
                else:
                    raise PromQLError("offset must follow a selector or subquery")
            elif t.text == "@":
                self.next()
                nxt = self.next()
                if nxt.text in ("start", "end"):
                    self.expect("(")
                    self.expect(")")
                    at = nxt.text
                elif nxt.kind in ("NUMBER", "DURATION"):
                    at = float(nxt.text)
                else:
                    raise PromQLError(f"invalid @ modifier at {nxt.pos}")
                if isinstance(node, (Selector, Subquery)):
                    node.at = at
                else:
                    raise PromQLError("@ must follow a selector or subquery")
            else:
                return node

    def label_list(self) -> list[str]:
        self.expect("(")
        out = []
        while not self.accept(")"):
            t = self.next()
            if t.kind not in ("IDENT", "STRING"):
                raise PromQLError(f"expected label name at {t.pos}")
            out.append(_unquote(t.text) if t.kind == "STRING" else t.text)
            if self.peek().text == ",":
                self.next()
        return out

    def atom(self) -> Node:
        t = self.peek()
        if t.text == "(":
            self.next()
            inner = self.expr(1)
            self.expect(")")
            return self.postfix(inner)
        if t.kind == "NUMBER":
            self.next()
            txt = t.text.lower()
            val = float(int(txt, 16)) if txt.startswith("0x") else float(txt)
            return NumLit(val)
        if t.kind == "STRING":
            self.next()
            return StrLit(_unquote(t.text))
        if t.kind == "IDENT":
            name = t.text
            if name in F.SET_OPS or name in ("bool", "on", "ignoring", "group_left", "group_right", "offset", "by", "without"):
                raise PromQLError(f"keyword {name!r} cannot start an expression")
            low = name.lower()
            if low in ("inf", "nan"):
                self.next()
                return NumLit(math.inf if low == "inf" else math.nan)
            if name in F.AGGREGATION_OPS and self.toks[self.i + 1].text in ("(", "by", "without"):
                return self.aggregation()
            if (
                name in F.RANGE_FUNCTIONS
                or name in F.INSTANT_FUNCTIONS
                or name in F.MISC_FUNCTIONS
                or name in F.TIME_FUNCTIONS
            ) and self.toks[self.i + 1].text == "(":
                self.next()
                self.expect("(")
                args: list[Node] = []
                while not self.accept(")"):
                    args.append(self.expr(1))
                    if self.peek().text == ",":
                        self.next()
                return Call(name, args)
            return self.selector()
        if t.text == "{":
            return self.selector()
        raise PromQLError(f"unexpected token {t.text!r} at {t.pos}")

    def aggregation(self) -> Node:
        op = self.next().text
        by = without = None
        if self.peek().text in ("by", "without"):
            kind = self.next().text
            labels = self.label_list()
            if kind == "by":
                by = labels
            else:
                without = labels
        self.expect("(")
        args: list[Node] = []
        while not self.accept(")"):
            args.append(self.expr(1))
            if self.peek().text == ",":
                self.next()
        if self.peek().text in ("by", "without"):
            kind = self.next().text
            labels = self.label_list()
            if kind == "by":
                by = labels
            else:
                without = labels
        if op in F.AGG_WITH_PARAM:
            if len(args) != 2:
                raise PromQLError(f"{op} expects (param, expr)")
            return Agg(op, args[1], args[0], by, without)
        if len(args) != 1:
            raise PromQLError(f"{op} expects one argument")
        return Agg(op, args[0], None, by, without)

    def selector(self) -> Node:
        metric = None
        matchers: list[ColumnFilter] = []
        t = self.peek()
        if t.kind == "IDENT":
            metric = self.next().text
        if self.accept("{"):
            while not self.accept("}"):
                lt = self.next()
                if lt.kind not in ("IDENT", "STRING") and lt.text not in F.SET_OPS:
                    raise PromQLError(f"expected label name at {lt.pos}")
                lname = _unquote(lt.text) if lt.kind == "STRING" else lt.text
                op = self.next().text
                if op not in ("=", "!=", "=~", "!~"):
                    raise PromQLError(f"bad matcher op {op!r}")
                vt = self.next()
                if vt.kind != "STRING":
                    raise PromQLError(f"expected quoted value at {vt.pos}")
                matchers.append(ColumnFilter(lname, op, _unquote(vt.text)))
                if self.peek().text == ",":
                    self.next()
        if metric is None and not matchers:
            raise PromQLError("empty selector")
        return Selector(metric, matchers)


# ---------------------------------------------------------------------------
# AST -> LogicalPlan
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class TimeStepParams:
    """Evaluation grid in epoch ms (reference TimeStepParams is seconds)."""

    start_ms: int
    end_ms: int
    step_ms: int


def _is_scalar_node(n: Node) -> bool:
    if isinstance(n, NumLit):
        return True
    if isinstance(n, Call) and n.func in F.TIME_FUNCTIONS:
        return True
    if isinstance(n, Call) and n.func == "scalar":
        return True
    if isinstance(n, Binary):
        return _is_scalar_node(n.lhs) and _is_scalar_node(n.rhs)
    if isinstance(n, Unary):
        return _is_scalar_node(n.expr)
    return False


class Converter:
    def __init__(self, params: TimeStepParams, lookback_ms: int = DEFAULT_LOOKBACK_MS):
        self.p = params
        self.lookback = lookback_ms

    def _resolve_at(self, at) -> int | None:
        if at is None:
            return None
        if at == "start":
            return self.p.start_ms
        if at == "end":
            return self.p.end_ms
        return int(float(at) * 1000)

    def to_plan(self, n: Node) -> LogicalPlan:
        p = self.p
        if isinstance(n, NumLit):
            return ScalarFixedDoublePlan(n.value, p.start_ms, p.end_ms, p.step_ms)
        if isinstance(n, Unary):
            inner = self.to_plan(n.expr)
            if isinstance(inner, ScalarFixedDoublePlan):
                return ScalarFixedDoublePlan(-inner.value, p.start_ms, p.end_ms, p.step_ms)
            return ScalarVectorBinaryOperation(
                "*", ScalarFixedDoublePlan(-1.0, p.start_ms, p.end_ms, p.step_ms), inner, True
            )
        if isinstance(n, Selector):
            return self.periodic_from_selector(n)
        if isinstance(n, Subquery):
            return self.subquery(n, None, ())
        if isinstance(n, Agg):
            return self.aggregate(n)
        if isinstance(n, Call):
            return self.call(n)
        if isinstance(n, Binary):
            return self.binary(n)
        raise PromQLError(f"cannot convert {n}")

    # -- selectors -------------------------------------------------------

    def _filters(self, sel: Selector) -> tuple[ColumnFilter, ...]:
        out = list(sel.matchers)
        if sel.metric is not None:
            out.append(ColumnFilter(METRIC_TAG, "=", sel.metric))
        else:
            # normalize __name__ matchers to _metric_
            out = [
                ColumnFilter(METRIC_TAG, f.op, f.value) if f.column == "__name__" else f
                for f in out
            ]
        return tuple(out)

    def periodic_from_selector(self, sel: Selector) -> LogicalPlan:
        if sel.window_ms is not None:
            raise PromQLError("range vector must be consumed by a range function")
        p = self.p
        at = self._resolve_at(sel.at)
        start, end = (at, at) if at is not None else (p.start_ms, p.end_ms)
        off = sel.offset_ms
        raw = RawSeries(
            self._filters(sel),
            start - self.lookback - off,
            end - off,
            offset_ms=off,
        )
        return PeriodicSeries(raw, p.start_ms, p.end_ms, p.step_ms, self.lookback, off, at)

    def windowed_from_selector(self, sel: Selector, func: str, args: tuple[float, ...]) -> LogicalPlan:
        p = self.p
        at = self._resolve_at(sel.at)
        start, end = (at, at) if at is not None else (p.start_ms, p.end_ms)
        off = sel.offset_ms
        window = sel.window_ms or 0
        raw = RawSeries(
            self._filters(sel),
            start - window - off,
            end - off,
            offset_ms=off,
        )
        return PeriodicSeriesWithWindowing(
            raw, func, window, p.start_ms, p.end_ms, p.step_ms, off, at, args
        )

    def subquery(self, sq: Subquery, func: str | None, args: tuple[float, ...]) -> LogicalPlan:
        p = self.p
        at = self._resolve_at(sq.at)
        start, end = (at, at) if at is not None else (p.start_ms, p.end_ms)
        sub_step = sq.sub_step_ms or DEFAULT_SUBQUERY_STEP_MS
        off = sq.offset_ms
        # inner evaluated over the extended aligned grid (reference
        # SubqueryUtils: start snapped down to a multiple of sub_step)
        inner_start = ((start - off - sq.window_ms) // sub_step) * sub_step
        if inner_start < start - off - sq.window_ms:
            inner_start += sub_step
        inner_end = ((end - off) // sub_step) * sub_step
        inner = Converter(
            TimeStepParams(inner_start, inner_end, sub_step), self.lookback
        ).to_plan(sq.inner)
        if func is None:
            return TopLevelSubquery(inner, p.start_ms, p.end_ms, p.step_ms, off)
        return SubqueryWithWindowing(
            inner, func, sq.window_ms, sub_step, p.start_ms, p.end_ms, p.step_ms, off, args
        )

    # -- functions -------------------------------------------------------

    def call(self, c: Call) -> LogicalPlan:
        p = self.p
        name = c.func
        if name in F.TIME_FUNCTIONS:
            if c.args and name != "pi":
                inner = self.to_plan(c.args[0])
                return ApplyInstantFunction(inner, name)
            if name == "pi":
                return ScalarFixedDoublePlan(math.pi, p.start_ms, p.end_ms, p.step_ms)
            return ScalarTimeBasedPlan(name, p.start_ms, p.end_ms, p.step_ms)
        if name in F.RANGE_FUNCTIONS:
            kernel, n_scalar, scalars_first = F.RANGE_FUNCTIONS[name]
            scalars: list[float] = []
            vec: Node | None = None
            for a in c.args:
                if isinstance(a, (Selector, Subquery)):
                    vec = a
                elif isinstance(a, NumLit):
                    scalars.append(a.value)
                elif isinstance(a, Unary) and isinstance(a.expr, NumLit):
                    scalars.append(-a.expr.value)
                else:
                    raise PromQLError(f"{name}: unsupported argument {a}")
            if vec is None:
                raise PromQLError(f"{name} needs a range-vector argument")
            if isinstance(vec, Subquery):
                return self.subquery(vec, kernel, tuple(scalars))
            if vec.window_ms is None:
                raise PromQLError(f"{name} needs a range vector (add [window])")
            return self.windowed_from_selector(vec, kernel, tuple(scalars))
        if name == "absent":
            inner_node = c.args[0]
            inner = self.to_plan(inner_node)
            filters = ()
            if isinstance(inner_node, Selector):
                filters = self._filters(inner_node)
            return ApplyAbsentFunction(inner, filters, p.start_ms, p.end_ms, p.step_ms)
        if name in ("sort", "sort_desc"):
            return ApplySortFunction(self.to_plan(c.args[0]), name == "sort_desc")
        if name == "scalar":
            return ScalarVaryingDoublePlan(self.to_plan(c.args[0]), "scalar")
        if name == "vector":
            return ScalarVaryingDoublePlan(self.to_plan(c.args[0]), "vector")
        if name == "limit":
            if len(c.args) != 2 or not isinstance(c.args[0], NumLit):
                raise PromQLError("limit expects (n, expr)")
            return ApplyLimitFunction(self.to_plan(c.args[1]), int(c.args[0].value))
        if name in ("optimize_with_agg", "no_optimize", "_filodb_chunkmeta_all"):
            # planner/lpopt markers + chunk-metadata debug wrapper
            if len(c.args) != 1:
                raise PromQLError(f"{name} expects exactly one argument")
            return ApplyMiscellaneousFunction(self.to_plan(c.args[0]), name)
        if name in ("label_replace", "label_join"):
            inner = self.to_plan(c.args[0])
            strs = []
            for a in c.args[1:]:
                if not isinstance(a, StrLit):
                    raise PromQLError(f"{name} expects string arguments")
                strs.append(a.value)
            return ApplyMiscellaneousFunction(inner, name, tuple(strs))
        if name in F.INSTANT_FUNCTIONS:
            # scalar args may come before (histogram_quantile) or after
            # (clamp, round) the vector argument
            scalars: list = []
            vec_plan: LogicalPlan | None = None
            for a in c.args:
                if _is_scalar_node(a):
                    lit = self.to_plan(a)
                    scalars.append(lit.value if isinstance(lit, ScalarFixedDoublePlan) else lit)
                else:
                    vec_plan = self.to_plan(a)
            if vec_plan is None:
                raise PromQLError(f"{name} needs a vector argument")
            return ApplyInstantFunction(vec_plan, name, tuple(scalars))
        raise PromQLError(f"unknown function {name!r}")

    def aggregate(self, a: Agg) -> LogicalPlan:
        inner = self.to_plan(a.expr)
        params: tuple = ()
        if a.param is not None:
            if isinstance(a.param, NumLit):
                params = (a.param.value,)
            elif isinstance(a.param, StrLit):
                params = (a.param.value,)
            elif isinstance(a.param, Unary) and isinstance(a.param.expr, NumLit):
                params = (-a.param.expr.value,)
            else:
                raise PromQLError(f"{a.op}: parameter must be a literal")
        return Aggregate(
            a.op,
            inner,
            params,
            tuple(a.by) if a.by is not None else None,
            tuple(a.without) if a.without is not None else None,
        )

    def binary(self, b: Binary) -> LogicalPlan:
        scalar_l = _is_scalar_node(b.lhs)
        scalar_r = _is_scalar_node(b.rhs)
        p = self.p
        if scalar_l and scalar_r:
            if b.op in F.SET_OPS:
                raise PromQLError(f"set operator {b.op} requires vector operands")
            lhs, rhs = self.to_plan(b.lhs), self.to_plan(b.rhs)
            return ScalarBinaryOperation(b.op, lhs, rhs, p.start_ms, p.end_ms, p.step_ms)
        if scalar_l or scalar_r:
            if b.op in F.SET_OPS:
                raise PromQLError(f"set operator {b.op} requires vector operands")
            sc = self.to_plan(b.lhs if scalar_l else b.rhs)
            vec = self.to_plan(b.rhs if scalar_l else b.lhs)
            return ScalarVectorBinaryOperation(b.op, sc, vec, scalar_l, b.return_bool)
        lhs, rhs = self.to_plan(b.lhs), self.to_plan(b.rhs)
        if b.op in F.SET_OPS:
            card = "many-to-many"
        elif b.group_side == "left":
            card = "many-to-one"
        elif b.group_side == "right":
            card = "one-to-many"
        else:
            card = "one-to-one"
        return BinaryJoin(
            lhs,
            b.op,
            rhs,
            card,
            tuple(b.on) if b.on is not None else None,
            tuple(b.ignoring or ()),
            tuple(b.include or ()),
            b.return_bool,
        )


# ---------------------------------------------------------------------------
# Entry points (reference Parser.queryToLogicalPlan:183 /
# queryRangeToLogicalPlan:190 / metadataQueryToLogicalPlan:104)
# ---------------------------------------------------------------------------


def parse_query(query: str) -> Node:
    return Parser(query).parse()


def query_range_to_logical_plan(
    query: str, start_s: float, end_s: float, step_s: float, lookback_ms: int = DEFAULT_LOOKBACK_MS
) -> LogicalPlan:
    params = TimeStepParams(int(start_s * 1000), int(end_s * 1000), max(int(step_s * 1000), 1))
    ast = parse_query(query)
    # bare matrix selector / subquery at top level => raw export / subquery
    if isinstance(ast, Selector) and ast.window_ms is not None:
        off = ast.offset_ms
        conv = Converter(params, lookback_ms)
        return RawSeries(
            conv._filters(ast),
            params.start_ms - ast.window_ms - off,
            params.end_ms - off,
            offset_ms=off,
        )
    return Converter(params, lookback_ms).to_plan(ast)


def query_to_logical_plan(query: str, time_s: float, lookback_ms: int = DEFAULT_LOOKBACK_MS) -> LogicalPlan:
    """Instant query: grid of one step at time_s."""
    return query_range_to_logical_plan(query, time_s, time_s, 1, lookback_ms)
