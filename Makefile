# filodb-tpu build/test/bench shortcuts

NATIVE_DIR := filodb_tpu/native

.PHONY: all native test test-alerting test-chaos test-index test-ingest-chaos test-jitter test-multichip test-observability test-replica test-rollup test-scheduler test-standing attest bench bench-smoke microbench serve clean tpu-watch tpu-watch-bg

all: native

native: $(NATIVE_DIR)/libfilodbcodecs.so $(NATIVE_DIR)/libfilodbindex.so $(NATIVE_DIR)/libfilodbprom.so $(NATIVE_DIR)/libfilodbrender.so

$(NATIVE_DIR)/libfilodbcodecs.so: $(NATIVE_DIR)/codecs.cpp
	g++ -O3 -march=native -shared -fPIC $< -o $@

$(NATIVE_DIR)/libfilodbindex.so: $(NATIVE_DIR)/index.cpp
	g++ -O3 -shared -fPIC $< -o $@

$(NATIVE_DIR)/libfilodbprom.so: $(NATIVE_DIR)/promparse.cpp
	g++ -O3 -march=native -std=c++17 -shared -fPIC $< -o $@

# best-effort: the renderer carries its own shortest-repr formatter so it
# builds on gcc >= 10 (integer std::to_chars only); runtime falls back to
# the vectorized numpy / pure-Python renderers (api/promjson.py) when the
# .so is absent
$(NATIVE_DIR)/libfilodbrender.so: $(NATIVE_DIR)/promrender.cpp
	-g++ -O3 -march=native -std=c++17 -shared -fPIC $< -o $@

# default test run; pair with `make bench-smoke` before sending a perf-
# sensitive change (the smoke gate catches losing the fused single-dispatch
# path or a staging-cache regression that unit tests can't see)
test: native
	python -m pytest tests/ -q

# deterministic fault-injection suite (doc/robustness.md): retries,
# circuit breakers, partial results, shard-reassignment convergence
test-chaos: native
	python -m pytest tests/ -q -m chaos

# ingest-concurrency suite (doc/robustness.md "superblock consistency
# model"): superblock extend/revalidate under live ingest, staging-cache
# liveness vs the interval-aware insert guard, downsample claim/release
# races and crash-mid-commit redo
test-ingest-chaos: native
	python -m pytest tests/ -q -m ingest_chaos

# mesh-sharded fused suite (doc/perf.md "Mesh-sharded fused path"): sharded
# vs single-device vs reference parity over the full operator set, the
# warm-query-is-ONE-dispatch assertion on the forced 8-device CPU mesh, and
# the sharded canonical query + histogram_quantile end-to-end through the
# MULTICHIP dryrun entry
test-multichip: native
	env JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python -m pytest tests/test_fused_mesh.py -q -m fused_mesh
	env JAX_PLATFORMS=cpu python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

# jitter-tolerant fused suite (doc/perf.md "Jitter-tolerant fused path"):
# fused-vs-reference parity on jitter5pct / jitter+holes grids across the
# epilogue families (hist_quantile included), warm single-dispatch
# assertions for regular/jittered/holey grids + the mesh twins on the
# forced 8-device CPU mesh, superblock grid-class isolation, and
# extension-under-ingest on a jittered block
test-jitter: native
	env JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python -m pytest tests/test_fused_jitter.py -q -m fused_jitter

# query dispatch scheduler suite (doc/operations.md "Cross-query batching &
# admission control"): batched-vs-sequential bit parity across the epilogue
# families, the ONE-dispatch-per-coalesced-group assertion, tenant quota
# shedding + fairness, 429/Retry-After surfaces, batching-off golden
# equivalence
test-scheduler: native
	python -m pytest tests/ -q -m scheduler

# standing-query engine suite (doc/operations.md "Standing queries &
# recording rules"): delta-maintenance bit-equality vs full re-evaluation
# across regular/jitter/holes grids and under concurrent in-place
# extension, zero-dispatch retained refreshes, promotion/demotion
# hysteresis over the scheduler's recurrence ring, one-materialization SSE
# fan-out to N subscribers, and recording-rule write-back
test-standing: native
	python -m pytest tests/test_standing.py -q -m standing

# vectorized part-key index suite (doc/perf.md "Vectorized part-key
# index"): randomized property equivalence of the posting-bitmap index vs
# the retained set-based oracle (eq/in/literal-alt/prefix/general-regex/
# negative/empty-matcher x interval overlap x limit), incremental
# add/update_end_time/remove parity, concurrent lookup-vs-ingest soak,
# and zero ledger drift for the opt-in device postings tier
test-index: native
	python -m pytest tests/test_index_bitmap.py -q -m index

# sketch rollup tier suite (doc/perf.md "Sketch rollup tier"): planner
# substitution (querylog path=rollup) + parity vs the raw path within the
# documented error bounds, bit-identical plan-time AND runtime fallback,
# chooser add/retire from querylog evidence, log-linear sketch property
# tests vs the numpy quantile oracle, psum-merge parity on the 8-device
# virtual mesh, and superblock pinning under eviction storms
test-rollup: native
	python -m pytest tests/test_rollup.py tests/test_sketch_property.py -q -m rollup

# replicated shard plane suite (doc/robustness.md "Replicated shard
# plane"): replica placement invariants, ingest fan-out with per-replica
# acks + lag watermarks, bit-equal failover to sibling replicas (control-
# plane kill, stale-mapping endpoint failure, open breaker as a routing
# signal), live rebalance with effect-log cutover proof + standing-query
# handoff, and the chaos storm: kill a node under 16 concurrent clients
# with partial results OFF and zero 5xx
test-replica: native
	python -m pytest tests/test_replica.py -q -m replica

# alerting plane suite (doc/observability.md "Alerting plane"): rule-file
# schema validation, the per-labelset pending→firing state machine with an
# injected clock (for:/keep_firing_for holds), ALERTS/ALERTS_FOR_STATE
# write-back + rehydration across restart, notification grouping/dedup +
# retry/backoff/breaker against a dead receiver, and the e2e proof:
# injected 5xx -> SLO burn -> firing -> exactly ONE grouped webhook
test-alerting: native
	python -m pytest tests/test_alerting.py -q -m alerting

# observability suite (doc/observability.md): trace propagation + stitching,
# slow-query log, query observatory (per-phase decomposition, query-log
# ring, _system round trips, SLO burn-rate rules), resource ledger +
# self-scrape, metrics exposition — plus the span-coverage + phase-coverage
# lint (every ExecPlan subclass executes under a span; every phase literal
# canonical and every fused path decomposed) and the metrics-doc lint
# (every filodb_* family emitted is documented, and vice versa)
test-observability: native test-alerting
	python tools/check_spans.py
	python tools/check_metrics.py
	python -m pytest tests/ -q -m "observability or chaos" --continue-on-collection-errors

bench: native
	python bench.py

# perf regression gate (doc/perf.md): 2k series, 3 runs, CPU backend;
# fails if p50 regresses >25% vs benchmarks/bench_smoke_floor.json —
# plus the attestation machinery smoke (one tiny workload through the
# bench -> kernel-snapshot -> verdict -> digest pipeline)
bench-smoke: native
	python tools/bench_smoke.py
	python tools/attest.py --smoke

# one-command hardware attestation (doc/operations.md "Attestation"):
# bench-smoke floors + MULTICHIP dryrun + per-workload kernel-observatory
# snapshots, bundled into one signed-off ATTEST_<backend>.json proving
# what compiled, dispatched and fell back. Runs on the CPU backend today
# and unchanged on hardware (workers label their backend honestly).
attest: native
	python tools/attest.py

microbench: native
	python -m benchmarks.run

serve:
	python -m filodb_tpu.cli serve --config conf/timeseries-dev.json

# probe the TPU tunnel all session; harvest + commit an attested bench number
# the moment a healthy window appears (tools/tpu_watch.py)
tpu-watch: native
	python tools/tpu_watch.py

tpu-watch-bg: native
	nohup python tools/tpu_watch.py >> tpu_watch_stdout.txt 2>&1 & echo "tpu-watch pid $$!"

clean:
	rm -f $(NATIVE_DIR)/*.so
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null || true
