"""End-to-end mesh execution through the planner: a QueryEngine configured
with an 8-device mesh must produce identical results to the host path for
distributed aggregations (the psum form of ReduceAggregateExec)."""

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.parallel.mesh import make_mesh
from filodb_tpu.testkit import counter_batch, machine_metrics

BASE = 1_600_000_000_000
START_S = (BASE + 600_000) / 1000
END_S = (BASE + 1_500_000) / 1000


@pytest.fixture(scope="module")
def engines():
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(8))
    ms.ingest_routed("prometheus", counter_batch(n_series=40, n_samples=160, start_ms=BASE), spread=3)
    ms.ingest_routed("prometheus", machine_metrics(n_series=40, n_samples=160, start_ms=BASE), spread=3)
    host = QueryEngine(ms, "prometheus")
    mesh = QueryEngine(ms, "prometheus", PlannerParams(mesh=make_mesh()))
    return host, mesh


def grids_map(res):
    out = {}
    for lbls, ts, vals in res.all_series():
        out[tuple(sorted(lbls.items()))] = (ts, vals)
    return out


@pytest.mark.parametrize("q", [
    "sum(rate(http_requests_total[5m]))",
    "sum by (instance) (rate(http_requests_total[5m]))",
    "avg(sum_over_time(heap_usage0[5m]))",
    "max by (instance) (avg_over_time(heap_usage0[5m]))",
    "count(last_over_time(heap_usage0[5m]))",
])
def test_mesh_matches_host_path(engines, q):
    host, mesh = engines
    r_host = host.query_range(q, START_S, END_S, 60)
    r_mesh = mesh.query_range(q, START_S, END_S, 60)
    mh, mm = grids_map(r_host), grids_map(r_mesh)
    assert mh.keys() == mm.keys()
    for k in mh:
        np.testing.assert_array_equal(mh[k][0], mm[k][0])
        np.testing.assert_allclose(mm[k][1], mh[k][1], rtol=2e-3, err_msg=q)


def test_mesh_plan_is_single_exec(engines):
    _, mesh = engines
    from filodb_tpu.parallel.exec import MeshAggregateExec
    from filodb_tpu.query.promql import query_range_to_logical_plan

    plan = query_range_to_logical_plan("sum(rate(http_requests_total[5m]))", START_S, END_S, 60)
    ep = mesh.planner.materialize(plan)
    assert isinstance(ep, MeshAggregateExec)


def test_unsupported_shapes_fall_back(engines):
    _, mesh = engines
    from filodb_tpu.parallel.exec import MeshAggregateExec
    from filodb_tpu.query.promql import query_range_to_logical_plan

    for q in [
        "topk(3, rate(http_requests_total[5m]))",          # non-mesh op
        "sum(rate(http_requests_total[5m] offset 1m))",    # offset
        "sum(quantile_over_time(0.9, heap_usage0[5m]))",   # sorted family
    ]:
        ep = mesh.planner.materialize(query_range_to_logical_plan(q, START_S, END_S, 60))
        assert not isinstance(ep, MeshAggregateExec), q


class TestTimeShardInEngine:
    def test_long_range_uses_time_shard(self, engines):
        host, mesh = engines
        from filodb_tpu.parallel.exec import TimeShardRangeExec
        from filodb_tpu.query.promql import query_range_to_logical_plan

        # 160 samples @10s = ~27min of data; query far more steps at 5s
        long_end = (BASE + 1_600_000) / 1000
        plan = query_range_to_logical_plan(
            "rate(http_requests_total[2m])", START_S, long_end, 1.5)
        ep = mesh.planner.materialize(plan)
        assert isinstance(ep, TimeShardRangeExec)
        r_mesh = ep.execute(mesh.context())
        r_host = host.query_range("rate(http_requests_total[2m])", START_S, long_end, 1.5)
        mh = grids_map(r_host)
        mm = grids_map(r_mesh)
        assert mh.keys() == mm.keys()
        for k in mh:
            np.testing.assert_allclose(mm[k][1], mh[k][1], rtol=2e-3)

    def test_short_range_stays_standard(self, engines):
        _, mesh = engines
        from filodb_tpu.parallel.exec import TimeShardRangeExec
        from filodb_tpu.query.promql import query_range_to_logical_plan

        plan = query_range_to_logical_plan(
            "rate(http_requests_total[5m])", START_S, END_S, 60)
        assert not isinstance(mesh.planner.materialize(plan), TimeShardRangeExec)


def test_mesh_quantile_sketch(engines):
    host, mesh = engines
    from filodb_tpu.parallel.exec import MeshQuantileExec
    from filodb_tpu.query.promql import query_range_to_logical_plan

    q = "quantile(0.5, rate(http_requests_total[5m]))"
    plan = query_range_to_logical_plan(q, START_S, END_S, 60)
    ep = mesh.planner.materialize(plan)
    assert isinstance(ep, MeshQuantileExec)
    r_mesh = ep.execute(mesh.context())
    r_host = host.query_range(q, START_S, END_S, 60)
    got = r_mesh.grids[0].values_np()[0]
    want = r_host.grids[0].values_np()[0]
    m = ~np.isnan(want)
    err = np.abs(got[m] - want[m]) / np.maximum(np.abs(want[m]), 1e-9)
    assert (err < 0.08).all()


def test_time_only_mesh_aggregation_falls_back_to_host(engines):
    """A ('time',) mesh must not route aggregations into the shard-psum
    program (which would crash on the missing axis)."""
    host, _ = engines
    from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
    from filodb_tpu.parallel.exec import MeshAggregateExec
    from filodb_tpu.parallel.timeshard import make_time_mesh
    from filodb_tpu.query.promql import query_range_to_logical_plan

    engine = QueryEngine(host.memstore, "prometheus", PlannerParams(mesh=make_time_mesh()))
    q = "sum(rate(http_requests_total[5m]))"
    plan = query_range_to_logical_plan(q, START_S, END_S, 60)
    ep = engine.planner.materialize(plan)
    assert not isinstance(ep, MeshAggregateExec)
    res = ep.execute(engine.context())
    want = host.query_range(q, START_S, END_S, 60)
    np.testing.assert_allclose(
        res.grids[0].values_np() if res.grids else list(res.all_series())[0][2],
        want.grids[0].values_np(), rtol=1e-3, equal_nan=True)
