"""Index time-lifecycle driven through SHARD INGEST (not the index directly)
— the gap the round-1 fuzz tests missed. Reference behavior:
TimeSeriesShard.scala:987-993 (updateIndexWithEndTime during flush) +
PartKeyLuceneIndex.scala:628 (updatePartKeyWithEndTime) + re-activation on
resumed ingest in getOrAddPartitionAndIngest."""

import numpy as np

from filodb_tpu.core.filters import equals
from filodb_tpu.core.records import SeriesBatch
from filodb_tpu.core.schemas import GAUGE, METRIC_TAG, Dataset
from filodb_tpu.coordinator.planner import QueryEngine
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.store.columnstore import NullColumnStore
from filodb_tpu.store.flush import FlushCoordinator

BASE = 1_600_000_000_000


def _gauge(tags, ts):
    return SeriesBatch(GAUGE, tags, ts, {"value": np.linspace(1.0, 2.0, len(ts))})


def _setup(n_live=3, n_dead=2):
    """n_dead series stop at BASE+600s; n_live keep ingesting past BASE+1200s."""
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), [0])
    sh = ms.shard("ds", 0)
    early = BASE + np.arange(60, dtype=np.int64) * 10_000        # BASE .. BASE+590s
    late = BASE + 600_000 + np.arange(60, dtype=np.int64) * 10_000
    for i in range(n_dead):
        sh.ingest_series(_gauge({METRIC_TAG: "m", "instance": f"dead-{i}"}, early))
    for i in range(n_live):
        sh.ingest_series(_gauge({METRIC_TAG: "m", "instance": f"live-{i}"}, early))
    return ms, sh, late


def _flush(ms):
    return FlushCoordinator(ms, NullColumnStore()).flush_shard("ds", 0)


class TestStartTimeFromIngest:
    def test_real_start_time_indexed(self):
        ms, sh, _ = _setup()
        f = [equals(METRIC_TAG, "m")]
        # query entirely BEFORE the first sample: index must prune everything
        assert len(sh.lookup_partitions(f, BASE - 1_000_000, BASE - 1)) == 0
        # overlapping range still finds all 5
        assert len(sh.lookup_partitions(f, BASE, BASE + 600_000)) == 5


class TestEndTimeLifecycle:
    def test_end_times_set_after_idle_flush_cycle(self):
        ms, sh, late = _setup()
        f = [equals(METRIC_TAG, "m")]
        _flush(ms)  # first flush: records watermark, nothing marked ended
        assert len(sh.lookup_partitions(f, BASE + 700_000, BASE + 800_000)) == 5
        # live series keep ingesting; dead ones do not
        for i in range(3):
            sh.ingest_series(_gauge({METRIC_TAG: "m", "instance": f"live-{i}"}, late))
        _flush(ms)  # watermark unchanged for dead series -> end time set
        pids = sh.lookup_partitions(f, BASE + 700_000, BASE + 1_300_000)
        assert len(pids) == 3
        tags = {sh.index.tags_of(int(p))["instance"] for p in pids}
        assert tags == {"live-0", "live-1", "live-2"}
        # range overlapping the dead series' lifetime still matches all 5
        assert len(sh.lookup_partitions(f, BASE, BASE + 300_000)) == 5

    def test_resumed_ingest_reactivates(self):
        ms, sh, late = _setup(n_live=1, n_dead=1)
        _flush(ms)
        _flush(ms)  # both idle now -> both marked ended
        f = [equals(METRIC_TAG, "m")]
        assert len(sh.lookup_partitions(f, BASE + 700_000, BASE + 1_300_000)) == 0
        # dead-0 resumes: end time snaps back to the still-ingesting sentinel
        sh.ingest_series(_gauge({METRIC_TAG: "m", "instance": "dead-0"}, late))
        pids = sh.lookup_partitions(f, BASE + 2_000_000, BASE + 3_000_000)
        assert len(pids) == 1
        assert sh.index.tags_of(int(pids[0]))["instance"] == "dead-0"

    def test_engine_query_outside_live_range_selects_zero(self):
        """VERDICT done-criterion: a query outside a series' live range selects
        0 series THROUGH THE ENGINE."""
        ms, sh, late = _setup(n_live=1, n_dead=2)
        for _ in range(2):
            _flush(ms)
        # only live-0 resumed past BASE+600s
        sh.ingest_series(_gauge({METRIC_TAG: "m", "instance": "live-0"}, late))
        eng = QueryEngine(ms, "ds")
        # window starting 400s after the dead series ended (lookback 5m cannot
        # reach their last samples)
        start_s = (BASE + 1_000_000) / 1000
        end_s = (BASE + 1_180_000) / 1000
        res = eng.query_range("m", start_s, end_s, 60)
        insts = {lbl.get("instance") for g in res.grids for lbl in g.labels}
        assert insts == {"live-0"}

    def test_recovery_restores_end_times(self):
        from filodb_tpu.store.columnstore import LocalColumnStore
        from filodb_tpu.store.flush import recover_shard
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            store = LocalColumnStore(d)
            ms, sh, late = _setup(n_live=1, n_dead=1)
            fc = FlushCoordinator(ms, store)
            fc.flush_shard("ds", 0)
            for _ in range(2):
                sh.ingest_series(_gauge({METRIC_TAG: "m", "instance": "live-0"}, late))
                fc.flush_shard("ds", 0)
            ms2 = TimeSeriesMemStore()
            ms2.setup(Dataset("ds"), [0])
            recover_shard(ms2, store, "ds", 0)
            sh2 = ms2.shard("ds", 0)
            f = [equals(METRIC_TAG, "m")]
            # start times survive recovery: query before first sample is empty
            assert len(sh2.lookup_partitions(f, BASE - 10_000, BASE - 1)) == 0
            assert len(sh2.lookup_partitions(f, BASE, BASE + 500_000)) == 2
