"""Native range-aware regex index path (native/index.cpp prefix-range scan
+ union; reference: tantivy_utils' range-aware regex,
PartKeyTantivyIndex.scala:38). The prefix extraction must be SAFE — a wrong
prefix silently drops matching series — so the nasty cases (quantifier
eating the last literal char, alternations bypassing the prefix) are pinned
here in addition to the randomized backend-parity fuzzing."""

import numpy as np
import pytest

from filodb_tpu.core.filters import ColumnFilter, regex
from filodb_tpu.memstore.index import PartKeyIndex

pytest.importorskip("filodb_tpu.memstore.index_native")
from filodb_tpu.memstore.index_native import (  # noqa: E402
    NativePartKeyIndex,
    native_index_available,
    regex_literal_prefix,
)

if not native_index_available():  # pragma: no cover
    pytest.skip("native index unavailable", allow_module_level=True)


@pytest.mark.parametrize("pattern,prefix", [
    ("http_5.*", "http_5"),
    ("http_.*_total", "http_"),
    ("abc", "abc"),
    ("ab*", "a"),        # * makes the b optional
    ("ab?", "a"),
    ("ab{0,2}", "a"),
    ("ab+", "ab"),       # + requires at least one b
    ("a|b", ""),         # alternation bypasses any prefix
    ("abc|z", ""),
    ("ab(c|d)e", ""),    # nested alternation: conservative collapse
    (".*foo", ""),
    (r"ab\.c", "ab"),    # escape stops the literal run (conservative)
    ("", ""),
])
def test_literal_prefix_extraction(pattern, prefix):
    got, _ = regex_literal_prefix(pattern)
    assert got == prefix, pattern


def _build(idx_cls, values):
    idx = idx_cls()
    for pid, v in enumerate(values):
        idx.add_partkey(pid, {"m": v, "dc": f"d{pid % 3}"}, 0, 10_000)
    return idx


VALUES = [
    "http_requests_total", "http_errors_total", "http_500", "http_5xx",
    "grpc_requests", "a", "ab", "abb", "abc", "z", "foo", "xfoo",
    "e1", "e2", "e3", "ab.c", "abXc",
]

PATTERNS = [
    "http_.*", "http_5.*", "http_.*_total", "ab*", "ab+", "ab?", "abc",
    "a|b", "abc|z", "ab(c|d)", ".*foo", r"ab\.c", "e1|e2", "http_[0-9]+",
    "h.*_5.*",
]


@pytest.mark.parametrize("pattern", PATTERNS)
def test_regex_parity_with_python_index(pattern):
    py = _build(PartKeyIndex, VALUES)
    nat = _build(NativePartKeyIndex, VALUES)
    f = [regex("m", pattern)]
    want = py.part_ids_from_filters(f, 0, 20_000)
    got = nat.part_ids_from_filters(f, 0, 20_000)
    np.testing.assert_array_equal(got, want, err_msg=pattern)


@pytest.mark.parametrize("pattern", PATTERNS)
def test_regex_and_equality_parity(pattern):
    py = _build(PartKeyIndex, VALUES)
    nat = _build(NativePartKeyIndex, VALUES)
    f = [regex("m", pattern), ColumnFilter("dc", "=", "d1")]
    want = py.part_ids_from_filters(f, 0, 20_000)
    got = nat.part_ids_from_filters(f, 0, 20_000)
    np.testing.assert_array_equal(got, want, err_msg=pattern)


@pytest.mark.parametrize("idx_cls", [PartKeyIndex, NativePartKeyIndex])
def test_metachar_patterns_never_take_literal_shortcut(idx_cls):
    """'ab+' and 'h.1' contain metacharacters: both backends must regex-
    match them (the old _LITERAL_ALT classed '.'/'+' as literals and looked
    the pattern up verbatim — wrong results in BOTH backends)."""
    idx = _build(idx_cls, ["ab", "abb", "h01", "hx1", "h.1", "ab+"])
    got = idx.part_ids_from_filters([regex("m", "ab+")], 0, 20_000)
    assert got.tolist() == [0, 1], "ab+ must match ab and abb"
    got = idx.part_ids_from_filters([regex("m", "h.1")], 0, 20_000)
    assert got.tolist() == [2, 3, 4], "h.1 must match h01, hx1 AND h.1"
    got = idx.part_ids_from_filters([regex("m", "ab|abb")], 0, 20_000)
    assert got.tolist() == [0, 1]


def test_time_overlap_applies_to_regex_union():
    nat = NativePartKeyIndex()
    nat.add_partkey(0, {"m": "http_a"}, 0, 100)
    nat.add_partkey(1, {"m": "http_b"}, 200, 300)
    got = nat.part_ids_from_filters([regex("m", "http_.*")], 150, 400)
    np.testing.assert_array_equal(got, [1])


def test_empty_matching_regex_stays_on_python_path():
    """Patterns matching the empty string must also match series MISSING
    the tag — the native union can't see those, so the python path must
    answer (and it does, identically to the python backend)."""
    py = _build(PartKeyIndex, VALUES)
    nat = _build(NativePartKeyIndex, VALUES)
    for idx in (py, nat):
        idx.add_partkey(900, {"other": "x"}, 0, 10_000)  # no "m" tag
    f = [regex("m", ".*")]
    want = py.part_ids_from_filters(f, 0, 20_000)
    got = nat.part_ids_from_filters(f, 0, 20_000)
    np.testing.assert_array_equal(got, want)
    assert 900 in got.tolist()


def test_values_prefix_buffer_regrowth():
    """The packed-values buffer must regrow when 64 KiB overflows."""
    nat = NativePartKeyIndex()
    long_vals = [f"metric_{'x' * 200}_{i:05d}" for i in range(600)]
    for pid, v in enumerate(long_vals):
        nat.add_partkey(pid, {"m": v}, 0, 10_000)
    got = nat._values_with_prefix(b"m", b"metric_")
    assert sorted(got) == sorted(long_vals)
    ids = nat.part_ids_from_filters([regex("m", "metric_.*")], 0, 20_000)
    assert len(ids) == 600
