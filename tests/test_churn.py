"""Label-churn finder (reference spark-jobs LabelChurnFinder — HLL sketches
of total vs active distinct label values per (ws, ns, label))."""

import numpy as np
import pytest

from filodb_tpu.downsample.churn import ChurnRecord, HllSketch, LabelChurnFinder
from filodb_tpu.store.columnstore import LocalColumnStore

NOW = 1_600_100_000_000
HOUR = 3_600_000


class TestHllSketch:
    def test_small_range_near_exact(self):
        s = HllSketch()
        s.add_all(f"v{i}" for i in range(100))
        assert abs(s.estimate() - 100) <= 3  # linear-counting regime

    def test_large_range_within_error(self):
        s = HllSketch()
        s.add_all(f"value-{i}" for i in range(20_000))
        assert abs(s.estimate() - 20_000) / 20_000 < 0.05

    def test_duplicates_not_counted(self):
        s = HllSketch()
        for _ in range(5):
            s.add_all(f"v{i}" for i in range(500))
        assert abs(s.estimate() - 500) <= 15

    def test_merge_is_union(self):
        a, b, u = HllSketch(), HllSketch(), HllSketch()
        a.add_all(f"x{i}" for i in range(3000))
        b.add_all(f"x{i}" for i in range(1500, 4500))  # overlaps a
        u.add_all(f"x{i}" for i in range(4500))
        a.merge(b)
        assert a.estimate() == pytest.approx(u.estimate())  # register-exact

    def test_hash_is_process_stable(self):
        # blake2b, not python hash(): sketches built in other processes
        # (Spark-executor analog) must merge meaningfully
        assert HllSketch._hash64("pod-abc123") == HllSketch._hash64("pod-abc123")


def _store_with_partkeys(tmp_path, n_shards=2):
    """Synthesize a persisted partkey population:

    - label 'pod': 400 historical values, only 10 still active -> churner
    - label 'instance': 20 values, all active -> stable
    """
    store = LocalColumnStore(str(tmp_path))
    for i in range(400):
        shard = i % n_shards
        active = i < 10
        end = NOW - 10_000 if active else NOW - 50 * HOUR
        tags = {
            "_ws_": "demo", "_ns_": "app", "_metric_": "http_requests_total",
            "pod": f"pod-{i:04d}", "instance": f"inst-{i % 20}",
        }
        store.write_partkey("prometheus", shard, tags, NOW - 100 * HOUR, end)
    return store


class TestLabelChurnFinder:
    def test_flags_churner_not_stable_label(self, tmp_path):
        store = _store_with_partkeys(tmp_path)
        finder = LabelChurnFinder(store, "prometheus", [0, 1], now_ms=NOW,
                                  active_ms=2 * HOUR)
        rows = finder.report(min_total=50, min_ratio=2.0)
        labels = [r.label for r in rows]
        assert "pod" in labels
        assert "instance" not in labels  # 20 total / 20 active: no churn
        pod = rows[labels.index("pod")]
        assert pod.prefix == ("demo", "app")
        assert abs(pod.total - 400) / 400 < 0.1
        assert pod.active <= 15  # ~10 live values
        assert pod.ratio > 20

    def test_cross_shard_values_dedup(self, tmp_path):
        """The same value written in every shard counts once (HLL union),
        unlike a naive per-shard sum."""
        store = LocalColumnStore(str(tmp_path))
        for shard in range(4):
            for i in range(50):
                store.write_partkey(
                    "prometheus", shard,
                    {"_ws_": "w", "_ns_": "n", "_metric_": "m", "zone": f"z{i}"},
                    NOW - HOUR, NOW,
                )
        finder = LabelChurnFinder(store, "prometheus", range(4), now_ms=NOW)
        sketches = finder.scan()
        tot, act = sketches[(("w", "n"), "zone")]
        assert abs(tot.estimate() - 50) <= 3
        assert abs(act.estimate() - 50) <= 3

    def test_shard_key_and_metric_tags_excluded(self, tmp_path):
        store = _store_with_partkeys(tmp_path)
        finder = LabelChurnFinder(store, "prometheus", [0, 1], now_ms=NOW)
        for (prefix, label) in finder.scan():
            assert label not in ("_ws_", "_ns_", "_metric_")

    def test_cli_churn_find(self, tmp_path, capsys):
        from filodb_tpu.cli import main

        _store_with_partkeys(tmp_path)
        main(["churn-find", "--store", str(tmp_path), "--min-total", "50"])
        out = capsys.readouterr().out
        assert "pod" in out and "ratio" in out


class TestChurnRecord:
    def test_ratio_guards_zero_active(self):
        assert ChurnRecord(("w", "n"), "l", 100, 0).ratio == 100.0
