"""FiloClient tests (reference client-package specs: LocalClient
QueryOps/ClusterOps against a running node)."""

import json

import numpy as np
import pytest

from filodb_tpu.api.http import serve_background
from filodb_tpu.client import FiloClient
from filodb_tpu.coordinator.planner import QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore

BASE = 1_600_000_000_000


@pytest.fixture(scope="module")
def client():
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(4))
    engine = QueryEngine(ms, "prometheus")
    srv, port = serve_background(engine)
    yield FiloClient(f"http://127.0.0.1:{port}")
    srv.shutdown()


def test_ingest_and_query_range(client):
    text = "# TYPE rq_total counter\n" + "\n".join(
        f'rq_total{{job="api"}} {40 + 10 * i} {BASE + i * 60_000}' for i in range(10)
    )
    assert client.ingest_prom(text) == 10
    ts, series = client.query_range(
        "rate(rq_total[5m])", (BASE + 400_000) / 1000, (BASE + 540_000) / 1000, 60
    )
    assert len(series) == 1
    assert series[0]["metric"]["job"] == "api"
    vals = series[0]["values"]
    assert len(vals) == len(ts) == 3
    np.testing.assert_allclose(vals[np.isfinite(vals)], 10 / 60, rtol=1e-3)


def test_instant_and_metadata(client):
    client.ingest_rows([
        {"tags": {"__name__": "g1", "kind": "x"}, "ts_ms": BASE, "value": 5.0}
    ])
    out = client.query("g1", (BASE + 100_000) / 1000)
    assert out["resultType"] == "vector" and len(out["result"]) == 1
    assert "rq_total" in client.labels() or "__name__" in client.labels()
    assert "g1" in client.label_values("__name__")
    md = client.metadata()
    assert md["rq_total"][0]["type"] == "counter"
    assert md["g1"][0]["type"] == "gauge"


def test_series_and_cardinality_and_health(client):
    client.ingest_rows([
        {"tags": {"__name__": "sc_metric", "job": "api"}, "ts_ms": BASE, "value": 1.0}
    ])
    s = client.series('sc_metric{job="api"}')
    assert len(s) == 1 and s[0]["__name__"] == "sc_metric"
    card = client.cardinality()
    assert card and card[0]["ts_count"] >= 1
    assert client.health()["status"] == "healthy"


def test_auth_roundtrip():
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), [0])
    engine = QueryEngine(ms, "prometheus")
    srv, port = serve_background(engine, auth_token="tk")
    try:
        c = FiloClient(f"http://127.0.0.1:{port}", token="tk")
        assert c.ingest_prom("m 1 1600000000000") == 1
        assert "__name__" in c.labels()
        bad = FiloClient(f"http://127.0.0.1:{port}")
        with pytest.raises(Exception):
            bad.labels()
    finally:
        srv.shutdown()


class TestGrpcClient:
    @pytest.fixture(scope="class")
    def pair(self):
        from filodb_tpu.api.grpc_exec import serve_grpc
        from filodb_tpu.testkit import counter_batch

        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), range(4))
        ms.ingest_routed(
            "prometheus", counter_batch(n_series=6, n_samples=60, start_ms=BASE),
            spread=2,
        )
        engine = QueryEngine(ms, "prometheus")
        hsrv, hport = serve_background(engine)
        gsrv, gport = serve_grpc(engine, port=0, host="127.0.0.1")
        http_c = FiloClient(f"http://127.0.0.1:{hport}")
        grpc_c = FiloClient(f"http://127.0.0.1:{hport}",
                            grpc_endpoint=f"grpc://127.0.0.1:{gport}")
        yield http_c, grpc_c
        hsrv.shutdown()
        gsrv.stop(grace=0)

    def test_query_range_parity(self, pair):
        """The binary transport returns the same grid as the JSON path."""
        http_c, grpc_c = pair
        args = ("sum(rate(http_requests_total[5m]))",
                (BASE + 400_000) / 1000, (BASE + 900_000) / 1000, 60)
        t1, s1 = http_c.query_range(*args)
        t2, s2 = grpc_c.query_range(*args)
        np.testing.assert_array_equal(t1, t2)
        assert len(s1) == len(s2) == 1
        np.testing.assert_allclose(s2[0]["values"], s1[0]["values"], rtol=1e-5)

    def test_instant_query_parity(self, pair):
        http_c, grpc_c = pair
        t = (BASE + 600_000) / 1000
        h = http_c.query("http_requests_total", t)
        g = grpc_c.query("http_requests_total", t)
        assert g["resultType"] == "vector"
        hk = sorted(json.dumps(r["metric"], sort_keys=True) for r in h["result"])
        gk = sorted(json.dumps(r["metric"], sort_keys=True) for r in g["result"])
        assert hk == gk
        assert all("__name__" in r["metric"] for r in g["result"])

    def test_metadata_still_http(self, pair):
        _, grpc_c = pair
        assert "job" in grpc_c.labels() or "__name__" in grpc_c.labels()


def test_grpc_scalar_query(request):
    """Scalar expressions over the binary transport (review: these were
    silently dropped — only grids were read)."""
    from filodb_tpu.api.grpc_exec import serve_grpc

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(2))
    engine = QueryEngine(ms, "prometheus")
    gsrv, gport = serve_grpc(engine, port=0, host="127.0.0.1")
    request.addfinalizer(lambda: gsrv.stop(grace=0))
    c = FiloClient("http://unused:1", grpc_endpoint=f"grpc://127.0.0.1:{gport}")
    out = c.query("1+1", (BASE + 60_000) / 1000)
    assert out["resultType"] == "scalar"
    assert float(out["result"][1]) == 2.0
    ts, series = c.query_range("3*2", (BASE + 60_000) / 1000, (BASE + 180_000) / 1000, 60)
    assert len(series) == 1
    np.testing.assert_allclose(series[0]["values"], 6.0)


def test_grpc_grid_alignment_offset_and_short(monkeypatch):
    """Advisor regression: the gRPC branch must align returned grids onto the
    client grid by timestamp (like the HTTP branch), not assume each grid
    exactly matches the requested (start, step, n)."""
    from filodb_tpu.query.rangevector import Grid, QueryResult

    start_s, end_s, step_s = 100.0, 100.0 + 5 * 60, 60.0
    # grid starts one step late and carries only 3 of the 6 requested steps
    g = Grid(labels=[{"job": "x"}], start_ms=160_000, step_ms=60_000,
             num_steps=3, values=np.array([[1.0, 2.0, 3.0]]))
    c = FiloClient("http://unused:1", grpc_endpoint="grpc://unused:2")
    monkeypatch.setattr(FiloClient, "_grpc_exec",
                        lambda self, *a, **k: QueryResult(grids=[g]))
    ts, series = c.query_range("m", start_s, end_s, step_s)
    assert len(series) == 1
    row = series[0]["values"]
    assert len(row) == 6
    assert np.isnan(row[0]) and np.isnan(row[4]) and np.isnan(row[5])
    np.testing.assert_array_equal(row[1:4], [1.0, 2.0, 3.0])
