"""Aggregation + histogram kernel tests (model: reference
AggrOverRangeVectorsSpec, HistogramQuantileMapperSpec, HistogramTest)."""

import numpy as np
import pytest

from filodb_tpu.ops import aggregations as A
from filodb_tpu.ops import hist_kernels as H


def grid(seed=0, S=20, J=10, nan_frac=0.2):
    rng = np.random.default_rng(seed)
    v = rng.standard_normal((S, J)) * 10 + 50
    if nan_frac:
        mask = rng.random((S, J)) < nan_frac
        v[mask] = np.nan
    return v.astype(np.float32)


class TestSegmentAggregate:
    @pytest.mark.parametrize("op", ["sum", "count", "avg", "min", "max", "stddev", "stdvar", "group"])
    def test_matches_numpy(self, op):
        v = grid(seed=3)
        gids = np.arange(20, dtype=np.int32) % 4
        got = np.asarray(A.segment_aggregate(op, v, gids, 4))
        want = np.full((4, 10), np.nan)
        for g in range(4):
            rows = v[gids == g].astype(np.float64)
            for j in range(10):
                col = rows[:, j]
                col = col[~np.isnan(col)]
                if len(col) == 0:
                    continue
                want[g, j] = {
                    "sum": col.sum, "count": lambda: len(col), "avg": col.mean,
                    "min": col.min, "max": col.max, "stddev": col.std,
                    "stdvar": col.var, "group": lambda: 1.0,
                }[op]()
        np.testing.assert_array_equal(np.isnan(got), np.isnan(want), err_msg=op)
        m = ~np.isnan(want)
        np.testing.assert_allclose(got[m], want[m], rtol=1e-4, atol=1e-4, err_msg=op)

    def test_all_nan_group(self):
        v = grid(seed=4)
        v[10:] = np.nan
        gids = (np.arange(20) >= 10).astype(np.int32)
        got = np.asarray(A.segment_aggregate("sum", v, gids, 2))
        assert np.isnan(got[1]).all()


class TestTopK:
    def test_topk_selects_k_largest_per_step(self):
        v = grid(seed=5, nan_frac=0)
        got = np.asarray(A.topk_mask(v, 3))
        for j in range(v.shape[1]):
            kept = np.nonzero(~np.isnan(got[:, j]))[0]
            assert len(kept) == 3
            thresh = np.sort(v[:, j])[-3]
            assert (v[kept, j] >= thresh).all()

    def test_bottomk(self):
        v = grid(seed=6, nan_frac=0)
        got = np.asarray(A.topk_mask(v, 2, bottom=True))
        for j in range(v.shape[1]):
            kept = np.nonzero(~np.isnan(got[:, j]))[0]
            assert len(kept) == 2
            thresh = np.sort(v[:, j])[1]
            assert (v[kept, j] <= thresh).all()

    def test_topk_with_nans(self):
        v = grid(seed=7, nan_frac=0.5)
        got = np.asarray(A.topk_mask(v, 5))
        # never selects a NaN slot
        assert not (np.isnan(v) & ~np.isnan(got)).any()


class TestSegmentQuantile:
    @pytest.mark.parametrize("q", [0.0, 0.25, 0.5, 0.9, 1.0])
    def test_matches_numpy(self, q):
        v = grid(seed=8, nan_frac=0.15)
        gids = np.arange(20, dtype=np.int32) % 3
        got = np.asarray(A.segment_quantile(v, gids, 3, np.float32(q)))
        for g in range(3):
            for j in range(10):
                col = v[gids == g][:, j].astype(np.float64)
                col = col[~np.isnan(col)]
                if len(col) == 0:
                    assert np.isnan(got[g, j])
                else:
                    np.testing.assert_allclose(got[g, j], np.quantile(col, q), rtol=1e-4, atol=1e-4)


class TestGroupIds:
    def test_by(self):
        labels = [{"job": "a", "inst": "1"}, {"job": "a", "inst": "2"}, {"job": "b", "inst": "1"}]
        gids, glabels = A.group_ids_for(labels, by=["job"], without=None)
        np.testing.assert_array_equal(gids, [0, 0, 1])
        assert glabels == [{"job": "a"}, {"job": "b"}]

    def test_without(self):
        labels = [{"_metric_": "m", "job": "a", "inst": "1"}, {"_metric_": "m", "job": "a", "inst": "2"}]
        gids, glabels = A.group_ids_for(labels, by=None, without=["inst"])
        np.testing.assert_array_equal(gids, [0, 0])
        assert glabels == [{"job": "a"}]

    def test_global(self):
        gids, glabels = A.group_ids_for([{"a": "1"}, {"b": "2"}], None, None)
        np.testing.assert_array_equal(gids, [0, 0])
        assert glabels == [{}]


class TestHistogramQuantile:
    def test_simple_uniform(self):
        les = np.array([1.0, 2.0, 4.0, np.inf], dtype=np.float32)
        # 10 obs per bucket -> uniform; median rank=20 -> at le=2.0
        buckets = np.array([[10.0, 20.0, 30.0, 40.0]], dtype=np.float32)
        got = np.asarray(H.histogram_quantile(np.float32(0.5), buckets, les))
        np.testing.assert_allclose(got, [2.0], rtol=1e-5)

    def test_interpolation_within_bucket(self):
        les = np.array([1.0, 2.0, np.inf], dtype=np.float32)
        buckets = np.array([[0.0, 10.0, 10.0]], dtype=np.float32)
        # all obs in (1,2]; q=0.5 -> 1.5
        got = np.asarray(H.histogram_quantile(np.float32(0.5), buckets, les))
        np.testing.assert_allclose(got, [1.5], rtol=1e-5)

    def test_first_bucket_lower_bound_zero(self):
        les = np.array([2.0, 4.0, np.inf], dtype=np.float32)
        buckets = np.array([[10.0, 10.0, 10.0]], dtype=np.float32)
        got = np.asarray(H.histogram_quantile(np.float32(0.5), buckets, les))
        np.testing.assert_allclose(got, [1.0], rtol=1e-5)  # interp from 0

    def test_top_bucket_clamps_to_highest_finite(self):
        les = np.array([1.0, 2.0, np.inf], dtype=np.float32)
        buckets = np.array([[0.0, 0.0, 10.0]], dtype=np.float32)
        got = np.asarray(H.histogram_quantile(np.float32(0.9), buckets, les))
        np.testing.assert_allclose(got, [2.0])

    def test_empty_histogram_nan(self):
        les = np.array([1.0, np.inf], dtype=np.float32)
        buckets = np.array([[0.0, 0.0]], dtype=np.float32)
        assert np.isnan(np.asarray(H.histogram_quantile(np.float32(0.5), buckets, les))[0])

    def test_batched_shapes(self):
        les = np.array([1.0, 2.0, 4.0, np.inf], dtype=np.float32)
        buckets = np.broadcast_to(
            np.array([10.0, 20.0, 30.0, 40.0], dtype=np.float32), (5, 7, 4)
        ).copy()
        got = np.asarray(H.histogram_quantile(np.float32(0.5), buckets, les))
        assert got.shape == (5, 7)
        np.testing.assert_allclose(got, 2.0, rtol=1e-5)


class TestHistogramFraction:
    def test_full_range_is_one(self):
        les = np.array([1.0, 2.0, np.inf], dtype=np.float32)
        buckets = np.array([[5.0, 10.0, 10.0]], dtype=np.float32)
        got = np.asarray(H.histogram_fraction(np.float32(0.0), np.float32(1e30), buckets, les))
        np.testing.assert_allclose(got, [1.0], rtol=1e-5)

    def test_half(self):
        les = np.array([1.0, 2.0, np.inf], dtype=np.float32)
        buckets = np.array([[10.0, 20.0, 20.0]], dtype=np.float32)
        got = np.asarray(H.histogram_fraction(np.float32(0.0), np.float32(1.0), buckets, les))
        np.testing.assert_allclose(got, [0.5], rtol=1e-5)


class TestHistRange:
    def test_hist_increase_matches_scalar_per_bucket(self):
        from filodb_tpu.ops.staging import stage_histogram_series, stage_series
        from filodb_tpu.ops.kernels import RangeParams, run_range_function
        from filodb_tpu.ops.hist_kernels import run_hist_range_function

        BASE = 1_600_000_000_000
        rng = np.random.default_rng(0)
        n, B = 200, 4
        ts = (BASE + np.arange(1, n + 1) * 10_000).astype(np.int64)
        incr = rng.poisson(3, size=(n, B)).astype(np.float64)
        incr[:, -1] = incr.sum(1)
        hist = np.cumsum(np.cumsum(incr, axis=1), axis=0)
        hb = stage_histogram_series([(ts, hist)], BASE, B, subtract_baseline=True)
        params = RangeParams(BASE + 400_000, 60_000, 5, 300_000)
        got = np.asarray(run_hist_range_function("increase", hb, params))[0, :5]
        # cross-check each bucket against the scalar kernel (counter path)
        for b in range(B):
            sb = stage_series([(ts, hist[:, b])], BASE, counter_corrected=True)
            want = np.asarray(
                run_range_function("increase", sb, params, is_counter=True)
            )[0, :5]
            np.testing.assert_allclose(got[:, b], want, rtol=1e-3, atol=1e-3)
