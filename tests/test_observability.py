"""Metrics, cardinality, tracing, profiler tests (model: reference
CardinalityTracker specs + TimeSeriesShardStats assertions)."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from filodb_tpu.api.http import serve_background
from filodb_tpu.coordinator.planner import QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.cardinality import CardinalityTracker, QuotaExceededError
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.metrics import REGISTRY, Registry, SamplingProfiler, current_trace, span
from filodb_tpu.testkit import machine_metrics

BASE = 1_600_000_000_000


class TestCardinalityTracker:
    def test_counts_by_prefix(self):
        t = CardinalityTracker()
        for i in range(10):
            t.series_created({"_ws_": "demo", "_ns_": f"app-{i % 2}", "_metric_": f"m{i}"})
        assert t.record_of(()).ts_count == 10
        assert t.record_of(("demo",)).ts_count == 10
        assert t.record_of(("demo", "app-0")).ts_count == 5
        assert t.record_of(("demo",)).children == 2

    def test_quota_enforced(self):
        t = CardinalityTracker()
        t.set_quota(("demo", "app"), 3)
        for i in range(3):
            t.series_created({"_ws_": "demo", "_ns_": "app", "_metric_": f"m{i}"})
        with pytest.raises(QuotaExceededError):
            t.series_created({"_ws_": "demo", "_ns_": "app", "_metric_": "m99"})
        # other namespaces unaffected
        t.series_created({"_ws_": "demo", "_ns_": "other", "_metric_": "ok"})

    def test_active_vs_total(self):
        t = CardinalityTracker()
        tags = {"_ws_": "w", "_ns_": "n", "_metric_": "m"}
        t.series_created(tags)
        t.series_stopped(tags)
        rec = t.record_of(("w", "n", "m"))
        assert rec.ts_count == 1 and rec.active_ts_count == 0

    def test_scan_depth(self):
        t = CardinalityTracker()
        for ns in ("a", "b", "c"):
            for i in range(int(ns == "a") * 2 + 1):
                t.series_created({"_ws_": "w", "_ns_": ns, "_metric_": f"m{i}"})
        recs = t.scan(("w",), 2)
        assert [r.prefix[-1] for r in recs][0] == "a"  # sorted by count desc

    def test_save_load(self, tmp_path):
        t = CardinalityTracker()
        t.set_quota(("w",), 100)
        t.series_created({"_ws_": "w", "_ns_": "n", "_metric_": "m"})
        p = str(tmp_path / "card.json")
        t.save(p)
        t2 = CardinalityTracker.load(p)
        assert t2.record_of(("w", "n")).ts_count == 1
        assert t2.quota_of(("w",)) == 100

    def test_shard_integration(self):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("ds"), [0])
        ms.ingest("ds", 0, machine_metrics(n_series=7, n_samples=5, start_ms=BASE))
        sh = ms.shard("ds", 0)
        assert sh.cardinality.record_of(()).ts_count == 7


class TestMetricsRegistry:
    def test_counter_gauge_histogram_expose(self):
        r = Registry()
        r.counter("reqs", code="200").inc(5)
        r.gauge("up").set(1)
        r.histogram("lat").observe(0.003)
        r.histogram("lat").observe(0.3)
        text = r.expose()
        assert 'reqs_total{code="200"} 5' in text
        assert "up 1" in text
        assert 'lat_bucket{le="0.005"} 1' in text
        assert 'lat_bucket{le="+Inf"} 2' in text
        assert "lat_count 2" in text

    def test_metrics_endpoint(self):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), [0])
        ms.ingest("prometheus", 0, machine_metrics(n_series=3, n_samples=50, start_ms=BASE))
        engine = QueryEngine(ms, "prometheus")
        engine.query_range("heap_usage0", (BASE + 600_000) / 1000, (BASE + 900_000) / 1000, 60)
        srv, port = serve_background(engine)
        try:
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics") as r:
                text = r.read().decode()
            assert "filodb_shard_partitions" in text
            assert "filodb_queries_total" in text
            assert "filodb_query_latency_seconds_bucket" in text
        finally:
            srv.shutdown()

    def test_cardinality_endpoint(self):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), [0, 1])
        ms.ingest_routed("prometheus", machine_metrics(n_series=10, n_samples=5, start_ms=BASE), spread=1)
        srv, port = serve_background(QueryEngine(ms, "prometheus"))
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/v1/cardinality?prefix=demo&depth=2"
            ) as r:
                out = json.loads(r.read())
            assert out["data"][0]["prefix"] == ["demo", "App-2"]
            assert out["data"][0]["ts_count"] == 10
        finally:
            srv.shutdown()


class TestTracing:
    def test_nested_spans(self):
        with span("root") as root:
            with span("child1"):
                time.sleep(0.01)
            with span("child2"):
                pass
        assert len(root.children) == 2
        assert root.duration_ms >= root.children[0].duration_ms
        assert "child1" in root.tree()

    def test_exec_plans_emit_spans(self):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), [0])
        ms.ingest("prometheus", 0, machine_metrics(n_series=2, n_samples=50, start_ms=BASE))
        engine = QueryEngine(ms, "prometheus")
        with span("query") as root:
            engine.query_range("sum(heap_usage0)", (BASE + 600_000) / 1000, (BASE + 900_000) / 1000, 60)
        names = [c.name for c in root.children]
        assert "ReduceAggregateExec" in names


class TestProfiler:
    def test_sampling_profiler_catches_busy_thread(self):
        def busy():
            end = time.time() + 0.4
            while time.time() < end:
                sum(range(1000))

        t = threading.Thread(target=busy)
        prof = SamplingProfiler(interval_s=0.005)
        prof.start()
        t.start()
        t.join()
        prof.stop()
        assert "busy" in prof.report()
