"""Metrics, cardinality, tracing, profiler tests (model: reference
CardinalityTracker specs + TimeSeriesShardStats assertions)."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from filodb_tpu.api.http import serve_background
from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.cardinality import CardinalityTracker, QuotaExceededError
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.metrics import (
    REGISTRY,
    SLOW_QUERY_LOG,
    Registry,
    SamplingProfiler,
    Span,
    activate,
    current_span,
    current_trace,
    span,
    trace_to_dict,
)
from filodb_tpu.testkit import counter_batch, grpc_cluster, machine_metrics

pytestmark = pytest.mark.observability

BASE = 1_600_000_000_000


def find_span(tree: dict, name: str) -> dict | None:
    """First span named ``name`` in a rendered trace tree (DFS)."""
    if tree is None:
        return None
    if tree.get("name") == name:
        return tree
    for c in tree.get("children", ()):
        hit = find_span(c, name)
        if hit is not None:
            return hit
    return None


class TestCardinalityTracker:
    def test_counts_by_prefix(self):
        t = CardinalityTracker()
        for i in range(10):
            t.series_created({"_ws_": "demo", "_ns_": f"app-{i % 2}", "_metric_": f"m{i}"})
        assert t.record_of(()).ts_count == 10
        assert t.record_of(("demo",)).ts_count == 10
        assert t.record_of(("demo", "app-0")).ts_count == 5
        assert t.record_of(("demo",)).children == 2

    def test_quota_enforced(self):
        t = CardinalityTracker()
        t.set_quota(("demo", "app"), 3)
        for i in range(3):
            t.series_created({"_ws_": "demo", "_ns_": "app", "_metric_": f"m{i}"})
        with pytest.raises(QuotaExceededError):
            t.series_created({"_ws_": "demo", "_ns_": "app", "_metric_": "m99"})
        # other namespaces unaffected
        t.series_created({"_ws_": "demo", "_ns_": "other", "_metric_": "ok"})

    def test_active_vs_total(self):
        t = CardinalityTracker()
        tags = {"_ws_": "w", "_ns_": "n", "_metric_": "m"}
        t.series_created(tags)
        t.series_stopped(tags)
        rec = t.record_of(("w", "n", "m"))
        assert rec.ts_count == 1 and rec.active_ts_count == 0

    def test_scan_depth(self):
        t = CardinalityTracker()
        for ns in ("a", "b", "c"):
            for i in range(int(ns == "a") * 2 + 1):
                t.series_created({"_ws_": "w", "_ns_": ns, "_metric_": f"m{i}"})
        recs = t.scan(("w",), 2)
        assert [r.prefix[-1] for r in recs][0] == "a"  # sorted by count desc

    def test_save_load(self, tmp_path):
        t = CardinalityTracker()
        t.set_quota(("w",), 100)
        t.series_created({"_ws_": "w", "_ns_": "n", "_metric_": "m"})
        p = str(tmp_path / "card.json")
        t.save(p)
        t2 = CardinalityTracker.load(p)
        assert t2.record_of(("w", "n")).ts_count == 1
        assert t2.quota_of(("w",)) == 100

    def test_shard_integration(self):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("ds"), [0])
        ms.ingest("ds", 0, machine_metrics(n_series=7, n_samples=5, start_ms=BASE))
        sh = ms.shard("ds", 0)
        assert sh.cardinality.record_of(()).ts_count == 7


class TestMetricsRegistry:
    def test_counter_gauge_histogram_expose(self):
        r = Registry()
        r.counter("reqs", code="200").inc(5)
        r.gauge("up").set(1)
        r.histogram("lat").observe(0.003)
        r.histogram("lat").observe(0.3)
        text = r.expose()
        assert 'reqs_total{code="200"} 5' in text
        assert "up 1" in text
        assert 'lat_bucket{le="0.005"} 1' in text
        assert 'lat_bucket{le="+Inf"} 2' in text
        assert "lat_count 2" in text

    def test_metrics_endpoint(self):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), [0])
        ms.ingest("prometheus", 0, machine_metrics(n_series=3, n_samples=50, start_ms=BASE))
        engine = QueryEngine(ms, "prometheus")
        engine.query_range("heap_usage0", (BASE + 600_000) / 1000, (BASE + 900_000) / 1000, 60)
        srv, port = serve_background(engine)
        try:
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics") as r:
                text = r.read().decode()
            assert "filodb_shard_partitions" in text
            assert "filodb_queries_total" in text
            assert "filodb_query_latency_seconds_bucket" in text
        finally:
            srv.shutdown()

    def test_cardinality_endpoint(self):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), [0, 1])
        ms.ingest_routed("prometheus", machine_metrics(n_series=10, n_samples=5, start_ms=BASE), spread=1)
        srv, port = serve_background(QueryEngine(ms, "prometheus"))
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/v1/cardinality?prefix=demo&depth=2"
            ) as r:
                out = json.loads(r.read())
            assert out["data"][0]["prefix"] == ["demo", "App-2"]
            assert out["data"][0]["ts_count"] == 10
        finally:
            srv.shutdown()


class TestTracing:
    def test_nested_spans(self):
        with span("root") as root:
            with span("child1"):
                time.sleep(0.01)
            with span("child2"):
                pass
        assert len(root.children) == 2
        assert root.duration_ms >= root.children[0].duration_ms
        assert "child1" in root.tree()

    def test_exec_plans_emit_spans(self):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), [0])
        ms.ingest("prometheus", 0, machine_metrics(n_series=2, n_samples=50, start_ms=BASE))
        engine = QueryEngine(ms, "prometheus")
        with span("query") as root:
            engine.query_range("sum(heap_usage0)", (BASE + 600_000) / 1000, (BASE + 900_000) / 1000, 60)
        names = [c.name for c in root.children]
        # default engine plans the aggregate as the fused single-dispatch
        # node; its stage/dispatch phases are child spans
        assert "FusedAggregateExec" in names
        fused = root.children[names.index("FusedAggregateExec")]
        child_names = {c.name for c in fused.children}
        assert "fused:stage" in child_names
        assert any(n.startswith("fused:dispatch") for n in child_names)


class TestRegistryEscaping:
    def test_label_values_escaped_per_exposition_spec(self):
        r = Registry()
        r.counter("reqs", path='say "hi"\\there\nnow').inc()
        r.gauge("g", v="a\\b").set(2)
        r.histogram("h", q='"').observe(0.01)
        text = r.expose()
        assert 'reqs_total{path="say \\"hi\\"\\\\there\\nnow"} 1' in text
        assert 'g{v="a\\\\b"} 2' in text
        # no raw (unescaped) newline may survive inside a label value:
        # every exposition SAMPLE line must end in a numeric sample value
        # (# HELP / # TYPE metadata lines are exempt)
        for line in text.strip().splitlines():
            if line.startswith("#"):
                continue
            float(line.rsplit(" ", 1)[1])

    def test_collectors_refresh_at_scrape_time(self):
        r = Registry()
        state = {"n": 1}
        r.register_collector("t", lambda: r.gauge("live_n").set(state["n"]))
        assert "live_n 1" in r.expose()
        state["n"] = 7
        assert "live_n 7" in r.expose()
        # re-registration replaces, never stacks
        r.register_collector("t", lambda: r.gauge("live_n").set(0))
        assert "live_n 0" in r.expose()

    def test_shard_stats_ride_shared_registry(self):
        """The /metrics handler no longer hand-rolls shard lines: gauges are
        refreshed by a scrape-time collector in the ONE registry."""
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), [0])
        ms.ingest("prometheus", 0, machine_metrics(n_series=4, n_samples=5, start_ms=BASE))
        srv, port = serve_background(QueryEngine(ms, "prometheus"))
        try:
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics") as resp:
                text = resp.read().decode()
            assert 'filodb_shard_partitions{dataset="prometheus",shard="0"} 4' in text
            # ingest more and re-scrape: the gauge refreshes
            ms.ingest("prometheus", 0, machine_metrics(
                n_series=6, n_samples=5, start_ms=BASE, metric="other_m"))
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics") as resp:
                text = resp.read().decode()
            assert 'filodb_shard_partitions{dataset="prometheus",shard="0"} 10' in text
        finally:
            srv.shutdown()


class TestTracePropagation:
    def test_spans_survive_thread_pool_via_activate(self):
        """The cross-thread primitive: a worker re-activating a captured
        span attaches its children under the right parent."""
        from concurrent.futures import ThreadPoolExecutor

        with span("root") as root:
            parent = current_span()

            def work(i):
                with activate(parent):
                    with span(f"child-{i}"):
                        return current_span() is not None

            with ThreadPoolExecutor(max_workers=2) as pool:
                assert all(pool.map(work, range(4)))
        names = sorted(c.name for c in root.children)
        assert names == [f"child-{i}" for i in range(4)]
        assert all(c.trace_id == root.trace_id for c in root.children)
        assert all(c.parent_id == root.span_id for c in root.children)

    def test_execute_children_pool_keeps_spans_parented(self):
        """Remote children dispatch on pool threads; their execute spans
        must land under the merge node's span, not as orphan roots."""
        from filodb_tpu.query.exec.plans import DistConcatExec, ExecPlan, QueryContext
        from filodb_tpu.query.rangevector import QueryResult

        seen = []

        class RemoteStub(ExecPlan):
            is_remote = True

            def __init__(self, endpoint):
                super().__init__()
                self.endpoint = endpoint

            def args_str(self):
                return f"endpoint={self.endpoint}"

            def do_execute(self, ctx):
                seen.append(current_span())
                return QueryResult()

        plan = DistConcatExec([RemoteStub("grpc://a:1"), RemoteStub("grpc://b:1")])
        ctx = QueryContext(None, "ds")
        with span("query") as root:
            plan.execute(ctx)
        concat = next(c for c in root.children if c.name == "DistConcatExec")
        child_names = sorted(c.name for c in concat.children)
        assert child_names == ["RemoteStub", "RemoteStub"]
        # the spans observed INSIDE the workers were real and correctly wired
        assert all(s is not None and s.trace_id == root.trace_id for s in seen)

    def test_distributed_grpc_trace_stitches_single_tree(self):
        """Acceptance: a distributed query through the in-process cluster
        testkit (parent -> remote gRPC child) returns ONE stitched span tree
        with per-node durations and QueryStats."""
        eng, _peer, stop = grpc_cluster(
            counter_batch(n_series=16, n_samples=60, start_ms=BASE),
        )
        try:
            res = eng.query_range(
                "sum(rate(http_requests_total[5m]))",
                BASE / 1000 + 400, BASE / 1000 + 900, 60,
            )
            tree = trace_to_dict(res.trace)
            assert tree["name"] == "query" and tree["trace_id"]
            remote = find_span(tree, "GrpcPlanRemoteExec")
            assert remote is not None, "no remote child span in trace"
            # the peer's tree was stitched IN-BAND under the dispatching span
            peer_root = find_span(remote, "query")
            assert peer_root is not None and peer_root["children"]
            peer_scan = find_span(peer_root, "SelectRawPartitionsExec")
            assert peer_scan is not None
            # stitched spans joined the LOCAL trace
            assert peer_root["trace_id"] == tree["trace_id"]
            assert peer_root["parent_id"] == remote["span_id"]
            # per-node durations + QueryStats annotations
            assert remote["duration_ms"] > 0 and peer_scan["duration_ms"] >= 0
            assert peer_scan["stats"]["series_scanned"] > 0
            assert peer_scan["stats"]["samples_scanned"] > 0
            # peer stats merged into the query-wide stats: all 16 series
            assert res.stats.series_scanned == 16
            local_scan = find_span(tree, "SelectRawPartitionsExec")
            assert local_scan is not None
        finally:
            stop()

    def test_http_trace_param_returns_stitched_tree(self):
        """?trace=true (and explain=analyze) on the HTTP edge returns the
        annotated plan tree for a distributed query."""
        eng, _peer, stop = grpc_cluster(
            counter_batch(n_series=16, n_samples=60, start_ms=BASE),
        )
        srv, port = serve_background(eng)
        try:
            q = ("query=sum(rate(http_requests_total[5m]))"
                 f"&start={BASE / 1000 + 400}&end={BASE / 1000 + 900}&step=60")
            base_url = f"http://127.0.0.1:{port}/api/v1/query_range?{q}"
            plain = json.loads(urllib.request.urlopen(base_url).read())
            assert "trace" not in plain["data"]
            for mode in ("&trace=true", "&explain=analyze"):
                out = json.loads(urllib.request.urlopen(base_url + mode).read())
                tree = out["data"]["trace"]
                remote = find_span(tree, "GrpcPlanRemoteExec")
                assert remote is not None and find_span(remote, "query") is not None
            # stats include the remote slice
            assert out["data"]["stats"]["seriesScanned"] == 16
        finally:
            srv.shutdown()
            stop()

    def test_trace_headers_link_parent_trace(self):
        """An origin's trace identity sent via headers becomes this node's
        trace id / root parent (cross-node linkage over HTTP)."""
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), [0])
        ms.ingest("prometheus", 0, machine_metrics(n_series=2, n_samples=30, start_ms=BASE))
        srv, port = serve_background(QueryEngine(ms, "prometheus"))
        try:
            q = f"query=heap_usage0&start={BASE / 1000 + 300}&end={BASE / 1000 + 500}&step=60&trace=1"
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/v1/query_range?{q}",
                headers={"X-FiloDB-Trace-Id": "feedfacefeedface",
                         "X-FiloDB-Parent-Span": "cafecafecafecafe"},
            )
            out = json.loads(urllib.request.urlopen(req).read())
            tree = out["data"]["trace"]
            assert tree["trace_id"] == "feedfacefeedface"
            assert tree["parent_id"] == "cafecafecafecafe"
        finally:
            srv.shutdown()

    def test_span_wire_roundtrip_rewrites_linkage(self):
        with span("peer-root") as s:
            with span("leaf"):
                pass
        grafted = Span.from_dict(s.to_dict(), trace_id="T" * 16, parent_id="P" * 16)
        assert grafted.trace_id == "T" * 16 and grafted.parent_id == "P" * 16
        assert grafted.children[0].trace_id == "T" * 16
        assert grafted.children[0].parent_id == grafted.span_id
        assert abs(grafted.duration_ms - s.duration_ms) < 0.01


class TestSlowQueryLog:
    def test_slow_query_recorded_with_trace(self):
        SLOW_QUERY_LOG.clear()
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), [0, 1])
        ms.ingest_routed("prometheus", machine_metrics(n_series=8, n_samples=50, start_ms=BASE), spread=1)
        engine = QueryEngine(ms, "prometheus",
                             PlannerParams(spread=1, slow_query_threshold_s=0.0))
        engine.query_range("sum(heap_usage0)", (BASE + 600_000) / 1000,
                           (BASE + 900_000) / 1000, 60)
        entries = SLOW_QUERY_LOG.entries()
        assert entries, "threshold 0 must record every query"
        e = entries[0]
        assert e["promql"] == "sum(heap_usage0)"
        assert e["duration_s"] > 0
        assert e["stats"]["series_scanned"] == 8
        assert find_span(e["trace"], "FusedAggregateExec") is not None

    def test_fast_queries_not_recorded(self):
        SLOW_QUERY_LOG.clear()
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), [0])
        ms.ingest("prometheus", 0, machine_metrics(n_series=2, n_samples=20, start_ms=BASE))
        engine = QueryEngine(ms, "prometheus",
                             PlannerParams(slow_query_threshold_s=3600.0))
        engine.query_range("heap_usage0", (BASE + 300_000) / 1000,
                           (BASE + 400_000) / 1000, 60)
        assert SLOW_QUERY_LOG.entries() == []

    def test_debug_endpoint_and_counter(self):
        SLOW_QUERY_LOG.clear()
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), [0])
        ms.ingest("prometheus", 0, machine_metrics(n_series=3, n_samples=30, start_ms=BASE))
        engine = QueryEngine(ms, "prometheus",
                             PlannerParams(slow_query_threshold_s=0.0))
        srv, port = serve_background(engine)
        try:
            engine.query_range("sum(heap_usage0)", (BASE + 300_000) / 1000,
                               (BASE + 600_000) / 1000, 60)
            out = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/slow_queries").read())
            assert out["status"] == "success" and out["data"]
            assert out["data"][0]["trace"] is not None
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics") as r:
                text = r.read().decode()
            assert "filodb_slow_queries_total" in text
        finally:
            srv.shutdown()

    def test_ring_buffer_bounded(self):
        from filodb_tpu.metrics import SlowQueryLog

        log = SlowQueryLog(max_entries=3)
        for i in range(10):
            log.record(f"q{i}", 1.0, dataset="d")
        entries = log.entries()
        assert len(entries) == 3
        assert entries[0]["promql"] == "q9"  # newest first


class TestKernelInstrumentation:
    def test_dispatch_histogram_and_jit_counters(self):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), [0])
        ms.ingest("prometheus", 0, counter_batch(n_series=4, n_samples=60, start_ms=BASE))
        engine = QueryEngine(ms, "prometheus")
        engine.query_range("sum(rate(http_requests_total[5m]))",
                           (BASE + 600_000) / 1000, (BASE + 900_000) / 1000, 60)
        text = REGISTRY.expose()
        # the fused path records ONE dispatch for the whole query
        assert 'filodb_kernel_dispatch_seconds_bucket{kernel="fused_sum_rate"' in text
        assert 'filodb_jit_cache_total{kernel="fused_sum_rate"' in text
        # a repeat of the same shape must record HITS, not new misses
        before = REGISTRY.counter("filodb_jit_cache", kernel="fused_sum_rate", outcome="hit").value
        engine.query_range("sum(rate(http_requests_total[5m]))",
                           (BASE + 630_000) / 1000, (BASE + 930_000) / 1000, 60)
        after = REGISTRY.counter("filodb_jit_cache", kernel="fused_sum_rate", outcome="hit").value
        assert after > before
        # the reference tree still records per-kernel dispatches
        ref = QueryEngine(ms, "prometheus", PlannerParams(fused_aggregate=False))
        ref.query_range("sum(rate(http_requests_total[5m]))",
                        (BASE + 600_000) / 1000, (BASE + 900_000) / 1000, 60)
        text = REGISTRY.expose()
        assert 'filodb_kernel_dispatch_seconds_bucket{kernel="rate"' in text
        assert 'filodb_kernel_dispatch_seconds_count{kernel="segment_sum"}' in text


class TestProfiler:
    def test_start_is_idempotent(self):
        prof = SamplingProfiler(interval_s=0.01)
        prof.start()
        t1 = prof._thread
        prof.start()  # must NOT leak a second sampler thread
        assert prof._thread is t1
        prof.stop()
        # restart after stop works
        prof.start()
        t2 = prof._thread
        assert t2 is not t1 and t2.is_alive()
        prof.stop()

    def test_debug_profile_endpoint_gated(self):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), [0])
        srv, port = serve_background(QueryEngine(ms, "prometheus"))
        try:
            # not wired (config off): 404
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(f"http://127.0.0.1:{port}/debug/profile")
            assert exc.value.code == 404
            # wired (what FiloServer does when filodb.profiler is enabled)
            prof = SamplingProfiler(interval_s=0.005)
            prof.start()
            srv.RequestHandlerClass.profiler_hook = staticmethod(prof.report)
            time.sleep(0.05)
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/debug/profile") as r:
                assert r.status == 200
            prof.stop()
        finally:
            srv.shutdown()

    def test_sampling_profiler_catches_busy_thread(self):
        def busy():
            end = time.time() + 0.4
            while time.time() < end:
                sum(range(1000))

        t = threading.Thread(target=busy)
        prof = SamplingProfiler(interval_s=0.005)
        prof.start()
        t.start()
        t.join()
        prof.stop()
        assert "busy" in prof.report()
